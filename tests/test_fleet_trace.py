"""Fleet-scope causal tracing tests (serve/fleet.py, ISSUE 18).

Five areas, all against stub HTTP replicas (canned JSON, no jax):

- **propagation round-trip**: the router's request id rides every
  attempt as `X-PBT-Trace`, seals as `fleet_request.trace_id`, and
  answers the client as `X-PBT-Request-Id` — one id end-to-end; the
  off arm (`propagate_trace=False`, the bench A/B baseline) sends no
  header and emits no `fleet_attempt`;
- **sibling-attempt accounting**: attempts on record == retries spent
  + 1 per trace, indices dense from 0, `backoff_s` rides exactly the
  failed attempts a retry followed, and per-trace retries sum to the
  router's `retries_spent`;
- **merged-stream ordering**: `FleetCollector` sorts by
  `(t, src, src_seq)`, re-stamps `seq` 0..N-1, tolerates a torn tail,
  and defaults `replica_id` to the source name without overwriting an
  existing stamp;
- **exactly-once fleet sealing**: one `fleet_request` per trace_id in
  the merged stream; `seal_violations` flags a doctored duplicate;
- **metrics-merge arithmetic**: `fleet_metrics()` sums counters,
  re-labels gauges per replica, merges histogram count/sum/min/max,
  and recomputes window percentiles over the CONCATENATED raw values
  — checked against hand-computed `nearest_rank` answers, plus the
  `GET /fleet/metrics` HTTP route and the unreachable-replica
  `missing` contract.

The cross-process half (a real replica's RequestTrace joining the
propagated id) is covered by tools/fleet_drill.py via
tests/test_fleet.py::TestFleetDrill.
"""

import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from proteinbert_tpu.obs import Telemetry, read_events
from proteinbert_tpu.obs.events import validate_record
from proteinbert_tpu.obs.metrics import nearest_rank
from proteinbert_tpu.serve.fleet import (
    FaultInjector, FleetCollector, FleetRouter, make_fleet_http_server,
)


class TraceStub:
    """Canned-JSON replica that RECORDS the X-PBT-Trace header of every
    POST (None when absent) and serves a scriptable /metrics.json — the
    two capture points the tracing tests need beyond test_fleet.py's
    StubReplica."""

    def __init__(self, name, metrics_payload=None):
        self.name = name
        self.trace_headers = []
        self.metrics_payload = metrics_payload
        self.lock = threading.Lock()
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _send(self, status, body: bytes):
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._send(200, b'{"ok": true, "stats": {}}')
                elif self.path == "/metrics.json":
                    if stub.metrics_payload is None:
                        self._send(404, b"{}")
                    else:
                        self._send(200, json.dumps(
                            stub.metrics_payload).encode())
                else:
                    self._send(404, b"{}")

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                with stub.lock:
                    stub.trace_headers.append(
                        self.headers.get("X-PBT-Trace"))
                self._send(200, json.dumps({"from": stub.name}).encode())

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def seen_traces(self):
        with self.lock:
            return list(self.trace_headers)

    def kill(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture()
def stubs():
    reps = [TraceStub(f"s{i}") for i in range(3)]
    yield reps
    for r in reps:
        r.kill()


def _router(stubs, **kw):
    kw.setdefault("health_interval_s", 0)  # tests drive health_tick()
    kw.setdefault("sleep", lambda s: None)  # no real backoff waits
    kw.setdefault("cache_size", 0)
    return FleetRouter([(r.name, r.url) for r in stubs], **kw).start()


def _body(seq="MKTAYIAK"):
    return json.dumps({"seq": seq}).encode()


def _events(path):
    return read_events(path, strict=True)


# ------------------------------------------------------- propagation


class TestPropagation:
    def test_trace_id_rides_header_seal_and_response(self, stubs,
                                                     tmp_path):
        tele = Telemetry(events_path=str(tmp_path / "ev.jsonl"))
        r = _router(stubs, telemetry=tele)
        status, body, headers = r.route("/v1/embed", _body())
        assert status == 200
        rid = headers["X-PBT-Request-Id"]
        # One id end-to-end: the fleet header IS the request id IS the
        # sealed trace_id IS the forwarded X-PBT-Trace.
        assert headers["X-PBT-Fleet-Request-Id"] == rid
        served = json.loads(body)["from"]
        forwarded = [h for s in stubs for h in s.seen_traces()]
        assert forwarded == [rid]
        r.drain()
        tele.close()
        evs = _events(str(tmp_path / "ev.jsonl"))
        seal = [e for e in evs if e["event"] == "fleet_request"]
        assert len(seal) == 1
        assert seal[0]["trace_id"] == seal[0]["request_id"] == rid
        assert seal[0]["replica_id"] == seal[0]["replica"] == served
        atts = [e for e in evs if e["event"] == "fleet_attempt"]
        assert [a["trace_id"] for a in atts] == [rid]
        assert atts[0]["attempt"] == 0
        assert atts[0]["outcome"] == "ok"
        assert atts[0]["replica"] == served

    def test_off_arm_sends_no_header_emits_no_attempts(self, stubs,
                                                       tmp_path):
        tele = Telemetry(events_path=str(tmp_path / "ev.jsonl"))
        r = _router(stubs, telemetry=tele, propagate_trace=False)
        status, _, headers = r.route("/v1/embed", _body())
        assert status == 200
        # The A/B baseline: no propagated context on the wire...
        assert [h for s in stubs for h in s.seen_traces()] == [None]
        # ...but the router still answers its own id and seals once —
        # sealing is the funnel invariant, not a tracing feature.
        assert headers["X-PBT-Request-Id"].startswith("f")
        r.drain()
        tele.close()
        evs = _events(str(tmp_path / "ev.jsonl"))
        assert [e for e in evs if e["event"] == "fleet_attempt"] == []
        assert len([e for e in evs
                    if e["event"] == "fleet_request"]) == 1

    def test_ids_are_unique_per_request(self, stubs):
        r = _router(stubs)
        rids = set()
        for i in range(8):
            _, _, headers = r.route("/v1/embed", _body(f"SEQ{i}" * 3))
            rids.add(headers["X-PBT-Request-Id"])
        assert len(rids) == 8
        r.drain()


# ------------------------------------------- sibling-attempt records


def _group_by_trace(evs):
    seals, attempts = {}, {}
    for e in evs:
        if e["event"] == "fleet_request":
            seals.setdefault(e["trace_id"], []).append(e)
        elif e["event"] == "fleet_attempt":
            attempts.setdefault(e["trace_id"], []).append(e)
    return seals, attempts


class TestAttemptAccounting:
    def test_attempts_equal_retries_plus_one(self, stubs, tmp_path):
        inj = FaultInjector()
        inj.kill("s0")  # transport failures force retries
        tele = Telemetry(events_path=str(tmp_path / "ev.jsonl"))
        r = _router(stubs, telemetry=tele, fault_injector=inj,
                    max_retries=2)
        for i in range(6):
            status, _, _ = r.route("/v1/embed", _body(f"SEQ{i}" * 3))
            assert status == 200
        st = r.stats()
        r.drain()
        tele.close()
        seals, attempts = _group_by_trace(
            _events(str(tmp_path / "ev.jsonl")))
        assert len(seals) == 6
        retried = 0
        for tid, seal in seals.items():
            assert len(seal) == 1  # exactly-once per trace
            retries = seal[0]["retries"]
            atts = attempts[tid]
            # THE accounting invariant: siblings == retries + 1, with
            # dense 0-based indices in emission order.
            assert len(atts) == retries + 1
            assert [a["attempt"] for a in atts] == list(range(retries + 1))
            # backoff rides exactly the failed attempts a retry
            # followed; the final attempt carries none.
            for a in atts[:-1]:
                assert a["outcome"] == "transport_failed"
                assert a["replica"] == "s0"
                assert a["backoff_s"] >= 0
            assert "backoff_s" not in atts[-1]
            assert atts[-1]["outcome"] == "ok"
            assert atts[-1]["replica"] == seal[0]["replica"]
            assert seal[0]["outcome"] == ("retried_ok" if retries
                                          else "ok")
            retried += retries
        assert retried >= 1  # the kill actually forced a failover
        assert retried == st["retries_spent"]

    def test_exhausted_budget_still_balances(self, stubs, tmp_path):
        inj = FaultInjector()
        for s in stubs:
            inj.kill(s.name)  # nothing routable after retries burn out
        tele = Telemetry(events_path=str(tmp_path / "ev.jsonl"))
        r = _router(stubs, telemetry=tele, fault_injector=inj,
                    max_retries=2)
        status, _, _ = r.route("/v1/embed", _body())
        assert status == 502
        r.drain()
        tele.close()
        seals, attempts = _group_by_trace(
            _events(str(tmp_path / "ev.jsonl")))
        (tid, seal), = seals.items()
        assert seal[0]["outcome"] == "failed"
        assert len(attempts[tid]) == seal[0]["retries"] + 1
        assert all(a["outcome"] == "transport_failed"
                   for a in attempts[tid])


# ---------------------------------------------- merged-stream funnel


def _write_stream(path, n, source):
    """n schema-valid note records via a real Telemetry writer."""
    tele = Telemetry(events_path=str(path))
    for i in range(n):
        tele.emit("note", source=source, kind=f"mark{i}")
    tele.close()


def _rewrite_t(path, ts, extra=None):
    """Re-stamp the t of each record (records stay schema-valid) so the
    merge order is deterministic; `extra` patches fields per index."""
    recs = [json.loads(ln) for ln in open(path) if ln.strip()]
    assert len(recs) == len(ts)
    with open(path, "w") as f:
        for i, rec in enumerate(recs):
            rec["t"] = ts[i]
            for k, v in (extra or {}).get(i, {}).items():
                rec[k] = v
            f.write(json.dumps(rec) + "\n")


class TestMergedStream:
    def test_order_restamp_and_replica_default(self, tmp_path):
        router_p = tmp_path / "router.jsonl"
        ra_p = tmp_path / "ra.jsonl"
        rb_p = tmp_path / "rb.jsonl"
        _write_stream(router_p, 3, "router")
        _write_stream(ra_p, 3, "ra")
        _write_stream(rb_p, 3, "rb")
        # Interleaved wall clocks with a 3-way tie at t=4.0 — the tie
        # must break by (src, src_seq), never by input order.
        _rewrite_t(router_p, [1.0, 4.0, 7.0])
        _rewrite_t(ra_p, [2.0, 4.0, 8.0],
                   extra={0: {"replica_id": "stamped"}})
        _rewrite_t(rb_p, [4.0, 4.0, 3.0])
        with open(rb_p, "a") as f:
            f.write('{"event": "note", "t": 9')  # torn tail (crash)
        coll = FleetCollector({"router": str(router_p)})
        coll.add_source("ra", str(ra_p))
        coll.add_source("rb", str(rb_p))
        merged = coll.collect()
        assert len(merged) == 9  # torn tail skipped, nothing else lost
        keys = [(r["t"], r["src"], r["src_seq"]) for r in merged]
        assert keys == sorted(keys)
        # rb's t went 4.0, 4.0, 3.0: src_seq breaks the intra-source
        # tie and t reorders across sources.
        assert keys[:2] == [(1.0, "router", 0), (2.0, "ra", 0)]
        assert [k[1] for k in keys if k[0] == 4.0] == \
            ["ra", "rb", "rb", "router"]
        # Dense re-sequencing: the merged stream passes the same
        # monotonic-seq validation as any single stream.
        assert [r["seq"] for r in merged] == list(range(9))
        for rec in merged:
            validate_record(rec)
        # replica_id defaults to the source name; an existing stamp
        # (a fleet_request's serving replica) is never overwritten.
        by_src = {}
        for rec in merged:
            by_src.setdefault(rec["src"], []).append(rec["replica_id"])
        assert by_src["router"] == ["router"] * 3
        assert by_src["rb"] == ["rb"] * 3
        assert sorted(by_src["ra"]) == ["ra", "ra", "stamped"]

    def test_missing_source_skipped(self, tmp_path):
        p = tmp_path / "only.jsonl"
        _write_stream(p, 2, "router")
        coll = FleetCollector({"router": str(p),
                               "gone": str(tmp_path / "never.jsonl")})
        assert len(coll.collect()) == 2

    def test_write_roundtrips_strict(self, tmp_path):
        p = tmp_path / "s.jsonl"
        _write_stream(p, 4, "router")
        coll = FleetCollector({"router": str(p)})
        out = tmp_path / "merged.jsonl"
        n = coll.write(str(out))
        assert n == 4
        back = read_events(str(out), strict=True)
        assert [r["seq"] for r in back] == list(range(4))


# --------------------------------------------- exactly-once sealing


class TestFleetSealing:
    def test_one_seal_per_trace_in_merged_stream(self, stubs, tmp_path):
        tele = Telemetry(events_path=str(tmp_path / "router.jsonl"))
        r = _router(stubs, telemetry=tele)
        rids = [r.route("/v1/embed", _body(f"SEQ{i}" * 3))[2]
                ["X-PBT-Request-Id"] for i in range(5)]
        r.drain()
        tele.close()
        merged = FleetCollector(
            {"router": str(tmp_path / "router.jsonl")}).collect()
        seals = [e for e in merged if e["event"] == "fleet_request"]
        assert sorted(e["trace_id"] for e in seals) == sorted(rids)
        assert FleetCollector.seal_violations(merged) == {}

    def test_violations_flag_duplicates_and_gaps(self):
        def seal(tid):
            return {"event": "fleet_request", "trace_id": tid}

        records = [seal("f1-1"), seal("f1-2"), seal("f1-2"),
                   {"event": "fleet_attempt", "trace_id": "f1-3"}]
        assert FleetCollector.seal_violations(records) == {"f1-2": 2}

    def test_request_id_fallback_for_old_streams(self):
        # Pre-ISSUE-18 fleet_request records carry request_id only;
        # sealing audits must still count them.
        records = [{"event": "fleet_request", "request_id": "f1-9"}] * 2
        assert FleetCollector.seal_violations(records) == {"f1-9": 2}


# ------------------------------------------------- aggregation plane


R0_METRICS = {
    "replica_id": "s0",
    "snapshot": {
        "counters": {"serve_requests_total": 3.0,
                     'serve_rejects_total{reason="queue_full"}': 1.0},
        "gauges": {"serve_queue_depth": 2.0},
        "histograms": {"serve_batch_rows": {
            "count": 2, "sum": 0.5, "min": 0.1, "max": 0.4}},
    },
    "windows": {"serve_e2e_seconds": [0.1, 0.2, 0.3]},
}
R1_METRICS = {
    "replica_id": "s1",
    "snapshot": {
        "counters": {"serve_requests_total": 4.0},
        "gauges": {"serve_queue_depth": 7.0},
        "histograms": {"serve_batch_rows": {
            "count": 1, "sum": 0.2, "min": 0.2, "max": 0.2}},
    },
    "windows": {"serve_e2e_seconds": [0.9, 0.05]},
}


@pytest.fixture()
def metric_stubs():
    reps = [TraceStub("s0", metrics_payload=R0_METRICS),
            TraceStub("s1", metrics_payload=R1_METRICS)]
    yield reps
    for r in reps:
        r.kill()


class TestMetricsMerge:
    def test_merge_arithmetic_vs_hand_computed(self, metric_stubs):
        r = _router(metric_stubs)
        fm = r.fleet_metrics()
        r.drain()
        assert fm["replicas"] == ["s0", "s1"]
        assert fm["missing"] == []
        # Counters SUM across replicas (labels and all); a counter only
        # one replica reports still surfaces.
        assert fm["counters"]["serve_requests_total"] == 7.0
        assert fm["counters"][
            'serve_rejects_total{reason="queue_full"}'] == 1.0
        # Gauges stay per-replica under a replica= label — a mean of
        # queue depths would hide the hot one.
        assert fm["gauges"]['serve_queue_depth{replica="s0"}'] == 2.0
        assert fm["gauges"]['serve_queue_depth{replica="s1"}'] == 7.0
        # Histograms: count/sum added, min/max combined.
        assert fm["histograms"]["serve_batch_rows"] == {
            "count": 3, "sum": 0.7, "min": 0.1, "max": 0.4}
        # Windows: percentiles over the CONCATENATED raw values — the
        # fleet p99 (0.9) is NOT any function of s0's p99 (0.3).
        concat = sorted([0.1, 0.2, 0.3, 0.9, 0.05])
        w = fm["windows"]["serve_e2e_seconds"]
        assert w["n"] == 5
        assert w["p50_s"] == round(nearest_rank(concat, 0.50), 6) == 0.2
        assert w["p99_s"] == round(nearest_rank(concat, 0.99), 6) == 0.9
        assert w["mean_s"] == round(sum(concat) / 5, 6)

    def test_unreachable_replica_listed_missing(self, metric_stubs):
        dead = TraceStub("s2")  # no /metrics.json payload -> 404
        dead.kill()             # and no socket either
        r = FleetRouter(
            [(s.name, s.url) for s in metric_stubs]
            + [("s2", dead.url)],
            health_interval_s=0, cache_size=0,
            health_timeout_s=0.5).start()
        fm = r.fleet_metrics()
        r.drain()
        # Partial view that says so beats a hang: the live replicas
        # still merge, the dead one is named.
        assert fm["replicas"] == ["s0", "s1"]
        assert fm["missing"] == ["s2"]
        assert fm["counters"]["serve_requests_total"] == 7.0

    def test_http_route_serves_merged_view(self, metric_stubs):
        r = _router(metric_stubs)
        httpd = make_fleet_http_server(r)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        port = httpd.server_address[1]
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/fleet/metrics",
                    timeout=5) as resp:
                assert resp.status == 200
                fm = json.loads(resp.read())
        finally:
            httpd.shutdown()
            httpd.server_close()
            r.drain()
        assert fm["counters"]["serve_requests_total"] == 7.0
        assert fm["windows"]["serve_e2e_seconds"]["p99_s"] == 0.9
