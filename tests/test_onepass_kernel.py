"""One-pass trunk kernel (ISSUE 16 tentpole): the whole block pass —
tap-decomposed local conv track AND ragged global attention — as ONE
VMEM-resident Pallas grid program, against the TWO-KERNEL composition
it replaces (`fused_local_track_segments` → `fused_packed_attention`).
Runs in interpret mode on the CPU test mesh; the same kernel compiles
via Mosaic on TPU.

The acceptance contract is BIT-identity in interpret mode: both sides
execute the same tap matmuls / `_finish_row` / `_attention_body` in
the same fp32 order, so the fusion may not change a single ulp — any
drift means the one-pass kernel reordered the math.

Cost discipline: ONE kernel shape (B, L, C, S) = (2, 256, 128, 4) —
L=256 so segment boundaries sit mid-row — with module-scoped params
and module-level jitted entries shared by every layout, mirroring
tests/test_attention_kernel.py.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from proteinbert_tpu.configs import ModelConfig
from proteinbert_tpu.kernels import attention as ka
from proteinbert_tpu.kernels import fused_block as fb
from proteinbert_tpu.kernels import one_pass as op
from proteinbert_tpu.models import proteinbert
from proteinbert_tpu.parallel.quant import quantize_params

B, L, C, S = 2, 256, 128, 4
G, KD, H = 64, 16, 4

TRACK_KEYS = ("narrow_conv", "wide_conv", "local_ln1", "local_dense",
              "local_ln2")


@pytest.fixture(scope="module")
def onepass_inputs():
    cfg = ModelConfig(local_dim=C, global_dim=G, key_dim=KD, num_heads=H,
                      num_blocks=1, num_annotations=16, dtype="float32")
    block = proteinbert.block_init(jax.random.PRNGKey(7), cfg)
    track = {k: block[k] for k in TRACK_KEYS}
    attn = block["attention"]
    kx, kb, kg = jax.random.split(jax.random.PRNGKey(8), 3)
    x = jax.random.normal(kx, (B, L, C), jnp.float32)
    bcast = jax.random.normal(kb, (B, S, C), jnp.float32)
    gseg = jax.random.normal(kg, (B, S, G), jnp.float32)
    return track, attn, x, bcast, gseg


def _seg_rows(*rows):
    """(n_rows, L) segment ids from [(segment_id, span), ...] specs —
    remaining positions stay 0 (pad)."""
    seg = np.zeros((len(rows), L), np.int32)
    for i, spans in enumerate(rows):
        pos = 0
        for sid, ln in spans:
            seg[i, pos:pos + ln] = sid
            pos += ln
    return jnp.asarray(seg)


@jax.jit
def _one(track, attn, x, bc, g, seg):
    return op.fused_onepass_segments(track, attn, x, bc, g, seg)


@jax.jit
def _two(track, attn, x, bc, g, seg):
    local = fb.fused_local_track_segments(track, x, bc, seg, 1, 5, True)
    return local, ka.fused_packed_attention(attn, local, g, seg,
                                            interpret=True)


@jax.jit
def _one_masked(track, attn, x, bc, g, seg, real):
    return op.fused_onepass_segments(track, attn, x, bc, g, seg,
                                     real_mask=real)


@jax.jit
def _two_masked(track, attn, x, bc, g, seg, real):
    local = fb.fused_local_track_segments(track, x, bc, seg, 1, 5, True)
    return local, ka.fused_packed_attention(attn, local, g, seg,
                                            real_mask=real,
                                            interpret=True)


@jax.jit
def _one_dense(track, attn, x, bc, g, pad):
    return op.fused_onepass_dense(track, attn, x, bc, g, pad_mask=pad)


@jax.jit
def _two_dense(track, attn, x, bc, g, pad):
    local = fb.fused_local_track(track, x, bc, 1, 5, True)
    return local, ka.fused_global_attention(attn, local, g, pad,
                                            interpret=True)


# The packed layout grid: the empty tail row (scheduler under-fill),
# a segment boundary AT the 128-lane tile edge, and the max-segments
# row all exercise distinct mask/one-hot corners of the shared (L, S)
# selector.
LAYOUTS = {
    "single_segment_full_row": [[(1, L)], [(1, L)]],
    "max_segments": [[(1, 64), (2, 64), (3, 64), (4, 50)],
                     [(1, 30), (2, 30), (3, 30), (4, 30)]],
    "empty_tail_rows": [[(1, 100), (2, 60)], []],  # row 1 ALL pad
    "boundary_at_tile_edge": [[(1, 128), (2, 100)],
                              [(1, 128), (2, 128)]],
}


@pytest.mark.parametrize("layout", sorted(LAYOUTS))
def test_onepass_bit_identity_across_layouts(onepass_inputs, layout):
    """ISSUE 16 acceptance: the one-pass kernel bit-matches the
    two-kernel composition on BOTH outputs across packed layouts, with
    ZERO fallbacks on this supported shape."""
    track, attn, x, bc, g = onepass_inputs
    assert op.pallas_onepass_supported(C, G, L, S, KD, H, "float32")
    seg = _seg_rows(*LAYOUTS[layout])
    before = op.ONEPASS_PATH_TOTAL.get(("reference", "segments"), 0)
    got_l, got_a = _one(track, attn, x, bc, g, seg)
    want_l, want_a = _two(track, attn, x, bc, g, seg)
    np.testing.assert_array_equal(np.asarray(got_l), np.asarray(want_l))
    np.testing.assert_array_equal(np.asarray(got_a), np.asarray(want_a))
    assert op.ONEPASS_PATH_TOTAL.get(("reference", "segments"),
                                     0) == before


def test_cross_segment_leakage_is_plus_zero(onepass_inputs):
    """The exact +0.0 cross-segment contract: perturbing every token of
    segment 2 must leave segment 1's local-track rows and attention
    vector BIT-unchanged (not just close) — same discipline as the
    constituent kernels' leakage tests."""
    track, attn, x, bc, g = onepass_inputs
    seg = _seg_rows([(1, 100), (2, 120)], [(1, L)])
    l0, a0 = _one(track, attn, x, bc, g, seg)
    bump = jnp.where((np.asarray(seg[0]) == 2)[None, :, None],
                     jnp.float32(17.0), 0.0)
    x2 = x.at[0].add(bump[0])
    l1, a1 = _one(track, attn, x2, bc, g, seg)
    # Segment 1 spans positions [0, 100); the wide-conv halo reaches
    # 20 positions, so rows [0, 80) see NO perturbed input at all.
    np.testing.assert_array_equal(np.asarray(l0[0, :80]),
                                  np.asarray(l1[0, :80]))
    np.testing.assert_array_equal(np.asarray(a0[0, 0]),
                                  np.asarray(a1[0, 0]))
    np.testing.assert_array_equal(np.asarray(l0[1]), np.asarray(l1[1]))


def test_serving_real_mask_bit_identity(onepass_inputs):
    """The ragged-serving layout: bucket-quantized spans whose tails
    hold <pad> tokens. `real_mask` narrows the ATTENTION mask exactly
    as the two-kernel path does, while the conv track still sees the
    full span (the dispatcher's span rule) — bit-identical on both
    outputs."""
    track, attn, x, bc, g = onepass_inputs
    seg = _seg_rows([(1, 64), (2, 128)], [(1, 128), (2, 64)])
    real = np.zeros((B, L), bool)
    real[0, :41] = True          # segment 1 real length 41 of span 64
    real[0, 64:64 + 99] = True   # segment 2 real length 99 of span 128
    real[1, :120] = True
    real[1, 128:128 + 30] = True
    real = jnp.asarray(real)
    got_l, got_a = _one_masked(track, attn, x, bc, g, seg, real)
    want_l, want_a = _two_masked(track, attn, x, bc, g, seg, real)
    np.testing.assert_array_equal(np.asarray(got_l), np.asarray(want_l))
    np.testing.assert_array_equal(np.asarray(got_a), np.asarray(want_a))


def test_dense_entry_bit_identity_with_all_pad_row(onepass_inputs):
    """The dense (S=1) entry vs the dense two-kernel composition,
    including a fully-padded row (a bucketed batch-class padding row):
    the kernel must keep the reference's uniform softmax there."""
    track, attn, x, bc, g = onepass_inputs
    bc_d, g_d = bc[:, 0, :], g[:, 0, :]
    pad = np.ones((B, L), bool)
    pad[0, 200:] = False
    pad[1, :] = False  # all-pad row
    pad = jnp.asarray(pad)
    before = dict(op.ONEPASS_PATH_TOTAL)
    got_l, got_a = _one_dense(track, attn, x, bc_d, g_d, pad)
    assert (op.ONEPASS_PATH_TOTAL.get(("pallas", "dense"), 0)
            >= before.get(("pallas", "dense"), 0))
    want_l, want_a = _two_dense(track, attn, x, bc_d, g_d, pad)
    np.testing.assert_array_equal(np.asarray(got_l), np.asarray(want_l))
    np.testing.assert_array_equal(np.asarray(got_a), np.asarray(want_a))
    assert got_a.shape == (B, G)


def test_gradient_parity(onepass_inputs):
    """The custom VJP (rematerialised oh-reference backward, matching
    the fused-block remat policy) against autodiff through the plain
    one-hot reference — the same 1e-4 tolerance as the constituent
    kernels' gradient tests (the two backwards run the same math in
    different XLA fusion contexts)."""
    track, attn, x, bc, g = onepass_inputs
    seg = _seg_rows([(1, 100), (2, 80)], [(1, L)])
    seg_oh = jnp.asarray(
        (np.asarray(seg)[:, :, None] == np.arange(1, S + 1)),
        jnp.float32)
    real = jnp.ones((B, L, 1), jnp.float32)

    def loss_one(tp, ap, xx, bb, gg):
        local, a = op.fused_onepass_segments(tp, ap, xx, bb, gg, seg)
        return jnp.sum(local ** 2) + jnp.sum(a ** 2)

    def loss_ref(tp, ap, xx, bb, gg):
        local, a = op.onepass_oh_reference(tp, ap, xx, bb, gg, seg_oh,
                                           real)
        return jnp.sum(local ** 2) + jnp.sum(a ** 2)

    g_one = jax.grad(loss_one, argnums=(0, 1, 2, 3, 4))(
        track, attn, x, bc, g)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4))(
        track, attn, x, bc, g)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4),
        g_one, g_ref)


def test_force_reference_env_override_both_entries(onepass_inputs,
                                                   monkeypatch):
    """PBT_FORCE_REFERENCE_KERNEL routes BOTH one-pass entries onto the
    reference composition — counted reason=forced on the onepass family
    and bit-identical to the forced composition (both land on the same
    XLA reference code). Fresh jits per probe: a re-jit of a cached
    function would skip the trace-time env read."""
    track, attn, x, bc, g = onepass_inputs
    seg = _seg_rows([(1, 200)], [(1, L)])
    monkeypatch.setenv(fb.FORCE_REFERENCE_ENV, "1")
    assert fb.force_reference_requested()

    before = op.ONEPASS_PATH_TOTAL.get(("reference", "forced"), 0)
    got = jax.jit(lambda tp, ap, xx, bb, gg: op.fused_onepass_segments(
        tp, ap, xx, bb, gg, seg))(track, attn, x, bc, g)
    assert op.ONEPASS_PATH_TOTAL.get(("reference", "forced"),
                                     0) == before + 1
    want = jax.jit(lambda tp, ap, xx, bb, gg: (
        lambda local: (local, ka.fused_packed_attention(
            ap, local, gg, seg, interpret=True)))(
        fb.fused_local_track_segments(tp, xx, bb, seg, 1, 5, True)))(
        track, attn, x, bc, g)
    for a, b in zip(got, want):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    bc_d, g_d = bc[:, 0, :], g[:, 0, :]
    before = op.ONEPASS_PATH_TOTAL.get(("reference", "forced"), 0)
    got_d = jax.jit(lambda tp, ap, xx, bb, gg: op.fused_onepass_dense(
        tp, ap, xx, bb, gg))(track, attn, x, bc_d, g_d)
    assert op.ONEPASS_PATH_TOTAL.get(("reference", "forced"),
                                     0) == before + 1
    # `fused_local_track` is the raw kernel (no force check of its
    # own — the dispatch above it owns that), so the forced dense
    # composition is the XLA reference directly.
    want_d = jax.jit(lambda tp, ap, xx, bb, gg: (
        lambda local: (local, ka.fused_global_attention(
            ap, local, gg, interpret=True)))(
        fb.local_track_reference(tp, xx, bb, 1, 5)))(
        track, attn, x, bc_d, g_d)
    for a, b in zip(got_d, want_d):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_int8_inkernel_dequant_bit_matches_hlo_dequant(onepass_inputs):
    """The int8 leg (ISSUE 16 second leg): the one-pass kernel loading
    `quantize_params`' int8 weights + per-channel scales into VMEM and
    dequantizing IN-KERNEL must produce bit-identical outputs to
    HLO-dequantizing the same quant tree first (`dequant_params`) and
    running the fp32 kernel — the dequant expression is the same
    `(q.astype(f32) * scale)` either way, so moving it inside the grid
    program may not change a single bit. Covers BOTH entries."""
    track, attn, x, bc, g = onepass_inputs
    qtrack, qattn = quantize_params(track), quantize_params(attn)
    assert fb.is_quant_leaf(qtrack["narrow_conv"]["kernel"])
    assert fb.is_quant_leaf(qattn["wq"])
    dtrack, dattn = fb.dequant_params(qtrack), fb.dequant_params(qattn)

    seg = _seg_rows([(1, 64), (2, 128)], [(1, 128), (2, 64)])
    before = dict(op.ONEPASS_PATH_TOTAL)
    got = _one(qtrack, qattn, x, bc, g, seg)
    assert (op.ONEPASS_PATH_TOTAL.get(("pallas", "packed"), 0)
            > before.get(("pallas", "packed"), 0))
    want = _one(dtrack, dattn, x, bc, g, seg)
    for a, b in zip(got, want):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    bc_d, g_d = bc[:, 0, :], g[:, 0, :]
    got_d = _one_dense(qtrack, qattn, x, bc_d, g_d, None)
    want_d = _one_dense(dtrack, dattn, x, bc_d, g_d, None)
    for a, b in zip(got_d, want_d):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
