"""Guard the driver entry points (__graft_entry__.py).

The driver compile-checks `entry()` single-chip and executes
`dryrun_multichip(n)` on n virtual CPU devices between rounds; a
regression there surfaces only in the driver artifacts, after the fact.
These tests keep both callable from inside the suite: `entry` is traced
via eval_shape (shape/dtype errors without paying a compile), and the
dry run executes fully on the conftest's 8-device CPU mesh.
"""

import jax
import pytest

import __graft_entry__ as graft


def test_entry_traces():
    fn, args = graft.entry()
    local, global_ = jax.eval_shape(fn, *args)
    params, tokens, annotations = args
    B, L = tokens.shape
    assert local.shape == (B, L, 26)
    assert global_.shape == (B, annotations.shape[1])


def test_mesh_plans_cover_axes_and_consume_devices():
    for n in (2, 4, 6, 8, 12, 16):
        plans = graft._mesh_plans(n)
        for axes in plans:
            product = 1
            for extent in axes.values():
                product *= extent
            assert product == n, (n, axes)
    # Multiples of 8: every axis sharded somewhere across the plan set.
    covered = {ax for axes in graft._mesh_plans(8)
               for ax, e in axes.items() if e > 1}
    assert covered == {"data", "fsdp", "model", "seq"}


@pytest.mark.slow
def test_dryrun_multichip_executes():
    graft.dryrun_multichip(8)
