"""REAL two-process multi-host execution on CPU (VERDICT r1 Missing #3).

Spawns two OS processes that `jax.distributed.initialize` against a
localhost coordinator (gloo CPU collectives), run 3 pretraining steps
through the full trainer — per-host sharded iterators,
`jax.make_array_from_process_local_data` batch assembly, cross-process
gradient psum — and asserts the losses match a single-process run on the
identical global batches. This executes the coordination path the
reference never had (SURVEY C18 absent) and round 1 only simulated.
"""

import os
import re
import socket
import subprocess
import sys

import pytest

_CHILD = os.path.join(os.path.dirname(__file__), "multihost_child.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _child_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    # The children configure platform/devices via jax.config themselves;
    # scrub any test-harness device forcing so they start clean.
    env.pop("XLA_FLAGS", None)
    return env


def _parse_losses(stdout: str):
    losses = {int(m.group(1)): float(m.group(2))
              for m in re.finditer(r"STEP (\d+) LOSS ([0-9.eE+-]+)", stdout)}
    assert losses, f"no losses in child output:\n{stdout}"
    return losses


def _run_pair(port, env, mode, extra, timeout=600, expect_rc=0,
              _retry=True):
    procs = [
        subprocess.Popen(
            [sys.executable, _CHILD, str(pid), "2", str(port), mode, *extra],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=_REPO,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    if _retry and any(rc != expect_rc and "Gloo context initialization"
                      in err for rc, _, err in outs):
        # Gloo's first-collective context setup has a fixed internal 30s
        # GetKeyValue deadline with no public knob; on a loaded host the
        # peer can miss it (observed under a concurrent corpus build).
        # One retry distinguishes that environmental flake from a real
        # coordination bug, which fails identically both times. Fresh
        # port: the loaded host that caused the flake may have claimed
        # the old one in the meantime.
        return _run_pair(_free_port(), env, mode, extra, timeout=timeout,
                         expect_rc=expect_rc, _retry=False)
    for rc, out, err in outs:
        assert rc == expect_rc, (
            f"child rc {rc} (wanted {expect_rc}):\n{err[-3000:]}")
    return outs


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["preempt", "preempt-bucketed"])
def test_two_process_preemption_resume_parity(tmp_path, mode):
    """VERDICT r3 item 7: SIGTERM both processes mid-run (collective
    orbax save through GracefulShutdown, exit 75), relaunch the same
    command (mesh-sharded template restore + data fast-forward), and
    assert the combined loss stream equals an uninterrupted two-process
    twin's step for step. The bucketed variant crosses the resume seam
    with the lockstep bucket bookkeeping live."""
    env = _child_env()
    ckpt = str(tmp_path / "ckpt")

    # Phase 1: fresh dir, self-SIGTERM at step 3 -> both exit 75.
    outs = _run_pair(_free_port(), env, mode, [ckpt, "3"],
                     expect_rc=75)
    phase1 = _parse_losses(outs[0][1])
    assert "PREEMPTED 3" in outs[0][1]
    assert set(phase1) == {1, 2, 3}

    # Phase 2: identical command on the populated dir -> restore at 3,
    # fast-forward, complete steps 4-6.
    outs = _run_pair(_free_port(), env, mode, [ckpt, "3"])
    phase2 = _parse_losses(outs[0][1])
    assert set(phase2) == {4, 5, 6}

    # Twin: fresh dir, never killed, runs 1-6 uninterrupted.
    twin_ckpt = str(tmp_path / "twin")
    outs = _run_pair(_free_port(), env, mode, [twin_ckpt, "0"])
    twin = _parse_losses(outs[0][1])
    assert set(twin) == {1, 2, 3, 4, 5, 6}

    resumed = {**phase1, **phase2}
    for step in range(1, 7):
        # Same topology, same restored RNG/opt state, same data stream
        # position: the seam must be invisible in the loss stream.
        assert resumed[step] == pytest.approx(twin[step], rel=1e-6), (
            step, resumed, twin)


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["plain", "bucketed"])
def test_two_process_training_matches_single_process(mode):
    """`plain` drives fixed-shape batches; `bucketed` drives the
    length-bucketed iterator, whose multi-host LOCKSTEP invariant (same
    bucket shape on every host at every step) only a real process
    boundary can falsify."""
    port = _free_port()
    env = _child_env()

    outs = _run_pair(port, env, mode, [])
    dist_losses = _parse_losses(outs[0][1])

    single = subprocess.run(
        [sys.executable, _CHILD, "0", "1", str(port), mode],
        capture_output=True, text=True, env=env, cwd=_REPO, timeout=600,
    )
    assert single.returncode == 0, single.stderr[-3000:]
    ref_losses = _parse_losses(single.stdout)

    assert set(dist_losses) == set(ref_losses) == {1, 2, 3}
    for step in (1, 2, 3):
        # Same global batch, same init, same corruption key; only the
        # reduction topology differs -> float32 tolerance.
        assert dist_losses[step] == pytest.approx(ref_losses[step],
                                                  rel=1e-5), (
            step, dist_losses, ref_losses)
