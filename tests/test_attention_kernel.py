"""Ragged Pallas global-attention kernel (ISSUE 13 tentpole) against
the masked-XLA references in ops/attention.py. Runs in interpret mode
on the CPU test mesh; the same kernel compiles via Mosaic on TPU.

Cost discipline: ONE kernel shape (B, L, C, S) = (2, 256, 128, 4) —
L=256 so segment boundaries sit mid-row — with module-scoped params
and TWO module-level jitted entries shared by every layout, mirroring
tests/test_packing.py's fused-block suite.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from proteinbert_tpu.configs import ModelConfig
from proteinbert_tpu.kernels import attention as ka
from proteinbert_tpu.models import proteinbert
from proteinbert_tpu.ops.attention import (
    global_attention_apply,
    global_attention_init,
    packed_global_attention_apply,
)

B, L, C, S = 2, 256, 128, 4
G, KD, H = 64, 16, 4


@pytest.fixture(scope="module")
def attn_inputs():
    kp, kx, kg = jax.random.split(jax.random.PRNGKey(7), 3)
    params = global_attention_init(kp, C, G, KD, H)
    local = jax.random.normal(kx, (B, L, C), jnp.float32)
    gseg = jax.random.normal(kg, (B, S, G), jnp.float32)
    return params, local, gseg


def _seg_rows(*rows):
    """(n_rows, L) segment ids from [(segment_id, span), ...] specs —
    remaining positions stay 0 (pad)."""
    seg = np.zeros((len(rows), L), np.int32)
    for i, spans in enumerate(rows):
        pos = 0
        for sid, ln in spans:
            seg[i, pos:pos + ln] = sid
            pos += ln
    return jnp.asarray(seg)


@jax.jit
def _fused(params, x, g, seg):
    return ka.fused_packed_attention(params, x, g, seg)


@jax.jit
def _ref(params, x, g, seg):
    return packed_global_attention_apply(params, x, g, seg)


@jax.jit
def _fused_masked(params, x, g, seg, real):
    return ka.fused_packed_attention(params, x, g, seg, real_mask=real)


@jax.jit
def _ref_masked(params, x, g, seg, real):
    return packed_global_attention_apply(params, x, g, seg,
                                         real_mask=real)


LAYOUTS = {
    "single_segment_full_row": [[(1, L)], [(1, L)]],
    "max_segments": [[(1, 64), (2, 64), (3, 64), (4, 50)],
                     [(1, 30), (2, 30), (3, 30), (4, 30)]],
    "empty_tail_rows": [[(1, 100), (2, 60)], []],  # row 1 ALL pad
    "boundary_at_tile_edge": [[(1, 128), (2, 100)],
                              [(1, 128), (2, 128)]],
}


@pytest.mark.parametrize("layout", sorted(LAYOUTS))
def test_packed_parity_across_layouts(attn_inputs, layout):
    """ISSUE 13 acceptance: fused-vs-reference parity at the documented
    jitted ≤1e-5 tolerance across segment layouts, with ZERO
    reason=segments fallbacks on this supported shape."""
    params, x, g = attn_inputs
    assert ka.pallas_attention_supported(C, G, L, S, KD, H, "float32")
    seg = _seg_rows(*LAYOUTS[layout])
    before = ka.ATTN_PATH_TOTAL.get(("reference", "segments"), 0)
    got = _fused(params, x, g, seg)
    want = _ref(params, x, g, seg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)
    assert ka.ATTN_PATH_TOTAL.get(("reference", "segments"), 0) == before


def test_serving_real_mask_parity(attn_inputs):
    """The ragged-serving layout: bucket-quantized spans whose tails
    hold <pad> tokens — `real_mask` must keep them out of the softmax
    exactly as the reference does (serve/dispatch.RaggedDispatcher's
    span rule)."""
    params, x, g = attn_inputs
    # Spans quantized to 64/128 buckets; the real lengths are shorter.
    seg = _seg_rows([(1, 64), (2, 128)], [(1, 128), (2, 64)])
    real = np.zeros((B, L), bool)
    real[0, :41] = True          # segment 1 real length 41 of span 64
    real[0, 64:64 + 99] = True   # segment 2 real length 99 of span 128
    real[1, :120] = True
    real[1, 128:128 + 30] = True
    real = jnp.asarray(real)
    got = _fused_masked(params, x, g, seg, real)
    want = _ref_masked(params, x, g, seg, real)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_dense_parity_and_all_pad_row(attn_inputs):
    """The dense (S=1) entry vs `global_attention_apply`, including a
    fully-padded row (a bucketed batch-class padding row): the kernel
    must keep the reference's uniform softmax there, not zero it."""
    params, x, _ = attn_inputs
    g2 = jax.random.normal(jax.random.PRNGKey(9), (B, G), jnp.float32)
    pad = np.ones((B, L), bool)
    pad[0, 200:] = False
    pad[1, :] = False  # all-pad row
    pad = jnp.asarray(pad)
    before = dict(ka.ATTN_PATH_TOTAL)
    got = jax.jit(lambda p, xx, gg, m: ka.fused_global_attention(
        p, xx, gg, m))(params, x, g2, pad)
    assert (ka.ATTN_PATH_TOTAL.get(("pallas", "dense"), 0)
            > before.get(("pallas", "dense"), 0))
    want = jax.jit(lambda p, xx, gg, m: global_attention_apply(
        p, xx, gg, m))(params, x, g2, pad)
    assert got.shape == (B, G)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_gradient_parity(attn_inputs):
    """The custom VJP (rematerialised oh-reference backward) against
    autodiff through the masked-XLA reference."""
    params, x, g = attn_inputs
    seg = _seg_rows([(1, 100), (2, 80)], [(1, L)])

    def loss_fused(p, xx, gg):
        return jnp.sum(ka.fused_packed_attention(p, xx, gg, seg) ** 2)

    def loss_ref(p, xx, gg):
        return jnp.sum(
            packed_global_attention_apply(p, xx, gg, seg) ** 2)

    g_fused = jax.grad(loss_fused, argnums=(0, 1, 2))(params, x, g)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(params, x, g)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4),
        g_fused, g_ref)


def test_cross_segment_leakage_bit_identical(attn_inputs):
    """Scrambling one segment's residues AND its global vector must not
    move the other segment's attention output by a single bit: masked
    scores' exp underflows to exact +0.0 and 0·V terms add exactly
    nothing (the same proof obligation as the fused block's
    `_segment_conv`)."""
    params, x, g = attn_inputs
    seg = _seg_rows([(1, 120), (2, 100)], [(1, 120), (2, 100)])
    out1 = np.asarray(_fused(params, x, g, seg))
    # Scramble segment 2's local rows and global vector.
    x2 = np.asarray(x).copy()
    x2[:, 120:220, :] = np.random.default_rng(0).normal(
        size=(B, 100, C)).astype(np.float32)
    g2 = np.asarray(g).copy()
    g2[:, 1, :] = 123.0
    out2 = np.asarray(_fused(params, jnp.asarray(x2), jnp.asarray(g2),
                             seg))
    np.testing.assert_array_equal(out1[:, 0], out2[:, 0])
    assert not np.array_equal(out1[:, 1], out2[:, 1])  # probe is live


def test_bf16_parity(attn_inputs):
    params, x, g = attn_inputs
    seg = _seg_rows([(1, 200)], [(1, 64), (2, 190)])
    got = ka.fused_packed_attention(
        params, x.astype(jnp.bfloat16), g.astype(jnp.bfloat16), seg
    ).astype(jnp.float32)
    want = packed_global_attention_apply(
        params, x.astype(jnp.bfloat16), g.astype(jnp.bfloat16), seg
    ).astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=0.05, atol=0.05)


def test_force_reference_env_override(attn_inputs, monkeypatch):
    """PBT_FORCE_REFERENCE_KERNEL (the kernel-family-wide debug
    override, ISSUE 13 satellite) routes the attention dispatch onto
    the reference path — bit-identical to calling the reference
    directly, counted as reason=forced."""
    from proteinbert_tpu.kernels import fused_block as fb

    params, x, g = attn_inputs
    seg = _seg_rows([(1, 200)], [(1, L)])
    monkeypatch.setenv(fb.FORCE_REFERENCE_ENV, "0")
    before = dict(ka.ATTN_PATH_TOTAL)
    _ = ka.fused_packed_attention(params, x, g, seg)
    assert (ka.ATTN_PATH_TOTAL.get(("reference", "forced"), 0)
            == before.get(("reference", "forced"), 0))
    monkeypatch.setenv(fb.FORCE_REFERENCE_ENV, "1")
    before = ka.ATTN_PATH_TOTAL.get(("reference", "forced"), 0)
    got = ka.fused_packed_attention(params, x, g, seg)
    assert ka.ATTN_PATH_TOTAL.get(("reference", "forced"), 0) == before + 1
    want = packed_global_attention_apply(params, x, g, seg)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # The dense entry honors it too.
    g2 = jnp.zeros((B, G), jnp.float32)
    got_d = ka.fused_global_attention(params, x, g2)
    assert ka.ATTN_PATH_TOTAL.get(("reference", "forced"), 0) == before + 2
    np.testing.assert_array_equal(
        np.asarray(got_d),
        np.asarray(global_attention_apply(params, x, g2)))


def test_supported_gating():
    sup = ka.pallas_attention_supported
    assert sup(128, 64, 256, 4, 16, 4, "float32")
    assert sup(512, 512, 512, 8, 64, 4)          # base config, bf16
    # Attention weights are tiny — Large C=1024 prices in (the whole
    # point: no supported shape leaves the fast path).
    assert sup(1024, 512, 512, 8, 64, 4)
    assert not sup(96, 64, 256, 4, 16, 4)        # non-lane-aligned C
    assert not sup(4096, 512, 512, 8, 64, 4)     # beyond MAX_TILED_DIM
    assert not sup(128, 64, 4, 4, 16, 4)         # seq too short
    assert not sup(128, 64, 256, 0, 16, 4)       # no segments
    assert not sup(128, 63, 256, 4, 16, 4)       # G % heads != 0
    # A very long row at fp32 blows the VMEM price.
    assert not sup(512, 512, 16384, 64, 64, 4, "float32")


def test_model_level_wiring_packed_and_dense(attn_inputs):
    """block_apply routes BOTH forms through the ONE-PASS trunk
    dispatch under use_pallas (ISSUE 16): a packed forward and a dense
    forward each bump the onepass (path=pallas) counters — NOT the
    per-kernel families, which only count when the one-pass plan
    doesn't fit — and match the reference config ≤1e-5."""
    from proteinbert_tpu.kernels import one_pass as op

    cfg = ModelConfig(local_dim=C, global_dim=G, key_dim=KD, num_heads=H,
                      num_blocks=1, num_annotations=16, dtype="float32",
                      use_pallas=True)
    rcfg = ModelConfig(**{**cfg.__dict__, "use_pallas": False})
    params = proteinbert.init(jax.random.PRNGKey(1), cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(4, 26, size=(B, L)).astype(np.int32))
    seg = _seg_rows([(1, 100), (2, 80)], [(1, L)])
    tokens = jnp.where(seg > 0, tokens, 0)
    ann = jnp.asarray((rng.random((B, S, 16)) < 0.1).astype(np.float32))
    assert op.pallas_onepass_supported(C, G, L, S, KD, H, "float32")
    before = dict(op.ONEPASS_PATH_TOTAL)
    out_f = proteinbert.apply(params, tokens, ann, cfg, segment_ids=seg)
    assert (op.ONEPASS_PATH_TOTAL.get(("pallas", "packed"), 0)
            > before.get(("pallas", "packed"), 0))
    out_r = proteinbert.apply(params, tokens, ann, rcfg, segment_ids=seg)
    for a, b in zip(out_f, out_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)
    # Dense (unpacked) form — the bucketed-serving executable shape.
    ann_d = jnp.asarray((rng.random((B, 16)) < 0.1).astype(np.float32))
    before = dict(op.ONEPASS_PATH_TOTAL)
    out_fd = proteinbert.apply(params, tokens, ann_d, cfg)
    assert (op.ONEPASS_PATH_TOTAL.get(("pallas", "dense"), 0)
            > before.get(("pallas", "dense"), 0))
    out_rd = proteinbert.apply(params, tokens, ann_d, rcfg)
    for a, b in zip(out_fd, out_rd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)
