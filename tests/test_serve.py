"""Online serving subsystem tests (proteinbert_tpu/serve/, ISSUE 5).

Two tiers in one file:

- **pure-logic tests** (queue, cache, scheduler formation) run against
  stub dispatchers and a fake clock — no jax, microseconds each. The
  scheduler is exercised through `poll(now=)` single-threaded, so batch
  formation is a deterministic function of arrival order and the clock.
- **end-to-end tests** share one tiny untrained trunk (module fixture)
  and prove the serving results against the offline inference surface:
  served-vs-offline `embed` BIT-parity per bucket, drain with nothing
  lost, cache short-circuits, HTTP status mapping, and `serve_*`
  events that round-trip the schema validator.
"""

import json
import logging
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

import jax

from proteinbert_tpu import inference
from proteinbert_tpu.configs import (
    CheckpointConfig, DataConfig, ModelConfig, OptimizerConfig,
    PretrainConfig, TrainConfig,
)
from proteinbert_tpu.serve import (
    BucketDispatcher, DeadlineExceededError, EmbeddingCache,
    MicroBatchScheduler, QueueFullError, Request, RequestQueue,
    SequenceTooLongError, Server, ServerClosedError, content_key,
)
from proteinbert_tpu.serve.dispatch import (
    default_batch_classes, resolve_buckets,
)
from proteinbert_tpu.train import create_train_state

SEQ_LEN = 48
BUCKETS = (16, 32, 48)


def _cfg():
    return PretrainConfig(
        model=ModelConfig(local_dim=16, global_dim=32, key_dim=8,
                          num_heads=2, num_blocks=2, num_annotations=32,
                          dtype="float32"),
        data=DataConfig(seq_len=SEQ_LEN, batch_size=4),
        optimizer=OptimizerConfig(warmup_steps=5),
        train=TrainConfig(seed=0, max_steps=1),
        checkpoint=CheckpointConfig(),
    )


@pytest.fixture(scope="module")
def trunk():
    cfg = _cfg()
    state = create_train_state(jax.random.PRNGKey(cfg.train.seed), cfg)
    return state.params, cfg


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


def _req(kind="embed", seq="MKT", bucket_len=16, clock=None, deadline=None,
         tokens=None):
    if tokens is None:
        tokens = np.zeros(bucket_len, np.int32)
    return Request(kind=kind, seq=seq, tokens=tokens, bucket_len=bucket_len,
                   future=Future(), enqueued_at=clock() if clock else 0.0,
                   deadline=deadline)


# ---------------------------------------------------------------- queue

class TestRequestQueue:
    def test_push_pop_fifo(self):
        q = RequestQueue(max_depth=4)
        reqs = [_req(seq=s) for s in "abc"]
        for r in reqs:
            q.push(r)
        assert len(q) == 3
        assert q.pop_all() == reqs
        assert len(q) == 0

    def test_overflow_evicts_oldest_with_typed_error(self):
        q = RequestQueue(max_depth=2)
        a, b, c = (_req(seq=s) for s in "abc")
        assert q.push(a) == []
        assert q.push(b) == []
        evicted = q.push(c)
        assert evicted == [a]
        assert q.evicted_total == 1
        with pytest.raises(QueueFullError):
            a.future.result(timeout=0)
        # The newer requests survive, in order.
        assert q.pop_all() == [b, c]

    def test_closed_queue_rejects_push_keeps_drain(self):
        q = RequestQueue(max_depth=4)
        r = _req()
        q.push(r)
        q.close()
        with pytest.raises(ServerClosedError):
            q.push(_req())
        assert q.pop_all() == [r]  # queued work survives the close

    def test_fail_all_empties_onto_exception(self):
        q = RequestQueue(max_depth=4)
        reqs = [_req(seq=s) for s in "ab"]
        for r in reqs:
            q.push(r)
        exc = ServerClosedError("aborted")
        assert q.fail_all(exc) == reqs
        for r in reqs:
            with pytest.raises(ServerClosedError):
                r.future.result(timeout=0)
        assert len(q) == 0


# ---------------------------------------------------------------- cache

class TestEmbeddingCache:
    def test_hit_miss_eviction_counters(self):
        c = EmbeddingCache(capacity=2)
        k1, k2, k3 = (content_key("embed", s) for s in ("a", "b", "c"))
        assert c.get(k1) is None and c.misses == 1
        c.put(k1, 1)
        c.put(k2, 2)
        assert c.get(k1) == 1 and c.hits == 1
        c.put(k3, 3)  # k2 is now LRU → evicted
        assert c.evictions == 1
        assert c.get(k2) is None
        assert c.get(k1) == 1 and c.get(k3) == 3
        assert c.stats()["size"] == 2
        assert 0.0 < c.hit_rate < 1.0

    def test_content_key_addresses_content(self):
        base = content_key("embed", "MKT")
        assert content_key("embed", "MKT") == base
        assert content_key("predict_go", "MKT") != base
        assert content_key("embed", "MKV") != base
        ann = np.zeros(4, np.float32)
        with_ann = content_key("embed", "MKT", ann)
        assert with_ann != base  # None != explicit all-zero vector
        ann2 = ann.copy()
        ann2[1] = 1.0
        assert content_key("embed", "MKT", ann2) != with_ann

    def test_capacity_zero_disables(self):
        c = EmbeddingCache(capacity=0)
        c.put("k", 1)
        assert c.get("k") is None
        assert len(c) == 0


# ------------------------------------------------- scheduler (fake clock)

class FakeDispatcher:
    """Stub with the dispatcher surface the scheduler touches; records
    every dispatched batch and echoes row indices as results."""

    def __init__(self, fail_kinds=()):
        self.cfg = type("C", (), {})()
        self.cfg.model = type("M", (), {"num_annotations": 4})()
        self.batches = []
        self.fail_kinds = set(fail_kinds)

    def batch_class(self, rows):
        c = 1
        while c < rows:
            c *= 2
        return c

    def run(self, kind, tokens, annotations=None):
        if kind in self.fail_kinds:
            raise RuntimeError(f"injected dispatch failure for {kind}")
        self.batches.append((kind, tokens.shape))
        return np.arange(tokens.shape[0], dtype=np.float32)


def _sched(queue, dispatcher, clock, **kw):
    done = []
    s = MicroBatchScheduler(
        queue, dispatcher, lambda req, row: done.append((req, row))
        or req.future.set_result(row),
        clock=clock, **kw)
    return s, done


class TestSchedulerFormation:
    def test_full_group_dispatches_immediately(self):
        clock = FakeClock()
        q = RequestQueue()
        d = FakeDispatcher()
        s, done = _sched(q, d, clock, max_batch=3, max_wait_s=10.0)
        for i in range(3):
            q.push(_req(seq=f"s{i}", clock=clock))
        assert s.poll() == 3  # full batch: no wait needed
        assert [r.seq for r, _ in done] == ["s0", "s1", "s2"]  # FIFO
        assert d.batches == [("embed", (3, 16))]
        assert s.poll() == 0

    def test_underfull_group_waits_for_max_wait(self):
        clock = FakeClock()
        q = RequestQueue()
        s, done = _sched(q, FakeDispatcher(), clock,
                         max_batch=8, max_wait_s=0.5)
        q.push(_req(seq="a", clock=clock))
        assert s.poll() == 0          # not full, not old enough
        clock.advance(0.49)
        assert s.poll() == 0
        clock.advance(0.02)           # head is now past max_wait
        assert s.poll() == 1
        assert done[0][0].seq == "a"

    def test_groups_split_by_kind_and_bucket(self):
        clock = FakeClock()
        q = RequestQueue()
        d = FakeDispatcher()
        s, _ = _sched(q, d, clock, max_batch=2, max_wait_s=10.0)
        q.push(_req(kind="embed", bucket_len=16, clock=clock))
        q.push(_req(kind="embed", bucket_len=32, clock=clock,
                    tokens=np.zeros(32, np.int32)))
        q.push(_req(kind="predict_go", bucket_len=16, clock=clock))
        assert s.poll() == 0  # three singleton groups, none full/overdue
        q.push(_req(kind="embed", bucket_len=16, clock=clock))
        assert s.poll() == 2  # (embed, 16) reached max_batch
        assert d.batches == [("embed", (2, 16))]

    def test_fullest_group_wins_tie_to_oldest(self):
        clock = FakeClock()
        q = RequestQueue()
        d = FakeDispatcher()
        s, _ = _sched(q, d, clock, max_batch=2, max_wait_s=10.0)
        q.push(_req(kind="predict_go", bucket_len=16, clock=clock))
        q.push(_req(kind="embed", bucket_len=16, clock=clock))
        q.push(_req(kind="embed", bucket_len=16, clock=clock))
        assert s.poll() == 2           # embed group is full; go is not
        assert d.batches[0][0] == "embed"
        clock.advance(11.0)
        assert s.poll() == 1           # go group dispatches on max_wait
        assert d.batches[1][0] == "predict_go"

    def test_oversize_group_dispatches_in_max_batch_chunks(self):
        clock = FakeClock()
        q = RequestQueue(max_depth=16)
        d = FakeDispatcher()
        s, done = _sched(q, d, clock, max_batch=4, max_wait_s=10.0)
        for i in range(6):
            q.push(_req(seq=f"s{i}", clock=clock))
        assert s.poll() == 4
        clock.advance(11.0)            # remainder rides the wait trigger
        assert s.poll() == 2
        assert [b[1][0] for b in d.batches] == [4, 2]
        assert [r.seq for r, _ in done] == [f"s{i}" for i in range(6)]

    def test_pending_deadline_expiry(self):
        clock = FakeClock()
        q = RequestQueue()
        s, done = _sched(q, FakeDispatcher(), clock,
                         max_batch=4, max_wait_s=0.1)
        late = _req(seq="late", clock=clock, deadline=clock.t + 0.05)
        fine = _req(seq="fine", clock=clock)
        q.push(late)
        q.push(fine)
        assert s.poll() == 0           # ingested, neither trigger fired
        clock.advance(0.2)             # late expired AND group overdue
        assert s.poll() == 1
        with pytest.raises(DeadlineExceededError):
            late.future.result(timeout=0)
        assert s.expired_total == 1
        assert [r.seq for r, _ in done] == ["fine"]

    def test_dispatch_failure_fails_batch_keeps_scheduler(self):
        clock = FakeClock()
        q = RequestQueue()
        d = FakeDispatcher(fail_kinds={"embed"})
        s, done = _sched(q, d, clock, max_batch=2, max_wait_s=10.0)
        bad = [_req(kind="embed", clock=clock) for _ in range(2)]
        for r in bad:
            q.push(r)
        assert s.poll() == 2
        for r in bad:
            with pytest.raises(RuntimeError, match="injected"):
                r.future.result(timeout=0)
        ok = [_req(kind="predict_go", clock=clock) for _ in range(2)]
        for r in ok:
            q.push(r)
        assert s.poll() == 2           # still serving after the failure
        assert len(done) == 2

    def test_drain_flushes_underfull_groups(self):
        clock = FakeClock()
        q = RequestQueue()
        s, done = _sched(q, FakeDispatcher(), clock,
                         max_batch=8, max_wait_s=60.0)
        q.push(_req(seq="a", clock=clock))
        q.push(_req(seq="b", clock=clock))
        assert s.poll() == 0           # neither trigger fired
        q.close()                      # drain: closed queue flushes
        assert s.poll() == 2
        assert len(done) == 2


# ------------------------------- pipelined dispatch window (ISSUE 19)

class AsyncFakeDispatcher(FakeDispatcher):
    """FakeDispatcher wearing the `run_timed_async` in-flight surface:
    submit records the batch and returns a handle; the row-index echo
    materializes only at finalize() — device completion decoupled from
    the host fetch, like the real BucketDispatcher. An optional
    `finalize_gate` Event holds every finalize until set, so threaded
    tests can pin work in flight deterministically."""

    def __init__(self, fail_kinds=(), finalize_gate=None):
        super().__init__(fail_kinds)
        self.finalized = []
        self.finalize_gate = finalize_gate

    def run_timed_async(self, kind, tokens, annotations=None,
                        timed=False, **extra):
        if kind in self.fail_kinds:
            raise RuntimeError(f"injected dispatch failure for {kind}")
        self.batches.append((kind, tokens.shape))
        disp = self

        class _Handle:
            def finalize(self):
                if disp.finalize_gate is not None:
                    disp.finalize_gate.wait(10)
                disp.finalized.append((kind, tokens.shape))
                return (np.arange(tokens.shape[0], dtype=np.float32), {})

        return _Handle()


class TestPipelinedWindow:
    def test_fake_clock_formation_deterministic_with_async_dispatch(self):
        """Single-threaded poll() has no completer, so the async entry
        sync-drains: formation, seal order, and results are
        byte-for-byte what the blocking stub produced — the fake-clock
        determinism contract survives the pipeline."""
        results = []
        for d in (FakeDispatcher(), AsyncFakeDispatcher()):
            clock = FakeClock()
            q = RequestQueue(max_depth=16)
            s, done = _sched(q, d, clock, max_batch=4, max_wait_s=0.5)
            for i in range(6):
                q.push(_req(seq=f"s{i}", clock=clock))
            assert s.poll() == 4       # full group, sealed before return
            assert len(done) == 4
            clock.advance(0.6)
            assert s.poll() == 2       # remainder on the wait trigger
            assert s.poll() == 0
            results.append((
                [r.seq for r, _ in done],
                [b[1] for b in d.batches],
                [float(r.future.result(timeout=0)) for r, _ in done]))
        assert results[0] == results[1]

    def test_sync_drain_never_accumulates_inflight(self):
        q = RequestQueue()
        s, _ = _sched(q, AsyncFakeDispatcher(), FakeClock(),
                      max_batch=2, max_wait_s=10.0)
        for i in range(4):
            q.push(_req(seq=f"s{i}"))
        assert s.poll() == 2 and s.poll() == 2
        stats = s.pipeline_stats()
        assert stats["inflight_max"] == 1   # submit → inline finalize
        assert stats["finalize_seconds_total"] > 0.0

    def test_submit_failure_rides_window_fails_batch_keeps_scheduler(self):
        clock = FakeClock()
        q = RequestQueue()
        d = AsyncFakeDispatcher(fail_kinds={"embed"})
        s, done = _sched(q, d, clock, max_batch=2, max_wait_s=10.0)
        bad = [_req(kind="embed", clock=clock) for _ in range(2)]
        for r in bad:
            q.push(r)
        assert s.poll() == 2
        for r in bad:
            with pytest.raises(RuntimeError, match="injected"):
                r.future.result(timeout=0)
        ok = [_req(kind="predict_go", clock=clock) for _ in range(2)]
        for r in ok:
            q.push(r)
        assert s.poll() == 2           # still serving after the failure
        assert len(done) == 2

    def _run_threaded(self, n_requests, finish):
        """Start a real scheduler+completer, pin the FIRST finalize
        behind a gate until `n_requests/4` batches are submitted (work
        genuinely in flight), then run `finish(s, q, reqs)` and join.
        Returns (scheduler, dispatcher, reqs, done)."""
        gate = threading.Event()
        d = AsyncFakeDispatcher(finalize_gate=gate)
        q = RequestQueue(max_depth=2 * n_requests)
        done = []
        s = MicroBatchScheduler(
            q, d, lambda req, row: done.append(req)
            or req.future.set_result(row),
            max_batch=4, max_wait_s=0.005, pipeline_depth=2)
        reqs = [_req(seq=f"s{i}") for i in range(n_requests)]
        for r in reqs:
            q.push(r)
        s.start()
        # Completer blocks on the gate; the scheduler keeps submitting
        # until the depth-2 window is full — batches pile up in flight.
        deadline = time.monotonic() + 5.0
        while len(d.batches) < 3 and time.monotonic() < deadline:
            time.sleep(0.002)
        assert len(d.batches) >= 3, "scheduler never filled the window"
        finish(s, q, reqs)
        gate.set()
        assert s.join(10), "scheduler thread failed to drain"
        return s, d, reqs, done

    def test_drain_with_batches_in_flight_seals_exactly_once(self):
        s, d, reqs, done = self._run_threaded(
            12, lambda s, q, reqs: q.close())
        # Every future sealed exactly once, results correct, nothing
        # finalized twice.
        assert len(done) == len(reqs)
        assert len({id(r) for r in done}) == len(reqs)
        for r in reqs:
            assert r.future.done() and r.future.exception() is None
        assert len(d.finalized) == len(d.batches) == 3
        assert s.stats_counts()[:2] == (3, 12)
        # The window genuinely overlapped: gate held finalize #1 while
        # later batches were submitted into the depth-2 window.
        assert s.pipeline_stats()["inflight_max"] == 2

    def test_abort_with_batch_in_flight_seals_exactly_once(self):
        boom = ServerClosedError("aborted")

        def finish(s, q, reqs):
            s.stop()  # abort: loop exits, epilogue resolves the window

        s, d, reqs, done = self._run_threaded(16, finish)
        failed = s.fail_pending(boom)  # what Server.abort does next
        # Disjoint exactly-once partition: every submitted batch's rows
        # sealed ok by the drain epilogue, every undispatched row
        # failed with the abort error — no request in both, none lost.
        sealed = {id(r) for r in done}
        aborted = {id(r) for r in failed}
        assert not (sealed & aborted)
        assert len(sealed) + len(aborted) == len(reqs)
        assert len(done) == len(d.finalized) * 4
        for r in reqs:
            assert r.future.done()
            exc = r.future.exception()
            assert exc is None or exc is boom


# --------------------------------------------------- dispatcher routing

class TestDispatchRouting:
    def test_resolve_buckets_validation(self, trunk):
        _, cfg = trunk
        assert resolve_buckets(cfg) == (SEQ_LEN,)
        assert resolve_buckets(cfg, BUCKETS) == BUCKETS
        with pytest.raises(ValueError, match="ascending"):
            resolve_buckets(cfg, (32, 16, 48))
        with pytest.raises(ValueError, match="seq_len"):
            resolve_buckets(cfg, (16, 32))
        with pytest.raises(ValueError, match="ints"):
            resolve_buckets(cfg, ("a", 48))

    def test_default_batch_classes(self):
        assert default_batch_classes(8) == (1, 2, 4, 8)
        assert default_batch_classes(12) == (1, 2, 4, 8, 12)
        assert default_batch_classes(1) == (1,)

    def test_default_batch_classes_mesh_multiple(self):
        # Mesh-aware ladder: every rung divisible by the replica count
        # (data*fsdp extent), so `pbt serve --mesh` starts out of the box.
        assert default_batch_classes(16, multiple=4) == (4, 8, 16)
        assert default_batch_classes(8, multiple=8) == (8,)
        assert default_batch_classes(12, multiple=2) == (2, 4, 8, 12)
        with pytest.raises(ValueError, match="not divisible"):
            default_batch_classes(8, multiple=3)

    def test_bucket_and_class_routing(self, trunk):
        params, cfg = trunk
        d = BucketDispatcher(params, cfg, buckets=BUCKETS, max_batch=8)
        assert d.bucket_len(10) == 16   # 12 tokens with sos/eos
        assert d.bucket_len(14) == 16
        assert d.bucket_len(15) == 32
        assert d.bucket_len(46) == SEQ_LEN
        assert d.bucket_len(1000) == SEQ_LEN  # over-window caps
        assert d.batch_class(1) == 1
        assert d.batch_class(3) == 4
        with pytest.raises(ValueError, match="exceed"):
            d.batch_class(9)


# ------------------------------------------------------- e2e: parity

@pytest.fixture(scope="module")
def server(trunk):
    params, cfg = trunk
    srv = Server(params, cfg, buckets=BUCKETS, max_batch=4,
                 max_wait_s=0.002, queue_depth=64, cache_size=32,
                 warm_kinds=())
    srv.start()
    yield srv
    srv.close(drain=True, timeout=30)


# Lengths chosen to hit all three buckets.
RAGGED = ["MKTAYIAKQR", "ACDEFGHIKLMNPQRSTVWY", "GG",
          "ACDEFGHIKLMNPQRSTVWY" * 2, "MKTAYIAKQRMKTAYIAKQRAC"]


class TestServedParity:
    def test_served_embed_bit_parity_per_bucket(self, trunk):
        """A full micro-batch of same-bucket requests, formed
        deterministically through submit()+poll(), must be BIT-identical
        to the offline bucketed path: both run the same jitted kernel at
        the same (bucket_len, batch_class) shape."""
        params, cfg = trunk
        for bucket, seqs in ((16, ["MKTAYIAKQR", "GG", "ACDEF", "MKT"]),
                             (32, ["ACDEFGHIKLMNPQRSTVWY"] * 4)):
            srv = Server(params, cfg, buckets=BUCKETS, max_batch=4,
                         max_wait_s=60.0, cache_size=0, warm_kinds=())
            # No scheduler thread: form the batch by hand for determinism.
            futures = [srv.submit("embed", s) for s in seqs]
            assert srv.scheduler.poll() == 4
            served = [f.result(timeout=0) for f in futures]
            offline = inference.embed(params, cfg, seqs, bucketed=True,
                                      buckets=BUCKETS, batch_size=4)
            for i, row in enumerate(served):
                assert srv.dispatcher.bucket_len(len(seqs[i])) == bucket
                np.testing.assert_array_equal(row["global"],
                                              offline["global"][i])
                np.testing.assert_array_equal(row["local_mean"],
                                              offline["local_mean"][i])

    def test_sync_facade_ragged_traffic(self, server, trunk):
        params, cfg = trunk
        offline = inference.embed(params, cfg, RAGGED, bucketed=True,
                                  buckets=BUCKETS, batch_size=4)
        for i, seq in enumerate(RAGGED):
            got = server.embed(seq, timeout=30)
            np.testing.assert_allclose(got["global"], offline["global"][i],
                                       rtol=2e-5, atol=2e-5)

    def test_predict_go_and_top_k(self, server, trunk):
        params, cfg = trunk
        probs = server.predict_go(RAGGED[0], timeout=30)
        assert probs.shape == (cfg.model.num_annotations,)
        assert ((probs >= 0) & (probs <= 1)).all()
        top = server.predict_go(RAGGED[0], top_k=3, timeout=30)
        assert len(top) == 3
        assert top[0][1] >= top[1][1] >= top[2][1]
        assert top[0][1] == pytest.approx(float(probs.max()), rel=1e-6)

    def test_predict_residues_fills_masks(self, server):
        filled, probs = server.predict_residues("MK?AYIA?QR", timeout=30)
        assert len(filled) == 10
        assert "?" not in filled
        assert filled[0] == "M" and filled[3] == "A"  # unmasked untouched
        assert probs.shape[0] >= 12  # bucket length ≥ tokenized length

    def test_concurrent_clients(self, server, trunk):
        params, cfg = trunk
        offline = inference.embed(params, cfg, RAGGED, bucketed=True,
                                  buckets=BUCKETS, batch_size=4)
        results = {}

        def client(i):
            results[i] = server.embed(RAGGED[i % len(RAGGED)], timeout=30)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert len(results) == 12
        for i, got in results.items():
            np.testing.assert_allclose(
                got["global"], offline["global"][i % len(RAGGED)],
                rtol=2e-5, atol=2e-5)


# ------------------------------------------- e2e: cache + backpressure

class TestServerContracts:
    def test_cache_short_circuits_repeats(self, trunk):
        params, cfg = trunk
        srv = Server(params, cfg, buckets=BUCKETS, max_batch=2,
                     max_wait_s=0.002, cache_size=8, warm_kinds=())
        with srv:
            first = srv.embed("MKTAYIAKQR", timeout=30)
            assert srv.cache.misses >= 1
            hits_before = srv.cache.hits
            again = srv.embed("MKTAYIAKQR", timeout=30)
            assert srv.cache.hits == hits_before + 1
            assert srv.cache_hit_returns == 1
            np.testing.assert_array_equal(first["global"], again["global"])

    def test_queue_overflow_rejected_not_dropped(self, trunk):
        params, cfg = trunk
        srv = Server(params, cfg, buckets=BUCKETS, max_batch=4,
                     max_wait_s=60.0, queue_depth=2, cache_size=0,
                     warm_kinds=())
        # Scheduler never started: the queue can only fill.
        futures = [srv.submit("embed", s) for s in ("MKT", "ACD", "GGG")]
        with pytest.raises(QueueFullError):
            futures[0].result(timeout=0)       # oldest evicted
        assert srv.rejected_total["queue_full"] == 1
        assert not futures[1].done() and not futures[2].done()
        srv.abort()                            # survivors observe the end
        for f in futures[1:]:
            with pytest.raises(ServerClosedError):
                f.result(timeout=5)

    def test_deadline_expiry_e2e(self, trunk):
        params, cfg = trunk
        clock = FakeClock()
        srv = Server(params, cfg, buckets=BUCKETS, max_batch=8,
                     max_wait_s=60.0, cache_size=0, warm_kinds=(),
                     clock=clock)
        f = srv.submit("embed", "MKT", deadline_s=0.5)
        clock.advance(1.0)
        assert srv.scheduler.poll() == 0
        with pytest.raises(DeadlineExceededError):
            f.result(timeout=0)
        # An expiry IS a rejection: it must land in the rejected stats
        # (and thus /metrics and the CLI's --max-requests accounting),
        # not only in scheduler.expired_total.
        assert srv.stats()["rejected"]["deadline"] == 1
        assert srv.scheduler.expired_total == 1

    def test_drain_completes_queued_work(self, trunk):
        """Nothing in flight is lost: requests queued behind a long
        max_wait all complete when the server drains."""
        params, cfg = trunk
        srv = Server(params, cfg, buckets=BUCKETS, max_batch=8,
                     max_wait_s=60.0, cache_size=0, warm_kinds=())
        srv.start()
        futures = [srv.submit("embed", s) for s in RAGGED]
        assert srv.drain(timeout=60)
        for f in futures:
            out = f.result(timeout=0)          # resolved, not dropped
            assert np.isfinite(out["global"]).all()
        assert srv.completed_total == len(RAGGED)
        with pytest.raises(ServerClosedError):
            srv.submit("embed", "MKT")
        assert srv.rejected_total["closed"] == 1

    def test_abort_fails_pending_with_typed_error(self, trunk):
        params, cfg = trunk
        srv = Server(params, cfg, buckets=BUCKETS, max_batch=8,
                     max_wait_s=60.0, cache_size=0, warm_kinds=())
        futures = [srv.submit("embed", s) for s in ("MKT", "ACD")]
        srv.abort()
        for f in futures:
            with pytest.raises(ServerClosedError):
                f.result(timeout=0)

    def test_on_long_reject_and_truncate(self, trunk):
        params, cfg = trunk
        window = cfg.data.seq_len - 2
        long_seq = "A" * (window + 10)
        rej = Server(params, cfg, buckets=BUCKETS, on_long="reject",
                     cache_size=0, warm_kinds=())
        with pytest.raises(SequenceTooLongError):
            rej.submit("embed", long_seq)
        assert rej.rejected_total["too_long"] == 1
        tr = Server(params, cfg, buckets=BUCKETS, on_long="truncate",
                    max_batch=1, max_wait_s=0.002, cache_size=0,
                    warm_kinds=())
        with tr:
            out = tr.embed(long_seq, timeout=30)
            assert tr.truncated_total == 1
            assert np.isfinite(out["global"]).all()
            # A '?' beyond the window can never be filled → reject even
            # under truncate.
            with pytest.raises(SequenceTooLongError):
                tr.submit("predict_residues", "A" * window + "?")


# -------------------------------------------- satellite: tokenization

class TestTokenizeOverflow:
    @pytest.fixture(autouse=True)
    def _propagate_package_logger(self):
        """utils.logging.start_log() (run by any earlier in-process CLI
        test) sets propagate=False on the package logger, which hides
        records from caplog's root handler — restore propagation for
        the duration of these assertions."""
        pkg = logging.getLogger("proteinbert_tpu")
        saved = pkg.propagate
        pkg.propagate = True
        yield
        pkg.propagate = saved

    def test_error_mode_raises_typed(self):
        with pytest.raises(SequenceTooLongError, match="model window"):
            inference._tokenize_masked(["A" * 47], 48, on_overflow="error")

    def test_warn_mode_counts_and_logs(self, caplog):
        before = inference.TRUNCATED_TOTAL[0]
        with caplog.at_level("WARNING", logger="proteinbert_tpu.inference"):
            out = inference._tokenize_masked(["A" * 50, "MKT"], 48)
        assert inference.TRUNCATED_TOTAL[0] == before + 1
        assert any("truncating" in r.message for r in caplog.records)
        assert out.shape == (2, 48)

    def test_count_mode_is_quiet(self, caplog):
        before = inference.TRUNCATED_TOTAL[0]
        with caplog.at_level("WARNING", logger="proteinbert_tpu.inference"):
            inference._tokenize_masked(["A" * 50], 48, on_overflow="count")
        assert inference.TRUNCATED_TOTAL[0] == before + 1
        assert not caplog.records

    def test_in_window_never_counts(self):
        before = inference.TRUNCATED_TOTAL[0]
        inference._tokenize_masked(["A" * 46], 48, on_overflow="error")
        assert inference.TRUNCATED_TOTAL[0] == before

    def test_bad_mode_rejected(self):
        with pytest.raises(ValueError, match="on_overflow"):
            inference._tokenize_masked(["MKT"], 48, on_overflow="quiet")


# --------------------------------------- satellite: offline bucketed=

class TestOfflineBucketed:
    def test_full_length_bucket_bit_identical(self, trunk):
        """buckets=(seq_len,) feeds the exact shapes the unbucketed path
        feeds → bit-identical results (the satellite's contract)."""
        params, cfg = trunk
        plain = inference.embed(params, cfg, RAGGED, batch_size=4)
        bucketed = inference.embed(params, cfg, RAGGED, batch_size=4,
                                   bucketed=True, buckets=(SEQ_LEN,))
        for k in plain:
            np.testing.assert_array_equal(plain[k], bucketed[k])

    def test_bucket_results_independent_of_traffic_mix(self, trunk):
        """The serving determinism contract: a sequence's bucketed
        result depends only on its own bucket — never on which other
        rows rode in the batch or in which order. (Cross-SHAPE equality
        is deliberately NOT claimed: the reference architecture's convs
        read the pad tail near boundaries, so the padded length is part
        of the model function — docs/serving.md. Per-shape parity is
        the contract, proved bit-exact above and in
        test_full_length_bucket_bit_identical.)"""
        params, cfg = trunk
        solo = inference.embed(params, cfg, [RAGGED[0]], batch_size=4,
                               bucketed=True, buckets=BUCKETS)
        mixed = inference.embed(params, cfg, RAGGED, batch_size=4,
                                bucketed=True, buckets=BUCKETS)
        shuffled = inference.embed(params, cfg, RAGGED[::-1], batch_size=4,
                                   bucketed=True, buckets=BUCKETS)
        np.testing.assert_array_equal(solo["global"][0],
                                      mixed["global"][0])
        np.testing.assert_array_equal(mixed["global"],
                                      shuffled["global"][::-1])

    def test_predict_go_bucketed(self, trunk):
        params, cfg = trunk
        plain = inference.predict_go(params, cfg, RAGGED, batch_size=4)
        full = inference.predict_go(params, cfg, RAGGED, batch_size=4,
                                    bucketed=True, buckets=(SEQ_LEN,))
        np.testing.assert_array_equal(full, plain)  # equal lengths: bits
        bucketed = inference.predict_go(params, cfg, RAGGED, batch_size=4,
                                        bucketed=True, buckets=BUCKETS)
        assert bucketed.shape == plain.shape
        assert ((bucketed >= 0) & (bucketed <= 1)).all()
        top = inference.predict_go(params, cfg, RAGGED[:1], top_k=3,
                                   bucketed=True, buckets=BUCKETS)
        assert len(top[0]) == 3

    def test_predict_residues_bucketed_zero_fills_tail(self, trunk):
        params, cfg = trunk
        seqs = ["MK?AYIA?QR", "AC?EF"]
        plain_f, plain_p = inference.predict_residues(params, cfg, seqs,
                                                      batch_size=4)
        full_f, full_p = inference.predict_residues(
            params, cfg, seqs, batch_size=4, bucketed=True,
            buckets=(SEQ_LEN,))
        assert full_f == plain_f           # equal lengths: same fills
        np.testing.assert_array_equal(full_p, plain_p)
        buck_f, buck_p = inference.predict_residues(
            params, cfg, seqs, batch_size=4, bucketed=True, buckets=BUCKETS)
        assert "?" not in "".join(buck_f)
        assert buck_p.shape == plain_p.shape
        assert (buck_p[0, :16] > 0).any()
        assert (buck_p[0, 16:] == 0).all()  # beyond the bucket: zeros
        assert (buck_p[1, 16:] == 0).all()

    def test_per_residue_incompatible(self, trunk):
        params, cfg = trunk
        with pytest.raises(ValueError, match="per_residue"):
            inference.embed(params, cfg, RAGGED, bucketed=True,
                            per_residue=True)


# ----------------------------------------------- e2e: telemetry + HTTP

class TestServeTelemetry:
    def test_events_validate_and_cover_lifecycle(self, trunk, tmp_path):
        from proteinbert_tpu.obs import Telemetry, read_events
        from proteinbert_tpu.obs.events import validate_record

        params, cfg = trunk
        path = str(tmp_path / "events.jsonl")
        tele = Telemetry(events_path=path)
        srv = Server(params, cfg, buckets=BUCKETS, max_batch=4,
                     max_wait_s=0.002, queue_depth=2, cache_size=8,
                     warm_kinds=(), telemetry=tele)
        srv.start()
        srv.embed("MKTAYIAKQR", timeout=30)
        srv.embed("MKTAYIAKQR", timeout=30)  # cache hit
        srv.drain(timeout=30)
        tele.close()
        recs = list(read_events(path))
        for rec in recs:
            validate_record(rec)
        kinds = [r["event"] for r in recs]
        assert kinds[0] == "serve_start"
        assert "serve_batch" in kinds
        assert kinds[-1] == "serve_end"
        end = recs[-1]
        assert end["outcome"] == "drained"
        assert end["stats"]["completed"] == 1
        assert end["stats"]["cache_hit_returns"] == 1
        batch = next(r for r in recs if r["event"] == "serve_batch")
        assert batch["bucket_len"] == 16 and batch["rows"] == 1
        # Metrics registry carries the serve instruments.
        snap = tele.metrics.snapshot()
        assert snap["counters"]['serve_requests_total{kind="embed"}'] == 2
        assert snap["counters"]["serve_cache_hits_total"] == 1
        assert snap["histograms"]["serve_latency_seconds"]["count"] == 1

    def test_validator_knows_serve_events(self):
        from proteinbert_tpu.obs.events import make_example, validate_record

        for event in ("serve_start", "serve_batch", "serve_reject",
                      "serve_end"):
            validate_record(make_example(event))
        with pytest.raises(ValueError, match="serve_end.outcome"):
            validate_record({**make_example("serve_end"),
                             "outcome": "bogus"})
        with pytest.raises(ValueError, match="serve_reject.reason"):
            validate_record({**make_example("serve_reject"),
                             "reason": "bogus"})


class TestHTTP:
    @pytest.fixture(scope="class")
    def endpoint(self, trunk):
        from proteinbert_tpu.serve.http import make_http_server

        params, cfg = trunk
        srv = Server(params, cfg, buckets=BUCKETS, max_batch=4,
                     max_wait_s=0.002, cache_size=8, warm_kinds=())
        srv.start()
        httpd = make_http_server(srv, port=0)
        port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        yield srv, f"http://127.0.0.1:{port}"
        httpd.shutdown()
        httpd.server_close()
        srv.close(drain=True, timeout=30)

    def _post(self, url, payload):
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def test_embed_roundtrip_matches_in_process(self, endpoint, trunk):
        srv, base = endpoint
        status, body = self._post(base + "/v1/embed",
                                  {"seq": "MKTAYIAKQR"})
        assert status == 200
        local = srv.embed("MKTAYIAKQR", timeout=30)
        np.testing.assert_allclose(body["global"], local["global"],
                                   rtol=1e-6, atol=1e-7)

    def test_predict_routes(self, endpoint):
        _, base = endpoint
        status, body = self._post(base + "/v1/predict_go",
                                  {"seq": "MKTAYIAKQR", "top_k": 2})
        assert status == 200 and len(body["top"]) == 2
        status, body = self._post(base + "/v1/predict_residues",
                                  {"seq": "MK?AYIAKQR"})
        assert status == 200 and "?" not in body["filled"]

    def test_error_status_mapping(self, endpoint, trunk):
        _, cfg = trunk
        _, base = endpoint
        status, body = self._post(base + "/v1/predict_residues",
                                  {"seq": "A" * (cfg.data.seq_len - 2)
                                   + "?"})
        assert status == 400 and body["type"] == "too_long"
        status, body = self._post(base + "/v1/embed", {"nope": 1})
        assert status == 400 and body["type"] == "bad_request"
        status, _ = self._post(base + "/v1/nope", {"seq": "MKT"})
        assert status == 404

    def test_healthz_and_metrics(self, endpoint):
        import urllib.request

        _, base = endpoint
        with urllib.request.urlopen(base + "/healthz", timeout=30) as r:
            body = json.loads(r.read())
        assert body["ok"] and "cache" in body["stats"]
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            assert r.status == 200


# --------------------------------------------------------- CLI wiring

def test_cli_serve_registered():
    from proteinbert_tpu.cli.main import build_parser, cmd_serve

    args = build_parser().parse_args(
        ["serve", "--pretrained", "/tmp/x", "--max-batch", "4",
         "--max-wait-ms", "5", "--queue-depth", "8", "--on-long",
         "reject", "--port", "0"])
    assert args.fn is cmd_serve
    assert args.max_batch == 4
    assert args.max_wait_ms == 5.0
    assert args.on_long == "reject"
