"""Failure detection + graceful preemption (SURVEY §5 — the reference has
none): NaN halt with diagnostic checkpoint, SIGTERM checkpoint-and-exit,
fine-tune epoch resume, multihost no-op."""

import os
import signal

import jax
import numpy as np
import pytest

from proteinbert_tpu.configs import (
    DataConfig, FinetuneConfig, ModelConfig, OptimizerConfig, PretrainConfig,
    TaskConfig, TrainConfig,
)
from proteinbert_tpu.data import InMemoryPretrainingDataset, make_pretrain_iterator
from proteinbert_tpu.data.synthetic import make_random_proteins, make_task_batches
from proteinbert_tpu.train import Checkpointer
from proteinbert_tpu.train.resilience import (
    GracefulShutdown, NonFiniteLossError, check_finite,
)
from proteinbert_tpu.train.trainer import pretrain

MODEL = ModelConfig(local_dim=16, global_dim=32, key_dim=8, num_heads=4,
                    num_blocks=1, num_annotations=64, dtype="float32")


def _cfg(**train_kw):
    return PretrainConfig(
        model=MODEL,
        data=DataConfig(seq_len=64, batch_size=8),
        optimizer=OptimizerConfig(warmup_steps=4),
        train=TrainConfig(max_steps=10, log_every=2, **train_kw),
    )


def _iterator(seed=0):
    rng = np.random.default_rng(seed)
    seqs, ann = make_random_proteins(64, rng, num_annotations=64)
    ds = InMemoryPretrainingDataset(seqs, ann, 64)
    return make_pretrain_iterator(ds, 8, seed=seed)


def test_check_finite():
    assert check_finite({"loss": 1.0, "grad_norm": 2.0}, 1)
    assert not check_finite({"loss": float("nan")}, 1, mode="warn")
    with pytest.raises(NonFiniteLossError, match="step 7"):
        check_finite({"loss": float("inf")}, 7, mode="halt")


def test_nan_halt_saves_diagnostic_checkpoint(tmp_path):
    # An absurd LR blows the tiny model up within a few steps.
    cfg = _cfg()
    cfg = cfg.replace(optimizer=OptimizerConfig(learning_rate=1e18,
                                                warmup_steps=1,
                                                grad_clip_norm=1e18))
    ck = Checkpointer(str(tmp_path / "ck"), async_save=False)
    with pytest.raises(NonFiniteLossError):
        pretrain(cfg, _iterator(), checkpointer=ck)
    # Diagnostic state lands in the SIBLING dir; the resume chain stays
    # clean (a restart must not restore NaN weights).
    assert ck.latest_step() is None
    diag = Checkpointer(str(tmp_path / "ck") + "-diagnostic",
                        async_save=False)
    assert diag.latest_step() is not None
    diag.close()
    ck.close()


def test_nan_warn_mode_continues():
    cfg = _cfg(on_nan="warn")
    cfg = cfg.replace(optimizer=OptimizerConfig(learning_rate=1e18,
                                                warmup_steps=1,
                                                grad_clip_norm=1e18))
    out = pretrain(cfg, _iterator())
    assert len(out["history"]) == 5  # ran to completion despite NaNs


def test_sigterm_checkpoints_and_exits(tmp_path):
    cfg = _cfg()
    ck = Checkpointer(str(tmp_path / "ck"), async_save=False)
    fired = []

    def send_signal(step, m):
        if step == 4 and not fired:
            fired.append(step)
            os.kill(os.getpid(), signal.SIGTERM)

    out = pretrain(cfg, _iterator(), checkpointer=ck, log_fn=send_signal)
    assert out["preempted"] is True
    assert ck.latest_step() == 4  # saved at the interrupted step, not max
    ck.close()

    # And the resume continues from there.
    ck2 = Checkpointer(str(tmp_path / "ck"), async_save=False)
    out2 = pretrain(cfg, lambda skip: _iterator(), checkpointer=ck2)
    assert out2["preempted"] is False
    assert int(out2["state"].step) == cfg.train.max_steps
    ck2.close()


def test_finetune_resume(tmp_path, rng):
    cfg = FinetuneConfig(
        model=MODEL,
        task=TaskConfig(kind="sequence_classification", num_outputs=3,
                        epochs=3),
        data=DataConfig(seq_len=64, batch_size=8),
        optimizer=OptimizerConfig(learning_rate=1e-3, warmup_steps=2,
                                  schedule="warmup_cosine", total_steps=100),
    )
    from proteinbert_tpu.train.finetune import finetune

    batches = make_task_batches(32, rng, "sequence_classification", 3, 64, 8)

    ck = Checkpointer(str(tmp_path / "ck"), async_save=False)
    two = cfg.replace(task=TaskConfig(kind="sequence_classification",
                                      num_outputs=3, epochs=2))
    out1 = finetune(two, lambda e: iter(batches), checkpointer=ck)
    ck.close()

    ck2 = Checkpointer(str(tmp_path / "ck"), async_save=False)
    out2 = finetune(cfg, lambda e: iter(batches), checkpointer=ck2)
    ck2.close()
    # Only the third epoch RAN, but history spans the whole run (the
    # pre-resume records come back from the checkpoint data).
    assert [r["epoch"] for r in out2["history"]] == [0, 1, 2]
    assert int(out2["state"].step) == 3 * len(batches)

    # A directory that already holds >= task.epochs completed epochs is
    # an error, not a silent zero-epoch "run".
    ck3 = Checkpointer(str(tmp_path / "ck"), async_save=False)
    with pytest.raises(ValueError, match="completed epochs"):
        finetune(cfg, lambda e: iter(batches), checkpointer=ck3)
    ck3.close()


def test_sigterm_mid_staged_checkpoint_dumps_flight(tmp_path):
    """ISSUE 3 flight-recorder signal path: SIGTERM while a STAGED
    checkpoint save is still in flight must leave a valid flight dump
    whose events include the in-flight stage's dispatch — and the
    preemption must still land the stage + save cleanly (the existing
    contract)."""
    import dataclasses
    import json
    import time

    from proteinbert_tpu import obs
    from proteinbert_tpu.configs import CheckpointConfig

    cfg = _cfg()
    cfg = cfg.replace(checkpoint=dataclasses.replace(
        CheckpointConfig(), directory=str(tmp_path / "ck"),
        every_steps=4, overlap=True))

    class SlowStageCheckpointer(Checkpointer):
        # Stretch the device→host fetch so the step-4 stage is still in
        # flight when the SIGTERM lands at the step-6 log point (two
        # ~ms steps later — 0.6s is ample margin without bloating the
        # tier-1 wall budget).
        def _stage_fetch(self, snapshot):
            time.sleep(0.6)
            return super()._stage_fetch(snapshot)

    ck = SlowStageCheckpointer(cfg.checkpoint.directory, async_save=False)
    tele = obs.Telemetry(events_path=str(tmp_path / "ev.jsonl"),
                         flight_dir=str(tmp_path))
    fired = []

    def send_signal(step, m):
        if step == 6 and not fired:
            assert ck.staged_in_flight(), "drill setup: stage already landed"
            fired.append(step)
            os.kill(os.getpid(), signal.SIGTERM)

    out = pretrain(cfg, _iterator(), checkpointer=ck, log_fn=send_signal,
                   telemetry=tele)
    ck.close()
    tele.close()
    assert out["preempted"] is True

    payload = json.load(open(obs.flight_path(str(tmp_path))))
    obs.validate_flight_dump(payload)
    assert payload["reason"].startswith("signal_")
    kinds = [(r["event"], r.get("phase")) for r in payload["events"]]
    # The in-flight stage's dispatch is in the forensics...
    assert ("ckpt_stage", "dispatch") in kinds
    # ...and so are its landing (flushed on the preemption path) and the
    # requeue record itself.
    assert ("ckpt_stage", "landed") in kinds
    assert any(r["event"] == "requeue" and r["reason"] == "signal_15"
               for r in payload["events"])
    # The events stream tells the same story and still validates.
    recs = obs.read_events(str(tmp_path / "ev.jsonl"), strict=True)
    assert any(r["event"] == "requeue" for r in recs)


def test_multihost_noop_single_host():
    from proteinbert_tpu.parallel import maybe_initialize_distributed

    # On the CPU test rig there is no cluster env: must return False
    # without touching jax state, and jax must keep working after.
    assert maybe_initialize_distributed() is False
    assert jax.device_count() >= 1


def test_graceful_shutdown_restores_handlers():
    before = signal.getsignal(signal.SIGTERM)
    with GracefulShutdown() as stop:
        assert not stop.requested
        os.kill(os.getpid(), signal.SIGTERM)
        assert stop.requested and stop.signum == signal.SIGTERM
    assert signal.getsignal(signal.SIGTERM) is before
