"""Torch→JAX checkpoint migration tests (interop.py).

Builds a reference-shaped torch state_dict (the key layout of reference
modules.py:234-304) without importing the reference code, converts it,
and checks every mapped weight lands transposed/reduced correctly.
"""

import jax
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from proteinbert_tpu import interop
from proteinbert_tpu.configs import ModelConfig

CFG = ModelConfig(local_dim=32, global_dim=64, key_dim=16, num_heads=4,
                  num_blocks=2, num_annotations=128, dtype="float32")
L = 48  # the torch model's fixed seq_len (its joint-LN shape)


def _reference_state_dict(seed=0):
    """The exact key/shape layout `ProteinBERT(...).state_dict()` yields
    (reference modules.py:249-293; local norms jointly over (L, C) per
    modules.py:148-151 — SURVEY ledger #4)."""
    g = torch.Generator().manual_seed(seed)
    C, G, A, V = CFG.local_dim, CFG.global_dim, CFG.num_annotations, 26
    sd = {
        "local_embedding.weight": torch.randn(V, C, generator=g),
        "global_linear_layer.0.weight": torch.randn(G, A, generator=g),
        "global_linear_layer.0.bias": torch.randn(G, generator=g),
        "pretraining_local_output.0.weight": torch.randn(V, C, generator=g),
        "pretraining_local_output.0.bias": torch.randn(V, generator=g),
        "pretraining_global_output.0.weight": torch.randn(A, G, generator=g),
        "pretraining_global_output.0.bias": torch.randn(A, generator=g),
    }
    for i in range(CFG.num_blocks):
        p = f"proteinBERT_blocks.{i}."
        sd.update({
            p + "local_narrow_conv_layer.0.weight":
                torch.randn(C, C, CFG.narrow_kernel, generator=g),
            p + "local_narrow_conv_layer.0.bias": torch.randn(C, generator=g),
            p + "local_wide_conv_layer.0.weight":
                torch.randn(C, C, CFG.wide_kernel, generator=g),
            p + "local_wide_conv_layer.0.bias": torch.randn(C, generator=g),
            p + "global_to_local_linear_layer.0.weight":
                torch.randn(C, G, generator=g),
            p + "global_to_local_linear_layer.0.bias":
                torch.randn(C, generator=g),
            p + "local_linear_layer.0.weight": torch.randn(C, C, generator=g),
            p + "local_linear_layer.0.bias": torch.randn(C, generator=g),
            p + "local_norm_1.weight": torch.randn(L, C, generator=g),
            p + "local_norm_1.bias": torch.randn(L, C, generator=g),
            p + "local_norm_2.weight": torch.randn(L, C, generator=g),
            p + "local_norm_2.bias": torch.randn(L, C, generator=g),
            p + "global_linear_layer_1.0.weight": torch.randn(G, G, generator=g),
            p + "global_linear_layer_1.0.bias": torch.randn(G, generator=g),
            p + "global_norm_1.weight": torch.randn(G, generator=g),
            p + "global_norm_1.bias": torch.randn(G, generator=g),
            p + "global_linear_layer_2.0.weight": torch.randn(G, G, generator=g),
            p + "global_linear_layer_2.0.bias": torch.randn(G, generator=g),
            p + "global_norm_2.weight": torch.randn(G, generator=g),
            p + "global_norm_2.bias": torch.randn(G, generator=g),
            p + "global_attention_layer.W_parameter":
                torch.randn(CFG.key_dim, generator=g),
        })
    return sd


def test_convert_maps_and_transposes():
    sd = _reference_state_dict()
    params = interop.convert_reference_state_dict(sd, CFG)

    np.testing.assert_array_equal(
        params["embedding"]["embedding"], sd["local_embedding.weight"].numpy())
    # Linear (out, in) → (in, out).
    np.testing.assert_array_equal(
        params["global_in"]["kernel"],
        sd["global_linear_layer.0.weight"].numpy().T)
    np.testing.assert_array_equal(
        params["global_head"]["bias"],
        sd["pretraining_global_output.0.bias"].numpy())
    # Conv (Cout, Cin, K) → (K, Cin, Cout): tap t, in-channel j, out ch o.
    blk0 = jax.tree.map(lambda a: a[0], params["blocks"]) \
        if CFG.scan_blocks else params["blocks"][0]
    w_t = sd["proteinBERT_blocks.0.local_narrow_conv_layer.0.weight"].numpy()
    np.testing.assert_array_equal(
        blk0["narrow_conv"]["kernel"][3, 5, 7], w_t[7, 5, 3])
    # Joint (L, C) norm affine → per-feature mean over L.
    np.testing.assert_allclose(
        blk0["local_ln1"]["scale"],
        sd["proteinBERT_blocks.0.local_norm_1.weight"].numpy().mean(0),
        rtol=1e-6)
    # Per-feature global norms pass through unchanged.
    np.testing.assert_array_equal(
        blk0["global_ln1"]["scale"],
        sd["proteinBERT_blocks.0.global_norm_1.weight"].numpy())


def test_convert_preserves_attention_init():
    """Attention params aren't in the reference state_dict (ledger #1) —
    conversion must keep the fresh init, deterministically from init_key."""
    sd = _reference_state_dict()
    key = jax.random.PRNGKey(7)
    params = interop.convert_reference_state_dict(sd, CFG, init_key=key)
    from proteinbert_tpu.models import proteinbert

    fresh = proteinbert.init(key, CFG)
    blk = jax.tree.map(lambda a: a[0], params["blocks"]) \
        if CFG.scan_blocks else params["blocks"][0]
    fblk = jax.tree.map(lambda a: np.asarray(a[0]), fresh["blocks"]) \
        if CFG.scan_blocks else jax.tree.map(np.asarray, fresh["blocks"][0])
    np.testing.assert_array_equal(blk["attention"]["wq"],
                                  fblk["attention"]["wq"])


def test_convert_runs_forward():
    """Converted params drive this framework's forward pass."""
    from proteinbert_tpu.models import proteinbert

    params = jax.tree.map(
        jax.numpy.asarray,
        interop.convert_reference_state_dict(_reference_state_dict(), CFG))
    tokens = jax.numpy.ones((2, L), jax.numpy.int32) * 5
    ann = jax.numpy.zeros((2, CFG.num_annotations), jax.numpy.float32)
    local_logits, global_logits = proteinbert.apply(params, tokens, ann, CFG)
    assert local_logits.shape == (2, L, 26)
    assert global_logits.shape == (2, CFG.num_annotations)
    assert np.isfinite(np.asarray(local_logits)).all()


def test_convert_rejects_shape_mismatch():
    sd = _reference_state_dict()
    bad = dict(sd)
    bad["local_embedding.weight"] = torch.randn(26, CFG.local_dim + 1)
    with pytest.raises(ValueError, match="converted shape"):
        interop.convert_reference_state_dict(bad, CFG)


def test_convert_rejects_unknown_keys():
    sd = _reference_state_dict()
    sd["mystery.weight"] = torch.randn(3)
    with pytest.raises(ValueError, match="unrecognized torch keys"):
        interop.convert_reference_state_dict(sd, CFG)


def test_load_reference_checkpoint_forms(tmp_path):
    """All three torch artifact forms the reference produces load; the
    periodic form carries its iteration counter."""
    sd = _reference_state_dict()
    p1 = tmp_path / "bare.pt"
    torch.save(sd, p1)
    p2 = tmp_path / "periodic.pt"
    torch.save({"model_state_dict": sd, "current_batch_iteration": 123}, p2)
    a, step_a = interop.load_reference_checkpoint(str(p1), CFG)
    b, step_b = interop.load_reference_checkpoint(str(p2), CFG)
    assert (step_a, step_b) == (0, 123)
    np.testing.assert_array_equal(a["embedding"]["embedding"],
                                  b["embedding"]["embedding"])


def test_convert_rejects_missing_keys():
    """More configured blocks than the checkpoint has → curated error,
    not a bare KeyError."""
    import dataclasses

    sd = _reference_state_dict()
    bigger = dataclasses.replace(CFG, num_blocks=3)
    with pytest.raises(ValueError, match="missing.*proteinBERT_blocks.2"):
        interop.convert_reference_state_dict(sd, bigger)


def test_convert_torch_cli_then_embed(tmp_path):
    """convert-torch → orbax dir → the embed command consumes it.
    In-process main() like the rest of the CLI suite (tests/test_cli.py)."""
    from proteinbert_tpu.cli.main import main

    torch.save(
        {"model_state_dict": _reference_state_dict(),
         "current_batch_iteration": 42},
        tmp_path / "ref.pt")
    out = tmp_path / "run"
    overrides = []
    for f in ("local_dim", "global_dim", "key_dim", "num_heads",
              "num_blocks", "num_annotations"):
        overrides.append(f"--set=model.{f}={getattr(CFG, f)}")
    overrides.append("--set=model.dtype=float32")
    assert main(["convert-torch", "--torch-ckpt", str(tmp_path / "ref.pt"),
                 "--output", str(out), "--preset", "tiny", *overrides]) == 0
    assert main(["embed", "--pretrained", str(out), "--preset", "tiny",
                 *[o.replace("--set=", "--pretrained-set=") for o in overrides],
                 "--output", str(tmp_path / "e.npz"), "MKTAYIAKQR"]) == 0
    emb = np.load(tmp_path / "e.npz")
    assert emb["global"].shape == (1, CFG.global_dim)
