"""Numerical parity of the Pallas fused local-track kernel vs the plain
jax.nn composition (SURVEY §4: "numerical parity tests of the Pallas fused
block against the plain jax.nn composition"). Runs in interpret mode on the
CPU test mesh; the same kernel compiles via Mosaic on TPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from proteinbert_tpu.configs import ModelConfig
from proteinbert_tpu.kernels import (
    fused_local_track,
    local_track_reference,
    pallas_supported,
)
from proteinbert_tpu.models import proteinbert


def _make_inputs(key, B=2, L=128, C=128, G=64, dtype=jnp.float32):
    cfg = ModelConfig(local_dim=C, global_dim=G, key_dim=16, num_heads=4,
                      num_blocks=1, num_annotations=32, dtype=str(dtype.dtype.name)
                      if hasattr(dtype, "dtype") else "float32")
    kp, kx, kb = jax.random.split(key, 3)
    block = proteinbert.block_init(kp, cfg)
    params = {k: block[k] for k in ("narrow_conv", "wide_conv", "local_ln1",
                                    "local_dense", "local_ln2")}
    x = jax.random.normal(kx, (B, L, C), dtype)
    bcast = jax.random.normal(kb, (B, C), dtype)
    return params, x, bcast


def test_forward_parity_fp32(key):
    params, x, bcast = _make_inputs(key)
    got = fused_local_track(params, x, bcast, 1, 5, True)
    want = local_track_reference(params, x, bcast, 1, 5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_forward_parity_tiled(key):
    # L=256 with tile 128 exercises the multi-tile grid + halo windows.
    params, x, bcast = _make_inputs(key, B=1, L=256, C=128)
    got = fused_local_track(params, x, bcast, 1, 5, True)
    want = local_track_reference(params, x, bcast, 1, 5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_forward_parity_bf16(key):
    params, x, bcast = _make_inputs(key, dtype=jnp.bfloat16)
    got = fused_local_track(params, x, bcast, 1, 5, True).astype(jnp.float32)
    want = local_track_reference(params, x, bcast, 1, 5).astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=0.05, atol=0.05)


def test_gradient_parity(key):
    params, x, bcast = _make_inputs(key, B=1, L=64, C=128)

    def loss_fused(p, xx, bb):
        return jnp.sum(fused_local_track(p, xx, bb, 1, 5, True) ** 2)

    def loss_ref(p, xx, bb):
        return jnp.sum(local_track_reference(p, xx, bb, 1, 5) ** 2)

    g_fused = jax.grad(loss_fused, argnums=(0, 1, 2))(params, x, bcast)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(params, x, bcast)
    # Backward recomputes the reference composition; the only forward-path
    # difference is the kernel's fp32 residual accumulation feeding the
    # output cotangent, so tolerances stay tight in fp32.
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
        ),
        g_fused, g_ref,
    )


def test_model_level_parity(key):
    cfg = ModelConfig(local_dim=128, global_dim=64, key_dim=16, num_heads=4,
                      num_blocks=2, num_annotations=32, dtype="float32")
    params = proteinbert.init(key, cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 4, 26)
    ann = (jax.random.uniform(jax.random.PRNGKey(2), (2, 32)) < 0.1
           ).astype(jnp.float32)

    plain_l, plain_g = proteinbert.apply(params, tokens, ann, cfg)
    pcfg = ModelConfig(**{**cfg.__dict__, "use_pallas": True})
    fused_l, fused_g = proteinbert.apply(params, tokens, ann, pcfg)
    np.testing.assert_allclose(np.asarray(fused_l), np.asarray(plain_l),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(fused_g), np.asarray(plain_g),
                               rtol=1e-4, atol=1e-4)


def test_pallas_supported_gating():
    assert pallas_supported(128, 256)
    assert pallas_supported(512, 512)               # base config, bf16
    assert pallas_supported(1024, 512)              # Large → channel-tiled
    assert not pallas_supported(1024, 512, "float32")  # fp32 tiled plan: no
    assert not pallas_supported(4096, 512)          # beyond MAX_TILED_DIM
    assert not pallas_supported(96, 256)            # non-lane-aligned C
    assert not pallas_supported(512, 512, "float32")  # fp32 weights blow VMEM
    assert pallas_supported(128, 64, "float32")     # small fp32 is fine
    # Unsharded long rows keep the whole padded row in VMEM — too big at
    # C=512; the seq-sharded per-shard length (2048/4=512) is what the
    # kernel sees under the long preset, and that fits.
    assert not pallas_supported(512, 2048)
    assert pallas_supported(512, 2048 // 4)


def test_pallas_segments_supported_gating():
    from proteinbert_tpu.kernels import pallas_segments_supported

    assert pallas_segments_supported(128, 256, 8, "float32")
    assert pallas_segments_supported(512, 512, 8)       # base config, bf16
    assert not pallas_segments_supported(96, 256, 8)    # non-lane-aligned C
    # Channel-tiled SEGMENT variant (ISSUE 13): Large C=1024 packed
    # rows now run the fast path instead of falling back with
    # reason="segments"…
    assert pallas_segments_supported(1024, 512, 8)
    # …but the fp32 tiled plan still has no room, like the dense one,
    # and nothing exceeds MAX_TILED_DIM.
    assert not pallas_segments_supported(1024, 512, 8, "float32")
    assert not pallas_segments_supported(4096, 512, 8)
    assert not pallas_segments_supported(512, 512, 8, "float32")  # VMEM
    assert not pallas_segments_supported(128, 4, 2)     # seq too short
    assert not pallas_segments_supported(128, 256, 0)   # no segments
    # Even tap counts break the symmetric-halo tap layout.
    assert not pallas_segments_supported(128, 256, 8, "float32",
                                         narrow_taps=8)
    # The one-hot row block is priced in: the dense kernel fits this
    # long-row bf16 shape, the segment kernel must still fit too (the
    # oh block is lane-padded but small next to the weights).
    assert pallas_segments_supported(256, 1024, 16)


def test_train_step_with_pallas(key):
    """One jitted train step with the fused kernel end to end."""
    from proteinbert_tpu.configs import (
        DataConfig, OptimizerConfig, PretrainConfig, TrainConfig,
    )
    from proteinbert_tpu.train import create_train_state, train_step

    cfg = PretrainConfig(
        model=ModelConfig(local_dim=128, global_dim=64, key_dim=16,
                          num_heads=4, num_blocks=2, num_annotations=32,
                          dtype="float32", use_pallas=True),
        data=DataConfig(seq_len=64, batch_size=2),
        optimizer=OptimizerConfig(warmup_steps=10),
        train=TrainConfig(max_steps=1),
    )
    state = create_train_state(key, cfg)
    rng = np.random.default_rng(0)
    batch = {
        "tokens": rng.integers(4, 26, size=(2, 64)).astype(np.int32),
        "annotations": (rng.random((2, 32)) < 0.1).astype(np.float32),
    }
    new_state, metrics = train_step(state, batch, cfg)
    assert np.isfinite(float(metrics["loss"]))
    assert int(new_state.step) == 1


# ------------------------------------------- channel-tiled variant (C>512)

def test_tiled_forward_parity_c1024(key):
    """Large-config C=1024 runs the channel-tiled kernel (scratch
    accumulation over the c grid axis). fp32 has no tiled VMEM plan, so
    parity runs in bf16 — the config the Large preset actually trains —
    with bf16-appropriate tolerances against the reference composition."""
    params, x, bcast = _make_inputs(key, B=1, L=128, C=1024,
                                    dtype=jnp.bfloat16)
    assert pallas_supported(1024, 128)
    got = fused_local_track(params, x, bcast, 1, 5, True).astype(jnp.float32)
    want = local_track_reference(params, x, bcast, 1, 5).astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=0.05, atol=0.05)


def test_tiled_multi_l_tiles_and_batch(key):
    """Multiple L tiles AND batch entries: the fp32 scratch row must be
    fully overwritten per (b, l) step — stale columns from the previous
    grid step would show up as cross-tile leakage."""
    params, x, bcast = _make_inputs(key, B=2, L=256, C=1024,
                                    dtype=jnp.bfloat16)
    got = fused_local_track(params, x, bcast, 1, 5, True).astype(jnp.float32)
    want = local_track_reference(params, x, bcast, 1, 5).astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=0.05, atol=0.05)


def test_tiled_gradient_parity(key):
    params, x, bcast = _make_inputs(key, B=1, L=64, C=1024,
                                    dtype=jnp.bfloat16)

    def f_fused(p, xx, bb):
        return (fused_local_track(p, xx, bb, 1, 5, True)
                .astype(jnp.float32).sum())

    def f_ref(p, xx, bb):
        return (local_track_reference(p, xx, bb, 1, 5)
                .astype(jnp.float32).sum())

    g_fused = jax.grad(f_fused, argnums=(0, 1, 2))(params, x, bcast)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(params, x, bcast)
    for a, b in zip(jax.tree.leaves(g_fused), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=0.05, atol=0.1)


def test_tiled_plan_details():
    from proteinbert_tpu.kernels.fused_block import _plan_tiled

    # Large preset, unsharded L=512: fits via the narrower L tile.
    tc, tile = _plan_tiled(1024, 512, "bfloat16")
    assert tc == 128 and tile == 128
    # Unequal tap counts can't use the stacked phase layout → no plan.
    assert _plan_tiled(1024, 512, "bfloat16", narrow_taps=9,
                       wide_taps=5)[0] == 0
    # The weights-resident order (full-row fp32 scratch) fits at Large
    # L=512 — the order the kernel actually runs there...
    assert _plan_tiled(1024, 512, "bfloat16", resident=True) == (128, 128)
    # ...but not at long L, where only the per-row order has a plan.
    assert _plan_tiled(640, 2048, "bfloat16", resident=True)[0] == 0
    assert _plan_tiled(640, 2048, "bfloat16") == (128, 128)


def test_tiled_per_row_order_parity(key):
    """C=640/L=2048 has no weights-resident plan (full-row scratch blows
    VMEM), so this shape exercises the per-row fallback grid order."""
    from proteinbert_tpu.kernels.fused_block import _plan_tiled

    assert _plan_tiled(640, 2048, "bfloat16", resident=True)[0] == 0
    assert pallas_supported(640, 2048)
    params, x, bcast = _make_inputs(key, B=1, L=2048, C=640,
                                    dtype=jnp.bfloat16)
    got = fused_local_track(params, x, bcast, 1, 5, True).astype(jnp.float32)
    want = local_track_reference(params, x, bcast, 1, 5).astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=0.05, atol=0.05)


def test_tiled_unequal_taps_falls_back_to_xla(key):
    """pallas_supported must refuse the stacked layout when the convs
    have different tap counts (the model then runs the XLA path)."""
    assert not pallas_supported(1024, 128, narrow_taps=9, wide_taps=5)


def test_tiled_prehaloed_parity(key):
    """The seq-parallel pre-haloed variant also routes through the tiled
    kernel at C=1024 (real halo rows, VALID output center)."""
    from proteinbert_tpu.kernels import (
        fused_local_track_valid, local_track_valid_reference, track_halo,
    )

    params, _, bcast = _make_inputs(key, B=1, L=64, C=1024,
                                    dtype=jnp.bfloat16)
    H = track_halo(params, 1, 5)
    xh = jax.random.normal(jax.random.PRNGKey(3), (1, 64 + 2 * H, 1024),
                           jnp.bfloat16)
    got = fused_local_track_valid(params, xh, bcast, 1, 5, True
                                  ).astype(jnp.float32)
    want = local_track_valid_reference(params, xh, bcast, 1, 5
                                       ).astype(jnp.float32)
    assert got.shape == (1, 64, 1024)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=0.05, atol=0.05)


# ------------------------------- channel-tiled SEGMENT variant (ISSUE 13)
# The C>512 packed fast path: same grid orders as the dense tiled
# kernel, segment one-hot operands folded in. Shapes here mirror the
# dense tiled tier so interpret cost stays bounded.

def _make_segment_inputs(key, B=1, L=128, C=1024, S=4,
                         dtype=jnp.bfloat16):
    params, x, _ = _make_inputs(key, B=B, L=L, C=C, dtype=dtype)
    bc = jax.random.normal(jax.random.PRNGKey(11), (B, S, C), dtype)
    rng = np.random.default_rng(5)
    seg = np.zeros((B, L), np.int32)
    for b in range(B):
        pos = 0
        for sid in range(1, S + 1):
            ln = int(rng.integers(8, max(9, L // S)))
            if pos + ln > L:
                break
            seg[b, pos:pos + ln] = sid
            pos += ln
    return params, x, bc, jnp.asarray(seg)


def test_tiled_segment_forward_parity_c1024(key):
    """Large-config C=1024 PACKED rows run the channel-tiled segment
    kernel instead of falling back with reason=segments (ISSUE 13
    acceptance). bf16 like the dense tiled tier (fp32 has no plan)."""
    from proteinbert_tpu.kernels import (
        fused_local_track_segments, gather_segment_broadcast,
        local_track_segment_reference, pallas_segments_supported,
    )
    from proteinbert_tpu.kernels import fused_block as fb

    params, x, bc, seg = _make_segment_inputs(key)
    assert pallas_segments_supported(1024, 128, 4)
    before = dict(fb.PATH_TOTAL)
    got = fused_local_track_segments(params, x, bc, seg, 1, 5, True
                                     ).astype(jnp.float32)
    assert (fb.PATH_TOTAL.get(("pallas", "packed"), 0)
            > before.get(("pallas", "packed"), 0))
    assert (fb.PATH_TOTAL.get(("reference", "segments"), 0)
            == before.get(("reference", "segments"), 0))
    want = local_track_segment_reference(
        params, x, gather_segment_broadcast(bc, seg), seg, 1, 5
    ).astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=0.05, atol=0.05)


def test_tiled_segment_multi_l_tiles_and_batch(key):
    """Multiple L tiles AND batch entries with a segment boundary at
    the tile edge: the fp32 scratch row must be fully overwritten per
    step and the one-hot masks must track the (b, j) window."""
    from proteinbert_tpu.kernels import (
        fused_local_track_segments, gather_segment_broadcast,
        local_track_segment_reference,
    )

    params, x, _, _ = _make_segment_inputs(key, B=2, L=256)
    bc = jax.random.normal(jax.random.PRNGKey(12), (2, 3, 1024),
                           jnp.bfloat16)
    seg = np.zeros((2, 256), np.int32)
    seg[0, :128] = 1
    seg[0, 128:220] = 2   # boundary exactly at the 128 tile edge
    seg[1, :100] = 1
    seg[1, 100:256] = 3
    seg = jnp.asarray(seg)
    got = fused_local_track_segments(params, x, bc, seg, 1, 5, True
                                     ).astype(jnp.float32)
    want = local_track_segment_reference(
        params, x, gather_segment_broadcast(bc, seg), seg, 1, 5
    ).astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=0.05, atol=0.05)


def test_tiled_segment_gradient_parity(key):
    """The existing custom VJP wraps whichever forward variant runs —
    the tiled segment path must keep the rematerialised oh-reference
    backward contract."""
    from proteinbert_tpu.kernels import fused_block as fb

    params, x, bc, seg = _make_segment_inputs(key, L=64)

    def f_fused(p, xx, bb):
        return (fb.fused_local_track_segments(p, xx, bb, seg, 1, 5, True)
                .astype(jnp.float32).sum())

    def f_ref(p, xx, bb):
        return (fb.local_track_segment_reference(
            p, xx, fb.gather_segment_broadcast(bb, seg), seg, 1, 5)
            .astype(jnp.float32).sum())

    g_fused = jax.grad(f_fused, argnums=(0, 1, 2))(params, x, bc)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(params, x, bc)
    for a, b in zip(jax.tree.leaves(g_fused), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=0.05, atol=0.1)


def test_tiled_segment_plan_details():
    from proteinbert_tpu.kernels.fused_block import _plan_tiled

    # Large preset packed: the per-row order has a plan at L=512; the
    # weights-resident order's full-row fp32 scratch only fits once
    # the one-hot/bcast extras shrink with L (the forward prefers
    # resident when it fits, per-row otherwise — both orders run).
    assert _plan_tiled(1024, 512, "bfloat16",
                       max_segments=8) == (128, 128)
    assert _plan_tiled(1024, 512, "bfloat16", resident=True,
                       max_segments=8)[0] == 0
    assert _plan_tiled(1024, 128, "bfloat16", resident=True,
                       max_segments=4) == (128, 128)
    # Long rows: only the per-row order fits (same shape family as the
    # dense tier's per-row case).
    assert _plan_tiled(640, 2048, "bfloat16", resident=True,
                       max_segments=16)[0] == 0
    assert _plan_tiled(640, 2048, "bfloat16",
                       max_segments=16) == (128, 128)
    # The one-hot/bcast price is real: a plan that fits dense can still
    # refuse segments when S is enormous.
    assert _plan_tiled(1024, 512, "bfloat16")[0] > 0
    assert _plan_tiled(1024, 512, "bfloat16", max_segments=4096)[0] == 0


def test_tiled_segment_per_row_order_parity(key):
    """C=640/L=2048 has no weights-resident segment plan (full-row
    scratch blows VMEM) — exercises the per-row fallback grid order of
    the SEGMENT kernel."""
    from proteinbert_tpu.kernels import (
        fused_local_track_segments, gather_segment_broadcast,
        local_track_segment_reference, pallas_segments_supported,
    )
    from proteinbert_tpu.kernels.fused_block import _plan_tiled

    assert _plan_tiled(640, 2048, "bfloat16", resident=True,
                       max_segments=2)[0] == 0
    assert pallas_segments_supported(640, 2048, 2)
    params, x, _ = _make_inputs(key, B=1, L=2048, C=640,
                                dtype=jnp.bfloat16)
    bc = jax.random.normal(jax.random.PRNGKey(13), (1, 2, 640),
                           jnp.bfloat16)
    seg = np.zeros((1, 2048), np.int32)
    seg[0, :1200] = 1
    seg[0, 1200:2000] = 2
    seg = jnp.asarray(seg)
    got = fused_local_track_segments(params, x, bc, seg, 1, 5, True
                                     ).astype(jnp.float32)
    want = local_track_segment_reference(
        params, x, gather_segment_broadcast(bc, seg), seg, 1, 5
    ).astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=0.05, atol=0.05)


# -------------------------------------------------- real-TPU hardware gate

@pytest.mark.tpu_hardware
@pytest.mark.skipif("PBT_TPU_TESTS" not in __import__("os").environ,
                    reason="set PBT_TPU_TESTS=1 to run against the real chip")
def test_resident_order_parity_on_tpu_hardware():
    """ADVICE r1: the resident-order out-map (output pinned to (b,0,0)
    during non-finish sweeps) relies on Mosaic flush semantics that
    interpret mode cannot exercise — run the exact C=1024 resident
    configuration through Mosaic on the real chip. Spawned as a
    subprocess because this suite's conftest pins the process to the
    8-device CPU mesh."""
    import os
    import subprocess
    import sys

    child = os.path.join(os.path.dirname(__file__), "tpu_kernel_child.py")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(child))
                         + os.pathsep + env.get("PYTHONPATH", ""))
    out = subprocess.run([sys.executable, child], capture_output=True,
                         text=True, env=env, timeout=900)
    if out.returncode == 3:
        pytest.skip("TPU backend unreachable (tunnel down)")
    assert out.returncode == 0, out.stderr[-3000:]
    assert "PARITY OK" in out.stdout, out.stdout
