"""Ragged packed serving (proteinbert_tpu/serve/, ISSUE 9).

Two tiers, mirroring tests/test_serve.py:

- **pure-logic tests**: `PackedBatchScheduler` formation against a stub
  packed dispatcher and a fake clock — first-fit placement geometry,
  the open-frontier dispatch trigger, max-wait, deadline expiry inside
  open rows, drain, fail_pending. Deterministic via `poll(now=)`.
- **end-to-end tests**: one tiny untrained trunk (module fixture)
  proving THE parity contract — every ragged-mode per-request output
  matches the bucketed dispatcher's on identical traffic within the
  documented jitted ≤1e-5 tolerance (PR 7 split-parity precedent;
  bucket-quantized spans make the two programs compute the same math —
  serve/dispatch.RaggedDispatcher module doc) — plus the O(kinds)
  executable-count collapse, packed telemetry fields round-tripping the
  schema validator, `pbt diagnose --serve` surfacing, and the
  fused-kernel fallback counter satellite.
"""

import logging
import threading
import time
from concurrent.futures import Future
from types import SimpleNamespace

import numpy as np
import pytest

import jax

from proteinbert_tpu import inference
from proteinbert_tpu.configs import (
    CheckpointConfig, DataConfig, ModelConfig, OptimizerConfig,
    PretrainConfig, TaskConfig, TrainConfig,
)
from proteinbert_tpu.data.vocab import ALPHABET
from proteinbert_tpu.heads.registry import LoadedHead
from proteinbert_tpu.models import finetune as ft_model
from proteinbert_tpu.serve import (
    DeadlineExceededError, PackedBatchScheduler, RaggedDispatcher,
    Request, RequestQueue, Server, ServerClosedError,
)
from proteinbert_tpu.train import create_train_state

SEQ_LEN = 48
BUCKETS = (16, 32, 48)
MODEL = ModelConfig(local_dim=16, global_dim=32, key_dim=8, num_heads=2,
                    num_blocks=2, num_annotations=32, dtype="float32")


def _cfg():
    return PretrainConfig(
        model=MODEL,
        data=DataConfig(seq_len=SEQ_LEN, batch_size=4, buckets=BUCKETS),
        optimizer=OptimizerConfig(warmup_steps=5),
        train=TrainConfig(seed=0, max_steps=1),
        checkpoint=CheckpointConfig(),
    )


@pytest.fixture(scope="module")
def trunk():
    cfg = _cfg()
    state = create_train_state(jax.random.PRNGKey(cfg.train.seed), cfg)
    return state.params, cfg


@pytest.fixture(scope="module")
def seqs():
    rng = np.random.default_rng(11)
    return ["".join(rng.choice(list(ALPHABET), size=int(n)))
            for n in rng.integers(4, SEQ_LEN - 2, size=14)]


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


class StubRaggedDispatcher:
    """Records packed batches; returns one token-of-proof per rider."""

    def __init__(self, seq_len=SEQ_LEN, num_ann=4):
        self.cfg = SimpleNamespace(
            data=SimpleNamespace(seq_len=seq_len),
            model=SimpleNamespace(num_annotations=num_ann))
        self.calls = []
        self.fail_with = None

    def run_packed(self, kind, tokens, segment_ids, annotations, riders,
                   heads=None):
        if self.fail_with is not None:
            raise self.fail_with
        self.calls.append({
            "kind": kind, "tokens": tokens.copy(),
            "segment_ids": segment_ids.copy(),
            "riders": [tuple(r) for r in riders]})
        return [("ok", kind) + tuple(r) for r in riders]

    def run_packed_timed(self, kind, tokens, segment_ids, annotations,
                         riders, heads=None, timed=True):
        # `timed` mirrors the real dispatcher's contract: the scheduler
        # now always calls this entry (timed=False on untimed batches,
        # so the quantized arm's event fields flow either way).
        outs = self.run_packed(kind, tokens, segment_ids, annotations,
                               riders, heads=heads)
        if not timed:
            return outs, {}
        real = int((tokens != 0).sum())
        grid = tokens.size
        return outs, {"pad_fraction": round(1 - real / grid, 6),
                      "segments": len(riders),
                      "segments_per_row": round(
                          len(riders) / tokens.shape[0], 4)}


def _req(kind="embed", seq="MKT", span=16, clock=None, deadline=None):
    tokens = np.full(span, 7, np.int32)
    return Request(kind=kind, seq=seq, tokens=tokens, bucket_len=span,
                   future=Future(), enqueued_at=clock() if clock else 0.0,
                   deadline=deadline)


def _sched(dispatcher=None, rows=2, max_wait=0.01, clock=None,
           max_segments=4, **kw):
    q = RequestQueue(max_depth=64)
    done = []

    def finalize(req, row):  # the Server's _finalize resolves futures
        done.append((req, row))
        if not req.future.done():
            req.future.set_result(row)

    sched = PackedBatchScheduler(
        q, dispatcher or StubRaggedDispatcher(), finalize,
        rows_per_batch=rows, max_wait_s=max_wait,
        clock=clock or FakeClock(), max_segments=max_segments, **kw)
    return q, sched, done


# ----------------------------------------------------- formation logic

class TestPackedFormation:
    def test_first_fit_geometry_rides_the_batch(self):
        clock = FakeClock()
        disp = StubRaggedDispatcher()
        q, sched, done = _sched(disp, rows=2, clock=clock)
        # spans 20+20 fill row0 to 40 (<48-2 left over), 20 opens row1
        for s in ("a", "b", "c"):
            q.push(_req(seq=s, span=20, clock=clock))
        q.close()
        assert sched.poll(clock()) == 3
        (call,) = disp.calls
        # riders: (row, seg0based, start, span), row-major
        assert call["riders"] == [(0, 0, 0, 20), (0, 1, 20, 20),
                                  (1, 0, 0, 20)]
        assert (call["segment_ids"][0, :20] == 1).all()
        assert (call["segment_ids"][0, 20:40] == 2).all()
        assert (call["segment_ids"][0, 40:] == 0).all()
        assert (call["segment_ids"][1, :20] == 1).all()
        assert len(done) == 3

    def test_open_frontier_trigger_keeps_newest_row(self):
        clock = FakeClock()
        disp = StubRaggedDispatcher()
        q, sched, done = _sched(disp, rows=1, clock=clock)
        # Two full-ish rows + a third opens: dispatch pops the OLDEST
        # row only; the frontier row stays open for more fill.
        for s in "abc":
            q.push(_req(seq=s, span=40, clock=clock))
        assert sched.poll(clock()) == 1      # >1 open rows -> oldest
        assert sched.pending_rows() == 2
        assert sched.poll(clock()) == 1      # still >1 (b, c)
        assert sched.pending_rows() == 1
        assert sched.poll(clock()) == 0      # one open row, not overdue
        clock.advance(0.02)                  # max_wait trigger
        assert sched.poll(clock()) == 1
        assert sched.pending_rows() == 0

    def test_max_wait_dispatches_underfull(self):
        clock = FakeClock()
        q, sched, done = _sched(rows=4, clock=clock)
        q.push(_req(span=16, clock=clock))
        assert sched.poll(clock()) == 0
        clock.advance(0.005)
        assert sched.poll(clock()) == 0      # not overdue yet
        clock.advance(0.006)
        assert sched.poll(clock()) == 1      # overdue -> ships 1 rider
        assert len(done) == 1

    def test_deadline_expires_inside_open_row(self):
        clock = FakeClock()
        q, sched, done = _sched(rows=4, clock=clock)
        doomed = _req(span=16, clock=clock, deadline=clock() + 0.002)
        live = _req(span=16, clock=clock)
        q.push(doomed)
        q.push(live)
        sched.poll(clock())                  # ingest + pack, no dispatch
        clock.advance(0.005)                 # past doomed's deadline
        sched.poll(clock())
        with pytest.raises(DeadlineExceededError):
            doomed.future.result(timeout=0)
        assert sched.expired_total == 1
        clock.advance(0.01)
        assert sched.poll(clock()) == 1      # live one still ships
        assert live.future.result(timeout=0)[0] == "ok"

    def test_dispatch_failure_fails_batch_only(self):
        clock = FakeClock()
        disp = StubRaggedDispatcher()
        q, sched, done = _sched(disp, rows=1, clock=clock)
        boom = RuntimeError("device on fire")
        disp.fail_with = boom
        r1 = _req(span=16, clock=clock)
        q.push(r1)
        clock.advance(0.02)
        assert sched.poll(clock()) == 1
        assert r1.future.exception(timeout=0) is boom
        disp.fail_with = None
        r2 = _req(span=16, clock=clock)
        q.push(r2)
        clock.advance(0.02)
        assert sched.poll(clock()) == 1      # scheduler survived
        assert r2.future.result(timeout=0)[0] == "ok"

    def test_fail_pending_drains_packed_rows(self):
        clock = FakeClock()
        q, sched, done = _sched(rows=8, clock=clock)
        reqs = [_req(seq=s, span=16, clock=clock) for s in "abcd"]
        for r in reqs:
            q.push(r)
        sched.poll(clock())                  # packed, not dispatched
        failed = sched.fail_pending(ServerClosedError("abort"))
        assert [id(r) for r in failed] == [id(r) for r in reqs]
        for r in reqs:
            with pytest.raises(ServerClosedError):
                r.future.result(timeout=0)
        assert sched.pending_rows() == 0

    def test_formation_deterministic_under_fake_clock(self):
        def run():
            clock = FakeClock()
            disp = StubRaggedDispatcher()
            q, sched, _ = _sched(disp, rows=2, clock=clock)
            rng = np.random.default_rng(5)
            for i in range(12):
                q.push(_req(seq=str(i), span=int(rng.choice(BUCKETS)),
                            clock=clock))
                clock.advance(0.001)
                sched.poll(clock())
            q.close()
            while sched.poll(clock()):
                pass
            return [c["riders"] for c in disp.calls]

        assert run() == run()


# ------------------------------------------------------- end to end

def _drain_poll(srv, futs):
    srv.queue.close()
    while srv.scheduler.poll():
        pass
    return [f.result(timeout=5) for f in futs]


def _serve(trunk, mode, kind, seqs, heads=None, head_of=None, **kw):
    params, cfg = trunk
    srv = Server(params, cfg, max_batch=4, max_wait_s=60.0, cache_size=0,
                 warm_kinds=(), serve_mode=mode, heads=heads, **kw)
    futs = [srv.submit(kind, s,
                       head_id=head_of(i) if head_of else None)
            for i, s in enumerate(seqs)]
    out = _drain_poll(srv, futs)
    stats = srv.stats()
    srv.drain(timeout=10)
    return out, stats


class TestRaggedParity:
    """THE acceptance gate: identical traffic, bucketed vs ragged,
    per-request outputs within the documented jitted ≤1e-5 tolerance."""

    def test_embed_parity_and_executable_collapse(self, trunk, seqs):
        b, bs = _serve(trunk, "bucketed", "embed", seqs)
        r, rs = _serve(trunk, "ragged", "embed", seqs)
        for x, y in zip(b, r):
            np.testing.assert_allclose(x["global"], y["global"],
                                       atol=1e-5, rtol=1e-5)
            np.testing.assert_allclose(x["local_mean"], y["local_mean"],
                                       atol=1e-5, rtol=1e-5)
        # O(kinds): one packed executable for the one kind served.
        assert rs["executables"] == 1
        assert bs["executables"] > rs["executables"]
        assert rs["serve_mode"] == "ragged"

    def test_predict_go_parity(self, trunk, seqs):
        b, _ = _serve(trunk, "bucketed", "predict_go", seqs)
        r, _ = _serve(trunk, "ragged", "predict_go", seqs)
        for x, y in zip(b, r):
            np.testing.assert_allclose(x, y, atol=1e-5, rtol=1e-5)

    def test_predict_residues_parity_shapes_and_fill(self, trunk, seqs):
        masked = [s[:2] + "?" + s[3:] if len(s) > 3 else s for s in seqs]
        b, _ = _serve(trunk, "bucketed", "predict_residues", masked)
        r, _ = _serve(trunk, "ragged", "predict_residues", masked)
        for (bf, bp), (rf, rp) in zip(b, r):
            assert bp.shape == rp.shape  # (bucket_len == span, V)
            np.testing.assert_allclose(bp, rp, atol=1e-5, rtol=1e-5)
            assert bf == rf              # same argmax fills

    def test_predict_task_mixed_heads_parity(self, trunk, seqs):
        tasks = [TaskConfig(kind="token_classification", num_outputs=4),
                 TaskConfig(kind="sequence_classification", num_outputs=3),
                 TaskConfig(kind="sequence_regression", num_outputs=1)]
        heads = [LoadedHead(f"h{i}", f"h{i}", t,
                            ft_model.head_init(jax.random.PRNGKey(i + 1),
                                               MODEL, t), {})
                 for i, t in enumerate(tasks)]
        b, _ = _serve(trunk, "bucketed", "predict_task", seqs,
                      heads=heads, head_of=lambda i: f"h{i % 3}")
        r, rs = _serve(trunk, "ragged", "predict_task", seqs,
                       heads=heads, head_of=lambda i: f"h{i % 3}")
        for i, (x, y) in enumerate(zip(b, r)):
            assert x.shape == y.shape, i
            np.testing.assert_allclose(x, y, atol=1e-5, rtol=1e-5)
        # One shared packed trunk; tails don't count as trunk shapes.
        assert rs["executables"] == 1

    def test_ragged_cache_short_circuits(self, trunk):
        params, cfg = trunk
        srv = Server(params, cfg, max_batch=2, max_wait_s=60.0,
                     cache_size=8, warm_kinds=(), serve_mode="ragged")
        f1 = srv.submit("embed", "MKTAYIAK")
        _drain_poll(srv, [f1])
        f2 = srv.submit("embed", "MKTAYIAK")  # hit: resolved future
        assert f2.done()
        np.testing.assert_array_equal(f1.result()["global"],
                                      f2.result()["global"])
        assert srv.cache_hit_returns == 1
        srv.drain(timeout=10)

    def test_ragged_drain_no_loss_under_threads(self, trunk, seqs):
        params, cfg = trunk
        srv = Server(params, cfg, max_batch=2, max_wait_s=0.002,
                     cache_size=0, warm_kinds=("embed",),
                     serve_mode="ragged").start()
        futs = []
        lock = threading.Lock()

        def client(w):
            for s in seqs[w::4]:
                f = srv.submit("embed", s)
                with lock:
                    futs.append(f)

        threads = [threading.Thread(target=client, args=(w,))
                   for w in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert srv.drain(timeout=30)
        assert len(futs) == len(seqs)
        for f in futs:
            assert f.result(timeout=5)["global"].shape == (
                MODEL.global_dim,)
        srv.close()


class TestRaggedTelemetry:
    def test_packed_events_validate_and_diagnose(self, trunk, seqs,
                                                 tmp_path):
        from proteinbert_tpu.obs import Telemetry, read_events
        from proteinbert_tpu.obs.diagnose import (
            render_serve, summarize_serve,
        )

        params, cfg = trunk
        path = tmp_path / "events.jsonl"
        tele = Telemetry(events_path=str(path))
        srv = Server(params, cfg, max_batch=2, max_wait_s=0.005,
                     cache_size=0, warm_kinds=("embed",),
                     serve_mode="ragged", telemetry=tele,
                     trace_sample_rate=1.0)
        srv.scheduler.time_batches = True
        srv.start()
        futs = [srv.submit("embed", s) for s in seqs]
        for f in futs:
            f.result(timeout=30)
        srv.drain(timeout=30)
        tele.close()

        recs = read_events(str(path), strict=True)  # schema-valid
        batches = [r for r in recs if r["event"] == "serve_batch"]
        assert batches
        for b in batches:
            assert b["mode"] == "ragged"
            assert b["bucket_len"] == SEQ_LEN
            assert b["rows"] == 2
            assert 1 <= b["segments"] <= 2 * 8
            assert 0.0 <= b["pad_fraction"] <= 1.0
        reqs = [r for r in recs if r["event"] == "serve_request"]
        assert reqs
        for r in reqs:
            assert r["mode"] == "ragged"
            assert r["segments"] >= 1
            # span rides the bucket_len field: a real bucket, not L
            assert r["bucket_len"] in BUCKETS
        start = next(r for r in recs if r["event"] == "serve_start")
        assert start["config"]["serve_mode"] == "ragged"

        summary = summarize_serve(recs)
        assert summary["batches"]["modes"] == {"ragged": len(batches)}
        assert summary["batches"]["segments"] == len(seqs)
        assert summary["batches"]["mean_segments_per_row"] > 0
        assert summary["executables"]["count"] == 1
        assert summary["executables"]["serve_mode"] == "ragged"
        text = render_serve(summary)
        assert "packed:" in text and "executables: 1 warm" in text
        # pad_wasted attribution (the ragged lever) present
        assert any("pad_wasted" in k
                   for k in summary["stage_attribution"])

    def test_executable_gauges_track_warmup(self, trunk):
        from proteinbert_tpu.obs import Telemetry

        params, cfg = trunk
        tele = Telemetry()
        srv = Server(params, cfg, max_batch=2, max_wait_s=60.0,
                     cache_size=0, warm_kinds=("embed", "predict_go"),
                     serve_mode="ragged", telemetry=tele)
        srv.start()
        m = tele.metrics
        assert m.gauge("serve_executable_count").value == 2  # O(kinds)
        assert m.gauge("serve_warmup_seconds_total").value > 0
        assert srv.stats()["executables"] == 2
        srv.drain(timeout=10)


def _tiny_track_params(C=4):
    import jax.numpy as jnp

    return {
        "narrow_conv": {"kernel": jnp.zeros((3, C, C)),
                        "bias": jnp.zeros(C)},
        "wide_conv": {"kernel": jnp.zeros((3, C, C)),
                      "bias": jnp.zeros(C)},
        "local_ln1": {"scale": jnp.ones(C), "bias": jnp.zeros(C)},
        "local_dense": {"kernel": jnp.eye(C), "bias": jnp.zeros(C)},
        "local_ln2": {"scale": jnp.ones(C), "bias": jnp.zeros(C)},
    }


class TestFusedPathCounter:
    """ISSUE 10 satellite: the two-sided fused_kernel_path counter and
    the per-(reason, shape) one-time warning (the per-process latch
    misled a server that built a reference executable for a NEW shape
    after a fused one)."""

    def test_two_sided_counter_and_shape_keyed_warning(self):
        import jax.numpy as jnp

        from proteinbert_tpu.kernels import fused_block as fb

        params = _tiny_track_params()
        seen_path, records = [], []

        def path_cb(p, r):
            seen_path.append((p, r))

        # Handler attached straight to the kernel logger: caplog relies
        # on propagation to root, which an earlier start_log() test may
        # have reconfigured.
        handler = logging.Handler()
        handler.emit = records.append
        fb.logger.addHandler(handler)
        fb.register_path_observer(path_cb)
        key = ("reference", "segments")
        before = fb.PATH_TOTAL.get(key, 0)
        # The deprecated one-release fused_kernel_fallback_total mirror
        # is GONE (removed in ISSUE 12, as PR 9 scheduled).
        assert not hasattr(fb, "FALLBACK_TOTAL")
        assert not hasattr(fb, "register_fallback_observer")
        # Reset the warn latch for exactly the shapes this test uses so
        # the count below is deterministic whatever ran earlier.
        shapes = [(1, 24, 4, 2, "float32"), (1, 40, 4, 2, "float32")]
        for sh in shapes:
            fb._FALLBACK_WARNED.discard(("segments", sh))
        try:
            x24 = jnp.zeros((1, 24, 4))
            x40 = jnp.zeros((1, 40, 4))
            bc = jnp.zeros((1, 2, 4))  # per-SEGMENT (B, S, C)
            seg24 = jnp.ones((1, 24), jnp.int32)
            seg40 = jnp.ones((1, 40), jnp.int32)
            # C=4 is not lane-aligned → reference, reason=segments.
            fb.fused_local_track_segments(params, x24, bc, seg24)
            fb.fused_local_track_segments(params, x24, bc, seg24)
            fb.fused_local_track_segments(params, x40, bc, seg40)
        finally:
            fb.logger.removeHandler(handler)
            fb.unregister_path_observer(path_cb)
        assert fb.PATH_TOTAL[key] == before + 3
        assert seen_path == [key] * 3
        warnings = [r for r in records
                    if "XLA reference" in r.getMessage()]
        # Same shape twice → ONE warning; the new shape → its own.
        assert len(warnings) == 2

    def test_server_mirrors_path_into_registry(self, trunk):
        from proteinbert_tpu.kernels import attention as ka
        from proteinbert_tpu.kernels import fused_block as fb
        from proteinbert_tpu.obs import Telemetry

        params, cfg = trunk
        tele = Telemetry()
        srv = Server(params, cfg, max_batch=2, max_wait_s=60.0,
                     cache_size=0, warm_kinds=(), serve_mode="ragged",
                     telemetry=tele)
        fb.note_kernel_path("reference", "segments", ("test-shape",))
        fb.note_kernel_path("pallas", "packed", ("test-shape",))
        # The attention counter mirrors alongside (ISSUE 13 satellite).
        ka.note_attention_path("pallas", "packed", ("test-shape",))
        ka.note_attention_path("reference", "segments", ("test-shape",))
        c_ref = tele.metrics.counter("fused_kernel_path_total",
                                     path="reference", reason="segments")
        c_pal = tele.metrics.counter("fused_kernel_path_total",
                                     path="pallas", reason="packed")
        a_ref = tele.metrics.counter("attention_kernel_path_total",
                                     path="reference", reason="segments")
        a_pal = tele.metrics.counter("attention_kernel_path_total",
                                     path="pallas", reason="packed")
        assert c_ref.value == 1 and c_pal.value == 1
        assert a_ref.value == 1 and a_pal.value == 1
        stats = srv.stats()
        assert stats["fused_path"]["reference/segments"] >= 1
        assert stats["fused_path"]["pallas/packed"] >= 1
        assert stats["attention_path"]["pallas/packed"] >= 1
        assert stats["attention_path"]["reference/segments"] >= 1
        # The deprecated one-sided stats mirror is gone (ISSUE 12).
        assert "fused_fallback" not in stats
        srv.drain(timeout=10)
        fb.note_kernel_path("pallas", "packed")  # observer released
        ka.note_attention_path("pallas", "packed")
        assert c_pal.value == 1
        assert a_pal.value == 1

    def test_ragged_packed_takes_pallas_path(self):
        """THE ragged-serve fast-path smoke (ISSUE 10/13/16
        acceptance): on a shape the kernels support, the packed
        executable the ragged dispatcher builds must land on the
        Pallas ONE-PASS path — the whole trunk block in one kernel —
        with zero fallbacks on any of the three counter families."""
        from proteinbert_tpu.kernels import attention as ka
        from proteinbert_tpu.kernels import fused_block as fb
        from proteinbert_tpu.kernels import one_pass as op

        pcfg = PretrainConfig(
            model=ModelConfig(local_dim=128, global_dim=32, key_dim=8,
                              num_heads=2, num_blocks=1,
                              num_annotations=32, dtype="float32",
                              use_pallas=True),
            data=DataConfig(seq_len=SEQ_LEN, batch_size=2,
                            buckets=BUCKETS),
            optimizer=OptimizerConfig(warmup_steps=5),
            train=TrainConfig(seed=0, max_steps=1),
            checkpoint=CheckpointConfig(),
        )
        assert op.pallas_onepass_supported(128, 32, SEQ_LEN, 4, 8, 2,
                                           "float32")
        params = create_train_state(jax.random.PRNGKey(0), pcfg).params
        disp = RaggedDispatcher(params, pcfg, rows_per_batch=2,
                                max_segments=4)
        before = dict(op.ONEPASS_PATH_TOTAL)
        fb_before = dict(fb.PATH_TOTAL)
        attn_before = dict(ka.ATTN_PATH_TOTAL)
        assert disp.warmup(("embed",)) == 1
        delta = {k: op.ONEPASS_PATH_TOTAL.get(k, 0) - before.get(k, 0)
                 for k in op.ONEPASS_PATH_TOTAL}
        assert delta.get(("pallas", "packed"), 0) >= 1
        assert delta.get(("reference", "segments"), 0) == 0
        # The supported shape never degrades to the two-kernel
        # composition, so the per-kernel families stay silent too.
        fb_delta = {k: fb.PATH_TOTAL.get(k, 0) - fb_before.get(k, 0)
                    for k in fb.PATH_TOTAL}
        assert fb_delta.get(("reference", "segments"), 0) == 0
        attn_delta = {k: ka.ATTN_PATH_TOTAL.get(k, 0)
                      - attn_before.get(k, 0)
                      for k in ka.ATTN_PATH_TOTAL}
        assert attn_delta.get(("reference", "segments"), 0) == 0


class TestRaggedMesh:
    """PR 8 residual closed (ISSUE 11 satellite): ragged packed batches
    shard over the mesh batch dim via serve_batch_sharding — parity
    against the unsharded ragged dispatcher within the jitted ≤1e-5
    tolerance, and indivisible row counts still rejected clearly."""

    def test_ragged_mesh_parity_vs_unsharded(self, trunk, seqs):
        from proteinbert_tpu.parallel import mesh_for_devices

        mesh = mesh_for_devices(2)
        b, _ = _serve(trunk, "ragged", "embed", seqs)
        r, rs = _serve(trunk, "ragged", "embed", seqs, mesh=mesh)
        for x, y in zip(b, r):
            np.testing.assert_allclose(x["global"], y["global"],
                                       atol=1e-5, rtol=1e-5)
            np.testing.assert_allclose(x["local_mean"], y["local_mean"],
                                       atol=1e-5, rtol=1e-5)
        assert rs["executables"] == 1  # sharding adds no executables

    def test_ragged_mesh_sharded_placement(self, trunk):
        from proteinbert_tpu.parallel import mesh_for_devices

        params, cfg = trunk
        mesh = mesh_for_devices(2)
        d = RaggedDispatcher(params, cfg, rows_per_batch=2, mesh=mesh)
        assert d._shardings is not None
        assert set(d._shardings) >= {"tokens", "segment_ids",
                                     "annotations"}
        tokens, seg, ann, _ = d._dummy_packed()
        tb, sb, ab = d._place_packed(tokens, seg, ann)
        for arr in (tb, sb, ab):
            assert len(arr.sharding.device_set) == 2

    def test_ragged_mesh_indivisible_rows_rejected(self, trunk):
        from proteinbert_tpu.parallel import mesh_for_devices

        params, cfg = trunk
        mesh = mesh_for_devices(2)
        with pytest.raises(ValueError, match="not divisible"):
            RaggedDispatcher(params, cfg, rows_per_batch=3, mesh=mesh)

    def test_mesh_serves_committed_params(self, trunk):
        """Regression: orbax-restored trunks arrive COMMITTED to one
        device, and a jitted call mixing them with batch-dim-sharded
        inputs is an 'incompatible devices' error — the dispatcher must
        replicate the trunk over the mesh (both modes; fresh
        uncommitted test params used to mask this)."""
        from proteinbert_tpu.parallel import mesh_for_devices
        from proteinbert_tpu.serve import BucketDispatcher

        params, cfg = trunk
        committed = jax.device_put(params, jax.devices()[0])
        mesh = mesh_for_devices(2)
        d = RaggedDispatcher(committed, cfg, rows_per_batch=2, mesh=mesh)
        tokens, seg, ann, riders = d._dummy_packed()
        out = d.run_packed("embed", tokens, seg, ann, riders)
        assert out[0]["global"].shape == (cfg.model.global_dim,)
        b = BucketDispatcher(committed, cfg, max_batch=2, mesh=mesh)
        res = b.run("embed", np.zeros((2, BUCKETS[0]), np.int32))
        assert res["global"].shape == (2, cfg.model.global_dim)
        # Registry-loaded HEADS arrive committed too — add_head must
        # replicate them the same way (predict_task tails mix head
        # params with mesh-sharded trunk outputs).
        task = TaskConfig(kind="sequence_classification", num_outputs=3)
        hp = jax.device_put(
            ft_model.head_init(jax.random.PRNGKey(5), MODEL, task),
            jax.devices()[0])
        b.add_head(LoadedHead("hx", "hx", task, hp, {}))
        rows = np.zeros((2, BUCKETS[0]), np.int32)
        outs = b.run("predict_task", rows,
                     heads=[b.get_head("hx")] * 2)
        assert outs[0].shape == (3,)


class TestRaggedDispatcherContracts:

    def test_bucketed_api_refuses_packed_dispatcher(self, trunk):
        params, cfg = trunk
        d = RaggedDispatcher(params, cfg, rows_per_batch=2)
        with pytest.raises(NotImplementedError, match="run_packed"):
            d.run("embed", np.zeros((2, SEQ_LEN), np.int32))

    def test_server_mode_validation(self, trunk):
        params, cfg = trunk
        with pytest.raises(ValueError, match="serve_mode"):
            Server(params, cfg, serve_mode="packed")
        with pytest.raises(ValueError, match="partition_heads"):
            Server(params, cfg, serve_mode="ragged",
                   partition_heads=True)
        with pytest.raises(ValueError, match="batch_classes"):
            Server(params, cfg, serve_mode="ragged",
                   batch_classes=(2, 4))


class TestNeighborsRideOnePass:
    """ISSUE 17: the embed leg of a /v1/neighbors request is not a new
    code path — it is the SAME packed one-pass executable the ragged
    trunk serves embeds with. Proven the same way as the fast-path
    smoke above: by counter delta, on a Pallas-supported shape."""

    def test_neighbors_query_takes_pallas_onepass_path(self, tmp_path):
        from proteinbert_tpu.heads import trunk_fingerprint
        from proteinbert_tpu.index import build_index
        from proteinbert_tpu.index.scorer import NeighborIndex
        from proteinbert_tpu.kernels import one_pass as op
        from tests.test_index import make_store

        pcfg = PretrainConfig(
            model=ModelConfig(local_dim=128, global_dim=32, key_dim=8,
                              num_heads=2, num_blocks=1,
                              num_annotations=32, dtype="float32",
                              use_pallas=True),
            data=DataConfig(seq_len=SEQ_LEN, batch_size=2,
                            buckets=BUCKETS),
            optimizer=OptimizerConfig(warmup_steps=5),
            train=TrainConfig(seed=0, max_steps=1),
            checkpoint=CheckpointConfig(),
        )
        assert op.pallas_onepass_supported(128, 32, SEQ_LEN, 4, 8, 2,
                                           "float32")
        params = create_train_state(jax.random.PRNGKey(0), pcfg).params
        store = str(tmp_path / "store")
        make_store(store, n=32, dim=pcfg.model.global_dim,
                   fingerprint=trunk_fingerprint(params))
        index_dir = str(tmp_path / "index")
        build_index(store, index_dir, num_centroids=4, block_size=8,
                    kmeans_iters=4)
        index = NeighborIndex.load(index_dir)

        srv = Server(params, pcfg, max_batch=4, max_wait_s=60.0,
                     cache_size=0, warm_kinds=(), serve_mode="ragged",
                     index=index, nprobe=4)
        before = dict(op.ONEPASS_PATH_TOTAL)
        fut = srv.submit("neighbors", "MKTAYIAKQRQISFVK", top_k=3)
        got = _drain_poll(srv, [fut])[0]
        delta = {k: op.ONEPASS_PATH_TOTAL.get(k, 0) - before.get(k, 0)
                 for k in op.ONEPASS_PATH_TOTAL}
        assert delta.get(("pallas", "packed"), 0) >= 1
        assert delta.get(("reference", "segments"), 0) == 0
        assert len(got["neighbors"]) == 3
        # The lookup leg rides the trunk's packed executable — it must
        # not have compiled a second trunk program.
        assert srv.stats()["executables"] == 1
        srv.drain(timeout=10)
