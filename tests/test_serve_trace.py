"""Per-request serve tracing + perf sentinel (ISSUE 6).

Three tiers in one file:

- **RequestTrace invariants** — stages are contiguous clock intervals,
  so they tile [submit, done] and sum to the end-to-end latency by
  construction; a trace seals exactly once; stride sampling emits an
  exact fraction with no RNG state.
- **propagation** — fake-clock scheduler tests (queue-wait recorded
  even when tracing is off, rejections carry queue depth, terminal
  complete callbacks) and end-to-end Server tests over a real tiny
  trunk: drain vs abort leave no orphaned spans, failed batches close
  their traces with error status, sampling suppresses ok-requests but
  never failures, SLO burn rates surface on stats()/metrics/events.
- **perf-regression sentinel** — tools/bench_trajectory.py flags a
  synthetic 20% regression, stays quiet on the checked-in real bench
  history (the zero-false-positive acceptance), and fails only on
  malformed inputs.
"""

import importlib.util
import json
import os
import threading
from concurrent.futures import Future

import numpy as np
import pytest

import jax

from proteinbert_tpu.configs import (
    CheckpointConfig, DataConfig, ModelConfig, OptimizerConfig,
    PretrainConfig, TrainConfig,
)
from proteinbert_tpu.obs import Telemetry, read_events
from proteinbert_tpu.obs.events import validate_record
from proteinbert_tpu.serve import (
    MicroBatchScheduler, Request, RequestQueue, RequestTrace, Server,
    ServerClosedError,
)
from proteinbert_tpu.serve.trace import STAGES, stride_sampled
from proteinbert_tpu.train import create_train_state

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SEQ_LEN = 48
BUCKETS = (16, 32, 48)


def _cfg():
    return PretrainConfig(
        model=ModelConfig(local_dim=16, global_dim=32, key_dim=8,
                          num_heads=2, num_blocks=2, num_annotations=32,
                          dtype="float32"),
        data=DataConfig(seq_len=SEQ_LEN, batch_size=4),
        optimizer=OptimizerConfig(warmup_steps=5),
        train=TrainConfig(seed=0, max_steps=1),
        checkpoint=CheckpointConfig(),
    )


@pytest.fixture(scope="module")
def trunk():
    cfg = _cfg()
    state = create_train_state(jax.random.PRNGKey(cfg.train.seed), cfg)
    return state.params, cfg


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


# ------------------------------------------------- trace invariants

class TestRequestTrace:
    def test_stages_tile_submit_to_done(self):
        tr = RequestTrace("r1", "embed", now=10.0, wall=0.0)
        tr.mark_enqueued(10.1)
        tr.mark_ingested(10.3)
        tr.mark_popped(10.6)
        tr.mark_run(11.0, 11.5)
        tr.mark_batch(32, 4, rows=3, pad_fraction=0.25,
                      prep_s=0.4, device_s=0.5)
        assert tr.finish("ok", now=11.7)
        stages = tr.stages()
        # `lookup` exists only on neighbors requests (ISSUE 17) — a
        # plain embed tiles the remaining stages exactly.
        assert list(stages) == [s for s in STAGES if s != "lookup"]
        assert stages["submit"] == pytest.approx(0.1)
        assert stages["queue"] == pytest.approx(0.2)
        assert stages["batch_form"] == pytest.approx(0.3)
        assert stages["dispatch"] == pytest.approx(0.4)
        assert stages["execute"] == pytest.approx(0.5)
        assert stages["finalize"] == pytest.approx(0.2)
        # The acceptance property: contiguous intervals sum to e2e.
        assert sum(stages.values()) == pytest.approx(tr.e2e_s(), abs=1e-9)
        assert tr.e2e_s() == pytest.approx(1.7)

    def test_lookup_mark_splits_tail_and_still_tiles(self):
        tr = RequestTrace("r1n", "neighbors", now=10.0, wall=0.0)
        tr.mark_enqueued(10.1)
        tr.mark_ingested(10.3)
        tr.mark_popped(10.6)
        tr.mark_run(11.0, 11.5)
        tr.mark_lookup(11.62)
        assert tr.finish("ok", now=11.7)
        stages = tr.stages()
        assert list(stages) == list(STAGES)
        assert stages["execute"] == pytest.approx(0.5)
        assert stages["lookup"] == pytest.approx(0.12)
        assert stages["finalize"] == pytest.approx(0.08)
        assert sum(stages.values()) == pytest.approx(tr.e2e_s(), abs=1e-9)

    def test_early_exit_has_fewer_marks_still_tiles(self):
        tr = RequestTrace("r2", "embed", now=5.0, wall=0.0)
        assert tr.finish("rejected", now=5.01)
        assert tr.stages() == {"submit": pytest.approx(0.01)}
        tr2 = RequestTrace("r3", "embed", now=5.0, wall=0.0)
        tr2.mark_enqueued(5.1)
        assert tr2.finish("evicted", now=5.5)
        stages = tr2.stages()
        assert list(stages) == ["submit", "queue"]
        assert sum(stages.values()) == pytest.approx(tr2.e2e_s())

    def test_seals_exactly_once(self):
        tr = RequestTrace("r4", "embed", now=0.0, wall=0.0)
        assert tr.finish("error", now=1.0, error=RuntimeError("boom"))
        assert not tr.finish("ok", now=2.0)
        assert tr.outcome == "error"
        assert tr.e2e_s() == pytest.approx(1.0)
        assert "RuntimeError: boom" == tr.error

    def test_out_of_order_marks_clamp_monotonic(self):
        """Marks come from two threads' reads of one clock: a poll()
        that took `now` before a concurrent submit finished stamps
        ingest EARLIER than enqueue. The derived chain clamps, so the
        tiling invariant holds exactly anyway."""
        tr = RequestTrace("r6", "embed", now=10.0, wall=0.0)
        tr.mark_enqueued(10.5)
        tr.mark_ingested(10.4)     # scheduler's stale poll-entry now
        tr.mark_popped(10.6)
        tr.mark_run(10.7, 10.9)
        tr.finish("ok", now=10.8)  # completion read also stale
        stages = tr.stages()
        assert all(v >= 0 for v in stages.values())
        assert sum(stages.values()) == pytest.approx(tr.e2e_s(),
                                                     abs=1e-9)
        assert stages["batch_form"] == pytest.approx(0.1)  # clamped
        assert tr.e2e_s() == pytest.approx(0.9)  # end = last mark

    def test_stride_sampling_exact_fraction(self):
        for rate, expect in ((0.0, 0), (0.25, 250), (1.0, 1000)):
            hits = sum(stride_sampled(n, rate) for n in range(1, 1001))
            assert hits == expect

    def test_event_fields_round_trip_schema(self):
        from proteinbert_tpu.obs.events import make_record

        tr = RequestTrace("r5", "embed", now=0.0, wall=0.0)
        tr.mark_enqueued(0.1)
        tr.mark_batch(16, 2, rows=2, pad_fraction=0.5)
        tr.finish("ok", now=0.4)
        rec = make_record("serve_request", seq=0, t=0.0,
                          **tr.event_fields())
        validate_record(rec)
        assert rec["bucket_len"] == 16 and rec["pad_fraction"] == 0.5

    def test_spans_per_request_lanes(self):
        from proteinbert_tpu.obs import SpanCollector

        col = SpanCollector()
        for rid in ("a", "b"):
            tr = RequestTrace(rid, "embed", now=0.0, wall=100.0)
            tr.mark_enqueued(0.1)
            tr.finish("ok", now=0.3)
            tr.export_spans(col)
        spans = [s for s in col.to_perfetto()["traceEvents"]
                 if s["ph"] == "X"]
        parents = [s for s in spans if s["name"] == "serve.request"]
        assert len(parents) == 2
        # Distinct synthetic lanes: concurrent requests never nest.
        assert len({s["tid"] for s in parents}) == 2
        for p in parents:
            kids = [s for s in spans if s["tid"] == p["tid"]
                    and s["name"] != "serve.request"]
            assert {k["name"] for k in kids} == {"serve.submit",
                                                 "serve.queue"}
            assert sum(k["dur"] for k in kids) \
                == pytest.approx(p["dur"], rel=1e-6)


# -------------------------------------------- scheduler propagation

class FakeDispatcher:
    def __init__(self, fail_kinds=()):
        self.cfg = type("C", (), {})()
        self.cfg.model = type("M", (), {"num_annotations": 4})()
        self.fail_kinds = set(fail_kinds)

    def batch_class(self, rows):
        c = 1
        while c < rows:
            c *= 2
        return c

    def run(self, kind, tokens, annotations=None):
        if kind in self.fail_kinds:
            raise RuntimeError(f"injected dispatch failure for {kind}")
        return np.arange(tokens.shape[0], dtype=np.float32)


def _req(clock, kind="embed", bucket_len=16, deadline=None, trace=None):
    return Request(kind=kind, seq="MKT",
                   tokens=np.zeros(bucket_len, np.int32),
                   bucket_len=bucket_len, future=Future(),
                   enqueued_at=clock(), deadline=deadline, trace=trace)


def _sched(clock, telemetry=None, fail_kinds=(), **kw):
    queue = RequestQueue(max_depth=64)
    done = []
    completed = []
    s = MicroBatchScheduler(
        queue, FakeDispatcher(fail_kinds),
        lambda req, row: req.future.set_result(row) or done.append(req),
        max_batch=2, max_wait_s=0.5, clock=clock, telemetry=telemetry,
        complete_observer=lambda req, outcome, now, err, ctx:
            completed.append((req, outcome, err, ctx)))
    return s, queue, completed


class TestSchedulerPropagation:
    def test_queue_wait_recorded_without_traces(self, tmp_path):
        """The cheap always-on histogram: tracing entirely off (no
        trace objects), yet every dispatched request's queue wait
        lands in serve_queue_wait_seconds AND the stats mirror."""
        clock = FakeClock()
        tele = Telemetry(events_path=str(tmp_path / "ev.jsonl"))
        s, queue, completed = _sched(clock, telemetry=tele)
        queue.push(_req(clock))
        queue.push(_req(clock))
        assert s.poll(now=clock.advance(0.25)) == 2
        assert s.queue_wait.count == 2
        assert s.queue_wait.max == pytest.approx(0.25)
        snap = tele.metrics.snapshot()
        assert snap["histograms"]["serve_queue_wait_seconds"]["count"] == 2
        assert [o for _, o, _, _ in completed] == ["ok", "ok"]
        tele.close()

    def test_expiry_emits_queue_depth_and_completes_expired(
            self, tmp_path):
        clock = FakeClock()
        path = str(tmp_path / "ev.jsonl")
        tele = Telemetry(events_path=path)
        s, queue, completed = _sched(clock, telemetry=tele)
        tr = RequestTrace("rx", "embed", clock.t)
        queue.push(_req(clock, deadline=clock.t + 0.1, trace=tr))
        queue.push(_req(clock))  # alive: still pending after expiry
        s.poll(now=clock.advance(0.2))
        tele.close()
        rej = [r for r in read_events(path, strict=True)
               if r["event"] == "serve_reject"]
        assert len(rej) == 1 and rej[0]["reason"] == "deadline"
        # Depth at rejection: the one surviving pending request.
        assert rej[0]["queue_depth"] == 1
        validate_record(rej[0])
        assert [(o, type(e).__name__ if e else None)
                for _, o, e, _ in completed] == [("expired", None)]
        # Expired requests count in the queue-wait histogram too.
        assert s.queue_wait.count == 1
        assert tr.t_ingested is not None  # marks up to the expiry

    def test_dispatch_failure_completes_error_with_context(self):
        clock = FakeClock()
        s, queue, completed = _sched(clock, fail_kinds=("embed",))
        tr = RequestTrace("rf", "embed", clock.t)
        queue.push(_req(clock, trace=tr))
        queue.push(_req(clock, trace=RequestTrace("rg", "embed", clock.t)))
        s.poll(now=clock.advance(0.01))
        assert [o for _, o, _, _ in completed] == ["error", "error"]
        _, _, err, ctx = completed[0]
        assert isinstance(err, RuntimeError)
        assert ctx["rows"] == 2 and ctx["bucket_len"] == 16
        # The failed batch still closed the trace's run interval.
        assert tr.t_run0 is not None and tr.rows == 2


# ----------------------------------------------- server end-to-end

RAGGED = ["MKTAYIAKQR", "ACDEFGHIKLMNPQRSTVWY", "GG",
          "ACDEFGHIKLMNPQRSTVWY" * 2, "MKTAYIAKQRMKTAYIAKQRAC"]


def _server(trunk, tele, **kw):
    params, cfg = trunk
    kw.setdefault("buckets", BUCKETS)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_wait_s", 0.002)
    kw.setdefault("queue_depth", 64)
    kw.setdefault("cache_size", 8)
    kw.setdefault("warm_kinds", ())
    return Server(params, cfg, telemetry=tele, **kw)


class TestServerTracing:
    def test_drain_traces_sum_and_no_orphaned_spans(self, trunk,
                                                    tmp_path):
        path = str(tmp_path / "ev.jsonl")
        tele = Telemetry(events_path=path, spans=True)
        srv = _server(trunk, tele)
        srv.start()
        for seq in RAGGED:
            srv.embed(seq, timeout=30)
        srv.embed(RAGGED[0], timeout=30)  # cache hit
        srv.drain(timeout=30)
        tele.close()
        recs = read_events(path, strict=True)
        for rec in recs:
            validate_record(rec)
        reqs = [r for r in recs if r["event"] == "serve_request"]
        assert len(reqs) == len(RAGGED) + 1
        outcomes = [r["outcome"] for r in reqs]
        assert outcomes.count("ok") == len(RAGGED)
        assert outcomes.count("cache_hit") == 1
        ids = [r["request_id"] for r in reqs]
        assert len(set(ids)) == len(ids)  # sealed exactly once each
        for r in reqs:
            assert set(r["stages"]) <= set(STAGES)
            # Contiguous stages tile the request exactly.
            assert sum(r["stages"].values()) \
                == pytest.approx(r["e2e_s"], abs=1e-5)
            if r["outcome"] == "ok":
                assert r["bucket_len"] in BUCKETS
                assert r["rows"] >= 1 and 0 <= r["pad_fraction"] < 1
                assert {"queue", "execute"} <= set(r["stages"])
            assert r["cache"] == ("hit" if r["outcome"] == "cache_hit"
                                  else "miss")
        # Spans: one closed parent lane per emitted trace, no orphans.
        spans = [s for s in tele.spans.to_perfetto()["traceEvents"]
                 if s["ph"] == "X"]
        parents = [s for s in spans if s["name"] == "serve.request"]
        assert sorted(p["args"]["request_id"] for p in parents) \
            == sorted(ids)
        assert all(p["args"]["outcome"] in ("ok", "cache_hit")
                   for p in parents)

    def test_sampled_out_suppresses_ok_never_failures(self, trunk,
                                                      tmp_path):
        path = str(tmp_path / "ev.jsonl")
        tele = Telemetry(events_path=path)
        srv = _server(trunk, tele, on_long="reject",
                      trace_sample_rate=0.0)
        srv.start()
        fut = srv.submit("embed", RAGGED[0])
        assert fut.pbt_request_id  # traced (cheap marks) even at rate 0
        fut.result(timeout=30)
        from proteinbert_tpu.serve import SequenceTooLongError

        with pytest.raises(SequenceTooLongError) as ei:
            srv.embed("A" * (SEQ_LEN + 10), timeout=30)
        srv.drain(timeout=30)
        tele.close()
        reqs = [r for r in read_events(path, strict=True)
                if r["event"] == "serve_request"]
        # The ok request is sampled out; the rejection always emits.
        assert [r["outcome"] for r in reqs] == ["rejected"]
        assert reqs[0]["sampled"] is False
        # Synchronous rejections carry the trace id on the exception
        # (the HTTP layer's X-PBT-Request-Id for 400/503 responses).
        assert ei.value.pbt_request_id == reqs[0]["request_id"]

    def test_abort_seals_every_trace_no_orphans(self, trunk, tmp_path):
        path = str(tmp_path / "ev.jsonl")
        tele = Telemetry(events_path=path, spans=True)
        # max_wait high + max_batch high: submits sit pending/queued
        # until the abort kills them.
        srv = _server(trunk, tele, max_batch=64, max_wait_s=60.0)
        srv.start()
        futs = [srv.submit("embed", seq) for seq in RAGGED[:3]]
        ids = {f.pbt_request_id for f in futs}
        srv.abort()
        tele.close()
        for f in futs:
            with pytest.raises(ServerClosedError):
                f.result(timeout=5)
        reqs = [r for r in read_events(path, strict=True)
                if r["event"] == "serve_request"]
        assert {r["request_id"] for r in reqs} == ids
        assert all(r["outcome"] == "aborted" for r in reqs)
        assert all("ServerClosedError" in r["error"] for r in reqs)
        parents = [s for s in tele.spans.to_perfetto()["traceEvents"]
                   if s.get("name") == "serve.request"]
        assert {p["args"]["request_id"] for p in parents} == ids
        assert all(p["args"]["outcome"] == "aborted" for p in parents)
        end = [r for r in read_events(path) if r["event"] == "serve_end"]
        assert end and end[-1]["outcome"] == "aborted"

    def test_failed_batch_closes_traces_with_error_status(
            self, trunk, tmp_path, monkeypatch):
        path = str(tmp_path / "ev.jsonl")
        tele = Telemetry(events_path=path, spans=True)
        srv = _server(trunk, tele, cache_size=0)
        def boom(*a, **kw):
            raise RuntimeError("injected device failure")
        monkeypatch.setattr(srv.dispatcher, "run_timed_async", boom)
        monkeypatch.setattr(srv.dispatcher, "run_timed", boom)
        monkeypatch.setattr(srv.dispatcher, "run", boom)
        srv.start()
        futs = [srv.submit("embed", s) for s in RAGGED[:2]]
        for f in futs:
            with pytest.raises(RuntimeError, match="injected"):
                f.result(timeout=30)
        srv.drain(timeout=30)
        tele.close()
        reqs = [r for r in read_events(path, strict=True)
                if r["event"] == "serve_request"]
        assert len(reqs) == 2
        for r in reqs:
            assert r["outcome"] == "error"
            assert "injected device failure" in r["error"]
            # The failed batch still closed its execute interval.
            assert "execute" in r["stages"]
            assert sum(r["stages"].values()) \
                == pytest.approx(r["e2e_s"], abs=1e-5)

    def test_eviction_seals_trace_with_queue_depth(self, trunk,
                                                   tmp_path):
        from proteinbert_tpu.serve import QueueFullError

        path = str(tmp_path / "ev.jsonl")
        tele = Telemetry(events_path=path)
        # Scheduler never started: the queue overflows synchronously.
        srv = _server(trunk, tele, queue_depth=1, cache_size=0)
        f1 = srv.submit("embed", RAGGED[0])
        srv.submit("embed", RAGGED[1])
        with pytest.raises(QueueFullError):
            f1.result(timeout=5)
        srv.abort()
        tele.close()
        recs = read_events(path, strict=True)
        rej = [r for r in recs if r["event"] == "serve_reject"]
        assert rej[0]["reason"] == "queue_full"
        assert rej[0]["queue_depth"] == 1
        by_outcome = {r["outcome"]: r for r in recs
                      if r["event"] == "serve_request"}
        assert by_outcome["evicted"]["request_id"] == f1.pbt_request_id
        assert "aborted" in by_outcome  # the survivor sealed too

    def test_stats_api_shape_kept_and_single_ring(self, trunk):
        """Satellite: the latency ring lives in the obs registry; the
        stats() surface (ISSUE 5 shape) must not change, and /metrics
        must read the SAME ring at scrape time."""
        tele = Telemetry()
        srv = _server(trunk, tele)
        srv.start()
        srv.embed(RAGGED[0], timeout=30)
        stats = srv.stats()
        assert {"n", "p50_s", "p99_s", "mean_s"} == set(stats["latency"])
        assert stats["latency"]["n"] == 1
        assert stats["queue_wait"]["count"] == 1
        assert stats["queue_wait"]["mean_s"] >= 0.0
        # One ring: the registry window IS the server's window.
        assert tele.metrics.quantile_window("serve_latency") \
            is srv.latencies
        prom = tele.metrics.prometheus_text()
        assert "pbt_serve_latency_p50_s" in prom
        assert "pbt_serve_queue_wait_seconds_count 1" in prom
        srv.drain(timeout=30)

    def test_null_telemetry_creates_no_traces_stats_still_real(
            self, trunk):
        srv = _server(trunk, None)
        srv.start()
        fut = srv.submit("embed", RAGGED[0])
        assert not hasattr(fut, "pbt_request_id")
        fut.result(timeout=30)
        stats = srv.stats()
        assert stats["latency"]["n"] == 1  # live unregistered ring
        assert stats["queue_wait"]["count"] == 1
        assert srv.trace_sample_rate is None
        srv.drain(timeout=30)

    def test_slo_surfaces_on_stats_metrics_events(self, trunk,
                                                  tmp_path):
        path = str(tmp_path / "ev.jsonl")
        tele = Telemetry(events_path=path)
        srv = _server(trunk, tele, cache_size=0,
                      slos=["kind=latency,threshold_s=1e-9,target=0.99",
                            "kind=error_rate,target=0.999"])
        srv.start()
        for seq in RAGGED[:3]:
            srv.embed(seq, timeout=30)
        # The future resolves a beat before the scheduler thread feeds
        # the SLO evaluator: drain first, then read.
        srv.drain(timeout=30)
        stats = srv.stats()
        slo = stats["slo"]["latency_e2e"]
        assert slo["breached"] and slo["burn_rate"] > 1.0
        assert slo["total"] == 3 and slo["bad"] == 3
        # Violation attribution includes the padding-waste lever.
        assert "pad_wasted" in slo["attribution"]
        assert "execute" in slo["attribution"]
        assert stats["slo"]["error_rate"]["bad"] == 0
        # Exemplars link a histogram bucket to a traced request id.
        exemplars = [b["exemplar"] for b in slo["histogram"]
                     if b["exemplar"]]
        assert exemplars and all(
            e["request_id"].endswith(("1", "2", "3"))
            for e in exemplars)
        prom = tele.metrics.prometheus_text()
        assert 'pbt_slo_burn_rate{objective="latency_e2e"}' in prom
        srv.drain(timeout=30)
        tele.close()
        breaches = [r for r in read_events(path, strict=True)
                    if r["event"] == "slo_breach"]
        assert breaches and breaches[0]["objective"] == "latency_e2e"
        assert breaches[0]["burn_rate"] > 1.0

    def test_stage_scoped_slo_requires_tracing(self, trunk):
        """A stage objective with tracing off would never observe —
        the Server rejects the dead config at init."""
        with pytest.raises(ValueError, match="stage-scoped"):
            _server(trunk, None,
                    slos=["kind=latency,stage=execute,threshold_ms=50"])
        with pytest.raises(ValueError, match="stage-scoped"):
            _server(trunk, Telemetry(), trace_sample_rate=None,
                    slos=["kind=latency,stage=execute,threshold_ms=50"])
        # e2e objectives work without tracing: no error.
        _server(trunk, Telemetry(), trace_sample_rate=None,
                slos=["kind=latency,threshold_ms=250"])

    def test_diagnose_serve_section(self, trunk, tmp_path, capsys):
        from proteinbert_tpu.obs.diagnose import (
            render_serve, summarize_serve,
        )

        path = str(tmp_path / "ev.jsonl")
        tele = Telemetry(events_path=path)
        srv = _server(trunk, tele,
                      slos=["kind=latency,threshold_s=1e-9,target=0.99"])
        srv.start()
        for seq in RAGGED:
            srv.embed(seq, timeout=30)
        srv.drain(timeout=30)
        tele.close()
        records = read_events(path, strict=True)
        s = summarize_serve(records)
        assert s["outcome"] == "drained"
        assert s["requests_traced"] == len(RAGGED)
        assert s["e2e"]["n"] == len(RAGGED)
        assert s["e2e"]["p99_s"] >= s["e2e"]["p50_s"] > 0
        attr = s["stage_attribution"]
        assert "execute" in attr and "queue" in attr
        # Wall-clock stages share out to 1.0; pad_wasted overlaps
        # execute, so it is reported beside them, not inside the sum.
        shares = [a["share"] for k, a in attr.items()
                  if a["share"] is not None and "(" not in k]
        assert sum(shares) == pytest.approx(1.0, abs=0.02)
        assert "pad_wasted(of execute)" in attr
        assert len(s["slowest"]) == min(5, len(RAGGED))
        assert s["batches"]["rows"] == len(RAGGED)
        assert s["final_slo"]["latency_e2e"]["burn_rate"] > 1.0
        text = render_serve(s)
        assert "where the time went" in text
        assert "e2e latency" in text


# --------------------------------------------- perf-regression sentinel

@pytest.fixture(scope="module")
def sentinel():
    spec = importlib.util.spec_from_file_location(
        "bench_trajectory", os.path.join(REPO, "tools",
                                         "bench_trajectory.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestSentinel:
    def test_flags_synthetic_20pct_regression(self, sentinel):
        s = sentinel.judge_series([100.0, 101.0, 99.0, 100.0, 80.0])
        assert s["verdict"] == "regression"
        assert "20.0% below" in s["reason"]

    def test_quiet_inside_noise_band(self, sentinel):
        # The band floors at 10% of baseline: a 5% dip is noise.
        s = sentinel.judge_series([100.0, 101.0, 99.0, 100.0, 95.0])
        assert s["verdict"] == "ok"
        # …and a genuinely noisy history widens it via the MAD.
        s = sentinel.judge_series([100.0, 300.0, 50.0, 200.0, 80.0])
        assert s["verdict"] == "ok"

    def test_improvement_and_direction(self, sentinel):
        s = sentinel.judge_series([100.0, 101.0, 99.0, 100.0, 120.0])
        assert s["verdict"] == "improved"
        # Lower-is-better flips the sign (latency-style series).
        s = sentinel.judge_series([100.0, 101.0, 99.0, 100.0, 120.0],
                                  higher_is_better=False)
        assert s["verdict"] == "regression"

    def test_two_points_are_an_anecdote(self, sentinel):
        s = sentinel.judge_series([100.0, 50.0])
        assert s["verdict"] == "insufficient_data"

    def test_zero_false_positives_on_real_history(self, sentinel):
        """The acceptance contract: the checked-in bench trajectory
        must produce no regression verdicts and no input errors."""
        import glob

        verdict = sentinel.build_verdict(
            sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json"))),
            os.path.join(REPO, "bench_events.jsonl"))
        assert verdict["errors"] == []
        assert verdict["overall"] in ("ok", "insufficient_data")
        flagged = [k for k, s in verdict["series"].items()
                   if s["verdict"] == "regression"]
        assert flagged == []
        assert len(verdict["series"]) >= 3  # it actually read history

    def _write_rounds(self, d, values):
        for i, v in enumerate(values, start=1):
            with open(os.path.join(d, f"BENCH_r{i:02d}.json"), "w") as f:
                json.dump({"parsed": {"metric": "residues_per_sec",
                                      "platform": "cpu",
                                      "value": v}}, f)

    def test_main_report_only_vs_fail_on_regression(self, sentinel,
                                                    tmp_path):
        d = str(tmp_path)
        self._write_rounds(d, [100.0, 101.0, 99.0, 100.0, 80.0])
        out = os.path.join(d, "verdict.json")
        assert sentinel.main(["--repo", d, "--output", out]) == 0
        verdict = json.load(open(out))
        assert verdict["overall"] == "regression"
        assert verdict["kind"] == "bench_trajectory_verdict"
        assert verdict["series"]["residues_per_sec/cpu"]["verdict"] \
            == "regression"
        assert sentinel.main(["--repo", d, "--fail-on-regression"]) == 1

    def test_malformed_input_is_the_only_gate(self, sentinel, tmp_path):
        d = str(tmp_path)
        self._write_rounds(d, [100.0, 101.0, 99.0, 100.0])
        with open(os.path.join(d, "BENCH_r06.json"), "w") as f:
            f.write("{not json")
        assert sentinel.main(["--repo", d]) == 2

    def test_verdict_mirrors_onto_event_stream(self, sentinel,
                                               tmp_path):
        d = str(tmp_path)
        self._write_rounds(d, [100.0, 101.0, 99.0, 100.0, 80.0])
        ev_path = os.path.join(d, "mirror.jsonl")
        assert sentinel.main(["--repo", d, "--events-jsonl",
                              ev_path]) == 0
        recs = read_events(ev_path, strict=True)
        assert len(recs) == 1
        assert recs[0]["event"] == "note"
        assert recs[0]["source"] == "bench_trajectory"
        assert recs[0]["overall"] == "regression"
        assert recs[0]["regressions"] == ["residues_per_sec/cpu"]


def test_run_tier1_has_sentinel_stage():
    sh = open(os.path.join(REPO, "tools", "run_tier1.sh")).read()
    assert "bench_trajectory.py" in sh
