"""Multi-tenant head registry + split-apply + shared-trunk serving
(ISSUE 8): registry round-trip/corruption/trunk-compat, split-apply
parity with the monolithic finetune forward, mixed-head micro-batch
parity vs per-head sequential serving, hot add/remove under concurrent
traffic with drain semantics, the downstream eval harness, and the
per-head diagnose section."""

import json
import threading

import jax
import numpy as np
import pytest

from proteinbert_tpu.configs import (
    DataConfig, FinetuneConfig, ModelConfig, OptimizerConfig,
    PretrainConfig, TaskConfig, TrainConfig,
)
from proteinbert_tpu.data.synthetic import make_task_batches
from proteinbert_tpu.data.vocab import ALPHABET
from proteinbert_tpu.heads import (
    CorruptHeadError, HeadRegistry, TrunkMismatchError, UnknownHeadError,
    trunk_fingerprint,
)
from proteinbert_tpu.heads import apply as heads_apply
from proteinbert_tpu.heads.registry import LoadedHead
from proteinbert_tpu.models import finetune as ft_model
from proteinbert_tpu.models import proteinbert
from proteinbert_tpu.serve import TASK_KIND, Server

MODEL = ModelConfig(local_dim=32, global_dim=64, key_dim=16, num_heads=4,
                    num_blocks=2, num_annotations=64, dtype="float32")
CFG = PretrainConfig(
    model=MODEL,
    data=DataConfig(seq_len=64, batch_size=4, buckets=(32, 64)),
    optimizer=OptimizerConfig(warmup_steps=5),
    train=TrainConfig(max_steps=1))

TASKS = [TaskConfig(kind="token_classification", num_outputs=4),
         TaskConfig(kind="sequence_classification", num_outputs=3),
         TaskConfig(kind="sequence_regression", num_outputs=1)]


@pytest.fixture(scope="module")
def params():
    return proteinbert.init(jax.random.PRNGKey(0), MODEL)


@pytest.fixture(scope="module")
def fp(params):
    return trunk_fingerprint(params)


@pytest.fixture(scope="module")
def registry(tmp_path_factory, params, fp):
    """A registry holding one head per task kind; yields
    (HeadRegistry, [head_id], [LoadedHead])."""
    reg = HeadRegistry(str(tmp_path_factory.mktemp("heads")))
    hids = []
    for i, task in enumerate(TASKS):
        hp = ft_model.head_init(jax.random.PRNGKey(i + 1), MODEL, task)
        hids.append(reg.save(jax.tree.map(np.asarray, hp), task, fp,
                             name=f"t{i}"))
    return reg, hids, [reg.load(h, trunk_fp=fp) for h in hids]


def _seqs(n, rng=None, lo=8, hi=28):
    rng = rng or np.random.default_rng(0)
    return ["".join(rng.choice(list(ALPHABET), size=int(L)))
            for L in rng.integers(lo, hi, size=n)]


# ---------------------------------------------------------------- registry

def test_registry_roundtrip_and_verify(registry, fp):
    reg, hids, heads = registry
    assert len(set(hids)) == 3
    metas = reg.list_heads()
    assert {m["head_id"] for m in metas} == set(hids)
    assert all(m["trunk_fingerprint"] == fp for m in metas)
    loaded = reg.load(hids[0])
    assert loaded.task.kind == "token_classification"
    assert loaded.meta["trunk_fingerprint"] == fp
    reg.verify(hids[0])  # digest matches
    # Round-trip preserves every leaf bit-exactly.
    original = ft_model.head_init(jax.random.PRNGKey(1), MODEL, TASKS[0])
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), b), original, loaded.params)
    assert hids[0] in reg and "nope" not in reg


def test_registry_idempotent_resave(registry, fp):
    reg, hids, _ = registry
    hp = ft_model.head_init(jax.random.PRNGKey(1), MODEL, TASKS[0])
    again = reg.save(jax.tree.map(np.asarray, hp), TASKS[0], fp, name="t0")
    assert again == hids[0]  # content-addressed: same content, same id
    reg.verify(again)


def test_registry_corruption_rejected(tmp_path, params, fp):
    reg = HeadRegistry(str(tmp_path))
    hp = ft_model.head_init(jax.random.PRNGKey(9), MODEL, TASKS[1])
    hid = reg.save(jax.tree.map(np.asarray, hp), TASKS[1], fp)
    npz = tmp_path / hid / "head.npz"
    blob = bytearray(npz.read_bytes())
    blob[len(blob) // 2] ^= 0xFF  # flip one byte mid-archive
    npz.write_bytes(bytes(blob))
    with pytest.raises(CorruptHeadError):
        reg.load(hid)
    # meta tampering is caught too
    meta_path = tmp_path / hid / "meta.json"
    meta = json.loads(meta_path.read_text())
    meta["head_digest"] = "0" * 64
    npz.write_bytes(blob)  # even with a "readable" npz
    meta_path.write_text(json.dumps(meta))
    with pytest.raises(CorruptHeadError):
        reg.verify(hid)


def test_registry_unknown_head(registry):
    reg, _, _ = registry
    with pytest.raises(UnknownHeadError):
        reg.load("deadbeef00000000")
    with pytest.raises(UnknownHeadError):
        reg.load("../escape")


def test_trunk_mismatch_is_typed(registry, params):
    reg, hids, _ = registry
    other = proteinbert.init(jax.random.PRNGKey(123), MODEL)
    with pytest.raises(TrunkMismatchError, match="trained against"):
        reg.load(hids[0], trunk_fp=trunk_fingerprint(other))
    # and without a fingerprint the load is allowed (caller's choice)
    assert reg.load(hids[0]).head_id == hids[0]


def test_fingerprint_strips_pretrain_heads(params):
    trunk_only = {k: v for k, v in params.items()
                  if k not in ("local_head", "global_head")}
    assert trunk_fingerprint(params) == trunk_fingerprint(trunk_only)
    # ... and actually depends on the weights
    other = proteinbert.init(jax.random.PRNGKey(5), MODEL)
    assert trunk_fingerprint(params) != trunk_fingerprint(other)


# ------------------------------------------------------------- split-apply

@pytest.mark.parametrize("task", TASKS, ids=lambda t: t.kind)
def test_split_apply_bit_parity_eager(params, task):
    """encode_trunk + apply_head IS the monolithic finetune.apply
    decomposition — eager-vs-eager they must agree bit for bit."""
    head = ft_model.head_init(jax.random.PRNGKey(7), MODEL, task)
    trunk = {k: v for k, v in params.items()
             if k not in ("local_head", "global_head")}
    tokens = jax.numpy.asarray(
        np.array([[2] + [5, 6, 7, 8] * 3 + [3] + [0] * 50,
                  [2, 9, 10, 3] + [0] * 60], np.int32))
    mono = np.asarray(ft_model.apply({"trunk": trunk, "head": head},
                                     tokens, MODEL, task))
    out = proteinbert.encode_trunk(trunk, tokens, MODEL)
    split = np.asarray(ft_model.apply_head(
        head, out["local"], out["global"], out["pad_mask"], task.kind))
    np.testing.assert_array_equal(mono, split)


@pytest.mark.parametrize("task", [TASKS[0], TASKS[2]],
                         ids=lambda t: t.kind)
def test_split_apply_jitted_tolerance(params, task):
    """The serving executables (jitted trunk_batch + head_batch) vs the
    eager monolithic forward: same math, different XLA fusion —
    documented fp32 tolerance (docs/serving.md)."""
    head = LoadedHead("hx", "hx", task,
                      ft_model.head_init(jax.random.PRNGKey(7), MODEL,
                                         task), {})
    trunk = {k: v for k, v in params.items()
             if k not in ("local_head", "global_head")}
    from proteinbert_tpu.data.transforms import tokenize_batch

    tokens = tokenize_batch(_seqs(4), 64)
    mono = np.asarray(ft_model.apply(
        {"trunk": trunk, "head": head.params},
        jax.numpy.asarray(tokens), MODEL, task))
    split = heads_apply.predict_task_rows(params, MODEL, head, tokens)
    np.testing.assert_allclose(split, mono, rtol=0, atol=1e-5)


# ------------------------------------------------------- shared-trunk serve

def test_mixed_batch_parity_vs_sequential(params, registry):
    """One micro-batch mixing all three heads through ONE shared trunk
    executable is bit-identical, row for row, to per-head sequential
    serving at the same compiled shape."""
    reg, hids, heads = registry
    seqs = _seqs(6)
    assign = [hids[i % 3] for i in range(6)]

    mixed = Server(params, CFG, max_batch=6, max_wait_s=60.0,
                   cache_size=0, warm_kinds=(), batch_classes=(6,),
                   registry=reg, heads=hids)
    futs = [mixed.submit(TASK_KIND, s, head_id=h)
            for s, h in zip(seqs, assign)]
    mixed.scheduler.poll()
    mixed_out = [f.result(timeout=30) for f in futs]
    assert mixed.scheduler.batches_total == 1  # ONE batch, 3 heads
    assert mixed.dispatcher.trunk_executable_count == 1
    mixed.abort()

    seq_srv = Server(params, CFG, max_batch=2, max_wait_s=60.0,
                     cache_size=0, warm_kinds=(), batch_classes=(6,),
                     registry=reg, heads=hids, partition_heads=True)
    futs = [seq_srv.submit(TASK_KIND, s, head_id=h)
            for s, h in zip(seqs, assign)]
    for _ in range(3):
        seq_srv.scheduler.poll()
    seq_out = [f.result(timeout=30) for f in futs]
    assert seq_srv.scheduler.batches_total == 3  # per-head batches
    seq_srv.abort()

    for m, s in zip(mixed_out, seq_out):
        np.testing.assert_array_equal(m, s)
    # Output shapes follow each row's task kind.
    assert mixed_out[0].shape == (32, 4)     # token head @ bucket 32
    assert mixed_out[1].shape == (3,)        # sequence classifier
    assert mixed_out[2].shape == (1,)        # regressor


def test_mixed_batch_matches_offline_split_apply(params, registry):
    """Served outputs vs offline predict_task_rows at the same padded
    shape: identical executables → bit-identical."""
    from proteinbert_tpu import inference

    reg, hids, heads = registry
    seqs = _seqs(6, np.random.default_rng(3))
    assign = [hids[i % 3] for i in range(6)]
    srv = Server(params, CFG, max_batch=6, max_wait_s=60.0,
                 cache_size=0, warm_kinds=(), batch_classes=(6,),
                 registry=reg, heads=hids)
    futs = [srv.submit(TASK_KIND, s, head_id=h)
            for s, h in zip(seqs, assign)]
    srv.scheduler.poll()
    tokens = inference._tokenize_masked(seqs, 64)[:, :32]
    by_id = {h.head_id: h for h in heads}
    for i, (f, hid) in enumerate(zip(futs, assign)):
        offline = heads_apply.predict_task_rows(
            params, MODEL, by_id[hid], tokens)[i]
        np.testing.assert_array_equal(f.result(timeout=30), offline)
    srv.abort()


def test_hot_add_never_recompiles_trunk(params, registry):
    """Warmup compiles the shared trunk once per shape and reports
    per-head incremental cost; adding a head to the LIVE server pays
    only the cheap tail — the trunk executable count stays flat."""
    reg, hids, heads = registry
    srv = Server(params, CFG, max_batch=4, max_wait_s=0.002,
                 cache_size=0, warm_kinds=(), batch_classes=(4,),
                 registry=reg, heads=hids[:2])
    srv.start()
    report = srv.dispatcher.warmup_report
    n_trunk = srv.dispatcher.trunk_executable_count
    assert n_trunk == report["trunk_executables"] == 2  # 2 buckets x 1 cls
    assert set(report["heads"]) == set(hids[:2])
    assert all(v >= 0.0 for v in report["heads"].values())

    # Hot add under a live scheduler; serve through it immediately.
    srv.add_head(hids[2])
    out = srv.predict_task(hids[2], "ACDEFGHIKL", timeout=30)
    assert out.shape == (1,)
    assert srv.dispatcher.trunk_executable_count == n_trunk  # FLAT
    assert hids[2] in srv.dispatcher.warmup_report["heads"]
    assert {h["head_id"] for h in srv.list_heads()} == set(hids)
    srv.drain(timeout=30)


def test_hot_remove_drains_under_concurrent_traffic(params, registry):
    """remove_head mid-traffic: already-admitted requests complete
    (they carry their own head reference), new submits get the typed
    UnknownHeadError, and nothing is lost."""
    reg, hids, heads = registry
    srv = Server(params, CFG, max_batch=4, max_wait_s=0.002,
                 cache_size=0, warm_kinds=(), batch_classes=(4,),
                 registry=reg, heads=hids)
    srv.start()
    seqs = _seqs(24, np.random.default_rng(7))
    results, errors = {}, []

    def client(w):
        for i in range(w, 24, 6):
            try:
                results[i] = srv.predict_task(hids[i % 3], seqs[i],
                                              timeout=60)
            except Exception as e:  # noqa: BLE001
                errors.append((i, e))

    threads = [threading.Thread(target=client, args=(w,))
               for w in range(6)]
    for t in threads:
        t.start()
    srv.remove_head(hids[0])  # mid-traffic
    for t in threads:
        t.join(120)
    # In-flight/queued head-0 requests admitted BEFORE the removal must
    # have completed; any head-0 submit AFTER it sees UnknownHeadError.
    assert all(isinstance(e, UnknownHeadError) for _, e in errors)
    assert len(results) + len(errors) == 24  # nothing lost
    assert all(i % 3 == 0 for i, _ in errors)
    with pytest.raises(UnknownHeadError):
        srv.predict_task(hids[0], "ACDEF", timeout=10)
    assert srv.stats()["rejected"]["unknown_head"] >= 1
    # The other tenants are untouched.
    assert srv.predict_task(hids[1], "ACDEFGH", timeout=30).shape == (3,)
    srv.drain(timeout=30)


def test_unknown_head_submit_and_validation(params, registry):
    reg, hids, _ = registry
    srv = Server(params, CFG, max_batch=2, max_wait_s=60.0,
                 cache_size=0, warm_kinds=(), registry=reg,
                 heads=hids[:1])
    with pytest.raises(UnknownHeadError):
        srv.submit(TASK_KIND, "ACDEF", head_id="not-registered")
    with pytest.raises(ValueError, match="head_id is required"):
        srv.submit(TASK_KIND, "ACDEF")
    with pytest.raises(ValueError, match="head_id is required"):
        srv.submit("embed", "ACDEF", head_id=hids[0])
    assert srv.stats()["rejected"]["unknown_head"] == 1
    srv.abort()


def test_server_registry_trunk_check(tmp_path, params):
    """Server head loading enforces trunk compatibility: a head trained
    against a different trunk raises TrunkMismatchError at add time."""
    reg = HeadRegistry(str(tmp_path))
    other = proteinbert.init(jax.random.PRNGKey(99), MODEL)
    hid = reg.save(
        jax.tree.map(np.asarray,
                     ft_model.head_init(jax.random.PRNGKey(1), MODEL,
                                        TASKS[1])),
        TASKS[1], trunk_fingerprint(other))
    with pytest.raises(TrunkMismatchError):
        Server(params, CFG, warm_kinds=(), registry=reg, heads=[hid])


def test_http_predict_task_and_head_lifecycle(params, registry):
    import urllib.error
    import urllib.request

    from proteinbert_tpu.serve.http import make_http_server

    reg, hids, heads = registry
    srv = Server(params, CFG, max_batch=2, max_wait_s=0.002,
                 cache_size=0, warm_kinds=(), batch_classes=(2,),
                 registry=reg, heads=hids[:2])
    srv.start()
    httpd = make_http_server(srv, port=0)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{port}"

    def post(path, payload):
        req = urllib.request.Request(
            base + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    try:
        status, body = post("/v1/predict_task",
                            {"head_id": hids[1], "seq": "ACDEFGHIKL"})
        assert status == 200 and body["head_id"] == hids[1]
        assert len(body["outputs"]) == 3
        # typed 404 for an unknown head — distinct from a route 404
        status, body = post("/v1/predict_task",
                            {"head_id": "nope", "seq": "ACDEF"})
        assert status == 404 and body["type"] == "unknown_head"
        # list / add / remove lifecycle
        with urllib.request.urlopen(base + "/v1/heads", timeout=30) as r:
            listed = json.loads(r.read())["heads"]
        assert {h["head_id"] for h in listed} == set(hids[:2])
        status, body = post("/v1/heads/add", {"head_id": hids[2]})
        assert status == 200 and len(body["heads"]) == 3
        status, body = post("/v1/predict_task",
                            {"head_id": hids[2], "seq": "ACDEFGHIKL"})
        assert status == 200 and len(body["outputs"]) == 1
        status, body = post("/v1/heads/remove", {"head_id": hids[2]})
        assert status == 200
        status, body = post("/v1/predict_task",
                            {"head_id": hids[2], "seq": "ACDEF"})
        assert status == 404 and body["type"] == "unknown_head"
        status, body = post("/v1/heads/remove", {"head_id": "nope"})
        assert status == 404
    finally:
        httpd.shutdown()
        httpd.server_close()
        srv.drain(timeout=30)


# ------------------------------------------------- finetune → register

def test_finetune_registers_head(tmp_path, params, fp):
    from proteinbert_tpu.obs import Telemetry, read_events
    from proteinbert_tpu.train.finetune import finetune

    reg = HeadRegistry(str(tmp_path / "reg"))
    events = str(tmp_path / "events.jsonl")
    cfg = FinetuneConfig(
        model=MODEL,
        task=TaskConfig(kind="sequence_classification", num_outputs=3,
                        epochs=1, freeze_trunk=True),
        data=DataConfig(seq_len=64, batch_size=8),
        optimizer=OptimizerConfig(learning_rate=3e-3, warmup_steps=5,
                                  schedule="warmup_cosine",
                                  total_steps=100),
        train=TrainConfig(seed=0))
    batches = make_task_batches(16, np.random.default_rng(0),
                                "sequence_classification", 3, 64, 8)
    tele = Telemetry(events_path=events)
    # finetune_step donates its state, which aliases pretrained_trunk —
    # hand it a host copy so the module-scoped params stay alive.
    out = finetune(cfg, lambda epoch: iter(batches),
                   eval_batches=lambda: iter(batches),
                   pretrained_trunk=jax.tree.map(np.asarray, params),
                   telemetry=tele, registry=reg, register_name="ft-test")
    tele.close()
    hid = out["head_id"]
    assert hid is not None
    meta = reg.verify(hid)
    assert meta["name"] == "ft-test"
    assert "eval_accuracy" in meta["metrics"]
    # freeze_trunk ⇒ the registered fingerprint IS the pretrain trunk's:
    # the head loads against the resident trunk with the check ON.
    loaded = reg.load(hid, trunk_fp=fp)
    assert loaded.task.num_outputs == 3
    recs = read_events(events, strict=True)
    reg_events = [r for r in recs if r["event"] == "head_registered"]
    assert len(reg_events) == 1
    assert reg_events[0]["head_id"] == hid
    assert reg_events[0]["trunk_fingerprint"] == fp


def test_finetune_unfrozen_trunk_mismatches(tmp_path, params, fp):
    """Without freeze_trunk the head is trained against a DRIFTED
    trunk; loading it against the pretrained trunk must raise the
    typed TrunkMismatchError instead of silently serving garbage."""
    from proteinbert_tpu.train.finetune import finetune

    reg = HeadRegistry(str(tmp_path / "reg"))
    cfg = FinetuneConfig(
        model=MODEL,
        task=TaskConfig(kind="sequence_regression", num_outputs=1,
                        epochs=1, freeze_trunk=False),
        data=DataConfig(seq_len=64, batch_size=8),
        optimizer=OptimizerConfig(learning_rate=3e-3, warmup_steps=2,
                                  schedule="warmup_cosine",
                                  total_steps=100),
        train=TrainConfig(seed=0))
    batches = make_task_batches(16, np.random.default_rng(1),
                                "sequence_regression", 1, 64, 8)
    out = finetune(cfg, lambda epoch: iter(batches),
                   pretrained_trunk=jax.tree.map(np.asarray, params),
                   registry=reg)
    with pytest.raises(TrunkMismatchError):
        reg.load(out["head_id"], trunk_fp=fp)
    # ... but loads fine unchecked (e.g. to serve its own trunk).
    assert reg.load(out["head_id"]).head_id == out["head_id"]


# ------------------------------------------------------- eval harness

def test_eval_metric_primitives():
    from proteinbert_tpu.heads.eval import auc_proxy, spearman

    assert spearman([1, 2, 3, 4], [10, 20, 30, 40]) == pytest.approx(1.0)
    assert spearman([1, 2, 3, 4], [4, 3, 2, 1]) == pytest.approx(-1.0)
    assert spearman([1, 1, 1], [1, 2, 3]) == 0.0  # degenerate → 0, not NaN
    scores = np.array([[0.9, 0.1], [0.8, 0.2], [0.1, 0.9], [0.2, 0.8]])
    labels = np.array([0, 0, 1, 1])
    assert auc_proxy(scores, labels) == pytest.approx(1.0)  # perfect
    assert auc_proxy(scores, 1 - labels) == pytest.approx(0.0)
    assert auc_proxy(scores[:2], np.array([0, 0])) is None  # one class


def test_evaluate_head_and_events(tmp_path, params, registry):
    from proteinbert_tpu.heads.eval import evaluate_heads
    from proteinbert_tpu.obs import Telemetry, read_events

    reg, hids, heads = registry
    events = str(tmp_path / "ev.jsonl")
    tele = Telemetry(events_path=events)
    results = evaluate_heads(
        params, MODEL, heads,
        lambda head: make_task_batches(
            16, np.random.default_rng(2), head.task.kind,
            head.task.num_outputs, 64, 8),
        telemetry=tele)
    tele.close()
    assert set(results) == set(hids)
    for hid, m in results.items():
        assert "score" in m and np.isfinite(m["score"])
    assert "per_residue_accuracy" in results[hids[0]]
    assert "auc_proxy" in results[hids[1]]
    assert "spearman" in results[hids[2]] and "mse" in results[hids[2]]
    recs = read_events(events, strict=True)
    evals = [r for r in recs if r["event"] == "head_eval"]
    assert {r["head_id"] for r in evals} == set(hids)
    assert all("score" in r["metrics"] for r in evals)


# --------------------------------------------------- diagnose per head

def test_diagnose_per_head_breakdown():
    from proteinbert_tpu.obs.diagnose import render_serve, summarize_serve
    from proteinbert_tpu.obs.events import make_record, validate_record

    recs = [make_record("serve_start", seq=0, t=0.0,
                        config={"max_batch": 4}, pid=1)]
    seq = 1
    for hid, lat, outcome in [("aaa", 0.010, "ok"), ("aaa", 0.014, "ok"),
                              ("bbb", 0.200, "ok"),
                              ("bbb", 0.250, "error"),
                              (None, 0.005, "ok")]:
        fields = {"kind": TASK_KIND if hid else "embed",
                  "outcome": outcome, "request_id": f"r{seq}",
                  "stages": {"queue": lat / 2, "execute": lat / 2},
                  "e2e_s": lat}
        if hid:
            fields["head_id"] = hid
        recs.append(make_record("serve_request", seq=seq, t=float(seq),
                                **fields))
        seq += 1
    recs.append(make_record("serve_reject", seq=seq, t=float(seq),
                            reason="unknown_head", head_id="ccc"))
    for r in recs:
        validate_record(r)
    summary = summarize_serve(recs)
    per = summary["per_head"]
    assert set(per) == {"aaa", "bbb"}  # the untagged embed is excluded
    assert per["aaa"]["n"] == 2 and per["aaa"]["errors"] == 0
    assert per["bbb"]["errors"] == 1
    assert per["bbb"]["p99_s"] >= per["bbb"]["p50_s"] >= 0.2
    assert summary["unknown_head_rejects"] == {"ccc": 1}
    text = render_serve(summary)
    assert "head aaa" in text and "head bbb" in text
    assert "unknown-head rejects: ccc x1" in text
