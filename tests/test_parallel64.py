"""64-virtual-device pod-shape tier (VERDICT round-6 item 5).

Extent-8 data collectives (and extent-16, and data*fsdp = 32 joint
replica axes) have never been constructed by any lower tier — the
in-suite mesh is 8 devices, the 16-device tier caps every axis at 4.
These tests spawn `tests/multidevice64_child.py` in fresh processes
with 64 virtual CPU devices at realistic v5e-64 shapes
(data=8·fsdp=4·model=2; data=16·seq=4 with bucketed lockstep
iterators) on the tiny model, asserting loss parity vs single-device —
and this tier is what validates the ZeRO-1 zero-update path at scale.
A compile-grep keeps the partitioner free of pathological reshards
(shardy arm only, like the 8/16-device greps).

Cost control: 64 virtual devices on a laptop-class CI host is minutes
of XLA per child, so the tier is DOUBLE-GATED — marked `slow` AND
`tier64` (tier-1's `-m 'not slow'` never collects it), and skipped
unless PBT_RUN_TIER64=1 (so even a bare `pytest -m slow` run opts in
explicitly; `tools/run_tier1.sh --pod64` sets it). On 1-core hosts the
64-way compile is pathological and the tier self-skips.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = [
    pytest.mark.slow,
    pytest.mark.tier64,
    pytest.mark.skipif(
        not os.environ.get("PBT_RUN_TIER64"),
        reason="64-device tier is opt-in: set PBT_RUN_TIER64=1 "
               "(or run tools/run_tier1.sh --pod64)"),
    pytest.mark.skipif(
        (os.cpu_count() or 1) < 2,
        reason="64 virtual devices on a 1-core host is pathological"),
]


def _child_env():
    """The child forces 64 devices via the config API; scrub the
    conftest's 8-device XLA flag so the two mechanisms can't fight."""
    from proteinbert_tpu.utils.compat import scrub_device_count_flag

    env = dict(os.environ)
    env["XLA_FLAGS"] = scrub_device_count_flag(env.get("XLA_FLAGS", ""))
    return env


def _run(args, timeout=1200):
    out = subprocess.run(
        [sys.executable, *args], env=_child_env(), cwd=REPO,
        capture_output=True, text=True, timeout=timeout)
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-3000:])
    return out.stdout


@pytest.mark.parametrize("scenario", ["dp8-fsdp4-model2",
                                      "zero-dp8-fsdp4-model2",
                                      "dp16-sp4-bucketed"])
def test_sixty_four_device_parity(scenario):
    stdout = _run([os.path.join(REPO, "tests", "multidevice64_child.py"),
                   scenario])
    rec = json.loads(stdout.strip().splitlines()[-1])
    assert rec["ok"] and rec["scenario"] == scenario
    if scenario == "dp16-sp4-bucketed":
        assert {r["L"] for r in rec["buckets"]} == {32, 128}
        assert rec["mesh"]["data"] == 16 and rec["mesh"]["seq"] == 4
    else:
        assert rec["mesh"] == {"data": 8, "fsdp": 4, "model": 2, "seq": 1}
        assert rec["max_param_err"] < 2e-5
    if scenario == "zero-dp8-fsdp4-model2":
        ob = rec["opt_state_bytes"]
        assert ob["zero"] * 4 <= ob["replicated"], ob


def test_zero_compile_has_no_involuntary_remat_warning_at_64():
    """The pathological-reshard grep at pod shape: the zero-update step
    compiled at data=8·fsdp=4·model=2 must not hit the partitioner's
    replicate-and-repartition fallback. Shardy arm only (on GSPMD-
    default jax the warning class is known-noisy and the 8/16-device
    positive controls cover the marker text)."""
    import jax

    if not jax.config.jax_use_shardy_partitioner:
        pytest.skip("default partitioner is GSPMD (jax 0.4.x) — the "
                    "warning-free property under test belongs to shardy")
    code = """
import jax
from proteinbert_tpu.utils.compat import request_cpu_devices
request_cpu_devices(64)
jax.config.update("jax_enable_compilation_cache", False)
import numpy as np
from proteinbert_tpu.configs import (DataConfig, MeshConfig, ModelConfig,
    OptimizerConfig, ParallelConfig, PretrainConfig, TrainConfig)
from proteinbert_tpu.parallel import batch_sharding, make_mesh, make_zero_train_step
from proteinbert_tpu.parallel.sharding import state_sharding
from proteinbert_tpu.train import create_train_state

mesh_cfg = MeshConfig(data=8, fsdp=4, model=2)
cfg = PretrainConfig(
    model=ModelConfig(local_dim=32, global_dim=64, key_dim=16, num_heads=4,
                      num_blocks=2, num_annotations=128, dtype="bfloat16",
                      remat=True, remat_policy="convs"),
    data=DataConfig(seq_len=64, batch_size=64),
    optimizer=OptimizerConfig(warmup_steps=10),
    mesh=mesh_cfg, parallel=ParallelConfig(zero_update=True),
    train=TrainConfig(max_steps=1))
mesh = make_mesh(mesh_cfg, jax.devices()[:64])
abstract = jax.eval_shape(lambda: create_train_state(jax.random.PRNGKey(0), cfg))
sh = state_sharding(mesh, abstract, zero_update=True)
bsh = batch_sharding(mesh)
bat = {"tokens": jax.ShapeDtypeStruct((64, 64), np.int32, sharding=bsh["tokens"]),
       "annotations": jax.ShapeDtypeStruct((64, 128), np.float32,
                                           sharding=bsh["annotations"])}
st = jax.tree.map(lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
                  abstract, sh)
make_zero_train_step(mesh, cfg).lower(st, bat).compile()
print("COMPILED-OK")
"""
    out = subprocess.run([sys.executable, "-c", code], env=_child_env(),
                         cwd=REPO, capture_output=True, text=True,
                         timeout=1200)
    assert "COMPILED-OK" in out.stdout, out.stderr[-3000:]
    assert "Involuntary full rematerialization" not in out.stderr, \
        out.stderr[-3000:]
