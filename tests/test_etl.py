"""End-to-end + unit tests for the offline ETL (reference C1-C4 parity).

Fixtures are synthetic miniatures of the real inputs: an OBO-style GO
file (the CAFA go.txt format, reference uniref_dataset.py:158-198), a
UniRef90-shaped XML (reference uniref_dataset.py:76-98 element layout),
and a FASTA of representative sequences keyed UniRef90_<accession>.
"""

import gzip
import json
import sqlite3

import numpy as np
import pytest

from proteinbert_tpu.etl import (
    FastaReader,
    UnirefToSqliteParser,
    create_h5_dataset,
    iter_fasta,
    load_seqs_and_annotations,
    merge_shard_dbs,
    parse_obo,
    read_aggregates,
    save_meta_csv,
)

# DAG: root → a → b, root → c; d is an orphan root.
GO_TXT = """\
[Term]
id: GO:0000001
name: root
namespace: molecular_function

[Term]
id: GO:0000002
name: a
namespace: molecular_function
is_a: GO:0000001 ! root

[Term]
id: GO:0000003
name: b
namespace: molecular_function
is_a: GO:0000002 ! a

[Term]
id: GO:0000004
name: c
namespace: molecular_function
is_a: GO:0000001 ! root

[Term]
id: GO:0000005
name: d
namespace: biological_process
"""

_XML_ENTRY = """\
  <entry id="UniRef90_{acc}" updated="2020-01-01">
    <name>Cluster: protein {acc}</name>
    <representativeMember>
      <dbReference type="UniProtKB ID" id="{acc}_HUMAN">
        <property type="NCBI taxonomy" value="{tax}"/>
{props}
      </dbReference>
      <sequence length="{length}">IGNORED</sequence>
    </representativeMember>
  </entry>
"""


def _make_xml(records):
    """records: list of (accession, tax, go_ids_by_category)."""
    entries = []
    for acc, tax, gos in records:
        props = "\n".join(
            f'        <property type="{cat}" value="{gid}"/>'
            for cat, gids in gos.items() for gid in gids
        )
        entries.append(_XML_ENTRY.format(acc=acc, tax=tax, props=props, length=10))
    return (
        '<?xml version="1.0" encoding="ISO-8859-1"?>\n'
        '<UniRef90 xmlns="http://uniprot.org/uniref" releaseDate="2020-01-01">\n'
        + "".join(entries)
        + "</UniRef90>\n"
    )


RECORDS = [
    ("P00001", 9606, {"GO Molecular Function": ["GO:0000003"]}),          # completes to {1,2,3}
    ("P00002", 10090, {"GO Biological Process": ["GO:0000004"]}),          # completes to {1,4}
    ("P00003", 9606, {"GO Molecular Function": ["GO:0000002", "GO:9999999"]}),  # unknown id dropped
    ("P00004", 562, {}),                                                   # no annotations
]

SEQS = {
    "UniRef90_P00001": "MKVLAAGIAKWT",
    "UniRef90_P00002": "ACDEFGHIKLMNPQRSTVWY",
    "UniRef90_P00003": "MSTNPKPQRKTKRNTNRRPQDVK",
    # P00004 intentionally missing from FASTA → join failure path
}


@pytest.fixture(scope="module")
def etl_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("etl")
    go_path = d / "go.txt"
    go_path.write_text(GO_TXT)
    xml_path = d / "uniref90.xml.gz"
    with gzip.open(xml_path, "wt") as f:
        f.write(_make_xml(RECORDS))
    fasta_path = d / "uniref90.fasta"

    def wrap(s, w=7):
        return "\n".join(s[i : i + w] for i in range(0, len(s), w))

    fasta_path.write_text(
        "".join(f">{k} some description\n{wrap(v)}\n" for k, v in SEQS.items())
    )
    return {"dir": d, "go": str(go_path), "xml": str(xml_path),
            "fasta": str(fasta_path)}


# ---------------------------------------------------------------- ontology

def test_obo_parse_and_closure(etl_files):
    onto = parse_obo(etl_files["go"])
    assert len(onto) == 5
    # ancestors include self (reference closure convention).
    assert onto.ancestors["GO:0000003"] == {"GO:0000001", "GO:0000002", "GO:0000003"}
    assert onto.ancestors["GO:0000001"] == {"GO:0000001"}
    assert onto.offspring["GO:0000001"] == {
        "GO:0000001", "GO:0000002", "GO:0000003", "GO:0000004"}
    assert set(onto.roots()) == {"GO:0000001", "GO:0000005"}


def test_complete_fixes_reference_bug(etl_files):
    # The reference computes the completion then stores raw indices
    # (SURVEY ledger #6); ours must store the completed set.
    onto = parse_obo(etl_files["go"])
    assert onto.complete_indices(["GO:0000003"]) == [0, 1, 2]
    assert onto.complete_indices(["GO:9999999"]) == []  # unknown → dropped


# ------------------------------------------------------------------- fasta

def test_fasta_reader_roundtrip(etl_files):
    with FastaReader(etl_files["fasta"]) as r:
        assert len(r) == len(SEQS)
        for name, seq in SEQS.items():
            assert r.fetch(name) == seq
            assert r.length(name) == len(seq)
        assert "UniRef90_P00004" not in r
    assert dict(iter_fasta(etl_files["fasta"])) == SEQS


def test_fasta_rejects_non_uniform_wrapping(tmp_path):
    # Offset arithmetic only holds for uniform wrapping; silent
    # truncation is worse than an error (pyfaidx also rejects this).
    p = tmp_path / "bad.fasta"
    p.write_text(">A\nABCDEFGHIJKLMNOPQRST\nUVWXY\nABCDEFGHIJ\n")
    with pytest.raises(ValueError, match="non-uniform"):
        FastaReader(str(p))
    # A short FINAL line is legal.
    q = tmp_path / "ok.fasta"
    q.write_text(">A\nABCDEFGHIJ\nKLM\n>B\nNOP\n")
    with FastaReader(str(q)) as r:
        assert r.fetch("A") == "ABCDEFGHIJKLM"
        assert r.fetch("B") == "NOP"
    # A blank INTERIOR line is a width-0 line → also non-uniform.
    b = tmp_path / "blank.fasta"
    b.write_text(">A\nABCDE\n\nFGHIJ\n")
    with pytest.raises(ValueError, match="non-uniform"):
        FastaReader(str(b))


def test_fasta_crlf(tmp_path):
    p = tmp_path / "crlf.fasta"
    p.write_bytes(b">A desc\r\nABCDE\r\nFGH\r\n")
    assert dict(iter_fasta(str(p))) == {"A": "ABCDEFGH"}
    with FastaReader(str(p)) as r:
        assert r.fetch("A") == "ABCDEFGH"


def test_h5_builder_errors_when_no_common_annotations(built_db, tmp_path):
    with pytest.raises(ValueError, match="min_records"):
        create_h5_dataset(
            built_db["db"], built_db["fasta"], built_db["meta"],
            str(tmp_path / "x.h5"), min_records_to_keep_annotation=100,
            verbose=False)


# ------------------------------------------------------------ xml → sqlite

def _parse_to_sqlite(etl_files, db_path, **kw):
    onto = parse_obo(etl_files["go"])
    parser = UnirefToSqliteParser(etl_files["xml"], onto, str(db_path),
                                  verbose=False, **kw)
    parser.parse()
    return onto, parser


def test_uniref_parser(etl_files, tmp_path):
    onto, parser = _parse_to_sqlite(etl_files, tmp_path / "ann.db")
    conn = sqlite3.connect(tmp_path / "ann.db")
    rows = conn.execute(
        "SELECT uniprot_name, tax_id, complete_go_annotation_indices, "
        "n_complete_go_annotations FROM protein_annotations ORDER BY entry_index"
    ).fetchall()
    conn.close()
    assert [r[0] for r in rows] == [
        "P00001_HUMAN", "P00002_HUMAN", "P00003_HUMAN", "P00004_HUMAN"]
    assert rows[0][1] == 9606
    assert json.loads(rows[0][2]) == [0, 1, 2]     # ancestor-completed
    assert json.loads(rows[1][2]) == [0, 3]
    assert json.loads(rows[2][2]) == [0, 1]        # unknown GO id dropped
    assert rows[3][3] == 0
    assert parser.n_records_with_any_go == 3
    assert parser.unrecognized_go == {"GO:9999999": 1}
    # per-term record counts (completed): root appears in 3 records.
    assert parser.go_record_counts["GO:0000001"] == 3
    assert parser.go_record_counts["GO:0000002"] == 2


def test_uniref_parser_sharding(etl_files, tmp_path):
    onto = parse_obo(etl_files["go"])
    paths = [str(tmp_path / f"s{k}.db") for k in range(2)]
    for k in range(2):
        UnirefToSqliteParser(
            etl_files["xml"], onto, paths[k], verbose=False,
            shard_index=k, num_shards=2,
        ).parse()
    merged = tmp_path / "merged.db"
    assert merge_shard_dbs(paths, str(merged)) == len(RECORDS)
    conn = sqlite3.connect(merged)
    n = conn.execute("SELECT COUNT(*) FROM protein_annotations").fetchone()[0]
    names = {r[0] for r in conn.execute(
        "SELECT uniprot_name FROM protein_annotations")}
    conn.close()
    assert n == len(RECORDS)
    assert names == {f"P0000{i}_HUMAN" for i in range(1, 5)}
    # Aggregates must be SUMMED across shards (not one shard's view) so
    # the h5 builder's >=min_records gate sees corpus-wide counts.
    counts, n_any = read_aggregates(str(merged))
    assert n_any == 3
    assert counts["GO:0000001"] == 3
    assert counts["GO:0000002"] == 2
    # ...and match an unsharded parse exactly.
    _, ref_parser = _parse_to_sqlite(etl_files, tmp_path / "ref.db")
    assert counts == ref_parser.go_record_counts


# ----------------------------------------------------- hostile inputs
# Real UniRef dumps contain malformed entries, and downloads get cut
# mid-gzip-member. The ETL contract (VERDICT round-6 item 7): counted
# and skipped, never a crash.

_HOSTILE_ENTRIES = """\
  <entry id="UniRef90_BAD1" updated="2020-01-01">
    <name>no representativeMember at all</name>
  </entry>
  <entry id="UniRef90_BAD2" updated="2020-01-01">
    <representativeMember>
      <sequence length="5">IGNOR</sequence>
    </representativeMember>
  </entry>
  <entry id="UniRef90_BAD3" updated="2020-01-01">
    <representativeMember>
      <dbReference type="UniProtKB ID" id="BAD3_HUMAN">
        <property type="GO Molecular Function" value="GO:0000002"/>
      </dbReference>
    </representativeMember>
  </entry>
  <entry id="UniRef90_BAD4" updated="2020-01-01">
    <representativeMember>
      <dbReference type="UniProtKB ID" id="">
        <property type="NCBI taxonomy" value="9606"/>
      </dbReference>
    </representativeMember>
  </entry>
"""


def _hostile_xml(etl_files):
    good = _make_xml(RECORDS[:1])
    # Splice the malformed entries (plus an unknown GO-looking category
    # on the good record's sibling) before the closing tag.
    weird = _XML_ENTRY.format(
        acc="P00009", tax=9606,
        props='        <property type="GO Imaginary Aspect" '
              'value="GO:0000004"/>',
        length=10)
    return good.replace("</UniRef90>",
                        _HOSTILE_ENTRIES + weird + "</UniRef90>")


def test_uniref_parser_skips_and_counts_malformed_entries(etl_files,
                                                          tmp_path):
    xml_path = tmp_path / "hostile.xml.gz"
    with gzip.open(xml_path, "wt") as f:
        f.write(_hostile_xml(etl_files))
    onto = parse_obo(etl_files["go"])
    parser = UnirefToSqliteParser(str(xml_path), onto,
                                  str(tmp_path / "hostile.db"),
                                  verbose=False)
    parser.parse()  # must not raise
    conn = sqlite3.connect(tmp_path / "hostile.db")
    names = [r[0] for r in conn.execute(
        "SELECT uniprot_name FROM protein_annotations")]
    stats = dict(conn.execute("SELECT key, value FROM etl_stats"))
    conn.close()
    # Only the two well-formed records survive; each fault is counted.
    assert names == ["P00001_HUMAN", "P00009_HUMAN"]
    assert parser.skipped_entries == {
        "no_representative_member": 1,   # BAD1
        "no_db_reference": 1,            # BAD2
        "no_tax_id": 1,                  # BAD3
        "no_uniprot_id": 1,              # BAD4
    }
    # ...persisted next to the rows so sharded runs merge them.
    assert stats["skipped_no_tax_id"] == 1
    assert stats["skipped_no_uniprot_id"] == 1
    # The unknown GO-looking category is counted, not folded in.
    assert parser.unrecognized_go_categories == {"GO Imaginary Aspect": 1}
    assert parser.stream_error is None


def test_uniref_parser_survives_truncated_gzip(etl_files, tmp_path):
    """A download cut mid-member: every entry parsed before the cut is
    kept, the fault is recorded, and parse() returns instead of
    blowing up hours into a corpus-scale run."""
    whole = tmp_path / "whole.xml.gz"
    with gzip.open(whole, "wt") as f:
        f.write(_make_xml(RECORDS))
    data = whole.read_bytes()
    cut = tmp_path / "cut.xml.gz"
    cut.write_bytes(data[: int(len(data) * 0.6)])

    onto = parse_obo(etl_files["go"])
    parser = UnirefToSqliteParser(str(cut), onto, str(tmp_path / "cut.db"),
                                  verbose=False)
    parser.parse()  # must not raise
    assert parser.stream_error is not None
    conn = sqlite3.connect(tmp_path / "cut.db")
    n = conn.execute(
        "SELECT COUNT(*) FROM protein_annotations").fetchone()[0]
    stats = dict(conn.execute("SELECT key, value FROM etl_stats"))
    conn.close()
    assert n < len(RECORDS)  # stream really was cut short
    assert stats["n_stream_errors"] == 1
    # Aggregates reflect exactly the rows kept.
    assert stats["n_entries"] == parser.n_entries == n


def test_join_counts_unjoinable_ids(built_db):
    """P00004 has an annotation row but no FASTA record: the join must
    skip it and report it via the stats out-param, not crash or
    silently shrink."""
    stats = {}
    rows = list(load_seqs_and_annotations(
        built_db["db"], built_db["fasta"], shuffle=False, verbose=False,
        stats=stats))
    assert stats == {"n_yielded": 3, "n_unjoinable": 1}
    assert len(rows) == 3


# ------------------------------------------------------- join + h5 builder

@pytest.fixture(scope="module")
def built_db(etl_files):
    d = etl_files["dir"]
    onto, parser = _parse_to_sqlite(etl_files, d / "full.db")
    meta_csv = d / "go_meta.csv"
    save_meta_csv(onto, str(meta_csv), counts=parser.go_record_counts,
                  total_records=parser.n_records_with_any_go)
    return {"db": str(d / "full.db"), "meta": str(meta_csv), **etl_files}


def test_join(built_db):
    rows = list(load_seqs_and_annotations(
        built_db["db"], built_db["fasta"], shuffle=False, verbose=False))
    # P00004 has no FASTA record → dropped, counted as failure.
    assert [r[0] for r in rows] == ["P00001_HUMAN", "P00002_HUMAN", "P00003_HUMAN"]
    assert rows[0][1] == SEQS["UniRef90_P00001"]
    assert rows[0][2] == [0, 1, 2]


def test_h5_builder_and_reader_roundtrip(built_db, tmp_path):
    import h5py

    out = tmp_path / "data.h5"
    # min_records 2: term counts are root=3, a=2, b=1, c=1, d=0 → keep root+a.
    n = create_h5_dataset(
        built_db["db"], built_db["fasta"], built_db["meta"], str(out),
        shuffle=True, min_records_to_keep_annotation=2, verbose=False)
    assert n == 3
    with h5py.File(out, "r") as f:
        kept = [s.decode() for s in f["included_annotations"][:]]
        assert kept == ["GO:0000001", "GO:0000002"]
        ids = [s.decode() for s in f["uniprot_ids"][:]]
        seqs = [s.decode() for s in f["seqs"][:]]
        masks = f["annotation_masks"][:]
        lengths = f["seq_lengths"][:]
    assert sorted(ids) == ["P00001_HUMAN", "P00002_HUMAN", "P00003_HUMAN"]
    by_id = {i: (s, m, l) for i, s, m, l in zip(ids, seqs, masks, lengths)}
    assert by_id["P00001_HUMAN"][0] == SEQS["UniRef90_P00001"]
    assert by_id["P00001_HUMAN"][2] == len(SEQS["UniRef90_P00001"])
    # P00001 completes to {root,a,b} → mask [1,1]; P00002 to {root,c} → [1,0].
    np.testing.assert_array_equal(by_id["P00001_HUMAN"][1], [True, True])
    np.testing.assert_array_equal(by_id["P00002_HUMAN"][1], [True, False])

    # The training-feed reader serves this file directly.
    from proteinbert_tpu.data.dataset import HDF5PretrainingDataset

    ds = HDF5PretrainingDataset(str(out), seq_len=32)
    assert len(ds) == 3
    row = ds[ids.index("P00001_HUMAN")]
    assert row["tokens"].shape == (32,)
    np.testing.assert_array_equal(
        row["annotations"], by_id["P00001_HUMAN"][1].astype(np.float32))
    ds.close()
