"""CLI tests (reference C15/C16 parity — and unlike the reference's
create_uniref_db.py, these parsers must actually construct)."""

import gzip
import json
import os

import numpy as np
import pytest

from proteinbert_tpu.cli.main import apply_overrides, build_parser, main
from proteinbert_tpu.configs import get_preset

from tests.test_etl import GO_TXT, RECORDS, SEQS, _make_xml


@pytest.fixture(scope="module")
def etl_inputs(tmp_path_factory):
    d = tmp_path_factory.mktemp("cli")
    (d / "go.txt").write_text(GO_TXT)
    with gzip.open(d / "uniref.xml.gz", "wt") as f:
        f.write(_make_xml(RECORDS))
    (d / "uniref.fasta").write_text(
        "".join(f">{k} desc\n{v}\n" for k, v in SEQS.items()))
    return d


def test_parser_constructs():
    p = build_parser()
    for cmd in ("create-uniref-db", "merge-uniref-dbs", "create-h5",
                "pretrain", "smoke"):
        assert cmd in p.format_help()


def test_apply_overrides():
    cfg = get_preset("tiny")
    cfg2 = apply_overrides(cfg, ["model.local_dim=64", "train.max_steps=7",
                                 "model.remat=true"])
    assert cfg2.model.local_dim == 64
    assert cfg2.train.max_steps == 7
    assert cfg2.model.remat is True
    assert cfg.model.local_dim == 32  # original untouched (frozen tree)
    with pytest.raises(SystemExit):
        apply_overrides(cfg, ["model.nope=1"])


def test_etl_commands_end_to_end(etl_inputs, tmp_path):
    db = tmp_path / "ann.db"
    csv = tmp_path / "meta.csv"
    h5 = tmp_path / "data.h5"
    assert main([
        "create-uniref-db",
        "--uniref-xml", str(etl_inputs / "uniref.xml.gz"),
        "--go-meta", str(etl_inputs / "go.txt"),
        "--output-db", str(db),
        "--go-meta-csv", str(csv),
    ]) == 0
    assert db.exists() and csv.exists()
    assert main([
        "create-h5",
        "--db", str(db),
        "--fasta", str(etl_inputs / "uniref.fasta"),
        "--go-meta-csv", str(csv),
        "--output", str(h5),
        "--min-records", "2",
    ]) == 0
    assert h5.exists()

    import h5py

    with h5py.File(h5, "r") as f:
        assert f["seqs"].shape[0] == 3  # one record has no FASTA entry


def test_sharded_etl_commands(etl_inputs, tmp_path):
    merged = tmp_path / "merged.db"
    csv = tmp_path / "meta.csv"
    for k in range(2):
        assert main([
            "create-uniref-db",
            "--uniref-xml", str(etl_inputs / "uniref.xml.gz"),
            "--go-meta", str(etl_inputs / "go.txt"),
            "--output-db", str(merged),
            "--task-index", str(k), "--task-count", "2",
        ]) == 0
    assert main([
        "merge-uniref-dbs",
        "--output-db", str(merged), "--num-shards", "2",
        "--go-meta", str(etl_inputs / "go.txt"),
        "--go-meta-csv", str(csv),
    ]) == 0
    from proteinbert_tpu.etl import read_aggregates

    counts, n_any = read_aggregates(str(merged))
    assert n_any == 3 and counts["GO:0000001"] == 3


def test_pretrain_cli_on_h5(etl_inputs, tmp_path):
    """Full user journey: ETL → pretrain CLI on the built file."""
    db, csv, h5 = tmp_path / "a.db", tmp_path / "m.csv", tmp_path / "d.h5"
    main(["create-uniref-db", "--uniref-xml", str(etl_inputs / "uniref.xml.gz"),
          "--go-meta", str(etl_inputs / "go.txt"), "--output-db", str(db),
          "--go-meta-csv", str(csv)])
    main(["create-h5", "--db", str(db), "--fasta", str(etl_inputs / "uniref.fasta"),
          "--go-meta-csv", str(csv), "--output", str(h5), "--min-records", "2"])
    hist = tmp_path / "hist.json"
    assert main([
        "pretrain", "--preset", "tiny", "--data", str(h5),
        "--max-steps", "4", "--checkpoint-dir", str(tmp_path / "ck"),
        "--history-json", str(hist),
        "--set", "data.batch_size=2", "--set", "train.log_every=2",
        "--set", "checkpoint.every_steps=0", "--set", "optimizer.warmup_steps=2",
        "--set", "model.num_blocks=1", "--set", "model.local_dim=8",
        "--set", "model.global_dim=16", "--set", "model.key_dim=4",
        "--set", "data.seq_len=32",
    ]) == 0
    h = json.loads(hist.read_text())
    assert len(h) == 2 and np.isfinite(h[-1]["loss"])


TINY_SETS = [
    "--set", "data.batch_size=4", "--set", "model.num_blocks=1",
    "--set", "model.local_dim=8", "--set", "model.global_dim=16",
    "--set", "model.key_dim=4", "--set", "model.num_annotations=32",
    "--set", "data.seq_len=32",
]


def test_finetune_cli_from_pretrained(tmp_path):
    """pretrain → checkpoint → finetune --pretrained loads the trunk."""
    ck = tmp_path / "ck"
    assert main([
        "pretrain", "--preset", "tiny", "--max-steps", "2",
        "--checkpoint-dir", str(ck), *TINY_SETS,
        "--set", "train.log_every=0", "--set", "checkpoint.every_steps=2",
        "--set", "checkpoint.async_save=false",
        "--set", "optimizer.warmup_steps=2",
    ]) == 0
    hist = tmp_path / "ft.json"
    ft_ck = tmp_path / "ft_ck"
    assert main([
        "finetune", "--preset", "tiny", "--task", "sequence_classification",
        "--num-outputs", "3", "--epochs", "1",
        "--pretrained", str(ck), "--history-json", str(hist),
        "--checkpoint-dir", str(ft_ck), *TINY_SETS,
    ]) == 0
    h = json.loads(hist.read_text())
    assert len(h) == 1 and np.isfinite(h[0]["train_loss"])
    assert "eval_accuracy" in h[0]
    # The fine-tuned weights were actually persisted (per-epoch ckpt).
    assert any(ft_ck.iterdir())


def test_finetune_cli_fresh_trunk(tmp_path):
    assert main([
        "finetune", "--preset", "tiny", "--task", "sequence_regression",
        "--num-outputs", "1", "--epochs", "1", "--freeze-trunk",
        "--checkpoint-dir", str(tmp_path / "ck"), *TINY_SETS,
    ]) == 0


def test_finetune_cli_tsv_data(tmp_path):
    """Real-data path: TSV → load → train → eval (secondary-structure
    shape: per-residue labels as a digit string)."""
    rng = np.random.default_rng(3)
    lines = []
    for _ in range(24):
        L = int(rng.integers(10, 30))
        seq = "".join(rng.choice(list("ACDEFGHIKLMNPQRSTVWY"), size=L))
        labels = "".join(str((ord(c) + 1) % 3) for c in seq)
        lines.append(f"{seq}\t{labels}")
    tsv = tmp_path / "ss.tsv"
    tsv.write_text("# seq<TAB>labels\n" + "\n".join(lines) + "\n")
    hist = tmp_path / "h.json"
    assert main([
        "finetune", "--preset", "tiny", "--task", "token_classification",
        "--num-outputs", "3", "--epochs", "3",
        "--data", str(tsv), "--eval-data", str(tsv),
        "--checkpoint-dir", str(tmp_path / "ck"),
        "--history-json", str(hist), *TINY_SETS,
        "--set", "optimizer.warmup_steps=2",
        "--set", "optimizer.learning_rate=3e-3",
    ]) == 0
    h = json.loads(hist.read_text())
    assert h[-1]["train_loss"] < h[0]["train_loss"]  # label fn is learnable


def test_merge_requires_shard_spec(tmp_path):
    with pytest.raises(SystemExit, match="--shards or --num-shards"):
        main(["merge-uniref-dbs", "--output-db", str(tmp_path / "m.db")])


def test_smoke_honors_preset_flag():
    # smoke defaults to tiny but must not silently override a user choice.
    p = build_parser()
    assert p.parse_args(["smoke"]).preset == "tiny"
    assert p.parse_args(["smoke", "--preset", "base"]).preset == "base"


def test_platform_flag(tmp_path):
    """--platform forces the backend before first device use — the only
    way to steer the CLI on images whose sitecustomize pins JAX_PLATFORMS
    (a dead TPU tunnel otherwise hangs every command at device init)."""
    import subprocess
    import sys

    p = build_parser()
    assert p.parse_args(["--platform", "cpu", "smoke"]).platform == "cpu"
    assert p.parse_args(["smoke"]).platform is None
    # PB_PLATFORM env (the examples' knob) is the flag's default, so any
    # CLI invocation — not just full_workflow.sh — honors it.
    import unittest.mock as mock

    with mock.patch.dict("os.environ", {"PB_PLATFORM": "cpu"}):
        assert build_parser().parse_args(["smoke"]).platform == "cpu"
    with mock.patch.dict("os.environ", {"PB_PLATFORM": ""}):
        assert build_parser().parse_args(["smoke"]).platform is None
    # End-to-end in a SUBPROCESS: forcing the platform initializes and
    # caches that backend set process-wide (restoring the config value
    # would not undo it), so the mutation must not happen in the pytest
    # process.
    code = (
        "import sys; from proteinbert_tpu.cli.main import main; "
        "sys.exit(main(["
        "'--platform', 'cpu', 'smoke', '--max-steps', '2', "
        "'--set', 'data.batch_size=4', '--set', 'train.log_every=1', "
        "'--set', 'model.num_blocks=1', '--set', 'model.local_dim=8', "
        "'--set', 'model.global_dim=16', '--set', 'model.key_dim=4', "
        "'--set', 'model.num_annotations=32', '--set', 'data.seq_len=32', "
        "'--set', 'optimizer.warmup_steps=2']))"
    )
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]


def test_smoke_cli(tmp_path):
    assert main([
        "smoke", "--max-steps", "4",
        "--set", "data.batch_size=4", "--set", "train.log_every=2",
        "--set", "model.num_blocks=1", "--set", "model.local_dim=8",
        "--set", "model.global_dim=16", "--set", "model.key_dim=4",
        "--set", "model.num_annotations=32", "--set", "data.seq_len=32",
        "--set", "checkpoint.every_steps=0",
    ]) == 0


def test_metrics_jsonl_flag(tmp_path):
    mj = tmp_path / "metrics.jsonl"
    assert main([
        "smoke", "--max-steps", "4", "--metrics-jsonl", str(mj),
        "--checkpoint-dir", str(tmp_path / "ck"), *TINY_SETS,
        "--set", "train.log_every=2", "--set", "checkpoint.every_steps=0",
    ]) == 0
    lines = [json.loads(x) for x in mj.read_text().splitlines()]
    assert [r["step"] for r in lines] == [2, 4]
    assert all(np.isfinite(r["loss"]) for r in lines)


def test_bucketed_pretrain_on_h5_with_resume(etl_inputs, tmp_path):
    """ETL → bucketed pretrain on the real HDF5 file → preempt-free
    checkpoint resume continues the bucketed stream (index-only skip)."""
    db, csv, h5 = tmp_path / "a.db", tmp_path / "m.csv", tmp_path / "d.h5"
    main(["create-uniref-db", "--uniref-xml", str(etl_inputs / "uniref.xml.gz"),
          "--go-meta", str(etl_inputs / "go.txt"), "--output-db", str(db),
          "--go-meta-csv", str(csv)])
    main(["create-h5", "--db", str(db), "--fasta", str(etl_inputs / "uniref.fasta"),
          "--go-meta-csv", str(csv), "--output", str(h5), "--min-records", "2"])
    ck = tmp_path / "ck"
    sets = ["--set", "data.batch_size=2", "--set", "model.num_blocks=1",
            "--set", "model.local_dim=8", "--set", "model.global_dim=16",
            "--set", "model.key_dim=4", "--set", "data.seq_len=32",
            "--set", "data.buckets=[16,32]", "--set", "train.log_every=0",
            "--set", "checkpoint.every_steps=2",
            "--set", "checkpoint.async_save=false",
            "--set", "optimizer.warmup_steps=2"]
    assert main(["pretrain", "--preset", "tiny", "--data", str(h5),
                 "--max-steps", "2", "--checkpoint-dir", str(ck), *sets]) == 0
    # Resume extends the same run two more steps.
    assert main(["pretrain", "--preset", "tiny", "--data", str(h5),
                 "--max-steps", "4", "--checkpoint-dir", str(ck), *sets]) == 0
    from proteinbert_tpu.train import Checkpointer

    c = Checkpointer(str(ck), async_save=False)
    assert c.latest_step() == 4
    c.close()


def test_config_json_roundtrip_all_presets():
    from proteinbert_tpu.configs import config_from_dict, config_to_dict

    for name in ("tiny", "base", "long", "large"):
        cfg = get_preset(name)
        assert config_from_dict(config_to_dict(cfg)) == cfg


def test_pretrain_writes_config_json_and_inference_needs_no_overrides(tmp_path):
    """The killer usability path: pretrain with custom geometry → every
    downstream command reconstructs the run config from config.json with
    NO --pretrained-set flags."""
    import json

    from proteinbert_tpu.cli.main import main
    from proteinbert_tpu.configs import load_config

    ck = str(tmp_path / "run")
    overrides = ["--set", "model.local_dim=32", "--set", "model.global_dim=64",
                 "--set", "model.key_dim=16", "--set", "model.num_blocks=2",
                 "--set", "model.num_annotations=64",
                 "--set", "model.dtype=float32", "--set", "data.seq_len=48",
                 "--set", "data.batch_size=4"]
    assert main(["pretrain", "--preset", "tiny", *overrides,
                 "--max-steps", "3", "--checkpoint-dir", ck]) == 0
    saved = load_config(str(tmp_path / "run" / "config.json"))
    assert saved.model.local_dim == 32 and saved.data.seq_len == 48

    emb = str(tmp_path / "e.npz")
    assert main(["embed", "--pretrained", ck, "--output", emb,
                 "MKTAYIAKQR"]) == 0
    import numpy as np
    assert np.load(emb)["global"].shape == (1, 64)

    out = str(tmp_path / "ev.json")
    assert main(["evaluate", "--pretrained", ck, "--max-batches", "2",
                 "--output", out]) == 0
    assert json.load(open(out))["step"] == 3

    npz = str(tmp_path / "w.npz")
    assert main(["export-weights", "--pretrained", ck,
                 "--output", npz]) == 0

    # finetune restores the trunk through config.json too
    assert main(["finetune", "--preset", "tiny", "--pretrained", ck,
                 "--task", "sequence_classification", "--num-outputs", "3",
                 "--epochs", "1",
                 "--set", "data.seq_len=48", "--set", "data.batch_size=4",
                 "--checkpoint-dir", str(tmp_path / "ft")]) == 0


def test_pretrained_set_overrides_config_json(tmp_path):
    """Explicit --pretrained-set still wins over the saved config."""
    from proteinbert_tpu.cli.main import _pretrain_run_config
    from proteinbert_tpu.configs import save_config

    cfg = get_preset("tiny")
    (tmp_path / "run").mkdir()
    save_config(cfg, str(tmp_path / "run" / "config.json"))
    got = _pretrain_run_config(str(tmp_path / "run"), "base",
                               ["data.seq_len=99"])
    assert got.data.seq_len == 99
    assert got.model.local_dim == cfg.model.local_dim  # from json, not preset


def test_corrupt_config_json_gives_clear_error(tmp_path):
    from proteinbert_tpu.cli.main import _pretrain_run_config

    (tmp_path / "run").mkdir()
    (tmp_path / "run" / "config.json").write_text('{"model": {"local_')
    with pytest.raises(SystemExit, match="corrupt config.json"):
        _pretrain_run_config(str(tmp_path / "run"), "tiny", [])


def test_save_config_leaves_no_tmp_and_is_readable(tmp_path):
    from proteinbert_tpu.configs import load_config, save_config

    cfg = get_preset("long")  # exercises the bucket tuple
    path = tmp_path / "config.json"
    save_config(cfg, str(path))
    assert load_config(str(path)) == cfg
    assert [p.name for p in tmp_path.iterdir()] == ["config.json"]


def test_data_bench_cli(tmp_path, capsys):
    import json as _json

    from proteinbert_tpu.cli.main import main

    assert main(["data-bench", "--preset", "tiny", "--batches", "5",
                 "--set", "model.num_annotations=64",
                 "--set", "data.batch_size=4",
                 "--set", "data.seq_len=48"]) == 0
    lines = [ln for ln in capsys.readouterr().out.strip().split("\n")
             if ln.startswith("{")]
    assert len(lines) == 2
    for ln in lines:
        r = _json.loads(ln)
        assert r["variant"] in ("direct", "prefetch")
        assert r["batches_per_sec"] > 0 and r["batches"] == 5


def test_finetune_writes_config_json(tmp_path):
    from proteinbert_tpu.cli.main import main
    from proteinbert_tpu.configs import FinetuneConfig, load_config

    ft = str(tmp_path / "ft")
    assert main(["finetune", "--preset", "tiny",
                 "--task", "sequence_classification", "--num-outputs", "3",
                 "--epochs", "1", "--set", "data.seq_len=48",
                 "--set", "data.batch_size=4", "--set", "model.local_dim=32",
                 "--set", "model.num_annotations=64",
                 "--checkpoint-dir", ft]) == 0
    saved = load_config(str(tmp_path / "ft" / "config.json"),
                        FinetuneConfig)
    assert saved.task.kind == "sequence_classification"
    assert saved.model.local_dim == 32


def test_finetune_rejects_shared_run_dir(tmp_path):
    from proteinbert_tpu.cli.main import main

    d = str(tmp_path / "run")
    with pytest.raises(SystemExit, match="must differ"):
        main(["finetune", "--preset", "tiny", "--pretrained", d,
              "--checkpoint-dir", d])
