"""The int8 ANN index + /v1/neighbors subsystem (proteinbert_tpu/index/,
ISSUE 17).

Three tiers:

- **builder durability** (jax-free, synthetic stores written through
  the mapper's own commit_block protocol): build determinism,
  torn-tail resume to byte identity, typed manifest-drift refusals
  BEFORE any write, `verify_index` corruption detection;
- **scorer quality**: the quantized index's recall@k vs exact fp32
  brute force at full probe — the int8-residual representation must
  not change what the index answers;
- **served integration** (one tiny trunk): `/v1/neighbors` through a
  ragged Server returns exactly the offline scorer's answer over the
  same embedding, per-outcome accounting + cache scoping behave, and a
  trunk-fingerprint mismatch is a typed refusal at attach time.
"""

import json
import os

import numpy as np
import pytest

from proteinbert_tpu.index import (
    IndexBuildError, build_index, index_digests, index_identity,
    verify_index,
)
from proteinbert_tpu.index.scorer import (
    NeighborIndex, evaluate_recall, exact_topk,
    store_vectors_in_index_order,
)
from proteinbert_tpu.mapper import StoreConfigError, StoreError
from proteinbert_tpu.mapper.store import (
    EmbeddingStore, ShardCursor, block_digest, commit_block,
    corpus_digest, serialize_block, shard_ranges,
)

DIM = 16
NUM_SHARDS = 2
STORE_BLOCK = 8
ANCHORS = 4


def make_store(store_dir, n=40, seed=7, dim=DIM, fingerprint=None,
               num_shards=NUM_SHARDS):
    """A complete embedding store with clustered synthetic vectors,
    written through the real durability protocol — the builder's input
    contract without a trunk forward. Returns the fp32 vectors in
    index row order (shard-major, corpus order within a shard — which
    for contiguous shard_ranges is just corpus order)."""
    rng = np.random.default_rng(seed)
    ids = [f"syn{i:05d}" for i in range(n)]
    seqs = ["A" * (10 + i % 7) for i in range(n)]
    anchors = rng.standard_normal((ANCHORS, dim)).astype(np.float32)
    vecs = (anchors[rng.integers(0, ANCHORS, size=n)]
            + 0.15 * rng.standard_normal((n, dim))).astype(np.float32)
    store = EmbeddingStore(store_dir)
    fingerprint = fingerprint or "deadbeef" * 8
    store.ensure_manifest({
        "kind": "embedding_store", "corpus_n": n,
        "corpus_digest": corpus_digest(ids, seqs),
        "model_fingerprint": fingerprint,
        "num_shards": num_shards, "block_size": STORE_BLOCK,
        "rows_per_batch": 2, "max_segments": 4, "seq_len": 48,
        "buckets": [16, 32, 48],
    })
    for shard, (lo, hi) in enumerate(shard_ranges(n, num_shards)):
        cursor = ShardCursor(store_dir, shard)
        state = cursor.write_state(cursor.fresh_state())
        for start in range(0, hi - lo, STORE_BLOCK):
            end = min(start + STORE_BLOCK, hi - lo)
            rows = slice(lo + start, lo + end)
            arrays = {
                "ids": np.array(ids[rows], dtype="S"),
                "lengths": np.array([len(s) for s in seqs[rows]],
                                    np.int32),
                "global": vecs[rows],
                "local_mean": np.zeros((end - start, dim), np.float32),
            }
            payload = serialize_block(
                {"shard": shard, "block": start // STORE_BLOCK,
                 "start": start, "end": end,
                 "model_fingerprint": fingerprint}, arrays)
            entry = {"block": start // STORE_BLOCK,
                     "digest": block_digest(payload), "start": start,
                     "end": end, "n": end - start, "quarantined": []}
            state = commit_block(store, cursor, state, payload, entry)
        cursor.write_state(dict(state, done=True))
    return vecs


BUILD_KW = dict(num_centroids=4, block_size=8, kmeans_iters=4)


class TestBuilderDurability:

    def test_build_deterministic_byte_identical(self, tmp_path):
        make_store(str(tmp_path / "store"))
        a, b = str(tmp_path / "a"), str(tmp_path / "b")
        sa = build_index(str(tmp_path / "store"), a, **BUILD_KW)
        sb = build_index(str(tmp_path / "store"), b, **BUILD_KW)
        assert sa["outcome"] == sb["outcome"] == "completed"
        assert index_digests(a) == index_digests(b)
        assert index_identity(a) == index_identity(b)
        for dg in index_digests(a).values():
            with open(EmbeddingStore(a).object_path(dg), "rb") as fa, \
                    open(EmbeddingStore(b).object_path(dg), "rb") as fb:
                assert fa.read() == fb.read()

    def test_torn_tail_resume_byte_identical(self, tmp_path):
        store = str(tmp_path / "store")
        make_store(store)
        control = str(tmp_path / "control")
        build_index(store, control, **BUILD_KW)
        chaos = str(tmp_path / "chaos")
        # Preempt mid-build, then tear the tail block object the way a
        # crash mid-write would — resume must drop + re-work that one
        # block and still converge on the control's bytes.
        pre = build_index(store, chaos, max_blocks=3, **BUILD_KW)
        assert pre["outcome"] == "preempted"
        state, _ = ShardCursor(chaos, 0).load()
        tail = state["blocks"][-1]["digest"]
        path = EmbeddingStore(chaos).object_path(tail)
        with open(path, "rb") as f:
            data = f.read()
        with open(path, "wb") as f:
            f.write(data[: len(data) // 2])
        stats = build_index(store, chaos, **BUILD_KW)
        assert stats["outcome"] == "completed"
        assert stats["reworked_blocks"] <= NUM_SHARDS
        assert index_digests(chaos) == index_digests(control)
        assert index_identity(chaos) == index_identity(control)

    def test_stale_store_pin_refused_before_any_write(self, tmp_path):
        store = str(tmp_path / "store")
        make_store(store)
        index = str(tmp_path / "index")
        build_index(store, index, **BUILD_KW)
        before = index_digests(index)
        # Different corpus AND different trunk: both pins must refuse.
        other = str(tmp_path / "other")
        make_store(other, seed=8, fingerprint="feedface" * 8)
        with pytest.raises(StoreConfigError) as ei:
            build_index(other, index, **BUILD_KW)
        msg = str(ei.value)
        assert "corpus_digest" in msg or "model_fingerprint" in msg
        assert index_digests(index) == before  # refusal preceded writes

    def test_unfinished_store_refused(self, tmp_path):
        store = str(tmp_path / "store")
        make_store(store)
        state, _ = ShardCursor(store, 1).load()
        ShardCursor(store, 1).write_state(dict(state, done=False))
        with pytest.raises(IndexBuildError, match="not done"):
            build_index(store, str(tmp_path / "index"), **BUILD_KW)

    def test_verify_catches_flip_and_hole_typed(self, tmp_path):
        store = str(tmp_path / "store")
        make_store(store)
        index = str(tmp_path / "index")
        build_index(store, index, **BUILD_KW)
        rep = verify_index(index)
        assert rep["ok"] and rep["complete"]
        victim = sorted(v for k, v in index_digests(index).items()
                        if k != "centroids")[0]
        path = EmbeddingStore(index).object_path(victim)
        with open(path, "rb") as f:
            good = f.read()
        with open(path, "wb") as f:
            f.write(good[:-1] + bytes([good[-1] ^ 0xFF]))
        rep = verify_index(index)
        assert not rep["ok"]
        assert any(c.get("reason") == "digest_mismatch"
                   for c in rep["corrupt"])
        os.remove(path)
        rep = verify_index(index)
        assert not rep["ok"]
        assert any(h["digest"] == victim for h in rep["holes"])
        with open(path, "wb") as f:
            f.write(good)
        assert verify_index(index)["ok"]

    def test_load_refuses_foreign_directory(self, tmp_path):
        with pytest.raises(StoreError):
            NeighborIndex.load(str(tmp_path / "nothing_here"))
        # An embedding STORE is not an INDEX — typed, not garbage.
        store = str(tmp_path / "store")
        make_store(store)
        with pytest.raises(StoreConfigError):
            NeighborIndex.load(store)


class TestScorerQuality:

    @pytest.fixture(scope="class")
    def built(self, tmp_path_factory):
        tmp = tmp_path_factory.mktemp("scorer")
        store = str(tmp / "store")
        make_store(store, n=96)
        index_dir = str(tmp / "index")
        stats = build_index(store, index_dir, **BUILD_KW)
        return (NeighborIndex.load(index_dir),
                store_vectors_in_index_order(store), stats)

    def test_quantized_recall_bound_at_full_probe(self, built):
        """The int8-residual representation must preserve the answers:
        at nprobe == num_centroids the shortlist is the whole corpus,
        so any recall loss is PURELY quantization error — gate it at
        the bench's 0.95 floor."""
        index, vectors, _stats = built
        queries = vectors[::5]
        recall = evaluate_recall(index, vectors, queries, k=10,
                                 nprobe=index.centroids.shape[0])
        assert recall >= 0.95

    def test_lookup_rows_matches_lookup_one(self, built):
        index, vectors, _stats = built
        q = vectors[3]
        scores, rows = index.lookup_rows(q[None, :], k=5,
                                         nprobe=index.centroids.shape[0])
        pairs = index.lookup_one(q, k=5,
                                 nprobe=index.centroids.shape[0])
        assert [p[0] for p in pairs] == [
            index.ids[r].decode() for r in rows[0]]
        np.testing.assert_allclose([p[1] for p in pairs], scores[0],
                                   rtol=1e-6)

    def test_self_is_top1_and_exact_topk_sane(self, built):
        index, vectors, _stats = built
        got = exact_topk(vectors, vectors[:8], k=1)[:, 0]
        np.testing.assert_array_equal(got, np.arange(8))
        for row in (0, 17, 41):
            pairs = index.lookup_one(vectors[row], k=1,
                                     nprobe=index.centroids.shape[0])
            assert pairs[0][0] == index.ids[row].decode()

    def test_bytes_ratio_accounting(self, built):
        _index, _vectors, stats = built
        assert stats["index_vector_bytes"] < stats["fp32_vector_bytes"]
        assert stats["bytes_ratio"] == pytest.approx(
            stats["index_vector_bytes"] / stats["fp32_vector_bytes"],
            abs=1e-4)

    def test_clamp_validation(self, built):
        index, _vectors, _stats = built
        q = np.zeros(index.dim, np.float32)
        with pytest.raises(ValueError, match="k"):
            index.lookup_one(q, k=0)
        with pytest.raises(ValueError, match="nprobe"):
            index.lookup_one(q, k=1, nprobe=0)


# ------------------------------------------------------- served tier

import jax  # noqa: E402

from proteinbert_tpu.configs import (  # noqa: E402
    DataConfig, ModelConfig, OptimizerConfig, PretrainConfig,
    TrainConfig,
)
from proteinbert_tpu.heads import TrunkMismatchError, trunk_fingerprint  # noqa: E402
from proteinbert_tpu.serve import Server  # noqa: E402
from proteinbert_tpu.serve.server import DEFAULT_NEIGHBORS_K  # noqa: E402
from proteinbert_tpu.train import create_train_state  # noqa: E402

SEQ_LEN = 48


@pytest.fixture(scope="module")
def trunk():
    cfg = PretrainConfig(
        model=ModelConfig(local_dim=16, global_dim=32, key_dim=8,
                          num_heads=2, num_blocks=2, num_annotations=32,
                          dtype="float32"),
        data=DataConfig(seq_len=SEQ_LEN, batch_size=4,
                        buckets=(16, 32, 48)),
        optimizer=OptimizerConfig(warmup_steps=5),
        train=TrainConfig(seed=0, max_steps=1))
    state = create_train_state(jax.random.PRNGKey(0), cfg)
    return state.params, cfg


@pytest.fixture(scope="module")
def trunk_index(trunk, tmp_path_factory):
    """An index pinned to the REAL trunk fingerprint (vectors are
    synthetic at the trunk's global_dim — attach-time compatibility is
    a fingerprint contract, not a geometry one)."""
    params, cfg = trunk
    tmp = tmp_path_factory.mktemp("served")
    store = str(tmp / "store")
    make_store(store, n=48, dim=cfg.model.global_dim,
               fingerprint=trunk_fingerprint(params))
    index_dir = str(tmp / "index")
    build_index(store, index_dir, **BUILD_KW)
    return NeighborIndex.load(index_dir)


def _drain(srv, futs):
    srv.queue.close()
    while srv.scheduler.poll():
        pass
    return [f.result(timeout=5) for f in futs]


class TestServedNeighbors:

    def test_served_equals_offline_over_same_embedding(
            self, trunk, trunk_index):
        params, cfg = trunk
        srv = Server(params, cfg, max_batch=4, max_wait_s=60.0,
                     cache_size=0, warm_kinds=(), serve_mode="ragged",
                     index=trunk_index, nprobe=4)
        seqs = ["MKTAYIAKQR", "GDSLAVVL", "MNNQRKKT"]
        nf = [srv.submit("neighbors", s, top_k=5) for s in seqs]
        ef = [srv.submit("embed", s) for s in seqs]
        out = _drain(srv, nf + ef)
        served, embeds = out[:3], out[3:]
        for got, emb in zip(served, embeds):
            offline = trunk_index.lookup_one(emb["global"], k=5,
                                             nprobe=4)
            assert got["neighbors"] == offline
        by = srv.stats()["neighbors"]["by_outcome"]
        assert by["ok"] == 3
        srv.drain(timeout=10)

    def test_default_k_and_outcome_accounting(self, trunk, trunk_index):
        params, cfg = trunk
        srv = Server(params, cfg, max_batch=2, max_wait_s=60.0,
                     cache_size=8, warm_kinds=(), serve_mode="ragged",
                     index=trunk_index, nprobe=2)
        f1 = srv.submit("neighbors", "MKTAYIAKQR")
        _drain(srv, [f1])
        assert len(f1.result()["neighbors"]) == DEFAULT_NEIGHBORS_K
        f2 = srv.submit("neighbors", "MKTAYIAKQR")  # cache hit
        assert f2.done()
        assert f2.result() == f1.result()
        stats = srv.stats()["neighbors"]
        assert stats["by_outcome"]["ok"] == 1
        assert stats["by_outcome"]["cache_hit"] == 1
        assert stats["index_digest"] == trunk_index.digest
        assert stats["num_vectors"] == trunk_index.num_vectors
        srv.drain(timeout=10)

    def test_no_index_is_typed_submit_error(self, trunk):
        params, cfg = trunk
        srv = Server(params, cfg, max_batch=2, max_wait_s=60.0,
                     cache_size=0, warm_kinds=(), serve_mode="ragged")
        with pytest.raises(ValueError, match="no neighbor index"):
            srv.submit("neighbors", "MKTAYIAKQR")
        assert srv.stats()["neighbors"] is None
        srv.drain(timeout=10)

    def test_trunk_mismatch_refused_at_attach(self, trunk, tmp_path):
        params, cfg = trunk
        store = str(tmp_path / "store")
        make_store(store, n=32, dim=cfg.model.global_dim,
                   fingerprint="feedface" * 8)  # some OTHER trunk
        index_dir = str(tmp_path / "index")
        build_index(store, index_dir, **BUILD_KW)
        with pytest.raises(TrunkMismatchError, match="rebuild"):
            Server(params, cfg, max_batch=2, warm_kinds=(),
                   serve_mode="ragged",
                   index=NeighborIndex.load(index_dir))


class TestFleetCacheScoping:

    def test_neighbors_cache_key_requires_index_digest(self):
        from proteinbert_tpu.serve.fleet import FleetRouter

        body = {"seq": "MKTAYIAK", "k": 5}
        url = ["http://localhost:1"]  # never contacted: key tests only
        blind = FleetRouter(url, cache_size=16)
        assert blind._cache_key("neighbors", body) is None
        digest = "ab" * 32
        scoped = FleetRouter(url, cache_size=16, index_digest=digest)
        key = scoped._cache_key("neighbors", body)
        assert key is not None
        # Same body, different fleet index → different key (two fleets
        # serving different corpora must never share answers).
        other = FleetRouter(url, cache_size=16, index_digest="cd" * 32)
        assert other._cache_key("neighbors", body) != key
        # k changes the answer → changes the key.
        assert scoped._cache_key("neighbors",
                                 {"seq": "MKTAYIAK", "k": 3}) != key
        # Non-neighbors kinds are unaffected by the digest.
        assert blind._cache_key("embed", {"seq": "MKTAYIAK"}) == \
            scoped._cache_key("embed", {"seq": "MKTAYIAK"})


class TestEventsAndCli:

    def test_build_events_schema_valid(self, tmp_path):
        from proteinbert_tpu.obs import Telemetry, read_events

        store = str(tmp_path / "store")
        make_store(store)
        path = tmp_path / "events.jsonl"
        tele = Telemetry(events_path=str(path))
        build_index(store, str(tmp_path / "index"), telemetry=tele,
                    **BUILD_KW)
        tele.close()
        recs = read_events(str(path), strict=True)
        builds = [r for r in recs if r["event"] == "index_build"]
        assert [b["state"] for b in builds] == ["start", "completed"]
        shard_done = [r for r in recs if r["event"] == "index_shard"
                      and r["state"] == "done"]
        assert len(shard_done) == NUM_SHARDS

    def test_cli_verify_report_shape(self, tmp_path, capsys):
        from proteinbert_tpu.cli.main import main as cli_main

        store = str(tmp_path / "store")
        make_store(store)
        index = str(tmp_path / "index")
        build_index(store, index, **BUILD_KW)
        assert cli_main(["index", "--index", index, "--verify"]) in (0,
                                                                     None)
        out = capsys.readouterr().out
        rep = json.loads(next(ln for ln in out.splitlines()
                              if ln.startswith("{")))
        assert rep["ok"] and rep["complete"]
        assert rep["vectors"] == 40
