"""Tests for datasets and the per-host sharded iterator (C7/C8)."""

import numpy as np
import pytest

from proteinbert_tpu.data.dataset import (
    InMemoryPretrainingDataset, make_pretrain_iterator,
)


def _ds(n=40, a=16, seq_len=32, rng=None):
    rng = rng or np.random.default_rng(0)
    from tests.conftest import make_random_proteins

    seqs, ann = make_random_proteins(n, rng, num_annotations=a, max_len=40)
    return InMemoryPretrainingDataset(seqs, ann, seq_len)


def test_inmemory_shapes_and_getitem():
    ds = _ds()
    assert len(ds) == 40
    row = ds[3]
    assert row["tokens"].shape == (32,) and row["annotations"].shape == (16,)
    batch = ds.get_batch(np.array([1, 5, 9]))
    assert batch["tokens"].shape == (3, 32)
    assert (batch["tokens"][1] == ds[5]["tokens"]).all()


def test_iterator_batches_and_epochs():
    ds = _ds(n=40)
    batches = list(make_pretrain_iterator(ds, 8, num_epochs=2))
    assert len(batches) == 10  # 5 per epoch x 2
    assert batches[0]["tokens"].shape == (8, 32)
    assert batches[0]["annotations"].dtype == np.float32


def test_iterator_raises_on_undersized_shard():
    ds = _ds(n=10)
    with pytest.raises(ValueError, match="cannot fill"):
        next(make_pretrain_iterator(ds, 32))
    with pytest.raises(ValueError, match="cannot fill"):
        next(make_pretrain_iterator(ds, 8, process_count=4))


def test_equal_batches_per_host():
    # n=15, 2 hosts: both hosts must see exactly 7 rows -> 1 batch of 4... 7//4=1
    ds = _ds(n=15)
    counts = []
    for p in range(2):
        it = make_pretrain_iterator(ds, 4, seed=3, num_epochs=1,
                                    process_index=p, process_count=2)
        counts.append(sum(1 for _ in it))
    assert counts[0] == counts[1] > 0


def test_hosts_disjoint():
    # Unique-by-construction rows (random fixtures can produce duplicate
    # short/empty sequences, which would collide across hosts by content).
    alphabet = "ACDEFGHIKLMNPQRSTVWY"
    seqs = [alphabet[i % 20] * (i // 20 + 1) + alphabet[: i % 20] for i in range(64)]
    ann = np.eye(64, 16, dtype=np.float32)
    ds = InMemoryPretrainingDataset(seqs, ann, 32)
    b0 = next(make_pretrain_iterator(ds, 16, seed=1, process_index=0, process_count=2))
    b1 = next(make_pretrain_iterator(ds, 16, seed=1, process_index=1, process_count=2))
    s0 = {t.tobytes() for t in b0["tokens"]}
    s1 = {t.tobytes() for t in b1["tokens"]}
    assert not (s0 & s1)


def test_shuffle_covers_all_rows():
    ds = _ds(n=32)
    it = make_pretrain_iterator(ds, 8, num_epochs=1)
    seen = set()
    for b in it:
        for t in b["tokens"]:
            seen.add(t.tobytes())
    all_rows = {t.tobytes() for t in ds.tokens}
    assert seen == all_rows


class _BlockDS(InMemoryPretrainingDataset):
    shuffle_block = 8


def test_block_shuffle_order_is_block_local():
    rng = np.random.default_rng(0)
    from proteinbert_tpu.data.dataset import _epoch_order

    order = _epoch_order(32, rng, shuffle=True, block=8)
    assert sorted(order.tolist()) == list(range(32))
    # each consecutive 8-run stays within one block
    for i in range(0, 32, 8):
        run = order[i : i + 8]
        assert len({int(v) // 8 for v in run}) == 1


def test_iterator_respects_shuffle_block_end_to_end():
    """The iterator must discover `shuffle_block` and keep each host's
    accesses block-local (one 8-row block per consecutive batch run)."""
    # Unique-by-construction rows: row identity is recovered from token
    # bytes, so duplicate random sequences would alias rows.
    alphabet = "ACDEFGHIKLMNPQRSTVWY"
    seqs = [alphabet[i % 20] * (i // 20 + 1) + alphabet[: i % 20] for i in range(32)]
    ann = np.eye(32, 16, dtype=np.float32)
    ds = _BlockDS(seqs, ann, 32)
    row_of = {ds[i]["tokens"].tobytes(): i for i in range(32)}
    for p in range(2):
        it = make_pretrain_iterator(ds, 8, seed=7, num_epochs=1,
                                    process_index=p, process_count=2)
        for b in it:
            rows = [row_of[t.tobytes()] for t in b["tokens"]]
            assert len({r // 8 for r in rows}) == 1, rows


def test_inmemory_recrops_long_rows_per_epoch():
    """With crop_seed, long sequences get a fresh window each EPOCH (the
    counter-based scheme: window = f(crop_seed, epoch, row)), while the
    same (epoch, row) always reproduces its window — that determinism is
    what makes checkpoint resume byte-identical (VERDICT r1 Weak #3)."""
    rng = np.random.default_rng(0)
    long_seq = "".join(rng.choice(list("ACDEFGHIKLMNPQRSTVWY"), size=500))
    ds = InMemoryPretrainingDataset(
        [long_seq], np.zeros((1, 4)), seq_len=32, crop_seed=1,
    )
    epoch_draws = {
        ds.get_batch(np.array([0]), epoch=e)["tokens"].tobytes()
        for e in range(10)
    }
    assert len(epoch_draws) > 1, "windows never vary across epochs"
    for e in (0, 3):
        a = ds.get_batch(np.array([0]), epoch=e)["tokens"]
        b = ds.get_batch(np.array([0]), epoch=e)["tokens"]
        np.testing.assert_array_equal(a, b)
    # __getitem__ serves the epoch-0 window.
    np.testing.assert_array_equal(
        ds[0]["tokens"], ds.get_batch(np.array([0]), epoch=0)["tokens"][0])


def test_single_row_and_batched_paths_agree_every_epoch(tmp_path):
    """`ds[i]` / `ds.get_row(i, epoch)` must equal `get_batch([i], epoch)`
    for EVERY epoch on all three dataset surfaces (in-memory, HDF5,
    Subset) — the single-row path used to pin epoch 0 while get_batch
    varied windows per epoch (VERDICT r2 Weak #4 / item 6)."""
    import h5py

    from proteinbert_tpu.data.dataset import (
        HDF5PretrainingDataset, Subset,
    )

    rng = np.random.default_rng(0)
    # Mix of short rows and rows long enough to be re-cropped per epoch.
    seqs = ["".join(rng.choice(list("ACDEFGHIKLMNPQRSTVWY"),
                               size=int(n)))
            for n in rng.integers(5, 200, size=12)]
    ann = (rng.random((12, 6)) < 0.3).astype(np.float32)

    path = tmp_path / "rows.h5"
    with h5py.File(path, "w") as f:
        sd = h5py.string_dtype()
        f.create_dataset("seqs", data=np.array(seqs, dtype=object), dtype=sd)
        f.create_dataset("seq_lengths",
                         data=np.array([len(s) for s in seqs], np.int32))
        f.create_dataset("annotation_masks", data=ann.astype(bool))

    mem = InMemoryPretrainingDataset(seqs, ann, seq_len=32, crop_seed=7)
    h5 = HDF5PretrainingDataset(str(path), seq_len=32, crop_seed=7)
    sub = Subset(mem, np.array([0, 3, 5, 7, 11]))
    try:
        for ds, n in ((mem, 12), (h5, 12), (sub, 5)):
            for i in (0, n - 1, n // 2):
                for epoch in range(4):
                    batch = ds.get_batch(np.array([i]), epoch=epoch)
                    row = ds.get_row(i, epoch=epoch)
                    for k in ("tokens", "annotations"):
                        np.testing.assert_array_equal(row[k], batch[k][0])
                # bare [] is the epoch-0 view of the SAME path
                np.testing.assert_array_equal(
                    ds[i]["tokens"],
                    ds.get_batch(np.array([i]), epoch=0)["tokens"][0])
            # windows genuinely vary somewhere across epochs (else the
            # equality above would be vacuous for the re-crop machinery)
            long_rows = [i for i, s in enumerate(seqs) if len(s) > 30]
            assert long_rows
        i = long_rows[0]
        assert len({mem.get_row(i, epoch=e)["tokens"].tobytes()
                    for e in range(8)}) > 1
    finally:
        h5.close()


def test_iterator_epoch_windows_and_resume_are_byte_identical():
    """End-to-end over the iterator: (a) crop windows differ across
    epochs; (b) an iterator restarted with skip_batches yields EXACTLY
    the bytes the uninterrupted run yields — including windows."""
    rng = np.random.default_rng(0)
    seqs = ["".join(rng.choice(list("ACDEFGHIKLMNPQRSTVWY"), size=300))
            for _ in range(8)]
    ann = np.zeros((8, 4), np.float32)

    def fresh():
        ds = InMemoryPretrainingDataset(seqs, ann, seq_len=32, crop_seed=5)
        return make_pretrain_iterator(ds, 4, seed=9, num_epochs=3)

    full = [b["tokens"].tobytes() for b in fresh()]
    assert len(set(full)) == len(full), "epoch windows repeated"

    ds2 = InMemoryPretrainingDataset(seqs, ann, seq_len=32, crop_seed=5)
    resumed = [b["tokens"].tobytes() for b in make_pretrain_iterator(
        ds2, 4, seed=9, num_epochs=3, skip_batches=3)]
    assert resumed == full[3:], "resume is not byte-identical"


def test_structured_proteins_properties():
    """The transfer-experiment corpus generator: deterministic for a
    seed, states aligned with sequences, annotations = 3-mer occurrence
    bits, and the hidden state only WEAKLY decodable per residue (the
    property that makes frozen-trunk probing discriminate context-
    integrating features from random ones)."""
    from proteinbert_tpu.data.synthetic import (
        _STATE_RESIDUES, make_structured_proteins,
    )

    a = make_structured_proteins(50, np.random.default_rng(4),
                                 num_annotations=32, max_len=100)
    b = make_structured_proteins(50, np.random.default_rng(4),
                                 num_annotations=32, max_len=100)
    assert a[0] == b[0] and (a[1] == b[1]).all()
    seqs, ann, states = a
    assert ann.shape == (50, 32) and 0 < ann.mean() < 0.2
    hydro = set(_STATE_RESIDUES[0])
    accs = []
    for s, st in zip(seqs, states):
        assert len(s) == len(st) and set(np.unique(st)) <= {0, 1}
        pred = np.fromiter((c in hydro for c in s), bool, len(s))
        accs.append(float((pred == (np.asarray(st) == 0)).mean()))
    acc = float(np.mean(accs))
    assert 0.7 < acc < 0.95, f"single-residue decodability {acc} out of band"


def test_row_lengths():
    seqs = ["ACDE", "A" * 100, ""]
    ds = InMemoryPretrainingDataset(seqs, np.zeros((3, 4)), seq_len=32)
    # tokenized = min(raw, seq_len-2) + sos + eos
    np.testing.assert_array_equal(ds.row_lengths(), [6, 32, 2])


def test_bucketed_iterator():
    from proteinbert_tpu.data.dataset import make_bucketed_iterator

    rng = np.random.default_rng(0)
    seqs = ["".join(rng.choice(list("ACDEFGHIKLMNPQRSTVWY"),
                               size=int(rng.integers(1, 120))))
            for _ in range(96)]
    ds = InMemoryPretrainingDataset(seqs, np.zeros((96, 8)), seq_len=128)
    it = make_bucketed_iterator(ds, 4, buckets=(32, 64, 128), seed=0,
                                num_epochs=1)
    seen = 0
    for batch in it:
        L = batch["tokens"].shape[1]
        assert L in (32, 64, 128)
        lengths = (batch["tokens"] != 0).sum(axis=1)
        # Every row fits its bucket and (except the smallest bucket)
        # would NOT fit the next smaller one.
        assert (lengths <= L).all()
        if L > 32:
            prev = {64: 32, 128: 64}[L]
            assert (lengths > prev).all()
        seen += len(batch["tokens"])
    assert seen >= 96 - 3 * 4 + 4  # at most one partial batch per bucket lost


def test_bucketed_iterator_validates():
    from proteinbert_tpu.data.dataset import make_bucketed_iterator

    ds = InMemoryPretrainingDataset(["ACDE"] * 8, np.zeros((8, 4)), seq_len=64)
    with pytest.raises(ValueError, match="must equal dataset seq_len"):
        next(make_bucketed_iterator(ds, 2, buckets=(32,), num_epochs=1))


def test_bucketed_iterator_multihost_lockstep():
    """Review fix: every host must emit the same batch-shape sequence and
    count (collective steps deadlock otherwise)."""
    from proteinbert_tpu.data.dataset import make_bucketed_iterator

    rng = np.random.default_rng(1)
    seqs = ["".join(rng.choice(list("ACDEFGHIKLMNPQRSTVWY"),
                               size=int(rng.integers(1, 120))))
            for _ in range(128)]
    ds = InMemoryPretrainingDataset(seqs, np.zeros((128, 8)), seq_len=128)
    shapes = []
    rows_seen = [set(), set()]
    for p in range(2):
        it = make_bucketed_iterator(ds, 4, buckets=(32, 64, 128), seed=3,
                                    num_epochs=1, process_index=p,
                                    process_count=2)
        host_shapes = []
        for b in it:
            host_shapes.append(b["tokens"].shape)
            assert b["tokens"].shape[0] == 4  # per-host batch size
            for t in b["tokens"]:
                rows_seen[p].add(t.tobytes())
        shapes.append(host_shapes)
    assert shapes[0] == shapes[1] and shapes[0]
    # Hosts fetch DISJOINT halves of each global batch.
    assert not (rows_seen[0] & rows_seen[1])


def test_bucketed_iterator_skip_batches():
    """skip_batches resumes the exact stream position without fetching."""
    from proteinbert_tpu.data.dataset import make_bucketed_iterator

    rng = np.random.default_rng(2)
    seqs = ["".join(rng.choice(list("ACDEFGHIKLMNPQRSTVWY"),
                               size=int(rng.integers(1, 120))))
            for _ in range(96)]
    ds = InMemoryPretrainingDataset(seqs, np.zeros((96, 8)), seq_len=128)
    full = list(make_bucketed_iterator(ds, 4, (32, 64, 128), seed=5,
                                       num_epochs=2))
    skipped = list(make_bucketed_iterator(ds, 4, (32, 64, 128), seed=5,
                                          num_epochs=2, skip_batches=3))
    assert len(skipped) == len(full) - 3
    for a, b in zip(full[3:], skipped):
        np.testing.assert_array_equal(a["tokens"], b["tokens"])


def test_bucketed_iterator_rejects_strings():
    from proteinbert_tpu.data.dataset import make_bucketed_iterator

    ds = InMemoryPretrainingDataset(["ACDE"] * 8, np.zeros((8, 4)), seq_len=64)
    with pytest.raises(ValueError, match="sequence of ints"):
        next(make_bucketed_iterator(ds, 2, "32,64", num_epochs=1))


def test_train_eval_split_sorted_and_disjoint():
    ds = InMemoryPretrainingDataset(["ACDE"] * 40, np.zeros((40, 4)), 16)
    tr, ev = InMemoryPretrainingDataset, None
    from proteinbert_tpu.data import train_eval_split

    tr, ev = train_eval_split(ds, 0.25, seed=0)
    assert len(tr) == 30 and len(ev) == 10
    assert (np.diff(tr._idx) > 0).all() and (np.diff(ev._idx) > 0).all()
    assert not set(tr._idx.tolist()) & set(ev._idx.tolist())
    # Sorted views forward the parent's block preference (None here, but
    # the attribute path must not raise).
    _ = tr.shuffle_block


# ---------------------------------------------------------------- prefetch

def test_prefetch_preserves_stream():
    from proteinbert_tpu.data.prefetch import prefetch

    src = [{"tokens": np.full((2, 4), i)} for i in range(20)]
    out = list(prefetch(iter(src), depth=3))
    assert len(out) == 20
    for i, b in enumerate(out):
        np.testing.assert_array_equal(b["tokens"], src[i]["tokens"])


def test_prefetch_propagates_errors():
    from proteinbert_tpu.data.prefetch import prefetch

    def bad():
        yield 1
        yield 2
        raise RuntimeError("source blew up")

    it = prefetch(bad(), depth=2)
    assert next(it) == 1 and next(it) == 2
    with pytest.raises(RuntimeError, match="source blew up"):
        next(it)


def test_prefetch_raising_source_surfaces_within_one_next():
    """ISSUE 4 satellite: a source that raises BEFORE yielding anything
    must surface its exception — with the producer's original traceback,
    not a generic StopIteration — at the very first __next__."""
    import traceback

    from proteinbert_tpu.data.prefetch import prefetch

    def bad():
        raise ValueError("broken at batch 0")
        yield  # pragma: no cover

    it = prefetch(bad(), depth=2)
    with pytest.raises(ValueError, match="broken at batch 0") as exc_info:
        next(it)
    # the traceback points into the producer, not only the queue plumbing
    frames = traceback.extract_tb(exc_info.value.__traceback__)
    assert any(f.name == "bad" for f in frames), [f.name for f in frames]
    # and the iterator is cleanly done afterwards, not wedged
    with pytest.raises(StopIteration):
        next(it)


def test_prefetch_close_stops_thread():
    import itertools

    from proteinbert_tpu.data.prefetch import prefetch

    it = prefetch(itertools.count(), depth=2)  # infinite source
    assert next(it) == 0
    it.close()
    it._thread.join(timeout=5)
    assert not it._thread.is_alive()


def test_prefetch_exhaustion_then_next_raises_stopiteration():
    """Review fix: repeated next() after the stream ends (or errors) must
    raise StopIteration, never block forever on a dead fill thread."""
    from proteinbert_tpu.data.prefetch import prefetch

    it = prefetch(iter([1, 2]), depth=2)
    assert list(it) == [1, 2]
    with pytest.raises(StopIteration):
        next(it)

    def bad():
        yield 1
        raise RuntimeError("boom")

    it2 = prefetch(bad(), depth=2)
    assert next(it2) == 1
    with pytest.raises(RuntimeError):
        next(it2)
    with pytest.raises(StopIteration):  # exhausted, not hung
        next(it2)
