"""ZeRO-1 sharded weight update (parallel/zero.py) on the virtual
8-device mesh: numerical parity with the replicated step, the per-chip
optimizer-state memory claim, the bf16 compressed-reduction error
bound, and byte-identical checkpoint resume (including the PR-1 staged
overlapped save path)."""

import dataclasses

import numpy as np
import pytest

import jax

from proteinbert_tpu.configs import (
    CheckpointConfig, DataConfig, MeshConfig, ModelConfig, OptimizerConfig,
    ParallelConfig, PretrainConfig, TrainConfig,
)
from proteinbert_tpu.data import (
    InMemoryPretrainingDataset, make_pretrain_iterator,
)
from proteinbert_tpu.parallel import (
    batch_sharding, make_mesh, make_zero_train_step, shard_train_state,
    zero_extent,
)
from proteinbert_tpu.parallel.sharding import state_sharding
from proteinbert_tpu.parallel.zero import (
    collective_bytes_from_hlo, per_chip_state_bytes, zero_gradient_update,
)
from proteinbert_tpu.train import Checkpointer, create_train_state, pretrain, train_step
from tests.conftest import make_random_proteins

requires_8 = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 (virtual) devices"
)


def cfg_for(mesh_cfg, parallel=None, **kw):
    model = dict(
        local_dim=16, global_dim=32, key_dim=8, num_heads=4, num_blocks=2,
        num_annotations=64, dtype="float32",
    )
    return PretrainConfig(
        model=ModelConfig(**model),
        data=DataConfig(seq_len=32, batch_size=16),
        optimizer=OptimizerConfig(learning_rate=1e-3, warmup_steps=10,
                                  **kw.pop("opt_kw", {})),
        mesh=mesh_cfg,
        parallel=parallel or ParallelConfig(zero_update=True),
        train=TrainConfig(max_steps=4, **kw.pop("train_kw", {})),
    )


# ONE canonical config for every single-device REFERENCE run in this
# module: cfg is a static jit arg, so giving each test its own
# mesh/parallel variant would recompile the identical reference
# train_step per test — with a shared config the module pays one
# reference compile (and the zero-vs-ref math never depends on the
# mesh/parallel fields the variants differ in).
REF_CFG = cfg_for(MeshConfig(), parallel=ParallelConfig())


def _ref_two_steps(batch):
    state = create_train_state(jax.random.PRNGKey(0), REF_CFG)
    state, m1 = train_step(state, dict(batch), REF_CFG)
    state, m2 = train_step(state, dict(batch), REF_CFG)
    return state, m1, m2


def make_batch(cfg, seed=0):
    rng = np.random.default_rng(seed)
    seqs, ann = make_random_proteins(
        cfg.data.batch_size, rng, num_annotations=cfg.model.num_annotations,
        max_len=40,
    )
    ds = InMemoryPretrainingDataset(seqs, ann, cfg.data.seq_len)
    return next(make_pretrain_iterator(ds, cfg.data.batch_size, seed=seed))


def _run_two_steps_zero(cfg, batch):
    mesh = make_mesh(cfg.mesh)
    state = shard_train_state(
        create_train_state(jax.random.PRNGKey(0), cfg), mesh,
        zero_update=True)
    zstep = make_zero_train_step(mesh, cfg)
    bsh = batch_sharding(mesh)
    dbatch = {k: jax.device_put(v, bsh[k]) for k, v in batch.items()}
    state, m1 = zstep(state, dbatch)
    state, m2 = zstep(state, dbatch)
    return state, m1, m2


def _max_param_err(ref_state, state):
    err = 0.0
    for r, g in zip(jax.tree.leaves(ref_state.params),
                    jax.tree.leaves(state.params)):
        err = max(err, float(np.max(np.abs(
            np.asarray(r, np.float64)
            - np.asarray(jax.device_get(g), np.float64)))))
    return err


@requires_8
@pytest.mark.parametrize(
    "mesh_cfg",
    [
        MeshConfig(data=8),                      # pure DP — the motivating case
        MeshConfig(data=4, fsdp=2),              # joint replica axis
        MeshConfig(data=2, fsdp=2, model=2),     # + tensor parallelism
    ],
    ids=["dp8", "dp4-fsdp2", "dp2-fsdp2-tp2"],
)
def test_zero_update_matches_replicated(mesh_cfg):
    """Reduce-scatter → sharded apply → all-gather must be numerically
    the replicated clip→Adam update: loss, grad_norm and every param
    leaf agree with the single-device step over two steps (fp32,
    tight tolerance — the acceptance criterion's parity gate)."""
    cfg = cfg_for(mesh_cfg)
    batch = make_batch(cfg)

    ref_state, ref_m1, ref_m2 = _ref_two_steps(batch)

    state, m1, m2 = _run_two_steps_zero(cfg, batch)
    assert int(jax.device_get(state.step)) == 2

    for ref_m, m in ((ref_m1, m1), (ref_m2, m2)):
        for key in ("loss", "grad_norm", "lr"):
            a, b = float(ref_m[key]), float(m[key])
            assert abs(a - b) <= 2e-5 * max(1.0, abs(a)), (key, a, b)
    assert _max_param_err(ref_state, state) < 2e-6


@requires_8
def test_zero_opt_state_sharded_and_smaller():
    """The memory claim, from the sharding rules themselves: Adam mu/nu
    carry the joint ('data','fsdp') axis, per-chip opt-state bytes drop
    by ~data_extent vs the fsdp-only layout, and params keep their
    storage layout (shapes and specs unchanged between modes)."""
    mesh_cfg = MeshConfig(data=4, fsdp=2)
    cfg = cfg_for(mesh_cfg)
    mesh = make_mesh(mesh_cfg)
    abstract = jax.eval_shape(
        lambda: create_train_state(jax.random.PRNGKey(0), cfg))

    rep = per_chip_state_bytes(mesh, abstract, zero_update=False)
    zer = per_chip_state_bytes(mesh, abstract, zero_update=True)
    assert zer["params"] == rep["params"]
    # ~(1 - 1/data_extent) of the (already fsdp-sharded) Adam state goes
    # away; small/indivisible leaves keep a bounded replicated remainder.
    assert zer["opt_state"] <= rep["opt_state"] / 3.0, (rep, zer)

    sh = state_sharding(mesh, abstract, zero_update=True)
    mu_specs = [s.spec for s in jax.tree.leaves(sh.opt_state[1][0].mu)]
    assert any(("data", "fsdp") in tuple(s) for s in mu_specs), mu_specs
    # params specs identical to the replicated rule
    sh_rep = state_sharding(mesh, abstract, zero_update=False)
    assert ([s.spec for s in jax.tree.leaves(sh.params)]
            == [s.spec for s in jax.tree.leaves(sh_rep.params)])


@requires_8
def test_bf16_grad_reduction_error_bounded():
    """parallel.grad_reduce_dtype='bf16' now routes to the QUANTIZED
    reduce-scatter (parallel/quant.py, ISSUE 12): per-replica partial
    gradients are stochastically rounded to bf16 and exchanged at 2
    bytes/element on the wire. Measured bound (documented in
    docs/distributed.md): after two steps at lr 1e-3 the max param
    deviation from the exact fp32 path stays under 5e-4 — the
    stochastic per-PARTIAL rounding of n=8 replicas accumulates
    ~sqrt(n) of the old post-reduction cast's error, which is the
    price of the wire actually moving bf16 — while the fp32 zero path
    stays under 2e-6 (the parity test). The loss at step 1 is computed
    BEFORE any update and must match exactly (same corruption ops on
    the same key; tests/test_quant.py holds the full payload grid)."""
    mesh_cfg = MeshConfig(data=4, fsdp=2)
    batch = make_batch(cfg_for(mesh_cfg))

    ref_state, ref_m1, _ = _ref_two_steps(batch)

    cfg16 = cfg_for(mesh_cfg, parallel=ParallelConfig(
        zero_update=True, grad_reduce_dtype="bf16"))
    state, m1, m2 = _run_two_steps_zero(cfg16, batch)

    assert abs(float(m1["loss"]) - float(ref_m1["loss"])) <= 2e-5
    err = _max_param_err(ref_state, state)
    assert 0.0 < err < 5e-4, err  # rounded (not exact), and bounded


def test_grad_reduce_dtype_rejected():
    mesh_cfg = MeshConfig(data=jax.device_count())
    cfg = cfg_for(mesh_cfg, parallel=ParallelConfig(
        zero_update=True, grad_reduce_dtype="fp8"))
    mesh = make_mesh(mesh_cfg)
    state = create_train_state(jax.random.PRNGKey(0), cfg)
    grads = jax.tree.map(np.zeros_like, state.params)
    with pytest.raises(ValueError, match="grad_reduce_dtype"):
        zero_gradient_update(mesh, cfg.optimizer, state.params, grads,
                             state.opt_state, grad_reduce_dtype="fp8")


@requires_8
def test_zero_seq_parallel_step_parity():
    """The explicit shard_map sequence-parallel step with zero_update on
    (its gradient_update routed through zero_gradient_update) matches
    the replicated implicit step on the same batch."""
    from proteinbert_tpu.parallel.seq_parallel import (
        make_seq_parallel_train_step,
    )

    mesh_cfg = MeshConfig(data=2, fsdp=2, seq=2)
    cfg = cfg_for(mesh_cfg)
    batch = make_batch(cfg)

    _, ref_m = train_step(
        create_train_state(jax.random.PRNGKey(0), REF_CFG), dict(batch),
        REF_CFG)

    mesh = make_mesh(mesh_cfg)
    assert zero_extent(mesh) == 4
    state = shard_train_state(
        create_train_state(jax.random.PRNGKey(0), cfg), mesh,
        zero_update=True)
    sstep = make_seq_parallel_train_step(mesh, cfg)
    _, m = sstep(state, dict(batch))
    ref_loss, got = float(ref_m["loss"]), float(m["loss"])
    assert abs(got - ref_loss) <= 1e-4 * max(1.0, abs(ref_loss))


@requires_8
def test_zero_trainer_resume_byte_identical(tmp_path):
    """Resume across a checkpoint boundary under zero_update — with the
    OVERLAPPED (staged-snapshot) save path on — must reproduce the
    uninterrupted run bit-for-bit: params, resharded Adam moments, RNG
    key, step, and the post-resume loss stream (the acceptance
    criterion's resume gate, riding the PR-1 staged-save machinery)."""
    mesh_cfg = MeshConfig(data=4, fsdp=2)

    def build_cfg():
        cfg = cfg_for(mesh_cfg, train_kw=dict(log_every=1))
        return cfg.replace(
            train=dataclasses.replace(cfg.train, max_steps=12, log_every=1),
            checkpoint=CheckpointConfig(every_steps=4, async_save=True,
                                        overlap=True))

    cfg = build_cfg()
    mesh = make_mesh(mesh_cfg)

    def make_iter(seed=0):
        rng = np.random.default_rng(seed)
        seqs, ann = make_random_proteins(
            64, rng, num_annotations=cfg.model.num_annotations, max_len=40)
        ds = InMemoryPretrainingDataset(seqs, ann, cfg.data.seq_len)
        return lambda skip: make_pretrain_iterator(
            ds, cfg.data.batch_size, seed=0, skip_batches=skip)

    full = pretrain(cfg, make_iter(), mesh=mesh)
    assert int(full["state"].step) == 12

    # Interrupted twin: stop at 6 (checkpoint landed at 4), resume to 12.
    half_cfg = cfg.replace(
        train=dataclasses.replace(cfg.train, max_steps=6))
    ck = Checkpointer(str(tmp_path / "ck"), async_save=True)
    pretrain(half_cfg, make_iter(), checkpointer=ck, mesh=mesh)
    assert 6 in ck.all_steps()
    ck.close()

    ck2 = Checkpointer(str(tmp_path / "ck"), async_save=True)
    resumed = pretrain(cfg, make_iter(), checkpointer=ck2, mesh=mesh)
    ck2.close()
    assert int(resumed["state"].step) == 12

    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        resumed["state"], full["state"])
    full_tail = {h["step"]: h["loss"] for h in full["history"]
                 if h["step"] > 6}
    res_tail = {h["step"]: h["loss"] for h in resumed["history"]
                if h["step"] > 6}
    assert res_tail == full_tail

    # The restored mu really came back SHARDED (not replicated): its
    # per-device shard must be 1/8 of the leaf.
    mu_leaf = jax.tree.leaves(resumed["state"].opt_state[1][0].mu)[0]
    nshards = len({d.id for d in mu_leaf.sharding.device_set})
    assert nshards == 8
    shard = mu_leaf.sharding.shard_shape(mu_leaf.shape)
    assert np.prod(shard) * 8 == np.prod(mu_leaf.shape), (
        shard, mu_leaf.shape)


@requires_8
def test_zero_checkpoint_interchangeable_with_replicated(tmp_path):
    """Leaf SHAPES are mode-independent, so a replicated-mode checkpoint
    restores into a zero-sharded template (and the values match)."""
    mesh_cfg = MeshConfig(data=4, fsdp=2)
    cfg = cfg_for(mesh_cfg)
    mesh = make_mesh(mesh_cfg)
    state = create_train_state(jax.random.PRNGKey(0), cfg)

    ck = Checkpointer(str(tmp_path / "ck"), async_save=False)
    ck.save(1, jax.device_get(state))
    template = shard_train_state(state, mesh, zero_update=True)
    restored, _ = ck.restore(template)
    ck.close()
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))),
        restored, state)


@requires_8
def test_zero_with_eval_keyed_plateau(tmp_path):
    """The zero step carries the plateau_value contract natively: an
    eval-keyed plateau run under zero_update matches the replicated
    eval-keyed run loss-for-loss (schedule semantics untouched)."""
    mesh_cfg = MeshConfig(data=4, fsdp=2)

    def build(parallel):
        cfg = cfg_for(
            mesh_cfg, parallel=parallel,
            opt_kw=dict(schedule="warmup_plateau", plateau_metric="eval_loss",
                        plateau_window=2))
        return cfg.replace(train=dataclasses.replace(
            cfg.train, max_steps=6, log_every=1, eval_every=2))

    rng = np.random.default_rng(7)
    seqs, ann = make_random_proteins(32, rng, num_annotations=64, max_len=40)
    ds = InMemoryPretrainingDataset(seqs, ann, 32)
    train_it = lambda: make_pretrain_iterator(ds, 16, seed=0)  # noqa: E731
    evb = lambda: make_pretrain_iterator(  # noqa: E731
        ds, 16, shuffle=False, num_epochs=1)

    mesh = make_mesh(mesh_cfg)
    runs = {}
    for name, parallel in (("rep", ParallelConfig()),
                           ("zero", ParallelConfig(zero_update=True))):
        out = pretrain(build(parallel), train_it(), mesh=mesh,
                       eval_batches=evb)
        runs[name] = {h["step"]: h["loss"] for h in out["history"]
                      if "loss" in h}
    assert runs["rep"].keys() == runs["zero"].keys() and runs["rep"]
    for step, loss in runs["rep"].items():
        assert abs(runs["zero"][step] - loss) <= 2e-5 * max(1.0, abs(loss)), (
            step, loss, runs["zero"][step])


def test_collective_bytes_from_hlo_parses_ops():
    hlo = """
  %g = f32[128,64]{1,0} all-gather(f32[16,64]{1,0} %p), dimensions={0}
  %ags = (f32[16,8]{1,0}, f32[128,8]{1,0}) all-gather-start(f32[16,8]{1,0} %q), dimensions={0}
  %agd = f32[128,8]{1,0} all-gather-done((f32[16,8]{1,0}, f32[128,8]{1,0}) %ags)
  %ar = bf16[1024]{0} all-reduce-start(bf16[1024]{0} %x), to_apply=%sum
  %ard = bf16[1024]{0} all-reduce-done(bf16[1024]{0} %ar)
  %rs = f32[32]{0} reduce-scatter(f32[256]{0} %y), dimensions={0}
  %cp = f32[4,4]{1,0} collective-permute(f32[4,4]{1,0} %z)
  %not_a_collective = f32[8]{0} add(f32[8]{0} %a, f32[8]{0} %b)
"""
    got = collective_bytes_from_hlo(hlo)
    # tuple-shaped async start: the leading operand alias is NOT counted
    assert got["all-gather"] == 128 * 64 * 4 + 128 * 8 * 4
    assert got["all-reduce"] == 1024 * 2  # -start counted, -done not
    assert got["reduce-scatter"] == 32 * 4
    assert got["collective-permute"] == 16 * 4
    assert got["total"] == sum(
        got[k] for k in ("all-reduce", "all-gather", "reduce-scatter",
                         "all-to-all", "collective-permute"))
