"""tools/reconstruct_windows.py — the cumulative→window inversion that
attributed the r3 sustained-run collapse (BASELINE.md round-5 section).

Two tiers: a synthetic stream with a KNOWN injected slow window (the
inversion must recover it exactly), and the real committed r3 stream
(the attribution's headline numbers are pinned so a tool regression
cannot silently rewrite the evidence)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "reconstruct_windows.py")


def _run(args):
    p = subprocess.run([sys.executable, TOOL, *args],
                       capture_output=True, text=True, timeout=60)
    assert p.returncode == 0, p.stderr
    return json.loads(p.stdout)


def test_inversion_recovers_known_window_rates(tmp_path):
    # Build a cumulative stream: 10 steps/s everywhere except one
    # 25-step window that takes 25s (1 step/s), logged every 25 steps.
    path = tmp_path / "m.jsonl"
    t, lines = 0.0, []
    for s in range(25, 501, 25):
        t += 25.0 if s == 275 else 2.5  # the 251-275 window stalls
        lines.append(json.dumps({
            "step": s, "loss": 1.0, "lr": 1e-4,
            "steps_per_sec": s / t}))
    path.write_text("\n".join(lines))
    out = _run([str(path), "--log-every", "25"])
    slow = {w["step"]: w for w in out["slow_windows"]}
    assert list(slow) == [275]
    assert slow[275]["rate"] == pytest.approx(1.0, rel=1e-6)
    assert slow[275]["dt_s"] == pytest.approx(25.0, rel=1e-6)
    assert out["median_rate"] == pytest.approx(10.0, rel=1e-6)


def test_r3_collapse_attribution_is_stable():
    """The recorded r3 stream's reconstruction: every one of the nine
    in-run eval+ckpt boundaries produced a slow following window, and
    the slow windows carry ~half the run's wall time — the numbers
    BASELINE.md's round-5 attribution cites."""
    out = _run([os.path.join(REPO, "experiments", "sustained_r3",
                             "metrics.jsonl"),
                "--seam", "2600", "--cadence", "500", "--log-every", "25"])
    assert out["windows"] == 197
    assert out["median_rate"] == pytest.approx(7.89, abs=0.05)
    # All nine boundaries (525 ... 4525) flagged, none missing.
    assert out["boundary_adjacent"] == [525 + 500 * i for i in range(9)]
    assert out["slow_time_frac"] == pytest.approx(0.49, abs=0.02)
    assert out["excess_time_s"] == pytest.approx(503, abs=10)
    # The one-time post-first-boundary stretch exists in phase 1.
    slow_steps = {w["step"] for w in out["slow_windows"]}
    assert {650, 700, 750, 800} <= slow_steps
