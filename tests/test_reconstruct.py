"""tools/reconstruct_windows.py — the cumulative→window inversion that
attributed the r3 sustained-run collapse (BASELINE.md round-5 section).

Two tiers: a synthetic stream with a KNOWN injected slow window (the
inversion must recover it exactly), and the real committed r3 stream
(the attribution's headline numbers are pinned so a tool regression
cannot silently rewrite the evidence)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "reconstruct_windows.py")


def _run(args):
    p = subprocess.run([sys.executable, TOOL, *args],
                       capture_output=True, text=True, timeout=60)
    assert p.returncode == 0, p.stderr
    return json.loads(p.stdout)


def test_inversion_recovers_known_window_rates(tmp_path):
    # Build a cumulative stream: 10 steps/s everywhere except one
    # 25-step window that takes 25s (1 step/s), logged every 25 steps.
    path = tmp_path / "m.jsonl"
    t, lines = 0.0, []
    for s in range(25, 501, 25):
        t += 25.0 if s == 275 else 2.5  # the 251-275 window stalls
        lines.append(json.dumps({
            "step": s, "loss": 1.0, "lr": 1e-4,
            "steps_per_sec": s / t}))
    path.write_text("\n".join(lines))
    out = _run([str(path), "--log-every", "25"])
    slow = {w["step"]: w for w in out["slow_windows"]}
    assert list(slow) == [275]
    assert slow[275]["rate"] == pytest.approx(1.0, rel=1e-6)
    assert slow[275]["dt_s"] == pytest.approx(25.0, rel=1e-6)
    assert out["median_rate"] == pytest.approx(10.0, rel=1e-6)


def test_wall_mode_finds_t_gaps_and_detects_relog_seam(tmp_path):
    """--wall reads the recorded wall clock `t` directly: brackets the
    discounted rate stream excludes must surface as t gaps, tagged with
    cadence adjacency and the ckpt_in_flight latch. The preemption seam
    is the REALISTIC re-log shape — killed at 750, restored from the
    ckpt at 500, resumed process re-logs 525 onward — and must be
    detected from the file-order step reset and reported separately,
    never as a (boundary-adjacent!) gap. Pre-warmup records carry no
    steps_per_sec; their `t` must still bound intervals."""
    path = tmp_path / "m.jsonl"
    t, lines = 0.0, []

    def rec(s, extra=None):
        lines.append(json.dumps({
            "step": s, "loss": 1.0, "lr": 1e-4, "t": t, **(extra or {})}))

    for s in range(25, 751, 25):   # phase 1: killed after 750
        t += 2.5
        if s == 525:               # bracket after the eval/ckpt at 500
            t += 30.0
        # First log point pre-warmup: no steps_per_sec yet.
        rec(s, None if s == 25 else
            {"steps_per_sec": 10.0,
             "ckpt_in_flight": 1.0 if s == 525 else 0.0})
    t += 120.0                     # restart + restore + recompile
    for s in range(525, 1001, 25):  # phase 2 re-logs from the restore
        t += 2.5
        rec(s, {"steps_per_sec": 10.0, "ckpt_in_flight": 0.0})
    path.write_text("\n".join(lines))
    out = _run([str(path), "--wall", "--cadence", "500",
                "--log-every", "25"])
    assert [g["step"] for g in out["gaps"]] == [525]
    assert out["gaps"][0]["dt_s"] == pytest.approx(32.5, abs=0.1)
    assert out["gaps"][0]["ckpt_in_flight"] is True
    assert out["boundary_adjacent"] == [525]
    assert out["seams"] == [{"after_step": 750, "resumed_at": 525,
                             "dt_s": pytest.approx(122.5, abs=0.1)}]
    assert out["median_interval_s"] == pytest.approx(2.5, abs=0.01)
    assert out["gap_excess_s"] == pytest.approx(30.0, abs=0.1)
    # Total spans the pre-warmup first record through the last.
    assert out["total_wall_s"] == pytest.approx(
        29 * 2.5 + 30.0 + 122.5 + 19 * 2.5, abs=0.1)
    # With a reset detected, an explicit --seam must NOT re-classify
    # the resumed segment's normal crossing of the kill step.
    out2 = _run([str(path), "--wall", "--seam", "750",
                 "--cadence", "500", "--log-every", "25"])
    assert out2["gaps"] == out["gaps"]
    assert out2["seams"] == out["seams"]


def test_wall_mode_declared_monotonic_seam(tmp_path):
    """The OTHER real resume shape (the round-5 sustained run's): the
    preemption save wrote at the kill step, phase 2's steps strictly
    advance, no reset exists — the restart interval can only be kept
    out of the gap list by declaring --seam."""
    path = tmp_path / "m.jsonl"
    t, lines = 0.0, []
    for s in range(25, 1001, 25):
        t += 2.5
        if s == 625:  # restart right after the kill at 600
            t += 100.0
        lines.append(json.dumps({
            "step": s, "loss": 1.0, "lr": 1e-4, "t": t}))
    path.write_text("\n".join(lines))
    out = _run([str(path), "--wall", "--seam", "600",
                "--cadence", "500", "--log-every", "25"])
    assert out["gaps"] == []
    assert out["seams"] == [{"after_step": 600, "resumed_at": 625,
                             "dt_s": pytest.approx(102.5, abs=0.1)}]
    # Undeclared, the same stream misattributes the restart as a gap.
    out2 = _run([str(path), "--wall", "--cadence", "500",
                 "--log-every", "25"])
    assert [g["step"] for g in out2["gaps"]] == [625]


def test_wall_mode_drops_duplicate_step_records(tmp_path):
    """An adjacent record with an EQUAL step (flush retry, double
    writer) is a duplicate to drop — not a re-log reset: the old
    behavior fabricated a zero-duration seam there and split real
    intervals across it."""
    path = tmp_path / "m.jsonl"
    t, lines = 0.0, []
    for s in range(25, 501, 25):
        t += 2.5
        lines.append(json.dumps({
            "step": s, "loss": 1.0, "lr": 1e-4, "t": t}))
        if s == 250:  # the duplicated flush
            lines.append(json.dumps({
                "step": s, "loss": 1.0, "lr": 1e-4, "t": t}))
    path.write_text("\n".join(lines))
    out = _run([str(path), "--wall"])
    assert out["seams"] == []          # no fabricated seam
    assert out["intervals"] == 19      # stream uncut
    assert out["gaps"] == []
    assert out["total_wall_s"] == pytest.approx(19 * 2.5, abs=0.1)


def test_wall_mode_honors_seam_alongside_unrelated_relog_reset(tmp_path):
    """A stream can hold BOTH resume shapes: an early re-log reset and
    a later monotonic preemption. The declared --seam must be honored
    when it does not fall inside the detected between-segment span —
    the old blanket suppression misreported the monotonic restart as a
    (boundary-adjacent!) gap."""
    path = tmp_path / "m.jsonl"
    t, lines = 0.0, []

    def rec(s):
        lines.append(json.dumps({
            "step": s, "loss": 1.0, "lr": 1e-4, "t": t}))

    for s in range(25, 301, 25):   # phase 1: killed after 300
        t += 2.5
        rec(s)
    t += 80.0                      # restart; restored from ckpt at 250
    for s in range(275, 601, 25):  # phase 2 re-logs 275 onward
        t += 2.5
        rec(s)
    t += 100.0                     # monotonic preemption right after 600
    for s in range(625, 801, 25):  # phase 3 strictly advances — no reset
        t += 2.5
        rec(s)
    path.write_text("\n".join(lines))
    out = _run([str(path), "--wall", "--seam", "600"])
    # Both restarts under seams, neither in gaps.
    assert [sm["after_step"] for sm in out["seams"]] == [300, 600]
    assert out["seams"][1]["dt_s"] == pytest.approx(102.5, abs=0.1)
    assert out["gaps"] == []
    # A seam declared INSIDE the detected span is still suppressed.
    out2 = _run([str(path), "--wall", "--seam", "300"])
    assert [sm["after_step"] for sm in out2["seams"]] == [300]
    # ... and the undeclared monotonic restart now shows up as a gap —
    # the failure mode the honored --seam above exists to prevent.
    assert [g["step"] for g in out2["gaps"]] == [625]


def test_step_less_records_are_skipped_not_fatal(tmp_path):
    """Records without a step (aggregate writer lines) must be filtered
    in both modes, not raise KeyError."""
    path = tmp_path / "m.jsonl"
    t, lines = 0.0, []
    for s in range(25, 301, 25):
        t += 2.5
        lines.append(json.dumps({
            "step": s, "loss": 1.0, "lr": 1e-4, "t": t,
            "steps_per_sec": 10.0}))
        if s == 100:  # a step-less summary line mid-stream
            lines.append(json.dumps({
                "loss": 1.0, "lr": 1e-4, "t": t, "steps_per_sec": 10.0}))
    path.write_text("\n".join(lines))
    out = _run([str(path), "--wall"])
    assert out["intervals"] == 11
    out2 = _run([str(path), "--log-every", "25"])
    assert out2["windows"] == 11


def test_wall_mode_attributes_overlapped_boundaries(tmp_path):
    """Overlapped checkpoint boundaries: the hidden fetch+write seconds
    arrive as window_overlap_s on the log records; --wall must total
    them (overlapped_boundary_s) while reporting NO gap at those
    boundaries — and a gap that still carries overlap seconds keeps
    them as its attribution column."""
    path = tmp_path / "m.jsonl"
    t, lines = 0.0, []
    for s in range(25, 501, 25):
        t += 2.5
        if s == 400:
            t += 20.0  # one genuinely slow window, overlap in flight
        lines.append(json.dumps({
            "step": s, "loss": 1.0, "lr": 1e-4, "t": t,
            # boundaries at 100/200/300/400: each hid 8 s of save work
            "window_overlap_s": 8.0 if s % 100 == 0 and s <= 400 else 0.0,
            "ckpt_in_flight": 1.0 if s % 100 == 0 else 0.0}))
    path.write_text("\n".join(lines))
    out = _run([str(path), "--wall", "--cadence", "100",
                "--log-every", "25"])
    assert out["overlapped_boundary_s"] == pytest.approx(32.0, abs=0.1)
    # The overlapped boundaries at 100/200/300 produced NO gaps.
    assert [g["step"] for g in out["gaps"]] == [400]
    assert out["gaps"][0]["overlap_s"] == pytest.approx(8.0, abs=0.1)
    assert out["gaps"][0]["ckpt_in_flight"] is True


def test_r3_collapse_attribution_is_stable():
    """The recorded r3 stream's reconstruction: every one of the nine
    in-run eval+ckpt boundaries produced a slow following window, and
    the slow windows carry ~half the run's wall time — the numbers
    BASELINE.md's round-5 attribution cites."""
    out = _run([os.path.join(REPO, "experiments", "sustained_r3",
                             "metrics.jsonl"),
                "--seam", "2600", "--cadence", "500", "--log-every", "25"])
    assert out["windows"] == 197
    assert out["median_rate"] == pytest.approx(7.89, abs=0.05)
    # All nine boundaries (525 ... 4525) flagged, none missing.
    assert out["boundary_adjacent"] == [525 + 500 * i for i in range(9)]
    assert out["slow_time_frac"] == pytest.approx(0.49, abs=0.02)
    assert out["excess_time_s"] == pytest.approx(503, abs=10)
    # The one-time post-first-boundary stretch exists in phase 1.
    slow_steps = {w["step"] for w in out["slow_windows"]}
    assert {650, 700, 750, 800} <= slow_steps
