"""Explicit sequence-parallel path vs the unsharded model (SURVEY §7
stage 10): shard_map forward/gradients, distributed softmax, pre-haloed
fused-track variants — all on the 8-device CPU mesh."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from proteinbert_tpu.configs import (
    DataConfig, MeshConfig, ModelConfig, OptimizerConfig, PretrainConfig,
    TrainConfig,
)
from proteinbert_tpu.kernels import (
    fused_local_track_valid, local_track_reference,
    local_track_valid_reference, track_halo,
)
from proteinbert_tpu.models import proteinbert
from proteinbert_tpu.parallel import make_mesh
from proteinbert_tpu.parallel.seq_parallel import (
    make_seq_parallel_train_step, seq_parallel_apply,
)

requires_8 = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 (virtual) devices"
)

MODEL = ModelConfig(local_dim=16, global_dim=32, key_dim=8, num_heads=4,
                    num_blocks=2, num_annotations=64, dtype="float32")


def _inputs(key, B=4, L=128, A=64):
    kt, ka = jax.random.split(key)
    tokens = np.array(jax.random.randint(kt, (B, L), 4, 26))
    # Real padding tails so the distributed softmax's masking is exercised.
    tokens[:, L - 16:] = 0
    ann = np.asarray(
        (jax.random.uniform(ka, (B, A)) < 0.1).astype(np.float32))
    return jnp.asarray(tokens), jnp.asarray(ann)


def test_valid_reference_matches_same_padding(key):
    """Center rows of the pre-haloed VALID track == zero-padded track when
    the halo rows really are zeros."""
    kp, kx, kb = jax.random.split(key, 3)
    block = proteinbert.block_init(kp, MODEL)
    track = {k: block[k] for k in ("narrow_conv", "wide_conv", "local_ln1",
                                   "local_dense", "local_ln2")}
    x = jax.random.normal(kx, (2, 64, MODEL.local_dim))
    b = jax.random.normal(kb, (2, MODEL.local_dim))
    H = track_halo(track, 1, MODEL.wide_dilation)
    xh = jnp.pad(x, ((0, 0), (H, H), (0, 0)))
    got = local_track_valid_reference(track, xh, b, 1, MODEL.wide_dilation)
    want = local_track_reference(track, x, b, 1, MODEL.wide_dilation)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_fused_valid_kernel_parity(key):
    """Pallas pre-haloed kernel == VALID reference (with REAL halo rows)."""
    C = 128
    cfg = dataclasses.replace(MODEL, local_dim=C)
    kp, kx, kb = jax.random.split(key, 3)
    block = proteinbert.block_init(kp, cfg)
    track = {k: block[k] for k in ("narrow_conv", "wide_conv", "local_ln1",
                                   "local_dense", "local_ln2")}
    H = track_halo(track, 1, cfg.wide_dilation)
    xh = jax.random.normal(kx, (2, 64 + 2 * H, C))  # halos are real data
    b = jax.random.normal(kb, (2, C))
    got = fused_local_track_valid(track, xh, b, 1, cfg.wide_dilation, True)
    want = local_track_valid_reference(track, xh, b, 1, cfg.wide_dilation)
    assert got.shape == (2, 64, C)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@requires_8
@pytest.mark.parametrize("unroll", [1, 2], ids=["u1", "u2"])
@pytest.mark.parametrize("mesh_cfg", [
    MeshConfig(data=2, seq=4),
    MeshConfig(data=2, fsdp=2, seq=2),
], ids=["dp-sp4", "dp-fsdp-sp2"])
def test_seq_parallel_forward_parity(key, mesh_cfg, unroll):
    # unroll=2 covers scan_unroll coexisting with the per-block halo
    # exchange + distributed-softmax collectives inside shard_map.
    model = dataclasses.replace(MODEL, scan_unroll=unroll)
    mesh = make_mesh(mesh_cfg)
    params = proteinbert.init(key, model)
    tokens, ann = _inputs(jax.random.fold_in(key, 1))
    want_l, want_g = proteinbert.apply(params, tokens, ann, MODEL)
    got_l, got_g = seq_parallel_apply(mesh, params, tokens, ann, model)
    np.testing.assert_allclose(np.asarray(got_l), np.asarray(want_l),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(got_g), np.asarray(want_g),
                               rtol=2e-5, atol=2e-5)


@requires_8
@pytest.mark.parametrize("variant", ["u1", "u2", "st"])
def test_seq_parallel_gradient_parity(key, variant):
    # u2 and st run under remat-convs — the exact backward regimes the
    # bench's remat-convs-u2/-st variants execute (unrolled scan body /
    # _split_transpose'd scan under shard_map); a grad regression there
    # is invisible to the forward-parity test.
    model = dataclasses.replace(
        MODEL,
        scan_unroll=2 if variant == "u2" else 1,
        scan_split_transpose=variant == "st",
        remat=variant != "u1",
        remat_policy="full" if variant == "u1" else "convs")
    mesh = make_mesh(MeshConfig(data=2, seq=4))
    params = proteinbert.init(key, model)
    tokens, ann = _inputs(jax.random.fold_in(key, 1))

    def loss_sharded(p):
        l, g = seq_parallel_apply(mesh, p, tokens, ann, model)
        return jnp.sum(l ** 2) + jnp.sum(g ** 2)

    def loss_plain(p):
        l, g = proteinbert.apply(p, tokens, ann, model)
        return jnp.sum(l ** 2) + jnp.sum(g ** 2)

    g_sharded = jax.grad(loss_sharded)(params)
    g_plain = jax.grad(loss_plain)(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4),
        g_sharded, g_plain,
    )


@requires_8
def test_seq_parallel_train_step(key):
    """Full seq-parallel train step (with the fused Pallas local track in
    interpret mode) matches the default train step's loss."""
    from proteinbert_tpu.parallel import batch_sharding, shard_train_state
    from proteinbert_tpu.train import create_train_state, train_step

    model = dataclasses.replace(MODEL, local_dim=128, use_pallas=True)
    mesh_cfg = MeshConfig(data=2, seq=4)
    cfg = PretrainConfig(
        model=model,
        data=DataConfig(seq_len=128, batch_size=4),
        optimizer=OptimizerConfig(warmup_steps=10),
        mesh=mesh_cfg,
        train=TrainConfig(max_steps=1),
    )
    tokens, ann = _inputs(jax.random.fold_in(key, 2), B=4, L=128,
                          A=model.num_annotations)
    batch = {"tokens": np.asarray(tokens), "annotations": np.asarray(ann)}

    ref_state, ref_metrics = train_step(
        create_train_state(jax.random.PRNGKey(0), cfg), dict(batch), cfg)

    mesh = make_mesh(mesh_cfg)
    state = shard_train_state(
        create_train_state(jax.random.PRNGKey(0), cfg), mesh)
    bsh = batch_sharding(mesh)
    dbatch = {k: jax.device_put(v, bsh[k]) for k, v in batch.items()}
    step = make_seq_parallel_train_step(mesh, cfg)
    new_state, metrics = step(state, dbatch)

    assert float(metrics["loss"]) == pytest.approx(
        float(ref_metrics["loss"]), rel=1e-4)
    assert int(jax.device_get(new_state.step)) == 1
    for r, g in zip(jax.tree.leaves(ref_state.params),
                    jax.tree.leaves(new_state.params)):
        np.testing.assert_allclose(np.asarray(r),
                                   np.asarray(jax.device_get(g)), atol=1e-4)


@requires_8
def test_long_preset_miniature_h5_bucketed_seq_parallel(key, tmp_path):
    """The `long` preset's machinery end to end, miniaturized: an HDF5
    corpus with mixed lengths → counter-based crops → length-bucketed
    per-host batches → the EXPLICIT seq-parallel train step on a
    {data:2, seq:4} mesh — each emitted bucket shape must produce the
    same loss as the default (implicit-SPMD) step on the identical
    batch. Binds together the pieces the long config uses that are
    otherwise only tested separately."""
    import h5py

    from proteinbert_tpu.data.dataset import (
        HDF5PretrainingDataset, make_bucketed_iterator,
    )
    from proteinbert_tpu.train import create_train_state, train_step

    rng = np.random.default_rng(0)
    N, A = 64, MODEL.num_annotations
    seqs = []
    for i in range(N):
        n = int(rng.integers(5, 28)) if i % 2 else int(rng.integers(80, 200))
        seqs.append("".join(rng.choice(list("ACDEFGHIKLMNPQRSTVWY"), size=n)))
    path = tmp_path / "mini.h5"
    with h5py.File(path, "w") as f:
        sd = h5py.string_dtype()
        f.create_dataset("seqs", data=np.array(seqs, dtype=object), dtype=sd)
        f.create_dataset("uniprot_ids",
                         data=np.array([f"P{i}" for i in range(N)],
                                       dtype=object), dtype=sd)
        f.create_dataset("seq_lengths",
                         data=np.array([len(s) for s in seqs], np.int32))
        f.create_dataset("annotation_masks",
                         data=rng.random((N, A)) < 0.1)
        f.create_dataset("included_annotations",
                         data=np.array([f"GO:{i:07d}" for i in range(A)],
                                       dtype=object), dtype=sd)

    mesh_cfg = MeshConfig(data=2, seq=4)
    cfg = PretrainConfig(
        model=MODEL,
        data=DataConfig(seq_len=128, batch_size=4, buckets=(32, 128)),
        optimizer=OptimizerConfig(warmup_steps=10),
        mesh=mesh_cfg,
        train=TrainConfig(max_steps=4),
    )
    mesh = make_mesh(mesh_cfg)
    sstep = make_seq_parallel_train_step(mesh, cfg)

    ds = HDF5PretrainingDataset(str(path), cfg.data.seq_len, crop_seed=5)
    it = make_bucketed_iterator(ds, cfg.data.batch_size, cfg.data.buckets,
                                seed=3, num_epochs=1)
    widths_seen = set()
    for batch, _ in zip(it, range(4)):
        L = batch["tokens"].shape[1]
        widths_seen.add(L)
        ref_state = create_train_state(jax.random.PRNGKey(0), cfg)
        _, ref_m = train_step(ref_state, dict(batch), cfg)
        sp_state = create_train_state(jax.random.PRNGKey(0), cfg)
        sp_state, sp_m = sstep(sp_state, dict(batch))
        assert np.isfinite(float(sp_m["loss"]))
        np.testing.assert_allclose(float(sp_m["loss"]),
                                   float(ref_m["loss"]),
                                   rtol=1e-4, atol=1e-4)
    ds.close()
    assert widths_seen == {32, 128}, widths_seen
