"""Pins the VMEM-guard decisions across the shared pricing refactor
(kernels/vmem_budget.py, ISSUE 16).

The three historical guards (`pallas_supported`,
`pallas_segments_supported`, `pallas_attention_supported`) and the
one-pass guard (`pallas_onepass_supported`) now compose the same
primitive formulas. These tests hardcode the decisions the guards made
BEFORE the extraction on a representative shape grid — supported and
unsupported points on every rejection axis (lane alignment, tiled
ceiling, short rows, segment count, budget overflow) — so any change
to the shared arithmetic that silently flips a dispatch decision
fails here, not in a production fallback.
"""

import pytest

from proteinbert_tpu.kernels import attention as ka
from proteinbert_tpu.kernels import fused_block as fb
from proteinbert_tpu.kernels import one_pass as op
from proteinbert_tpu.kernels import vmem_budget as vb

# (local_dim, seq_len, dtype) -> decision, pinned pre-refactor.
DENSE_GRID = [
    ((128, 128, "float32"), True),
    ((128, 512, "bfloat16"), True),
    ((512, 512, "bfloat16"), True),
    ((512, 1024, "float32"), False),
    ((1024, 512, "bfloat16"), True),
    ((2048, 512, "bfloat16"), False),
    ((192, 128, "bfloat16"), False),   # not lane-aligned
    ((130, 128, "bfloat16"), False),   # not lane-aligned
    ((128, 4, "float32"), False),      # sublane-short row
    ((4096, 512, "bfloat16"), False),  # beyond the tiled ceiling
]

# (local_dim, seq_len, max_segments, dtype) -> decision.
SEGMENT_GRID = [
    ((128, 128, 4, "float32"), True),
    ((128, 512, 8, "bfloat16"), True),
    ((512, 512, 8, "bfloat16"), True),
    ((512, 1024, 8, "float32"), False),
    ((1024, 128, 2, "bfloat16"), True),
    ((1024, 512, 64, "bfloat16"), True),
    ((2048, 512, 8, "bfloat16"), False),
    ((128, 128, 0, "float32"), False),  # no segments
    ((192, 128, 4, "bfloat16"), False),
    ((128, 4, 4, "float32"), False),
]

# (local_dim, global_dim, seq_len, max_segments, key_dim, num_heads,
#  dtype) -> decision.
ATTENTION_GRID = [
    ((128, 64, 128, 4, 16, 4, "float32"), True),
    ((128, 64, 128, 1, 16, 4, "float32"), True),
    ((512, 512, 512, 8, 64, 8, "bfloat16"), True),
    ((1024, 512, 512, 8, 64, 8, "bfloat16"), True),
    ((1024, 512, 2048, 64, 64, 8, "bfloat16"), False),
    ((2048, 512, 2048, 64, 64, 8, "float32"), False),
    ((128, 60, 128, 4, 16, 4, "float32"), True),
    ((130, 64, 128, 4, 16, 4, "float32"), False),
    ((128, 64, 4, 4, 16, 4, "float32"), False),
    ((2048, 512, 2048, 200, 64, 8, "float32"), False),
]


@pytest.mark.parametrize("shape,want", DENSE_GRID)
def test_dense_guard_pinned(shape, want):
    C, L, dt = shape
    assert fb.pallas_supported(C, L, dt) is want


@pytest.mark.parametrize("shape,want", SEGMENT_GRID)
def test_segment_guard_pinned(shape, want):
    C, L, S, dt = shape
    assert fb.pallas_segments_supported(C, L, S, dt) is want


@pytest.mark.parametrize("shape,want", ATTENTION_GRID)
def test_attention_guard_pinned(shape, want):
    C, G, L, S, k, H, dt = shape
    assert ka.pallas_attention_supported(C, G, L, S, k, H, dt) is want


def test_lane_roundup_is_a_roundup():
    assert vb.lanes(1) == 128
    assert vb.lanes(128) == 128
    assert vb.lanes(129) == 256
    assert vb.lanes(192) == 256


def test_constants_reexported_under_historical_names():
    """attention.py/fused_block.py consumers keep the names they
    imported before the extraction."""
    assert fb.MAX_PALLAS_DIM == vb.MAX_PALLAS_DIM == 512
    assert fb.MAX_TILED_DIM == vb.MAX_TILED_DIM == 2048
    assert fb._LANE == vb.LANE == 128
    assert fb._VMEM_BUDGET == vb.VMEM_BUDGET == 13 * 1024 * 1024


def test_onepass_guard_composes_shared_pricing():
    """The one-pass guard prices the UNION working set: shapes whose
    two-kernel halves both fit can still overflow the fused budget
    (honest fallback), and every structural rejection axis matches the
    shared prechecks."""
    # The smoke/test shape fits.
    assert op.pallas_onepass_supported(128, 64, 128, 4, 16, 4,
                                       "float32")
    assert op.pallas_onepass_supported(128, 64, 128, 1, 16, 4,
                                       "float32")
    # Structural rejections mirror the other families.
    assert not op.pallas_onepass_supported(130, 64, 128, 4, 16, 4,
                                           "float32")
    assert not op.pallas_onepass_supported(128, 64, 4, 4, 16, 4,
                                           "float32")
    assert not op.pallas_onepass_supported(128, 64, 128, 0, 16, 4,
                                           "float32")
    assert not op.pallas_onepass_supported(128, 60, 128, 4, 16, 4,
                                           "float32")
    # One-pass has NO channel-tiled variant: beyond MAX_PALLAS_DIM it
    # must defer to the two-kernel composition even though both halves
    # individually support C=1024.
    assert fb.pallas_segments_supported(1024, 128, 2, "bfloat16")
    assert ka.pallas_attention_supported(1024, 512, 128, 2, 64, 8,
                                         "bfloat16")
    assert not op.pallas_onepass_supported(1024, 512, 128, 2, 64, 8,
                                           "bfloat16")
    # Budget overflow inside the supported structural range: fp32
    # C=512 weights alone exceed the shared budget.
    assert not op.pallas_onepass_supported(512, 512, 512, 8, 64, 8,
                                           "float32")
