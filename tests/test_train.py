"""Training-engine tests: loss decreases, schedules, checkpoint/resume.

The end-to-end smoke mirrors the reference's only integration test
(reference dummy_tests.py:96-143: synthetic proteins → full pretrain loop)
but asserts decreasing loss instead of eyeballing prints (SURVEY §4).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from proteinbert_tpu.configs import (
    DataConfig, ModelConfig, OptimizerConfig, PretrainConfig, TrainConfig,
    CheckpointConfig,
)
from proteinbert_tpu.data import InMemoryPretrainingDataset, make_pretrain_iterator
from proteinbert_tpu.train import (
    Checkpointer, create_train_state, make_schedule, pretrain, train_step,
)
from proteinbert_tpu.train.loss import pretrain_loss
from proteinbert_tpu.train.metrics import forward_flops
from tests.conftest import make_random_proteins


def smoke_cfg(max_steps=60, schedule="warmup_cosine", **model_kw):
    model = dict(
        local_dim=16, global_dim=32, key_dim=8, num_heads=4, num_blocks=2,
        num_annotations=32, dtype="float32",
    )
    model.update(model_kw)
    return PretrainConfig(
        model=ModelConfig(**model),
        data=DataConfig(seq_len=32, batch_size=8),
        optimizer=OptimizerConfig(
            learning_rate=1e-3, warmup_steps=10, schedule=schedule,
            total_steps=max_steps,
        ),
        train=TrainConfig(max_steps=max_steps, log_every=10),
    )


def make_iter(cfg, n=64, seed=0):
    rng = np.random.default_rng(seed)
    seqs, ann = make_random_proteins(
        n, rng, num_annotations=cfg.model.num_annotations, max_len=40
    )
    ds = InMemoryPretrainingDataset(seqs, ann, cfg.data.seq_len)
    return make_pretrain_iterator(ds, cfg.data.batch_size, seed=seed)


def test_loss_decreases_end_to_end():
    cfg = smoke_cfg(max_steps=60)
    out = pretrain(cfg, make_iter(cfg))
    hist = out["history"]
    assert len(hist) == 6
    first, last = hist[0]["loss"], hist[-1]["loss"]
    assert np.isfinite(first) and np.isfinite(last)
    assert last < first, f"loss did not decrease: {first} -> {last}"
    assert int(out["state"].step) == 60


def test_convergence_reaches_loss_target():
    """VERDICT r1 Weak #6: 'loss decreases' cannot catch a silent
    optimizer/corruption/loss-weighting regression that still decreases,
    just worse. Calibrated target: this config/seed settles at ~2.0 by
    step 90 (observed last-3 mean 2.00, start 4.28); the 2.4 band allows
    ~20% numeric drift but fails the historical regression modes (double
    softmax, unmasked pad loss, mis-weighted dual loss all plateau
    > 2.8 here). The reference's only integration signal is 'it runs 250
    iters' (reference dummy_tests.py:141)."""
    cfg = smoke_cfg(max_steps=150)
    out = pretrain(cfg, make_iter(cfg))
    tail = [h["loss"] for h in out["history"][-3:]]
    assert len(tail) == 3
    target = float(np.mean(tail))
    assert target < 2.4, (
        f"converged loss {target:.3f} missed the calibrated target 2.4; "
        f"history={[round(h['loss'], 3) for h in out['history']]}")


def test_loss_decreases_with_plateau_schedule():
    cfg = smoke_cfg(max_steps=40, schedule="warmup_plateau")
    out = pretrain(cfg, make_iter(cfg))
    assert out["history"][-1]["loss"] < out["history"][0]["loss"]


def test_warmup_crosses_reference_crash_point():
    """Ledger #7: the reference crashes at the warmup→plateau boundary
    (utils.py:257-264). Run past the boundary with both schedules."""
    for sched in ("warmup_cosine", "warmup_plateau"):
        cfg = smoke_cfg(max_steps=25, schedule=sched)
        cfg = cfg.replace(optimizer=cfg.optimizer.__class__(
            learning_rate=1e-3, warmup_steps=20, schedule=sched, total_steps=25,
        ))
        out = pretrain(cfg, make_iter(cfg))
        assert int(out["state"].step) == 25


def test_plateau_ignores_per_step_noise():
    """VERDICT r1 Weak #1: per-step batch loss is noisy; the plateau
    transform must not cut the LR while the WINDOWED loss is improving.
    Round-1 behavior (accumulation_size=1) cut LR 10x after any 10
    consecutive steps without a new best batch loss — routine noise."""
    from proteinbert_tpu.train.schedule import make_optimizer

    cfg = OptimizerConfig(
        learning_rate=1e-3, warmup_steps=1, schedule="warmup_plateau",
        plateau_window=20, plateau_patience=5, plateau_cooldown=5,
    )
    tx = make_optimizer(cfg)
    params = {"w": jnp.zeros(3)}
    grads = {"w": jnp.ones(3)}
    state = tx.init(params)

    def scale(state):
        return float(state[-1].scale)

    # Noisy but improving: per-step noise (std 0.2) dwarfs the per-step
    # trend (0.005), so raw best-loss tracking stalls for >patience steps
    # routinely — the round-1 failure. Windowed (/sqrt(20)) the trend
    # dominates and no window sequence plateaus.
    rng = np.random.default_rng(0)
    for t in range(300):
        loss = 3.0 - 0.005 * t + 0.2 * rng.standard_normal()
        _, state = tx.update(grads, state, params, value=jnp.float32(loss))
    assert scale(state) == 1.0, "LR was cut on noisy-but-improving loss"

    # A genuine plateau (constant loss) MUST trigger: needs patience+1
    # windows to fill and compare, plus slack for the cooldown machinery.
    for _ in range(cfg.plateau_window * (cfg.plateau_patience + 2)):
        _, state = tx.update(grads, state, params, value=jnp.float32(1.0))
    assert scale(state) == pytest.approx(cfg.plateau_factor), (
        "LR was not cut on a genuine plateau"
    )


def test_schedule_shapes():
    cfg = OptimizerConfig(learning_rate=1e-3, warmup_steps=100,
                          schedule="warmup_cosine", total_steps=1000)
    s = make_schedule(cfg)
    assert float(s(0)) == 0.0
    assert float(s(100)) == pytest.approx(1e-3, rel=1e-3)
    assert float(s(1000)) < 1e-4
    const = make_schedule(OptimizerConfig(schedule="constant", warmup_steps=10))
    assert float(const(500)) == pytest.approx(2e-4)


def test_train_step_is_deterministic():
    cfg = smoke_cfg()
    it = make_iter(cfg)
    batch = next(it)
    s1 = create_train_state(jax.random.PRNGKey(0), cfg)
    s2 = create_train_state(jax.random.PRNGKey(0), cfg)
    _, m1 = train_step(s1, batch, cfg)
    _, m2 = train_step(s2, batch, cfg)
    assert float(m1["loss"]) == float(m2["loss"])


def test_loss_masks_padding():
    """Fully-padded positions must not contribute: a batch with extra pad
    columns yields the same local loss."""
    B, L, V, A = 2, 8, 26, 16
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(B, L, V)), jnp.float32)
    tgt = jnp.asarray(rng.integers(4, V, size=(B, L)))
    w = jnp.ones((B, L))
    glogits = jnp.zeros((B, A))
    gt = jnp.zeros((B, A))
    gw = jnp.zeros((B, A))
    _, m1 = pretrain_loss(logits, glogits, {"local": tgt, "global": gt},
                          {"local": w, "global": gw})
    # add padded tail with garbage logits
    logits2 = jnp.concatenate([logits, 100 * jnp.ones((B, 4, V))], axis=1)
    tgt2 = jnp.concatenate([tgt, jnp.zeros((B, 4), tgt.dtype)], axis=1)
    w2 = jnp.concatenate([w, jnp.zeros((B, 4))], axis=1)
    _, m2 = pretrain_loss(logits2, glogits, {"local": tgt2, "global": gt},
                          {"local": w2, "global": gw})
    assert float(m1["local_loss"]) == pytest.approx(float(m2["local_loss"]), rel=1e-6)
    # zero global weight mass -> zero global loss, not NaN
    assert float(m1["global_loss"]) == 0.0


def test_checkpoint_resume(tmp_path):
    """Stop at 30, resume to 60: identical final loss to an uninterrupted
    60-step run (incl. RNG and data position — reference loses both)."""
    cfg = smoke_cfg(max_steps=60)
    ck_cfg = CheckpointConfig(every_steps=30, async_save=False)
    cfg_a = cfg.replace(checkpoint=ck_cfg, train=TrainConfig(max_steps=30, log_every=10))

    full = pretrain(cfg, make_iter(cfg))

    ck1 = Checkpointer(str(tmp_path / "ck"), async_save=False)
    pretrain(cfg_a, make_iter(cfg_a), checkpointer=ck1)
    ck1.close()

    ck2 = Checkpointer(str(tmp_path / "ck"), async_save=False)
    state = create_train_state(jax.random.PRNGKey(cfg.train.seed), cfg)
    state, data_state = ck2.restore(state)
    assert int(state.step) == 30
    assert data_state["batches_consumed"] == 30
    it = make_iter(cfg, seed=0)
    # fast-forward the data stream to the checkpointed position
    from proteinbert_tpu.data import InMemoryPretrainingDataset  # noqa
    resumed = pretrain(cfg, _skip(it, 30), state=state)
    ck2.close()
    assert float(resumed["state"].step) == 60
    np.testing.assert_allclose(
        resumed["history"][-1]["loss"], full["history"][-1]["loss"], rtol=1e-4
    )


def test_warm_start_checkpoint(tmp_path):
    """checkpoint.warm_start saves at the start step BEFORE training
    (pre-timer: the r3 collapse's one-time first-save cost, BASELINE.md
    round-5 attribution), does not disturb training numerics, and is
    skipped on resume where the start step's checkpoint already exists."""
    cfg = smoke_cfg(max_steps=20)
    ck_cfg = CheckpointConfig(every_steps=10, async_save=False,
                              warm_start=True)
    cfg_w = cfg.replace(checkpoint=ck_cfg,
                        train=TrainConfig(max_steps=20, log_every=10))

    ck = Checkpointer(str(tmp_path / "ck"), async_save=False)
    out = pretrain(cfg_w, make_iter(cfg_w), checkpointer=ck)
    # The warm save is REAL: step 0 is on disk alongside the cadenced
    # 10 and 20 (deleting the trainer's warm branch fails this line).
    assert ck.all_steps() == [0, 10, 20]
    # Warm-start must not change the training stream: same loss as the
    # plain run with no checkpointer at all.
    plain = pretrain(cfg, make_iter(cfg))
    np.testing.assert_allclose(out["history"][-1]["loss"],
                               plain["history"][-1]["loss"], rtol=1e-5)
    ck.close()

    # Resume: restore at 20 and extend; the warm save is SKIPPED (the
    # directory is not pristine — and orbax silently no-ops saves at
    # step <= latest anyway) and the run completes with no step-20
    # re-save or other extra checkpoint.
    ck2 = Checkpointer(str(tmp_path / "ck"), async_save=False)
    cfg_more = cfg_w.replace(train=TrainConfig(max_steps=30, log_every=10))
    out2 = pretrain(cfg_more, lambda skip: _skip(make_iter(cfg_more), skip),
                    checkpointer=ck2)
    assert int(out2["state"].step) == 30
    # No new step-0/20 write appeared; the warm save participates in
    # normal retention (max_to_keep=3 evicts it once 30 lands) — its
    # job is timing, not retention.
    assert sorted(ck2.all_steps()) == [10, 20, 30]
    ck2.close()


@pytest.mark.parametrize("schedule", ["warmup_cosine", "warmup_plateau"])
def test_checkpoint_resume_is_exact_with_cropping(tmp_path, schedule):
    """VERDICT r1 Weak #3, end to end: with LONG sequences re-cropped per
    epoch (crop_seed), a run resumed through the orbax checkpointer must
    reproduce the uninterrupted run EXACTLY — bit-equal losses, not just
    close. Counter-based windows + checkpointed RNG + replayed epoch
    permutations make every post-resume batch byte-identical. The
    plateau variant additionally pins the reduce_on_plateau state
    (windowed average, counters, scale) through the orbax round trip."""
    cfg = smoke_cfg(max_steps=20, schedule=schedule)
    cfg = cfg.replace(train=TrainConfig(max_steps=20, log_every=1))
    rng = np.random.default_rng(3)
    # All sequences longer than seq_len-2 -> every row takes a crop window.
    seqs = ["".join(rng.choice(list("ACDEFGHIKLMNPQRSTVWY"), size=80))
            for _ in range(32)]
    ann = (rng.random((32, cfg.model.num_annotations)) < 0.05).astype(np.float32)

    def fresh_iter(skip=0):
        ds = InMemoryPretrainingDataset(seqs, ann, cfg.data.seq_len,
                                        crop_seed=7)
        return make_pretrain_iterator(ds, cfg.data.batch_size, seed=1,
                                      skip_batches=skip)

    full = pretrain(cfg, fresh_iter())

    cfg_a = cfg.replace(train=TrainConfig(max_steps=12, log_every=1),
                        checkpoint=CheckpointConfig(every_steps=12,
                                                    async_save=False))
    ck1 = Checkpointer(str(tmp_path / "ck"), async_save=False)
    partial = pretrain(cfg_a, fresh_iter(), checkpointer=ck1)
    ck1.close()

    ck2 = Checkpointer(str(tmp_path / "ck"), async_save=False)
    state = create_train_state(jax.random.PRNGKey(cfg.train.seed), cfg)
    state, data_state = ck2.restore(state)
    # EVERY leaf of the state — params, Adam moments, schedule/plateau
    # counters, RNG key — must round-trip bit-exactly; the loss check
    # below can't see e.g. a corrupted plateau accumulator while the LR
    # scale is still 1.0.
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        partial["state"], state)
    resumed = pretrain(cfg, fresh_iter(data_state["batches_consumed"]),
                       state=state)
    ck2.close()

    full_tail = {h["step"]: h["loss"] for h in full["history"]
                 if h["step"] > 12}
    res_tail = {h["step"]: h["loss"] for h in resumed["history"]}
    assert set(res_tail) == set(full_tail)
    for step, loss in full_tail.items():
        assert res_tail[step] == loss, (
            f"step {step}: resumed {res_tail[step]} != full {loss}")


def _skip(it, n):
    for _ in range(n):
        next(it)
    return it


def test_auto_resume_uses_data_position(tmp_path):
    """pretrain(checkpointer=...) with an iterator FACTORY must restore
    the state AND fast-forward the data stream — matching an
    uninterrupted run exactly."""
    cfg = smoke_cfg(max_steps=60)
    ck_cfg = CheckpointConfig(every_steps=30, async_save=False)
    cfg_a = cfg.replace(checkpoint=ck_cfg,
                        train=TrainConfig(max_steps=30, log_every=10))
    cfg_b = cfg.replace(checkpoint=ck_cfg,
                        train=TrainConfig(max_steps=60, log_every=10))

    full = pretrain(cfg, make_iter(cfg))

    factory = lambda skip: make_pretrain_iterator(  # noqa: E731
        _make_ds(cfg), cfg.data.batch_size, seed=0, skip_batches=skip)
    ck1 = Checkpointer(str(tmp_path / "ck"), async_save=False)
    pretrain(cfg_a, factory, checkpointer=ck1)
    ck1.close()

    ck2 = Checkpointer(str(tmp_path / "ck"), async_save=False)
    resumed = pretrain(cfg_b, factory, checkpointer=ck2)
    ck2.close()
    assert int(resumed["state"].step) == 60
    np.testing.assert_allclose(
        resumed["history"][-1]["loss"], full["history"][-1]["loss"], rtol=1e-4)


def _make_ds(cfg, n=64, seed=0):
    rng = np.random.default_rng(seed)
    seqs, ann = make_random_proteins(
        n, rng, num_annotations=cfg.model.num_annotations, max_len=40)
    return InMemoryPretrainingDataset(seqs, ann, cfg.data.seq_len)


def test_checkpoint_restore_without_data_item(tmp_path):
    """save(step, state) with no data_state is documented-optional;
    restore must not crash on the missing 'data' item."""
    cfg = smoke_cfg(max_steps=5)
    state = create_train_state(jax.random.PRNGKey(0), cfg)
    ck = Checkpointer(str(tmp_path / "ck"), async_save=False)
    ck.save(5, state)
    restored, data_state = ck.restore(state)
    ck.close()
    assert data_state is None
    np.testing.assert_array_equal(
        np.asarray(restored.step), np.asarray(state.step))


def test_iterator_skip_batches_matches_manual_skip():
    cfg = smoke_cfg()
    it_a = make_iter(cfg)
    for _ in range(5):
        next(it_a)
    a = next(it_a)
    rng = np.random.default_rng(0)
    seqs, ann = make_random_proteins(64, rng, num_annotations=32, max_len=40)
    ds = InMemoryPretrainingDataset(seqs, ann, cfg.data.seq_len)
    it_b = make_pretrain_iterator(ds, cfg.data.batch_size, seed=0, skip_batches=5)
    b = next(it_b)
    np.testing.assert_array_equal(a["tokens"], b["tokens"])


def test_flops_model_positive_and_monotone():
    cfg = smoke_cfg().model
    f1 = forward_flops(cfg, batch=8, seq_len=32)
    f2 = forward_flops(cfg, batch=8, seq_len=64)
    assert 0 < f1 < f2


def _fake_clock(monkeypatch):
    """Deterministic perf_counter for StepTimer tests: each update()
    costs 10 ms of 'virtual' time; stalls are explicit advances. No
    real sleeps -> no scheduler-noise flakes on a loaded host."""
    import proteinbert_tpu.train.metrics as metrics_mod

    clock = {"now": 0.0}
    monkeypatch.setattr(metrics_mod.time, "perf_counter",
                        lambda: clock["now"])

    def advance(seconds):
        clock["now"] += seconds

    return advance


def test_step_timer_sync_extends_window(monkeypatch):
    # Async dispatch: update() timestamps measure host enqueue rate.
    # sync() (called after the log-point device fetch) must fold the
    # fetch wait into the window so reported throughput is device rate,
    # not enqueue rate — the tunneled backend otherwise logs MFUs > 1.
    from proteinbert_tpu.train.metrics import StepTimer

    advance = _fake_clock(monkeypatch)

    def step(t):
        advance(0.01)
        t.update()

    timer = StepTimer(smoke_cfg().model, batch=8, seq_len=32)
    for _ in range(4):  # 2 warmup + 2 timed "enqueues"
        step(timer)
    fast = timer.summary()["step_ms"]
    assert fast == pytest.approx(10.0)
    advance(0.3)  # the device drain the float() fetch waits on
    timer.sync()
    synced = timer.summary()["step_ms"]
    assert synced == pytest.approx(fast + 150.0)  # 300 ms over 2 steps
    # sync before timing starts must be a no-op, not a crash
    fresh = StepTimer(smoke_cfg().model, batch=8, seq_len=32)
    fresh.sync()
    assert fresh.summary() == {}
    # A drain at the warmup boundary (t0 set, nothing timed yet) waits
    # on compile/warmup backlog — it must re-anchor the window START,
    # not charge that wait to the first timed window.
    warm = StepTimer(smoke_cfg().model, batch=8, seq_len=32)
    step(warm), step(warm)  # warmup done, t0 anchored at enqueue
    advance(0.3)  # the log-point fetch draining compile backlog
    warm.sync()
    step(warm), step(warm)
    assert warm.summary()["step_ms"] == pytest.approx(10.0)


def test_device_metric_accumulator():
    """Batched-drain accumulation: sums match per-batch float() exactly,
    weights and key renames apply, and pending buffers stay bounded by
    drain_every (the memory/backpressure contract)."""
    from proteinbert_tpu.train.metrics import DeviceMetricAccumulator

    acc = DeviceMetricAccumulator(drain_every=4)
    expect = {}
    for i in range(11):
        m = {"loss": jnp.float32(i * 0.5), "acc": jnp.float32(i)}
        w = 1.0 + (i % 3)
        acc.add(m, weight=w, key_fn=lambda k: f"x_{k}")
        for k, v in m.items():
            expect[f"x_{k}"] = expect.get(f"x_{k}", 0.0) + float(v) * w
        assert len(acc._pending) < 4  # drained at the stride, not hoarded
    got = acc.sums()
    assert acc.count == 11
    for k, v in expect.items():
        assert got[k] == pytest.approx(v, rel=1e-12)
    # Idempotent final drain.
    assert acc.sums() == got


def test_step_timer_window_rate_recovers_after_stall(monkeypatch):
    """VERDICT r3 Weak #2: the cumulative rate re-reports a transient
    stall forever; the window_* rate must cover only the steps since the
    last summary() so a live operator can tell 'currently slow' from
    'was slow once'."""
    from proteinbert_tpu.train.metrics import StepTimer

    advance = _fake_clock(monkeypatch)

    def step(t):
        advance(0.01)
        t.update()

    timer = StepTimer(smoke_cfg().model, batch=8, seq_len=32)
    for _ in range(4):  # 2 warmup + 2 timed
        step(timer)
    advance(0.4)  # a transient stall inside the first window
    timer.sync()
    first = timer.summary()
    assert first["window_step_ms"] == pytest.approx(210.0)  # stall in w1
    # Next window: fast steps only — the window rate must recover while
    # the cumulative rate stays depressed by the old stall.
    step(timer), step(timer)
    second = timer.summary()
    assert second["window_step_ms"] == pytest.approx(10.0)
    assert second["step_ms"] == pytest.approx(110.0)  # carries the stall
    assert second["window_steps_per_sec"] > second["steps_per_sec"]
    # An eval/save discount inside a window must not be charged to it
    # (trainer order: steps, eval bracket + discount, more steps, log).
    step(timer), step(timer)
    advance(0.3)  # the eval bracket
    timer.discount(0.3)
    step(timer), step(timer)
    third = timer.summary()
    assert third["window_step_ms"] == pytest.approx(10.0)
    # Back-to-back summary() (trainer's final perf right after a log
    # point): zero new steps -> no window keys, cumulative intact.
    fourth = timer.summary()
    assert "window_step_ms" not in fourth and "step_ms" in fourth


def test_pretrain_with_eval_split():
    """Held-out eval wired through the trainer (reference C8's train/test
    split, completed): eval_* records appear at eval_every cadence and
    are deterministic run-to-run."""
    from proteinbert_tpu.configs import (
        DataConfig, ModelConfig, OptimizerConfig, PretrainConfig, TrainConfig,
    )
    from proteinbert_tpu.data import (
        InMemoryPretrainingDataset, make_pretrain_iterator, train_eval_split,
    )
    from proteinbert_tpu.data.synthetic import make_random_proteins
    from proteinbert_tpu.train.trainer import pretrain

    rng = np.random.default_rng(0)
    seqs, ann = make_random_proteins(96, rng, num_annotations=64)
    ds = InMemoryPretrainingDataset(seqs, ann, 64)
    train_ds, eval_ds = train_eval_split(ds, 0.25, seed=0)
    assert len(train_ds) + len(eval_ds) == 96 and len(eval_ds) == 24

    cfg = PretrainConfig(
        model=ModelConfig(local_dim=16, global_dim=32, key_dim=8,
                          num_heads=4, num_blocks=1, num_annotations=64,
                          dtype="float32"),
        data=DataConfig(seq_len=64, batch_size=8),
        optimizer=OptimizerConfig(warmup_steps=4),
        train=TrainConfig(max_steps=6, log_every=0, eval_every=3),
    )

    def run():
        return pretrain(
            cfg,
            make_pretrain_iterator(train_ds, 8, seed=0),
            eval_batches=lambda: make_pretrain_iterator(
                eval_ds, 8, shuffle=False, num_epochs=1),
        )

    hist = run()["history"]
    evals = [h for h in hist if "eval_loss" in h]
    assert [h["step"] for h in evals] == [3, 6]
    assert all(np.isfinite(h["eval_loss"]) for h in evals)
    evals2 = [h for h in run()["history"] if "eval_loss" in h]
    assert evals[0]["eval_loss"] == evals2[0]["eval_loss"]  # deterministic


def test_eval_keyed_plateau_transform_wiring():
    """plateau_metric='eval_loss' (VERDICT r3 Weak #5): the transform
    must cut the LR scale when the observed value stalls and must not
    when it keeps improving — independent of the (train) loss used for
    gradients."""
    import jax.numpy as jnp

    from proteinbert_tpu.configs import OptimizerConfig
    from proteinbert_tpu.train.schedule import (
        make_optimizer, plateau_uses_eval,
    )

    cfg = OptimizerConfig(schedule="warmup_plateau", warmup_steps=0,
                          plateau_window=2, plateau_patience=2,
                          plateau_cooldown=0, plateau_factor=0.5,
                          plateau_metric="eval_loss")
    assert plateau_uses_eval(cfg)

    def run(values):
        tx = make_optimizer(cfg)
        params = {"w": jnp.ones(3)}
        st = tx.init(params)
        for v in values:
            _, st = tx.update({"w": jnp.ones(3)}, st, params,
                              value=jnp.float32(v))
        return float(st[-1].scale)

    # Constant eval loss: window 1 sets the baseline, windows 2-3 stall
    # -> 0.5 cut lands within 6 updates (and chains if the stall holds).
    assert run([1.0] * 8) == 0.5
    # Strictly improving eval loss: never cut.
    assert run([1.0 - 0.05 * i for i in range(12)]) == 1.0

    import pytest

    with pytest.raises(ValueError, match="plateau_metric"):
        plateau_uses_eval(OptimizerConfig(plateau_metric="bogus"))


def _early_stop_cfg(**train_kw):
    from proteinbert_tpu.configs import (
        DataConfig, ModelConfig, OptimizerConfig, PretrainConfig, TrainConfig,
    )

    train_kw.setdefault("log_every", 0)
    return PretrainConfig(
        model=ModelConfig(local_dim=16, global_dim=32, key_dim=8,
                          num_heads=4, num_blocks=1, num_annotations=64,
                          dtype="float32"),
        data=DataConfig(seq_len=64, batch_size=8),
        optimizer=OptimizerConfig(warmup_steps=2),
        train=TrainConfig(**train_kw),
    )


def test_early_stop_on_eval_stall(tmp_path):
    """train.early_stop_patience: a run whose eval cannot improve (the
    min_delta bar is unreachable) must checkpoint and stop at the
    patience-th stalled eval, not grind to max_steps — the r3 sustained
    run overfit for 1,500 steps with no hook to stop it."""
    from proteinbert_tpu.data import (
        InMemoryPretrainingDataset, make_pretrain_iterator, train_eval_split,
    )
    from proteinbert_tpu.data.synthetic import make_random_proteins
    from proteinbert_tpu.train.checkpoint import Checkpointer
    from proteinbert_tpu.train.trainer import pretrain

    rng = np.random.default_rng(0)
    seqs, ann = make_random_proteins(96, rng, num_annotations=64)
    train_ds, eval_ds = train_eval_split(
        InMemoryPretrainingDataset(seqs, ann, 64), 0.25, seed=0)
    cfg = _early_stop_cfg(max_steps=40, eval_every=3,
                          early_stop_patience=2,
                          early_stop_min_delta=1e9)  # unreachable bar
    ckpt = Checkpointer(str(tmp_path / "ckpt"), async_save=False)
    out = pretrain(
        cfg, make_pretrain_iterator(train_ds, 8, seed=0),
        checkpointer=ckpt,
        eval_batches=lambda: make_pretrain_iterator(
            eval_ds, 8, shuffle=False, num_epochs=1))
    # Eval 1 (step 3) sets best; evals 2-3 (steps 6, 9) stall -> stop.
    assert out["early_stopped"] and not out["preempted"]
    assert int(out["state"].step) == 9 < cfg.train.max_steps
    assert ckpt.latest_step() == 9  # state preserved at the stop point
    ckpt.close()


def test_ckpt_in_flight_flag_logged(tmp_path):
    """Every logged train record carries the async-save-in-flight flag
    when a checkpointer is attached (the attribution signal for slow
    windows); absent without one."""
    from proteinbert_tpu.data import InMemoryPretrainingDataset, \
        make_pretrain_iterator
    from proteinbert_tpu.data.synthetic import make_random_proteins
    from proteinbert_tpu.train.checkpoint import Checkpointer
    from proteinbert_tpu.train.trainer import pretrain

    rng = np.random.default_rng(0)
    seqs, ann = make_random_proteins(32, rng, num_annotations=64)
    ds = InMemoryPretrainingDataset(seqs, ann, 64)
    cfg = _early_stop_cfg(max_steps=4, log_every=1)
    ck = Checkpointer(str(tmp_path / "ck"), async_save=False)
    out = pretrain(cfg, make_pretrain_iterator(ds, 8, seed=0),
                   checkpointer=ck)
    ck.close()
    train_recs = [h for h in out["history"] if "loss" in h]
    assert train_recs and all("ckpt_in_flight" in r for r in train_recs)
    # Default cadence (1000) means no periodic save in 4 steps.
    assert all(r["ckpt_in_flight"] == 0.0 for r in train_recs)
    out2 = pretrain(cfg, make_pretrain_iterator(ds, 8, seed=0))
    assert all("ckpt_in_flight" not in h for h in out2["history"])

    # The latch: a save at step 2 must flag the NEXT log record (step 3)
    # even if the (async) save already finished — a point sample at the
    # log instant would report the r3-style save-contended window clean.
    from proteinbert_tpu.configs import CheckpointConfig

    cfg2 = cfg.replace(checkpoint=CheckpointConfig(
        directory=str(tmp_path / "ck2"), every_steps=2, async_save=True))
    ck2 = Checkpointer(str(tmp_path / "ck2"), async_save=True)
    out3 = pretrain(cfg2, make_pretrain_iterator(ds, 8, seed=0),
                    checkpointer=ck2)
    ck2.close()
    flags = {h["step"]: h["ckpt_in_flight"] for h in out3["history"]
             if "loss" in h}
    assert flags[3] == 1.0  # window containing the step-2 save
    assert flags[2] == 0.0  # stamped before that save starts


def test_eval_stream_state_survives_resume(tmp_path):
    """The early-stop baseline and the plateau's observed eval loss are
    checkpointed: a preempt/requeue loop must not reset the patience
    counter (each requeue would otherwise register its first eval as an
    'improvement' over a fresh +inf and the run could never stop), and
    the post-resume steps must keep feeding the LAST eval loss — not
    fall back to train loss — into the restored plateau state."""
    import dataclasses

    from proteinbert_tpu.data import (
        InMemoryPretrainingDataset, make_pretrain_iterator, train_eval_split,
    )
    from proteinbert_tpu.data.synthetic import make_random_proteins
    from proteinbert_tpu.train.checkpoint import Checkpointer
    from proteinbert_tpu.train.trainer import pretrain

    rng = np.random.default_rng(0)
    seqs, ann = make_random_proteins(96, rng, num_annotations=64)
    train_ds, eval_ds = train_eval_split(
        InMemoryPretrainingDataset(seqs, ann, 64), 0.25, seed=0)
    evb = lambda: make_pretrain_iterator(  # noqa: E731
        eval_ds, 8, shuffle=False, num_epochs=1)
    factory = lambda skip: make_pretrain_iterator(  # noqa: E731
        train_ds, 8, seed=0, skip_batches=skip)

    # Segment 1: the seed eval (step 0) claims the best-loss baseline,
    # then the two cadenced evals (steps 3, 6) both stall under the
    # unreachable min_delta bar; patience 3 keeps the run alive.
    cfg = _early_stop_cfg(max_steps=6, eval_every=3,
                          early_stop_patience=3, early_stop_min_delta=1e9)
    cfg = cfg.replace(optimizer=dataclasses.replace(
        cfg.optimizer, schedule="warmup_plateau",
        plateau_metric="eval_loss", plateau_window=3))
    ck = Checkpointer(str(tmp_path / "ckpt"), async_save=False)
    out1 = pretrain(cfg, factory, checkpointer=ck, eval_batches=evb)
    assert not out1["early_stopped"]
    # The seed eval is recorded in history at the start step.
    assert [h for h in out1["history"] if "eval_loss" in h][0]["step"] == 0
    _, ds1 = ck.restore(out1["state"])
    es = ds1["eval_stream"]
    assert es["stalled"] == 2 and es["best"] is not None
    assert es["last"] == pytest.approx(
        [h for h in out1["history"] if "eval_loss" in h][-1]["eval_loss"])

    # Segment 2 (the requeue): max_steps extended. last_eval_loss is
    # restored finite, so NO second seed eval runs; with the restored
    # baseline (best set, stalled=2) the eval at step 9 reaches
    # patience 3 -> stop at step 9. A reset baseline would count the
    # step-9 eval as an improvement over fresh +inf and run much longer.
    cfg2 = cfg.replace(train=dataclasses.replace(cfg.train, max_steps=20))
    out2 = pretrain(cfg2, factory, checkpointer=ck, eval_batches=evb)
    assert out2["early_stopped"]
    assert int(out2["state"].step) == 9
    assert not any(h["step"] == 6 and "eval_loss" in h
                   for h in out2["history"])  # no re-seed on resume
    ck.close()


def test_early_stop_and_eval_plateau_require_eval_stream():
    import pytest

    from proteinbert_tpu.data import (
        InMemoryPretrainingDataset, make_pretrain_iterator,
    )
    from proteinbert_tpu.data.synthetic import make_random_proteins
    from proteinbert_tpu.train.trainer import pretrain

    rng = np.random.default_rng(0)
    seqs, ann = make_random_proteins(32, rng, num_annotations=64)
    ds = InMemoryPretrainingDataset(seqs, ann, 64)

    cfg = _early_stop_cfg(max_steps=4, early_stop_patience=1)
    with pytest.raises(ValueError, match="early_stop_patience"):
        pretrain(cfg, make_pretrain_iterator(ds, 8, seed=0))

    import dataclasses

    cfg = _early_stop_cfg(max_steps=4)
    cfg = cfg.replace(optimizer=dataclasses.replace(
        cfg.optimizer, schedule="warmup_plateau",
        plateau_metric="eval_loss"))
    with pytest.raises(ValueError, match="plateau_metric"):
        pretrain(cfg, make_pretrain_iterator(ds, 8, seed=0))


def test_eval_keyed_plateau_end_to_end_cut():
    """Through the trainer: with a near-zero LR the eval loss cannot
    move, so the eval-keyed plateau must cut the LR scale within the
    run; the per-step history `lr` reflects the cut."""
    import dataclasses

    from proteinbert_tpu.data import (
        InMemoryPretrainingDataset, make_pretrain_iterator, train_eval_split,
    )
    from proteinbert_tpu.data.synthetic import make_random_proteins
    from proteinbert_tpu.train.trainer import pretrain

    rng = np.random.default_rng(0)
    seqs, ann = make_random_proteins(96, rng, num_annotations=64)
    train_ds, eval_ds = train_eval_split(
        InMemoryPretrainingDataset(seqs, ann, 64), 0.25, seed=0)
    cfg = _early_stop_cfg(max_steps=14, eval_every=2, log_every=1)
    cfg = cfg.replace(optimizer=dataclasses.replace(
        cfg.optimizer, schedule="warmup_plateau", plateau_metric="eval_loss",
        learning_rate=1e-12,  # frozen in effect: eval loss cannot improve
        warmup_steps=0, plateau_window=2, plateau_patience=2,
        plateau_cooldown=0, plateau_factor=0.5))
    out = pretrain(
        cfg, make_pretrain_iterator(train_ds, 8, seed=0),
        eval_batches=lambda: make_pretrain_iterator(
            eval_ds, 8, shuffle=False, num_epochs=1))
    assert float(out["state"].opt_state[-1].scale) < 1.0
    lrs = [h["lr"] for h in out["history"] if "lr" in h]
    assert lrs[-1] < lrs[0]  # the cut is visible in the logged LR


# ------------------------------------------- overlapped checkpoint boundaries

class _SlowStager(Checkpointer):
    """Checkpointer whose staged device→host fetch takes `delay` seconds
    — makes 'a snapshot is in flight while training advances' a
    certainty instead of a race, so the overlap invariants (no torn
    snapshot, flush-before-exit, backpressure) are actually exercised."""

    def __init__(self, *a, delay=0.0, **kw):
        super().__init__(*a, **kw)
        self.delay = delay
        self.fetch_done_at = []

    def _stage_fetch(self, snapshot):
        import time

        time.sleep(self.delay)
        out = super()._stage_fetch(snapshot)
        self.fetch_done_at.append(time.perf_counter())
        return out


def _interrupting_factory(cfg, at_batch, fired):
    """Batch-iterator factory that SIGTERMs the process while producing
    batch `at_batch` of a fresh (skip=0) stream — the in-process stand-in
    for a preemption landing mid-run."""
    import signal
    import time

    def factory(skip):
        it = make_iter(cfg, seed=0)
        for _ in range(skip):
            next(it)

        def gen():
            for i, b in enumerate(it):
                if skip == 0 and i == at_batch:
                    fired["t"] = time.perf_counter()
                    signal.raise_signal(signal.SIGTERM)
                yield b

        return gen()

    return factory


def test_overlapped_ckpt_interrupt_mid_overlap_resumes_byte_identical(tmp_path):
    """Kill the run while a staged snapshot is STILL IN FLIGHT: the
    preemption path must flush the stage to disk before exiting, and the
    resumed run must be byte-identical (losses, eval stream, final
    state) to an uninterrupted one — RNG, data position, and eval-stream
    state all survive the overlapped boundary."""
    import dataclasses

    cfg = smoke_cfg(max_steps=30)
    cfg = cfg.replace(
        train=dataclasses.replace(cfg.train, log_every=1, eval_every=5),
        checkpoint=CheckpointConfig(every_steps=10, async_save=True))
    eval_rng = np.random.default_rng(9)
    eval_seqs, eval_ann = make_random_proteins(
        16, eval_rng, num_annotations=cfg.model.num_annotations, max_len=40)
    eval_ds = InMemoryPretrainingDataset(eval_seqs, eval_ann,
                                         cfg.data.seq_len)
    evb = lambda: make_pretrain_iterator(  # noqa: E731
        eval_ds, cfg.data.batch_size, shuffle=False, num_epochs=1)

    full = pretrain(cfg, make_iter(cfg), eval_batches=evb)

    fired = {}
    ck = _SlowStager(str(tmp_path / "ck"), delay=1.0, async_save=True)
    out1 = pretrain(cfg, _interrupting_factory(cfg, 14, fired),
                    checkpointer=ck, eval_batches=evb)
    assert out1["preempted"]
    kill_step = int(out1["state"].step)
    assert 10 < kill_step < 20  # landed while the step-10 stage ran
    # The stage WAS in flight at the interrupt (fetch completed after
    # the signal fired) and still landed on disk before exit.
    assert ck.fetch_done_at and fired["t"] < ck.fetch_done_at[0]
    assert 10 in ck.all_steps() and kill_step in ck.all_steps()
    ck.close()

    ck2 = Checkpointer(str(tmp_path / "ck"), async_save=True)
    resumed = pretrain(cfg, lambda skip: _skip(make_iter(cfg), skip),
                       checkpointer=ck2, eval_batches=evb)
    ck2.close()
    assert int(resumed["state"].step) == 30
    # Bit-equal final state: params, Adam moments, RNG key, step.
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        resumed["state"], full["state"])
    # Bit-equal post-kill history: train losses AND eval records.
    def tail(hist, key):
        return {h["step"]: h[key] for h in hist
                if key in h and h["step"] > kill_step}
    for key in ("loss", "eval_loss"):
        want, got = tail(full["history"], key), tail(resumed["history"], key)
        assert set(got) == set(want) and want, key
        for s, v in want.items():
            assert got[s] == v, f"{key}@{s}: resumed {got[s]} != full {v}"


def test_staged_save_observes_boundary_state_not_torn(tmp_path):
    """The staged snapshot must capture the BOUNDARY step's state even
    though training advances (and donates the live buffers) while the
    device→host fetch sleeps: the overlapped run's step-10 checkpoint
    is bit-equal to a synchronous run's step-10 checkpoint."""
    import dataclasses

    cfg = smoke_cfg(max_steps=20)
    cfg_over = cfg.replace(
        train=dataclasses.replace(cfg.train, log_every=0),
        checkpoint=CheckpointConfig(every_steps=10, async_save=True))
    cfg_sync = cfg_over.replace(
        train=dataclasses.replace(cfg_over.train, max_steps=10),
        checkpoint=CheckpointConfig(every_steps=10, async_save=False,
                                    overlap=False))

    ck_a = _SlowStager(str(tmp_path / "over"), delay=0.5, async_save=True)
    out = pretrain(cfg_over, make_iter(cfg_over), checkpointer=ck_a)
    assert 10 in ck_a.all_steps()
    # The hidden fetch+write seconds are REPORTED, not bookkept away.
    assert out["perf"].get("overlap_s", 0.0) > 0.0
    ck_a.close()

    ck_b = Checkpointer(str(tmp_path / "sync"), async_save=False)
    pretrain(cfg_sync, make_iter(cfg_sync), checkpointer=ck_b)
    ck_b.close()

    template = create_train_state(jax.random.PRNGKey(cfg.train.seed), cfg)
    ck_a2 = Checkpointer(str(tmp_path / "over"))
    st_over, ds_over = ck_a2.restore(template, step=10)
    ck_a2.close()
    ck_b2 = Checkpointer(str(tmp_path / "sync"))
    st_sync, ds_sync = ck_b2.restore(template, step=10)
    ck_b2.close()
    assert ds_over["batches_consumed"] == ds_sync["batches_consumed"] == 10
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        st_over, st_sync)


def test_staged_save_error_propagates(tmp_path):
    """A stager failure (disk full, serialization bug) must surface in
    the train loop — at the next boundary/flush — never be swallowed."""
    import dataclasses

    class _BrokenStager(Checkpointer):
        def _stage_fetch(self, snapshot):
            raise RuntimeError("staged fetch exploded")

    cfg = smoke_cfg(max_steps=12)
    cfg = cfg.replace(
        train=dataclasses.replace(cfg.train, log_every=0),
        checkpoint=CheckpointConfig(every_steps=5, async_save=True))
    ck = _BrokenStager(str(tmp_path / "ck"), async_save=True)
    with pytest.raises(RuntimeError, match="staged fetch exploded"):
        pretrain(cfg, make_iter(cfg), checkpointer=ck)
    ck._staged = None  # the failure is consumed; close() must not re-raise
    ck.close()


def test_step_timer_overlap_accounting(monkeypatch):
    """overlap() records hidden boundary seconds WITHOUT moving the
    timing anchors (the wall clock never stopped for them): rates are
    unchanged, summary() reports cumulative overlap_s and a per-window
    window_overlap_s that resets each summary."""
    from proteinbert_tpu.train.metrics import StepTimer

    advance = _fake_clock(monkeypatch)

    def step(t):
        advance(0.01)
        t.update()

    timer = StepTimer(smoke_cfg().model, batch=8, seq_len=32)
    for _ in range(4):  # 2 warmup + 2 timed
        step(timer)
    timer.overlap(0.7)  # a staged save that ran hidden
    first = timer.summary()
    assert first["step_ms"] == pytest.approx(10.0)  # anchors untouched
    assert first["overlap_s"] == pytest.approx(0.7)
    assert first["window_overlap_s"] == pytest.approx(0.7)
    step(timer), step(timer)
    second = timer.summary()
    assert second["overlap_s"] == pytest.approx(0.7)  # cumulative
    assert second["window_overlap_s"] == 0.0          # window reset
    assert second["window_step_ms"] == pytest.approx(10.0)
    # Before any overlap is recorded the keys are absent (records from
    # pre-overlap runs stay byte-compatible with round-4/5 streams).
    fresh = StepTimer(smoke_cfg().model, batch=8, seq_len=32)
    step(fresh), step(fresh), step(fresh)
    assert "overlap_s" not in fresh.summary()


# ------------------------------------------------- GO ranking eval metrics

def _brute_force_auroc(scores, labels, valid):
    """O(n^2) pairwise AUROC over valid elements (test oracle)."""
    s = scores[valid]
    y = labels[valid]
    pos, neg = s[y], s[~y]
    if len(pos) == 0 or len(neg) == 0:
        return 0.5
    wins = (pos[:, None] > neg[None, :]).sum()
    ties = (pos[:, None] == neg[None, :]).sum()
    return (wins + 0.5 * ties) / (len(pos) * len(neg))


def test_global_auroc_matches_brute_force():
    from proteinbert_tpu.train.loss import global_ranking_metrics

    rng = np.random.default_rng(0)
    for trial in range(5):
        logits = rng.normal(size=(6, 40)).astype(np.float32)
        targets = (rng.random((6, 40)) < 0.15).astype(np.float32)
        # weight rows like the pretrain contract: 1 iff any positive
        w = np.repeat(targets.any(-1, keepdims=True), 40, 1).astype(np.float32)
        m = global_ranking_metrics(jnp.asarray(logits), jnp.asarray(targets),
                                   jnp.asarray(w))
        want = _brute_force_auroc(logits.ravel(),
                                  (targets > 0).ravel() & (w > 0).ravel(),
                                  (w > 0).ravel())
        np.testing.assert_allclose(float(m["global_auroc"]), want, atol=1e-5)


def test_global_auroc_perfect_and_inverted():
    from proteinbert_tpu.train.loss import global_ranking_metrics

    targets = np.zeros((2, 8), np.float32)
    targets[:, :2] = 1.0
    w = np.ones((2, 8), np.float32)
    perfect = jnp.asarray(np.where(targets > 0, 5.0, -5.0)
                          + np.random.default_rng(0).normal(size=(2, 8)) * .1)
    m = global_ranking_metrics(perfect, jnp.asarray(targets), jnp.asarray(w))
    assert float(m["global_auroc"]) == pytest.approx(1.0)
    assert float(m["global_p_at_k"]) == pytest.approx(2 / 8)  # k=8 here
    m = global_ranking_metrics(-perfect, jnp.asarray(targets), jnp.asarray(w))
    assert float(m["global_auroc"]) == pytest.approx(0.0)


def test_global_auroc_degenerate_cases():
    from proteinbert_tpu.train.loss import global_ranking_metrics

    logits = jnp.ones((2, 8))
    # no positives at all / everything weighted out → neutral 0.5
    m = global_ranking_metrics(logits, jnp.zeros((2, 8)), jnp.ones((2, 8)))
    assert float(m["global_auroc"]) == pytest.approx(0.5)
    m = global_ranking_metrics(logits, jnp.ones((2, 8)), jnp.zeros((2, 8)))
    assert float(m["global_auroc"]) == pytest.approx(0.5)


def test_pooled_ranking_stats_match_brute_force_multibatch():
    """Split-level AUROC/p@k pooled from per-batch sufficient statistics
    must match the brute-force oracle over the CONCATENATED batches —
    the multi-batch extension of the brute-force check (VERDICT r2
    item 7: a dataset AUROC is not a mean of per-batch AUROCs)."""
    from proteinbert_tpu.train.loss import (
        global_ranking_metrics, global_ranking_stats,
        ranking_metrics_from_stats,
    )

    rng = np.random.default_rng(3)
    batches = []
    for _ in range(3):
        logits = rng.normal(scale=4.0, size=(5, 24)).astype(np.float32)
        targets = (rng.random((5, 24)) < 0.2).astype(np.float32)
        w = np.repeat(targets.any(-1, keepdims=True), 24, 1).astype(np.float32)
        batches.append((logits, targets, w))

    stats = None
    for logits, targets, w in batches:
        s = jax.device_get(global_ranking_stats(
            jnp.asarray(logits), jnp.asarray(targets), jnp.asarray(w)))
        stats = s if stats is None else jax.tree.map(lambda a, b: a + b,
                                                     stats, s)
    pooled = ranking_metrics_from_stats(stats)

    all_logits = np.concatenate([b[0] for b in batches])
    all_targets = np.concatenate([b[1] for b in batches])
    all_w = np.concatenate([b[2] for b in batches])
    want = _brute_force_auroc(
        all_logits.ravel(),
        (all_targets > 0).ravel() & (all_w > 0).ravel(),
        (all_w > 0).ravel())
    # bin-width ties bound the histogram approximation
    np.testing.assert_allclose(pooled["global_auroc"], want, atol=2e-3)

    # pooled == exact single-batch metrics when there is only one batch
    logits, targets, w = batches[0]
    one = ranking_metrics_from_stats(jax.device_get(global_ranking_stats(
        jnp.asarray(logits), jnp.asarray(targets), jnp.asarray(w))))
    exact = global_ranking_metrics(jnp.asarray(logits), jnp.asarray(targets),
                                   jnp.asarray(w))
    np.testing.assert_allclose(one["global_auroc"],
                               float(exact["global_auroc"]), atol=2e-3)
    np.testing.assert_allclose(one["global_p_at_k"],
                               float(exact["global_p_at_k"]), atol=1e-6)

    # pooled p@k is exactly decomposable — verify against direct compute
    per_row = []
    row_w = []
    for logits, targets, w in batches:
        k = 10
        top = np.argsort(-logits, axis=-1)[:, :k]
        labels = (targets > 0) & (w > 0)
        hits = np.take_along_axis(labels, top, axis=-1)
        per_row.extend(hits.mean(-1))
        row_w.extend((w > 0).any(-1).astype(float))
    want_pk = float(np.sum(np.array(per_row) * np.array(row_w))
                    / np.sum(row_w))
    np.testing.assert_allclose(pooled["global_p_at_k"], want_pk, atol=1e-6)


def test_evaluate_batches_pools_ranking_metrics():
    """evaluate_batches reports split-level (pooled) ranking metrics and
    renames the per-batch means *_batch_mean."""
    from proteinbert_tpu.train.trainer import evaluate_batches

    cfg = smoke_cfg()
    state = create_train_state(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)

    def batches():
        for _ in range(3):
            yield {
                "tokens": rng.integers(
                    4, 26, size=(cfg.data.batch_size, cfg.data.seq_len)
                ).astype(np.int32),
                "annotations": (rng.random(
                    (cfg.data.batch_size, cfg.model.num_annotations)) < 0.1
                ).astype(np.float32),
            }

    m, n, rows = evaluate_batches(state, batches(), lambda b: b, cfg,
                                  jax.random.PRNGKey(7))
    assert n == 3
    assert 0.0 <= m["eval_global_auroc"] <= 1.0
    assert "eval_global_auroc_batch_mean" in m
    assert "eval_ranking_stats" not in m  # stats are consumed, not leaked
    for k, v in m.items():
        assert np.isscalar(v) or np.ndim(v) == 0, k


def test_eval_step_reports_ranking_metrics():
    cfg = smoke_cfg()
    state = create_train_state(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    batch = {
        "tokens": rng.integers(4, 26, size=(cfg.data.batch_size,
                                            cfg.data.seq_len)).astype(np.int32),
        "annotations": (rng.random((cfg.data.batch_size,
                                    cfg.model.num_annotations)) < 0.1
                        ).astype(np.float32),
    }
    from proteinbert_tpu.train.train_state import eval_step

    m = eval_step(state, batch, jax.random.PRNGKey(1), cfg)
    assert 0.0 <= float(m["global_auroc"]) <= 1.0
    assert 0.0 <= float(m["global_p_at_k"]) <= 1.0


def test_global_auroc_no_overflow_at_real_shapes():
    """B=256 x A=8943: n_pos*n_neg ~ 4e9 overflows int32; the metric must
    stay exact (float32 rank arithmetic) — checked against an int64 numpy
    rank-based oracle."""
    from proteinbert_tpu.train.loss import global_ranking_metrics

    rng = np.random.default_rng(0)
    B, A = 256, 8943
    logits = rng.normal(size=(B, A)).astype(np.float32)
    targets = (rng.random((B, A)) < 0.003).astype(np.float32)
    w = np.repeat(targets.any(-1, keepdims=True), A, 1).astype(np.float32)

    m = global_ranking_metrics(jnp.asarray(logits), jnp.asarray(targets),
                               jnp.asarray(w))
    got = float(m["global_auroc"])

    flat = logits.ravel()
    pos = (targets > 0).ravel() & (w > 0).ravel()
    val = (w > 0).ravel()
    order = np.argsort(np.where(val, flat, -np.inf))
    ranks = np.empty(len(flat), np.int64)
    ranks[order] = np.arange(len(flat), dtype=np.int64)
    n_pos = int(pos.sum()); n_val = int(val.sum())
    n_inv = len(flat) - n_val; n_neg = n_val - n_pos
    u = int(ranks[pos].sum()) - n_pos * (n_pos - 1) // 2 - n_pos * n_inv
    want = u / (n_pos * n_neg)
    assert 0.0 <= got <= 1.0
    np.testing.assert_allclose(got, want, atol=1e-4)
