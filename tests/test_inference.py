"""Inference surface tests: embeddings, GO prediction, residue filling.

The reference has no inference path at all (its README defers even the
pretrained model, reference README.md:5-6); these tests cover the
capability this framework adds on top (proteinbert_tpu/inference.py) and
its CLI commands.
"""

import numpy as np
import pytest

from proteinbert_tpu import inference
from proteinbert_tpu.configs import (
    CheckpointConfig, DataConfig, ModelConfig, OptimizerConfig,
    PretrainConfig, TrainConfig,
)
from proteinbert_tpu.data.vocab import ALPHABET, VOCAB_SIZE
from proteinbert_tpu.train import Checkpointer, create_train_state

import jax


def _cfg():
    return PretrainConfig(
        model=ModelConfig(local_dim=32, global_dim=64, key_dim=16,
                          num_heads=4, num_blocks=2, num_annotations=64,
                          dtype="float32"),
        data=DataConfig(seq_len=48, batch_size=4),
        optimizer=OptimizerConfig(warmup_steps=5),
        train=TrainConfig(seed=0, max_steps=1),
        checkpoint=CheckpointConfig(),
    )


@pytest.fixture(scope="module")
def trunk(tmp_path_factory):
    """A saved (untrained) state + its restore via load_trunk."""
    cfg = _cfg()
    d = str(tmp_path_factory.mktemp("ck"))
    state = create_train_state(jax.random.PRNGKey(cfg.train.seed), cfg)
    state = state.replace(step=jax.numpy.asarray(3, jax.numpy.int32))
    ck = Checkpointer(d, async_save=False)
    ck.save(3, state, {"batches_consumed": 3})
    ck.close()
    params, step = inference.load_trunk(d, cfg)
    assert step == 3
    return params, cfg, d


SEQS = ["MKTAYIAKQR", "ACDEFGHIKLMNPQRSTVWY" * 3, "GG"]


def test_embed_shapes_and_determinism(trunk):
    params, cfg, _ = trunk
    out = inference.embed(params, cfg, SEQS, batch_size=2)
    assert out["global"].shape == (3, cfg.model.global_dim)
    assert out["local_mean"].shape == (3, cfg.model.local_dim)
    assert all(np.isfinite(v).all() for v in out.values())
    again = inference.embed(params, cfg, SEQS, batch_size=2)
    np.testing.assert_array_equal(out["global"], again["global"])


def test_embed_batch_padding_invariance(trunk):
    """A sequence's embedding must not depend on which batch it rode in
    (the tail batch is padded to the compiled batch shape)."""
    params, cfg, _ = trunk
    solo = inference.embed(params, cfg, [SEQS[2]], batch_size=4)
    batched = inference.embed(params, cfg, SEQS, batch_size=4)
    np.testing.assert_allclose(
        solo["global"][0], batched["global"][2], rtol=2e-5, atol=2e-5)


def test_embed_per_residue(trunk):
    params, cfg, _ = trunk
    out = inference.embed(params, cfg, SEQS[:1], per_residue=True)
    assert out["local"].shape == (1, cfg.data.seq_len, cfg.model.local_dim)
    assert out["tokens"].shape == (1, cfg.data.seq_len)
    # local_mean is the pad-masked mean of the per-residue track.
    mask = out["tokens"][0] != 0
    np.testing.assert_allclose(
        out["local"][0][mask].mean(0), out["local_mean"][0],
        rtol=1e-4, atol=1e-5)


def test_embed_annotations_shape_checked(trunk):
    params, cfg, _ = trunk
    with pytest.raises(ValueError, match="annotations shape"):
        inference.embed(params, cfg, SEQS, annotations=np.zeros((3, 5)))


def test_predict_go_probs_and_topk(trunk):
    params, cfg, _ = trunk
    probs = inference.predict_go(params, cfg, SEQS)
    assert probs.shape == (3, cfg.model.num_annotations)
    assert ((probs >= 0) & (probs <= 1)).all()
    top = inference.predict_go(params, cfg, SEQS, top_k=5)
    assert len(top) == 3 and all(len(row) == 5 for row in top)
    for row in top:
        ps = [p for _, p in row]
        assert ps == sorted(ps, reverse=True)
    # top-1 matches the dense argmax
    assert top[0][0][0] == int(probs[0].argmax())


def test_predict_residues_fills_masks(trunk):
    params, cfg, _ = trunk
    masked = "MKTA?IAK?R"
    filled, probs = inference.predict_residues(params, cfg, [masked])
    assert probs.shape == (1, cfg.data.seq_len, VOCAB_SIZE)
    assert len(filled[0]) == len(masked)
    for i, ch in enumerate(masked):
        if ch == inference.MASK_CHAR:
            assert filled[0][i] in ALPHABET  # never pad/sos/eos/unk
        else:
            assert filled[0][i] == ch


def test_predict_residues_rejects_mask_beyond_window(trunk):
    """A '?' the crop window would silently drop must be an error."""
    params, cfg, _ = trunk
    long_seq = "A" * (cfg.data.seq_len + 5) + "?"
    with pytest.raises(ValueError, match="beyond position"):
        inference.predict_residues(params, cfg, [long_seq])


def test_empty_input_rejected(trunk):
    params, cfg, _ = trunk
    with pytest.raises(ValueError, match="no sequences"):
        inference.embed(params, cfg, [])


def test_load_trunk_missing_dir(tmp_path):
    with pytest.raises(FileNotFoundError):
        inference.load_trunk(str(tmp_path / "nope"), _cfg())


def test_embed_cli_roundtrip(trunk, tmp_path):
    """embed → HDF5 with ids aligned to inputs; predict-residues → TSV.
    In-process main() like the rest of the CLI suite (tests/test_cli.py)."""
    import h5py

    from proteinbert_tpu.cli.main import main

    _, cfg, ckdir = trunk
    fasta = tmp_path / "q.fasta"
    fasta.write_text(">p1 desc\nMKTAYIAKQR\n>p2\nGGAC\nDEFG\n")
    out_h5 = tmp_path / "emb.h5"
    overrides = [
        f"--pretrained-set=model.{f}={getattr(cfg.model, f)}"
        for f in ("local_dim", "global_dim", "key_dim", "num_heads",
                  "num_blocks", "num_annotations")
    ] + ["--pretrained-set=model.dtype=float32",
         f"--pretrained-set=data.seq_len={cfg.data.seq_len}"]
    assert main(["embed", "--pretrained", ckdir, "--preset", "tiny",
                 *overrides, "--fasta", str(fasta),
                 "--output", str(out_h5), "--batch-size", "2"]) == 0
    with h5py.File(out_h5) as h5f:
        ids = [x.decode() for x in h5f["ids"][:]]
        assert ids == ["p1", "p2"]
        assert h5f["global"].shape == (2, cfg.model.global_dim)

    out_tsv = tmp_path / "filled.tsv"
    assert main(["predict-residues", "--pretrained", ckdir,
                 "--preset", "tiny", *overrides,
                 "--output", str(out_tsv), "MK?AYI"]) == 0
    name, seq = out_tsv.read_text().strip().split("\t")
    assert name == "seq0" and len(seq) == 6 and "?" not in seq


def test_predict_go_cli_with_go_ids(trunk, tmp_path):
    """predict-go TSV output joins annotation columns to GO ids from a
    training-format HDF5 file."""
    import h5py

    from proteinbert_tpu.cli.main import main

    _, cfg, ckdir = trunk
    data_h5 = tmp_path / "train.h5"
    go_ids = [f"GO:{i:07d}" for i in range(cfg.model.num_annotations)]
    with h5py.File(data_h5, "w") as h5f:
        h5f.create_dataset("included_annotations",
                           data=[g.encode() for g in go_ids],
                           dtype=h5py.string_dtype())
    overrides = [
        f"--pretrained-set=model.{f}={getattr(cfg.model, f)}"
        for f in ("local_dim", "global_dim", "key_dim", "num_heads",
                  "num_blocks", "num_annotations")
    ] + ["--pretrained-set=model.dtype=float32",
         f"--pretrained-set=data.seq_len={cfg.data.seq_len}"]
    out = tmp_path / "go.tsv"
    assert main(["predict-go", "--pretrained", ckdir, "--preset", "tiny",
                 *overrides, "--data", str(data_h5), "--top-k", "3",
                 "--output", str(out), "MKTAYIAKQR"]) == 0
    rows = [ln.split("\t") for ln in out.read_text().strip().split("\n")]
    assert len(rows) == 3
    for name, col, gid, _gname, prob in rows:
        assert name == "seq0"
        assert gid == go_ids[int(col)]
        assert 0.0 <= float(prob) <= 1.0


def test_evaluate_cli(trunk, tmp_path, capsys):
    """Standalone evaluate: JSON metrics incl. ranking, deterministic
    given the same seed."""
    import json

    from proteinbert_tpu.cli.main import main

    _, cfg, ckdir = trunk
    overrides = [
        f"--pretrained-set=model.{f}={getattr(cfg.model, f)}"
        for f in ("local_dim", "global_dim", "key_dim", "num_heads",
                  "num_blocks", "num_annotations")
    ] + ["--pretrained-set=model.dtype=float32",
         f"--pretrained-set=data.seq_len={cfg.data.seq_len}",
         "--pretrained-set=data.batch_size=4"]
    out = tmp_path / "eval.json"
    assert main(["evaluate", "--pretrained", ckdir, "--preset", "tiny",
                 *overrides, "--max-batches", "3",
                 "--output", str(out)]) == 0
    r1 = json.load(open(out))
    assert r1["step"] == 3 and r1["batches"] == 3 and r1["rows"] == 12
    for k in ("loss", "local_acc", "global_auroc", "global_p_at_k"):
        assert k in r1 and np.isfinite(r1[k])
    assert 0.0 <= r1["global_auroc"] <= 1.0
    assert main(["evaluate", "--pretrained", ckdir, "--preset", "tiny",
                 *overrides, "--max-batches", "3",
                 "--output", str(out)]) == 0
    r2 = json.load(open(out))
    assert r1 == r2  # fixed seed → reproducible


def _write_h5(path, n, num_annotations, rng):
    import h5py

    seqs = ["".join(rng.choice(list("ACDEFGHIKLMNPQRSTVWY"),
                               size=int(rng.integers(5, 30))))
            for _ in range(n)]
    with h5py.File(path, "w") as f:
        dt = h5py.string_dtype()
        f.create_dataset("seqs", data=[s.encode() for s in seqs], dtype=dt)
        f.create_dataset("uniprot_ids",
                         data=[f"id{i}".encode() for i in range(n)], dtype=dt)
        f.create_dataset("seq_lengths",
                         data=np.array([len(s) for s in seqs], np.int32))
        f.create_dataset("annotation_masks",
                         data=rng.random((n, num_annotations)) < 0.2)
        f.create_dataset(
            "included_annotations",
            data=[f"GO:{i:07d}".encode() for i in range(num_annotations)],
            dtype=dt)


def test_evaluate_cli_covers_tail_rows(trunk, tmp_path):
    """10 rows at batch 4 → 3 batches, ALL 10 rows scored (the tail batch
    is smaller, not dropped)."""
    import json

    from proteinbert_tpu.cli.main import main

    _, cfg, ckdir = trunk
    rng = np.random.default_rng(0)
    data = tmp_path / "eval.h5"
    _write_h5(str(data), 10, cfg.model.num_annotations, rng)
    overrides = [
        f"--pretrained-set=model.{f}={getattr(cfg.model, f)}"
        for f in ("local_dim", "global_dim", "key_dim", "num_heads",
                  "num_blocks", "num_annotations")
    ] + ["--pretrained-set=model.dtype=float32",
         f"--pretrained-set=data.seq_len={cfg.data.seq_len}",
         "--pretrained-set=data.batch_size=4"]
    out = tmp_path / "eval.json"
    assert main(["evaluate", "--pretrained", ckdir, "--preset", "tiny",
                 *overrides, "--data", str(data),
                 "--output", str(out)]) == 0
    r = json.load(open(out))
    assert r["rows"] == 10 and r["batches"] == 3


def test_evaluate_cli_rejects_annotation_mismatch(trunk, tmp_path):
    from proteinbert_tpu.cli.main import main

    _, cfg, ckdir = trunk
    rng = np.random.default_rng(0)
    data = tmp_path / "wrong.h5"
    _write_h5(str(data), 8, cfg.model.num_annotations + 3, rng)
    overrides = [
        f"--pretrained-set=model.num_annotations={cfg.model.num_annotations}",
        "--pretrained-set=model.dtype=float32"]
    with pytest.raises(SystemExit, match="must match"):
        main(["evaluate", "--pretrained", ckdir, "--preset", "tiny",
              *overrides, "--data", str(data)])


def test_evaluate_like_step_matches_training_eval(tmp_path):
    """--like-step reproduces the pretrain loop's eval_* history values
    on the same held-out batches."""
    import dataclasses as dc

    from proteinbert_tpu.configs import (
        DataConfig as DC, ModelConfig as MC, OptimizerConfig as OC,
        PretrainConfig as PC, TrainConfig as TC,
    )
    from proteinbert_tpu.data.dataset import InMemoryPretrainingDataset
    from proteinbert_tpu.data.synthetic import make_random_proteins
    from proteinbert_tpu.train import pretrain
    from proteinbert_tpu.train.trainer import eval_base_key, evaluate_batches

    cfg = PC(model=MC(local_dim=16, global_dim=32, key_dim=8, num_heads=4,
                      num_blocks=2, num_annotations=32, dtype="float32"),
             data=DC(seq_len=32, batch_size=8),
             optimizer=OC(learning_rate=1e-3, warmup_steps=5),
             train=TC(max_steps=10, log_every=10, eval_every=10))
    rng = np.random.default_rng(0)
    seqs, ann = make_random_proteins(48, rng, num_annotations=32, max_len=30)
    train_ds = InMemoryPretrainingDataset(seqs, ann, 32)
    ev_seqs, ev_ann = make_random_proteins(16, rng, num_annotations=32,
                                           max_len=30)
    ev_ds = InMemoryPretrainingDataset(ev_seqs, ev_ann, 32)

    from proteinbert_tpu.data.dataset import make_pretrain_iterator

    eval_batches = lambda: make_pretrain_iterator(  # noqa: E731
        ev_ds, 8, shuffle=False, num_epochs=1)
    out = pretrain(cfg, make_pretrain_iterator(train_ds, 8, seed=0),
                   eval_batches=eval_batches)
    hist_eval = [h for h in out["history"] if "eval_loss" in h][-1]

    # Standalone: same state, same batches, --like-step key derivation.
    m, _, _ = evaluate_batches(out["state"], eval_batches(), lambda b: b,
                               cfg, eval_base_key(cfg, hist_eval["step"]))
    np.testing.assert_allclose(m["eval_loss"], hist_eval["eval_loss"],
                               rtol=1e-6)


def test_embed_batches_streaming_matches_embed(trunk):
    """The streaming generator concatenates to exactly embed()'s output."""
    params, cfg, _ = trunk
    whole = inference.embed(params, cfg, SEQS, batch_size=2,
                            per_residue=True)
    parts = list(inference.embed_batches(params, cfg, SEQS, batch_size=2,
                                         per_residue=True))
    assert [len(p["global"]) for p in parts] == [2, 1]
    for k in whole:
        np.testing.assert_array_equal(
            whole[k], np.concatenate([p[k] for p in parts]))


def test_evaluate_cli_empty_dataset(trunk, tmp_path):
    from proteinbert_tpu.cli.main import main

    _, cfg, ckdir = trunk
    rng = np.random.default_rng(0)
    data = tmp_path / "empty.h5"
    _write_h5(str(data), 0, cfg.model.num_annotations, rng)
    overrides = [
        f"--pretrained-set=model.{f}={getattr(cfg.model, f)}"
        for f in ("local_dim", "global_dim", "key_dim", "num_heads",
                  "num_blocks", "num_annotations")
    ] + ["--pretrained-set=model.dtype=float32"]
    with pytest.raises(SystemExit, match="dataset is empty"):
        main(["evaluate", "--pretrained", ckdir, "--preset", "tiny",
              *overrides, "--data", str(data)])


def test_evaluate_batches_max_batches_does_not_overfetch():
    """The cap must prevent fetching batch N+1, not fetch-and-discard."""
    import jax as _jax

    from proteinbert_tpu.configs import (
        DataConfig as DC, ModelConfig as MC, OptimizerConfig as OC,
        PretrainConfig as PC, TrainConfig as TC,
    )
    from proteinbert_tpu.train import create_train_state
    from proteinbert_tpu.train.trainer import evaluate_batches

    cfg = PC(model=MC(local_dim=16, global_dim=32, key_dim=8, num_heads=4,
                      num_blocks=2, num_annotations=32, dtype="float32"),
             data=DC(seq_len=32, batch_size=4),
             optimizer=OC(warmup_steps=5), train=TC())
    state = create_train_state(_jax.random.PRNGKey(0), cfg)
    fetched = []

    def batches():
        rng = np.random.default_rng(0)
        for i in range(10):
            fetched.append(i)
            yield {"tokens": rng.integers(4, 26, (4, 32)).astype(np.int32),
                   "annotations": (rng.random((4, 32)) < 0.2
                                   ).astype(np.float32)}

    _, n, rows = evaluate_batches(state, batches(), lambda b: b, cfg,
                                  _jax.random.PRNGKey(0), max_batches=2)
    assert n == 2 and rows == 8
    assert fetched == [0, 1]
