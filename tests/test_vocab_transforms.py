"""Unit tests for vocab (C5) and host-side transforms (C6a/b)."""

import numpy as np
import pytest

from proteinbert_tpu.data.vocab import (
    ALPHABET, EOS_ID, N_SPECIAL, PAD_ID, SOS_ID, UNK_ID, VOCAB_SIZE, get_vocab,
)
from proteinbert_tpu.data.transforms import random_crop, tokenize, tokenize_batch


def test_vocab_ids_match_reference_layout():
    # reference data_processing.py:337-348: <pad>=0,<sos>=1,<eos>=2,<unk>=3, AAs 4..25
    v = get_vocab()
    assert len(v) == VOCAB_SIZE == 26
    assert v.stoi["<pad>"] == PAD_ID == 0
    assert v.stoi["<sos>"] == SOS_ID == 1
    assert v.stoi["<eos>"] == EOS_ID == 2
    assert v.stoi["<unk>"] == UNK_ID == 3
    for i, ch in enumerate(ALPHABET):
        assert v.stoi[ch] == N_SPECIAL + i


def test_encode_roundtrip_and_unk():
    v = get_vocab()
    ids = v.encode("ACDY")
    assert ids.dtype == np.int32
    assert v.decode(ids) == "ACDY"
    assert v.encode("AZB")[1] == UNK_ID  # Z, B are not in the 22-char alphabet
    assert (v.encode("ACD") >= N_SPECIAL).all()


def test_tokenize_layout():
    t = tokenize("ACD", seq_len=8)
    assert t.tolist() == [SOS_ID] + [v for v in get_vocab().encode("ACD")] + [EOS_ID, 0, 0, 0]


def test_tokenize_truncates_long():
    t = tokenize("A" * 100, seq_len=16)
    assert t.shape == (16,)
    assert t[0] == SOS_ID and t[-1] == EOS_ID
    assert (t != PAD_ID).all()


def test_random_crop_window():
    s = "ACDEFGHIKL"
    out = random_crop(s, 4, crop_seed=7)
    assert len(out) == 4 and out in s
    assert random_crop(s, 100, crop_seed=7) == s
    # Pure function of (seed, row_id): same inputs, same window...
    assert random_crop(s, 4, crop_seed=7) == out
    # ...and the window varies across seeds/rows (some collisions are
    # fine; over 20 draws there must be more than one distinct window).
    draws = {random_crop(s, 4, crop_seed=sd) for sd in range(20)}
    assert len(draws) > 1


def test_tokenize_batch_shapes():
    seqs = ["", "A", "ACDEFGHIKLMNPQRSTVWY" * 20]
    b = tokenize_batch(seqs, 32, crop_seed=3)
    assert b.shape == (3, 32)
    assert (b[:, 0] == SOS_ID).all()
    assert b[0, 1] == EOS_ID  # empty sequence: sos,eos,pad...


def test_crop_windows_independent_of_batch_composition():
    """A row's window depends on (seed, global row id) only — the same
    row in a different batch, position, or path (single-row tokenize)
    gets the same window."""
    long = "ACDEFGHIKLMNPQRSTVWY" * 30
    alone = tokenize_batch([long], 32, crop_seed=11,
                           row_ids=np.array([42]), use_native=False)[0]
    batched = tokenize_batch(["AAA", long, "CCC"], 32, crop_seed=11,
                             row_ids=np.array([7, 42, 9]),
                             use_native=False)[1]
    np.testing.assert_array_equal(alone, batched)

    from proteinbert_tpu.data.transforms import tokenize

    np.testing.assert_array_equal(
        tokenize(long, 32, crop_seed=11, row_id=42), alone)


def test_crop_starts_bounds_and_coverage():
    """Property test of the window primitive: starts are always within
    [0, len-cap]; the boundary length len==cap never crops; len==cap+1
    draws both of its two legal windows across rows; and large lengths
    cover the full start range rather than clustering."""
    from proteinbert_tpu.data.transforms import crop_starts

    cap = 30
    rng = np.random.default_rng(0)
    lengths = rng.integers(0, 500, size=2000)
    row_ids = np.arange(2000)
    starts = crop_starts(lengths, cap, 123, row_ids)
    assert (starts >= 0).all()
    over = lengths > cap
    assert (starts[~over] == 0).all()
    assert (starts[over] <= lengths[over] - cap).all()

    # len == cap + 1: exactly two legal windows, both must occur.
    two = crop_starts(np.full(200, cap + 1), cap, 9, np.arange(200))
    assert set(np.unique(two)) == {0, 1}

    # Large fixed length: starts spread over most of the legal range.
    wide = crop_starts(np.full(500, 400), cap, 7, np.arange(500))
    assert wide.max() > 300 and wide.min() < 50
    assert len(np.unique(wide)) > 100


def test_epoch_crop_seed_varies_and_is_stable():
    from proteinbert_tpu.data.transforms import epoch_crop_seed

    seeds = [epoch_crop_seed(5, e) for e in range(10)]
    assert len(set(seeds)) == 10          # fresh windows every epoch
    assert seeds == [epoch_crop_seed(5, e) for e in range(10)]  # pure
