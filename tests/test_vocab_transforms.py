"""Unit tests for vocab (C5) and host-side transforms (C6a/b)."""

import numpy as np
import pytest

from proteinbert_tpu.data.vocab import (
    ALPHABET, EOS_ID, N_SPECIAL, PAD_ID, SOS_ID, UNK_ID, VOCAB_SIZE, get_vocab,
)
from proteinbert_tpu.data.transforms import random_crop, tokenize, tokenize_batch


def test_vocab_ids_match_reference_layout():
    # reference data_processing.py:337-348: <pad>=0,<sos>=1,<eos>=2,<unk>=3, AAs 4..25
    v = get_vocab()
    assert len(v) == VOCAB_SIZE == 26
    assert v.stoi["<pad>"] == PAD_ID == 0
    assert v.stoi["<sos>"] == SOS_ID == 1
    assert v.stoi["<eos>"] == EOS_ID == 2
    assert v.stoi["<unk>"] == UNK_ID == 3
    for i, ch in enumerate(ALPHABET):
        assert v.stoi[ch] == N_SPECIAL + i


def test_encode_roundtrip_and_unk():
    v = get_vocab()
    ids = v.encode("ACDY")
    assert ids.dtype == np.int32
    assert v.decode(ids) == "ACDY"
    assert v.encode("AZB")[1] == UNK_ID  # Z, B are not in the 22-char alphabet
    assert (v.encode("ACD") >= N_SPECIAL).all()


def test_tokenize_layout():
    t = tokenize("ACD", seq_len=8)
    assert t.tolist() == [SOS_ID] + [v for v in get_vocab().encode("ACD")] + [EOS_ID, 0, 0, 0]


def test_tokenize_truncates_long():
    t = tokenize("A" * 100, seq_len=16)
    assert t.shape == (16,)
    assert t[0] == SOS_ID and t[-1] == EOS_ID
    assert (t != PAD_ID).all()


def test_random_crop_window(rng):
    s = "ACDEFGHIKL"
    out = random_crop(s, 4, rng)
    assert len(out) == 4 and out in s
    assert random_crop(s, 100, rng) == s


def test_tokenize_batch_shapes(rng):
    seqs = ["", "A", "ACDEFGHIKLMNPQRSTVWY" * 20]
    b = tokenize_batch(seqs, 32, rng)
    assert b.shape == (3, 32)
    assert (b[:, 0] == SOS_ID).all()
    assert b[0, 1] == EOS_ID  # empty sequence: sos,eos,pad...
