"""The example workflow's synthetic-input generator must keep producing
artifacts the real parsers accept (examples/full_workflow.sh drives the
CLI on them; this guards the generator against rot without running the
full multi-minute script)."""

import pathlib
import subprocess
import sys

from proteinbert_tpu.cli.main import main
from proteinbert_tpu.data.finetune_data import load_task_tsv

_GENERATOR = (pathlib.Path(__file__).resolve().parents[1]
              / "examples" / "make_synthetic_inputs.py")


def test_example_inputs_feed_the_etl(tmp_path):
    out = subprocess.run(
        [sys.executable, str(_GENERATOR), str(tmp_path / "inputs")],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr[-1000:]

    db = tmp_path / "ann.db"
    h5 = tmp_path / "data.h5"
    assert main([
        "create-uniref-db",
        "--uniref-xml", str(tmp_path / "inputs" / "uniref90.xml.gz"),
        "--go-meta", str(tmp_path / "inputs" / "go.txt"),
        "--output-db", str(db),
        "--go-meta-csv", str(tmp_path / "meta.csv"),
    ]) == 0
    assert main([
        "create-h5", "--db", str(db),
        "--fasta", str(tmp_path / "inputs" / "uniref90.fasta"),
        "--go-meta-csv", str(tmp_path / "meta.csv"),
        "--output", str(h5), "--min-records", "2",
    ]) == 0

    import h5py

    with h5py.File(h5) as f:
        n, a = f["annotation_masks"].shape
        assert n == 120 and a > 0
        assert f["seqs"].shape[0] == n

    # fine-tune TSVs parse and carry both classes
    tokens, labels = load_task_tsv(
        str(tmp_path / "inputs" / "train.tsv"),
        kind="sequence_classification", seq_len=128)
    assert tokens.shape[0] == labels.shape[0] > 0
    assert len(set(int(l) for l in labels)) == 2


def test_etl_scale_rehearsal_script(tmp_path):
    """The scale-rehearsal script (examples/etl_scale_rehearsal.py) must
    keep running end to end and emitting its JSON summary — guarded at
    tiny N so the suite stays fast; the recorded 100k numbers live in
    BASELINE.md."""
    import json

    script = _GENERATOR.parent / "etl_scale_rehearsal.py"
    out = subprocess.run(
        [sys.executable, str(script), "300", str(tmp_path / "rehearsal")],
        capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stderr[-1500:]
    summary = json.loads(out.stdout.strip().splitlines()[-1])
    assert summary["n_entries"] == 300
    assert summary["rows_in_h5"] == 300
    assert set(summary["stages"]) == {"generate", "xml_to_sqlite",
                                      "fasta_index", "h5_build"}
    assert summary["pipeline_entries_per_sec"] > 0
    # Artifacts kept because an out_dir was passed explicitly.
    assert (tmp_path / "rehearsal" / "dataset.h5").exists()
