"""Segment-aware sequence packing (ISSUE 4): packer determinism and
multi-host lockstep, packed-vs-unpacked model/loss parity, the
cross-segment-leakage bit-identity proof, and the pad_fraction /
dropped-row telemetry shared with the bucketed iterator.

Cost discipline: ONE canonical fp32 tiny model config and ONE packed
shape serve every jitted test in this module (cfg is a static jit arg —
every variant recompiles); the planner/iterator tests are pure numpy.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from proteinbert_tpu.configs import (
    DataConfig, ModelConfig, OptimizerConfig, PretrainConfig, TrainConfig,
)
from proteinbert_tpu.data import (
    InMemoryPretrainingDataset, make_packed_iterator,
)
from proteinbert_tpu.data.corruption import corrupt_packed_batch, packed_weights
from proteinbert_tpu.data.packing import PackPlanner, pad_fraction, unpack_segments
from proteinbert_tpu.data.vocab import N_SPECIAL, PAD_ID
from proteinbert_tpu.models import proteinbert
from proteinbert_tpu.train.loss import (
    packed_pretrain_loss, packed_segment_losses, pretrain_loss,
)

SEQ_LEN = 128
MAX_SEG = 4
A = 32

CFG = ModelConfig(local_dim=32, global_dim=64, key_dim=16, num_heads=4,
                  num_blocks=2, num_annotations=A, dtype="float32")


def _corpus(n=64, max_len=50, seed=0):
    from proteinbert_tpu.data.synthetic import make_random_proteins

    rng = np.random.default_rng(seed)
    seqs, ann = make_random_proteins(n, rng, num_annotations=A,
                                     max_len=max_len, density=0.1)
    # Guarantee every row exists (length >= 1) so per-sequence parity
    # bookkeeping is simple.
    seqs = [s or "A" for s in seqs]
    return InMemoryPretrainingDataset(seqs, ann, SEQ_LEN)


@pytest.fixture(scope="module")
def ds():
    return _corpus()


@pytest.fixture(scope="module")
def packed_batch(ds):
    return next(make_packed_iterator(ds, batch_size=2, seed=0,
                                     max_segments=MAX_SEG))


@pytest.fixture(scope="module")
def params():
    return proteinbert.init(jax.random.PRNGKey(0), CFG)


# ------------------------------------------------------------- planner

def test_planner_first_fit_and_bounds():
    p = PackPlanner(seq_len=100, max_segments=3, max_open=4)
    closed = []
    for rid, ln in enumerate([60, 30, 50, 40, 10]):
        closed += p.add(rid, ln)
    closed += p.flush()
    rows = {r for g in closed for r in g}
    assert rows == set(range(5))  # nothing lost
    for g in closed:
        assert len(g) <= 3
    # first-fit: 30 lands with 60 (fits), 40 with 50, 10 back with 60+30
    assert [0, 1, 4] in closed and [2, 3] in closed


def test_planner_full_row_and_segment_cap():
    p = PackPlanner(seq_len=100, max_segments=2, max_open=8)
    # A full-length row closes immediately (remaining 0 < min fit).
    assert p.add(0, 100) == [[0]]
    # Segment cap closes a row even with capacity left.
    assert p.add(1, 10) == []
    assert p.add(2, 10) == [[1, 2]]
    assert p.flush() == []


def test_planner_fuzz_adversarial_streams():
    """ISSUE 9 satellite: seeded fuzz over random length streams —
    PackPlanner and the serving-side OnlinePacker must both uphold the
    packing invariants on every stream: no row exceeds seq_len, no row
    exceeds max_segments, every id is emitted exactly once, and the
    plan is deterministic across re-runs."""
    from proteinbert_tpu.data.packing import OnlinePacker

    rng = np.random.default_rng(1234)
    for trial in range(25):
        seq_len = int(rng.integers(8, 200))
        max_seg = int(rng.integers(1, 9))
        max_open = int(rng.integers(1, 12))
        n = int(rng.integers(1, 120))
        # Adversarial mix: tiny, huge (clamped), exact-fit, and
        # off-by-one lengths all appear.
        lengths = rng.choice(
            [1, 2, 3, seq_len // 2, seq_len - 1, seq_len,
             seq_len + 17, int(rng.integers(1, 2 * seq_len))],
            size=n).astype(int)

        def run_planner():
            p = PackPlanner(seq_len, max_seg, max_open)
            groups = []
            for rid, ln in enumerate(lengths):
                groups += p.add(rid, int(ln))
            groups += p.flush()
            return groups

        groups = run_planner()
        assert groups == run_planner()  # deterministic re-run
        seen = [r for g in groups for r in g]
        assert sorted(seen) == list(range(n)), (trial, "ids lost/dup")
        for g in groups:
            assert 1 <= len(g) <= max_seg
            assert sum(min(int(lengths[r]), seq_len) for r in g) <= seq_len

        def run_online():
            if seq_len < 2:
                return None
            op = OnlinePacker(seq_len, max_seg)
            popped = []
            for rid, ln in enumerate(lengths):
                op.place(rid, min(max(int(ln), 1), seq_len))
                if len(op) > max_open:  # caller-driven dispatch
                    popped += op.pop_rows(max_open // 2 + 1)
            popped += op.pop_rows(len(op))
            return popped

        rows = run_online()
        assert rows == run_online()  # deterministic re-run
        seen = [item[0] for row in rows for item in row]
        assert sorted(seen) == list(range(n)), (trial, "online ids")
        for row in rows:
            assert 1 <= len(row) <= max_seg
            # spans tile the row without overlap and stay in bounds
            end = 0
            for _, start, span in row:
                assert start >= end and span >= 1
                end = start + span
            assert end <= seq_len


def test_online_packer_expire_and_row_heads():
    from proteinbert_tpu.data.packing import OnlinePacker

    op = OnlinePacker(100, 4)
    for rid, span in enumerate([60, 30, 50, 40]):
        op.place(rid, span)
    # first-fit: row0=[0(60),1(30)], row1=[2(50),3(40)]
    assert op.row_heads() == [0, 2]
    assert op.total_items() == 4
    removed = op.expire(lambda r: r in (0, 2))
    assert removed == [0, 2]
    # holes keep later items' starts; rows survive while non-empty
    assert op.row_heads() == [1, 3]
    rows = op.pop_rows(5)
    assert [(i, start) for row in rows for i, start, _ in row] == \
        [(1, 60), (3, 50)]
    assert len(op) == 0 and op.drain_items() == []


def test_packed_iterator_shapes_and_invariants(ds, packed_batch):
    b = packed_batch
    assert b["tokens"].shape == (2, SEQ_LEN)
    assert b["segment_ids"].shape == (2, SEQ_LEN)
    assert b["annotations"].shape == (2, MAX_SEG, A)
    # pad positions and segment-0 positions coincide exactly
    np.testing.assert_array_equal(b["tokens"] == PAD_ID,
                                  b["segment_ids"] == 0)
    # segments are contiguous, 1..n in order, no interior pad
    for row in b["segment_ids"]:
        nz = row[row > 0]
        assert (np.diff(nz) >= 0).all() and nz[0] == 1
    # every packed segment round-trips to a dataset row
    tok_set = {tuple(t[t != PAD_ID]) for t in ds.tokens}
    for toks, _ in unpack_segments(b):
        assert tuple(toks) in tok_set
    # packing actually packs: multiple segments and low pad on this corpus
    assert all(row.max() >= 2 for row in b["segment_ids"])
    assert pad_fraction(b["tokens"]) < 0.5


def test_packed_iterator_deterministic_and_restart(ds):
    a = [next(it) for it in [make_packed_iterator(ds, 2, seed=3)] for _ in range(4)]
    it2 = make_packed_iterator(ds, 2, seed=3)
    b = [next(it2) for _ in range(4)]
    for x, y in zip(a, b):
        for k in x:
            np.testing.assert_array_equal(x[k], y[k])
    # skip_batches replays the plan without data: batch 2 == batch 2
    it3 = make_packed_iterator(ds, 2, seed=3, skip_batches=2)
    resumed = next(it3)
    for k in resumed:
        np.testing.assert_array_equal(resumed[k], a[2][k])


def test_packed_iterator_multihost_lockstep(ds):
    """Two hosts with the same seed agree on the global packing plan and
    take disjoint slices of it (the multi-host invariant collective
    steps require)."""
    h0 = next(make_packed_iterator(ds, 2, seed=1, process_index=0,
                                   process_count=2))
    h1 = next(make_packed_iterator(ds, 2, seed=1, process_index=1,
                                   process_count=2))
    assert h0["tokens"].shape == h1["tokens"].shape
    seqs0 = {tuple(t) for t, _ in unpack_segments(h0)}
    seqs1 = {tuple(t) for t, _ in unpack_segments(h1)}
    assert seqs0 and seqs1 and not (seqs0 & seqs1)


def test_pad_fraction_and_drop_metrics(ds):
    """Packed and bucketed iterators report pad_fraction under the SAME
    metric name (strategy-labeled) plus dropped-row counters — the
    cross-strategy comparison contract (ISSUE 4 satellite)."""
    from proteinbert_tpu.data.dataset import make_bucketed_iterator
    from proteinbert_tpu.obs import MetricsRegistry

    reg = MetricsRegistry()
    n_pack = sum(1 for _ in make_packed_iterator(
        ds, 2, seed=0, num_epochs=1, metrics=reg))
    snap = reg.snapshot()
    g = snap["gauges"]['data_pad_fraction{strategy="packed"}']
    assert 0.0 <= g < 1.0 and n_pack > 0
    assert snap["counters"]["data_packed_rows_total"] == 2 * n_pack
    assert snap["counters"]["data_packed_segments_total"] >= 2 * n_pack
    # the sub-batch remainder is counted, not silently lost
    dropped = snap["counters"].get(
        'data_dropped_rows_total{strategy="packed"}', 0)
    segs = snap["counters"]["data_packed_segments_total"]
    assert segs + dropped == len(ds)

    reg2 = MetricsRegistry()
    n_buck = sum(1 for _ in make_bucketed_iterator(
        ds, 2, buckets=(64, SEQ_LEN), seed=0, num_epochs=1, metrics=reg2))
    snap2 = reg2.snapshot()
    assert 'data_pad_fraction{strategy="bucketed"}' in snap2["gauges"]
    rows_emitted = 2 * n_buck
    dropped2 = snap2["counters"].get(
        'data_dropped_rows_total{strategy="bucketed"}', 0)
    assert rows_emitted + dropped2 == len(ds)


# ---------------------------------------------------------- corruption

def test_packed_corruption_protects_every_segments_specials(packed_batch):
    tokens = jnp.asarray(packed_batch["tokens"])
    seg = jnp.asarray(packed_batch["segment_ids"])
    ann = jnp.asarray(packed_batch["annotations"])
    X, Y, W = corrupt_packed_batch(jax.random.PRNGKey(7), tokens, seg, ann,
                                   token_randomize_prob=0.9)
    special = np.asarray(tokens) < N_SPECIAL  # <pad>/<sos>/<eos> anywhere
    np.testing.assert_array_equal(np.asarray(X["local"])[special],
                                  np.asarray(tokens)[special])
    # weights: local == real positions; global == segment exists AND has
    # a positive annotation
    np.testing.assert_array_equal(np.asarray(W["local"]),
                                  (np.asarray(seg) > 0).astype(np.float32))
    gw = np.asarray(W["global"])
    seg_np = np.asarray(seg)
    ann_np = np.asarray(ann)
    for b in range(gw.shape[0]):
        for s in range(gw.shape[1]):
            exists = (seg_np[b] == s + 1).any()
            expect = 1.0 if (exists and ann_np[b, s].sum() > 0) else 0.0
            assert (gw[b, s] == expect).all()


def test_packed_annotation_corruption_is_per_segment(packed_batch):
    """The keep/hide draw is independent per packed protein — find a key
    where two segments of one row take different branches."""
    tokens = jnp.asarray(packed_batch["tokens"])
    seg = jnp.asarray(packed_batch["segment_ids"])
    ann = jnp.ones_like(jnp.asarray(packed_batch["annotations"]))
    seen_mixed = False
    for k in range(8):
        X, _, _ = corrupt_packed_batch(
            jax.random.PRNGKey(k), tokens, seg, ann,
            annotation_corrupt_prob=0.5, annotation_drop_prob=0.0,
            annotation_add_prob=0.0)
        hidden = np.asarray(X["global"]).sum(-1) == 0  # (B, S)
        if hidden.any() and (~hidden).any():
            seen_mixed = True
            break
    assert seen_mixed


# ------------------------------------------------- model parity / leak

def _solo_rows(packed_batch):
    """Each packed protein alone in its own (1, L) row via the S=1
    packed path — the pad-correct per-sequence baseline."""
    rows = []
    for toks, ann in unpack_segments(packed_batch):
        t = np.zeros((SEQ_LEN,), np.int32)
        t[: len(toks)] = toks
        s = np.zeros((SEQ_LEN,), np.int32)
        s[: len(toks)] = 1
        rows.append((t, s, ann))
    return rows


def test_packed_vs_solo_per_sequence_parity(params, packed_batch):
    """Packed-on vs packed-off parity: the same proteins run (a) packed
    several-per-row and (b) one-per-row, and the per-sequence local
    logits, global vectors, and losses agree within fp32 tolerance (the
    two programs have different shapes, so XLA's reduction orders differ
    by ~1e-6 — bit-identity is asserted by the leakage test, which
    compares within ONE program)."""
    seg = jnp.asarray(packed_batch["segment_ids"])
    ll_p, gl_p = proteinbert.apply(
        params, jnp.asarray(packed_batch["tokens"]),
        jnp.asarray(packed_batch["annotations"]), CFG, segment_ids=seg)
    Y = {"local": jnp.asarray(packed_batch["tokens"]),
         "global": jnp.asarray(packed_batch["annotations"])}
    W = packed_weights(Y["local"], seg, Y["global"])
    per_seg = jax.tree.map(np.asarray, packed_segment_losses(
        ll_p, gl_p, Y, W, seg))
    ll_p, gl_p = np.asarray(ll_p), np.asarray(gl_p)

    solo = _solo_rows(packed_batch)
    i = 0
    for b in range(packed_batch["tokens"].shape[0]):
        for s in range(1, int(packed_batch["segment_ids"][b].max()) + 1):
            t, sid, ann = solo[i]
            i += 1
            ll1, gl1 = proteinbert.apply(
                params, jnp.asarray(t[None]), jnp.asarray(ann[None, None]),
                CFG, segment_ids=jnp.asarray(sid[None]))
            n = int(sid.sum())
            mask = packed_batch["segment_ids"][b] == s
            np.testing.assert_allclose(ll_p[b][mask], np.asarray(ll1)[0, :n],
                                       atol=1e-5, rtol=1e-5)
            np.testing.assert_allclose(gl_p[b, s - 1], np.asarray(gl1)[0, 0],
                                       atol=1e-5, rtol=1e-5)
            # per-sequence losses: packed per-segment vs solo per-segment
            Y1 = {"local": jnp.asarray(t[None]),
                  "global": jnp.asarray(ann[None, None])}
            W1 = packed_weights(Y1["local"], jnp.asarray(sid[None]),
                                Y1["global"])
            solo_seg = jax.tree.map(np.asarray, packed_segment_losses(
                ll1, gl1, Y1, W1, jnp.asarray(sid[None])))
            np.testing.assert_allclose(per_seg["local"][b, s - 1],
                                       solo_seg["local"][0, 0], atol=1e-5)
            np.testing.assert_allclose(per_seg["global"][b, s - 1],
                                       solo_seg["global"][0, 0], atol=1e-5)
    assert i == len(solo)


def test_single_segment_full_row_matches_unpacked_model(params):
    """On rows with NO padding the segment-aware path (tap-decomposed
    masked convs + per-segment attention) must reproduce the plain
    unpacked model within fp32 tolerance — this pins the implicit-GEMM
    conv decomposition against lax.conv_general_dilated. (On PADDED
    rows the two paths deliberately diverge: the unpacked convs read
    pad-position activations near the tail, the packed path masks them
    — docs/data.md 'Packing' section.)"""
    rng = np.random.default_rng(5)
    from proteinbert_tpu.data.vocab import ALPHABET

    seqs = ["".join(rng.choice(list(ALPHABET), size=SEQ_LEN - 2))
            for _ in range(2)]
    ann = (rng.random((2, A)) < 0.1).astype(np.float32)
    full_ds = InMemoryPretrainingDataset(seqs, ann, SEQ_LEN)
    full = full_ds.tokens
    assert (full != PAD_ID).all()
    ll_u, gl_u = proteinbert.apply(params, jnp.asarray(full),
                                   jnp.asarray(ann), CFG)
    seg = np.ones_like(full)
    ll_p, gl_p = proteinbert.apply(params, jnp.asarray(full),
                                   jnp.asarray(ann[:, None, :]), CFG,
                                   segment_ids=jnp.asarray(seg))
    np.testing.assert_allclose(np.asarray(ll_u), np.asarray(ll_p),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gl_u), np.asarray(gl_p)[:, 0],
                               atol=1e-5, rtol=1e-5)


def test_cross_segment_leakage_bit_identical(params, packed_batch):
    """THE leakage proof (ISSUE 4 acceptance): perturb one segment's
    tokens AND annotations; every other segment's local outputs, global
    vector, and per-segment losses are BIT-identical (same compiled
    program, so masked contributions are exact zeros — multiplication
    by a zero mask / exp-underflowed softmax weights, not small
    numbers)."""
    fwd = jax.jit(lambda t, a, s: proteinbert.apply(
        params, t, a, CFG, segment_ids=s))
    seg = jnp.asarray(packed_batch["segment_ids"])

    def outputs(tokens_np, ann_np):
        ll, gl = fwd(jnp.asarray(tokens_np), jnp.asarray(ann_np), seg)
        Y = {"local": jnp.asarray(tokens_np), "global": jnp.asarray(ann_np)}
        W = packed_weights(Y["local"], seg, Y["global"])
        losses = packed_segment_losses(ll, gl, Y, W, seg)
        return (np.asarray(ll), np.asarray(gl),
                jax.tree.map(np.asarray, losses))

    ll0, gl0, seg0 = outputs(packed_batch["tokens"],
                             packed_batch["annotations"])
    t1 = np.array(packed_batch["tokens"])
    a1 = np.array(packed_batch["annotations"])
    pos = np.flatnonzero(packed_batch["segment_ids"][0] == 1)
    t1[0, pos[1:-1]] = ((t1[0, pos[1:-1]] - N_SPECIAL + 7)
                        % (26 - N_SPECIAL)) + N_SPECIAL
    a1[0, 0] = 1.0 - a1[0, 0]
    ll1, gl1, seg1 = outputs(t1, a1)

    # the perturbed segment itself did change (the test has teeth)
    assert not np.array_equal(ll0[0][pos], ll1[0][pos])
    # every OTHER segment: bit-identical local slice, global row, losses
    other = np.asarray(packed_batch["segment_ids"][0]) >= 2
    np.testing.assert_array_equal(ll0[0][other], ll1[0][other])
    np.testing.assert_array_equal(gl0[0, 1:], gl1[0, 1:])
    for k in ("local", "global", "local_acc"):
        np.testing.assert_array_equal(seg0[k][0, 1:], seg1[k][0, 1:])
    # untouched ROWS are bit-identical wholesale
    np.testing.assert_array_equal(ll0[1:], ll1[1:])
    np.testing.assert_array_equal(gl0[1:], gl1[1:])
    for k in ("local", "global"):
        np.testing.assert_array_equal(seg0[k][1:], seg1[k][1:])


# -------------------------------------------------------- train / loss

def test_packed_loss_normalizes_per_segment():
    """A long and a short segment contribute equally: per-token CE of
    1.0 on both -> local_loss 1.0 regardless of length ratio."""
    B, L, S, V = 1, 16, 2, 5
    seg = jnp.asarray([[1] * 12 + [2] * 3 + [0]], jnp.int32)
    tgt = jnp.zeros((B, L), jnp.int32)
    # logits chosen so CE is identical at every position
    ll = jnp.zeros((B, L, V), jnp.float32)
    gl = jnp.zeros((B, S, 3), jnp.float32)
    Y = {"local": tgt, "global": jnp.ones((B, S, 3), jnp.float32)}
    W = {"local": (seg > 0).astype(jnp.float32),
         "global": jnp.ones((B, S, 3), jnp.float32)}
    total, m = packed_pretrain_loss(ll, gl, Y, W, seg)
    expect_ce = float(np.log(V))
    np.testing.assert_allclose(float(m["local_loss"]), expect_ce, rtol=1e-6)
    # and the unpacked token-weighted loss would give the same here
    # (uniform CE), so the per-segment normalization is scale-compatible
    np.testing.assert_allclose(float(m["global_loss"]),
                               float(np.log(1 + np.e ** -0)), rtol=1e-5)


def test_packed_train_and_eval_step(packed_batch):
    """End-to-end: the jitted train/eval steps take the packed branch
    from the batch's pytree structure, losses are finite, params move."""
    from proteinbert_tpu.train import create_train_state
    from proteinbert_tpu.train.train_state import eval_step, train_step

    cfg = PretrainConfig(
        model=CFG,
        data=DataConfig(seq_len=SEQ_LEN, batch_size=2, packing=True,
                        pack_max_segments=MAX_SEG),
        optimizer=OptimizerConfig(warmup_steps=5),
        train=TrainConfig(max_steps=3))
    state = create_train_state(jax.random.PRNGKey(0), cfg)
    p0 = jax.tree.leaves(state.params)[0].copy()
    state, m = train_step(state, packed_batch, cfg)
    state, m = train_step(state, packed_batch, cfg)
    assert np.isfinite(float(m["loss"])) and float(m["grad_norm"]) > 0
    assert not np.allclose(np.asarray(jax.tree.leaves(state.params)[0]),
                           np.asarray(p0))
    em = eval_step(state, packed_batch, jax.random.PRNGKey(1), cfg)
    assert np.isfinite(float(em["loss"]))
    assert 0.0 <= float(em["global_auroc"]) <= 1.0
    assert "ranking_stats" in em


# ------------------------------------ fused packed fast path (ISSUE 10)
# The segment-aware Pallas kernel (interpret mode on CPU — the same
# code Mosaic compiles on TPU) against the `_segment_conv` reference
# oracle, across segment layouts. Cost discipline: ONE kernel shape
# (B, L, C, S) = (2, 256, 128, 4) — L=256 gives tile 128, so a segment
# boundary placed AT position 128 exercises the tile edge — and two
# module-level jitted entries shared by every layout.

from proteinbert_tpu.kernels import fused_block as fb  # noqa: E402

FC, FS, FL = 128, 4, 256

PCFG = ModelConfig(local_dim=FC, global_dim=64, key_dim=16, num_heads=4,
                   num_blocks=1, num_annotations=A, dtype="float32",
                   use_pallas=True)
RCFG = ModelConfig(**{**PCFG.__dict__, "use_pallas": False})


@pytest.fixture(scope="module")
def fused_inputs():
    kp, kx, kb = jax.random.split(jax.random.PRNGKey(3), 3)
    block = proteinbert.block_init(kp, PCFG)
    params = {k: block[k] for k in ("narrow_conv", "wide_conv",
                                    "local_ln1", "local_dense",
                                    "local_ln2")}
    x = jax.random.normal(kx, (2, FL, FC), jnp.float32)
    bc = jax.random.normal(kb, (2, FS, FC), jnp.float32)
    return params, x, bc


def _seg_rows(*rows):
    """(n_rows, FL) segment ids from [(segment_id, span), ...] specs —
    remaining positions stay 0 (pad)."""
    seg = np.zeros((len(rows), FL), np.int32)
    for i, spans in enumerate(rows):
        pos = 0
        for sid, ln in spans:
            seg[i, pos:pos + ln] = sid
            pos += ln
    return jnp.asarray(seg)


@jax.jit
def _fused(params, x, bc, seg):
    return fb.fused_local_track_segments(params, x, bc, seg, 1, 5, True)


@jax.jit
def _ref(params, x, bc, seg):
    return fb.local_track_segment_reference(
        params, x, fb.gather_segment_broadcast(bc, seg), seg, 1, 5)


LAYOUTS = {
    "single_segment_full_row": [[(1, FL)], [(1, FL)]],
    "max_segments": [[(1, 64), (2, 64), (3, 64), (4, 50)],
                     [(1, 30), (2, 30), (3, 30), (4, 30)]],
    "empty_tail_rows": [[(1, 100), (2, 60)], []],  # row 1 ALL pad
    "boundary_at_tile_edge": [[(1, 128), (2, 100)],
                              [(1, 128), (2, 128)]],
}


@pytest.mark.parametrize("layout", sorted(LAYOUTS))
def test_packed_fused_vs_reference_identity(fused_inputs, layout):
    """ISSUE 10 acceptance: fused-vs-reference identity at the
    documented jitted tolerance across segment layouts, with ZERO
    reason=segments fallbacks on this supported shape."""
    params, x, bc = fused_inputs
    assert fb.pallas_segments_supported(FC, FL, FS, "float32")
    seg = _seg_rows(*LAYOUTS[layout])
    before = fb.PATH_TOTAL.get(("reference", "segments"), 0)
    got = _fused(params, x, bc, seg)
    want = _ref(params, x, bc, seg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)
    assert fb.PATH_TOTAL.get(("reference", "segments"), 0) == before


def test_packed_fused_gradient_parity(fused_inputs):
    """The custom VJP (rematerialised oh-reference backward) against
    autodiff through the reference composition — same tolerances as
    the dense kernel's gradient test (fp32 residual accumulation is
    the only forward-path difference)."""
    params, x, bc = fused_inputs
    seg = _seg_rows([(1, 100), (2, 80)], [(1, FL)])

    def loss_fused(p, xx, bb):
        return jnp.sum(
            fb.fused_local_track_segments(p, xx, bb, seg, 1, 5, True) ** 2)

    def loss_ref(p, xx, bb):
        return jnp.sum(fb.local_track_segment_reference(
            p, xx, fb.gather_segment_broadcast(bb, seg), seg, 1, 5) ** 2)

    g_fused = jax.grad(loss_fused, argnums=(0, 1, 2))(params, x, bc)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(params, x, bc)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=2e-4),
        g_fused, g_ref)


def test_force_reference_env_override(fused_inputs, monkeypatch):
    """PBT_FORCE_REFERENCE_KERNEL (documented debug override) routes
    the dispatch onto the reference path — bit-identical to calling
    the reference directly, counted as reason=forced."""
    params, x, bc = fused_inputs
    seg = _seg_rows([(1, 200)], [(1, FL)])
    # "=0"/"false" must NOT force (parsed like every other PBT_* flag).
    monkeypatch.setenv(fb.FORCE_REFERENCE_ENV, "0")
    assert not fb.force_reference_requested()
    monkeypatch.setenv(fb.FORCE_REFERENCE_ENV, "false")
    assert not fb.force_reference_requested()
    monkeypatch.setenv(fb.FORCE_REFERENCE_ENV, "1")
    assert fb.force_reference_requested()
    before = fb.PATH_TOTAL.get(("reference", "forced"), 0)
    got = fb.fused_local_track_segments(params, x, bc, seg, 1, 5, True)
    assert fb.PATH_TOTAL.get(("reference", "forced"), 0) == before + 1
    want = fb.local_track_segment_reference(
        params, x, fb.gather_segment_broadcast(bc, seg), seg, 1, 5)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_packed_model_fused_matches_reference(packed_batch):
    """Model-level wiring (encode → block_apply → one-pass dispatch):
    the packed forward under use_pallas matches the reference config at
    the jitted tolerance AND actually takes the fast path — since the
    one-pass trunk fusion, supported shapes bump the onepass counter
    (the per-kernel families only count on the two-kernel fallback)."""
    from proteinbert_tpu.kernels import one_pass as op

    params = proteinbert.init(jax.random.PRNGKey(4), PCFG)
    tokens = jnp.asarray(packed_batch["tokens"])
    seg = jnp.asarray(packed_batch["segment_ids"])
    ann = jnp.asarray(packed_batch["annotations"])
    before = op.ONEPASS_PATH_TOTAL.get(("pallas", "packed"), 0)
    ll_f, gl_f = proteinbert.apply(params, tokens, ann, PCFG,
                                   segment_ids=seg)
    assert op.ONEPASS_PATH_TOTAL.get(("pallas", "packed"), 0) > before
    ll_r, gl_r = proteinbert.apply(params, tokens, ann, RCFG,
                                   segment_ids=seg)
    np.testing.assert_allclose(np.asarray(ll_f), np.asarray(ll_r),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gl_f), np.asarray(gl_r),
                               atol=1e-5, rtol=1e-5)


def test_packed_train_step_through_fused_kernel(packed_batch):
    """Training wiring: the jitted packed train step under use_pallas
    (custom-VJP backward) runs, moves params, and lands on the fast
    path — the plain-DP leg of the tentpole (the ZeRO-1 leg is the
    opt-in zero_pallas child below)."""
    from proteinbert_tpu.train import create_train_state
    from proteinbert_tpu.train.train_state import train_step

    cfg = PretrainConfig(
        model=PCFG,
        data=DataConfig(seq_len=SEQ_LEN, batch_size=2, packing=True,
                        pack_max_segments=MAX_SEG),
        optimizer=OptimizerConfig(warmup_steps=5),
        train=TrainConfig(max_steps=2))
    from proteinbert_tpu.kernels import one_pass as op

    state = create_train_state(jax.random.PRNGKey(0), cfg)
    p0 = jax.tree.leaves(state.params)[0].copy()
    before = op.ONEPASS_PATH_TOTAL.get(("pallas", "packed"), 0)
    state, m = train_step(state, packed_batch, cfg)
    assert op.ONEPASS_PATH_TOTAL.get(("pallas", "packed"), 0) > before
    state, m = train_step(state, packed_batch, cfg)  # step 1: warmed LR
    assert np.isfinite(float(m["loss"])) and float(m["grad_norm"]) > 0
    assert not np.allclose(np.asarray(jax.tree.leaves(state.params)[0]),
                           np.asarray(p0))


# --------------------------------------- opt-in multi-device parity tier
# Same gate style as the PBT_RUN_TIER64 pod tier: slow-marked (tier-1's
# -m 'not slow' never collects it) AND env-gated, spawning a fresh
# 8-virtual-device child so the packed sharding rules (segment_ids like
# tokens; (B, S, A) annotations batch-sharded) are proven off the
# in-suite process. tools/run_tier1.sh --packed-md runs it.

import subprocess  # noqa: E402
import sys  # noqa: E402
import os  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_md = pytest.mark.skipif(
    not os.environ.get("PBT_RUN_PACKED_MD"),
    reason="multi-device packed tier is opt-in: set PBT_RUN_PACKED_MD=1 "
           "(or run tools/run_tier1.sh --packed-md)")


@pytest.mark.slow
@_md
@pytest.mark.parametrize("scenario", ["dp", "zero", "zero_pallas"])
def test_multidevice_packed_parity_child(scenario):
    import json

    from proteinbert_tpu.utils.compat import scrub_device_count_flag

    env = dict(os.environ)
    env["XLA_FLAGS"] = scrub_device_count_flag(env.get("XLA_FLAGS", ""))
    out = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tests", "multidevice_packed_child.py"),
         scenario],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=900)
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-3000:])
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["ok"] and rec["scenario"] == scenario
    assert abs(rec["sharded_loss"] - rec["ref_loss"]) <= 2e-5
