"""Child process for the REAL-TPU Pallas parity test (spawned by
tests/test_kernels.py::test_resident_order_parity_on_tpu_hardware; not a
pytest module).

Why this exists (ADVICE r1): the C=1024 channel-tiled kernel's
weights-RESIDENT grid order pins its output block to (b, 0, 0) during
non-finish sweeps and relies on Mosaic's flush-on-block-index-change
semantics. Interpret mode overwrites every block on the finish sweep, so
a wrong out-map passes CPU parity tests and only corrupts output on real
hardware — this child runs the exact resident configuration through
Mosaic on a TPU and checks parity against the jax.nn composition.

Prints "PARITY OK <max_abs_err>" on success; exits 3 when no TPU backend
is reachable (the parent skips).
"""

import sys


def main() -> None:
    import jax

    if jax.devices()[0].platform != "tpu":
        print(f"no tpu: platform is {jax.devices()[0].platform}")
        sys.exit(3)

    import jax.numpy as jnp
    import numpy as np

    from proteinbert_tpu.configs import ModelConfig
    from proteinbert_tpu.kernels import fused_local_track, local_track_reference
    from proteinbert_tpu.kernels.fused_block import _plan_tiled
    from proteinbert_tpu.models import proteinbert

    # The Large-preset local track: C=1024 bf16, L long enough for
    # several L tiles. The resident plan must exist here — if it stops
    # existing, this test must fail loudly rather than silently test the
    # fallback order.
    C, L, B = 1024, 512, 2
    tc, tile = _plan_tiled(C, L, "bfloat16", resident=True)
    assert tc > 0, "no weights-resident plan at C=1024/L=512 — update test"

    cfg = ModelConfig(local_dim=C, global_dim=64, key_dim=16, num_heads=4,
                      num_blocks=1, num_annotations=32, dtype="bfloat16")
    kp, kx, kb = jax.random.split(jax.random.PRNGKey(0), 3)
    block = proteinbert.block_init(kp, cfg)
    params = {k: block[k] for k in ("narrow_conv", "wide_conv", "local_ln1",
                                    "local_dense", "local_ln2")}
    x = jax.random.normal(kx, (B, L, C), jnp.bfloat16)
    bcast = jax.random.normal(kb, (B, C), jnp.bfloat16)

    got = np.asarray(
        fused_local_track(params, x, bcast, 1, 5, False).astype(jnp.float32))
    want = np.asarray(
        local_track_reference(params, x, bcast, 1, 5).astype(jnp.float32))
    err = float(np.max(np.abs(got - want)))
    np.testing.assert_allclose(got, want, rtol=0.05, atol=0.05)

    # ISSUE 13: the tiled SEGMENT variant and the ragged attention
    # kernel through Mosaic on the same chip — interpret mode is the
    # tier-1 oracle, the hardware run is the lowering proof (the
    # attention kernel's A·Bᵀ / Aᵀ·B dot_generals and the segment
    # kernel's one-hot operands must actually lower).
    from proteinbert_tpu.kernels import (
        fused_local_track_segments, fused_packed_attention,
        gather_segment_broadcast, local_track_segment_reference,
    )
    from proteinbert_tpu.ops.attention import (
        global_attention_init, packed_global_attention_apply,
    )

    S = 4
    seg = np.zeros((B, L), np.int32)
    for b in range(B):
        seg[b, : L // 2] = 1
        seg[b, L // 2 : L - 30] = 2
    seg = jnp.asarray(seg)
    bc_seg = jax.random.normal(jax.random.PRNGKey(7), (B, S, C),
                               jnp.bfloat16)
    got_s = np.asarray(fused_local_track_segments(
        params, x, bc_seg, seg, 1, 5, False).astype(jnp.float32))
    want_s = np.asarray(local_track_segment_reference(
        params, x, gather_segment_broadcast(bc_seg, seg), seg, 1, 5
    ).astype(jnp.float32))
    err_s = float(np.max(np.abs(got_s - want_s)))
    np.testing.assert_allclose(got_s, want_s, rtol=0.05, atol=0.05)

    aparams = global_attention_init(jax.random.PRNGKey(8), C, 64, 16, 4)
    gseg = jax.random.normal(jax.random.PRNGKey(9), (B, S, 64),
                             jnp.bfloat16)
    got_a = np.asarray(fused_packed_attention(
        aparams, x, gseg, seg, interpret=False).astype(jnp.float32))
    want_a = np.asarray(packed_global_attention_apply(
        aparams, x, gseg, seg).astype(jnp.float32))
    err_a = float(np.max(np.abs(got_a - want_a)))
    np.testing.assert_allclose(got_a, want_a, rtol=0.05, atol=0.05)

    print(f"PARITY OK {err:.6f} (resident plan tc={tc} tile={tile}) "
          f"segment {err_s:.6f} attention {err_a:.6f}")


if __name__ == "__main__":
    main()
