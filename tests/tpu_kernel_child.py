"""Child process for the REAL-TPU Pallas parity test (spawned by
tests/test_kernels.py::test_resident_order_parity_on_tpu_hardware; not a
pytest module).

Why this exists (ADVICE r1): the C=1024 channel-tiled kernel's
weights-RESIDENT grid order pins its output block to (b, 0, 0) during
non-finish sweeps and relies on Mosaic's flush-on-block-index-change
semantics. Interpret mode overwrites every block on the finish sweep, so
a wrong out-map passes CPU parity tests and only corrupts output on real
hardware — this child runs the exact resident configuration through
Mosaic on a TPU and checks parity against the jax.nn composition.

Prints "PARITY OK <max_abs_err>" on success; exits 3 when no TPU backend
is reachable (the parent skips).
"""

import sys


def main() -> None:
    import jax

    if jax.devices()[0].platform != "tpu":
        print(f"no tpu: platform is {jax.devices()[0].platform}")
        sys.exit(3)

    import jax.numpy as jnp
    import numpy as np

    from proteinbert_tpu.configs import ModelConfig
    from proteinbert_tpu.kernels import fused_local_track, local_track_reference
    from proteinbert_tpu.kernels.fused_block import _plan_tiled
    from proteinbert_tpu.models import proteinbert

    # The Large-preset local track: C=1024 bf16, L long enough for
    # several L tiles. The resident plan must exist here — if it stops
    # existing, this test must fail loudly rather than silently test the
    # fallback order.
    C, L, B = 1024, 512, 2
    tc, tile = _plan_tiled(C, L, "bfloat16", resident=True)
    assert tc > 0, "no weights-resident plan at C=1024/L=512 — update test"

    cfg = ModelConfig(local_dim=C, global_dim=64, key_dim=16, num_heads=4,
                      num_blocks=1, num_annotations=32, dtype="bfloat16")
    kp, kx, kb = jax.random.split(jax.random.PRNGKey(0), 3)
    block = proteinbert.block_init(kp, cfg)
    params = {k: block[k] for k in ("narrow_conv", "wide_conv", "local_ln1",
                                    "local_dense", "local_ln2")}
    x = jax.random.normal(kx, (B, L, C), jnp.bfloat16)
    bcast = jax.random.normal(kb, (B, C), jnp.bfloat16)

    got = np.asarray(
        fused_local_track(params, x, bcast, 1, 5, False).astype(jnp.float32))
    want = np.asarray(
        local_track_reference(params, x, bcast, 1, 5).astype(jnp.float32))
    err = float(np.max(np.abs(got - want)))
    np.testing.assert_allclose(got, want, rtol=0.05, atol=0.05)
    print(f"PARITY OK {err:.6f} (resident plan tc={tc} tile={tile})")


if __name__ == "__main__":
    main()
