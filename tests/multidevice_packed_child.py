"""Multi-device PACKED-batch parity child (ISSUE 4 satellite).

Packed batches add a third input tensor (segment_ids, sharded like
tokens) and a per-segment (B, S, A) annotation tensor to the sharding
rules; this child proves, in its own process with 8 virtual CPU
devices, that the sharded packed train step is numerically identical to
the single-device packed step — including under the ZeRO-1 zero-update
path (whose shard_map in/out specs must digest the packed grads tree).

Usage: python tests/multidevice_packed_child.py {dp|zero|zero_pallas}
Prints one JSON line with the compared losses. Opt-in via the parent
tests at the bottom of tests/test_packing.py (PBT_RUN_PACKED_MD=1, same
gate style as the PBT_RUN_TIER64 pod tier; tools/run_tier1.sh
--packed-md).

`zero_pallas` (ISSUE 10): the same ZeRO-1 parity at a lane-aligned
local_dim=128 with use_pallas=True, so the sharded packed step runs
the segment-aware fused Pallas kernel (interpret mode on CPU) inside
the zero-update's shard_map — asserting the fast path was actually
taken AND that it matches the single-device reference.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MODEL = dict(local_dim=16, global_dim=64, key_dim=16, num_heads=4,
             num_blocks=2, num_annotations=64, dtype="float32")


def _parity(scenario):
    import numpy as np

    import jax
    from proteinbert_tpu.configs import (
        DataConfig, MeshConfig, ModelConfig, OptimizerConfig,
        ParallelConfig, PretrainConfig, TrainConfig,
    )
    from proteinbert_tpu.data import (
        InMemoryPretrainingDataset, make_packed_iterator,
    )
    from proteinbert_tpu.data.synthetic import make_random_proteins
    from proteinbert_tpu.parallel import (
        batch_sharding, make_mesh, shard_train_state,
    )
    from proteinbert_tpu.parallel.sharding import state_sharding
    from proteinbert_tpu.parallel.zero import make_zero_train_step
    from proteinbert_tpu.train import create_train_state, train_step

    zero = scenario.startswith("zero")
    pallas = scenario.endswith("_pallas")
    model_kw = dict(MODEL)
    if pallas:
        # Lane-aligned dim so pallas_segments_supported holds — the
        # fused packed fast path inside the zero-update shard_map.
        model_kw.update(local_dim=128, use_pallas=True)
    mesh_cfg = MeshConfig(data=4, fsdp=2)
    cfg = PretrainConfig(
        model=ModelConfig(**model_kw),
        data=DataConfig(seq_len=64, batch_size=8, packing=True,
                        pack_max_segments=4),
        optimizer=OptimizerConfig(learning_rate=1e-3, warmup_steps=10),
        mesh=mesh_cfg,
        parallel=ParallelConfig(zero_update=zero),
        train=TrainConfig(max_steps=2),
    )
    rng = np.random.default_rng(0)
    seqs, ann = make_random_proteins(
        64, rng, num_annotations=MODEL["num_annotations"], max_len=24)
    seqs = [s or "A" for s in seqs]
    ds = InMemoryPretrainingDataset(seqs, ann, cfg.data.seq_len)
    batch = next(make_packed_iterator(ds, cfg.data.batch_size, seed=0,
                                      max_segments=4))
    assert max(int(s.max()) for s in batch["segment_ids"]) >= 2

    ref_state, ref_m = train_step(
        create_train_state(jax.random.PRNGKey(0), cfg), dict(batch), cfg)

    mesh = make_mesh(mesh_cfg)
    state = create_train_state(jax.random.PRNGKey(0), cfg)
    if zero:
        abstract = jax.eval_shape(lambda: state)
        state = jax.device_put(state, state_sharding(mesh, abstract,
                                                     zero_update=True))
        step = make_zero_train_step(mesh, cfg)
        step_fn = lambda s, b: step(s, b)  # noqa: E731
    else:
        state = shard_train_state(state, mesh)
        step_fn = lambda s, b: train_step(s, b, cfg)  # noqa: E731
    bsh = batch_sharding(mesh)
    dbatch = {k: jax.device_put(v, bsh[k]) for k, v in batch.items()}
    new_state, m = step_fn(state, dbatch)

    ref_loss, got_loss = float(ref_m["loss"]), float(m["loss"])
    assert abs(got_loss - ref_loss) <= 2e-5 * max(1.0, abs(ref_loss)), (
        ref_loss, got_loss)
    max_err = 0.0
    for r, g in zip(jax.tree.leaves(ref_state.params),
                    jax.tree.leaves(new_state.params)):
        err = float(np.max(np.abs(
            np.asarray(r, np.float64)
            - np.asarray(jax.device_get(g), np.float64))))
        max_err = max(max_err, err)
    assert max_err < 2e-5, (scenario, max_err)
    if pallas:
        from proteinbert_tpu.kernels import fused_block as fb

        assert fb.PATH_TOTAL.get(("pallas", "packed"), 0) > 0, (
            "pallas scenario never took the fused packed fast path")
        assert fb.PATH_TOTAL.get(("reference", "segments"), 0) == 0, (
            "reason=segments fallback on a supported shape")
    return {"mesh": dict(mesh.shape), "zero_update": zero,
            "use_pallas": pallas,
            "ref_loss": ref_loss, "sharded_loss": got_loss,
            "max_param_err": max_err}


def main():
    scenario = sys.argv[1]
    import jax

    from proteinbert_tpu.utils.compat import request_cpu_devices

    request_cpu_devices(8)
    assert jax.device_count() == 8, jax.device_count()
    out = _parity(scenario)
    print(json.dumps({"scenario": scenario, "ok": True, **out}))


if __name__ == "__main__":
    main()
