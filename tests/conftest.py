"""Test harness: force JAX onto 8 virtual CPU devices BEFORE jax initializes.

This is the TPU-native answer to "test multi-device without a cluster"
(SURVEY.md §4): every sharding/collective test in this suite runs against a
fake 8-device CPU mesh; the driver separately dry-run-compiles the
multi-chip path via __graft_entry__.dryrun_multichip.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (import after env setup is the point)

# On images whose sitecustomize imports jax at interpreter start (the axon
# plugin registration), jax reads its env vars BEFORE conftest runs, so
# none of the settings above take in-process — everything must also go
# through the config API, before any device use. The version-compat
# mechanics (config API on >= 0.5, XLA_FLAGS on 0.4.x) live in ONE
# helper shared with the child scripts and the driver entry.
from proteinbert_tpu.utils.compat import request_cpu_devices  # noqa: E402

_NEW_JAX = request_cpu_devices(8)

# Persistent XLA compilation cache: the suite is compile-bound on CPU (the
# same train-step HLO is rebuilt by many tests), and a warm cache cuts
# single-test wall time ~3x (without it the tier-1 suite blows its 870 s
# budget). On jax 0.4.x the cache is only safe WITHOUT buffer donation:
# executables DESERIALIZED from the persistent cache mis-handle donated
# buffers on the CPU backend — reproduced as a hard segfault
# (orbax-restored state + donated train_step + warm cache) and, worse,
# SILENT wrong numerics (a warm-cache donated finetune_step stopped
# applying head updates; sharded train_step loss diverged from the
# single-device reference; the identical runs are bit-correct with
# donation off). So on old jax the harness disables donation instead of
# the cache — PBT_DISABLE_DONATION is read by the framework's donating
# steps at import (train/train_state.py), and the env vars are inherited
# by every pytest-spawned subprocess. Donation buys nothing on CPU smoke
# shapes; production TPU runs keep it.
if not _NEW_JAX:
    os.environ["PBT_DISABLE_DONATION"] = "1"
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/proteinbert_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.3")
jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])
jax.config.update(
    "jax_persistent_cache_min_compile_time_secs",
    float(os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]),
)

# Fail at collection, loudly and once, if neither mechanism produced the
# 8-device CPU mesh — otherwise every sharding/collective test fails
# later with a confusing "axis size mismatch" instead of the real cause
# (a sitecustomize that initialized the backend before XLA_FLAGS took).
if jax.device_count() < 8:
    raise RuntimeError(
        f"test harness needs 8 virtual CPU devices, got "
        f"{jax.device_count()} — the backend was initialized before "
        "XLA_FLAGS=--xla_force_host_platform_device_count=8 could apply "
        "(and this jax has no jax_num_cpu_devices option)")

import numpy as np
import pytest

# ---------------------------------------------------------------- map relief
# XLA's CPU thunk runtime JIT-maps every compiled kernel as its own small
# executable mapping and never unmaps it; a full-suite run accumulates
# ~60k mappings and segfaults inside LLVM when the process hits the
# kernel's vm.max_map_count (65530 default) — observed twice, always at
# the same test. Tearing the backend down releases them (measured
# 3320 → 610). This valve fires between MODULES only: module-scoped
# fixtures (tests/test_inference.py's `trunk`) legally hold device arrays
# across tests within a module, and a mid-module reset would kill them.
#
# INVARIANT for test authors: NO live jax.Array may be held across a
# module boundary — not via module-scoped fixtures only, but ANY
# mechanism (module-level globals, session-scoped fixtures, caches like
# functools.lru_cache over device arrays). clear_backends() invalidates
# every buffer created before it runs; a cross-module array surfaces
# later as a confusing "deleted/donated buffer" error in an unrelated
# test. Keep device state module-local, or re-create it per module.

_MAP_RESET_THRESHOLD = 35_000


def _map_count() -> int:
    try:
        with open("/proc/self/maps") as f:
            return sum(1 for _ in f)
    except OSError:  # non-Linux: no /proc, no known map ceiling either
        return 0


@pytest.fixture(autouse=True, scope="module")
def _jax_map_pressure_relief():
    if _map_count() >= _MAP_RESET_THRESHOLD:
        import gc

        import jax.extend.backend

        jax.clear_caches()
        jax.extend.backend.clear_backends()
        gc.collect()
    yield


# ---------------------------------------------------------- marker audit
# --strict-markers (pyproject) rejects UNREGISTERED marks; this check
# covers the other failure mode — a registered-but-FORGOTTEN mark. The
# scale tiers spawn multi-minute children; if a test in one of these
# modules ships without `slow`, tier-1's `-m 'not slow'` run collects it
# and the 870 s budget dies quietly. Fail at collection, naming the test.

_SLOW_REQUIRED_MODULES = ("test_parallel64", "test_multihost")


def pytest_collection_modifyitems(config, items):
    unmarked = [
        item.nodeid for item in items
        if item.module.__name__.rsplit(".", 1)[-1] in _SLOW_REQUIRED_MODULES
        and "slow" not in item.keywords
    ]
    if unmarked:
        raise pytest.UsageError(
            "scale-tier tests must carry the `slow` marker (tier-1's "
            "timeout budget assumes -m 'not slow' excludes them): "
            + ", ".join(unmarked))
    for item in items:
        if "tier64" in item.keywords and "slow" not in item.keywords:
            raise pytest.UsageError(
                f"{item.nodeid}: tier64 tests must also be marked slow")


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture
def key():
    return jax.random.PRNGKey(0)


from proteinbert_tpu.data.synthetic import make_random_proteins  # noqa: E402


@pytest.fixture
def random_proteins(rng):
    return make_random_proteins(64, rng)
