"""Unified telemetry subsystem (ISSUE 3): event schema round-trip,
metrics registry, span tracing, flight recorder, diagnose, and the
trainer wiring end-to-end on a CPU mesh."""

import importlib.util
import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from proteinbert_tpu import obs
from proteinbert_tpu.obs.diagnose import render, summarize

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------- events

def test_every_event_type_roundtrips_the_validator(tmp_path):
    """The tier-1 schema round trip: each event type → EventLog → JSONL
    → read back → validate_record, plus the validator tool itself."""
    path = tmp_path / "ev.jsonl"
    log = obs.EventLog(str(path))
    for event in sorted(obs.EVENT_FIELDS):
        example = obs.make_example(event)
        payload = {k: v for k, v in example.items()
                   if k not in ("v", "event", "seq", "t")}
        assert log.emit(event, **payload) is not None
    log.close()
    recs = obs.read_events(str(path), strict=True)
    assert [r["event"] for r in recs] == sorted(obs.EVENT_FIELDS)
    assert [r["seq"] for r in recs] == list(range(len(recs)))
    # And through the CLI validator (no jax import — fast).
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "validate_events.py"),
         str(path)], capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "0 errors" in out.stdout


def test_validator_self_test_and_rejection(tmp_path):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "validate_events.py"),
         "--self-test"], capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stdout + out.stderr
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps({"v": 1, "event": "step", "seq": 0,
                               "t": 0.0}) + "\n")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "validate_events.py"),
         str(bad)], capture_output=True, text=True, timeout=60)
    assert out.returncode == 1
    assert "missing required field" in out.stdout


def test_torn_tail_is_skipped_not_fatal(tmp_path):
    path = tmp_path / "ev.jsonl"
    log = obs.EventLog(str(path))
    log.emit("note", source="t")
    log.emit("note", source="t")
    log.close()
    with open(path, "a") as f:
        f.write('{"v": 1, "event": "note", "se')  # crash mid-write
    recs = obs.read_events(str(path), strict=True)
    assert len(recs) == 2  # torn tail dropped silently even under strict
    # A malformed MIDDLE line is a real corruption: strict raises.
    with open(path, "a") as f:
        f.write("\n" + json.dumps(obs.make_example("note")) + "\n")
    with pytest.raises(ValueError):
        obs.read_events(str(path), strict=True)
    assert len(obs.read_events(str(path))) == 3  # lax mode skips it


def test_emit_survives_record_key_collision(tmp_path):
    """A payload field colliding with a record key (t/seq/event/v) must
    be dropped, not raise out of emit (the never-raises contract —
    tools forward arbitrary status dicts into note events)."""
    log = obs.EventLog(str(tmp_path / "ev.jsonl"))
    assert log.emit("note", source="x", t=123.0) is None
    assert log.emit("note", source="x", seq=7) is None
    assert log.emit("note", source="x") is not None
    log.close()
    t = obs.Telemetry()  # flight-only mode has the same contract
    assert t.emit("note", source="x", t=123.0) is None
    assert t.emit("note", source="x") is not None


def test_sanitize_makes_nan_and_numpy_json_safe():
    rec = obs.sanitize({"loss": float("nan"), "inf": float("inf"),
                        "np": np.float32(1.5), "arr": (1, 2),
                        "nested": {"x": float("-inf")}})
    assert rec == {"loss": None, "inf": None, "np": 1.5,
                   "arr": [1, 2], "nested": {"x": None}}
    json.dumps(rec)  # strict-JSON safe


def test_emit_never_raises_on_bad_payload(tmp_path):
    log = obs.EventLog(str(tmp_path / "ev.jsonl"))
    assert log.emit("step", step=1) is None          # missing metrics
    assert log.emit("no_such_event") is None
    assert log.emit("step", step=1, metrics={"a": 1}) is not None
    log.close()
    assert len(obs.read_events(str(tmp_path / "ev.jsonl"),
                               strict=True)) == 1


# ------------------------------------------------------------ metrics

def test_metrics_registry_instruments_and_exports(tmp_path):
    reg = obs.MetricsRegistry()
    reg.counter("steps_total").inc(5)
    reg.gauge("mfu", window="cum").set(0.5)
    h = reg.histogram("stage_s")
    for v in (1.0, 3.0, 2.0):
        h.observe(v)
    with reg.timer("phase"):
        pass
    snap = reg.snapshot()
    assert snap["counters"]["steps_total"] == 5
    assert snap["gauges"]['mfu{window="cum"}'] == 0.5
    assert snap["histograms"]["stage_s"]["count"] == 3
    assert snap["histograms"]["stage_s"]["max"] == 3.0
    assert snap["histograms"]["phase"]["count"] == 1
    text = reg.prometheus_text()
    assert "# TYPE pbt_steps_total counter" in text
    assert 'pbt_mfu{window="cum"} 0.5' in text
    assert "pbt_stage_s_sum 6" in text
    # TYPE lines are per sample family, labels stripped — a labeled
    # histogram types pbt_<name>_count, never a bare pbt_<name>.
    lreg = obs.MetricsRegistry()
    with lreg.timer("phase", part="a"):
        pass
    ltext = lreg.prometheus_text()
    assert "# TYPE pbt_phase_count counter" in ltext
    assert 'pbt_phase_count{part="a"} 1' in ltext
    assert "# TYPE pbt_phase counter" not in ltext
    prom = tmp_path / "metrics.prom"
    reg.write_prometheus(str(prom))
    assert prom.read_text() == text
    reg.write_snapshot(str(tmp_path / "snap.jsonl"))
    line = json.loads((tmp_path / "snap.jsonl").read_text())
    assert line["counters"]["steps_total"] == 5


def test_zero_comm_bytes_land_in_registry():
    """The registry absorbs the ZeRO comm accounting: the same HLO
    parser bench.py --comm uses, exported as labeled gauges."""
    from proteinbert_tpu.parallel.zero import record_comm_metrics

    hlo = ("  x = f32[8,128]{1,0} all-reduce(f32[8,128]{1,0} p), "
           "replica_groups={}\n"
           "  y = f32[4,64]{1,0} reduce-scatter(f32[8,64]{1,0} q)\n")
    reg = obs.MetricsRegistry()
    out = record_comm_metrics(reg, hlo)
    gauges = reg.snapshot()["gauges"]
    assert gauges['collective_bytes{kind="all-reduce"}'] == 8 * 128 * 4
    assert gauges['collective_bytes{kind="reduce-scatter"}'] == 4 * 64 * 4
    assert gauges['collective_bytes{kind="total"}'] == out["total"]


def test_disabled_registry_is_a_noop():
    reg = obs.MetricsRegistry(enabled=False)
    reg.counter("c").inc()
    reg.gauge("g").set(1)
    reg.histogram("h").observe(1)
    with reg.timer("t"):
        pass
    reg.set_many({"a": 1.0})
    assert reg.snapshot() == {"counters": {}, "gauges": {},
                              "histograms": {}}


def test_null_facade_is_inert_and_is_the_default():
    # obs.NULL is the do-nothing telemetry every instrumented call
    # site runs through when none is configured: emit returns None,
    # span is a shared nullcontext, the registry is disabled, and
    # as_telemetry(None) hands back exactly this object.
    assert obs.as_telemetry(None) is obs.NULL
    assert obs.NULL.enabled is False
    assert obs.NULL.emit("step", step=1, metrics={}) is None
    with obs.NULL.span("anything"):
        pass
    assert obs.NULL.dump_flight("reason") is None
    obs.NULL.metrics.counter("c").inc()
    assert obs.NULL.metrics.snapshot() == {
        "counters": {}, "gauges": {}, "histograms": {}}
    tele = obs.Telemetry(metrics=False)
    assert obs.as_telemetry(tele) is tele


def test_profiler_shim_keeps_api_and_feeds_registry():
    from proteinbert_tpu.utils.profiling import Profiler

    reg = obs.MetricsRegistry()
    prof = Profiler(registry=reg)
    with prof.measure("etl"):
        pass
    with prof.measure("etl"):
        pass
    s = prof.summary()
    assert s["etl"]["count"] == 2
    assert s["etl"]["total_s"] >= 0
    assert "etl" in prof.report()
    # The sections landed in the SHARED registry, not a private dict.
    assert reg.snapshot()["histograms"]["etl"]["count"] == 2


# ------------------------------------------------------------ tracing

def test_span_collector_dump_feeds_trace_attribution(tmp_path):
    col = obs.SpanCollector()
    with obs.span("outer", collector=col):
        with obs.span("inner", collector=col, step=3):
            pass
    assert len(col) == 2
    names = {s["name"]: s for s in col.to_perfetto()["traceEvents"]
             if s["ph"] == "X"}
    assert names["inner"]["args"]["depth"] == 1
    assert names["inner"]["args"]["step"] == 3
    path = col.dump(str(tmp_path / "spans.trace.json"))
    # One format: the device-trace attribution tool parses a span dump.
    spec = importlib.util.spec_from_file_location(
        "trace_attribution", os.path.join(REPO, "tools",
                                          "trace_attribution.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    per_op = mod.parse_trace(path)
    assert set(per_op) == {"outer", "inner"}
    # Nested spans attribute SELF time: a 10s parent enclosing an 8s
    # child reports 2s + 8s, never 18s of double-counted wall.
    nested = tmp_path / "nested.trace.json"
    nested.write_text(json.dumps({"traceEvents": [
        {"ph": "X", "name": "parent", "pid": 1, "tid": 1,
         "ts": 0, "dur": 10_000_000, "args": {"depth": 0}},
        {"ph": "X", "name": "child", "pid": 1, "tid": 1,
         "ts": 1_000_000, "dur": 8_000_000, "args": {"depth": 1}},
    ]}))
    per = mod.parse_trace(str(nested))
    assert per["child"] == 8_000_000
    assert per["parent"] == 2_000_000


def test_prefetch_exposes_wait_accounting():
    from proteinbert_tpu.data.prefetch import prefetch

    it = prefetch(iter([{"a": 1}] * 5), depth=2)
    assert sum(1 for _ in it) == 5
    assert it.batches == 5
    assert it.wait_s >= 0.0


# ------------------------------------------------------------- flight

def test_flight_recorder_ring_and_dump(tmp_path):
    fr = obs.FlightRecorder(capacity=3, directory=str(tmp_path))
    for i in range(5):
        fr.record(obs.make_record("note", seq=i, t=float(i), source="t"))
    assert [r["seq"] for r in fr.snapshot()] == [2, 3, 4]  # bounded ring
    path = fr.dump("unit_test")
    assert path == obs.flight_path(str(tmp_path))
    payload = json.load(open(path))
    obs.validate_flight_dump(payload)
    assert payload["reason"] == "unit_test"
    assert [r["seq"] for r in payload["events"]] == [2, 3, 4]


def test_flight_excepthook_dumps_then_defers(tmp_path):
    fr = obs.FlightRecorder(capacity=8, directory=str(tmp_path))
    fr.record(obs.make_record("note", seq=0, t=0.0, source="t"))
    seen = []
    prev = sys.excepthook
    sys.excepthook = lambda *a: seen.append(a)
    try:
        fr.install_excepthook()
        sys.excepthook(RuntimeError, RuntimeError("boom"), None)
        assert seen, "previous hook was not chained"
        payload = json.load(open(obs.flight_path(str(tmp_path))))
        obs.validate_flight_dump(payload)
        assert payload["reason"] == "unhandled_RuntimeError"
    finally:
        fr.uninstall_excepthook()
        sys.excepthook = prev


# ----------------------------------------------------------- diagnose

def _synthetic_stream(path):
    t = obs.Telemetry(events_path=str(path))
    t.emit("run_start", step=0, config={"train": {}}, jax_version="0",
           pid=os.getpid(), mesh={"data": 8}, n_chips=8, resumed=False)
    for i, (win_ms, ckpt) in enumerate(
            [(100.0, 0.0), (105.0, 0.0), (900.0, 1.0), (110.0, 0.0)]):
        t.emit("step", step=10 * (i + 1), metrics={
            "loss": 1.0 / (i + 1), "steps_per_sec": 9.5,
            "window_steps_per_sec": 1000.0 / win_ms,
            "window_step_ms": win_ms, "ckpt_in_flight": ckpt})
    t.emit("ckpt_stage", step=20, phase="dispatch")
    t.emit("ckpt_stage", step=20, phase="landed", saved=True,
           overlap_s=2.0)
    t.emit("eval", step=20, metrics={"eval_loss": 0.5})
    t.emit("run_end", outcome="completed", step=40,
           perf={"steps_per_sec": 9.5, "overlap_s": 2.0})
    t.close()


def test_diagnose_summary_and_render(tmp_path):
    path = tmp_path / "ev.jsonl"
    _synthetic_stream(path)
    recs = obs.read_events(str(path), strict=True)
    s = summarize(recs, slow_top=2, last=3)
    assert s["outcome"] == "completed"
    assert s["manifest"]["mesh"] == {"data": 8}
    assert s["step_rate"]["steps_per_sec"] == 9.5
    # The injected 900ms window tops the stall list, latch attached.
    assert s["stalls"][0]["step"] == 30
    assert s["stalls"][0]["ckpt_in_flight"] is True
    assert s["boundary"]["ckpt_stages_landed"] == 1
    assert s["boundary"]["overlap_s"] == 2.0
    assert s["boundary"]["overlap_ratio"] is not None
    assert len(s["last_events"]) == 3
    text = render(s)
    assert "[ckpt]" in text and "900.00" in text


def test_diagnose_segments_requeued_stream(tmp_path):
    """A requeued run appends a fresh run_start to the same file; the
    summary's manifest/rates must cover the LAST incarnation, not mix
    the dead run's pid and the restart gap into the numbers."""
    path = tmp_path / "ev.jsonl"
    t = obs.Telemetry(events_path=str(path))
    t.emit("run_start", step=0, config={}, jax_version="0", pid=111)
    t.emit("step", step=10, metrics={"loss": 1.0})
    t.emit("requeue", step=10, reason="signal_15")
    t.emit("run_start", step=10, config={}, jax_version="0", pid=222,
           resumed=True)
    t.emit("step", step=20, metrics={"loss": 0.5, "steps_per_sec": 3.0})
    t.emit("run_end", outcome="completed", step=20, perf={})
    t.close()
    s = summarize(obs.read_events(str(path), strict=True))
    assert s["incarnations"] == 2
    assert s["manifest"]["pid"] == 222          # the live incarnation
    assert s["counts"]["run_start"] == 2        # whole file still counted
    assert s["counts"]["requeue"] == 1


def test_diagnose_cli_json_and_flight(tmp_path, capsys):
    from proteinbert_tpu.cli.main import main

    path = tmp_path / "ev.jsonl"
    _synthetic_stream(path)
    # A flight dump from the same stream.
    fr = obs.FlightRecorder(capacity=4, directory=str(tmp_path))
    for r in obs.read_events(str(path)):
        fr.record(r)
    fpath = fr.dump("sigterm_test")
    assert main(["diagnose", str(path), "--flight", fpath, "--json"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["outcome"] == "completed"
    assert out["flight"]["reason"] == "sigterm_test"
    assert out["last_events"][-1]["event"] == "run_end"
    # Human report mode on the same artifacts.
    assert main(["diagnose", str(path)]) == 0
    assert "step rate" in capsys.readouterr().out


# ------------------------------------------------- trainer end-to-end

def test_pretrain_emits_validating_stream_matching_steptimer(tmp_path):
    """The acceptance dryrun: a short CPU-mesh training run produces one
    events JSONL that validates, holds every lifecycle record, and from
    which diagnose reports step rate and boundary overlap matching
    StepTimer within 1%."""
    from proteinbert_tpu.configs import (
        CheckpointConfig, DataConfig, MeshConfig, ModelConfig,
        OptimizerConfig, PretrainConfig, TrainConfig,
    )
    from proteinbert_tpu.data import (
        InMemoryPretrainingDataset, make_pretrain_iterator,
    )
    from proteinbert_tpu.data.synthetic import make_random_proteins
    from proteinbert_tpu.parallel import make_mesh
    from proteinbert_tpu.train import Checkpointer
    from proteinbert_tpu.train.trainer import pretrain

    cfg = PretrainConfig(
        model=ModelConfig(local_dim=16, global_dim=32, key_dim=8,
                          num_heads=4, num_blocks=1, num_annotations=64,
                          dtype="float32"),
        data=DataConfig(seq_len=64, batch_size=8),
        optimizer=OptimizerConfig(warmup_steps=4),
        mesh=MeshConfig(data=2),
        checkpoint=CheckpointConfig(directory=str(tmp_path / "ck"),
                                    every_steps=4, overlap=True),
        train=TrainConfig(max_steps=8, log_every=2, eval_every=4),
    )
    rng = np.random.default_rng(0)
    seqs, ann = make_random_proteins(64, rng, num_annotations=64)
    ds = InMemoryPretrainingDataset(seqs, ann, 64)
    ck = Checkpointer(cfg.checkpoint.directory, async_save=False)
    tele = obs.Telemetry(events_path=str(tmp_path / "ev.jsonl"))
    out = pretrain(
        cfg, lambda skip: make_pretrain_iterator(ds, 8, seed=0),
        checkpointer=ck,
        mesh=make_mesh(cfg.mesh, devices=jax.devices()[:2]),
        eval_batches=lambda: make_pretrain_iterator(ds, 8, seed=1,
                                                    num_epochs=1),
        telemetry=tele)
    ck.close()
    tele.close()

    recs = obs.read_events(str(tmp_path / "ev.jsonl"), strict=True)
    kinds = {r["event"] for r in recs}
    assert {"run_start", "step", "ckpt_stage", "eval", "run_end"} <= kinds
    assert recs[0]["event"] == "run_start"
    assert recs[0]["jax_version"]
    assert recs[0]["config"]["train"]["max_steps"] == 8
    assert recs[0]["mesh"] == {"data": 2, "fsdp": 1, "model": 1, "seq": 1}
    assert recs[-1]["event"] == "run_end"
    assert recs[-1]["outcome"] == "completed"
    # The per-chip state-bytes gauges landed (sharding-rule accounting).
    gauges = tele.metrics.snapshot()["gauges"]
    assert gauges.get('per_chip_state_bytes{part="total"}', 0) > 0

    s = summarize(recs)
    perf = out["perf"]
    assert s["step_rate"]["steps_per_sec"] == pytest.approx(
        perf["steps_per_sec"], rel=0.01)
    assert s["boundary"]["overlap_s"] == pytest.approx(
        perf.get("overlap_s", 0.0), rel=0.01, abs=1e-9)
    # Step events carry the data-pipeline wait gauge (prefetch_depth=2).
    step_recs = [r for r in recs if r["event"] == "step"]
    assert all("data_wait_s" in r for r in step_recs)
    # The registry absorbed the run: counters + StepTimer gauges live.
    snap = tele.metrics.snapshot()
    assert snap["counters"]["steps_total"] == 8
    assert "steps_per_sec" in snap["gauges"]
