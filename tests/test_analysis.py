"""`pbt check` static analyzer (ISSUE 15): per-rule seeded-violation +
clean fixtures, baseline round-trip, --json schema, and the repo-wide
zero-findings smoke that IS the tier-1 gate's contract.

Fixtures are tiny trees written under tmp_path and run through the
same `run_check` the tier-1 stage uses — no monkeypatching of rule
internals, so a rule that silently stopped matching its pattern fails
its seeded fixture here before it silently passes the repo."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from proteinbert_tpu.analysis import (
    CheckConfig, load_baseline, run_check, save_baseline,
    split_by_baseline,
)
from proteinbert_tpu.analysis.findings import BaselineError, report_dict

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def write_tree(root, files):
    for rel, content in files.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(content))


def fixture_cfg(root, **overrides):
    defaults = dict(
        root=str(root), scan_roots=("pkg",), durability_files=(),
        events_py="pkg/events.py", docs_md="docs.md",
        reference_roots=("pkg",))
    defaults.update(overrides)
    return CheckConfig(**defaults)


def keys(result):
    return {f.key for f in result["findings"]}


def rules_hit(result):
    return {f.rule for f in result["findings"]}


# ------------------------------------------------------------ rule 1

JIT_VIOLATION = """
    import os
    import random
    import time

    import jax
    import numpy as np


    def helper(x):
        return x * time.time()          # clock at trace time


    def step(x):
        if os.environ.get("MY_FLAG"):   # unsanctioned env read
            x = x + random.random()     # host randomness
        return helper(x) + np.random.rand()


    train = jax.jit(step)
"""

JIT_CLEAN = """
    import time

    import jax


    def sanctioned_reader():
        import os
        return bool(os.environ.get("FLAG"))


    def step(x):
        return x * 2 if sanctioned_reader() else x


    def host_loop(x):
        t0 = time.time()                # host side: fine
        return jax.jit(step)(x), time.time() - t0
"""


def test_jit_purity_seeded_violation(tmp_path):
    write_tree(tmp_path, {"pkg/m.py": JIT_VIOLATION})
    res = run_check(fixture_cfg(tmp_path), rules=["jit-purity"])
    got = keys(res)
    # All four host-state classes flagged, including through the
    # module-local call chain (step -> helper).
    assert any("time.time" in k and "helper" in k for k in got)
    assert any("os.environ" in k for k in got)
    assert any("random.random" in k for k in got)
    assert any("np.random.rand" in k for k in got)


def test_jit_purity_clean_and_sanctioned(tmp_path):
    write_tree(tmp_path, {"pkg/m.py": JIT_CLEAN})
    cfg = fixture_cfg(tmp_path,
                      sanctioned_env_readers=("sanctioned_reader",))
    res = run_check(cfg, rules=["jit-purity"])
    assert res["findings"] == []


def test_jit_purity_pallas_dispatch_wrapper_is_a_root(tmp_path):
    # A function CONTAINING a pallas_call runs at trace time of the
    # (cross-module) jit that wraps it — its body is held to the bar.
    write_tree(tmp_path, {"pkg/k.py": """
        import os
        from jax.experimental import pallas as pl


        def kernel(ref):
            ref[...] = ref[...] * 2


        def dispatch(x):
            if os.environ.get("FORCE_SLOW"):
                return x
            return pl.pallas_call(kernel)(x)
    """})
    res = run_check(fixture_cfg(tmp_path), rules=["jit-purity"])
    assert any("os.environ" in k and "dispatch" in k for k in keys(res))


def test_jit_purity_composed_dispatch_chain(tmp_path):
    # The one-pass trunk pattern (ISSUE 16): a dispatch wrapper whose
    # FALLBACK path calls another dispatch wrapper. Host state anywhere
    # along the composed chain (onepass -> inner) is still trace-time
    # state of the outer jit, so the rule must flag it through the
    # chain — while the sanctioned force-override reader stays clean.
    write_tree(tmp_path, {"pkg/k.py": """
        import time

        from jax.experimental import pallas as pl


        def force_reference_requested():
            import os
            return bool(os.environ.get("FORCE_SLOW"))


        def kernel(ref):
            ref[...] = ref[...] * 2


        def inner(x):
            x = x * time.time()         # clock at trace time
            return pl.pallas_call(kernel)(x)


        def onepass(x):
            if force_reference_requested():   # sanctioned: clean
                return inner(x)
            return pl.pallas_call(kernel)(x)
    """})
    cfg = fixture_cfg(
        tmp_path, sanctioned_env_readers=("force_reference_requested",))
    res = run_check(cfg, rules=["jit-purity"])
    got = keys(res)
    assert any("time.time" in k and "inner" in k for k in got)
    assert not any("os.environ" in k for k in got)


# ------------------------------------------------------------ rule 2

LOCK_VIOLATION = """
    import threading


    class Router:
        def __init__(self):
            self._lock = threading.Lock()
            self.sealed = 0        # guarded-by: _lock
            self.ended = False     # guarded-by: _lock

        def seal(self):
            with self._lock:
                self.sealed += 1

        def drain(self):
            if not self.ended:     # unlocked read
                self.ended = True  # unlocked write
"""

LOCK_CLEAN = """
    import threading


    class Router:
        def __init__(self):
            self._lock = threading.Lock()
            self.sealed = 0        # guarded-by: _lock

        def seal(self):
            with self._lock:
                self.sealed += 1

        def _report(self):  # lock-held: _lock
            return self.sealed

        def stats(self):
            with self._lock:
                return {"sealed": self.sealed, "r": self._report()}
"""


def test_lock_discipline_seeded_violation(tmp_path):
    write_tree(tmp_path, {"pkg/m.py": LOCK_VIOLATION})
    res = run_check(fixture_cfg(tmp_path), rules=["lock-discipline"])
    got = keys(res)
    assert "lock-discipline::pkg/m.py::Router.drain:ended" in got
    # seal() is locked — must NOT be flagged.
    assert not any("seal" in k for k in got)


def test_lock_discipline_clean_with_lock_held_annotation(tmp_path):
    write_tree(tmp_path, {"pkg/m.py": LOCK_CLEAN})
    res = run_check(fixture_cfg(tmp_path), rules=["lock-discipline"])
    assert res["findings"] == []


def test_lock_discipline_closure_does_not_inherit_region(tmp_path):
    write_tree(tmp_path, {"pkg/m.py": """
        import threading


        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0         # guarded-by: _lock

            def make_cb(self):
                def cb():
                    return self.n  # runs later, lock NOT held
                with self._lock:
                    return cb
    """})
    res = run_check(fixture_cfg(tmp_path), rules=["lock-discipline"])
    assert any("make_cb.cb:n" in k for k in keys(res))


def test_lock_order_cycle_detected(tmp_path):
    write_tree(tmp_path, {"pkg/m.py": """
        import threading

        a_lock = threading.Lock()
        b_lock = threading.Lock()


        def one():
            with a_lock:
                with b_lock:
                    pass


        def two():
            with b_lock:
                with a_lock:
                    pass
    """})
    res = run_check(fixture_cfg(tmp_path), rules=["lock-discipline"])
    assert any(k.startswith("lock-discipline::pkg/m.py::lock-order:")
               for k in keys(res))


# ------------------------------------------------------------ rule 3

DURABILITY_VIOLATION = """
    import os


    def save_no_fsync(path, data):
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)       # no fsync between write and rename


    def save_bare(path, data):
        with open(path, "wb") as f:  # bytes straight to the final path
            f.write(data)
"""

DURABILITY_CLEAN = """
    import os


    def save(path, data):
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)


    def append_log(path, line):
        with open(path, "a", buffering=1) as f:   # append-only: exempt
            f.write(line)
"""


def test_durability_seeded_violations(tmp_path):
    write_tree(tmp_path, {"pkg/store.py": DURABILITY_VIOLATION})
    cfg = fixture_cfg(tmp_path, durability_files=("pkg/store.py",))
    res = run_check(cfg, rules=["durability-protocol"])
    got = keys(res)
    assert any("rename-without-fsync" in k and "save_no_fsync" in k
               for k in got)
    assert any("bare-final-write" in k and "save_bare" in k for k in got)


def test_durability_clean(tmp_path):
    write_tree(tmp_path, {"pkg/store.py": DURABILITY_CLEAN})
    cfg = fixture_cfg(tmp_path, durability_files=("pkg/store.py",))
    res = run_check(cfg, rules=["durability-protocol"])
    assert res["findings"] == []


# ------------------------------------------------------------ rule 4

EVENTS_PY = """
    EVENT_FIELDS = {
        "step": {"step": int, "metrics": dict},
        "note": {"source": str},
        "burn": {"rate": (int, float)},
    }
"""


def test_event_schema_seeded_violations(tmp_path):
    write_tree(tmp_path, {
        "pkg/events.py": EVENTS_PY,
        "pkg/m.py": """
            def go(tele, fields):
                tele.emit("stepp", step=1, metrics={})     # unknown
                tele.emit("step", step=1)                  # missing
                tele.emit("step", step="one", metrics={})  # wrong type
                tele.emit("burn", rate=2)                  # int ok
                tele.emit("step", **fields)                # spread: skip
        """,
    })
    res = run_check(fixture_cfg(tmp_path), rules=["event-schema"])
    got = keys(res)
    assert "event-schema::pkg/m.py::emit:stepp:unknown-event" in got
    assert "event-schema::pkg/m.py::emit:step:missing:metrics" in got
    assert "event-schema::pkg/m.py::emit:step:type:step" in got
    assert len(got) == 3  # burn + spread pass


def test_event_schema_clean(tmp_path):
    write_tree(tmp_path, {
        "pkg/events.py": EVENTS_PY,
        "pkg/m.py": """
            def go(tele, n):
                tele.emit("step", step=n, metrics={"loss": 1.0})
                tele.emit("note", source="test", extra=True)
        """,
    })
    res = run_check(fixture_cfg(tmp_path), rules=["event-schema"])
    assert res["findings"] == []


# ------------------------------------------------------------ rule 5

def test_obs_doc_drift_seeded_violations(tmp_path):
    write_tree(tmp_path, {
        "pkg/events.py": EVENTS_PY,
        "pkg/m.py": """
            def instruments(reg):
                reg.counter("undocumented_thing_total").inc()
                reg.gauge("documented_depth").set(1)
        """,
        "docs.md": """
            # doc

            ## Event schema

            | event | payload |
            |---|---|
            | `step` | `step`, `metrics` |
            | `note` | `source` |
            | `ghost_event` | gone |

            ## Metric names

            `documented_depth` and `ghost_metric_total` are exported.

            ## Next section
        """,
    })
    res = run_check(fixture_cfg(tmp_path), rules=["obs-doc-drift"])
    got = keys(res)
    assert any("event-undocumented:burn" in k for k in got)
    assert any("event-ghost:ghost_event" in k for k in got)
    assert any("metric-undocumented:undocumented_thing_total" in k
               for k in got)
    assert any("metric-ghost:ghost_metric_total" in k for k in got)


def test_obs_doc_drift_clean_with_brace_expansion(tmp_path):
    write_tree(tmp_path, {
        "pkg/events.py": """
            EVENT_FIELDS = {"note": {"source": str}}
        """,
        "pkg/m.py": """
            def instruments(reg):
                reg.counter("cache_hits_total").inc()
                reg.counter("cache_misses_total").inc()
        """,
        "docs.md": """
            ## Event schema

            | `note` | `source` |

            ## Metric names

            `cache_{hits,misses}_total` counters.
        """,
    })
    res = run_check(fixture_cfg(tmp_path), rules=["obs-doc-drift"])
    assert res["findings"] == []


# ------------------------------------------------------------ rule 6

def test_dead_export_seeded_violation(tmp_path):
    write_tree(tmp_path, {
        "pkg/__init__.py": """
            from pkg.mod import used_fn, dead_fn

            __all__ = ["used_fn", "dead_fn"]
        """,
        "pkg/mod.py": """
            def used_fn():
                return 1


            def dead_fn():
                return 2
        """,
        "pkg/caller.py": """
            from pkg.mod import used_fn

            print(used_fn())
        """,
    })
    res = run_check(fixture_cfg(tmp_path), rules=["dead-export"])
    got = keys(res)
    assert "dead-export::pkg/__init__.py::export:dead_fn" in got
    assert not any("used_fn" in k for k in got)


def test_dead_export_clean(tmp_path):
    write_tree(tmp_path, {
        "pkg/__init__.py": """
            from pkg.mod import used_fn

            __all__ = ["used_fn"]
        """,
        "pkg/mod.py": "def used_fn():\n    return 1\n",
        "pkg/caller.py": "from pkg.mod import used_fn\nused_fn()\n",
    })
    res = run_check(fixture_cfg(tmp_path), rules=["dead-export"])
    assert res["findings"] == []


# ---------------------------------------------------- parse gate

def test_syntax_error_is_a_finding_not_a_skip(tmp_path):
    write_tree(tmp_path, {"pkg/broken.py": "def broken(:\n"})
    res = run_check(fixture_cfg(tmp_path), rules=["jit-purity"])
    assert any(f.rule == "parse" for f in res["findings"])


def test_write_baseline_refuses_to_suppress_syntax_errors(tmp_path):
    """A baselined parse finding would let every rule silently skip
    that file forever — --write-baseline must refuse (exit 2), never
    stub it."""
    write_tree(tmp_path, {
        "proteinbert_tpu/broken.py": "def broken(:\n",
        "proteinbert_tpu/obs/events.py": "EVENT_FIELDS = {}\n",
        "docs/observability.md": "## Event schema\n\n## Metric names\n",
    })
    baseline = str(tmp_path / "b.json")
    proc = run_pbt_check("--root", str(tmp_path), "--baseline",
                         baseline, "--write-baseline")
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert not os.path.exists(baseline)


# ---------------------------------------------------- baseline

def test_baseline_round_trip_and_staleness(tmp_path):
    write_tree(tmp_path, {"pkg/m.py": LOCK_VIOLATION})
    cfg = fixture_cfg(tmp_path)
    res = run_check(cfg, rules=["lock-discipline"])
    assert res["findings"]
    path = str(tmp_path / "baseline.json")
    save_baseline(path, {f.key: "accepted: fixture debt"
                         for f in res["findings"]})
    loaded = load_baseline(path)
    new, suppressed, stale = split_by_baseline(res["findings"], loaded)
    assert new == [] and len(suppressed) == len(res["findings"])
    assert stale == []
    # An entry whose violation is gone must surface as stale.
    loaded["lock-discipline::pkg/gone.py::X.y:z"] = "paid down"
    new, suppressed, stale = split_by_baseline(res["findings"], loaded)
    assert stale == ["lock-discipline::pkg/gone.py::X.y:z"]


def test_baseline_requires_reasons(tmp_path):
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps(
        {"v": 1, "suppressions": [{"key": "a::b::c", "reason": "  "}]}))
    with pytest.raises(BaselineError):
        load_baseline(str(path))


def test_report_dict_json_schema():
    rep = report_dict([], [], [], {}, ["jit-purity"])
    assert rep["v"] == 1 and rep["kind"] == "pbt_check_report"
    assert rep["ok"] is True
    assert rep["counts"] == {"new": 0, "baselined": 0,
                             "stale_baseline": 0,
                             "check_findings_total": 0}
    json.dumps(rep)  # strict-JSON-able


# ---------------------------------------------------- CLI / gate

def run_pbt_check(*argv):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "pbt_check.py"),
         *argv],
        capture_output=True, text=True, cwd=REPO)


def test_tier1_gate_fails_on_injected_violation(tmp_path):
    """The acceptance-criteria smoke: the tier-1 stage (the same
    tools/pbt_check.py invocation run_tier1.sh makes) must exit
    nonzero on a tree with an injected violation."""
    write_tree(tmp_path, {
        "proteinbert_tpu/bad.py": LOCK_VIOLATION,
        # Minimal schema/doc so the other rules run without config
        # errors against this synthetic root.
        "proteinbert_tpu/obs/events.py": 'EVENT_FIELDS = {}\n',
        "docs/observability.md": "## Event schema\n\n## Metric names\n",
    })
    proc = run_pbt_check("--root", str(tmp_path), "--json")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    rep = json.loads(proc.stdout)
    assert rep["ok"] is False
    assert any(f["rule"] == "lock-discipline" for f in rep["findings"])
    # --write-baseline then re-check: the gate goes green, proving the
    # suppression path end to end.
    baseline = str(tmp_path / "b.json")
    assert run_pbt_check("--root", str(tmp_path), "--baseline", baseline,
                         "--write-baseline").returncode == 0
    proc = run_pbt_check("--root", str(tmp_path), "--baseline", baseline,
                         "--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rep = json.loads(proc.stdout)
    assert rep["counts"]["new"] == 0 and rep["counts"]["baselined"] > 0


def test_repo_smoke_zero_nonbaselined_findings():
    """THE repo gate: `pbt check` over the real tree with the real
    baseline is clean, jax-free, and the baseline holds <= 5 entries
    (ISSUE 15 acceptance criteria)."""
    proc = run_pbt_check("--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rep = json.loads(proc.stdout)
    assert rep["ok"] is True and rep["findings"] == []
    assert rep["counts"]["baselined"] <= 5
    assert rep["stale_baseline"] == []
    assert set(rep["rules"]) == {
        "jit-purity", "lock-discipline", "durability-protocol",
        "event-schema", "obs-doc-drift", "dead-export"}


def test_pbt_check_runs_without_jax(tmp_path):
    """tools/pbt_check.py must work where jax cannot import — the
    whole point of the stub-package entry. Simulate by poisoning jax
    on the import path."""
    (tmp_path / "jax").mkdir()
    (tmp_path / "jax" / "__init__.py").write_text(
        'raise ImportError("jax must not be imported by pbt check")\n')
    env = dict(os.environ,
               PYTHONPATH=str(tmp_path) + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "pbt_check.py")],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_schema_sync_mode_covers_every_event_type():
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "validate_events.py"),
         "--schema-sync"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "schema-sync OK" in proc.stdout


def test_trajectory_learns_check_findings_series(tmp_path):
    # History: three check_capture notes mirrored by `pbt check
    # --events-jsonl` (emitted through the real runner so the
    # platform="static" key can never drift from what the trajectory
    # expects) + the fresh artifact — all FOUR points must land on ONE
    # judged series.
    events = tmp_path / "bench_events.jsonl"
    for _ in range(3):
        proc = run_pbt_check("--events-jsonl", str(events))
        assert proc.returncode == 0, proc.stdout + proc.stderr
    artifact = tmp_path / "check.json"
    artifact.write_text(json.dumps(
        {"v": 1, "kind": "pbt_check_report",
         "counts": {"check_findings_total": 2}}))
    out = tmp_path / "verdict.json"
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "bench_trajectory.py"),
         "--repo", str(tmp_path), "--check-json", str(artifact),
         "--output", str(out)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    verdict = json.loads(out.read_text())
    series = verdict["series"]["check_findings_total/static"]
    assert series["values"] == [0.0, 0.0, 0.0, 2.0]
    assert series["higher_is_better"] is False
    # With 3 prior points the newest is actually JUDGED (the whole
    # point of unifying the event and artifact series keys).
    assert series["verdict"] != "insufficient_data"
    # A malformed artifact is an input ERROR (exit 2), never silence.
    artifact.write_text("{}")
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "bench_trajectory.py"),
         "--repo", str(tmp_path), "--check-json", str(artifact)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 2


# ------------------------------------------- fixed-violation regressions

def test_scheduler_stats_counts_is_locked_and_coherent():
    """Regression for the unlocked stats()-path reads the lock rule
    surfaced (ISSUE 15 satellite): the dispatch counters update under
    _pending_lock and read through one locked stats_counts()."""
    import threading

    from proteinbert_tpu.serve.queue import RequestQueue
    from proteinbert_tpu.serve.scheduler import MicroBatchScheduler

    class StubDispatcher:
        class cfg:
            class model:
                num_annotations = 0

        def batch_class(self, n):
            return n

        def run(self, kind, tokens, annotations):
            return [t for t in tokens]

    sched = MicroBatchScheduler(RequestQueue(8), StubDispatcher(),
                                finalize=lambda req, row: None)
    assert sched.stats_counts() == (0, 0, 0)
    # The locked read must not deadlock against a concurrent locked
    # update (both sides use _pending_lock).
    done = []

    def reader():
        for _ in range(200):
            sched.stats_counts()
        done.append(True)

    t = threading.Thread(target=reader)
    t.start()
    for _ in range(200):
        with sched._pending_lock:
            sched.batches_total += 1
    t.join(timeout=10)
    assert done and sched.stats_counts()[0] == 200


def test_fleet_drain_emits_exactly_one_terminal_record():
    """Regression for the unlocked `_ended` latch in FleetRouter.drain
    (ISSUE 15 satellite): concurrent drains seal exactly once."""
    import threading

    from proteinbert_tpu import obs
    from proteinbert_tpu.serve.fleet import FleetRouter

    class RecordingTele(obs.Telemetry):
        def __init__(self):
            super().__init__(metrics=False)
            self.fleet_ends = 0
            self._count_lock = threading.Lock()

        def emit(self, event, **fields):
            if event == "fleet_end":
                with self._count_lock:
                    self.fleet_ends += 1
            return None

    tele = RecordingTele()
    router = FleetRouter(["http://127.0.0.1:1"], telemetry=tele,
                         health_interval_s=0)
    threads = [threading.Thread(target=router.drain) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert tele.fleet_ends == 1
