"""Tests for host utilities (reference C17/C20/C21 parity)."""

import os
import numpy as np
import pytest

from proteinbert_tpu.utils import (
    Profiler,
    TimeMeasure,
    shard_items,
    shard_range,
    task_identity,
    to_chunks,
)
from proteinbert_tpu.utils.h5 import (
    find_linearly_independent_columns,
    normalize,
    random_mask,
    transpose_dataset,
)


def test_to_chunks():
    assert list(to_chunks(range(7), 3)) == [[0, 1, 2], [3, 4, 5], [6]]
    assert list(to_chunks([], 3)) == []
    with pytest.raises(ValueError):
        list(to_chunks([1], 0))


def test_shard_range_covers_and_balances():
    n, k = 17, 5
    spans = [shard_range(n, i, k) for i in range(k)]
    assert spans[0][0] == 0 and spans[-1][1] == n
    for (a, b), (c, d) in zip(spans, spans[1:]):
        assert b == c
    sizes = [b - a for a, b in spans]
    assert max(sizes) - min(sizes) <= 1
    assert shard_items(list(range(10)), 1, 3) == [4, 5, 6]


def test_task_identity(monkeypatch):
    monkeypatch.delenv("SLURM_ARRAY_TASK_ID", raising=False)
    monkeypatch.delenv("TASK_INDEX", raising=False)
    assert task_identity() == (0, 1)
    assert task_identity(2, 4) == (2, 4)
    with pytest.raises(ValueError):
        task_identity(4, 4)
    monkeypatch.setenv("SLURM_ARRAY_TASK_ID", "3")
    monkeypatch.setenv("SLURM_ARRAY_TASK_COUNT", "8")
    assert task_identity() == (3, 8)
    monkeypatch.setenv("TASK_ID_OFFSET", "10")
    assert task_identity() == (13, 8)


def test_profiler_and_time_measure():
    p = Profiler()
    with p.measure("a"):
        pass
    with p.measure("a"):
        pass
    with p.measure("b"):
        pass
    s = p.summary()
    assert s["a"]["count"] == 2 and s["b"]["count"] == 1
    assert "a:" in p.report()
    with TimeMeasure("t", verbose=False) as tm:
        pass
    assert tm.elapsed is not None and tm.elapsed >= 0


def test_start_log_idempotent_file_handler(tmp_path):
    """Regression (ISSUE 3 satellite): repeated start_log() with the
    same log_dir attached a SECOND FileHandler — every line then landed
    twice in the file."""
    import logging as pylogging

    from proteinbert_tpu.utils.logging import _LOGGER, log, start_log

    try:
        p1 = start_log(log_dir=str(tmp_path), pid_stamp=False)
        n_handlers = len(_LOGGER.handlers)
        p2 = start_log(log_dir=str(tmp_path), pid_stamp=False)
        assert p1 == p2
        assert len(_LOGGER.handlers) == n_handlers  # no double handler
        log("once-only-marker")
        with open(p1) as f:
            assert f.read().count("once-only-marker") == 1
        # A DIFFERENT directory is a new sink, not a duplicate.
        other = tmp_path / "other"
        start_log(log_dir=str(other), pid_stamp=False)
        assert len(_LOGGER.handlers) == n_handlers + 1
    finally:
        for h in list(_LOGGER.handlers):
            if isinstance(h, pylogging.FileHandler):
                _LOGGER.removeHandler(h)
                h.close()


def test_transpose_dataset(tmp_path):
    import h5py

    rng = np.random.default_rng(0)
    x = rng.random((37, 11)).astype(np.float32)
    with h5py.File(tmp_path / "t.h5", "w") as f:
        f.create_dataset("src", data=x)
        transpose_dataset(f, "src", "dst", chunk_rows=8)
        np.testing.assert_array_equal(f["dst"][:], x.T)


def test_numpy_helpers():
    rng = np.random.default_rng(0)
    v = normalize(rng.random((4, 6)))
    np.testing.assert_allclose(np.linalg.norm(v, axis=-1), 1.0, atol=1e-9)
    m = random_mask((1000,), 0.3, rng)
    assert 0.2 < m.mean() < 0.4
    # col2 = col0 + col1 → dependent; expect 3 independent of 4.
    a = rng.random((10, 2))
    x = np.column_stack([a[:, 0], a[:, 1], a[:, 0] + a[:, 1], rng.random(10)])
    idx = find_linearly_independent_columns(x)
    assert len(idx) == 3


# ---------------------------------------------------------------- stats (C22)

def test_one_hot():
    from proteinbert_tpu.utils.stats import one_hot

    out = one_hot([0, 2, 1], 4)
    assert out.shape == (3, 4)
    np.testing.assert_array_equal(out.argmax(1), [0, 2, 1])
    assert out.sum() == 3
    # Inferred class count + empty input (the reference's version returns
    # None always — SURVEY ledger #12).
    assert one_hot([1, 1]).shape == (2, 2)
    assert one_hot([]).shape == (0, 0)
    with pytest.raises(ValueError):
        one_hot([-1])


def test_benjamini_hochberg():
    from proteinbert_tpu.utils.stats import benjamini_hochberg

    p = np.array([0.01, 0.04, 0.03, 0.005])
    q = benjamini_hochberg(p)
    # BH: sorted p * n/rank with monotone enforcement
    np.testing.assert_allclose(q, [0.02, 0.04, 0.04, 0.02])
    assert benjamini_hochberg([]).size == 0
    assert (benjamini_hochberg(np.ones(5)) == 1.0).all()


def test_benjamini_hochberg_with_nulls():
    from proteinbert_tpu.utils.stats import (
        benjamini_hochberg, benjamini_hochberg_with_nulls)

    # NaN holes are excluded from the ranking (reference
    # shared_utils/util.py:888-898): the 4 real p-values must get the
    # SAME q-values as if the NaNs were never there.
    p = np.array([0.01, np.nan, 0.04, 0.03, np.nan, 0.005])
    sig, q = benjamini_hochberg_with_nulls(p, alpha=0.05)
    mask = ~np.isnan(p)
    np.testing.assert_allclose(q[mask], benjamini_hochberg(p[mask]))
    assert np.isnan(q[~mask]).all()
    assert sig[mask].all() and not sig[~mask].any()
    # Significance respects alpha on the adjusted values.
    sig_tight, q_tight = benjamini_hochberg_with_nulls(p, alpha=0.03)
    np.testing.assert_array_equal(sig_tight, q_tight <= 0.03,
                                  err_msg="holes compare False vs NaN")
    # All-NaN and empty inputs degrade gracefully.
    sig_n, q_n = benjamini_hochberg_with_nulls([np.nan, np.nan])
    assert not sig_n.any() and np.isnan(q_n).all()
    sig_e, q_e = benjamini_hochberg_with_nulls([])
    assert sig_e.size == 0 and q_e.size == 0


def test_fisher_enrichment():
    from proteinbert_tpu.utils.stats import fisher_enrichment

    # Strong overlap → small p; no overlap → p ~= 1.
    odds, p = fisher_enrichment(18, 20, 20, 1000)
    assert p < 1e-10 and odds > 1
    _, p_null = fisher_enrichment(0, 20, 20, 1000)
    assert p_null > 0.5
    with pytest.raises(ValueError, match="inconsistent"):
        fisher_enrichment(30, 20, 20, 1000)


def test_drop_redundant_columns():
    from proteinbert_tpu.utils.stats import drop_redundant_columns

    rng = np.random.default_rng(0)
    a = rng.normal(size=(20, 3))
    x = np.c_[a, a[:, 0] + a[:, 1]]  # 4th col dependent
    out = drop_redundant_columns(x)
    assert out.shape == (20, 3)
    assert np.linalg.matrix_rank(out) == 3


# --------------------------------------------------------------- genome (C23)

def test_genome_reader(tmp_path):
    from proteinbert_tpu.etl.genome import GenomeReader

    fa = tmp_path / "genome.fasta"
    fa.write_text(
        ">chr1\nACGTACGTAC\nGTACGTACGT\nACGT\n"
        ">chrX\nTTTTGGGG\n"
        ">MT\nCCCCAAAA\n"
    )
    with GenomeReader(str(fa)) as g:
        assert g.length("1") == 24
        assert g.length("chr1") == 24
        # 1-based inclusive (genomics convention)
        assert g.fetch("1", 1, 4) == "ACGT"
        assert g.fetch(1, 9, 12) == "ACGT"       # crosses a line wrap
        assert g.fetch0("chr1", 0, 4) == "ACGT"  # 0-based half-open
        # synonyms: 23=X, M/MT/25/26
        assert g.fetch("X", 1, 4) == "TTTT"
        assert g.fetch("23", 1, 4) == "TTTT"
        assert g.fetch("M", 5, 8) == "AAAA"
        assert g.fetch("chrMT", 1, 4) == "CCCC"
        assert g.fetch("26", 1, 4) == "CCCC"
        assert "chr2" not in g and "1" in g
        with pytest.raises(KeyError):
            g.fetch("nope", 1, 2)


def test_fetch_range(tmp_path):
    from proteinbert_tpu.etl.fasta import FastaReader

    fa = tmp_path / "p.fasta"
    fa.write_text(">A\nABCDEFGHIJ\nKLMNOPQRST\nUVWXY\n")
    with FastaReader(str(fa)) as r:
        assert r.fetch_range("A", 0, 10) == "ABCDEFGHIJ"
        assert r.fetch_range("A", 8, 12) == "IJKL"      # crosses wrap
        assert r.fetch_range("A", 19, 25) == "TUVWXY"   # into last line
        assert r.fetch_range("A", 0, 999) == r.fetch("A")  # clamped
        assert r.fetch_range("A", 5, 5) == ""


def test_one_hot_out_of_range():
    from proteinbert_tpu.utils.stats import one_hot

    with pytest.raises(ValueError, match="out of range"):
        one_hot([3], num_classes=2)


def test_monitor_memory_and_device_report():
    from proteinbert_tpu.utils.profiling import (
        device_memory_report, monitor_memory)

    # numpy arrays are not gc-tracked; the walker sees them through
    # whatever holds them. Cover the subtle holders: a dict of only-
    # untracked values is itself untracked (reachable only through a
    # tracked ancestor), instance attributes live in an untracked
    # __dict__, and deques are non-builtin containers.
    import collections

    class Holder:
        def __init__(self):
            self.buf = np.zeros(28 * 1024 ** 2, dtype=np.uint8)

    holder = [np.zeros(30 * 1024 ** 2, dtype=np.uint8),
              {"d": np.zeros(25 * 1024 ** 2, dtype=np.uint8)}]
    inst = Holder()
    dq = collections.deque([np.zeros(22 * 1024 ** 2, dtype=np.uint8)])
    found = monitor_memory(threshold_bytes=20 * 1024 ** 2, verbose=False)
    sizes_found = sorted(n for t, n in found
                         if t == "ndarray" and n >= 20 * 1024 ** 2)
    for want in (22, 25, 28, 30):
        assert any(n >= want * 1024 ** 2 for n in sizes_found), want
    # sorted largest-first
    sizes = [n for _, n in found]
    assert sizes == sorted(sizes, reverse=True)
    # a higher threshold must exclude the smaller arrays
    high = monitor_memory(threshold_bytes=29 * 1024 ** 2, verbose=False)
    assert all(n >= 29 * 1024 ** 2 for _, n in high)
    assert any(n >= 30 * 1024 ** 2 for _, n in high)
    del holder, inst, dq

    rep = device_memory_report()
    assert len(rep) >= 1
    for stats in rep.values():
        assert all(isinstance(v, int) for v in stats.values())


def test_manhattan_plot(tmp_path):
    from proteinbert_tpu.utils.stats import manhattan_plot

    rng = np.random.default_rng(0)
    chroms = ["1"] * 50 + ["2"] * 50
    pos = list(rng.integers(0, 10_000, 50)) + list(rng.integers(0, 8_000, 50))
    pvals = rng.uniform(1e-8, 1.0, 100)
    out = tmp_path / "manhattan.png"
    manhattan_plot(chroms, pos, pvals, str(out))
    assert out.stat().st_size > 0
    with pytest.raises(ValueError, match="align"):
        manhattan_plot(chroms, pos[:-1], pvals, str(out))


def test_write_excel_fallback(tmp_path):
    import pandas as pd

    from proteinbert_tpu.utils.stats import write_excel

    sheets = {"a": pd.DataFrame({"x": [1, 2]}), "b": pd.DataFrame({"y": [3]})}
    out = tmp_path / "report.xlsx"
    paths = write_excel(sheets, str(out))
    # with an xlsx engine present one file; without, one CSV per sheet —
    # either way every written path exists and round-trips rows
    assert paths
    for p in paths:
        assert os.path.exists(p)
    if paths == [str(out)]:
        assert pd.read_excel(out, sheet_name="a")["x"].tolist() == [1, 2]
    else:
        assert pd.read_csv(paths[0])["x"].tolist() == [1, 2]


def test_liftover_gated():
    from proteinbert_tpu.utils.stats import liftover_positions

    try:
        import pyliftover  # noqa: F401
        pytest.skip("pyliftover present; gating branch not reachable")
    except ImportError:
        pass
    with pytest.raises(ImportError, match="pyliftover"):
        liftover_positions("chain.gz", "chr1", [100])
