"""Tests for host utilities (reference C17/C20/C21 parity)."""

import numpy as np
import pytest

from proteinbert_tpu.utils import (
    Profiler,
    TimeMeasure,
    shard_items,
    shard_range,
    task_identity,
    to_chunks,
)
from proteinbert_tpu.utils.h5 import (
    find_linearly_independent_columns,
    normalize,
    random_mask,
    transpose_dataset,
)


def test_to_chunks():
    assert list(to_chunks(range(7), 3)) == [[0, 1, 2], [3, 4, 5], [6]]
    assert list(to_chunks([], 3)) == []
    with pytest.raises(ValueError):
        list(to_chunks([1], 0))


def test_shard_range_covers_and_balances():
    n, k = 17, 5
    spans = [shard_range(n, i, k) for i in range(k)]
    assert spans[0][0] == 0 and spans[-1][1] == n
    for (a, b), (c, d) in zip(spans, spans[1:]):
        assert b == c
    sizes = [b - a for a, b in spans]
    assert max(sizes) - min(sizes) <= 1
    assert shard_items(list(range(10)), 1, 3) == [4, 5, 6]


def test_task_identity(monkeypatch):
    monkeypatch.delenv("SLURM_ARRAY_TASK_ID", raising=False)
    monkeypatch.delenv("TASK_INDEX", raising=False)
    assert task_identity() == (0, 1)
    assert task_identity(2, 4) == (2, 4)
    with pytest.raises(ValueError):
        task_identity(4, 4)
    monkeypatch.setenv("SLURM_ARRAY_TASK_ID", "3")
    monkeypatch.setenv("SLURM_ARRAY_TASK_COUNT", "8")
    assert task_identity() == (3, 8)
    monkeypatch.setenv("TASK_ID_OFFSET", "10")
    assert task_identity() == (13, 8)


def test_profiler_and_time_measure():
    p = Profiler()
    with p.measure("a"):
        pass
    with p.measure("a"):
        pass
    with p.measure("b"):
        pass
    s = p.summary()
    assert s["a"]["count"] == 2 and s["b"]["count"] == 1
    assert "a:" in p.report()
    with TimeMeasure("t", verbose=False) as tm:
        pass
    assert tm.elapsed is not None and tm.elapsed >= 0


def test_transpose_dataset(tmp_path):
    import h5py

    rng = np.random.default_rng(0)
    x = rng.random((37, 11)).astype(np.float32)
    with h5py.File(tmp_path / "t.h5", "w") as f:
        f.create_dataset("src", data=x)
        transpose_dataset(f, "src", "dst", chunk_rows=8)
        np.testing.assert_array_equal(f["dst"][:], x.T)


def test_numpy_helpers():
    rng = np.random.default_rng(0)
    v = normalize(rng.random((4, 6)))
    np.testing.assert_allclose(np.linalg.norm(v, axis=-1), 1.0, atol=1e-9)
    m = random_mask((1000,), 0.3, rng)
    assert 0.2 < m.mean() < 0.4
    # col2 = col0 + col1 → dependent; expect 3 independent of 4.
    a = rng.random((10, 2))
    x = np.column_stack([a[:, 0], a[:, 1], a[:, 0] + a[:, 1], rng.random(10)])
    idx = find_linearly_independent_columns(x)
    assert len(idx) == 3
