"""Weight export/import tests (export.py): flat-NPZ round trips across
both block layouts, and the CLI path."""

import dataclasses

import jax
import numpy as np
import pytest

from proteinbert_tpu import export
from proteinbert_tpu.configs import ModelConfig
from proteinbert_tpu.models import proteinbert

CFG = ModelConfig(local_dim=32, global_dim=64, key_dim=16, num_heads=4,
                  num_blocks=2, num_annotations=64, dtype="float32")


def _assert_tree_equal(a, b):
    ja, jb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(ja) == len(jb)
    for x, y in zip(ja, jb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_roundtrip_scan_layout(key, tmp_path):
    params = proteinbert.init(key, CFG)
    path = str(tmp_path / "w.npz")
    n = export.export_params(params, path)
    flat = export.flatten_params(params)
    assert n == len(flat)
    # Self-describing names, per-block entries despite the stacked layout.
    assert "embedding/embedding" in flat
    assert "blocks/0/narrow_conv/kernel" in flat
    assert "blocks/1/attention/wq" in flat
    assert flat["blocks/0/narrow_conv/kernel"].shape == (9, 32, 32)
    restored = export.import_params(path, scan_blocks=True)
    _assert_tree_equal(params, restored)


def test_roundtrip_unrolled_layout(key, tmp_path):
    cfg = dataclasses.replace(CFG, scan_blocks=False)
    params = proteinbert.init(key, cfg)
    path = str(tmp_path / "w.npz")
    export.export_params(params, path)
    restored = export.import_params(path, scan_blocks=False)
    _assert_tree_equal(params, restored)


def test_layouts_export_identically(key, tmp_path):
    """The NPZ contents must not depend on cfg.scan_blocks — the file is
    the portable form."""
    stacked = proteinbert.init(key, CFG)
    unrolled = proteinbert.init(
        key, dataclasses.replace(CFG, scan_blocks=False))
    fa = export.flatten_params(stacked)
    fb = export.flatten_params(unrolled)
    assert set(fa) == set(fb)
    for k in fa:
        np.testing.assert_array_equal(fa[k], fb[k])


def test_exported_params_drive_forward(key, tmp_path):
    params = proteinbert.init(key, CFG)
    path = str(tmp_path / "w.npz")
    export.export_params(params, path)
    restored = jax.tree.map(jax.numpy.asarray,
                            export.import_params(path))
    tokens = jax.numpy.ones((2, 32), jax.numpy.int32) * 7
    ann = jax.numpy.zeros((2, CFG.num_annotations), jax.numpy.float32)
    a = proteinbert.apply(params, tokens, ann, CFG)
    b = proteinbert.apply(restored, tokens, ann, CFG)
    np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b[0]))


def test_export_weights_cli(tmp_path):
    from proteinbert_tpu.cli.main import main
    from proteinbert_tpu.configs import (
        DataConfig, OptimizerConfig, PretrainConfig, TrainConfig,
    )
    from proteinbert_tpu.train import Checkpointer, create_train_state

    cfg = PretrainConfig(model=CFG, data=DataConfig(seq_len=48, batch_size=4),
                         optimizer=OptimizerConfig(warmup_steps=5),
                         train=TrainConfig(seed=0))
    state = create_train_state(jax.random.PRNGKey(0), cfg)
    ck = Checkpointer(str(tmp_path / "ck"), async_save=False)
    ck.save(0, state, None)
    ck.close()
    out = str(tmp_path / "w.npz")
    overrides = [
        f"--pretrained-set=model.{f}={getattr(CFG, f)}"
        for f in ("local_dim", "global_dim", "key_dim", "num_heads",
                  "num_blocks", "num_annotations")
    ] + ["--pretrained-set=model.dtype=float32",
         "--pretrained-set=data.seq_len=48"]
    assert main(["export-weights", "--pretrained", str(tmp_path / "ck"),
                 "--preset", "tiny", *overrides, "--output", out]) == 0
    restored = export.import_params(out)
    _assert_tree_equal(state.params, restored)


def test_import_weights_cli_roundtrip(tmp_path):
    """export-weights → import-weights → the new run dir serves embed."""
    from proteinbert_tpu.cli.main import main
    from proteinbert_tpu.configs import (
        DataConfig, OptimizerConfig, PretrainConfig, TrainConfig,
    )
    from proteinbert_tpu.train import Checkpointer, create_train_state

    cfg = PretrainConfig(model=CFG, data=DataConfig(seq_len=48, batch_size=4),
                         optimizer=OptimizerConfig(warmup_steps=5),
                         train=TrainConfig(seed=0))
    state = create_train_state(jax.random.PRNGKey(0), cfg)
    ck = Checkpointer(str(tmp_path / "ck"), async_save=False)
    ck.save(0, state, None)
    ck.close()
    npz = str(tmp_path / "w.npz")
    setargs = [
        f"--set=model.{f}={getattr(CFG, f)}"
        for f in ("local_dim", "global_dim", "key_dim", "num_heads",
                  "num_blocks", "num_annotations")
    ] + ["--set=model.dtype=float32", "--set=data.seq_len=48"]
    psetargs = [a.replace("--set=", "--pretrained-set=") for a in setargs]
    assert main(["export-weights", "--pretrained", str(tmp_path / "ck"),
                 "--preset", "tiny", *psetargs, "--output", npz]) == 0
    out_dir = str(tmp_path / "imported")
    assert main(["import-weights", "--weights", npz, "--output", out_dir,
                 "--preset", "tiny", "--step", "7", *setargs]) == 0
    emb = str(tmp_path / "e.npz")
    assert main(["embed", "--pretrained", out_dir, "--preset", "tiny",
                 *psetargs, "--output", emb, "MKTAYIAKQR"]) == 0
    assert np.load(emb)["global"].shape == (1, CFG.global_dim)


def test_import_weights_cli_rejects_geometry_mismatch(tmp_path, key):
    from proteinbert_tpu.cli.main import main

    params = proteinbert.init(key, CFG)
    npz = str(tmp_path / "w.npz")
    export.export_params(params, npz)
    with pytest.raises(SystemExit, match="does not match"):
        main(["import-weights", "--weights", npz,
              "--output", str(tmp_path / "o"), "--preset", "tiny",
              "--set=model.local_dim=64", "--set=model.dtype=float32"])


def test_import_weights_cli_rejects_malformed_npz(tmp_path, key):
    """Inconsistent block subtrees must produce the curated error, not a
    raw jax.tree traceback."""
    from proteinbert_tpu.cli.main import main

    flat = export.flatten_params(proteinbert.init(key, CFG))
    bad = {k: v for k, v in flat.items()
           if not k.startswith("blocks/1/attention")}
    npz = str(tmp_path / "bad.npz")
    np.savez(npz, **bad)
    with pytest.raises(SystemExit, match="not a well-formed"):
        main(["import-weights", "--weights", npz,
              "--output", str(tmp_path / "o"), "--preset", "tiny",
              "--set=model.dtype=float32"])
