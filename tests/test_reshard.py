"""Mesh-agnostic checkpoint resharding (parallel/reshard.py, ISSUE 11).

The acceptance grid: round trips across 1×1 ↔ 4×2 ↔ 8×1 CPU-virtual
meshes, plain AND ZeRO-1, must restore byte-identical params and
optimizer state (compared in the mesh-independent canonical form —
device_get assembles global arrays, so two layouts compare equal iff
the VALUES are). Plus: the collective-schedule wire-byte accounting
(`reshard_schedule_bytes` over the existing HLO byte-counter), the
`pbt reshard` CLI verb, torn-final-checkpoint restore fallback
(ISSUE 11 satellite — the read-side mirror of the write-side
torn-snapshot guarantees), and `reshard` events that round-trip the
schema validator.
"""

import dataclasses
import json
import os
import shutil

import numpy as np
import pytest

import jax

from proteinbert_tpu.configs import (
    CheckpointConfig, DataConfig, ModelConfig, OptimizerConfig,
    PretrainConfig, TrainConfig, save_config,
)
from proteinbert_tpu.parallel.reshard import (
    mesh_from_config, parse_mesh_spec, reshard_checkpoint,
    reshard_schedule_bytes, reshard_state, states_byte_identical,
    target_template, tree_digest,
)
from proteinbert_tpu.train.checkpoint import Checkpointer


def _cfg(mesh_spec="1", zero=False):
    cfg = PretrainConfig(
        model=ModelConfig(local_dim=16, global_dim=32, key_dim=8,
                          num_heads=2, num_blocks=2, num_annotations=32,
                          dtype="float32"),
        data=DataConfig(seq_len=32, batch_size=4),
        optimizer=OptimizerConfig(warmup_steps=5),
        train=TrainConfig(seed=0, max_steps=1),
        checkpoint=CheckpointConfig(),
    )
    return cfg.replace(
        mesh=parse_mesh_spec(mesh_spec),
        parallel=dataclasses.replace(cfg.parallel, zero_update=zero))


def _save_run(directory, cfg, state, step=0, data=None):
    ck = Checkpointer(str(directory), async_save=False)
    assert ck.save(step, state, data)
    ck.close()
    save_config(cfg, os.path.join(str(directory), "config.json"))


# ------------------------------------------------------------ mesh specs

class TestMeshSpec:
    def test_forms(self):
        assert parse_mesh_spec("4x2").shape == (4, 2, 1, 1)
        assert parse_mesh_spec("8x1x1x1").shape == (8, 1, 1, 1)
        assert parse_mesh_spec("1").shape == (1, 1, 1, 1)
        assert parse_mesh_spec("data=4,fsdp=2").shape == (4, 2, 1, 1)
        assert parse_mesh_spec("seq=2").shape == (1, 1, 1, 2)

    def test_rejects_garbage(self):
        with pytest.raises(ValueError):
            parse_mesh_spec("")
        with pytest.raises(ValueError):
            parse_mesh_spec("2x2x2x2x2")
        with pytest.raises(ValueError):
            parse_mesh_spec("bogus=2")
        with pytest.raises(ValueError):
            parse_mesh_spec("4xtwo")
        # A zero/negative extent would silently degrade to the
        # single-device layout — must error, not 'succeed'.
        with pytest.raises(ValueError, match=">= 1"):
            parse_mesh_spec("0x4")
        with pytest.raises(ValueError, match=">= 1"):
            parse_mesh_spec("data=0,fsdp=4")
        with pytest.raises(ValueError, match=">= 1"):
            parse_mesh_spec("-2")

    def test_single_device_is_no_mesh(self):
        assert mesh_from_config(parse_mesh_spec("1")) is None
        assert mesh_from_config(parse_mesh_spec("4x2")) is not None


# ----------------------------------------------------- round-trip grid

# Each case: source layout -> target layout -> back to source; every
# hop restores through a TARGET-layout template (the restore half) and
# byte-compares in canonical form (the parity gate). Covers shrink
# (8 devices -> 1), grow (1 -> 8), and same-set relayout (4x2 <-> 8x1).
GRID = [("1", "4x2"), ("4x2", "8x1"), ("8x1", "1")]


@pytest.mark.parametrize("zero", [False, True], ids=["plain", "zero1"])
@pytest.mark.parametrize("src_spec,dst_spec", GRID,
                         ids=[f"{a}to{b}" for a, b in GRID])
def test_round_trip_byte_identical(tmp_path, src_spec, dst_spec, zero):
    cfg = _cfg(src_spec, zero=zero)
    mesh = mesh_from_config(cfg.mesh)
    state = target_template(cfg, mesh, zero_update=zero)
    src = tmp_path / "src"
    _save_run(src, cfg, state, data={"batches_consumed": 7})
    origin = tree_digest(state)

    out1 = reshard_checkpoint(str(src), str(tmp_path / "fwd"),
                              target_mesh_cfg=parse_mesh_spec(dst_spec))
    assert out1["parity"] is True
    assert out1["zero_update"] is zero  # layout intent carried over
    out2 = reshard_checkpoint(str(tmp_path / "fwd"),
                              str(tmp_path / "back"),
                              target_mesh_cfg=parse_mesh_spec(src_spec))
    assert out2["parity"] is True

    # The round trip is byte-identical: params, Adam mu/nu, RNG key,
    # step — compared leaf-by-leaf in canonical (unsharded) form.
    canonical = target_template(cfg, None)
    ck = Checkpointer(str(tmp_path / "back"), async_save=False)
    back, data_state = ck.restore(canonical)
    ck.close()
    assert tree_digest(back) == origin
    assert data_state == {"batches_consumed": 7}

    # The rewritten config.json records the target topology, so a
    # resumed run builds the right mesh without extra flags.
    from proteinbert_tpu.configs import load_config

    fwd_cfg = load_config(str(tmp_path / "fwd" / "config.json"))
    want = parse_mesh_spec(dst_spec)
    assert fwd_cfg.mesh.shape == want.shape
    assert fwd_cfg.parallel.zero_update is zero


def test_source_mesh_larger_than_host_still_reshards(tmp_path):
    """The headline shrink case: a checkpoint whose config claims a
    mesh BIGGER than this host must still restore onto a small target —
    the source mesh exists only for wire-byte accounting, so its
    absence downgrades the schedule report to host_staged, never
    crashes the restore."""
    cfg16 = _cfg("4x4")  # 16 devices; the test host has 8
    state = target_template(cfg16, None)
    src = tmp_path / "src"
    _save_run(src, cfg16, state)
    out = reshard_checkpoint(str(src), str(tmp_path / "dst"),
                             target_mesh_cfg=parse_mesh_spec("1"))
    assert out["schedule"] == "host_staged"
    assert out["parity"] is True
    canonical = target_template(cfg16, None)
    ck = Checkpointer(str(tmp_path / "dst"), async_save=False)
    back, _ = ck.restore(canonical)
    ck.close()
    assert states_byte_identical(state, back)


def test_reshard_state_live_move():
    cfg = _cfg("4x2")
    mesh = mesh_from_config(cfg.mesh)
    state = target_template(cfg, mesh)
    moved = reshard_state(state, mesh_from_config(parse_mesh_spec("8x1")))
    assert states_byte_identical(state, moved)
    single = reshard_state(moved, None)
    assert states_byte_identical(state, single)
    leaf = jax.tree_util.tree_leaves(single.params)[0]
    assert len(leaf.sharding.device_set) == 1


# ------------------------------------------------- schedule accounting

class TestScheduleBytes:
    def test_same_device_set_is_collective(self):
        cfg = _cfg()
        m42 = mesh_from_config(parse_mesh_spec("4x2"))
        m81 = mesh_from_config(parse_mesh_spec("8x1"))
        wb, sched = reshard_schedule_bytes(cfg, m42, m81)
        assert sched == "collective"
        assert wb["total"] > 0
        # The breakdown is the byte-counter's: every collective kind
        # keyed, totals consistent.
        assert wb["total"] == sum(v for k, v in wb.items()
                                  if k != "total")

    def test_cross_device_set_is_host_staged(self):
        cfg = _cfg()
        m42 = mesh_from_config(parse_mesh_spec("4x2"))
        wb, sched = reshard_schedule_bytes(cfg, m42, None)
        assert sched == "host_staged" and wb["total"] == 0
        wb, sched = reshard_schedule_bytes(cfg, None, m42)
        assert sched == "host_staged" and wb["total"] == 0

    def test_identity_layout_moves_nothing(self):
        cfg = _cfg()
        wb, sched = reshard_schedule_bytes(cfg, None, None)
        assert sched == "identity" and wb["total"] == 0

    def test_zero_relayout_costs_wire_bytes(self):
        # plain -> ZeRO-1 on the SAME mesh: the mu/nu re-slice is a real
        # collective move, and it must be accounted, not assumed free.
        cfg = _cfg()
        m42 = mesh_from_config(parse_mesh_spec("4x2"))
        wb, sched = reshard_schedule_bytes(cfg, m42, m42,
                                           source_zero=False,
                                           target_zero=True)
        assert sched == "collective"
        assert wb["total"] > 0


# ------------------------------------------------------------- the CLI

def test_pbt_reshard_cli(tmp_path, capsys):
    from proteinbert_tpu.cli.main import main

    cfg = _cfg("4x2")
    mesh = mesh_from_config(cfg.mesh)
    state = target_template(cfg, mesh)
    src = tmp_path / "run"
    _save_run(src, cfg, state, data={"batches_consumed": 3})
    events = tmp_path / "events.jsonl"
    rc = main(["reshard", "--src", str(src),
               "--output", str(tmp_path / "out"),
               "--target-mesh", "8x1",
               "--events-jsonl", str(events)])
    assert rc == 0
    json_lines = [ln for ln in capsys.readouterr().out.splitlines()
                  if ln.startswith("{")]
    summary = json.loads(json_lines[-1])
    assert summary["target_mesh"]["data"] == 8
    assert summary["parity"] is True
    assert summary["schedule"] == "collective"
    assert summary["wire_bytes"]["total"] > 0

    from proteinbert_tpu.obs import read_events

    recs = read_events(str(events), strict=True)  # schema round trip
    assert [r["event"] for r in recs].count("reshard") == 1

    canonical = target_template(cfg, None)
    ck = Checkpointer(str(tmp_path / "out"), async_save=False)
    back, _ = ck.restore(canonical)
    ck.close()
    assert states_byte_identical(state, back)


def test_pbt_reshard_cli_missing_checkpoint(tmp_path):
    from proteinbert_tpu.cli.main import main

    src = tmp_path / "empty"
    os.makedirs(src)
    save_config(_cfg(), os.path.join(str(src), "config.json"))
    with pytest.raises(SystemExit, match="reshard failed"):
        main(["reshard", "--src", str(src),
              "--output", str(tmp_path / "out"), "--target-mesh", "1"])


# --------------------------------------- torn-final-checkpoint fallback

def _tear_step(run_dir, step):
    """Maul a saved step the way a crash mid-write does: remove part of
    its payload but leave the step directory listed."""
    step_dir = os.path.join(str(run_dir), str(step))
    assert os.path.isdir(step_dir), os.listdir(str(run_dir))
    torn = False
    for name in os.listdir(step_dir):
        target = os.path.join(step_dir, name)
        if os.path.isdir(target):
            shutil.rmtree(target)
            torn = True
    assert torn, f"nothing to tear in {step_dir}"


class TestTornRestoreFallback:
    def test_falls_back_to_previous_valid_step_with_note(self, tmp_path):
        cfg = _cfg()
        good = target_template(cfg, None)
        other = dataclasses.replace(
            good, step=good.step + 1,
            key=jax.random.PRNGKey(99))
        ck = Checkpointer(str(tmp_path), async_save=False)
        assert ck.save(1, good, {"batches_consumed": 1})
        assert ck.save(2, other, {"batches_consumed": 2})
        ck.close()
        _tear_step(tmp_path, 2)

        notes = []
        ck = Checkpointer(str(tmp_path), async_save=False)
        ck.on_note = lambda **f: notes.append(f)
        state, data = ck.restore(target_template(cfg, None))
        ck.close()
        # Salvaged the previous valid step, byte-identical.
        assert states_byte_identical(state, good)
        assert data == {"batches_consumed": 1}
        assert len(notes) == 1
        assert notes[0]["kind"] == "restore_fallback"
        assert notes[0]["bad_step"] == 2
        # ISSUE 14 satellite: the payload names BOTH ends of the skip —
        # the torn step and the step the restore landed on.
        assert notes[0]["landed_step"] == 1
        # The note payload is emittable as a schema-valid `note` event.
        from proteinbert_tpu.obs.events import make_record, validate_record

        validate_record(make_record("note", seq=0, t=0.0, **notes[0]))

    def test_explicit_step_stays_strict(self, tmp_path):
        cfg = _cfg()
        ck = Checkpointer(str(tmp_path), async_save=False)
        assert ck.save(1, target_template(cfg, None))
        assert ck.save(2, target_template(cfg, None))
        ck.close()
        _tear_step(tmp_path, 2)
        ck = Checkpointer(str(tmp_path), async_save=False)
        with pytest.raises(Exception):
            ck.restore(target_template(cfg, None), step=2)
        ck.close()

    def test_single_torn_step_raises_original_error(self, tmp_path):
        # Nothing to salvage: the original orbax error surfaces as
        # itself (no misleading "torn checkpoint" smearing).
        cfg = _cfg()
        ck = Checkpointer(str(tmp_path), async_save=False)
        assert ck.save(1, target_template(cfg, None))
        ck.close()
        _tear_step(tmp_path, 1)
        notes = []
        ck = Checkpointer(str(tmp_path), async_save=False)
        ck.on_note = lambda **f: notes.append(f)
        with pytest.raises(Exception) as ei:
            ck.restore(target_template(cfg, None))
        ck.close()
        assert not isinstance(ei.value, AssertionError)
        assert notes == []  # no fallback happened, so no note

    def test_fallback_skips_exactly_one_step(self, tmp_path):
        # A failure at the fallback step too is a REAL error (e.g. a
        # wrong restore template would fail at every step): it raises
        # as itself instead of burning a restore per retained step.
        cfg = _cfg()
        ck = Checkpointer(str(tmp_path), max_to_keep=5, async_save=False)
        for s in (1, 2, 3):
            assert ck.save(s, target_template(cfg, None))
        ck.close()
        _tear_step(tmp_path, 3)
        _tear_step(tmp_path, 2)
        notes = []
        ck = Checkpointer(str(tmp_path), max_to_keep=5, async_save=False)
        ck.on_note = lambda **f: notes.append(f)
        with pytest.raises(Exception) as ei:
            ck.restore(target_template(cfg, None))
        ck.close()
        assert not isinstance(ei.value, AssertionError)
        assert len(notes) == 1 and notes[0]["bad_step"] == 3
        # The fallback TARGET is on the note even when restoring it
        # then fails too (the note reports where the fallback aimed).
        assert notes[0]["landed_step"] == 2

    def test_empty_dir_still_returns_none(self, tmp_path):
        ck = Checkpointer(str(tmp_path), async_save=False)
        state, data = ck.restore(target_template(_cfg(), None))
        ck.close()
        assert state is None and data is None
