"""Child process for the compile-cache warm-boot test (ISSUE 11
satellite): arm the persistent compilation cache at argv[1], boot a
tiny serve Server (warming two request kinds), and print one JSON line
{"warmup_seconds", "executables"}. Run twice against the SAME fresh
cache dir by tests/test_fleet.py: the first boot compiles cold, the
second deserializes warm executables and must be faster — the number a
restarted fleet replica's boot time rides on.

A separate process per boot is the point: the in-process jit cache
would make a second same-process boot trivially 'warm' without ever
touching the persistent cache.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("PBT_DISABLE_DONATION", "1")


def main() -> int:
    cache_dir = sys.argv[1]
    from proteinbert_tpu.utils.compat import configure_compile_cache

    configure_compile_cache(cache_dir)

    import jax

    from proteinbert_tpu.configs import (
        DataConfig, ModelConfig, OptimizerConfig, PretrainConfig,
        TrainConfig,
    )
    from proteinbert_tpu.serve import Server
    from proteinbert_tpu.train import create_train_state

    cfg = PretrainConfig(
        model=ModelConfig(local_dim=32, global_dim=64, key_dim=16,
                          num_heads=2, num_blocks=2, num_annotations=48,
                          dtype="float32"),
        data=DataConfig(seq_len=64, batch_size=4),
        optimizer=OptimizerConfig(warmup_steps=5),
        train=TrainConfig(seed=0, max_steps=1),
    )
    params = create_train_state(jax.random.PRNGKey(0), cfg).params
    srv = Server(params, cfg, buckets=(32, 64), max_batch=2,
                 cache_size=0, warm_kinds=("embed", "predict_go"))
    srv.start()
    out = {"warmup_seconds": srv.dispatcher.warmup_seconds_total,
           "executables": srv.dispatcher.executable_count}
    srv.drain(timeout=30)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
