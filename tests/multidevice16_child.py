"""16-virtual-device parity child (VERDICT r4 weak #4 / round-5 item 5).

Every in-suite mesh caps fsdp/model at extent 2 (the pytest process is
pinned to 8 virtual CPU devices at backend init), but off-by-N bugs in
gather/reduce-scatter sharding rules characteristically appear only at
extents >2. This child runs in its OWN process with 16 virtual CPU
devices — forced through the config API, since env vars don't take on
images whose sitecustomize pre-imports jax — and asserts the sharded
step is numerically identical to the single-device step. Cheap
insurance before real-pod day (SURVEY C18/C19; the reference has no
distributed path at all).

Usage: python tests/multidevice16_child.py {fsdp4|model4|sp4-bucketed}
Prints one JSON line with the compared losses.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Small dims, all divisible by the >2 axis extents below.
MODEL = dict(local_dim=16, global_dim=64, key_dim=16, num_heads=4,
             num_blocks=2, num_annotations=64, dtype="float32")


def _cfg(mesh_cfg, **data_kw):
    from proteinbert_tpu.configs import (
        DataConfig, ModelConfig, OptimizerConfig, PretrainConfig,
        TrainConfig,
    )

    data = dict(seq_len=32, batch_size=16)
    data.update(data_kw)
    return PretrainConfig(
        model=ModelConfig(**MODEL),
        data=DataConfig(**data),
        optimizer=OptimizerConfig(learning_rate=1e-3, warmup_steps=10),
        mesh=mesh_cfg,
        train=TrainConfig(max_steps=2),
    )


def _dense_parity(scenario):
    """fsdp=4 / model=4: sharded train_step vs single-device, same batch
    and init — sharding must not change the math (the 8-device tier's
    test_sharded_train_step_matches_single_device at doubled extents)."""
    import numpy as np

    import jax
    from proteinbert_tpu.configs import MeshConfig
    from proteinbert_tpu.data import (
        InMemoryPretrainingDataset, make_pretrain_iterator,
    )
    from proteinbert_tpu.data.synthetic import make_random_proteins
    from proteinbert_tpu.parallel import (
        batch_sharding, make_mesh, shard_train_state,
    )
    from proteinbert_tpu.train import create_train_state, train_step

    mesh_cfg = (MeshConfig(data=2, fsdp=4, model=2) if scenario == "fsdp4"
                else MeshConfig(data=2, fsdp=2, model=4))
    cfg = _cfg(mesh_cfg)
    rng = np.random.default_rng(0)
    seqs, ann = make_random_proteins(
        cfg.data.batch_size, rng, num_annotations=MODEL["num_annotations"],
        max_len=40)
    ds = InMemoryPretrainingDataset(seqs, ann, cfg.data.seq_len)
    batch = next(make_pretrain_iterator(ds, cfg.data.batch_size, seed=0))

    ref_state, ref_m = train_step(
        create_train_state(jax.random.PRNGKey(0), cfg), dict(batch), cfg)

    mesh = make_mesh(mesh_cfg)
    state = shard_train_state(
        create_train_state(jax.random.PRNGKey(0), cfg), mesh)
    bsh = batch_sharding(mesh)
    dbatch = {k: jax.device_put(v, bsh[k]) for k, v in batch.items()}
    new_state, m = train_step(state, dbatch, cfg)

    ref_loss, got_loss = float(ref_m["loss"]), float(m["loss"])
    assert abs(got_loss - ref_loss) <= 2e-5 * max(1.0, abs(ref_loss)), (
        ref_loss, got_loss)
    max_err = 0.0
    for r, g in zip(jax.tree.leaves(ref_state.params),
                    jax.tree.leaves(new_state.params)):
        err = float(np.max(np.abs(
            np.asarray(r, np.float64)
            - np.asarray(jax.device_get(g), np.float64))))
        max_err = max(max_err, err)
    assert max_err < 2e-5, (scenario, max_err)
    return {"mesh": dict(mesh.shape), "ref_loss": ref_loss,
            "sharded_loss": got_loss, "max_param_err": max_err}


def _sp4_bucketed():
    """data=2 x fsdp=2 x seq=4: mixed-length corpus -> length-bucketed
    lockstep batches -> the EXPLICIT seq-parallel step (halo conv +
    distributed softmax) — every emitted bucket shape must match the
    implicit-SPMD step's loss on the identical batch (the 8-device
    test_long_preset_miniature_h5_bucketed_seq_parallel, with the seq
    axis at 4 alongside a live fsdp axis)."""
    import numpy as np

    import jax
    from proteinbert_tpu.configs import MeshConfig
    from proteinbert_tpu.data import InMemoryPretrainingDataset
    from proteinbert_tpu.data.dataset import make_bucketed_iterator
    from proteinbert_tpu.parallel import make_mesh
    from proteinbert_tpu.parallel.seq_parallel import (
        make_seq_parallel_train_step,
    )
    from proteinbert_tpu.train import create_train_state, train_step

    mesh_cfg = MeshConfig(data=2, fsdp=2, seq=4)
    cfg = _cfg(mesh_cfg, seq_len=128, batch_size=8, buckets=(32, 128))
    rng = np.random.default_rng(0)
    seqs = []
    for i in range(64):
        n = (int(rng.integers(5, 28)) if i % 2
             else int(rng.integers(80, 120)))
        seqs.append("".join(
            rng.choice(list("ACDEFGHIKLMNPQRSTVWY"), size=n)))
    ann = (rng.random((64, MODEL["num_annotations"])) < 0.1)
    ds = InMemoryPretrainingDataset(seqs, ann, cfg.data.seq_len)

    mesh = make_mesh(mesh_cfg)
    sstep = make_seq_parallel_train_step(mesh, cfg)
    it = make_bucketed_iterator(ds, cfg.data.batch_size, cfg.data.buckets,
                                seed=3, num_epochs=1)
    widths, rows = set(), []
    for batch, _ in zip(it, range(4)):
        widths.add(batch["tokens"].shape[1])
        _, ref_m = train_step(
            create_train_state(jax.random.PRNGKey(0), cfg), dict(batch),
            cfg)
        _, sp_m = sstep(
            create_train_state(jax.random.PRNGKey(0), cfg), dict(batch))
        ref_loss, sp_loss = float(ref_m["loss"]), float(sp_m["loss"])
        assert np.isfinite(sp_loss)
        assert abs(sp_loss - ref_loss) <= 1e-4 * max(1.0, abs(ref_loss)), (
            ref_loss, sp_loss)
        rows.append({"L": int(batch["tokens"].shape[1]),
                     "ref_loss": ref_loss, "sp_loss": sp_loss})
    assert widths == {32, 128}, widths  # both buckets actually ran
    return {"mesh": dict(mesh.shape), "buckets": rows}


def main():
    scenario = sys.argv[1]
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 16)
    except AttributeError:  # jax 0.4.x: env route, pre-backend-init
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=16").strip()
    assert jax.device_count() == 16, jax.device_count()

    if scenario in ("fsdp4", "model4"):
        out = _dense_parity(scenario)
    elif scenario == "sp4-bucketed":
        out = _sp4_bucketed()
    else:
        raise SystemExit(f"unknown scenario {scenario!r}")
    print(json.dumps({"scenario": scenario, "ok": True, **out}))


if __name__ == "__main__":
    main()
