"""Native C++ batch tokenizer: build infra, parity with the numpy path,
crop semantics, and throughput sanity."""

import numpy as np
import pytest

from proteinbert_tpu.data.transforms import tokenize_batch
from proteinbert_tpu.data.vocab import EOS_ID, PAD_ID, SOS_ID, UNK_ID
from proteinbert_tpu.native import native_available, tokenize_batch_native

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no C++ toolchain in this environment"
)


def _random_seqs(rng, n, max_len=300):
    return ["".join(rng.choice(list("ACDEFGHIKLMNPQRSTVWYXZ*"),
                               size=int(rng.integers(0, max_len))))
            for _ in range(n)]


def test_parity_no_crop(rng):
    seqs = _random_seqs(rng, 64, max_len=60)
    want = tokenize_batch(seqs, 64, use_native=False)
    got = tokenize_batch_native(seqs, 64)
    np.testing.assert_array_equal(got, want)


def test_parity_head_truncation(rng):
    # Longer than seq_len-2 without rng → head-truncate, same as numpy.
    seqs = _random_seqs(rng, 32, max_len=200)
    want = tokenize_batch(seqs, 48, use_native=False)
    got = tokenize_batch_native(seqs, 48)
    np.testing.assert_array_equal(got, want)


def test_crop_windows_are_valid_substrings(rng):
    seq = "".join(rng.choice(list("ACDEFGHIKLMNPQRSTVWY"), size=500))
    cap = 30
    starts = set()
    for trial in range(50):
        row = tokenize_batch_native([seq], cap + 2, crop_seed=trial)[0]
        assert row[0] == SOS_ID and row[cap + 1] == EOS_ID
        decoded = row[1:cap + 1]
        # The cropped window must be a contiguous substring of the source.
        full = tokenize_batch([seq], len(seq) + 2, use_native=False)[0][1:-1]
        matches = [s for s in range(len(seq) - cap + 1)
                   if np.array_equal(full[s:s + cap], decoded)]
        assert matches, "crop is not a substring"
        starts.add(matches[0])
    assert len(starts) > 5, "crop windows never vary"


def test_crop_deterministic_given_seed():
    seqs = ["A" * 10 + "C" * 300, "D" * 400]
    a = tokenize_batch_native(seqs, 32, crop_seed=7)
    b = tokenize_batch_native(seqs, 32, crop_seed=7)
    np.testing.assert_array_equal(a, b)


def test_crop_parity_native_vs_numpy(rng):
    """The counter-based windows are BIT-IDENTICAL across the C++ and
    numpy paths (both compute splitmix64(seed + row_id) % span) — round 1
    only promised 'reproducible but not window-identical'."""
    seqs = _random_seqs(rng, 64, max_len=300)
    row_ids = np.asarray(rng.integers(0, 10**9, size=64), np.int64)
    for seed in (0, 7, 2**63 + 11):
        want = tokenize_batch(seqs, 48, crop_seed=seed, row_ids=row_ids,
                              use_native=False)
        got = tokenize_batch_native(seqs, 48, crop_seed=seed,
                                    row_ids=row_ids)
        np.testing.assert_array_equal(got, want)


def test_unknown_chars_map_to_unk():
    got = tokenize_batch_native(["B1?", "acde"], 8)
    assert (got[0][1:4] == UNK_ID).all()
    # lowercase residues are soft-masked FASTA → real ids, like the LUT.
    want = tokenize_batch(["acde"], 8, use_native=False)[0]
    np.testing.assert_array_equal(got[1], want)


def test_empty_batch_and_empty_seq():
    assert tokenize_batch_native([], 16).shape == (0, 16)
    row = tokenize_batch_native([""], 16)[0]
    assert row[0] == SOS_ID and row[1] == EOS_ID and (row[2:] == PAD_ID).all()


def test_dispatch_through_tokenize_batch(rng):
    """transforms.tokenize_batch auto-routes big batches to native."""
    seqs = _random_seqs(rng, 32, max_len=40)
    native = tokenize_batch(seqs, 64)            # auto → native
    python = tokenize_batch(seqs, 64, use_native=False)
    np.testing.assert_array_equal(native, python)


def test_native_throughput_sanity(rng):
    """The point of the C++ path: it must beat the per-row numpy loop."""
    import time

    seqs = _random_seqs(rng, 512, max_len=400)
    tokenize_batch_native(seqs, 512)  # warm (library load)
    t0 = time.perf_counter()
    for _ in range(5):
        tokenize_batch_native(seqs, 512)
    t_native = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(5):
        tokenize_batch(seqs, 512, use_native=False)
    t_python = time.perf_counter() - t0
    assert t_native < t_python, (t_native, t_python)


# ---------------------------------------------------------- fasta indexer

def _fai_both_ways(tmp_path, text, name):
    """Build the .fai with the C++ scanner and the Python loop; return
    both index file contents."""
    from proteinbert_tpu.etl.fasta import build_index

    fa = tmp_path / f"{name}.fasta"
    fa.write_bytes(text if isinstance(text, bytes) else text.encode())
    native_fai = build_index(str(fa), str(tmp_path / f"{name}.native.fai"))
    python_fai = build_index(str(fa), str(tmp_path / f"{name}.python.fai"),
                             use_native=False)
    return (open(native_fai).read(), open(python_fai).read())


@pytest.mark.parametrize("text,name", [
    (">a desc\nMKTAYI\n>b\nGGG\n", "simple"),
    (">a\nMKTAYIAK\nQRQISF\n>b x y\nAC\n", "wrapped_short_tail"),
    (">a\nMKTAYIAK\nQRQISFVK\nGG", "no_trailing_newline"),
    (">a\r\nMKTAYIAK\r\nQR\r\n>b\r\nAC\r\n", "crlf"),
    (">a\nMKTAYI\n\n>b\nACDE\n", "blank_line_between_records"),
    (">\nAC\n", "empty_header"),
    (">only_header\n", "zero_length_record"),
    ("", "empty_file"),
], ids=lambda v: v if isinstance(v, str) and "\n" not in str(v) else None)
def test_fai_native_matches_python(tmp_path, text, name):
    native_text, python_text = _fai_both_ways(tmp_path, text, name)
    assert native_text == python_text


def test_fai_native_rejects_ragged(tmp_path):
    from proteinbert_tpu.etl.fasta import build_index

    fa = tmp_path / "ragged.fasta"
    fa.write_text(">a\nMKTA\nYIAKQRQI\n")  # line grows: illegal wrap
    with pytest.raises(ValueError, match="non-uniform"):
        build_index(str(fa), str(tmp_path / "r.native.fai"))
    with pytest.raises(ValueError, match="non-uniform"):
        build_index(str(fa), str(tmp_path / "r.python.fai"),
                    use_native=False)


def test_fai_native_feeds_reader(tmp_path):
    """An index built natively serves FastaReader fetches correctly."""
    from proteinbert_tpu.etl.fasta import FastaReader, build_index

    fa = tmp_path / "r.fasta"
    fa.write_text(">p1 some desc\nMKTAYIAK\nQRQISFVK\nSHFS\n>p2\nACDEFG\n")
    build_index(str(fa))
    with FastaReader(str(fa)) as rd:
        assert rd.fetch("p1") == "MKTAYIAKQRQISFVKSHFS"
        assert rd.fetch("p2") == "ACDEFG"


def test_fai_native_throughput_sanity(tmp_path, rng):
    """The point of the C++ scanner: beat the Python line loop."""
    import time

    from proteinbert_tpu.etl.fasta import build_index

    fa = tmp_path / "big.fasta"
    with open(fa, "w") as f:
        for i in range(4000):
            f.write(f">seq{i} d\n")
            seq = "".join(rng.choice(list("ACDEFGHIKLMNPQRSTVWY"), size=600))
            for j in range(0, 600, 60):
                f.write(seq[j:j + 60] + "\n")
    build_index(str(fa), str(tmp_path / "warm.fai"))  # warm (library load)
    t0 = time.perf_counter()
    build_index(str(fa), str(tmp_path / "n.fai"))
    t_native = time.perf_counter() - t0
    t0 = time.perf_counter()
    build_index(str(fa), str(tmp_path / "p.fai"), use_native=False)
    t_python = time.perf_counter() - t0
    assert open(tmp_path / "n.fai").read() == open(tmp_path / "p.fai").read()
    assert t_native < t_python, (t_native, t_python)


def test_fai_header_whitespace_and_preheader_parity(tmp_path):
    """Cases the first parity matrix missed: whitespace after '>' (name
    still parses) and ragged data BEFORE any header (both paths raise,
    naming record None)."""
    native_text, python_text = _fai_both_ways(
        tmp_path, ">  a desc\nMKTA\n>\t b\nGG\n", "ws_header")
    assert native_text == python_text
    assert native_text.splitlines()[0].startswith("a\t")
    assert native_text.splitlines()[1].startswith("b\t")

    from proteinbert_tpu.etl.fasta import build_index

    fa = tmp_path / "preheader.fasta"
    fa.write_text("AB\nABCD\n>a\nAC\n")
    for kw in ({}, {"use_native": False}):
        with pytest.raises(ValueError, match="record None"):
            build_index(str(fa), str(tmp_path / "ph.fai"), **kw)


def test_fai_error_message_names_record(tmp_path):
    from proteinbert_tpu.etl.fasta import build_index

    fa = tmp_path / "ragged2.fasta"
    fa.write_text(">ok\nAAAA\n>bad_rec\nMKTA\nYIAKQRQI\n")
    for kw in ({}, {"use_native": False}):
        with pytest.raises(ValueError, match="record 'bad_rec'"):
            build_index(str(fa), str(tmp_path / "rr.fai"), **kw)


def test_fai_failed_build_leaves_no_index(tmp_path):
    """A raising build must not leave a truncated .fai that FastaReader
    would later trust."""
    from proteinbert_tpu.etl.fasta import build_index

    fa = tmp_path / "ragged3.fasta"
    fa.write_text(">ok\nAAAA\n>bad\nMKTA\nYIAKQRQI\n")
    for kw in ({}, {"use_native": False}):
        with pytest.raises(ValueError):
            build_index(str(fa), **kw)
        assert not (tmp_path / "ragged3.fasta.fai").exists()
        assert not list(tmp_path.glob("*.tmp*"))
