"""Native C++ batch tokenizer: build infra, parity with the numpy path,
crop semantics, and throughput sanity."""

import numpy as np
import pytest

from proteinbert_tpu.data.transforms import tokenize_batch
from proteinbert_tpu.data.vocab import EOS_ID, PAD_ID, SOS_ID, UNK_ID
from proteinbert_tpu.native import native_available, tokenize_batch_native

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no C++ toolchain in this environment"
)


def _random_seqs(rng, n, max_len=300):
    return ["".join(rng.choice(list("ACDEFGHIKLMNPQRSTVWYXZ*"),
                               size=int(rng.integers(0, max_len))))
            for _ in range(n)]


def test_parity_no_crop(rng):
    seqs = _random_seqs(rng, 64, max_len=60)
    want = tokenize_batch(seqs, 64, use_native=False)
    got = tokenize_batch_native(seqs, 64)
    np.testing.assert_array_equal(got, want)


def test_parity_head_truncation(rng):
    # Longer than seq_len-2 without rng → head-truncate, same as numpy.
    seqs = _random_seqs(rng, 32, max_len=200)
    want = tokenize_batch(seqs, 48, use_native=False)
    got = tokenize_batch_native(seqs, 48)
    np.testing.assert_array_equal(got, want)


def test_crop_windows_are_valid_substrings(rng):
    seq = "".join(rng.choice(list("ACDEFGHIKLMNPQRSTVWY"), size=500))
    cap = 30
    starts = set()
    for trial in range(50):
        row = tokenize_batch_native([seq], cap + 2,
                                    np.random.default_rng(trial))[0]
        assert row[0] == SOS_ID and row[cap + 1] == EOS_ID
        decoded = row[1:cap + 1]
        # The cropped window must be a contiguous substring of the source.
        full = tokenize_batch([seq], len(seq) + 2, use_native=False)[0][1:-1]
        matches = [s for s in range(len(seq) - cap + 1)
                   if np.array_equal(full[s:s + cap], decoded)]
        assert matches, "crop is not a substring"
        starts.add(matches[0])
    assert len(starts) > 5, "crop windows never vary"


def test_crop_deterministic_given_rng_state():
    seqs = ["A" * 10 + "C" * 300, "D" * 400]
    a = tokenize_batch_native(seqs, 32, np.random.default_rng(7))
    b = tokenize_batch_native(seqs, 32, np.random.default_rng(7))
    np.testing.assert_array_equal(a, b)


def test_unknown_chars_map_to_unk():
    got = tokenize_batch_native(["B1?", "acde"], 8)
    assert (got[0][1:4] == UNK_ID).all()
    # lowercase residues are soft-masked FASTA → real ids, like the LUT.
    want = tokenize_batch(["acde"], 8, use_native=False)[0]
    np.testing.assert_array_equal(got[1], want)


def test_empty_batch_and_empty_seq():
    assert tokenize_batch_native([], 16).shape == (0, 16)
    row = tokenize_batch_native([""], 16)[0]
    assert row[0] == SOS_ID and row[1] == EOS_ID and (row[2:] == PAD_ID).all()


def test_dispatch_through_tokenize_batch(rng):
    """transforms.tokenize_batch auto-routes big batches to native."""
    seqs = _random_seqs(rng, 32, max_len=40)
    native = tokenize_batch(seqs, 64)            # auto → native
    python = tokenize_batch(seqs, 64, use_native=False)
    np.testing.assert_array_equal(native, python)


def test_native_throughput_sanity(rng):
    """The point of the C++ path: it must beat the per-row numpy loop."""
    import time

    seqs = _random_seqs(rng, 512, max_len=400)
    tokenize_batch_native(seqs, 512)  # warm (library load)
    t0 = time.perf_counter()
    for _ in range(5):
        tokenize_batch_native(seqs, 512)
    t_native = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(5):
        tokenize_batch(seqs, 512, use_native=False)
    t_python = time.perf_counter() - t0
    assert t_native < t_python, (t_native, t_python)
