"""Distribution tests on the virtual 8-device CPU mesh (SURVEY §4 plan).

Covers: mesh construction, sharding rules (DP/FSDP/TP/SP), numerical
parity of the sharded train step vs single-device, and the explicit
halo-exchange sequence-parallel conv vs the unsharded conv.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from proteinbert_tpu.configs import (
    DataConfig, MeshConfig, ModelConfig, OptimizerConfig, PretrainConfig,
    TrainConfig,
)
from proteinbert_tpu.data import make_pretrain_iterator, InMemoryPretrainingDataset
from proteinbert_tpu.ops.layers import conv1d_init, conv1d_apply
from proteinbert_tpu.parallel import (
    batch_sharding, conv1d_halo, make_mesh, seq_parallel_conv1d,
    shard_train_state, state_sharding,
)
from proteinbert_tpu.train import create_train_state, train_step
from tests.conftest import make_random_proteins

requires_8 = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 (virtual) devices"
)


def cfg_for(mesh_cfg, **model_kw):
    model = dict(
        local_dim=16, global_dim=32, key_dim=8, num_heads=4, num_blocks=2,
        num_annotations=64, dtype="float32",
    )
    model.update(model_kw)
    return PretrainConfig(
        model=ModelConfig(**model),
        data=DataConfig(seq_len=32, batch_size=16),
        optimizer=OptimizerConfig(learning_rate=1e-3, warmup_steps=10),
        mesh=mesh_cfg,
        train=TrainConfig(max_steps=4),
    )


def make_batch(cfg, seed=0):
    rng = np.random.default_rng(seed)
    seqs, ann = make_random_proteins(
        cfg.data.batch_size, rng, num_annotations=cfg.model.num_annotations,
        max_len=40,
    )
    ds = InMemoryPretrainingDataset(seqs, ann, cfg.data.seq_len)
    return next(make_pretrain_iterator(ds, cfg.data.batch_size, seed=seed))


@requires_8
def test_mesh_construction():
    mesh = make_mesh(MeshConfig(data=2, fsdp=2, model=2, seq=1))
    assert mesh.shape == {"data": 2, "fsdp": 2, "model": 2, "seq": 1}
    with pytest.raises(ValueError, match="devices"):
        make_mesh(MeshConfig(data=3))


@requires_8
def test_sharding_rules_tp_and_fsdp():
    mesh = make_mesh(MeshConfig(data=2, fsdp=2, model=2, seq=1))
    cfg = cfg_for(MeshConfig(data=2, fsdp=2, model=2, seq=1))
    abstract = jax.eval_shape(
        lambda: create_train_state(jax.random.PRNGKey(0), cfg)
    )
    sh = state_sharding(mesh, abstract)
    # TP: global head column-sharded over 'model'
    assert sh.params["global_head"]["kernel"].spec == P(None, "model")
    assert sh.params["global_in"]["kernel"].spec == P("model", None)
    # scalars replicated
    assert sh.step.spec == P()
    # FSDP: some block tensor carries the fsdp axis, never on axis 0
    block_specs = jax.tree.leaves(
        jax.tree.map(lambda s: s.spec, sh.params["blocks"],
                     is_leaf=lambda x: hasattr(x, "spec"))
    )
    fsdp_specs = [s for s in block_specs if "fsdp" in tuple(s)]
    assert fsdp_specs, "no block param is fsdp-sharded"
    for s in fsdp_specs:
        assert s[0] is None


@requires_8
@pytest.mark.parametrize(
    "mesh_cfg",
    [
        MeshConfig(data=8),                      # pure DP
        MeshConfig(data=2, fsdp=2, model=2),     # DP+FSDP+TP
        MeshConfig(data=2, seq=4),               # DP+SP
        MeshConfig(data=2, fsdp=2, seq=2),       # DP+FSDP+SP
    ],
    ids=["dp", "dp-fsdp-tp", "dp-sp", "dp-fsdp-sp"],
)
def test_sharded_train_step_matches_single_device(mesh_cfg):
    """The compiled distributed step must be numerically equivalent to the
    single-device step (XLA inserts psum/all-gather/halo automatically)."""
    _assert_sharded_step_matches(cfg_for(mesh_cfg))


def _assert_sharded_step_matches(cfg):
    mesh_cfg = cfg.mesh
    batch = make_batch(cfg)

    state0 = create_train_state(jax.random.PRNGKey(0), cfg)
    ref_state, ref_metrics = train_step(state0, batch, cfg)

    mesh = make_mesh(mesh_cfg)
    state = create_train_state(jax.random.PRNGKey(0), cfg)
    state = shard_train_state(state, mesh)
    bsh = batch_sharding(mesh)
    dbatch = {k: jax.device_put(v, bsh[k]) for k, v in batch.items()}
    new_state, metrics = train_step(state, dbatch, cfg)

    assert float(metrics["loss"]) == pytest.approx(
        float(ref_metrics["loss"]), rel=2e-5
    )
    ref_leaves = jax.tree.leaves(ref_state.params)
    got_leaves = jax.tree.leaves(new_state.params)
    for r, g in zip(ref_leaves, got_leaves):
        np.testing.assert_allclose(
            np.asarray(r), np.asarray(jax.device_get(g)), atol=2e-5,
            err_msg=str(mesh_cfg),
        )


@requires_8
@pytest.mark.parametrize("model_kw", [
    # num_blocks=5 with unroll=2 keeps a REAL loop (2 iterations of 2
    # bodies + remainder) — at the default num_blocks=2 the scan would
    # fully unroll to straight-line code and never compile the mixed
    # loop-plus-unroll pattern this test exists to cover.
    dict(scan_unroll=2, num_blocks=5, remat=True, remat_policy="convs"),
    dict(scan_split_transpose=True, remat=True, remat_policy="convs"),
    # Both levers together — the bench's remat-convs-u2st variant.
    dict(scan_unroll=2, num_blocks=5, scan_split_transpose=True,
         remat=True, remat_policy="convs"),
], ids=["u2-remat-convs", "st-remat-convs", "u2st-remat-convs"])
def test_scan_knobs_match_single_device_under_fsdp(model_kw):
    """The scan scheduling knobs (partial unroll / split transpose) on
    the implicit-SPMD path must stay numerically equivalent to the
    single-device step when the stacked-block params are fsdp-sharded —
    with unroll the scan body consumes k fsdp-sharded block slices per
    iteration, a different all-gather pattern than the u1 scan the other
    parity tests compile."""
    mesh_cfg = MeshConfig(data=2, fsdp=2, seq=2)
    _assert_sharded_step_matches(cfg_for(mesh_cfg, **model_kw))


@requires_8
@pytest.mark.parametrize("dilation", [1, 5])
def test_halo_conv_matches_dense(dilation):
    """Explicit shard_map halo conv == unsharded 'SAME' conv."""
    mesh = make_mesh(MeshConfig(data=2, seq=4))
    key = jax.random.PRNGKey(0)
    C = 8
    params = conv1d_init(key, 9, C, C)
    x = jax.random.normal(jax.random.fold_in(key, 1), (4, 64, C))
    ref = conv1d_apply(params, x, dilation=dilation)
    got = seq_parallel_conv1d(mesh, params, x, dilation=dilation)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(jax.device_get(got)), atol=1e-5
    )


@requires_8
def test_halo_conv_single_shard_degenerates():
    mesh = make_mesh(MeshConfig(data=8, seq=1))
    key = jax.random.PRNGKey(0)
    params = conv1d_init(key, 9, 4, 4)
    x = jax.random.normal(key, (8, 16, 4))
    ref = conv1d_apply(params, x, dilation=2)
    got = seq_parallel_conv1d(mesh, params, x, dilation=2)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got), atol=1e-5)


# ------------------------------------------------------ multi-slice mesh

class _FakeTpuDev:
    """Stub with the attributes mesh_utils consults (id, process_index,
    slice_index, coords, core_on_chip, device_kind, platform)."""

    def __init__(self, i, slice_index):
        self.id = i
        self.process_index = slice_index
        self.slice_index = slice_index
        self.platform = "tpu"
        self.device_kind = "faketpu"
        j = i % 4
        self.coords = (j % 2, j // 2, 0)
        self.core_on_chip = 0

    def __repr__(self):
        return f"fake{self.id}@slice{self.slice_index}"


def test_multislice_mesh_puts_data_axis_on_dcn():
    """2 slices x 4 chips: the data axis must span slices (outer DCN hop)
    while fsdp/model stay within a slice's ICI."""
    from proteinbert_tpu.configs import MeshConfig
    from proteinbert_tpu.parallel.mesh import make_mesh

    devs = [_FakeTpuDev(i, i // 4) for i in range(8)]
    mesh = make_mesh(MeshConfig(data=2, fsdp=2, model=2, seq=1), devs)
    assert dict(mesh.shape) == {"data": 2, "fsdp": 2, "model": 2, "seq": 1}
    arr = mesh.devices
    # Each data-axis row is one slice; every other axis stays intra-slice.
    for d in range(2):
        slices = {dev.slice_index for dev in arr[d].flatten()}
        assert slices == {d}, f"data row {d} spans slices {slices}"


def test_multislice_mesh_rejects_indivisible_data_axis():
    from proteinbert_tpu.configs import MeshConfig
    from proteinbert_tpu.parallel.mesh import make_mesh

    devs = [_FakeTpuDev(i, i // 4) for i in range(8)]
    with pytest.raises(ValueError, match="multiple of the 2 slices"):
        make_mesh(MeshConfig(data=1, fsdp=2, model=2, seq=2), devs)


def test_fsdp_compile_has_no_involuntary_remat_warning():
    """The fsdp-bearing mesh must compile the train step without the SPMD
    partitioner's "Involuntary full rematerialization" fallback (VERDICT
    r2 Weak #3: the scan-boundary stash of per-block bf16 param casts
    used to trigger it; fixed by hoisting the cast out of the scan and
    FSDP-sharding stacked-block leaves on their LAST divisible axis).
    XLA emits the warning from C++ on stderr, so compile in a subprocess
    and grep — an in-process warnings filter cannot see it. As a
    POSITIVE control against silent rot (XLA rewording the message, or a
    log-level knob suppressing C++ warnings would otherwise keep this
    green forever), the same compile under the classic GSPMD partitioner
    (shardy off) is known to emit the warning and must still match the
    grep."""
    import os
    import subprocess
    import sys

    if not jax.config.jax_use_shardy_partitioner:
        pytest.skip("default partitioner is GSPMD (jax 0.4.x: shardy not "
                    "yet the default) — the warning-free property under "
                    "test belongs to the shardy partitioner")

    code = """
import jax
from proteinbert_tpu.utils.compat import request_cpu_devices
request_cpu_devices(8)
# A persistent-cache hit loads an AOT result and SKIPS partitioning, so
# neither arm would emit the warning (observed: the positive control
# went silent once the suite's cache warmed) — force fresh compiles.
jax.config.update("jax_enable_compilation_cache", False)
import os as _os
if _os.environ.get("PBT_TEST_FORCE_GSPMD"):
    jax.config.update("jax_use_shardy_partitioner", False)
import numpy as np
from proteinbert_tpu.configs import (DataConfig, MeshConfig, ModelConfig,
    OptimizerConfig, PretrainConfig, TrainConfig)
from proteinbert_tpu.parallel import batch_sharding, make_mesh
from proteinbert_tpu.parallel.sharding import state_sharding
from proteinbert_tpu.train import create_train_state
import proteinbert_tpu.train.train_state as TS

mesh_cfg = MeshConfig(data=2, fsdp=2, model=2, seq=1)
cfg = PretrainConfig(
    model=ModelConfig(local_dim=32, global_dim=64, key_dim=16, num_heads=4,
                      num_blocks=2, num_annotations=128, dtype="bfloat16",
                      remat=True, remat_policy="convs"),
    data=DataConfig(seq_len=64, batch_size=8),
    optimizer=OptimizerConfig(warmup_steps=10),
    mesh=mesh_cfg, train=TrainConfig(max_steps=1))
mesh = make_mesh(mesh_cfg, jax.devices()[:8])
abstract = jax.eval_shape(lambda: create_train_state(jax.random.PRNGKey(0), cfg))
sh = state_sharding(mesh, abstract)
bsh = batch_sharding(mesh)
bat = {"tokens": jax.ShapeDtypeStruct((8, 64), np.int32, sharding=bsh["tokens"]),
       "annotations": jax.ShapeDtypeStruct((8, 128), np.float32,
                                           sharding=bsh["annotations"])}
st = jax.tree.map(lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
                  abstract, sh)
TS.train_step.lower(st, bat, cfg).compile()
print("COMPILED-OK")
"""
    def compile_once(force_gspmd):
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        if force_gspmd:
            env["PBT_TEST_FORCE_GSPMD"] = "1"
        return subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, timeout=420,
                              env=env)

    marker = "Involuntary full rematerialization"
    out = compile_once(force_gspmd=False)
    assert "COMPILED-OK" in out.stdout, out.stderr[-2000:]
    assert marker not in out.stderr, out.stderr[-3000:]

    control = compile_once(force_gspmd=True)
    assert "COMPILED-OK" in control.stdout, control.stderr[-2000:]
    assert marker in control.stderr, (
        "positive control failed: the GSPMD compile no longer emits the "
        "warning text this test greps for — update the marker (XLA may "
        "have reworded it) before trusting the negative assertion above")
