"""Child process for the REAL two-process multi-host test (not a pytest
module — spawned by tests/test_multihost.py).

Round 1 only simulated multi-host by passing process_index/process_count
ints into the iterators (VERDICT r1 Missing #3); this script executes the
actual coordination path: `jax.distributed.initialize` against a
localhost coordinator, a global mesh spanning both processes' CPU
devices, per-host data shards assembled into global arrays via
`jax.make_array_from_process_local_data` (trainer._make_batch_put's
process_count>1 branch), and psum-under-jit gradient reduction across
process boundaries — the plan SURVEY §5 (distributed backend bullet)
prescribes, executed for real.

Usage: python multihost_child.py <process_id> <num_processes> <port> [mode]
                                 [ckpt_dir] [kill_at]
mode: "plain" (default) — fixed-shape make_pretrain_iterator;
      "bucketed" — make_bucketed_iterator, exercising the multi-host
      LOCKSTEP invariant (every host must emit the same bucket shape at
      every step or the collective step deadlocks/mismatches) across a
      real process boundary;
      "preempt" / "preempt-bucketed" — 6-step run with an orbax
      checkpointer in <ckpt_dir>; on a FRESH directory every process
      SIGTERMs itself at step <kill_at> (kill_at=0: run straight
      through), driving the GracefulShutdown → collective orbax save
      path and exiting 75; re-launched on the now-populated directory
      it restores (mesh-sharded template), fast-forwards the data
      stream, and completes — the two-process preemption/resume drill
      of VERDICT r3 item 7. The -bucketed variant drives the bucketed
      iterator's lockstep bookkeeping across the resume seam.
Prints one line per step: STEP <i> LOSS <float>  (process 0 only),
plus "PREEMPTED <step>" when the drill's SIGTERM fired.
"""

import os
import signal
import sys


def main() -> None:
    process_id, num_processes, port = (int(a) for a in sys.argv[1:4])
    mode = sys.argv[4] if len(sys.argv) > 4 else "plain"

    import jax

    # Before any backend use: 2 local CPU devices per process, gloo
    # cross-process collectives (the CPU stand-in for ICI/DCN).
    from proteinbert_tpu.utils.compat import request_cpu_devices

    request_cpu_devices(2)
    if num_processes > 1:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        jax.distributed.initialize(
            coordinator_address=f"127.0.0.1:{port}",
            num_processes=num_processes,
            process_id=process_id,
        )
        assert jax.process_count() == num_processes
        assert jax.local_device_count() == 2
    n_devices = jax.device_count()

    import numpy as np

    from proteinbert_tpu.configs import (
        DataConfig, MeshConfig, ModelConfig, OptimizerConfig, PretrainConfig,
        TrainConfig,
    )
    from proteinbert_tpu.data import (
        InMemoryPretrainingDataset, make_bucketed_iterator,
        make_pretrain_iterator,
    )
    from proteinbert_tpu.data.synthetic import make_random_proteins
    from proteinbert_tpu.parallel import make_mesh, shard_train_state
    from proteinbert_tpu.train import create_train_state, pretrain

    global_batch = 8
    max_steps = 6 if mode.startswith("preempt") else 3
    cfg = PretrainConfig(
        model=ModelConfig(
            local_dim=16, global_dim=32, key_dim=8, num_heads=4,
            num_blocks=2, num_annotations=32, dtype="float32",
        ),
        data=DataConfig(seq_len=32, batch_size=global_batch // num_processes,
                        prefetch_depth=0),
        optimizer=OptimizerConfig(
            learning_rate=1e-3, warmup_steps=4, schedule="constant"),
        mesh=MeshConfig(data=n_devices),
        train=TrainConfig(max_steps=max_steps, log_every=1),
    )

    # Every process builds the same full dataset (same seed); the
    # iterator hands each its disjoint shard, exactly as on a pod.
    rng = np.random.default_rng(0)
    if "bucketed" in mode:
        # Long rows + crop_seed + two length buckets: every host must run
        # the SAME bucket bookkeeping and emit the same shape per step.
        seqs, ann = make_random_proteins(48, rng, num_annotations=32,
                                         max_len=60)
        ds = InMemoryPretrainingDataset(seqs, ann, cfg.data.seq_len,
                                        crop_seed=7)
        buckets = (16, cfg.data.seq_len)

        def host_iter(pid, pcount, batch, skip=0):
            return make_bucketed_iterator(
                ds, batch, buckets, seed=1,
                process_index=pid, process_count=pcount, skip_batches=skip)
    else:
        seqs, ann = make_random_proteins(16, rng, num_annotations=32,
                                         max_len=40)
        ds = InMemoryPretrainingDataset(seqs, ann, cfg.data.seq_len)

        def host_iter(pid, pcount, batch, skip=0):
            return make_pretrain_iterator(
                ds, batch, seed=1, process_index=pid, process_count=pcount,
                skip_batches=skip)

    if mode.startswith("preempt"):
        ckpt_dir, kill_at = sys.argv[5], int(sys.argv[6])

        from proteinbert_tpu.train.checkpoint import Checkpointer

        # Sync save: the drill must be deterministic step-for-step; the
        # async path's timing is exercised by the hardware sustained run.
        ckpt = Checkpointer(ckpt_dir, async_save=False)
        fresh = ckpt.latest_step() is None

        def factory(skip):
            return host_iter(process_id, num_processes, cfg.data.batch_size,
                             skip)

        kill_hook = None
        if fresh and kill_at:
            # Every process SIGTERMs ITSELF at the same step — the
            # deterministic stand-in for a pod-wide preemption notice;
            # GracefulShutdown then drives the collective orbax save.
            def kill_hook(step, m):
                if step == kill_at:
                    os.kill(os.getpid(), signal.SIGTERM)

        mesh = make_mesh(cfg.mesh, jax.devices())
        losses = []

        def record(step, m):
            if "loss" in m:
                losses.append((step, m["loss"]))
            if kill_hook is not None:
                kill_hook(step, m)

        out = pretrain(cfg, factory, state=None, checkpointer=ckpt,
                       mesh=mesh, log_fn=record)
        ckpt.close()
        if process_id == 0:
            for step, loss in losses:
                print(f"STEP {step} LOSS {loss:.8f}", flush=True)
            if out["preempted"]:
                print(f"PREEMPTED {int(out['state'].step)}", flush=True)
        sys.exit(75 if out["preempted"] else 0)

    if num_processes > 1:
        it = host_iter(process_id, num_processes, cfg.data.batch_size)
    else:
        # Reference mode: ONE process reproduces the exact global batch
        # the 2-process run assembles — host h's shard occupies the h-th
        # slice of the data axis, so the global batch is the
        # concatenation of both hosts' per-host batches.
        def concat_host_shards():
            its = [host_iter(p, 2, global_batch // 2) for p in range(2)]
            while True:
                parts = [next(i) for i in its]
                yield {k: np.concatenate([p[k] for p in parts])
                       for k in parts[0]}

        it = concat_host_shards()

    mesh = make_mesh(cfg.mesh, jax.devices())
    state = shard_train_state(create_train_state(jax.random.PRNGKey(0), cfg),
                              mesh)

    losses = []
    out = pretrain(cfg, it, state=state, mesh=mesh,
                   log_fn=lambda step, m: losses.append((step, m["loss"])))
    assert int(out["state"].step) == 3
    if process_id == 0:
        for step, loss in losses:
            print(f"STEP {step} LOSS {loss:.8f}", flush=True)


if __name__ == "__main__":
    main()
