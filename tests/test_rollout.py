"""Blue-green trunk rollout (ISSUE 20): parity scoring, candidate-arm
refusals, flip-under-load atomicity + bit-identical rollback, shadow
invisibility, registry fingerprint migration with the unfrozen-head
refusal, the fleet fingerprint-coherence sweep, and the rollout event
schema round-trips."""

import math
import threading

import jax
import numpy as np
import pytest

from proteinbert_tpu.configs import (
    DataConfig, ModelConfig, PretrainConfig, TaskConfig,
)
from proteinbert_tpu.heads import HeadRegistry, trunk_fingerprint
from proteinbert_tpu.heads.registry import (
    UnfrozenHeadError, UnknownHeadError,
)
from proteinbert_tpu.models import finetune as ft_model
from proteinbert_tpu.models import proteinbert
from proteinbert_tpu.obs import Telemetry, read_events
from proteinbert_tpu.obs.events import make_example, validate_record
from proteinbert_tpu.rollout import RolloutController
from proteinbert_tpu.rollout.controller import parity_delta
from proteinbert_tpu.serve import Server
from proteinbert_tpu.serve.errors import (
    CandidateUnfitError, NoCandidateError,
)
from proteinbert_tpu.serve.fleet import FleetRouter

MODEL = ModelConfig(local_dim=16, global_dim=32, key_dim=8, num_heads=2,
                    num_blocks=2, num_annotations=32, dtype="float32")
BUCKETS = (24, 48)
CFG = PretrainConfig(model=MODEL,
                     data=DataConfig(seq_len=48, batch_size=4,
                                     buckets=BUCKETS))
PROBE = "MKTAYIAKQRQISFVKSH"


@pytest.fixture(scope="module")
def params():
    return proteinbert.init(jax.random.PRNGKey(0), MODEL)


@pytest.fixture(scope="module")
def cand_params(params):
    """A structurally identical trunk with slightly different weights —
    a realistic re-pretrain candidate."""
    leaves, treedef = jax.tree.flatten(params)
    rng = np.random.default_rng(3)
    out = []
    for leaf in leaves:
        a = np.asarray(leaf)
        out.append(a + (1e-3 * rng.standard_normal(a.shape))
                   .astype(a.dtype))
    return jax.tree.unflatten(treedef, out)


# ------------------------------------------------------- parity scoring

class TestParityDelta:
    def test_numeric_leaves(self):
        assert parity_delta({"a": 1.0}, {"a": 1.5}) == 0.5
        assert parity_delta([1, 2], [1, 2.25]) == 0.25
        assert parity_delta({"a": {"b": [0.0]}},
                            {"a": {"b": [0.0]}}) == 0.0

    def test_non_numeric_leaves_differ_freely(self):
        # Request ids and names are EXPECTED to differ live-vs-shadow.
        assert parity_delta({"id": "req-1", "x": 2.0},
                            {"id": "req-9", "x": 2.0}) == 0.0

    def test_structural_mismatch_is_inf(self):
        assert math.isinf(parity_delta([1.0, 2.0], [1.0]))
        assert math.isinf(parity_delta({"a": 1.0}, {}))
        assert math.isinf(parity_delta(1.0, "one"))

    def test_bools_compare_by_equality(self):
        assert parity_delta({"ok": True}, {"ok": True}) == 0.0
        assert math.isinf(parity_delta({"ok": True}, {"ok": False}))
        # Bools use Python equality (so True == 1 passes, True != 2.0).
        assert math.isinf(parity_delta(True, 2.0))

    def test_missing_non_numeric_key_tolerated(self):
        assert parity_delta({"x": 1.0, "note": "hi"}, {"x": 1.0}) == 0.0


# -------------------------------------------- registry pin migration

def _save_head(reg, model_params, task, name, seed=1):
    hp = ft_model.head_init(jax.random.PRNGKey(seed), MODEL, task)
    return reg.save(jax.tree.map(np.asarray, hp), task,
                    trunk_fingerprint(model_params), name=name)


class TestMigrateFingerprint:
    def test_roundtrip_with_audit(self, tmp_path, params, cand_params):
        reg = HeadRegistry(str(tmp_path))
        task = TaskConfig(kind="sequence_classification", num_outputs=3,
                          freeze_trunk=True)
        hid = _save_head(reg, params, task, "frozen")
        old_fp = trunk_fingerprint(params)
        new_fp = trunk_fingerprint(cand_params)

        meta = reg.migrate_fingerprint(hid, new_fp, note="promo")
        assert meta["trunk_fingerprint"] == new_fp
        assert [m["note"] for m in meta["migrations"]] == ["promo"]
        # The artifact still loads and verifies under the new pin.
        assert reg.load(hid, trunk_fp=new_fp).head_id == hid
        # Idempotent re-pin: no second audit record.
        again = reg.migrate_fingerprint(hid, new_fp)
        assert len(again["migrations"]) == 1
        # Rollback re-pin appends a second record.
        back = reg.migrate_fingerprint(hid, old_fp, note="rollback")
        assert back["trunk_fingerprint"] == old_fp
        assert len(back["migrations"]) == 2

    def test_unfrozen_head_typed_refusal(self, tmp_path, params,
                                         cand_params):
        reg = HeadRegistry(str(tmp_path))
        task = TaskConfig(kind="sequence_regression", num_outputs=1,
                          freeze_trunk=False)
        hid = _save_head(reg, params, task, "unfrozen")
        with pytest.raises(UnfrozenHeadError):
            reg.migrate_fingerprint(hid, trunk_fingerprint(cand_params))
        # The refusal left the pin untouched.
        assert reg._read_meta(hid)["trunk_fingerprint"] \
            == trunk_fingerprint(params)

    def test_unknown_head(self, tmp_path):
        with pytest.raises(UnknownHeadError):
            HeadRegistry(str(tmp_path)).migrate_fingerprint("nope", "f")


# ----------------------------------------------- candidate arm refusals

class TestCandidateArm:
    def test_refusals_are_typed(self, params, cand_params):
        srv = Server(params, CFG, buckets=BUCKETS, max_batch=4,
                     max_wait_s=0.005, cache_size=8, warm_kinds=())
        with srv:
            with pytest.raises(NoCandidateError):
                srv.flip()
            with pytest.raises(NoCandidateError):
                srv.rollback_trunk()
            with pytest.raises(NoCandidateError):
                srv.shadow_submit("embed", PROBE)
            with pytest.raises(CandidateUnfitError):
                srv.load_candidate(params=cand_params,
                                   hbm_budget_bytes=1)
            # The refusal left no residue on the arm.
            assert srv.rollout_status()["candidate_fingerprint"] is None
            with pytest.raises(ValueError):
                srv.load_candidate()  # neither params nor source
            with pytest.raises(ValueError):
                srv.load_candidate(source="x")  # no candidate_loader

    def test_shadow_invisibility(self, params, cand_params):
        srv = Server(params, CFG, buckets=BUCKETS, max_batch=4,
                     max_wait_s=0.005, cache_size=8, warm_kinds=())
        with srv:
            live = srv.embed(PROBE, timeout=60)
            srv.load_candidate(params=cand_params)
            before = srv.stats()
            shadow = srv.shadow_submit("embed", PROBE)
            after = srv.stats()
            # Same result SHAPE as the live path (the parity scorer
            # depends on structural agreement), different weights...
            jsonable = lambda out: {k: np.asarray(v).tolist()
                                    for k, v in out.items()}
            delta = parity_delta(jsonable(live), jsonable(shadow))
            assert 0.0 < delta < math.inf
            # ...but NO live-path bookkeeping moved: not a completion,
            # not a cache entry, not a rejection.
            assert after["completed"] == before["completed"]
            assert after["cache"] == before["cache"]
            assert after["rejected"] == before["rejected"]
            assert after["rollout"]["shadow_requests"] \
                == before["rollout"]["shadow_requests"] + 1
            assert srv.unload_candidate()

    def test_flip_under_load_and_bitwise_rollback(self, params,
                                                  cand_params):
        """Concurrent submits across a flip each see EXACTLY one trunk
        (resident xor candidate, never a torn mix), and rollback
        restores bit-identical resident numerics."""
        # max_batch=1 pins every request to the SAME (1, L) executable
        # (row padding to a larger batch class would change the compiled
        # shape and void bitwise comparison); references come from the
        # server's own arms — shadow_submit shares the live path's
        # prep/padding, so it is the exact candidate-arm reference.
        srv = Server(params, CFG, buckets=BUCKETS, max_batch=1,
                     max_wait_s=0.002, cache_size=0, warm_kinds=())
        with srv:
            res_ref = srv.embed(PROBE, timeout=60)
            srv.load_candidate(params=cand_params)
            cand_ref = srv.shadow_submit("embed", PROBE)
            assert not np.array_equal(res_ref["global"],
                                      cand_ref["global"])
            results = [None] * 24
            start = threading.Barrier(4)

            def client(w):
                start.wait()
                for i in range(w, len(results), 3):
                    results[i] = srv.embed(PROBE, timeout=60)

            threads = [threading.Thread(target=client, args=(w,))
                       for w in range(3)]
            for t in threads:
                t.start()
            start.wait()
            flip_report = srv.flip()
            for t in threads:
                t.join(timeout=120)
            assert flip_report["fingerprint"] \
                == trunk_fingerprint(cand_params)
            for out in results:
                is_res = np.array_equal(out["global"], res_ref["global"])
                is_cand = np.array_equal(out["global"],
                                         cand_ref["global"])
                assert is_res != is_cand, \
                    "a request saw a torn trunk mix across the flip"
            # Post-flip requests see only the candidate.
            assert np.array_equal(srv.embed(PROBE, timeout=60)["global"],
                                  cand_ref["global"])
            # Instant rollback: bit-identical resident numerics.
            srv.rollback_trunk()
            back = srv.embed(PROBE, timeout=60)
            assert np.array_equal(back["global"], res_ref["global"])
            assert np.array_equal(back["local_mean"],
                                  res_ref["local_mean"])
            assert srv.trunk_fp() == trunk_fingerprint(params)


# ------------------------------------------- fleet coherence + schema

class TestFleetFingerprintSweep:
    def test_mixed_fleet_degrades(self, tmp_path):
        events = str(tmp_path / "router.jsonl")
        tele = Telemetry(events_path=events)
        router = FleetRouter([("a", "http://127.0.0.1:1"),
                              ("b", "http://127.0.0.1:2")],
                             telemetry=tele)

        def health(rep, fp, cand=None):
            payload = {"ok": True, "trunk_fingerprint": fp,
                       "quant": "fp32", "stats": {}}
            if cand is not None:
                payload["stats"]["rollout"] = {
                    "candidate_fingerprint": cand}
            router._apply_health(rep, payload)

        a, b = router.replicas
        health(a, "f" * 64)
        health(b, "f" * 64)
        router._sweep_fingerprints()
        assert router.fingerprint_status()["fleet_state"] == "coherent"

        health(b, "e" * 64, cand="c" * 64)
        router._sweep_fingerprints()
        st = router.fingerprint_status()
        assert st["fleet_state"] == "degraded"
        assert st["fingerprints"] == {"a": "f" * 64, "b": "e" * 64}
        assert st["candidates"] == {"b": "c" * 64}
        assert router.stats()["fleet_state"] == "degraded"

        # A dead replica is not "mixed": the sweep only counts
        # routable arms, so the fleet converges when b dies.
        with router._lock:
            router._transition(b, "dead", reason="test")
        router._sweep_fingerprints()
        assert router.fingerprint_status()["fleet_state"] == "coherent"

        tele.close()
        fleet_evs = [r for r in read_events(events, strict=True)
                     if r["event"] == "rollout_fleet"]
        assert [r["state"] for r in fleet_evs] == ["degraded",
                                                   "coherent"]

    def test_controller_spec_validation(self):
        for bad in (dict(source=""), dict(source="x", sample_every=0),
                    dict(source="x", window_requests=0),
                    dict(source="x", windows_required=0)):
            with pytest.raises((ValueError, TypeError)):
                RolloutController(object(), **bad)
        ctl = RolloutController(object(), source="x")
        assert ctl.state == "idle" and ctl.terminal()
        with pytest.raises(RuntimeError):
            ctl.promote()  # no green streak, not even shadowing
        with pytest.raises(RuntimeError):
            ctl.breach()


class TestRolloutEventSchema:
    @pytest.mark.parametrize("event", ["rollout_state", "rollout_window",
                                       "rollout_shadow", "rollout_flip",
                                       "rollout_fleet"])
    def test_examples_roundtrip(self, event):
        validate_record(make_example(event))

    def test_shadow_must_be_literally_true(self):
        rec = make_example("rollout_shadow")
        rec["shadow"] = False
        with pytest.raises(ValueError):
            validate_record(rec)
