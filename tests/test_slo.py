"""SLO layer (proteinbert_tpu/obs/slo.py, ISSUE 6): declarative
objective parsing, fake-clock burn-rate math, exemplar histograms,
breach actions, and the on-demand profile trigger.

Everything here runs against an injected fake clock — burn rates are
exact arithmetic over a deterministic window, never wall-clock."""

import threading
import time

import pytest

from proteinbert_tpu.obs import MetricsRegistry
from proteinbert_tpu.obs.slo import (
    ExemplarHistogram, ProfileTrigger, SLObjective, SLOEvaluator,
    parse_slo, parse_slos,
)


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


# ------------------------------------------------------------- parsing

class TestParseSLO:
    def test_cli_string_full(self):
        o = parse_slo("kind=latency,threshold_ms=250,target=0.99,"
                      "window_s=300")
        assert o.kind == "latency"
        assert o.threshold_s == pytest.approx(0.25)
        assert o.target == 0.99
        assert o.window_s == 300.0
        assert o.stage == "e2e"
        assert o.name == "latency_e2e"
        assert o.budget == pytest.approx(0.01)

    def test_percent_target_and_stage(self):
        o = parse_slo("kind=latency,stage=execute,threshold_ms=50,"
                      "target=99.9%")
        assert o.target == pytest.approx(0.999)
        assert o.stage == "execute"
        assert o.name == "latency_execute"

    def test_error_rate_from_dict(self):
        o = parse_slo({"kind": "error_rate", "target": 0.999,
                       "bad_outcomes": "error|expired|evicted"})
        assert o.kind == "error_rate"
        assert o.bad_outcomes == ("error", "expired", "evicted")
        assert o.name == "error_rate"

    def test_stage_names_match_request_trace(self):
        """VALID_STAGES must track serve/trace.STAGES: a drift would
        let parse_slo accept a stage the tracer never produces."""
        from proteinbert_tpu.obs.slo import VALID_STAGES
        from proteinbert_tpu.serve.trace import STAGES

        assert set(STAGES) < set(VALID_STAGES)
        assert set(VALID_STAGES) - set(STAGES) == {"e2e", "pad_wasted"}

    def test_unknown_stage_rejected_at_parse(self):
        with pytest.raises(ValueError, match="unknown stage"):
            parse_slo("kind=latency,stage=exeucte,threshold_ms=50")

    def test_rejects_unknown_key_bad_kind_and_double_threshold(self):
        with pytest.raises(ValueError, match="unknown slo spec key"):
            parse_slo("kind=latency,threshold_ms=1,bogus=1")
        with pytest.raises(ValueError, match="kind must be one of"):
            parse_slo("kind=throughput")
        with pytest.raises(ValueError, match="not both"):
            parse_slo("kind=latency,threshold_s=1,threshold_ms=1000")
        with pytest.raises(ValueError, match="needs threshold_s"):
            parse_slo("kind=latency")
        with pytest.raises(ValueError, match="no error budget"):
            SLObjective(name="x", kind="latency", target=1.0,
                        threshold_s=1.0)

    def test_duplicate_names_rejected(self):
        with pytest.raises(ValueError, match="duplicate slo objective"):
            parse_slos(["kind=latency,threshold_ms=1",
                        "kind=latency,threshold_ms=2"])


# ----------------------------------------------------- burn-rate math

class TestBurnRate:
    def _eval(self, spec="kind=latency,threshold_s=0.1,target=0.9,"
                         "window_s=10", **kw):
        clock = FakeClock()
        return SLOEvaluator([spec], clock=clock, **kw), clock

    def test_burn_is_bad_fraction_over_budget(self):
        """target 0.9 → budget 0.1. 1 bad in 10 → bad_fraction 0.1 →
        burn exactly 1.0; 2 bad in 10 → 2.0."""
        ev, clock = self._eval()
        for i in range(9):
            ev.observe("ok", 0.01, now=clock.advance(0.1))
        ev.observe("ok", 0.5, now=clock.advance(0.1))  # 1 violation
        assert ev.burn_rate("latency_e2e", now=clock.t) \
            == pytest.approx(1.0)
        ev.observe("ok", 0.5, now=clock.advance(0.1))  # 2 of 11
        assert ev.burn_rate("latency_e2e", now=clock.t) \
            == pytest.approx((2 / 11) / 0.1)

    def test_window_prunes_old_observations(self):
        ev, clock = self._eval()
        ev.observe("ok", 0.5, now=clock.t)       # violation at t=1000
        assert ev.burn_rate("latency_e2e", now=clock.t) \
            == pytest.approx(10.0)               # 1/1 bad / 0.1 budget
        clock.advance(9.0)
        ev.observe("ok", 0.01, now=clock.t)      # good at t=1009
        assert ev.burn_rate("latency_e2e", now=clock.t) \
            == pytest.approx(5.0)                # 1/2 / 0.1
        clock.advance(1.5)                       # violation now >10s old
        assert ev.burn_rate("latency_e2e", now=clock.t) \
            == pytest.approx(0.0)

    def test_empty_window_burns_zero(self):
        ev, clock = self._eval()
        assert ev.burn_rate("latency_e2e", now=clock.t) == 0.0
        assert not ev._states["latency_e2e"].window

    def test_stage_objective_reads_stages_dict(self):
        ev, clock = self._eval(spec="kind=latency,stage=execute,"
                                    "threshold_s=0.05,target=0.9,"
                                    "window_s=10")
        # e2e is slow but execute is fast: not a violation for the
        # stage-scoped objective…
        ev.observe("ok", 0.5, stages={"queue": 0.46, "execute": 0.04},
                   now=clock.advance(0.1))
        assert ev.burn_rate("latency_execute", now=clock.t) == 0.0
        # …and vice versa.
        ev.observe("ok", 0.5, stages={"queue": 0.01, "execute": 0.49},
                   now=clock.advance(0.1))
        assert ev.burn_rate("latency_execute", now=clock.t) \
            == pytest.approx(5.0)
        # No stage measurement (tracing off / never reached the stage):
        # the observation is SKIPPED, never judged against e2e.
        ev.observe("ok", 9.9, stages=None, now=clock.advance(0.1))
        ev.observe("ok", 9.9, stages={"queue": 9.9},
                   now=clock.advance(0.1))
        assert ev.status(now=clock.t)["latency_execute"]["total"] == 2

    def test_error_rate_objective_and_admission_exclusion(self):
        ev, clock = self._eval(spec="kind=error_rate,target=0.9,"
                                    "window_s=10")
        for outcome in ("ok", "ok", "cache_hit", "error"):
            ev.observe(outcome, 0.01, now=clock.advance(0.1))
        # Latency objectives ignore admission control, error_rate
        # counts what its bad_outcomes say: error in 4 observed.
        assert ev.burn_rate("error_rate", now=clock.t) \
            == pytest.approx((1 / 4) / 0.1)
        # Rejections/evictions are load shedding: they enter the window
        # as good unless configured bad.
        ev.observe("rejected", 0.0, now=clock.advance(0.1))
        assert ev._states["error_rate"].bad == 1

    def test_burn_gauge_surfaces_on_registry(self):
        reg = MetricsRegistry()
        clock = FakeClock()
        ev = SLOEvaluator(["kind=latency,threshold_s=0.1,target=0.9,"
                           "window_s=10"], metrics=reg, clock=clock)
        ev.observe("ok", 0.5, now=clock.t)
        snap = reg.snapshot()
        assert snap["gauges"]['slo_burn_rate{objective="latency_e2e"}'] \
            == pytest.approx(10.0)
        assert 'slo_burn_rate{objective="latency_e2e"}' \
            in reg.prometheus_text(prefix="")
        # Idle decay: once the window empties, a scrape-time refresh
        # (refresh_gauges / status) pulls the gauge back to 0 — it
        # must not freeze at the last observed burn.
        clock.advance(11.0)
        ev.refresh_gauges(now=clock.t)
        snap = reg.snapshot()
        assert snap["gauges"]['slo_burn_rate{objective="latency_e2e"}'] \
            == 0.0

    def test_attribution_accumulates_violators_only(self):
        ev, clock = self._eval()
        ev.observe("ok", 0.01, stages={"queue": 0.005, "execute": 0.005},
                   now=clock.advance(0.1))
        ev.observe("ok", 0.5, stages={"queue": 0.4, "execute": 0.1},
                   now=clock.advance(0.1))
        ev.observe("ok", 0.6, stages={"queue": 0.55, "execute": 0.05},
                   now=clock.advance(0.1))
        st = ev.status(now=clock.t)["latency_e2e"]
        # Only the two violating requests contribute: the good
        # request's 5ms never shows.
        assert st["attribution"]["queue"] == pytest.approx(0.95)
        assert st["attribution"]["execute"] == pytest.approx(0.15)


# ------------------------------------------------- breaches + actions

class TestBreach:
    def test_breach_fires_once_per_cooldown_and_emits(self):
        hits = []

        class Tele:
            spans = None
            emitted = []

            def emit(self, event, **fields):
                self.emitted.append((event, fields))

        clock = FakeClock()
        ev = SLOEvaluator(
            ["kind=latency,threshold_s=0.1,target=0.9,window_s=100"],
            clock=clock, telemetry=Tele(),
            on_breach=lambda name, st: hits.append((name, st)),
            breach_cooldown_s=60.0)
        for _ in range(5):          # burn 10x: breach on first observe
            ev.observe("ok", 0.5, now=clock.advance(1.0))
        assert len(hits) == 1       # cooldown holds the rest back
        name, status = hits[0]
        assert name == "latency_e2e"
        assert status["breached"] and status["burn_rate"] > 1.0
        clock.advance(61.0)
        ev.observe("ok", 0.5, now=clock.t)
        assert len(hits) == 2
        events = [e for e, _ in Tele.emitted]
        assert events.count("slo_breach") == 2
        # The breach event round-trips the schema validator.
        from proteinbert_tpu.obs.events import (
            make_record, validate_record,
        )
        _, fields = Tele.emitted[0]
        validate_record(make_record("slo_breach", seq=0, t=0.0, **fields))

    def test_on_breach_exception_never_escapes(self):
        clock = FakeClock()
        ev = SLOEvaluator(
            ["kind=latency,threshold_s=0.1,target=0.9,window_s=100"],
            clock=clock, on_breach=lambda *a: 1 / 0)
        ev.observe("ok", 0.5, now=clock.t)  # must not raise

    def test_status_shape(self):
        ev = SLOEvaluator(["kind=latency,threshold_ms=100"],
                          clock=FakeClock())
        st = ev.status()["latency_e2e"]
        assert st["kind"] == "latency"
        assert st["total"] == 0 and st["bad"] == 0
        assert st["burn_rate"] == 0.0 and not st["breached"]
        assert isinstance(st["histogram"], list)


# -------------------------------------------------- exemplar histogram

class TestExemplarHistogram:
    def test_buckets_and_exemplars(self):
        h = ExemplarHistogram(buckets=(0.01, 0.1, 1.0))
        h.observe(0.005, "req-a", t=1.0)
        h.observe(0.05, "req-b", t=2.0)
        h.observe(0.06, "req-c", t=3.0)   # replaces req-b's slot
        h.observe(50.0, "req-d", t=4.0)   # overflow bucket
        snap = h.snapshot()
        assert [b["le"] for b in snap] == [0.01, 0.1, 1.0, None]
        assert [b["count"] for b in snap] == [1, 2, 0, 1]
        assert snap[1]["exemplar"]["request_id"] == "req-c"
        assert snap[3]["exemplar"]["request_id"] == "req-d"
        assert snap[2]["exemplar"] is None

    def test_needs_bounds(self):
        with pytest.raises(ValueError):
            ExemplarHistogram(buckets=())


# --------------------------------------------------- profile trigger

class TestProfileTrigger:
    def test_capture_cooldown_and_single_flight(self):
        calls = []
        done = threading.Event()
        trig = ProfileTrigger(
            "/tmp/prof", duration_s=0.01, cooldown_s=300.0,
            clock=FakeClock(),
            start=lambda d: calls.append(("start", d)),
            stop=lambda: (calls.append(("stop",)), done.set()))
        trig("latency_e2e", {"burn_rate": 2.0})
        trig("latency_e2e", {"burn_rate": 3.0})  # in flight: skipped
        assert calls == [("start", "/tmp/prof")]
        assert done.wait(5.0)
        assert calls[-1] == ("stop",)
        assert not trig._active
        trig("latency_e2e", {"burn_rate": 2.0})  # cooldown: skipped
        assert len(trig.captures) == 1
        trig.clock.advance(301.0)
        done.clear()
        trig("latency_e2e", {"burn_rate": 2.0})
        assert len(trig.captures) == 2
        assert done.wait(5.0)

    def test_start_failure_degrades(self):
        def boom(d):
            raise OSError("disk full")

        trig = ProfileTrigger("/tmp/prof", clock=FakeClock(),
                              start=boom, stop=lambda: None)
        trig("latency_e2e", {"burn_rate": 2.0})  # must not raise
        assert not trig._active and not trig.captures

    def test_no_jax_no_capture(self, monkeypatch):
        import sys

        monkeypatch.delitem(sys.modules, "jax", raising=False)
        trig = ProfileTrigger("/tmp/prof", clock=FakeClock())
        trig("latency_e2e", {"burn_rate": 2.0})  # degrades to a no-op
        assert not trig.captures


# --------------------------------------------- fake-clock end-to-end

def test_evaluator_threadsafe_under_concurrent_observe():
    """Smoke: concurrent observers never corrupt the window counters
    (the burn denominator must equal the number of observations)."""
    ev = SLOEvaluator(["kind=latency,threshold_s=10,target=0.9,"
                       "window_s=1e6"], clock=time.monotonic)

    def feed():
        for _ in range(200):
            ev.observe("ok", 0.01)

    threads = [threading.Thread(target=feed) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    st = ev.status()["latency_e2e"]
    assert st["total"] == 800 and st["bad"] == 0
