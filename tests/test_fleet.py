"""Serve fleet tests (serve/fleet.py, ISSUE 11).

Three tiers:

- **router-logic tests** against stub HTTP replicas (canned JSON, no
  jax): routing, retry-with-backoff on dead replicas, the 429/504
  shed-don't-retry contract, retry budget exhaustion, drain/re-admit,
  the shared content-addressed result cache, torn-health handling, and
  the exactly-once seal accounting;
- **the fleet drill** (tools/fleet_drill.run_drill): three REAL
  in-process serve replicas behind a real router under concurrent
  load, one killed mid-request — zero lost accepted requests, router
  metrics show the failover, every router/replica event schema-valid;
- **warm boot** (tests/serve_warm_child.py): two subprocess boots
  against one fresh persistent compilation cache — the second must be
  faster (the `--compile-cache-dir` satellite).
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from types import SimpleNamespace

import pytest

from proteinbert_tpu.obs import Telemetry, read_events
from proteinbert_tpu.serve.fleet import (
    FaultInjector, FleetRouter, make_fleet_http_server,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class StubReplica:
    """Canned-JSON serve replica: scriptable per-path status/payload,
    request counting, torn-health mode, and a hard kill (socket gone)."""

    def __init__(self, name):
        self.name = name
        self.requests = []
        self.responses = {}  # path -> (status, payload dict)
        self.health = {"ok": True, "stats": {}}
        self.torn_health = False
        self.lock = threading.Lock()
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _send(self, status, body: bytes):
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    if stub.torn_health:
                        # A replica dying mid-write: half a JSON object.
                        self._send(200, b'{"ok": tru')
                    else:
                        self._send(200, json.dumps(stub.health).encode())
                else:
                    self._send(404, b"{}")

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                with stub.lock:
                    stub.requests.append((self.path, body))
                status, payload = stub.responses.get(
                    self.path, (200, {"from": stub.name}))
                self._send(status, json.dumps(payload).encode())

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.alive = True

    def request_count(self):
        with self.lock:
            return len(self.requests)

    def kill(self):
        if self.alive:
            self.alive = False
            self.httpd.shutdown()
            self.httpd.server_close()


@pytest.fixture()
def stubs():
    reps = [StubReplica(f"s{i}") for i in range(3)]
    yield reps
    for r in reps:
        r.kill()


def _router(stubs, **kw):
    kw.setdefault("health_interval_s", 0)  # tests drive health_tick()
    kw.setdefault("sleep", lambda s: None)  # no real backoff waits
    kw.setdefault("cache_size", 0)
    return FleetRouter([(r.name, r.url) for r in stubs], **kw).start()


def _body(seq="MKTAYIAK"):
    return json.dumps({"seq": seq}).encode()


class TestRouting:
    def test_ok_routes_and_seals_once(self, stubs):
        r = _router(stubs)
        status, body, headers = r.route("/v1/embed", _body())
        assert status == 200
        assert json.loads(body)["from"] in {"s0", "s1", "s2"}
        assert headers["X-PBT-Fleet-Replica"] == json.loads(body)["from"]
        st = r.stats()
        assert st["accepted"] == st["sealed"] == 1
        assert st["outcomes"] == {"ok": 1}
        r.drain()

    def test_least_inflight_spreads_load(self, stubs):
        r = _router(stubs)
        for i in range(9):
            r.route("/v1/embed", _body(f"SEQ{i}" * 3))
        counts = [s.request_count() for s in stubs]
        assert sum(counts) == 9
        assert all(c >= 1 for c in counts)  # round-robin tiebreak
        r.drain()

    def test_retry_on_dead_replica_then_ok(self, stubs):
        stubs[0].kill()
        stubs[1].kill()
        r = _router(stubs, max_retries=3)
        status, body, _ = r.route("/v1/embed", _body())
        assert status == 200
        assert json.loads(body)["from"] == "s2"
        st = r.stats()
        assert st["outcomes"] == {"retried_ok": 1}
        assert st["retries_spent"] >= 1
        r.drain()

    def test_replica_503_is_retried(self, stubs):
        stubs[0].responses["/v1/embed"] = (503, {"type": "closed"})
        stubs[1].responses["/v1/embed"] = (503, {"type": "closed"})
        r = _router(stubs, max_retries=3)
        status, body, _ = r.route("/v1/embed", _body())
        assert status == 200 and json.loads(body)["from"] == "s2"
        r.drain()

    def test_429_sheds_without_retry(self, stubs):
        for s in stubs:
            s.responses["/v1/embed"] = (429, {"type": "queue_full"})
        r = _router(stubs, max_retries=3)
        status, body, _ = r.route("/v1/embed", _body())
        assert status == 429
        assert json.loads(body)["type"] == "queue_full"
        # Exactly ONE replica was asked — backpressure never amplified.
        assert sum(s.request_count() for s in stubs) == 1
        assert r.stats()["outcomes"] == {"shed": 1}
        assert r.stats()["retries_spent"] == 0
        r.drain()

    def test_504_deadline_sheds_without_retry(self, stubs):
        stubs[0].responses["/v1/embed"] = (504, {"type": "deadline"})
        stubs[1].responses["/v1/embed"] = (504, {"type": "deadline"})
        stubs[2].responses["/v1/embed"] = (504, {"type": "deadline"})
        r = _router(stubs)
        status, _, _ = r.route("/v1/embed", _body())
        assert status == 504
        assert sum(s.request_count() for s in stubs) == 1
        r.drain()

    def test_client_error_passes_through_as_failed(self, stubs):
        for s in stubs:
            s.responses["/v1/predict_task"] = (404,
                                               {"type": "unknown_head"})
        r = _router(stubs)
        status, body, _ = r.route(
            "/v1/predict_task",
            json.dumps({"seq": "MKT", "head_id": "nope"}).encode())
        assert status == 404
        assert r.stats()["outcomes"] == {"failed": 1}
        assert sum(s.request_count() for s in stubs) == 1  # no retry
        r.drain()

    def test_all_dead_returns_typed_502_failed(self, stubs):
        for s in stubs:
            s.kill()
        r = _router(stubs, max_retries=2)
        status, body, _ = r.route("/v1/embed", _body())
        assert status == 502
        assert json.loads(body)["type"] == "replica_unavailable"
        assert r.stats()["outcomes"] == {"failed": 1}
        r.drain()

    def test_retry_budget_caps_retry_storm(self, stubs):
        for s in stubs:
            s.kill()
        r = _router(stubs, max_retries=10, retry_budget_floor=3,
                    retry_budget_ratio=0.0)
        statuses = [r.route("/v1/embed", _body(f"S{i}" * 4))[0]
                    for i in range(4)]
        # Every request seals TYPED (502 unreachable / 503 no-capacity
        # shed once the dead replicas leave the rotation) — and the
        # budget floor of 3 bounds fleet-wide retries no matter how
        # high the per-request cap is.
        assert all(s in (502, 503) for s in statuses), statuses
        st = r.stats()
        assert st["retries_spent"] == 3
        assert st["sealed"] == 4
        assert set(st["outcomes"]) <= {"failed", "shed"}
        r.drain()


class TestHealthAndLifecycle:
    def test_torn_health_kills_then_readmits(self, stubs, tmp_path):
        tele = Telemetry(events_path=str(tmp_path / "ev.jsonl"))
        r = _router(stubs, telemetry=tele, fail_threshold=2,
                    readmit_threshold=2)
        stubs[0].torn_health = True
        for _ in range(2):
            r.health_tick()
        assert r.replica_status()[0]["state"] == "dead"
        stubs[0].torn_health = False
        for _ in range(2):
            r.health_tick()
        assert r.replica_status()[0]["state"] == "up"
        r.drain()
        tele.close()
        recs = read_events(str(tmp_path / "ev.jsonl"), strict=True)
        states = [x["state"] for x in recs
                  if x["event"] == "fleet_replica"
                  and x["replica"] == "s0"]
        assert states == ["dead", "admitted"]

    def test_dead_replica_not_routed(self, stubs):
        r = _router(stubs, fail_threshold=1)
        stubs[0].torn_health = True
        r.health_tick()
        for i in range(6):
            r.route("/v1/embed", _body(f"Q{i}" * 3))
        assert stubs[0].request_count() == 0
        r.drain()

    def test_slo_burn_degrades_and_deprioritizes(self, stubs):
        stubs[0].health = {"ok": True, "stats": {"slo": {
            "latency_e2e": {"burn_rate": 2.5}}}}
        r = _router(stubs, degrade_burn=1.0)
        r.health_tick()
        assert r.replica_status()[0]["state"] == "degraded"
        for i in range(6):
            r.route("/v1/embed", _body(f"W{i}" * 3))
        # Healthy replicas absorb everything while any exist.
        assert stubs[0].request_count() == 0
        # ...but a degraded replica is still the last resort.
        stubs[1].kill()
        stubs[2].kill()
        status, body, _ = r.route("/v1/embed", _body("LASTRESORT"))
        assert status == 200 and json.loads(body)["from"] == "s0"
        r.drain()

    def test_drain_admit_round_trip_no_capacity_shed(self, stubs):
        r = _router(stubs)
        for s in ("s0", "s1", "s2"):
            r.drain_replica(s)
        status, body, _ = r.route("/v1/embed", _body())
        assert status == 503
        assert json.loads(body)["type"] == "no_capacity"
        assert r.stats()["outcomes"] == {"shed": 1}
        r.admit_replica("s1")
        status, body, _ = r.route("/v1/embed", _body("AGAIN"))
        assert status == 200 and json.loads(body)["from"] == "s1"
        with pytest.raises(KeyError):
            r.drain_replica("nope")
        r.drain()

    def test_shared_cache_survives_failover(self, stubs):
        r = _router(stubs, cache_size=16)
        status, body1, _ = r.route("/v1/embed", _body("CACHEDSEQ"))
        assert status == 200
        served_by = json.loads(body1)["from"]
        # Kill EVERY replica: the warm result must still be served.
        for s in stubs:
            s.kill()
        status, body2, headers = r.route("/v1/embed", _body("CACHEDSEQ"))
        assert status == 200
        assert body2 == body1
        assert headers.get("X-PBT-Fleet-Cache") == "hit"
        st = r.stats()
        assert st["outcomes"]["cache_hit"] == 1
        assert st["cache"]["hits"] == 1, served_by
        r.drain()

    def test_cache_key_scopes_kind_head_topk(self, stubs):
        r = _router(stubs, cache_size=16)
        r.route("/v1/embed", _body("SCOPESEQ"))
        # Same seq, different kind/top_k: MISS, not a wrong-kind hit.
        r.route("/v1/predict_go", json.dumps(
            {"seq": "SCOPESEQ", "top_k": 3}).encode())
        r.route("/v1/predict_go", json.dumps(
            {"seq": "SCOPESEQ", "top_k": 5}).encode())
        assert r.stats()["cache"]["hits"] == 0
        assert sum(s.request_count() for s in stubs) == 3
        r.drain()


class TestFleetHTTPFront:
    def test_http_front_routes_and_controls(self, stubs, tmp_path):
        tele = Telemetry(events_path=str(tmp_path / "ev.jsonl"))
        r = _router(stubs, telemetry=tele)
        httpd = make_fleet_http_server(r, "127.0.0.1", 0)
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            req = urllib.request.Request(
                base + "/v1/embed", data=_body(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as resp:
                assert resp.status == 200
                assert resp.headers["X-PBT-Fleet-Request-Id"]
            with urllib.request.urlopen(base + "/healthz",
                                        timeout=10) as resp:
                health = json.loads(resp.read())
            assert health["ok"] and len(health["replicas"]) == 3
            req = urllib.request.Request(
                base + "/fleet/drain",
                data=json.dumps({"replica": "s0"}).encode())
            with urllib.request.urlopen(req, timeout=10) as resp:
                out = json.loads(resp.read())
            assert out["ok"]
            assert [x for x in out["replicas"]
                    if x["name"] == "s0"][0]["state"] == "draining"
            req = urllib.request.Request(
                base + "/fleet/admit",
                data=json.dumps({"replica": "s0"}).encode())
            with urllib.request.urlopen(req, timeout=10) as resp:
                assert json.loads(resp.read())["ok"]
            with urllib.request.urlopen(base + "/fleet/status",
                                        timeout=10) as resp:
                st = json.loads(resp.read())
            assert st["stats"]["accepted"] == st["stats"]["sealed"] == 1
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=10) as resp:
                text = resp.read().decode()
            assert "fleet_requests_total" in text
        finally:
            httpd.shutdown()
            httpd.server_close()
            r.drain()
            tele.close()
        recs = read_events(str(tmp_path / "ev.jsonl"), strict=True)
        events = [x["event"] for x in recs]
        assert "fleet_start" in events and "fleet_end" in events
        assert events.count("fleet_request") == 1
        # Operator drain/admit are on the record as replica states.
        states = [x["state"] for x in recs
                  if x["event"] == "fleet_replica"]
        assert "draining" in states and "admitted" in states


class TestFleetDrill:
    """The acceptance drill: one of three REAL replicas killed
    mid-request under concurrent load — zero lost accepted requests,
    failover visible in router metrics, every event schema-valid.
    Small knobs of the same harness tier-1 runs bigger."""

    def test_kill_one_of_three_zero_lost(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            from fleet_drill import run_drill
        finally:
            sys.path.pop(0)
        summary = run_drill(SimpleNamespace(
            replicas=3, requests=24, clients=4, kill_frac=0.25, seed=3,
            outdir=str(tmp_path)))
        assert summary["ok"], summary["failures"]
        assert summary["router"]["accepted"] == 24
        assert summary["router"]["outcomes"].get("retried_ok", 0) >= 1
        # Router metrics show the failover (retries spent, dead seen).
        assert summary["router"]["retries_spent"] >= 1
        assert "dead" in summary["replica_states_seen"]


class TestWarmBoot:
    """`--compile-cache-dir` satellite: the second boot of an identical
    replica against one persistent compilation cache must be faster —
    two subprocess jax boots, because the in-process jit cache would
    fake the win. The fleet story rides on this: a replacement replica
    boots warm."""

    def test_second_boot_is_faster(self, tmp_path):
        cache = tmp_path / "compile_cache"
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("JAX_COMPILATION_CACHE_DIR", None)

        def boot():
            out = subprocess.run(
                [sys.executable,
                 os.path.join(REPO, "tests", "serve_warm_child.py"),
                 str(cache)],
                env=env, capture_output=True, text=True, timeout=600)
            assert out.returncode == 0, out.stderr[-3000:]
            return json.loads(out.stdout.strip().splitlines()[-1])

        cold = boot()
        cached_files = [f for _, _, fs in os.walk(cache) for f in fs]
        assert cached_files, "first boot populated no cache entries"
        warm = boot()
        assert warm["executables"] == cold["executables"]
        assert warm["warmup_seconds"] < cold["warmup_seconds"], (
            cold, warm)
        # Report the saving the serve_warmup_seconds_total gauge shows.
        print(f"warm boot: {cold['warmup_seconds']:.2f}s -> "
              f"{warm['warmup_seconds']:.2f}s")
