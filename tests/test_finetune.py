"""Fine-tuning layer tests (SURVEY C14 — the reference's fine-tune harness
is commented-out code; this is its completed equivalent)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from proteinbert_tpu.configs import (
    DataConfig, FinetuneConfig, ModelConfig, OptimizerConfig, TaskConfig,
    TrainConfig,
)
from proteinbert_tpu.data.synthetic import make_task_batches
from proteinbert_tpu.models import finetune as ft_model, proteinbert
from proteinbert_tpu.train.finetune import (
    create_finetune_state, finetune, finetune_eval_step, finetune_step,
)

MODEL = ModelConfig(local_dim=32, global_dim=64, key_dim=16, num_heads=4,
                    num_blocks=2, num_annotations=64, dtype="float32")


def _cfg(kind, num_outputs, epochs=2, freeze=False):
    return FinetuneConfig(
        model=MODEL,
        task=TaskConfig(kind=kind, num_outputs=num_outputs, epochs=epochs,
                        freeze_trunk=freeze),
        data=DataConfig(seq_len=64, batch_size=8),
        optimizer=OptimizerConfig(learning_rate=3e-3, warmup_steps=5,
                                  schedule="warmup_cosine", total_steps=200),
        train=TrainConfig(seed=0),
    )


@pytest.mark.parametrize("kind,num_outputs,out_shape", [
    ("token_classification", 8, (4, 64, 8)),
    ("sequence_classification", 5, (4, 5)),
    ("sequence_regression", 1, (4, 1)),
])
def test_head_shapes(key, kind, num_outputs, out_shape):
    task = TaskConfig(kind=kind, num_outputs=num_outputs)
    params = ft_model.init(key, MODEL, task)
    tokens = jax.random.randint(key, (4, 64), 4, 26)
    out = ft_model.apply(params, tokens, MODEL, task)
    assert out.shape == out_shape
    assert out.dtype == jnp.float32


def test_unknown_kind_raises(key):
    with pytest.raises(ValueError, match="unknown task kind"):
        ft_model.init(key, MODEL, TaskConfig(kind="nope"))


def test_init_from_pretrained_trunk(key):
    pre = proteinbert.init(key, MODEL)
    params = ft_model.init(key, MODEL, TaskConfig(), pretrained_trunk=pre)
    # Trunk weights are the pretrained ones, pretraining heads dropped.
    np.testing.assert_array_equal(
        np.asarray(params["trunk"]["embedding"]["embedding"]),
        np.asarray(pre["embedding"]["embedding"]))
    assert "local_head" not in params["trunk"]
    assert "global_head" not in params["trunk"]


@pytest.mark.parametrize("kind,num_outputs", [
    ("token_classification", 4),
    ("sequence_classification", 3),
    ("sequence_regression", 1),
])
def test_finetune_learns(rng, kind, num_outputs):
    cfg = _cfg(kind, num_outputs, epochs=3)
    batches = make_task_batches(64, rng, kind, num_outputs,
                                cfg.data.seq_len, cfg.data.batch_size)
    out = finetune(cfg, lambda epoch: iter(batches),
                   eval_batches=lambda: iter(batches))
    first, last = out["history"][0], out["history"][-1]
    assert last["train_loss"] < first["train_loss"]
    assert np.isfinite(last["train_loss"])
    assert out["best"]["epoch"] >= 0


def test_freeze_trunk(rng, key):
    cfg = _cfg("sequence_classification", 3, epochs=1, freeze=True)
    state = create_finetune_state(key, cfg)
    trunk_before = jax.tree.map(np.asarray, state.params["trunk"])
    head_before = jax.tree.map(np.asarray, state.params["head"])
    batches = make_task_batches(16, rng, "sequence_classification", 3,
                                cfg.data.seq_len, cfg.data.batch_size)
    for b in batches:
        state, _ = finetune_step(state, b, cfg)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(a, np.asarray(b)),
        trunk_before, state.params["trunk"])
    # ... while the head DID move.
    moved = jax.tree.leaves(jax.tree.map(
        lambda a, b: bool(np.any(a != np.asarray(b))),
        head_before, state.params["head"]))
    assert any(moved)


def test_task_tsv_roundtrip(tmp_path):
    from proteinbert_tpu.data.finetune_data import batch_task_data, load_task_tsv
    from proteinbert_tpu.data.vocab import PAD_ID, SOS_ID

    tsv = tmp_path / "t.tsv"
    tsv.write_text("# comment\nACDE\t0123\nKLM\t0,1,2\n")
    tokens, labels = load_task_tsv(str(tsv), "token_classification", 16)
    assert tokens.shape == labels.shape == (2, 16)
    assert tokens[0, 0] == SOS_ID
    # Residue j's label sits at position j+1 (after <sos>).
    np.testing.assert_array_equal(labels[0, 1:5], [0, 1, 2, 3])
    np.testing.assert_array_equal(labels[1, 1:4], [0, 1, 2])
    assert labels[0, 0] == -1 and labels[0, 5] == -1  # sos/eos unlabeled
    assert (labels[:, 8:] == -1).all()                # padding unlabeled
    assert (tokens[:, 8:] == PAD_ID).all()

    batches = batch_task_data(tokens, labels, 2)
    assert len(batches) == 1 and batches[0]["tokens"].shape == (2, 16)

    tsv2 = tmp_path / "r.tsv"
    tsv2.write_text("ACDE\t0.5\nKLM\t-1.25\n")
    _, vals = load_task_tsv(str(tsv2), "sequence_regression", 16)
    np.testing.assert_allclose(vals, [0.5, -1.25])


def test_task_tsv_errors(tmp_path):
    from proteinbert_tpu.data.finetune_data import load_task_tsv

    bad = tmp_path / "b.tsv"
    bad.write_text("ACDE\t012\n")  # 3 labels, 4 residues
    with pytest.raises(ValueError, match="3 labels for 4 residues"):
        load_task_tsv(str(bad), "token_classification", 16)
    bad.write_text("ACDE\n")
    with pytest.raises(ValueError, match="sequence<TAB>label"):
        load_task_tsv(str(bad), "sequence_classification", 16)


def test_eval_step_metrics(rng, key):
    cfg = _cfg("token_classification", 4)
    state = create_finetune_state(key, cfg)
    batch = make_task_batches(8, rng, "token_classification", 4,
                              cfg.data.seq_len, cfg.data.batch_size)[0]
    m = finetune_eval_step(state, batch, cfg)
    assert set(m) == {"loss", "accuracy"}
    assert np.isfinite(float(m["loss"]))
    assert 0.0 <= float(m["accuracy"]) <= 1.0


def test_finetune_convergence_reaches_score_target(rng):
    """VERDICT r1 Weak #6 (fine-tune side): a concrete eval-score floor,
    not just 'loss decreased'. Calibrated: this task/seed reaches eval
    accuracy 0.98 by epoch 4 (0.86 by epoch 1); 0.85 leaves headroom for
    numeric drift while failing silent head/trunk/optimizer regressions
    (an untrained head scores ~1/3 on the 3-class task)."""
    cfg = _cfg("sequence_classification", 3, epochs=4)
    batches = make_task_batches(64, rng, "sequence_classification", 3,
                                cfg.data.seq_len, cfg.data.batch_size)
    out = finetune(cfg, lambda epoch: iter(batches),
                   eval_batches=lambda: iter(batches))
    assert out["best"]["score"] >= 0.85, out["best"]
