"""Property tests for the on-device corruption ops (C6c/C6d semantics)."""

import jax
import jax.numpy as jnp
import numpy as np

from proteinbert_tpu.data.corruption import (
    corrupt_annotations, corrupt_batch, pretrain_weights, randomize_tokens,
)
from proteinbert_tpu.data.transforms import tokenize_batch
from proteinbert_tpu.data.vocab import N_SPECIAL, PAD_ID, VOCAB_SIZE


def _tokens(rng, b=16, l=64):
    from tests.conftest import make_random_proteins

    seqs, _ = make_random_proteins(b, rng, max_len=l - 2)
    return jnp.asarray(tokenize_batch(seqs, l))


def test_randomize_never_touches_specials(key, rng):
    toks = _tokens(rng)
    out = randomize_tokens(key, toks, prob=1.0)
    specials = toks < N_SPECIAL
    assert (np.asarray(out)[np.asarray(specials)] == np.asarray(toks)[np.asarray(specials)]).all()
    # replaced positions get real AA tokens only
    assert (np.asarray(out) >= N_SPECIAL)[~np.asarray(specials)].all()
    assert (np.asarray(out) < VOCAB_SIZE).all()


def test_randomize_rate_close_to_p(key, rng):
    toks = _tokens(rng, b=64, l=128)
    out = randomize_tokens(key, toks, prob=0.05)
    nonspecial = np.asarray(toks >= N_SPECIAL)
    changed = np.asarray(out != toks)[nonspecial]
    # replacement draws can coincide with the original token (21/22 visible rate)
    rate = changed.mean()
    assert 0.02 < rate < 0.08


def test_annotation_hide_all_branch(key):
    ann = jnp.ones((512, 32), jnp.float32)
    out = np.asarray(corrupt_annotations(key, ann, corrupt_prob=0.5,
                                         drop_prob=0.0, add_prob=0.0))
    hidden = (out.sum(axis=1) == 0).mean()
    assert 0.4 < hidden < 0.6  # reference data_processing.py:127-128 p=0.5
    kept = out[out.sum(axis=1) > 0]
    assert (kept == 1).all()


def test_annotation_drop_and_add(key):
    ann = jnp.zeros((64, 1000), jnp.float32).at[:, :500].set(1.0)
    out = np.asarray(corrupt_annotations(key, ann, corrupt_prob=1.0,
                                         drop_prob=0.25, add_prob=0.1))
    drop_rate = 1.0 - out[:, :500].mean()
    add_rate = out[:, 500:].mean()
    assert 0.2 < drop_rate < 0.3
    assert 0.05 < add_rate < 0.15


def test_weights_contract(rng):
    toks = _tokens(rng)
    ann = jnp.zeros((toks.shape[0], 8), jnp.float32).at[0, 3].set(1.0)
    w = pretrain_weights(toks, ann)
    assert (np.asarray(w["local"]) == np.asarray(toks != PAD_ID)).all()
    assert w["global"].shape == ann.shape
    assert np.asarray(w["global"])[0].all() and not np.asarray(w["global"])[1:].any()


def test_corrupt_batch_is_jittable_and_targets_clean(key, rng):
    toks = _tokens(rng)
    ann = jnp.ones((toks.shape[0], 16), jnp.float32)
    fn = jax.jit(corrupt_batch)
    X, Y, W = fn(key, toks, ann)
    assert (np.asarray(Y["local"]) == np.asarray(toks)).all()
    assert (np.asarray(Y["global"]) == np.asarray(ann)).all()
    assert X["local"].shape == toks.shape and X["global"].shape == ann.shape
    assert set(W) == {"local", "global"}
