"""Kill-anywhere offline inference (ISSUE 14): the mapper subsystem.

Three tiers:
- store/cursor unit tests (no jax): canonical serialization, the
  crash-safe cursor protocol — including the satellite's parametrized
  kill-at-every-boundary atomicity test — and `verify_store`'s typed
  corruption/hole/coverage detection;
- engine tests (tiny model): completion + parity with the bucketed
  offline surface, resume byte-identity through torn artifacts, typed
  poison quarantine, NaN shard halt with a flight dump, transient
  retry/budget semantics, manifest pinning;
- events: the map_* schema rows and the diagnose --map summary.

The full chaos drill (real subprocesses, real SIGKILL) lives in
tools/map_drill.py and runs as a tier-1 smoke stage.
"""

import json
import os

import numpy as np
import pytest

from proteinbert_tpu.mapper import (
    BlockFormatError, BlockIntegrityError, CursorError, EmbeddingStore,
    MapFaults, ShardCursor, StoreConfigError, block_digest,
    commit_block, deserialize_block, next_offset, resume_shard,
    serialize_block, shard_ranges, store_digests, verify_store,
)

SEQ_LEN = 48
BUCKETS = (16, 32, 48)


# ------------------------------------------------- canonical block bytes

class TestBlockSerialization:
    def _arrays(self):
        return {
            "ids": np.array([b"a", b"bb"], dtype="S2"),
            "lengths": np.array([3, 4], np.int32),
            "global": np.arange(6, dtype=np.float32).reshape(2, 3),
            "local_mean": np.ones((2, 2), np.float32),
        }

    def test_roundtrip_and_byte_determinism(self):
        a = self._arrays()
        p1 = serialize_block({"shard": 0, "block": 1}, a)
        p2 = serialize_block({"shard": 0, "block": 1},
                             {k: v.copy() for k, v in a.items()})
        assert p1 == p2  # no timestamps, no dict-order dependence
        meta, arrays = deserialize_block(p1)
        assert meta == {"shard": 0, "block": 1}
        for k in a:
            assert np.array_equal(arrays[k], a[k])

    def test_meta_changes_digest(self):
        a = self._arrays()
        d1 = block_digest(serialize_block({"block": 0}, a))
        d2 = block_digest(serialize_block({"block": 1}, a))
        assert d1 != d2

    def test_torn_payload_typed(self):
        p = serialize_block({}, self._arrays())
        with pytest.raises(BlockFormatError, match="torn"):
            deserialize_block(p[:-3])
        with pytest.raises(BlockFormatError, match="magic"):
            deserialize_block(b"NOPE" + p)
        with pytest.raises(BlockFormatError, match="trailing"):
            deserialize_block(p + b"x")


def test_shard_ranges_cover_exactly_once():
    for n, k in ((44, 2), (10, 3), (3, 5), (0, 2), (7, 1)):
        ranges = shard_ranges(n, k)
        assert len(ranges) == k
        covered = [i for lo, hi in ranges for i in range(lo, hi)]
        assert covered == list(range(n))
    with pytest.raises(ValueError):
        shard_ranges(4, 0)


# --------------------------------------------------------------- cursors

def _mk_store(tmp_path, n=24, num_shards=1, block_size=4):
    store = EmbeddingStore(str(tmp_path / "store"))
    store.ensure_manifest({
        "kind": "embedding_store", "corpus_n": n, "corpus_digest": "cd",
        "model_fingerprint": "mf", "num_shards": num_shards,
        "block_size": block_size, "rows_per_batch": 2,
        "max_segments": 4, "seq_len": SEQ_LEN,
        "buckets": list(BUCKETS)})
    return store


def _payload(shard, block, start, end, n_rows):
    ids = np.array([f"s{i}".encode() for i in range(start, start + n_rows)],
                   dtype="S8")
    arrays = {"ids": ids,
              "lengths": np.full(n_rows, 7, np.int32),
              "global": np.full((n_rows, 3), float(block), np.float32),
              "local_mean": np.zeros((n_rows, 2), np.float32)}
    return serialize_block({"shard": shard, "block": block,
                            "start": start, "end": end}, arrays)


def _commit(store, cursor, state, shard, block, start, end,
            quarantined=(), crash=None):
    n = end - start - len(quarantined)  # embedded rows exclude poison
    payload = _payload(shard, block, start, end, n)
    entry = {"block": block, "digest": block_digest(payload),
             "start": start, "end": end, "n": n,
             "quarantined": [list(q) for q in quarantined]}
    return commit_block(store, cursor, state, payload, entry,
                        crash=crash)


class TestCursor:
    def test_fresh_then_generations(self, tmp_path):
        store = _mk_store(tmp_path)
        cur = ShardCursor(store.directory, 0)
        state, source = cur.load()
        assert source == "fresh" and state["blocks"] == []
        state = cur.write_state(state)
        state = _commit(store, cur, state, 0, 0, 0, 4)
        reloaded, source = ShardCursor(store.directory, 0).load()
        assert source == "main"
        assert [b["block"] for b in reloaded["blocks"]] == [0]
        assert next_offset(reloaded) == 4

    def test_torn_main_falls_back_one_generation(self, tmp_path):
        store = _mk_store(tmp_path)
        cur = ShardCursor(store.directory, 0)
        state = cur.write_state(cur.load()[0])
        state = _commit(store, cur, state, 0, 0, 0, 4)
        state = _commit(store, cur, state, 0, 1, 4, 8)
        with open(cur.path, "r+b") as f:  # tear mid-file
            f.truncate(40)
        reloaded, source = ShardCursor(store.directory, 0).load()
        assert source == "prev"
        # Exactly ONE generation lost: block 1 re-works, block 0 stays.
        assert [b["block"] for b in reloaded["blocks"]] == [0]

    def test_double_fault_is_typed_not_silent_restart(self, tmp_path):
        store = _mk_store(tmp_path)
        cur = ShardCursor(store.directory, 0)
        state = cur.write_state(cur.load()[0])
        _commit(store, cur, state, 0, 0, 0, 4)
        for path in (cur.path, cur.prev_path):
            with open(path, "w") as f:
                f.write("{garbage")
        with pytest.raises(CursorError, match="both cursor generations"):
            ShardCursor(store.directory, 0).load()

    def test_checksum_rejects_bitrot(self, tmp_path):
        store = _mk_store(tmp_path)
        cur = ShardCursor(store.directory, 0)
        state = cur.write_state(cur.load()[0])
        _commit(store, cur, state, 0, 0, 0, 4)
        with open(cur.path, "rb") as f:
            raw = bytearray(f.read())
        i = raw.index(b'"end": 4') + 8 - 1
        raw[i:i + 1] = b"5"  # parseable JSON, wrong content
        with open(cur.path, "wb") as f:
            f.write(bytes(raw))
        _, source = ShardCursor(store.directory, 0).load()
        assert source == "prev"  # checksum caught it

    def test_quarantine_sidecar_dedupes_and_tolerates_torn_tail(
            self, tmp_path):
        store = _mk_store(tmp_path)
        cur = ShardCursor(store.directory, 0)
        cur.append_quarantine(0, [("p1", "empty")])
        cur.append_quarantine(0, [("p1", "empty")])  # re-worked block
        cur.append_quarantine(1, [("p2", "invalid_char")])
        with open(cur.quarantine_path, "a") as f:
            f.write('{"torn')  # crash mid-append
        recs = cur.read_quarantine()
        assert {r["id"]: r["reason"] for r in recs} == {
            "p1": "empty", "p2": "invalid_char"}


# ------------------------------- the satellite: kill at EVERY boundary

CRASH_POINTS = ("before_object", "after_object", "cursor_serialized",
                "cursor_prev_updated", "cursor_tmp_written",
                "cursor_renamed")


class SimulatedKill(BaseException):
    """Stands in for SIGKILL inside one process: nothing below the
    raise runs, exactly like the real signal (the drill does the real
    one through subprocesses)."""


@pytest.mark.parametrize("point", CRASH_POINTS)
@pytest.mark.parametrize("victim_block", [0, 1, 2])
def test_kill_between_flush_and_rename_never_loses_or_duplicates(
        tmp_path, point, victim_block):
    """Kill the writer at every filesystem boundary of the commit
    protocol, for every block position, then resume — the store must
    cover every sequence exactly once, with at most one block of
    re-work (the ISSUE 14 cursor-atomicity satellite)."""
    n, block = 12, 4
    store = _mk_store(tmp_path, n=n, block_size=block)
    cur = ShardCursor(store.directory, 0)
    state = cur.write_state(cur.load()[0])

    def crash_at(reached):
        if reached == point:
            raise SimulatedKill(point)

    committed = 0
    with pytest.raises(SimulatedKill):
        for b in range(n // block):
            crash = crash_at if b == victim_block else None
            state = _commit(store, cur, state, 0, b, b * block,
                            (b + 1) * block, crash=crash)
            committed += 1
        raise SimulatedKill("no-crash control never happens")
    assert committed == victim_block  # died inside the victim's commit

    # ---- resume from disk exactly as the engine does
    state, info = resume_shard(store, 0)
    start = next_offset(state)
    # The victim block is the ONLY re-work, and only when its cursor
    # advance had not landed (the commit point is cursor_renamed).
    expected_next = (victim_block + 1 if point == "cursor_renamed"
                     else victim_block) * block
    assert start == expected_next
    assert info["tail_dropped"] is None  # objects were never torn
    for b in range(start // block, n // block):
        state = _commit(store, cur, state, 0, b, b * block,
                        (b + 1) * block)

    # ---- audit: contiguous coverage, every sequence exactly once
    final, source = ShardCursor(store.directory, 0).load()
    assert source == "main"
    assert [b["block"] for b in final["blocks"]] == list(range(n // block))
    seen = []
    for entry in final["blocks"]:
        _, arrays = store.read_block(entry["digest"])
        seen.extend(i.decode() for i in arrays["ids"])
    assert seen == [f"s{i}" for i in range(n)]  # none lost, none doubled


def test_resume_drops_torn_tail_object_only(tmp_path):
    store = _mk_store(tmp_path)
    cur = ShardCursor(store.directory, 0)
    state = cur.write_state(cur.load()[0])
    state = _commit(store, cur, state, 0, 0, 0, 4)
    state = _commit(store, cur, state, 0, 1, 4, 8)
    tail = state["blocks"][-1]["digest"]
    with open(store.object_path(tail), "r+b") as f:
        f.truncate(10)
    state, info = resume_shard(store, 0)
    assert info["tail_dropped"]["block"] == 1
    assert [b["block"] for b in state["blocks"]] == [0]
    assert next_offset(state) == 4


# ----------------------------------------------------------- verification

class TestVerify:
    def _full_store(self, tmp_path):
        store = _mk_store(tmp_path, n=8, num_shards=2, block_size=4)
        digests = {}
        for shard in range(2):
            cur = ShardCursor(store.directory, shard)
            state = cur.write_state(cur.load()[0])
            state = _commit(store, cur, state, shard, 0, 0, 4,
                            quarantined=[("px", "empty")] if shard == 0
                            else ())
            digests[shard] = state["blocks"][0]["digest"]
            cur.write_state(dict(state, done=True))
        return store, digests

    def test_clean_store_ok_and_complete(self, tmp_path):
        store, _ = self._full_store(tmp_path)
        rep = verify_store(store.directory)
        assert rep["ok"] and rep["complete"]
        assert rep["blocks_checked"] == 2
        assert rep["quarantined"] == 1
        assert store_digests(store.directory).keys() == {(0, 0), (1, 0)}

    def test_flipped_byte_is_typed_digest_mismatch(self, tmp_path):
        store, digests = self._full_store(tmp_path)
        path = store.object_path(digests[1])
        with open(path, "r+b") as f:
            data = bytearray(f.read())
            data[-1] ^= 0xFF
            f.seek(0)
            f.write(bytes(data))
        rep = verify_store(store.directory)
        assert not rep["ok"]
        assert rep["corrupt"] == [{"shard": 1, "block": 0,
                                   "digest": digests[1],
                                   "reason": "digest_mismatch"}]
        with pytest.raises(BlockIntegrityError) as ei:
            store.read_block(digests[1])
        assert ei.value.reason == "digest_mismatch"

    def test_deleted_object_is_a_hole(self, tmp_path):
        store, digests = self._full_store(tmp_path)
        os.remove(store.object_path(digests[0]))
        rep = verify_store(store.directory)
        assert not rep["ok"]
        assert rep["holes"][0]["reason"] == "missing"
        assert rep["holes"][0]["digest"] == digests[0]

    def test_coverage_gap_detected(self, tmp_path):
        store = _mk_store(tmp_path, n=8, num_shards=1, block_size=4)
        cur = ShardCursor(store.directory, 0)
        state = cur.write_state(cur.load()[0])
        # Block 0 claims [0, 4) then block 1 claims [5, 8): a gap.
        state = _commit(store, cur, state, 0, 0, 0, 4)
        state = _commit(store, cur, state, 0, 1, 5, 8)
        rep = verify_store(store.directory)
        assert not rep["ok"]
        assert any("gap or overlap" in e for e in rep["coverage_errors"])

    def test_manifest_mismatch_is_typed(self, tmp_path):
        store = _mk_store(tmp_path)
        with pytest.raises(StoreConfigError, match="block_size"):
            store.ensure_manifest({
                "kind": "embedding_store", "corpus_n": 24,
                "corpus_digest": "cd", "model_fingerprint": "mf",
                "num_shards": 1, "block_size": 8, "rows_per_batch": 2,
                "max_segments": 4, "seq_len": SEQ_LEN,
                "buckets": list(BUCKETS)})

    def test_verify_without_manifest_is_typed(self, tmp_path):
        with pytest.raises(StoreConfigError, match="manifest"):
            verify_store(str(tmp_path / "nothing"))


# ----------------------------------------------------------- fault specs

class TestMapFaults:
    def test_parse_roundtrip(self):
        f = MapFaults.parse("crash=0:1:after_object;fail=1:2:2;"
                            "nan=0:0;latency=0.5")
        assert f.armed() and f.latency_s == 0.5
        assert f.poison_output(0, 0) and not f.poison_output(1, 0)
        assert f.take_failure(1, 2) and f.take_failure(1, 2)
        assert not f.take_failure(1, 2)  # consumed
        assert f.crash_hook(0, 1) is not None
        assert f.crash_hook(0, 0) is None

    def test_empty_spec_inert(self):
        f = MapFaults.parse("")
        assert not f.armed()

    @pytest.mark.parametrize("bad", [
        "crash=0:1", "crash=0:1:nowhere", "fail=1", "nan=1",
        "bogus=1:2", "crash0:1:after_object",
    ])
    def test_malformed_specs_raise(self, bad):
        with pytest.raises(ValueError):
            MapFaults.parse(bad)


# -------------------------------------------------------------- events

class TestMapEvents:
    def test_examples_validate(self):
        from proteinbert_tpu.obs.events import make_example, validate_record

        for ev in ("map_start", "map_shard", "map_block", "map_end"):
            validate_record(json.loads(json.dumps(make_example(ev))))

    def test_typed_rejections(self):
        from proteinbert_tpu.obs.events import make_record, validate_record

        for bad in (
            dict(event="map_shard", shard=0, state="limping"),
            dict(event="map_block", shard=0, block=0, digest="zz", n=1),
            dict(event="map_end", outcome="gone", stats={}),
            dict(event="note", source="checkpoint",
                 kind="restore_fallback", bad_step=1, landed_step=-1),
        ):
            event = bad.pop("event")
            rec = make_record(event, seq=0, t=0.0, **bad)
            with pytest.raises(ValueError):
                validate_record(rec)


def test_diagnose_map_counts_rework_across_incarnations():
    from proteinbert_tpu.obs.diagnose import render_map, summarize_map
    from proteinbert_tpu.obs.events import make_record

    dg = "0" * 64
    recs = [
        make_record("map_start", 0, 0.0, config={"corpus_n": 8,
                                                 "num_shards": 1}, pid=1),
        make_record("map_shard", 1, 0.1, shard=0, state="start",
                    next=0, size=8),
        make_record("map_block", 2, 0.2, shard=0, block=0, digest=dg,
                    n=4, retries=1, quarantined=1, seqs_per_s=10.0),
        # killed; second incarnation re-works block 0, finishes
        make_record("map_start", 0, 1.0, config={"corpus_n": 8,
                                                 "num_shards": 1}, pid=2),
        make_record("map_shard", 1, 1.1, shard=0, state="resume",
                    next=0, size=8),
        make_record("map_block", 2, 1.2, shard=0, block=0, digest=dg,
                    n=4, seqs_per_s=12.0),
        make_record("map_block", 3, 1.3, shard=0, block=1, digest=dg,
                    n=4, seqs_per_s=11.0),
        make_record("map_shard", 4, 1.4, shard=0, state="done",
                    blocks=2),
        make_record("map_end", 5, 1.5, outcome="completed",
                    stats={"blocks": 2}),
    ]
    s = summarize_map(recs)
    assert s["incarnations"] == 2
    assert s["outcome"] == "completed"
    assert s["rework_blocks"] == 1
    assert s["blocks"] == 3 and s["seqs"] == 12
    assert s["retries"] == 1 and s["quarantined"] == 1
    assert s["per_shard"]["0"]["last_state"] == "done"
    text = render_map(s)
    assert "re-worked" in text and "shard 0" in text


# ------------------------------------------------------------- engine

jax = pytest.importorskip("jax")

from proteinbert_tpu.configs import (  # noqa: E402
    DataConfig, ModelConfig, OptimizerConfig, PretrainConfig, TrainConfig,
)
from proteinbert_tpu.mapper.engine import run_map  # noqa: E402
from proteinbert_tpu.train import create_train_state  # noqa: E402

ALPHABET = "ACDEFGHIKLMNPQRSTVWY"


def _cfg():
    return PretrainConfig(
        model=ModelConfig(local_dim=16, global_dim=32, key_dim=8,
                          num_heads=2, num_blocks=2, num_annotations=32,
                          dtype="float32"),
        data=DataConfig(seq_len=SEQ_LEN, batch_size=4),
        optimizer=OptimizerConfig(warmup_steps=5),
        train=TrainConfig(seed=0, max_steps=1),
    )


@pytest.fixture(scope="module")
def trunk():
    cfg = _cfg()
    state = create_train_state(jax.random.PRNGKey(cfg.train.seed), cfg)
    return state.params, cfg


@pytest.fixture()
def corpus():
    rng = np.random.default_rng(11)
    seqs = ["".join(rng.choice(list(ALPHABET), size=int(n)))
            for n in rng.integers(5, 30, size=18)]
    return [f"p{i}" for i in range(len(seqs))], seqs


MAP_KW = dict(num_shards=2, block_size=4, rows_per_batch=2,
              max_segments=4, buckets=BUCKETS,
              stop_flag=lambda: False)


class TestEngine:
    def test_completes_verifies_and_matches_bucketed_offline(
            self, trunk, corpus, tmp_path):
        params, cfg = trunk
        ids, seqs = corpus
        out = run_map(params, cfg, ids, seqs, str(tmp_path / "store"),
                      **MAP_KW)
        assert out["outcome"] == "completed"
        assert out["seqs"] == len(seqs) and out["quarantined"] == 0
        rep = verify_store(str(tmp_path / "store"))
        assert rep["ok"] and rep["complete"]
        assert rep["embedded"] == len(seqs)

        # Numbers match the bucketed OFFLINE surface within the
        # documented jitted tolerance — the store is not a third
        # numerics regime (the spans are serving-rule quantized).
        from proteinbert_tpu import inference
        from proteinbert_tpu.mapper import iter_embeddings

        ref = inference.embed(params, cfg, seqs, batch_size=8,
                              bucketed=True, buckets=BUCKETS)
        got = dict(iter_embeddings(str(tmp_path / "store")))
        for k, rid in enumerate(ids):
            np.testing.assert_allclose(got[rid]["global"],
                                       ref["global"][k], atol=1e-5)
            np.testing.assert_allclose(got[rid]["local_mean"],
                                       ref["local_mean"][k], atol=1e-5)

    def test_resume_after_tearing_is_byte_identical(
            self, trunk, corpus, tmp_path):
        params, cfg = trunk
        ids, seqs = corpus
        control = str(tmp_path / "control")
        chaos = str(tmp_path / "chaos")
        run_map(params, cfg, ids, seqs, control, **MAP_KW)

        kw = dict(MAP_KW, max_blocks=3)
        out = run_map(params, cfg, ids, seqs, chaos, **kw)
        assert out["outcome"] == "preempted"
        # Hostile storage while "down": tear shard 0's main cursor AND
        # shard 1's tail block object.
        cur0 = ShardCursor(chaos, 0)
        with open(cur0.path, "r+b") as f:
            f.truncate(30)
        s1, _ = ShardCursor(chaos, 1).load()
        if s1["blocks"]:
            tail = s1["blocks"][-1]["digest"]
            with open(EmbeddingStore(chaos).object_path(tail),
                      "r+b") as f:
                f.truncate(12)
        out = run_map(params, cfg, ids, seqs, chaos, **MAP_KW)
        assert out["outcome"] == "completed"
        # The resume's own stats own BOTH torn-artifact re-works: the
        # prev-generation cursor fallback (shard 0) and the dropped
        # tail object (shard 1) — what diagnose counts from the stream.
        assert out["rework"] == 2
        assert store_digests(chaos) == store_digests(control)
        rep = verify_store(chaos)
        assert rep["ok"] and rep["complete"]

    def test_events_stream_validates_and_diagnoses(
            self, trunk, corpus, tmp_path):
        from proteinbert_tpu.obs import Telemetry, read_events
        from proteinbert_tpu.obs.diagnose import summarize_map

        params, cfg = trunk
        ids, seqs = corpus
        ev = str(tmp_path / "events.jsonl")
        tele = Telemetry(events_path=ev)
        run_map(params, cfg, ids, seqs, str(tmp_path / "store"),
                telemetry=tele, **MAP_KW)
        tele.close()
        recs = read_events(ev, strict=True)  # raises on schema drift
        kinds = {r["event"] for r in recs}
        assert {"map_start", "map_shard", "map_block",
                "map_end"} <= kinds
        s = summarize_map(recs)
        assert s["outcome"] == "completed" and s["rework_blocks"] == 0
        # Metrics surfaced (progress/throughput/counters).
        snap = tele.metrics.snapshot()
        names = set(snap["counters"]) | set(snap["gauges"])
        assert any(n.startswith("map_blocks_total") for n in names)
        assert any(n.startswith("map_seqs_per_s") for n in names)

    def test_poison_quarantined_typed_not_fatal(self, trunk, tmp_path):
        params, cfg = trunk
        seqs = ["ACDEFGH", "", "AC DEF", 12345, "MKLVWY"]
        ids = [f"p{i}" for i in range(len(seqs))]
        out = run_map(params, cfg, ids, seqs, str(tmp_path / "store"),
                      num_shards=1, block_size=8, rows_per_batch=2,
                      max_segments=4, buckets=BUCKETS,
                      stop_flag=lambda: False)
        assert out["outcome"] == "completed"
        assert out["quarantined"] == 3 and out["seqs"] == 2
        recs = ShardCursor(str(tmp_path / "store"), 0).read_quarantine()
        assert {r["id"]: r["reason"] for r in recs} == {
            "p1": "empty", "p2": "invalid_char", "p3": "non_string"}
        rep = verify_store(str(tmp_path / "store"))
        assert rep["ok"] and rep["complete"] and rep["quarantined"] == 3

    def test_non_ascii_ids_round_trip(self, trunk, tmp_path):
        # Real-world FASTA headers carry non-ASCII; an ID must never be
        # able to kill a run (np.array(dtype="S") on str would raise).
        from proteinbert_tpu.mapper import iter_embeddings

        params, cfg = trunk
        ids = ["prötein/1", "βeta_2"]
        out = run_map(params, cfg, ids, ["ACDEFGH", "MKLVWY"],
                      str(tmp_path / "store"), num_shards=1,
                      block_size=4, rows_per_batch=2, max_segments=4,
                      buckets=BUCKETS, stop_flag=lambda: False)
        assert out["outcome"] == "completed" and out["seqs"] == 2
        got = dict(iter_embeddings(str(tmp_path / "store")))
        assert set(got) == set(ids)

    def test_overlong_sequence_truncates_not_poison(self, trunk,
                                                    tmp_path):
        params, cfg = trunk
        seqs = ["A" * (SEQ_LEN * 3), "MKLVWY"]
        out = run_map(params, cfg, ["long", "ok"], seqs,
                      str(tmp_path / "store"), num_shards=1,
                      block_size=4, rows_per_batch=2, max_segments=4,
                      buckets=BUCKETS, stop_flag=lambda: False)
        assert out["outcome"] == "completed"
        assert out["quarantined"] == 0 and out["seqs"] == 2

    def test_nan_halts_shard_with_flight_dump(self, trunk, corpus,
                                              tmp_path):
        from proteinbert_tpu.obs import Telemetry, read_events

        params, cfg = trunk
        ids, seqs = corpus
        ev = str(tmp_path / "events.jsonl")
        tele = Telemetry(events_path=ev)
        out = run_map(params, cfg, ids, seqs, str(tmp_path / "store"),
                      telemetry=tele,
                      faults=MapFaults.parse("nan=0:0"),
                      **MAP_KW)
        tele.close()
        assert out["outcome"] == "halted"
        assert out["halted_shards"] == [0]
        # The OTHER shard still completed — containment, not collapse.
        assert [s for s in out["shards"] if s["shard"] == 1][0]["done"]
        halts = [r for r in read_events(ev, strict=True)
                 if r["event"] == "map_shard" and r["state"] == "halted"]
        assert halts and halts[0]["reason"] == "non_finite_embeddings"
        assert halts[0]["flight"] and os.path.exists(halts[0]["flight"])

    def test_transient_failures_retry_then_succeed(self, trunk, corpus,
                                                   tmp_path):
        params, cfg = trunk
        ids, seqs = corpus
        control = str(tmp_path / "control")
        run_map(params, cfg, ids, seqs, control, **MAP_KW)
        out = run_map(params, cfg, ids, seqs, str(tmp_path / "store"),
                      faults=MapFaults.parse("fail=0:1:2"),
                      backoff_base_s=0.001, **MAP_KW)
        assert out["outcome"] == "completed" and out["retries"] == 2
        assert store_digests(str(tmp_path / "store")) \
            == store_digests(control)

    def test_retry_exhaustion_fails_shard_typed(self, trunk, corpus,
                                                tmp_path):
        params, cfg = trunk
        ids, seqs = corpus
        out = run_map(params, cfg, ids, seqs, str(tmp_path / "store"),
                      faults=MapFaults.parse("fail=0:0:99"),
                      retry_limit=2, backoff_base_s=0.001, **MAP_KW)
        assert out["outcome"] == "error"
        assert out["failed_shards"] == [0]
        # The healthy shard still finished.
        assert [s for s in out["shards"] if s["shard"] == 1][0]["done"]

    def test_manifest_pins_geometry(self, trunk, corpus, tmp_path):
        params, cfg = trunk
        ids, seqs = corpus
        store = str(tmp_path / "store")
        run_map(params, cfg, ids, seqs, store, **MAP_KW)
        with pytest.raises(StoreConfigError, match="block_size"):
            run_map(params, cfg, ids, seqs, store,
                    **dict(MAP_KW, block_size=5))
        with pytest.raises(StoreConfigError, match="corpus"):
            run_map(params, cfg, ids, list(reversed(seqs)), store,
                    **MAP_KW)

    def test_stop_flag_preempts_resumably(self, trunk, corpus, tmp_path):
        params, cfg = trunk
        ids, seqs = corpus
        calls = [0]

        def stop():
            calls[0] += 1
            return calls[0] > 2  # allow two blocks, then "SIGTERM"

        out = run_map(params, cfg, ids, seqs, str(tmp_path / "store"),
                      **dict(MAP_KW, stop_flag=stop))
        assert out["outcome"] == "preempted" and out["blocks"] == 2
        out = run_map(params, cfg, ids, seqs, str(tmp_path / "store"),
                      **MAP_KW)
        assert out["outcome"] == "completed"
        assert verify_store(str(tmp_path / "store"))["complete"]


# ------------------------- pipelined block window (ISSUE 19 tentpole)


class TestPipelinedMapper:
    """The one-block-in-flight window: block N+1's device compute
    overlaps block N's host fetch + commit.  The contract is that the
    window moves WHEN work happens, never WHAT gets committed — so the
    gates here are byte-identity against the serial path and the typed
    crash taxonomy, not wall-clock."""

    def test_on_vs_off_byte_identical_with_overlap(self, trunk, corpus,
                                                   tmp_path):
        params, cfg = trunk
        ids, seqs = corpus
        on, off = str(tmp_path / "on"), str(tmp_path / "off")
        out_on = run_map(params, cfg, ids, seqs, on, **MAP_KW)
        out_off = run_map(params, cfg, ids, seqs, off, pipeline=False,
                          **MAP_KW)
        assert out_on["outcome"] == "completed"
        assert out_off["outcome"] == "completed"
        assert out_on["pipeline"] is True
        assert out_off["pipeline"] is False
        # Every shard here has >= 2 blocks, so the window accrues
        # overlapped commit seconds; the serial path never does.
        assert out_on["overlap_ratio"] > 0.0
        assert out_off["overlap_ratio"] == 0.0
        assert store_digests(on) == store_digests(off)

    def test_block_fetched_is_a_typed_crash_point(self):
        from proteinbert_tpu.mapper.faults import (
            CRASH_POINTS as ENGINE_CRASH_POINTS,
        )

        # The new window opens a new crash window: device result
        # fetched, nothing committed.  It leads the taxonomy (it
        # precedes the whole commit protocol).
        assert ENGINE_CRASH_POINTS[0] == "block_fetched"
        faults = MapFaults.parse("crash=0:1:block_fetched")
        hook = faults.crash_hook(0, 1)
        assert hook is not None
        assert faults.crash_hook(1, 1) is None
        with pytest.raises(ValueError, match="crash"):
            MapFaults.parse("crash=0:1:mid_flight")

    def test_preempt_with_block_in_flight_resumes_byte_identical(
            self, trunk, corpus, tmp_path):
        params, cfg = trunk
        ids, seqs = corpus
        control = str(tmp_path / "control")
        run_map(params, cfg, ids, seqs, control, **MAP_KW)
        store = str(tmp_path / "store")
        out = run_map(params, cfg, ids, seqs, store,
                      **dict(MAP_KW, max_blocks=1))
        # SIGTERM contract with the window open: the in-flight block is
        # committed before the shard parks, so the preempt is clean.
        assert out["outcome"] == "preempted"
        out = run_map(params, cfg, ids, seqs, store, **MAP_KW)
        assert out["outcome"] == "completed"
        assert verify_store(store)["complete"]
        assert store_digests(store) == store_digests(control)
