"""16-virtual-device tier (VERDICT r4 weak #4 / round-5 item 5).

The in-process suite is pinned to 8 virtual devices at backend init, so
every fsdp/model axis it can build caps at extent 2 — and extent-2
meshes cannot catch off-by-N bugs in gather/reduce-scatter sharding
rules. These tests spawn `tests/multidevice16_child.py` (and the
driver's own `dryrun_multichip`) in fresh processes with 16 virtual CPU
devices and assert numerical parity at fsdp=4, model=4, and
data=2 x fsdp=2 x seq=4 with bucketed lockstep batches.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _child_env():
    """The child forces 16 devices via the config API; scrub the
    conftest's 8-device XLA flag so the two mechanisms can't fight."""
    from proteinbert_tpu.utils.compat import scrub_device_count_flag

    env = dict(os.environ)
    env["XLA_FLAGS"] = scrub_device_count_flag(env.get("XLA_FLAGS", ""))
    return env


def _run(args, timeout=600):
    out = subprocess.run(
        [sys.executable, *args], env=_child_env(), cwd=REPO,
        capture_output=True, text=True, timeout=timeout)
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-3000:])
    return out.stdout


@pytest.mark.parametrize("scenario", ["fsdp4", "model4", "sp4-bucketed"])
def test_sixteen_device_parity(scenario):
    stdout = _run([os.path.join(REPO, "tests", "multidevice16_child.py"),
                   scenario])
    rec = json.loads(stdout.strip().splitlines()[-1])
    assert rec["ok"] and rec["scenario"] == scenario
    if scenario == "sp4-bucketed":
        assert {r["L"] for r in rec["buckets"]} == {32, 128}
    else:
        axis = "fsdp" if scenario == "fsdp4" else "model"
        assert rec["mesh"][axis] == 4
        assert rec["max_param_err"] < 2e-5


def test_fsdp4_compile_has_no_involuntary_remat_warning():
    """This tier's first catch: with the embedding table FSDP-sharded,
    the token-lookup gather's feature-sharded output forced the SPMD
    partitioner's replicate-and-repartition fallback at fsdp=4 (fine at
    fsdp=2 — exactly the extent>2 class this tier exists for). Fixed by
    replicating the few-KB table (parallel/sharding.py); this grep keeps
    it fixed. The marker text's positive control (GSPMD arm) lives in
    tests/test_parallel.py::test_fsdp_compile_has_no_involuntary_remat_warning."""
    import jax

    if not jax.config.jax_use_shardy_partitioner:
        import pytest

        pytest.skip("default partitioner is GSPMD (jax 0.4.x) — the "
                    "warning-free property under test belongs to shardy")
    code = """
import jax
from proteinbert_tpu.utils.compat import request_cpu_devices
request_cpu_devices(16)
jax.config.update("jax_enable_compilation_cache", False)
import numpy as np
from proteinbert_tpu.configs import (DataConfig, MeshConfig, ModelConfig,
    OptimizerConfig, PretrainConfig, TrainConfig)
from proteinbert_tpu.parallel import batch_sharding, make_mesh
from proteinbert_tpu.parallel.sharding import state_sharding
from proteinbert_tpu.train import create_train_state
import proteinbert_tpu.train.train_state as TS

mesh_cfg = MeshConfig(data=2, fsdp=4, model=2, seq=1)
cfg = PretrainConfig(
    model=ModelConfig(local_dim=32, global_dim=64, key_dim=16, num_heads=4,
                      num_blocks=2, num_annotations=128, dtype="bfloat16",
                      remat=True, remat_policy="convs"),
    data=DataConfig(seq_len=64, batch_size=16),
    optimizer=OptimizerConfig(warmup_steps=10),
    mesh=mesh_cfg, train=TrainConfig(max_steps=1))
mesh = make_mesh(mesh_cfg, jax.devices()[:16])
abstract = jax.eval_shape(lambda: create_train_state(jax.random.PRNGKey(0), cfg))
sh = state_sharding(mesh, abstract)
bsh = batch_sharding(mesh)
bat = {"tokens": jax.ShapeDtypeStruct((16, 64), np.int32, sharding=bsh["tokens"]),
       "annotations": jax.ShapeDtypeStruct((16, 128), np.float32,
                                           sharding=bsh["annotations"])}
st = jax.tree.map(lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
                  abstract, sh)
TS.train_step.lower(st, bat, cfg).compile()
print("COMPILED-OK")
"""
    out = subprocess.run([sys.executable, "-c", code], env=_child_env(),
                         cwd=REPO, capture_output=True, text=True,
                         timeout=600)
    assert "COMPILED-OK" in out.stdout, out.stderr[-3000:]
    assert "Involuntary full rematerialization" not in out.stderr, \
        out.stderr[-3000:]


def test_dryrun_multichip_16():
    """The driver's dry run at 16 devices must cover every axis at
    extent >2 in some mesh (fsdp=4, model=4, seq=4) and keep losses
    equal across meshes."""
    stdout = _run(
        ["-c", "import __graft_entry__; __graft_entry__.dryrun_multichip(16)"])
    lines = [ln for ln in stdout.splitlines() if "dryrun_multichip" in ln]
    assert len(lines) == 3, stdout
    for ax in ("'fsdp': 4", "'model': 4", "'seq': 4"):
        assert any(ax in ln for ln in lines), (ax, lines)
