"""bench.py sweep plumbing (no hardware): variant-list invariants the
parent↔child `--run-index` protocol and the persisted-record merge rely
on, plus the last-good merge semantics themselves."""

import json
import os

import pytest

import bench


def test_variant_rows_unique():
    """persist_last_good keys rows by (variant, seq_len, batch) — a
    duplicate key would silently overwrite a row mid-sweep; and the
    child re-derives the list by index, so it must be deterministic."""
    v1, _ = bench.build_variants(True)
    v2, _ = bench.build_variants(True)
    keys = [(name, seq, b) for name, _, seq, b in v1]
    assert len(set(keys)) == len(keys)
    assert keys == [(name, seq, b) for name, _, seq, b in v2]


def test_only_filter_matches_names_and_shape_keys():
    """--only matches the bare variant name (backward compat, anchored
    patterns included) and the 'name:seq/batch' shape key (so one row
    of a multi-shape variant can be refreshed in a short window)."""
    import re

    variants, _ = bench.build_variants(True, gate_pallas=False)

    def hits(pattern):
        pat = re.compile(pattern)
        return [(v[0], v[2], v[3]) for v in variants
                if bench.variant_matches(pat, v)]

    # Name-anchored pattern keeps matching despite the shape-key text.
    assert hits("u2st$") == [("remat-convs-u2st", 1024, 256)]
    # Row-targeted: exactly one shape of a six-shape variant.
    assert hits("remat-convs:1024/512$") == [("remat-convs", 1024, 512)]
    # Plain substring still matches every shape of the variant family.
    assert len(hits("pallas")) == 3
    assert hits("nonexistent") == []


def test_cpu_fallback_variant_is_tiny():
    (name, model, seq, batch), steps = bench.build_variants(False)[0][0], \
        bench.build_variants(False)[1]
    assert name == "xla" and model.num_blocks <= 2 and steps <= 5


def test_persist_merge_never_demotes(tmp_path, monkeypatch):
    """A later partial sweep must only add/refresh rows, never drop the
    stronger evidence already recorded."""
    monkeypatch.setattr(bench, "LAST_GOOD_PATH",
                        str(tmp_path / "last_good.json"))
    bench.persist_last_good([
        {"variant": "a", "seq_len": 512, "batch": 64,
         "ms_per_step": 10.0, "residues_per_sec": 100.0, "mfu": 0.5},
        {"variant": "b", "seq_len": 512, "batch": 64,
         "ms_per_step": 10.0, "residues_per_sec": 200.0, "mfu": 0.6},
    ])
    bench.persist_last_good([
        {"variant": "a", "seq_len": 512, "batch": 64,
         "ms_per_step": 9.0, "residues_per_sec": 150.0, "mfu": 0.55},
    ])
    rec = json.load(open(tmp_path / "last_good.json"))
    rows = {(r["variant"], r["seq_len"], r["batch"]):
            r["residues_per_sec"] for r in rec["sweep"]}
    assert rows[("a", 512, 64)] == 150.0  # refreshed
    assert rows[("b", 512, 64)] == 200.0  # survived the partial sweep
    assert rec["value"] == 200.0  # headline = best merged row


def test_preset_provenance_variants_track_presets():
    """The large/long sweep rows exist to certify the PRESET shapes
    (VERDICT r3 Weak #3) — they must be the presets' own model configs,
    not hand-copied twins that can drift."""
    from proteinbert_tpu.configs import get_preset

    by_name = {}
    for name, model, _, _ in bench.build_variants(True)[0]:
        by_name.setdefault(name, model)
    assert by_name["large"] == get_preset("large").model
    assert by_name["long"] == get_preset("long").model


def test_cpu_fallback_promotes_stale_tpu_record(tmp_path, monkeypatch,
                                                capsys):
    """VERDICT r3 item 5: with the tunnel down, the TOP-LEVEL record is
    the last-good TPU evidence (stale:true, captured_at), the live CPU
    number is demoted to live_fallback, and the line stays short — the
    full sweep must NOT be embedded (it overflowed the driver's parser
    in round 3)."""
    monkeypatch.setattr(bench, "LAST_GOOD_PATH",
                        str(tmp_path / "last_good.json"))
    monkeypatch.setattr(bench, "probe_tpu", lambda: (False, "fake down"))
    bench.persist_last_good([
        {"variant": "remat-convs", "seq_len": 1024, "batch": 256,
         "ms_per_step": 465.0, "residues_per_sec": 563000.0,
         "mfu": 0.567}])
    capsys.readouterr()

    def fake_run_variant(i, on_tpu):
        assert not on_tpu
        return {"variant": "xla", "seq_len": 128, "batch": 8,
                "ms_per_step": 200.0, "residues_per_sec": 4000.0,
                "mfu": 0.009, "platform": "cpu"}

    monkeypatch.setattr(bench, "run_variant", fake_run_variant)
    monkeypatch.setattr(bench, "force_cpu_backend", lambda: None)
    monkeypatch.setattr(bench.sys, "argv", ["bench.py"])
    bench.main()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    record = json.loads(line)
    assert record["platform"] == "tpu" and record["stale"] is True
    assert record["value"] == 563000.0 and record["captured_at"]
    assert record["live_fallback"]["platform"] == "cpu"
    assert record["live_fallback"]["value"] == 4000.0
    assert "sweep" not in record and len(line) < 600


def test_stale_age_hours_helper():
    """Unparseable/absent stamps degrade to None (age unknown) — the
    fallback path must never crash before its JSON line."""
    from datetime import datetime, timezone

    now = datetime(2026, 8, 1, 12, 0, 0, tzinfo=timezone.utc)
    assert bench.stale_age_hours("2026-08-01T00:00:00+0000",
                                 now=now) == pytest.approx(12.0)
    # A future stamp (clock skew) clamps to 0, not negative.
    assert bench.stale_age_hours("2026-08-02T00:00:00+0000", now=now) == 0.0
    assert bench.stale_age_hours(None) is None
    assert bench.stale_age_hours("not-a-date") is None


def test_stale_promotion_carries_age_and_warns(tmp_path, monkeypatch,
                                               capsys):
    """VERDICT r4 weak #5: a promoted stale headline must carry its age
    and shout once it exceeds the bound, so a long capture gap reads as
    'unverified' instead of a standing vs_baseline."""
    monkeypatch.setattr(bench, "LAST_GOOD_PATH",
                        str(tmp_path / "last_good.json"))
    monkeypatch.setattr(bench, "probe_tpu", lambda: (False, "fake down"))
    monkeypatch.setenv("PBT_STALE_WARN_HOURS", "48")
    bench.persist_last_good([
        {"variant": "remat-convs", "seq_len": 1024, "batch": 256,
         "ms_per_step": 465.0, "residues_per_sec": 563000.0,
         "mfu": 0.567}])
    # Age the record: rewrite both the row-level and file-level stamps.
    lg = json.load(open(tmp_path / "last_good.json"))
    lg["captured_at"] = "2026-07-01T00:00:00+0000"
    for r in lg["sweep"]:
        r["captured_at"] = "2026-07-01T00:00:00+0000"
    json.dump(lg, open(tmp_path / "last_good.json", "w"))
    capsys.readouterr()

    def fake_run_variant(i, on_tpu):
        return {"variant": "xla", "seq_len": 128, "batch": 8,
                "ms_per_step": 200.0, "residues_per_sec": 4000.0,
                "mfu": 0.009, "platform": "cpu"}

    monkeypatch.setattr(bench, "run_variant", fake_run_variant)
    monkeypatch.setattr(bench, "force_cpu_backend", lambda: None)
    monkeypatch.setattr(bench.sys, "argv", ["bench.py"])
    bench.main()
    cap = capsys.readouterr()
    record = json.loads(cap.out.strip().splitlines()[-1])
    assert record["stale"] is True
    assert record["stale_age_hours"] > 24 * 30  # a month old
    assert "WARNING" in cap.err and "unverified" in cap.err


def test_sweep_budget_clamps_child_timeout(tmp_path, monkeypatch, capsys):
    """ADVICE r4: once the budget is set, a hung variant after fast
    ones must not overshoot it by a full variant_timeout — the child
    timeout is clamped to the remaining budget (first variant keeps the
    full timeout so at least one row always lands)."""
    monkeypatch.setattr(bench, "LAST_GOOD_PATH",
                        str(tmp_path / "last_good.json"))
    monkeypatch.setattr(bench, "probe_tpu", lambda: (True, "fake"))
    monkeypatch.setenv("PBT_BENCH_MAX_SECONDS", "2000")

    clock = {"now": 0.0}
    monkeypatch.setattr(bench.time, "time", lambda: clock["now"])
    timeouts = []

    def fake_run(cmd, **kw):
        timeouts.append(kw["timeout"])
        clock["now"] += 300.0  # each variant "takes" 5 minutes
        i = int(cmd[-1])
        name, _, seq, batch = bench.build_variants(True)[0][i]
        row = {"variant": name, "seq_len": seq, "batch": batch,
               "ms_per_step": 1.0, "residues_per_sec": 1000.0 + i,
               "mfu": 0.5, "platform": "tpu"}
        return _FakeCompleted(0, json.dumps(row).encode())

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench.sys, "argv", ["bench.py"])
    bench.main()
    assert json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    # First child gets the full 900s timeout; later children are capped
    # by what's left of the 2000s budget (t=1200 -> 800, t=1500 -> 500);
    # nothing ever exceeds the per-variant timeout.
    assert timeouts[0] == 900
    assert timeouts[-1] == 500 and timeouts[-2] == 800
    assert all(t <= 900 for t in timeouts)


def test_sweep_wall_budget_stops_early_but_still_emits(
        tmp_path, monkeypatch, capsys):
    """PBT_BENCH_MAX_SECONDS: a caller-killed hours-long sweep emits NO
    line (the r3 parsed=null mode); the budget stops after the current
    variant instead, emits the line, and keeps the persisted rows. At
    least one variant always runs."""
    monkeypatch.setattr(bench, "LAST_GOOD_PATH",
                        str(tmp_path / "last_good.json"))
    monkeypatch.setattr(bench, "probe_tpu", lambda: (True, "fake"))
    monkeypatch.setenv("PBT_BENCH_MAX_SECONDS", "1500")

    clock = {"now": 0.0}
    monkeypatch.setattr(bench.time, "time", lambda: clock["now"])

    def fake_run(cmd, **kw):
        clock["now"] += 600.0  # each variant "takes" 10 minutes
        i = int(cmd[-1])
        name, _, seq, batch = bench.build_variants(True)[0][i]
        row = {"variant": name, "seq_len": seq, "batch": batch,
               "ms_per_step": 1.0, "residues_per_sec": 1000.0 + i,
               "mfu": 0.5, "platform": "tpu"}
        return _FakeCompleted(0, json.dumps(row).encode())

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench.sys, "argv", ["bench.py"])
    bench.main()
    record = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert record["platform"] == "tpu" and "stale" not in record
    # Projection uses the observed 600s/variant: variants at t=0 and 600
    # fit the 1500s budget; the third (1200 + 600 > 1500) does not.
    persisted = json.load(open(tmp_path / "last_good.json"))
    assert len(persisted["sweep"]) == 2


def test_sweep_decision_tool(tmp_path):
    """tools/sweep_decision.py: the defaults-flip call must be the
    data's — win only above the noise threshold, null below it,
    unmeasured when rows are absent."""
    import subprocess
    import sys as _sys

    tool = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "sweep_decision.py")

    def run(rows):
        p = tmp_path / "lg.json"
        p.write_text(json.dumps({"platform": "tpu", "sweep": rows}))
        out = subprocess.run([_sys.executable, tool, str(p)],
                             capture_output=True, text=True)
        assert out.returncode in (0, 1), out.stderr
        return json.loads(out.stdout)

    base = {"variant": "remat-convs", "seq_len": 1024, "batch": 256,
            "residues_per_sec": 563000.0, "mfu": 0.567}

    def sv(name, rps):
        return {"variant": name, "seq_len": 1024, "batch": 256,
                "residues_per_sec": rps, "mfu": 0.57}

    assert run([base])["decision"] == "unmeasured"
    # +3% u2: clears the 1.5% bar (decisive even with siblings missing).
    d = run([base, sv("remat-convs-u2", 580000.0)])
    assert d["decision"] == "flip-default:remat-convs-u2"
    # +0.5% with a sibling still unmeasured: the question stays OPEN —
    # a null close needs every lever measured.
    d = run([base, sv("remat-convs-u2", 565800.0),
             sv("remat-convs-st", 540000.0)])
    assert d["decision"] == "partially-measured"
    # All four measured (incl. the u2+st combo), none above noise: the
    # recorded null result.
    d = run([base, sv("remat-convs-u2", 565800.0),
             sv("remat-convs-u3", 560000.0),
             sv("remat-convs-st", 540000.0),
             sv("remat-convs-u2st", 562000.0)])
    assert d["decision"] == "null-result"
    assert run([])["decision"] == "no-baseline"


def test_post_capture_report_smoke(tmp_path):
    """The report generator must render whatever artifacts exist and
    name the missing ones explicitly — never fail, never go silent."""
    import subprocess
    import sys as _sys

    tool = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "post_capture_report.py")
    out_md = tmp_path / "report.md"
    p = subprocess.run([_sys.executable, tool, "--out", str(out_md)],
                       capture_output=True, text=True)
    assert p.returncode == 0, p.stderr
    text = out_md.read_text()
    for header in ("## Bench sweep", "## Scan-lever decision",
                   "## Transfer", "## Sustained run"):
        assert header in text, text[:500]


class _FakeCompleted:
    def __init__(self, rc, stdout=b""):
        self.returncode = rc
        self.stdout = stdout


def test_parent_sweep_filters_and_survives_bad_children(
        tmp_path, monkeypatch, capsys):
    """The TPU parent loop must skip timeouts/crashes/garbage, DISCARD
    rows measured on a fallen-back backend (fabrication guard), persist
    after every good row, and headline the best TPU row."""
    import subprocess as sp

    monkeypatch.setattr(bench, "LAST_GOOD_PATH",
                        str(tmp_path / "last_good.json"))
    monkeypatch.setattr(bench, "probe_tpu", lambda: (True, "fake"))
    n = len(bench.build_variants(True)[0])

    def fake_run(cmd, **kw):
        i = int(cmd[-1])
        name, _, seq, batch = bench.build_variants(True)[0][i]
        if i == 0:
            raise sp.TimeoutExpired(cmd, kw.get("timeout"))
        if i == 1:
            return _FakeCompleted(1)
        if i == 2:
            return _FakeCompleted(0, b"not json")
        row = {"variant": name, "seq_len": seq, "batch": batch,
               "ms_per_step": 1.0, "residues_per_sec": 1000.0 + i,
               "mfu": 0.5,
               "platform": "cpu" if i == 3 else "tpu"}
        return _FakeCompleted(0, json.dumps(row).encode())

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench.sys, "argv", ["bench.py"])
    bench.main()

    out = capsys.readouterr()
    record = json.loads(out.out.strip().splitlines()[-1])
    assert record["platform"] == "tpu"
    # Best = highest-index surviving TPU child (i == n-1).
    assert record["value"] == 1000.0 + (n - 1)
    persisted = json.load(open(tmp_path / "last_good.json"))
    rows = {(r["variant"], r["seq_len"], r["batch"]) for r in
            persisted["sweep"]}
    v = bench.build_variants(True)[0]
    # Children 0-3 contributed nothing; 4..n-1 all landed.
    assert len(rows) == len({(v[i][0], v[i][2], v[i][3])
                             for i in range(4, n)})
    assert not any(r.get("platform") for r in persisted["sweep"])


def test_boundary_bench_emits_record_and_overlap_wins():
    """`bench.py --boundary` (the CI-measurable overlap win): one JSON
    line with both per-boundary stall numbers, and the overlapped
    boundary strictly cheaper than the synchronous one. Sizes are
    shrunk via the env knobs so this stays a plumbing-and-direction
    test; the ≥5x magnitude claim is the bench's own default-size run."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PBT_BOUNDARY_BENCH_BOUNDARIES="2",
               PBT_BOUNDARY_BENCH_STEPS="3",
               PBT_BOUNDARY_BENCH_DIM="32")
    p = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--boundary"],
        capture_output=True, text=True, timeout=420, env=env, cwd=repo)
    assert p.returncode == 0, p.stderr[-2000:]
    record = json.loads(p.stdout.strip().splitlines()[-1])
    assert record["metric"] == "ckpt_boundary_stall_s"
    assert record["platform"] == "cpu"
    assert record["boundaries"] == 2
    assert record["overlapped_stall_s_per_boundary"] > 0
    assert record["sync_stall_s_per_boundary"] > \
        record["overlapped_stall_s_per_boundary"]
    assert record["stall_reduction_x"] > 1
    # The hidden work really ran (fetch+write seconds were recorded).
    assert record["overlap_hidden_s_per_boundary"] > 0


@pytest.mark.slow
def test_comm_bench_records_zero_update_win():
    """`bench.py --comm` (the ZeRO-1 memory/comm artifact): one JSON
    line comparing replicated vs zero-update compiled programs. The
    acceptance-criteria numbers asserted here come from the COMPILED
    HLO and the sharding rules, not from the docstring: per-chip
    optimizer-state bytes reduced by ~(1 - 1/data_extent), per-step
    collective bytes within ~1.5x of the replicated all-reduce, int8
    grad-reduction wire <= 0.30x the fp32 explicit reduce-scatter.
    Model dim shrunk via env, but the subprocess still pays five full
    sharded compiles — slow lane; tier-1 covers the helpers in-process
    (tests/test_zero.py, tests/test_quant.py + the quant smoke stage)
    and the docs/performance.md row records the default-size capture."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PBT_COMM_MESH="4x2", PBT_COMM_DIM="32")
    # Scrub the 8-device flag so the child's own request can't fight it.
    from proteinbert_tpu.utils.compat import scrub_device_count_flag

    env["XLA_FLAGS"] = scrub_device_count_flag(env.get("XLA_FLAGS", ""))
    p = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--comm"],
        capture_output=True, text=True, timeout=780, env=env, cwd=repo)
    assert p.returncode == 0, p.stderr[-2000:]
    record = json.loads(p.stdout.strip().splitlines()[-1])
    assert record["metric"] == "zero_update_comm"
    assert record["platform"] == "cpu-virtual"
    assert record["mesh"] == {"data": 4, "fsdp": 2}
    modes = {r["mode"]: r for r in record["modes"]}
    assert set(modes) == {"replicated", "zero", "zero_rs_fp32",
                          "zero_bf16", "zero_int8"}
    # Memory: Adam state per chip shrinks ~data_extent (4), params don't.
    assert record["opt_state_bytes_reduction_x"] >= 3.0
    assert (modes["zero"]["state_bytes_per_chip"]["params"]
            == modes["replicated"]["state_bytes_per_chip"]["params"])
    # Comm: reduce-scatter + all-gather stays within ~1.5x all-reduce.
    assert 0 < record["collective_bytes_ratio"] <= 1.5
    for r in record["modes"]:
        assert r["collective_bytes"]["total"] > 0
        assert r["wire_bytes"]["total"] > 0
    # Quantized wire (ISSUE 12, the ROADMAP item 1 acceptance): the
    # int8 payload moves <= 0.30x the fp32 explicit reduce-scatter's
    # grad-reduction wire bytes (bench exits 1 past the gate; asserted
    # here too so the record itself carries the evidence), bf16 ~0.5x.
    assert 0 < record["int8_grad_wire_ratio"] <= 0.30
    assert 0 < record["bf16_grad_wire_ratio"] <= 0.60
