"""CPU fault-injection drill for the stall-attribution machinery
(VERDICT r4 round-5 item 3).

The r4 window metrics (window_* rates, wall-clock `t`, ckpt_in_flight
latch, slow-window summary) were validated only by unit tests with
injected clocks; nothing had demonstrated that a REAL run with a real
stall gets that stall *localized*. This drill injects a deliberate
host-side stall into a real `tools/sustained_pretrain.py` run (two CLI
subprocesses, SIGTERM seam and all) via the trainer's env-gated
PBT_FAULT_STALL_AT hook, and asserts the summary's slow-window list
names the right log window with the checkpoint latch set — the
test-multi-node-without-a-cluster philosophy (SURVEY §4) applied to
observability.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_fault_stall_spec_parsing(monkeypatch):
    from proteinbert_tpu.train.trainer import _fault_stall_spec

    monkeypatch.delenv("PBT_FAULT_STALL_AT", raising=False)
    assert _fault_stall_spec() is None
    monkeypatch.setenv("PBT_FAULT_STALL_AT", "27:8.5")
    assert _fault_stall_spec() == (27, 8.5)
    monkeypatch.setenv("PBT_FAULT_STALL_AT", "garbage")
    assert _fault_stall_spec() is None  # malformed -> ignored, not fatal


def test_eval_stall_does_not_masquerade_as_training_stall(tmp_path):
    """Negative control: a 6s stall INSIDE every (discounted) eval
    bracket must not flag the eval-adjacent windows — the discount
    machinery, end to end, keeps eval/I-O time out of the training-rate
    windows, so a slow-window flag really means the training path
    stalled. (Evals run at 25/50 after those steps' log points, so an
    undiscounted stall would surface in the 26-30 / 51-55 windows.)"""
    env = dict(os.environ, PBT_FAULT_EVAL_STALL="6")
    out = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "sustained_pretrain.py"),
         "--scale", "mini", "--steps", "60", "--kill-at", "35",
         # The drill validates the DISCOUNT/attribution machinery — the
         # synchronous boundary path by definition. The overlapped
         # boundary's stager thread contends for the single CPU core
         # with the train steps (on TPU the fetch+write is truly
         # parallel) and can noise exactly the windows asserted below.
         "--set", "checkpoint.overlap=false",
         "--outdir", str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=900)
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-3000:])
    # Guard against a vacuous pass: the injection must have ACTUALLY
    # fired in the trainer subprocesses (both phases log the warning).
    cli_log = open(tmp_path / "cli.log").read()
    assert "FAULT INJECTION ACTIVE" in cli_log and "per eval bracket" \
        in cli_log, cli_log[-2000:]
    summary = json.load(open(tmp_path / "sustained_summary.json"))
    win = summary["windows"]
    slow_steps = [s for s, _, _ in win["slow_windows"]]
    # An UNdiscounted 6 s eval stall would flag EVERY eval-adjacent
    # window (26-30 and 51-55) deterministically — that systematic
    # signature is what this control guards against. A single one of
    # them appearing is indistinguishable from the load-noise spike any
    # window can take on a contended 1-core host (observed once in a
    # full-suite run: windows 15 and 30 slow, 55 clean), so only the
    # pair is a failure; unrelated windows get the same noise allowance
    # as the positive test.
    assert not ({30, 55} <= set(slow_steps)), (slow_steps, win)
    assert len(slow_steps) <= 2, (slow_steps, win)


def test_injected_stall_is_localized_by_window_metrics(tmp_path):
    """An 8s stall at step 27 (log_every=5, ckpt at 25) must surface as
    a slow 26-30 window flagged ckpt_in_flight — and only as a minority
    of windows, i.e. the machinery LOCALIZES rather than smears."""
    env = dict(os.environ, PBT_FAULT_STALL_AT="27:8")
    out = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "sustained_pretrain.py"),
         "--scale", "mini", "--steps", "60", "--kill-at", "35",
         # Synchronous boundaries for the drill: see the negative
         # control above — the stager thread's single-core contention
         # must not smear the windows this test localizes against.
         "--set", "checkpoint.overlap=false",
         "--outdir", str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=900)
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-3000:])

    summary = json.load(open(tmp_path / "sustained_summary.json"))
    win = summary["windows"]
    assert win, "no windowed rates in the summary"
    slow_steps = [s for s, _, _ in win["slow_windows"]]
    # The injected stall lands inside the 26-30 window.
    assert 30 in slow_steps, (slow_steps, win)
    # The checkpoint save at step 25 started since the step-25 log
    # point, so the step-30 window carries the latch: the summary
    # attributes the slow window to a save overlap.
    assert 30 in win["slow_with_ckpt_in_flight"], win
    # Localization: the flag names the faulted window, not the run.
    assert len(slow_steps) <= 3, (slow_steps, win)
    # Slow windows carry wall-clock stamps for external correlation.
    assert all(t is not None for _, _, t in win["slow_windows"])
