"""tpu_watch daemon logic without hardware: probe → sweep → after-sweep
hook chaining, all subprocess calls faked."""

import json
import types


def _load(monkeypatch, tmp_path):
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "tpu_watch", os.path.join(os.path.dirname(__file__), "..",
                                  "tools", "tpu_watch.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "STATUS_PATH", str(tmp_path / "status.json"))
    # Isolate the startup age guard from the real repo's
    # bench_last_tpu.json — otherwise test output would vary with how
    # old the checked-in capture happens to be.
    monkeypatch.setattr(mod, "LAST_GOOD_PATH",
                        str(tmp_path / "last_good.json"))
    monkeypatch.setattr(mod, "POLL_WAIT", 0)
    return mod


def test_after_sweep_hook_runs_on_capture(monkeypatch, tmp_path):
    mod = _load(monkeypatch, tmp_path)
    monkeypatch.setattr(mod, "probe", lambda: (True, None))
    proof = tmp_path / "hook_proof"
    monkeypatch.setenv("PBT_WATCH_AFTER_SWEEP",
                       f"echo chained > {proof}")

    record = {"platform": "tpu", "value": 1.0}
    calls = []

    def fake_run(cmd, **kw):
        assert isinstance(cmd, list) and any("bench.py" in c for c in cmd)
        calls.append("bench")
        return types.SimpleNamespace(
            returncode=0, stderr="", stdout=json.dumps(record) + "\n")

    # Only the sweep goes through subprocess.run; the hook runs via a
    # REAL Popen in its own session (group-kill semantics), so the proof
    # file is written by an actual shell.
    monkeypatch.setattr(mod.subprocess, "run", fake_run)
    rc = mod.main()
    assert rc == 0
    assert calls == ["bench"]
    assert proof.read_text().strip() == "chained"
    status = json.load(open(tmp_path / "status.json"))
    assert status["status"] == "captured"


def test_no_hook_when_sweep_falls_back(monkeypatch, tmp_path):
    mod = _load(monkeypatch, tmp_path)
    monkeypatch.setattr(mod, "DEADLINE_H", 0.0001)  # one loop, then out
    # Defeat the no-fit skip (the near-zero deadline would otherwise
    # exit 7 before sweeping — this test needs the sweep to RUN).
    monkeypatch.setattr(mod, "variant_timeout", lambda: -120)
    monkeypatch.setattr(mod, "probe", lambda: (True, None))
    proof = tmp_path / "hook_proof"
    monkeypatch.setenv("PBT_WATCH_AFTER_SWEEP", f"echo chained > {proof}")

    def fake_run(cmd, **kw):
        return types.SimpleNamespace(
            returncode=0, stderr="",
            stdout=json.dumps({"platform": "cpu"}) + "\n")

    monkeypatch.setattr(mod.subprocess, "run", fake_run)
    rc = mod.main()
    assert rc == 3  # deadline, never captured
    assert not proof.exists()


def test_stale_promoted_record_is_not_a_capture(monkeypatch, tmp_path):
    """bench's CPU-fallback line now PROMOTES the last-good TPU row to
    the top level (platform "tpu" + stale true). That is evidence of a
    PAST window — treating it as a capture would fire the after-sweep
    hardware hook on a dead tunnel and exit the watch for nothing."""
    mod = _load(monkeypatch, tmp_path)
    monkeypatch.setattr(mod, "DEADLINE_H", 0.0001)
    monkeypatch.setattr(mod, "variant_timeout", lambda: -120)  # no-fit off
    monkeypatch.setattr(mod, "probe", lambda: (True, None))
    proof = tmp_path / "hook_proof"
    monkeypatch.setenv("PBT_WATCH_AFTER_SWEEP", f"echo chained > {proof}")

    def fake_run(cmd, **kw):
        return types.SimpleNamespace(
            returncode=0, stderr="",
            stdout=json.dumps({"platform": "tpu", "stale": True,
                               "value": 1.0}) + "\n")

    monkeypatch.setattr(mod.subprocess, "run", fake_run)
    rc = mod.main()
    assert rc == 3  # deadline — stale evidence never counts as captured
    assert not proof.exists()
    status = json.load(open(tmp_path / "status.json"))
    assert status["status"] != "captured"


def test_sweep_budget_clamped_to_remaining_deadline(monkeypatch, tmp_path):
    """A sweep that starts near the watcher deadline must not hold the
    shared chip past it (the round driver's own bench follows): the
    subprocess timeout is clamped to the remaining deadline and bench
    gets a NONZERO wall budget (0 would mean unbounded) so it winds
    down between variants instead of being SIGKILLed mid-variant."""
    mod = _load(monkeypatch, tmp_path)
    monkeypatch.setattr(mod, "DEADLINE_H", 0.5)  # 1800s of deadline left
    monkeypatch.setattr(mod, "probe", lambda: (True, None))
    seen = {}

    def fake_run(cmd, **kw):
        seen["timeout"] = kw["timeout"]
        seen["budget"] = kw["env"]["PBT_BENCH_MAX_SECONDS"]
        return types.SimpleNamespace(
            returncode=0, stderr="",
            stdout=json.dumps({"platform": "tpu", "value": 1.0}) + "\n")

    monkeypatch.setattr(mod.subprocess, "run", fake_run)
    rc = mod.main()
    assert rc == 0
    assert seen["timeout"] <= 1800
    assert 1 <= int(seen["budget"]) <= 1800  # clamped => nonzero bound


def test_sweep_skipped_when_deadline_inside_one_variant(monkeypatch,
                                                        tmp_path):
    """With less deadline than one variant's budget, even a clamped
    sweep would be SIGKILLed mid-first-variant with nothing persisted —
    the watcher must leave the chip to the round driver's bench."""
    mod = _load(monkeypatch, tmp_path)
    monkeypatch.setattr(mod, "DEADLINE_H", 0.05)  # 180s < variant+120
    monkeypatch.setattr(mod, "probe", lambda: (True, None))

    def fake_run(cmd, **kw):  # pragma: no cover - must not be reached
        raise AssertionError("sweep launched inside the no-fit window")

    monkeypatch.setattr(mod.subprocess, "run", fake_run)
    rc = mod.main()
    assert rc == 7
    status = json.load(open(tmp_path / "status.json"))
    assert status["status"] == "deadline_before_sweep"


def test_captured_status_reports_fresh_age(monkeypatch, tmp_path):
    """After a successful sweep the terminal status must report the
    FRESH capture's age, not the weeks-old pre-sweep stamp resolved at
    startup — else a poller reads status=captured paired with a huge
    last_good_age_h and distrusts a just-measured number."""
    mod = _load(monkeypatch, tmp_path)
    monkeypatch.delenv("PBT_WATCH_AFTER_SWEEP", raising=False)
    old = "2026-07-01T00:00:00+0000"
    rec = {"platform": "tpu", "variant": "v", "seq_len": 1, "batch": 1,
           "captured_at": old,
           "sweep": [{"variant": "v", "seq_len": 1, "batch": 1,
                      "captured_at": old}]}
    json.dump(rec, open(tmp_path / "last_good.json", "w"))
    monkeypatch.setattr(mod, "probe", lambda: (True, None))

    def fake_run(cmd, **kw):
        import time as _t

        now = _t.strftime("%Y-%m-%dT%H:%M:%S%z")
        fresh = json.loads(json.dumps(rec))
        fresh["captured_at"] = now
        fresh["sweep"][0]["captured_at"] = now
        json.dump(fresh, open(tmp_path / "last_good.json", "w"))
        return types.SimpleNamespace(
            returncode=0, stderr="",
            stdout=json.dumps({"platform": "tpu", "value": 1.0}) + "\n")

    monkeypatch.setattr(mod.subprocess, "run", fake_run)
    rc = mod.main()
    assert rc == 0
    status = json.load(open(tmp_path / "status.json"))
    assert status["status"] == "captured"
    assert status["last_good_age_h"] < 1.0


def test_stale_age_warns_at_startup_and_persists_in_status(
        monkeypatch, tmp_path, capsys):
    """VERDICT r4 weak #5: an old last-good record must produce a loud
    startup warning AND a last_good_age_h field that survives the
    in-loop status rewrites — pollers read tpu_watch_status.json, not
    the startup log line."""
    mod = _load(monkeypatch, tmp_path)
    old = "2026-07-01T00:00:00+0000"
    json.dump({"platform": "tpu", "variant": "v", "seq_len": 1,
               "batch": 1, "captured_at": old,
               "sweep": [{"variant": "v", "seq_len": 1, "batch": 1,
                          "captured_at": old}]},
              open(tmp_path / "last_good.json", "w"))
    monkeypatch.setattr(mod, "DEADLINE_H", 0.0001)
    monkeypatch.setattr(mod, "probe", lambda: (False, None))
    rc = mod.main()
    assert rc == 3
    assert "WARNING" in capsys.readouterr().out
    # The LAST write (the terminal deadline status) still carries age.
    status = json.load(open(tmp_path / "status.json"))
    assert status["last_good_age_h"] > 24 * 30


def test_sweep_timeout_cap_stops_the_daemon(monkeypatch, tmp_path):
    """Each sweep timeout burns the whole sweep budget on the shared
    chip; an unbounded retry loop of SIGKILLed multi-hour sweeps must
    cap out (ADVICE r3)."""
    import subprocess as sp

    mod = _load(monkeypatch, tmp_path)
    monkeypatch.setattr(mod, "probe", lambda: (True, None))
    monkeypatch.setattr(mod, "SWEEP_TIMEOUT_CAP", 2)
    # Each timeout drains the orphaned child's self-destruct bound
    # (variant_timeout()+60) before re-probing; zero it for the test.
    monkeypatch.setattr(mod, "variant_timeout", lambda: -60)

    def fake_run(cmd, **kw):
        raise sp.TimeoutExpired(cmd, kw.get("timeout"))

    monkeypatch.setattr(mod.subprocess, "run", fake_run)
    rc = mod.main()
    assert rc == 6
    status = json.load(open(tmp_path / "status.json"))
    assert status["status"] == "sweep_timeout_cap"


def test_hook_timeout_kills_process_group(monkeypatch, tmp_path):
    """A compound hook command that outlives the bound must be killed as
    a GROUP — run(shell=True) would kill only the sh wrapper and leave
    the experiment process hammering the shared chip."""
    mod = _load(monkeypatch, tmp_path)
    monkeypatch.setattr(mod, "probe", lambda: (True, None))
    monkeypatch.setattr(mod, "HOOK_TIMEOUT", 1)
    marker = tmp_path / "survivor"
    # sleep is the grandchild; if only sh died, the second command would
    # still create the marker afterwards.
    monkeypatch.setenv("PBT_WATCH_AFTER_SWEEP",
                       f"sleep 30 && echo alive > {marker}")

    def fake_run(cmd, **kw):
        return types.SimpleNamespace(
            returncode=0, stderr="",
            stdout=json.dumps({"platform": "tpu", "value": 1.0}) + "\n")

    monkeypatch.setattr(mod.subprocess, "run", fake_run)
    import time
    t0 = time.time()
    rc = mod.main()
    assert rc == 0 and time.time() - t0 < 25
    time.sleep(1.5)
    assert not marker.exists()
    status = json.load(open(tmp_path / "status.json"))
    assert status["status"] == "captured"
