"""Quantized collectives + int8 serving (parallel/quant.py, ISSUE 12):
stochastic-rounding determinism and lockstep, quantize→dequantize
error bounds per dtype, the int8/bf16 reduce-scatter parity grid
(plain/ZeRO-1 x fp32/bf16/int8) on the virtual 8-device mesh,
checkpoint interchangeability across reduce dtypes, the typed config
rejections, and the quantized serving arm (weights, parity sampling,
event fields)."""

import dataclasses
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from proteinbert_tpu.configs import (
    CheckpointConfig, DataConfig, MeshConfig, ModelConfig,
    OptimizerConfig, ParallelConfig, PretrainConfig, TrainConfig,
)
from proteinbert_tpu.data import (
    InMemoryPretrainingDataset, make_pretrain_iterator,
)
from proteinbert_tpu.parallel import (
    batch_sharding, make_mesh, make_zero_train_step, shard_train_state,
)
from proteinbert_tpu.parallel import quant as q
from proteinbert_tpu.train import (
    Checkpointer, create_train_state, train_step,
)
from tests.conftest import make_random_proteins

requires_8 = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 (virtual) devices"
)

# Documented parity bounds (docs/distributed.md, quantized reduction):
# max param deviation from the exact fp32 reference after two steps at
# lr 1e-3 on the tiny grid model. The fp32-PAYLOAD explicit control
# bounds the harness itself.
INT8_BOUND = 1e-3
BF16_BOUND = 5e-4
CONTROL_BOUND = 1e-6


def cfg_for(mesh_cfg, parallel=None, **kw):
    model = dict(
        local_dim=16, global_dim=32, key_dim=8, num_heads=4, num_blocks=2,
        num_annotations=64, dtype="float32",
    )
    return PretrainConfig(
        model=ModelConfig(**model),
        data=DataConfig(seq_len=32, batch_size=16, **kw.pop("data_kw", {})),
        optimizer=OptimizerConfig(learning_rate=1e-3, warmup_steps=10),
        mesh=mesh_cfg,
        parallel=parallel or ParallelConfig(zero_update=True,
                                            grad_reduce_dtype="int8"),
        train=TrainConfig(max_steps=4, **kw.pop("train_kw", {})),
    )


MESH_CFG = MeshConfig(data=4, fsdp=2)
REF_CFG = cfg_for(MeshConfig(), parallel=ParallelConfig())


def make_batch(cfg, seed=0):
    rng = np.random.default_rng(seed)
    seqs, ann = make_random_proteins(
        cfg.data.batch_size, rng, num_annotations=cfg.model.num_annotations,
        max_len=40,
    )
    ds = InMemoryPretrainingDataset(seqs, ann, cfg.data.seq_len)
    return next(make_pretrain_iterator(ds, cfg.data.batch_size, seed=seed))


def _two_steps_quant(cfg, batch, payload=None):
    mesh = make_mesh(cfg.mesh)
    state = shard_train_state(
        create_train_state(jax.random.PRNGKey(0), cfg), mesh,
        zero_update=True)
    if payload is not None:
        step = q.make_quant_zero_train_step(mesh, cfg, payload=payload)
    else:
        step = make_zero_train_step(mesh, cfg)
    bsh = batch_sharding(mesh)
    dbatch = {k: jax.device_put(v, bsh[k]) for k, v in batch.items()}
    state, m1 = step(state, dbatch)
    state, m2 = step(state, dbatch)
    return state, m1, m2


def _max_param_err(ref_state, state):
    err = 0.0
    for r, g in zip(jax.tree.leaves(ref_state.params),
                    jax.tree.leaves(state.params)):
        err = max(err, float(np.max(np.abs(
            np.asarray(r, np.float64)
            - np.asarray(jax.device_get(g), np.float64)))))
    return err


# ------------------------------------------------------------ primitives


class TestPrimitives:
    def test_bf16_stochastic_rounding_deterministic_and_bounded(self):
        x = jnp.asarray(
            np.random.default_rng(0).normal(size=(4096,)), jnp.float32)
        key = jax.random.PRNGKey(7)
        a = q.stochastic_round_bf16(x, key)
        b = q.stochastic_round_bf16(x, key)
        assert a.dtype == jnp.bfloat16
        assert np.array_equal(np.asarray(a, np.float32),
                              np.asarray(b, np.float32))
        # Different key → different noise → different rounding pattern.
        c = q.stochastic_round_bf16(x, jax.random.PRNGKey(8))
        assert not np.array_equal(np.asarray(a, np.float32),
                                  np.asarray(c, np.float32))
        # Per-element error bounded by one bf16 ulp (2^-8 relative).
        err = np.abs(np.asarray(a, np.float32) - np.asarray(x))
        assert float(np.max(err / np.abs(np.asarray(x)))) <= 2 ** -7
        # Unbiased-ish: the mean residual is far below one ulp.
        assert abs(float(np.mean(np.asarray(a, np.float32)
                                 - np.asarray(x)))) < 1e-4

    def test_int8_chunks_roundtrip_error_bounded(self):
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(3, 1300)) * 10.0, jnp.float32)
        key = jax.random.PRNGKey(3)
        qq, scale, m = q.quantize_int8_chunks(x, key)
        assert qq.dtype == jnp.int8 and m == 1300
        back = q.dequantize_int8_chunks(qq, scale, m)
        assert back.shape == x.shape
        # Stochastic floor(y + u) lands within one quantum of y.
        err = np.abs(np.asarray(back) - np.asarray(x))
        bound = np.repeat(np.asarray(scale), qq.shape[-1],
                          axis=-1)[..., :m]
        assert np.all(err <= bound + 1e-6)
        # Deterministic under a fixed key; rounds-to-nearest without.
        q2, s2, _ = q.quantize_int8_chunks(x, key)
        assert np.array_equal(np.asarray(qq), np.asarray(q2))
        qd, sd, _ = q.quantize_int8_chunks(x, None)
        errd = np.abs(q.dequantize_int8_chunks(qd, sd, m) - x)
        assert np.all(np.asarray(errd)
                      <= np.asarray(bound) / 2 + 1e-6)

    def test_int8_chunks_zero_and_tiny_slices(self):
        # All-zero chunks must not divide by zero, and a slice smaller
        # than the chunk must not be padded up to it (the wire-bytes
        # property the comm gate measures).
        z = jnp.zeros((2, 16), jnp.float32)
        qq, scale, m = q.quantize_int8_chunks(z, None)
        assert qq.shape[-1] <= 16 and m == 16
        assert np.all(np.asarray(qq) == 0)
        assert np.all(np.asarray(scale) == 1.0)

    def test_lockstep_noise_is_replica_indexed(self):
        # The multi-host lockstep property reduced to its mechanism:
        # the rounding noise is a pure function of (key, replica index)
        # — same inputs, same noise on every host; different replicas,
        # different noise (partials must not round identically).
        key = jax.random.PRNGKey(0)
        k0 = jax.random.fold_in(key, 0)
        k1 = jax.random.fold_in(key, 1)
        x = jnp.asarray(np.random.default_rng(2).normal(size=(512,)),
                        jnp.float32)
        a0, _, _ = q.quantize_int8_chunks(x[None], k0)
        a0b, _, _ = q.quantize_int8_chunks(x[None], k0)
        a1, _, _ = q.quantize_int8_chunks(x[None], k1)
        assert np.array_equal(np.asarray(a0), np.asarray(a0b))
        assert not np.array_equal(np.asarray(a0), np.asarray(a1))


# ------------------------------------------------------- the parity grid


@requires_8
@pytest.mark.parametrize(
    "payload,bound",
    [("fp32", CONTROL_BOUND), ("bf16", BF16_BOUND), ("int8", INT8_BOUND)],
    ids=["fp32-control", "bf16", "int8"],
)
def test_quant_reduce_scatter_parity_grid(payload, bound):
    """The ZeRO-1 quantized reduce-scatter vs the PLAIN replicated fp32
    step (the full plain/ZeRO x payload grid): step-1 loss identical
    (same corruption ops on the same key — deviation is quantization
    alone), two-step param deviation within the documented bound per
    payload, and the fp32-payload explicit control within 1e-6 (the
    harness itself adds nothing)."""
    cfg = cfg_for(MESH_CFG, parallel=ParallelConfig(
        zero_update=True,
        grad_reduce_dtype=payload if payload != "fp32" else "int8"))
    batch = make_batch(cfg)

    ref_state = create_train_state(jax.random.PRNGKey(0), REF_CFG)
    ref_state, rm1 = train_step(ref_state, dict(batch), REF_CFG)
    ref_state, _ = train_step(ref_state, dict(batch), REF_CFG)

    state, m1, m2 = _two_steps_quant(
        cfg, batch, payload="fp32" if payload == "fp32" else None)
    assert abs(float(m1["loss"]) - float(rm1["loss"])) \
        <= 2e-5 * max(1.0, abs(float(rm1["loss"])))
    err = _max_param_err(ref_state, state)
    assert err <= bound, (payload, err)
    if payload != "fp32":
        assert err > 0.0, "quantization did not round anything"


@requires_8
def test_quant_step_deterministic():
    """Bit-determinism across runs from the same state — the noise is
    seeded from the (replicated, checkpointed) step key, so re-runs and
    every host of a multi-host mesh draw identical noise."""
    cfg = cfg_for(MESH_CFG)
    batch = make_batch(cfg)
    a, _, _ = _two_steps_quant(cfg, batch)
    b, _, _ = _two_steps_quant(cfg, batch)
    for x, y in zip(jax.tree.leaves(a.params), jax.tree.leaves(b.params)):
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(x)), np.asarray(jax.device_get(y)))


@requires_8
def test_quant_packed_batch_parity():
    """A PACKED batch through the int8 quantized step vs the replicated
    fp32 packed step: the per-segment loss decomposition inside the
    quantized shard_map must reproduce packed_pretrain_loss."""
    parallel = ParallelConfig(zero_update=True, grad_reduce_dtype="int8")
    cfg = cfg_for(MESH_CFG, parallel=parallel,
                  data_kw=dict(packing=True, pack_max_segments=4))
    ref_cfg = cfg_for(MeshConfig(), parallel=ParallelConfig(),
                      data_kw=dict(packing=True, pack_max_segments=4))
    rng = np.random.default_rng(3)
    seqs, ann = make_random_proteins(48, rng, num_annotations=64,
                                     max_len=14)
    from proteinbert_tpu.data.packing import make_packed_iterator

    ds = InMemoryPretrainingDataset(seqs, ann, cfg.data.seq_len)
    batch = next(make_packed_iterator(
        ds, cfg.data.batch_size, seed=0, max_segments=4))

    ref_state = create_train_state(jax.random.PRNGKey(0), ref_cfg)
    ref_state, rm1 = train_step(ref_state, dict(batch), ref_cfg)
    ref_state, _ = train_step(ref_state, dict(batch), ref_cfg)

    state, m1, _ = _two_steps_quant(cfg, batch)
    assert abs(float(m1["loss"]) - float(rm1["loss"])) \
        <= 2e-5 * max(1.0, abs(float(rm1["loss"])))
    err = _max_param_err(ref_state, state)
    assert 0.0 < err <= INT8_BOUND, err


@requires_8
def test_sustained_loss_trajectory_tracks_fp32():
    """The short sustained-pretrain check of the documented
    methodology (docs/distributed.md): 12 steps over a real batch
    stream, int8 and bf16 reductions must track the fp32 ZeRO loss
    curve within 1% relative at every step — quantization noise may
    perturb, it must not bend the trajectory."""
    mesh = make_mesh(MESH_CFG)
    bsh = batch_sharding(mesh)
    rng = np.random.default_rng(11)
    seqs, ann = make_random_proteins(64, rng, num_annotations=64,
                                     max_len=40)
    base = cfg_for(MESH_CFG, parallel=ParallelConfig(zero_update=True))
    ds = InMemoryPretrainingDataset(seqs, ann, base.data.seq_len)

    def run(grd):
        cfg = cfg_for(MESH_CFG, parallel=ParallelConfig(
            zero_update=True, grad_reduce_dtype=grd))
        it = make_pretrain_iterator(ds, cfg.data.batch_size, seed=0)
        state = shard_train_state(
            create_train_state(jax.random.PRNGKey(0), cfg), mesh,
            zero_update=True)
        step = make_zero_train_step(mesh, cfg)
        losses = []
        for _ in range(12):
            batch = {k: jax.device_put(v, bsh[k])
                     for k, v in next(it).items()}
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        return losses

    ref = run("fp32")
    assert all(np.isfinite(ref))
    for grd in ("bf16", "int8"):
        got = run(grd)
        for i, (a, b) in enumerate(zip(ref, got)):
            assert abs(a - b) <= 0.01 * max(1.0, abs(a)), (grd, i, a, b)


@requires_8
def test_checkpoints_interchangeable_across_reduce_dtypes(tmp_path):
    """Leaf shapes and shardings are payload-independent, so a
    checkpoint written under int8 reduction restores into an fp32 run
    (and vice versa) byte-for-byte — the reduce dtype is a per-run
    execution knob, not a format."""
    int8_cfg = cfg_for(MESH_CFG)
    batch = make_batch(int8_cfg)
    mesh = make_mesh(MESH_CFG)
    state = shard_train_state(
        create_train_state(jax.random.PRNGKey(0), int8_cfg), mesh,
        zero_update=True)
    step8 = make_zero_train_step(mesh, int8_cfg)
    bsh = batch_sharding(mesh)
    dbatch = {k: jax.device_put(v, bsh[k]) for k, v in batch.items()}
    state, _ = step8(state, dbatch)

    ck = Checkpointer(str(tmp_path / "ck"), async_save=False)
    ck.save(1, jax.device_get(state))
    template = shard_train_state(
        create_train_state(jax.random.PRNGKey(0), int8_cfg), mesh,
        zero_update=True)
    restored, _ = ck.restore(template)
    ck.close()
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))),
        restored, state)
    # ...and the restored state steps under the FP32 zero rule.
    fp32_cfg = cfg_for(MESH_CFG, parallel=ParallelConfig(
        zero_update=True, grad_reduce_dtype="fp32"))
    step32 = make_zero_train_step(mesh, fp32_cfg)
    nxt, m = step32(restored, dbatch)
    assert int(jax.device_get(nxt.step)) == 2
    assert np.isfinite(float(m["loss"]))


# ------------------------------------------------------- typed rejections


class TestConfigRejections:
    def test_unknown_payload_rejected(self):
        mesh = make_mesh(MESH_CFG) if jax.device_count() >= 8 else None
        if mesh is None:
            pytest.skip("needs 8 virtual devices")
        with pytest.raises(q.QuantConfigError, match="payload"):
            q.check_quant_mesh(mesh, "fp8")

    @requires_8
    def test_model_axis_rejected(self):
        mesh_cfg = MeshConfig(data=2, fsdp=2, model=2)
        mesh = make_mesh(mesh_cfg)
        with pytest.raises(q.QuantConfigError, match="model"):
            q.check_quant_mesh(mesh, "int8")

    @requires_8
    def test_indivisible_batch_rejected(self):
        mesh = make_mesh(MESH_CFG)
        with pytest.raises(q.QuantConfigError, match="batch"):
            q.check_quant_mesh(mesh, "int8", batch_size=12)

    @requires_8
    def test_seq_parallel_pallas_step_rejects_int8(self):
        """The ISSUE 12 satellite: grad_reduce_dtype='int8' + the
        explicit seq-parallel Pallas step is a typed QuantConfigError
        (mirroring that step's packing rejection); bf16 keeps its
        documented cast-only legacy path there."""
        from proteinbert_tpu.parallel.seq_parallel import (
            make_seq_parallel_train_step,
        )

        mesh_cfg = MeshConfig(data=2, fsdp=2, seq=2)
        cfg = cfg_for(mesh_cfg, parallel=ParallelConfig(
            zero_update=True, grad_reduce_dtype="int8"))
        cfg = cfg.replace(model=dataclasses.replace(
            cfg.model, use_pallas=True))
        mesh = make_mesh(mesh_cfg)
        with pytest.raises(q.QuantConfigError,
                           match="sequence-parallel"):
            make_seq_parallel_train_step(mesh, cfg)

    @requires_8
    def test_seq_axis_rejected_for_quant_zero(self):
        mesh_cfg = MeshConfig(data=2, fsdp=2, seq=2)
        cfg = cfg_for(mesh_cfg)
        mesh = make_mesh(mesh_cfg)
        with pytest.raises(q.QuantConfigError, match="seq"):
            q.make_quant_zero_train_step(mesh, cfg)


# --------------------------------------------------------- serving arm


@pytest.fixture(scope="module")
def serve_setup():
    cfg = PretrainConfig(
        model=ModelConfig(local_dim=16, global_dim=32, key_dim=8,
                          num_heads=4, num_blocks=2, num_annotations=64,
                          dtype="float32"),
        data=DataConfig(seq_len=64, batch_size=4))
    from proteinbert_tpu.models import proteinbert

    params = proteinbert.init(jax.random.PRNGKey(0), cfg.model)
    rng = np.random.default_rng(5)
    from proteinbert_tpu.data.vocab import ALPHABET

    alphabet = np.array(list(ALPHABET))
    seqs = ["".join(rng.choice(alphabet, size=int(n)))
            for n in rng.integers(8, 50, size=8)]
    return params, cfg, seqs


class TestServeQuant:
    # Documented weight-quantization serving bound at these tiny dims
    # (docs/serving.md): per-channel int8 weights on an UNTRAINED
    # d=16 trunk.
    PARITY_BOUND = 0.15

    def test_quantize_params_roundtrip_and_bytes(self, serve_setup):
        params, cfg, _ = serve_setup
        qp = q.quantize_params(params)
        back = q.dequantize_params(qp)
        # Structure preserved; >=2-D leaves quantized within one scale
        # quantum, 1-D leaves untouched.
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
            assert a.shape == b.shape
        flat_q = jax.tree.leaves(qp, is_leaf=q._is_quant_leaf)
        assert any(q._is_quant_leaf(x) for x in flat_q)
        ratio = q.param_bytes(qp) / q.param_bytes(params)
        assert ratio <= 0.40, ratio
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
            if a.ndim >= 2:
                scale = np.max(np.abs(np.asarray(a)), axis=-2,
                               keepdims=True) / 127.0
                assert np.all(np.abs(np.asarray(a) - np.asarray(b))
                              <= scale / 2 + 1e-7)
            else:
                np.testing.assert_array_equal(np.asarray(a),
                                              np.asarray(b))

    def test_bucketed_quant_arm_parity_and_sampling(self, serve_setup,
                                                    tmp_path):
        from proteinbert_tpu.obs import Telemetry, read_events
        from proteinbert_tpu.serve import Server

        params, cfg, seqs = serve_setup
        ev = str(tmp_path / "events.jsonl")
        tele = Telemetry(events_path=ev)
        fp = Server(params, cfg, max_batch=4, max_wait_s=0.005)
        qs = Server(params, cfg, max_batch=4, max_wait_s=0.005,
                    quant="int8", quant_parity_every=1, telemetry=tele)
        with fp, qs:
            # Warmup dummy batches must not consume the parity cadence
            # or count as LIVE parity samples (review fix): before any
            # real request the sample count is zero.
            assert qs.dispatcher.quant_report.get("parity_samples",
                                                  0) == 0
            assert qs.dispatcher._quant_batches == 0
            worst = 0.0
            for s in seqs:
                a = fp.embed(s, timeout=120)
                b = qs.embed(s, timeout=120)
                for k in a:
                    worst = max(worst,
                                float(np.max(np.abs(a[k] - b[k]))))
            go_a = fp.predict_go(seqs[0], timeout=120)
            go_b = qs.predict_go(seqs[0], timeout=120)
            stats = qs.stats()
        tele.close()
        assert 0.0 < worst <= self.PARITY_BOUND, worst
        assert float(np.max(np.abs(go_a - go_b))) <= self.PARITY_BOUND
        assert stats["quant"]["mode"] == "int8"
        assert stats["quant"]["parity_samples"] >= 1
        assert 0.0 < stats["quant"]["parity_max"] <= self.PARITY_BOUND
        assert stats["quant"]["weight_bytes_ratio"] <= 0.40
        recs = read_events(ev, strict=True)
        srs = [r for r in recs if r["event"] == "serve_request"]
        assert srs and all(r.get("quant") == "int8" for r in srs)
        sbs = [r for r in recs if r["event"] == "serve_batch"]
        assert sbs and all(r.get("quant") == "int8" for r in sbs)
        assert any(r.get("quant_parity_max") is not None for r in sbs)

    def test_ragged_quant_arm_parity(self, serve_setup):
        from proteinbert_tpu.serve import Server

        params, cfg, seqs = serve_setup
        fp = Server(params, cfg, max_batch=2, max_wait_s=0.005,
                    serve_mode="ragged")
        qs = Server(params, cfg, max_batch=2, max_wait_s=0.005,
                    serve_mode="ragged", quant="int8",
                    quant_parity_every=1)
        with fp, qs:
            worst = 0.0
            for s in seqs[:4]:
                a = fp.embed(s, timeout=120)
                b = qs.embed(s, timeout=120)
                for k in a:
                    worst = max(worst,
                                float(np.max(np.abs(a[k] - b[k]))))
            stats = qs.stats()
        assert 0.0 < worst <= self.PARITY_BOUND, worst
        assert stats["quant"]["parity_samples"] >= 1

    def test_ragged_rejects_act_quant(self, serve_setup):
        from proteinbert_tpu.serve import Server

        params, cfg, _ = serve_setup
        with pytest.raises(ValueError, match="int8_act"):
            Server(params, cfg, max_batch=2, serve_mode="ragged",
                   quant="int8_act")

    def test_act_arm_runs_and_stays_bounded(self, serve_setup):
        from proteinbert_tpu.serve import Server

        params, cfg, seqs = serve_setup
        fp = Server(params, cfg, max_batch=4, max_wait_s=0.005)
        qa = Server(params, cfg, max_batch=4, max_wait_s=0.005,
                    quant="int8_act")
        with fp, qa:
            a = fp.embed(seqs[0], timeout=120)
            b = qa.embed(seqs[0], timeout=120)
        worst = max(float(np.max(np.abs(a[k] - b[k]))) for k in a)
        # Activation fake-quant adds error on top of the weight arm;
        # documented looser bound.
        assert 0.0 < worst <= 2 * self.PARITY_BOUND, worst

    def test_fp32_trunk_parked_on_host_without_parity_shadow(
            self, serve_setup):
        """With no parity shadow the fp32 trunk has no device consumer,
        so resident HBM must hold ONLY the int8 weights (the footprint
        claim) — and the server still serves."""
        from proteinbert_tpu.serve import Server

        params, cfg, seqs = serve_setup
        srv = Server(params, cfg, max_batch=4, max_wait_s=0.005,
                     quant="int8")  # quant_parity_every defaults to 0
        assert srv.dispatcher.quant_report["fp32_resident"] == "host"
        assert all(isinstance(x, np.ndarray)
                   for x in jax.tree.leaves(srv.dispatcher.params))
        with srv:
            out = srv.embed(seqs[0], timeout=120)
        assert np.isfinite(out["global"]).all()
        # With the shadow on, both trunks stay resident by design.
        srv2 = Server(params, cfg, max_batch=4, quant="int8",
                      quant_parity_every=2)
        assert srv2.dispatcher.quant_report["fp32_resident"] == "device"
        srv2.abort()

    def test_fp32_arm_events_have_no_quant_fields(self, serve_setup,
                                                  tmp_path):
        """The documented contract is absent-means-fp32: a plain fp32
        server's serve_batch/serve_request events must not carry
        quant/quant_parity_max keys at all (not even as null)."""
        from proteinbert_tpu.obs import Telemetry, read_events
        from proteinbert_tpu.serve import Server

        params, cfg, seqs = serve_setup
        ev = str(tmp_path / "fp32_events.jsonl")
        tele = Telemetry(events_path=ev)
        with Server(params, cfg, max_batch=4, max_wait_s=0.005,
                    telemetry=tele) as srv:
            srv.embed(seqs[0], timeout=120)
        tele.close()
        recs = read_events(ev, strict=True)
        for r in recs:
            if r["event"] in ("serve_batch", "serve_request"):
                assert "quant" not in r, r
                assert "quant_parity_max" not in r, r

    def test_unknown_quant_mode_rejected(self, serve_setup):
        from proteinbert_tpu.serve import Server

        params, cfg, _ = serve_setup
        with pytest.raises(ValueError, match="quant"):
            Server(params, cfg, quant="int4")

    def test_serve_config_default_rides_run_config(self, serve_setup):
        from proteinbert_tpu.configs import ServeConfig
        from proteinbert_tpu.serve import Server

        params, cfg, _ = serve_setup
        qcfg = cfg.replace(serve=ServeConfig(quant="int8",
                                             quant_parity_every=3))
        srv = Server(params, qcfg, max_batch=4)
        assert srv.quant == "int8"
        assert srv.dispatcher.quant_parity_every == 3
        srv.abort()
        # Explicit ctor args override the config default.
        srv2 = Server(params, qcfg, max_batch=4, quant="fp32")
        assert srv2.quant == "fp32"
        assert srv2.dispatcher.qparams is None
        srv2.abort()


# ------------------------------------------------- trajectory sentinel


def test_trajectory_fits_quant_series(tmp_path):
    """tools/bench_trajectory.py fits the new quant series from
    bench_events.jsonl notes, with the ratio/parity series judged
    LOWER-is-better (a rising int8 wire ratio must flag as a
    regression, not an improvement)."""
    import sys

    sys.path.insert(0, "tools")
    import bench_trajectory as bt

    events = tmp_path / "bench_events.jsonl"
    lines = []
    seqn = 0

    def note(**fields):
        nonlocal seqn
        rec = {"v": 1, "event": "note", "seq": seqn, "t": float(seqn),
               "source": "bench", **fields}
        seqn += 1
        lines.append(json.dumps(rec))

    for ratio in (0.27, 0.28, 0.27, 0.55):  # regressing ratio (UP)
        note(kind="comm_quant", platform="cpu-virtual",
             int8_grad_wire_ratio=ratio, bf16_grad_wire_ratio=0.51)
    for rps, pmax in ((100.0, 0.02), (110.0, 0.021), (105.0, 0.02),
                      (104.0, 0.019)):
        note(kind="serve_quant_capture", platform="cpu",
             quant_requests_per_sec=rps, parity_max=pmax,
             weight_bytes_ratio=0.31)
    for smin in (0.8, 0.81, 0.8, 0.82):
        note(kind="heads_capture", platform="cpu",
             eval_score_min_quant=smin, eval_score_min=0.9)
    events.write_text("\n".join(lines) + "\n")

    verdict = bt.build_verdict([], str(events))
    s = verdict["series"]
    assert s["comm_bytes_int8_ratio/cpu-virtual"]["verdict"] \
        == "regression"
    assert not s["comm_bytes_int8_ratio/cpu-virtual"]["higher_is_better"]
    assert s["serve_quant_requests_per_sec/cpu"]["verdict"] == "ok"
    assert s["serve_quant_parity_max/cpu"]["verdict"] == "ok"
    assert s["heads_eval_score_min_quant/cpu"]["verdict"] == "ok"
    assert verdict["overall"] == "regression"
    assert not verdict["errors"]
