"""64-virtual-device pod-shape parity child (VERDICT round-6 item 5).

Extent-8 data collectives have never been constructed by any lower
tier (the in-suite mesh caps at 8 devices, the 16-device tier at
extent 4); this child runs realistic v5e-64 mesh shapes on 64 virtual
CPU devices and asserts the sharded step — including the ZeRO-1
sharded weight update this tier exists to validate at scale — is
numerically identical to the single-device step.

Usage: python tests/multidevice64_child.py
           {dp8-fsdp4-model2 | zero-dp8-fsdp4-model2 | dp16-sp4-bucketed}
Prints one JSON line with the compared losses.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Small dims, all divisible by the extents below (data*fsdp = 32).
MODEL = dict(local_dim=32, global_dim=64, key_dim=16, num_heads=4,
             num_blocks=2, num_annotations=64, dtype="float32")


def _cfg(mesh_cfg, parallel=None, **data_kw):
    from proteinbert_tpu.configs import (
        DataConfig, ModelConfig, OptimizerConfig, ParallelConfig,
        PretrainConfig, TrainConfig,
    )

    data = dict(seq_len=32, batch_size=64)
    data.update(data_kw)
    return PretrainConfig(
        model=ModelConfig(**MODEL),
        data=DataConfig(**data),
        optimizer=OptimizerConfig(learning_rate=1e-3, warmup_steps=10),
        mesh=mesh_cfg,
        parallel=parallel or ParallelConfig(),
        train=TrainConfig(max_steps=2),
    )


def _dense_parity(zero):
    """data=8 x fsdp=4 x model=2 (the v5e-64 flagship assignment):
    sharded train_step — replicated or ZeRO-1 — vs single-device."""
    import numpy as np

    import jax
    from proteinbert_tpu.configs import MeshConfig, ParallelConfig
    from proteinbert_tpu.data import (
        InMemoryPretrainingDataset, make_pretrain_iterator,
    )
    from proteinbert_tpu.data.synthetic import make_random_proteins
    from proteinbert_tpu.parallel import (
        batch_sharding, make_mesh, shard_train_state,
    )
    from proteinbert_tpu.train import create_train_state, train_step

    mesh_cfg = MeshConfig(data=8, fsdp=4, model=2)
    cfg = _cfg(mesh_cfg,
               parallel=ParallelConfig(zero_update=zero))
    rng = np.random.default_rng(0)
    seqs, ann = make_random_proteins(
        cfg.data.batch_size, rng, num_annotations=MODEL["num_annotations"],
        max_len=40)
    ds = InMemoryPretrainingDataset(seqs, ann, cfg.data.seq_len)
    batch = next(make_pretrain_iterator(ds, cfg.data.batch_size, seed=0))

    ref_state, ref_m = train_step(
        create_train_state(jax.random.PRNGKey(0), cfg), dict(batch), cfg)

    mesh = make_mesh(mesh_cfg)
    state = shard_train_state(
        create_train_state(jax.random.PRNGKey(0), cfg), mesh,
        zero_update=zero)
    bsh = batch_sharding(mesh)
    dbatch = {k: jax.device_put(v, bsh[k]) for k, v in batch.items()}
    if zero:
        from proteinbert_tpu.parallel import make_zero_train_step

        zstep = make_zero_train_step(mesh, cfg)
        new_state, m = zstep(state, dbatch)
    else:
        new_state, m = train_step(state, dbatch, cfg)

    ref_loss, got_loss = float(ref_m["loss"]), float(m["loss"])
    assert abs(got_loss - ref_loss) <= 2e-5 * max(1.0, abs(ref_loss)), (
        ref_loss, got_loss)
    max_err = 0.0
    for r, g in zip(jax.tree.leaves(ref_state.params),
                    jax.tree.leaves(new_state.params)):
        err = float(np.max(np.abs(
            np.asarray(r, np.float64)
            - np.asarray(jax.device_get(g), np.float64))))
        max_err = max(max_err, err)
    assert max_err < 2e-5, max_err
    out = {"mesh": dict(mesh.shape), "ref_loss": ref_loss,
           "sharded_loss": got_loss, "max_param_err": max_err}
    if zero:
        # The at-scale memory claim: per-chip Adam state ~1/8 (= the
        # data extent) of the fsdp-only layout.
        from proteinbert_tpu.parallel.zero import per_chip_state_bytes

        abstract = jax.eval_shape(
            lambda: create_train_state(jax.random.PRNGKey(0), cfg))
        rep = per_chip_state_bytes(mesh, abstract, zero_update=False)
        zer = per_chip_state_bytes(mesh, abstract, zero_update=True)
        assert zer["opt_state"] <= rep["opt_state"] / 4.0, (rep, zer)
        out["opt_state_bytes"] = {"replicated": rep["opt_state"],
                                  "zero": zer["opt_state"]}
    return out


def _sp4_bucketed():
    """data=16 x seq=4: mixed-length corpus -> bucketed lockstep batches
    -> the EXPLICIT seq-parallel step, extent-16 data collectives live."""
    import numpy as np

    import jax
    from proteinbert_tpu.configs import MeshConfig
    from proteinbert_tpu.data import InMemoryPretrainingDataset
    from proteinbert_tpu.data.dataset import make_bucketed_iterator
    from proteinbert_tpu.parallel import make_mesh
    from proteinbert_tpu.parallel.seq_parallel import (
        make_seq_parallel_train_step,
    )
    from proteinbert_tpu.train import create_train_state, train_step

    mesh_cfg = MeshConfig(data=16, seq=4)
    cfg = _cfg(mesh_cfg, seq_len=128, batch_size=16, buckets=(32, 128))
    rng = np.random.default_rng(0)
    seqs = []
    for i in range(96):
        n = (int(rng.integers(5, 28)) if i % 2
             else int(rng.integers(80, 120)))
        seqs.append("".join(
            rng.choice(list("ACDEFGHIKLMNPQRSTVWY"), size=n)))
    ann = (rng.random((96, MODEL["num_annotations"])) < 0.1)
    ds = InMemoryPretrainingDataset(seqs, ann, cfg.data.seq_len)

    mesh = make_mesh(mesh_cfg)
    sstep = make_seq_parallel_train_step(mesh, cfg)
    it = make_bucketed_iterator(ds, cfg.data.batch_size, cfg.data.buckets,
                                seed=3, num_epochs=1)
    widths, rows = set(), []
    for batch, _ in zip(it, range(4)):
        widths.add(batch["tokens"].shape[1])
        _, ref_m = train_step(
            create_train_state(jax.random.PRNGKey(0), cfg), dict(batch),
            cfg)
        _, sp_m = sstep(
            create_train_state(jax.random.PRNGKey(0), cfg), dict(batch))
        ref_loss, sp_loss = float(ref_m["loss"]), float(sp_m["loss"])
        assert np.isfinite(sp_loss)
        assert abs(sp_loss - ref_loss) <= 1e-4 * max(1.0, abs(ref_loss)), (
            ref_loss, sp_loss)
        rows.append({"L": int(batch["tokens"].shape[1]),
                     "ref_loss": ref_loss, "sp_loss": sp_loss})
    assert widths == {32, 128}, widths  # both buckets actually ran
    return {"mesh": dict(mesh.shape), "buckets": rows}


def main():
    scenario = sys.argv[1]
    import jax

    from proteinbert_tpu.utils.compat import request_cpu_devices

    request_cpu_devices(64)
    assert jax.device_count() == 64, jax.device_count()

    if scenario == "dp8-fsdp4-model2":
        out = _dense_parity(zero=False)
    elif scenario == "zero-dp8-fsdp4-model2":
        out = _dense_parity(zero=True)
    elif scenario == "dp16-sp4-bucketed":
        out = _sp4_bucketed()
    else:
        raise SystemExit(f"unknown scenario {scenario!r}")
    print(json.dumps({"scenario": scenario, "ok": True, **out}))


if __name__ == "__main__":
    main()
