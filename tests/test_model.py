"""Model tests: shapes, gradient coverage, length-parametricity, masking.

Mirrors what the reference's smoke driver eyeballs (reference
dummy_tests.py:96-143: shape/param-count via torchinfo.summary) but as
real assertions, plus regression tests for each paper-correction in the
SURVEY faithfulness ledger (#1-#4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from proteinbert_tpu.configs import ModelConfig
from proteinbert_tpu.data.vocab import PAD_ID, SOS_ID, EOS_ID, N_SPECIAL, VOCAB_SIZE
from proteinbert_tpu.models import proteinbert
from proteinbert_tpu.ops.attention import (
    global_attention_apply,
    global_attention_init,
)


def tiny_cfg(**kw):
    defaults = dict(
        local_dim=16, global_dim=32, key_dim=8, num_heads=4, num_blocks=2,
        num_annotations=64, dtype="float32",
    )
    defaults.update(kw)
    return ModelConfig(**defaults)


def make_batch(key, cfg, batch=4, seq_len=32):
    k1, k2 = jax.random.split(key)
    tokens = jax.random.randint(k1, (batch, seq_len), N_SPECIAL, VOCAB_SIZE)
    tokens = tokens.at[:, 0].set(SOS_ID).at[:, -1].set(EOS_ID)
    ann = (jax.random.uniform(k2, (batch, cfg.num_annotations)) < 0.05).astype(
        jnp.float32
    )
    return tokens, ann


def test_forward_shapes(key):
    cfg = tiny_cfg()
    params = proteinbert.init(key, cfg)
    tokens, ann = make_batch(key, cfg)
    local_logits, global_logits = jax.jit(
        proteinbert.apply, static_argnames="cfg"
    )(params, tokens, ann, cfg)
    assert local_logits.shape == (4, 32, cfg.vocab_size)
    assert global_logits.shape == (4, cfg.num_annotations)
    assert local_logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(local_logits)).all()
    assert np.isfinite(np.asarray(global_logits)).all()


def test_heads_emit_logits_not_probs(key):
    """Reference heads emit probabilities (modules.py:277-293, ledger #3);
    ours must emit logits — i.e. per-position local outputs must not sum
    to 1 under exp (they're unnormalized)."""
    cfg = tiny_cfg()
    params = proteinbert.init(key, cfg)
    tokens, ann = make_batch(key, cfg)
    local_logits, global_logits = proteinbert.apply(params, tokens, ann, cfg)
    sums = np.asarray(jnp.exp(local_logits).sum(-1))
    assert not np.allclose(sums, 1.0, atol=1e-3)
    g = np.asarray(global_logits)
    assert (g < 0).any() or (g > 1).any()


def test_all_params_receive_gradients(key):
    """Ledger #1 regression: the reference's attention-head params were
    invisible to autograd (modules.py:73-81). Every leaf here must get a
    nonzero gradient from a loss touching both heads."""
    cfg = tiny_cfg()
    params = proteinbert.init(key, cfg)
    tokens, ann = make_batch(key, cfg)

    def loss_fn(p):
        l, g = proteinbert.apply(p, tokens, ann, cfg)
        return jnp.abs(l).mean() + jnp.abs(g).mean()

    grads = jax.grad(loss_fn)(params)
    flat, _ = jax.tree_util.tree_flatten_with_path(grads)
    for path, g in flat:
        assert np.abs(np.asarray(g)).max() > 0, f"zero grad at {jax.tree_util.keystr(path)}"


def test_length_parametric(key):
    """Ledger #4 regression: one parameter set must serve multiple L
    (the reference LayerNorm hard-codes L, modules.py:148-151)."""
    cfg = tiny_cfg()
    params = proteinbert.init(key, cfg)
    for L in (16, 64, 128):
        tokens, ann = make_batch(key, cfg, batch=2, seq_len=L)
        local_logits, _ = proteinbert.apply(params, tokens, ann, cfg)
        assert local_logits.shape == (2, L, cfg.vocab_size)


def test_attention_softmax_over_sequence(key):
    """Ledger #2 regression: softmax must run over L — attention weights
    over the sequence sum to 1, verified indirectly: with V constant over
    L, output must equal that constant row regardless of scores."""
    B, L, C, G, H, k = 2, 10, 8, 16, 2, 4
    params = global_attention_init(key, C, G, k, H)
    local = jnp.broadcast_to(
        jax.random.normal(key, (B, 1, C)), (B, L, C)
    )  # constant over L
    global_ = jax.random.normal(jax.random.fold_in(key, 1), (B, G))
    out = global_attention_apply(params, local, global_)
    v = jax.nn.gelu(jnp.einsum("blc,hcv->bhlv", local, params["wv"]))
    expected = v[:, :, 0, :].reshape(B, G)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=1e-5)


def test_attention_pad_masking(key):
    """Padding positions must not influence the global track: outputs with
    garbage in padded local positions must match outputs with zeros there."""
    B, L, C, G, H, k = 2, 12, 8, 16, 2, 4
    params = global_attention_init(key, C, G, k, H)
    mask = jnp.array([[True] * 6 + [False] * 6] * B)
    base = jax.random.normal(key, (B, L, C))
    garbage = base + jnp.where(mask[..., None], 0.0, 100.0)
    global_ = jax.random.normal(jax.random.fold_in(key, 1), (B, G))
    out1 = global_attention_apply(params, base, global_, mask)
    out2 = global_attention_apply(params, garbage, global_, mask)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), atol=1e-5)


def test_scan_matches_unrolled(key):
    """lax.scan over stacked block params must equal the unrolled loop."""
    cfg_scan = tiny_cfg(scan_blocks=True)
    cfg_loop = tiny_cfg(scan_blocks=False)
    params_loop = proteinbert.init(key, cfg_loop)
    params_scan = dict(params_loop)
    params_scan["blocks"] = jax.tree.map(
        lambda *xs: jnp.stack(xs), *params_loop["blocks"]
    )
    tokens, ann = make_batch(key, cfg_scan)
    out_s = proteinbert.apply(params_scan, tokens, ann, cfg_scan)
    out_l = proteinbert.apply(params_loop, tokens, ann, cfg_loop)
    for a, b in zip(out_s, out_l):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_scan_unroll_matches(key):
    """Partial scan unroll is a scheduling knob — values and gradients
    must be bit-compatible with unroll=1, including a factor that does
    not divide num_blocks (lax.scan handles the remainder)."""
    cfg1 = tiny_cfg(remat=True, remat_policy="convs", num_blocks=5)
    params = proteinbert.init(key, cfg1)
    tokens, ann = make_batch(key, cfg1)

    def loss(p, c):
        l, g = proteinbert.apply(p, tokens, ann, c)
        return jnp.abs(l).mean() + jnp.abs(g).mean()

    g1 = jax.grad(loss)(params, cfg1)
    out1 = proteinbert.apply(params, tokens, ann, cfg1)
    for unroll in (2, 3):  # neither divides 5: remainder path covered
        cfg_u = tiny_cfg(remat=True, remat_policy="convs", num_blocks=5,
                         scan_unroll=unroll)
        out_u = proteinbert.apply(params, tokens, ann, cfg_u)
        for a, b in zip(out1, out_u):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)
        gu = jax.grad(loss)(params, cfg_u)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5
            ),
            g1,
            gu,
        )


def test_scan_unroll_plus_split_transpose_matches(key):
    """Both scheduling knobs TOGETHER (the bench's remat-convs-u2st
    variant) must still be value- and gradient-equivalent to the
    knob-off baseline — the sharded parity test alone compares the
    combo against itself on both sides and would miss a numerics
    change common to both paths."""
    cfg1 = tiny_cfg(remat=True, remat_policy="convs", num_blocks=5)
    cfg_c = tiny_cfg(remat=True, remat_policy="convs", num_blocks=5,
                     scan_unroll=2, scan_split_transpose=True)
    params = proteinbert.init(key, cfg1)
    tokens, ann = make_batch(key, cfg1)

    def loss(p, c):
        l, g = proteinbert.apply(p, tokens, ann, c)
        return jnp.abs(l).mean() + jnp.abs(g).mean()

    out1 = proteinbert.apply(params, tokens, ann, cfg1)
    out_c = proteinbert.apply(params, tokens, ann, cfg_c)
    for a, b in zip(out1, out_c):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        jax.grad(loss)(params, cfg1),
        jax.grad(loss)(params, cfg_c),
    )


def test_scan_split_transpose_matches(key):
    """_split_transpose restructures only the scan's TRANSPOSE (the
    backward); forward values must be identical and gradients must match
    the default scan's to numerical tolerance, with and without remat."""
    for kw in ({}, dict(remat=True, remat_policy="convs")):
        cfg1 = tiny_cfg(**kw)
        cfg_s = tiny_cfg(scan_split_transpose=True, **kw)
        params = proteinbert.init(key, cfg1)
        tokens, ann = make_batch(key, cfg1)

        def loss(p, c):
            l, g = proteinbert.apply(p, tokens, ann, c)
            return jnp.abs(l).mean() + jnp.abs(g).mean()

        out1 = proteinbert.apply(params, tokens, ann, cfg1)
        out_s = proteinbert.apply(params, tokens, ann, cfg_s)
        for a, b in zip(out1, out_s):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6)
        g1 = jax.grad(loss)(params, cfg1)
        gs = jax.grad(loss)(params, cfg_s)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5
            ),
            g1,
            gs,
        )


def test_remat_matches(key):
    cfg = tiny_cfg()
    cfg_r = tiny_cfg(remat=True)
    params = proteinbert.init(key, cfg)
    tokens, ann = make_batch(key, cfg)

    def loss(p, c):
        l, g = proteinbert.apply(p, tokens, ann, c)
        return jnp.abs(l).mean() + jnp.abs(g).mean()

    g1 = jax.grad(loss)(params, cfg)
    g2 = jax.grad(loss)(params, cfg_r)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        g1,
        g2,
    )


def test_remat_policy_validated():
    """Unknown policy names must fail loudly (typos would otherwise run
    silently at full-remat speed), and validation fires with remat off."""
    from proteinbert_tpu.models.proteinbert import remat_wrap

    with pytest.raises(ValueError, match="remat_policy"):
        remat_wrap(lambda *a: a, tiny_cfg(remat=True, remat_policy="conv"))
    with pytest.raises(ValueError, match="remat_policy"):
        remat_wrap(lambda *a: a, tiny_cfg(remat_policy="kv"))


def test_remat_convs_policy_matches(key):
    """The selective "convs" policy (save conv outputs, recompute the
    tail — the base preset's default) is a pure scheduling change: its
    gradients must equal the no-remat path exactly (full remat is
    covered against no-remat by test_remat_matches above)."""
    cfg = tiny_cfg()
    cfg_c = tiny_cfg(remat=True, remat_policy="convs")
    params = proteinbert.init(key, cfg)
    tokens, ann = make_batch(key, cfg)

    def loss(p, c):
        l, g = proteinbert.apply(p, tokens, ann, c)
        return jnp.abs(l).mean() + jnp.abs(g).mean()

    g1 = jax.grad(loss)(params, cfg)
    g2 = jax.grad(loss)(params, cfg_c)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        g1,
        g2,
    )


def test_param_count_scales():
    cfg = tiny_cfg()
    p = proteinbert.init(jax.random.PRNGKey(0), cfg)
    n = proteinbert.param_count(p)
    assert n > 0
    cfg_big = tiny_cfg(num_blocks=4)
    p_big = proteinbert.init(jax.random.PRNGKey(0), cfg_big)
    assert proteinbert.param_count(p_big) > n


def test_bfloat16_activations(key):
    """bf16 path stays finite and heads still return fp32."""
    cfg = tiny_cfg(dtype="bfloat16")
    params = proteinbert.init(key, cfg)
    tokens, ann = make_batch(key, cfg)
    l, g = proteinbert.apply(params, tokens, ann, cfg)
    assert l.dtype == jnp.float32 and g.dtype == jnp.float32
    assert np.isfinite(np.asarray(l)).all()
