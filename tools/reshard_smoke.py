#!/usr/bin/env python
"""Reshard smoke (ISSUE 11, tier-1 stage): save a tiny train state on
one CPU-virtual mesh, reshard it onto another through the real
`pbt reshard` verb (parallel/reshard.reshard_checkpoint), and assert

  - the round trip is byte-identical in the mesh-independent canonical
    form (params AND optimizer state, ZeRO-1 leg included),
  - the collective schedule's wire bytes were counted (same-device-set
    legs report a nonzero 'collective' schedule; the to-single-chip leg
    honestly reports 'host_staged'),
  - the emitted `reshard` events round-trip the schema validator.

Exit nonzero on any violation — this stage GATES (run_tier1.sh).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PBT_DISABLE_DONATION", "1")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()


def main() -> int:
    import dataclasses

    import jax

    from proteinbert_tpu.utils.compat import request_cpu_devices

    request_cpu_devices(8)

    from proteinbert_tpu.configs import (
        DataConfig, ModelConfig, OptimizerConfig, PretrainConfig,
        TrainConfig, save_config,
    )
    from proteinbert_tpu.obs import read_events
    from proteinbert_tpu.parallel.reshard import (
        mesh_from_config, parse_mesh_spec, reshard_checkpoint,
        states_byte_identical, target_template,
    )
    from proteinbert_tpu.train.checkpoint import Checkpointer

    if jax.device_count() < 8:
        print(f"SMOKE SKIP-FAIL: need 8 virtual CPU devices, have "
              f"{jax.device_count()}")
        return 2

    cfg = PretrainConfig(
        model=ModelConfig(local_dim=16, global_dim=32, key_dim=8,
                          num_heads=2, num_blocks=2, num_annotations=32,
                          dtype="float32"),
        data=DataConfig(seq_len=32, batch_size=4),
        optimizer=OptimizerConfig(warmup_steps=5),
        train=TrainConfig(seed=0, max_steps=1),
    )
    cfg42 = cfg.replace(mesh=dataclasses.replace(cfg.mesh, data=4, fsdp=2),
                        parallel=dataclasses.replace(cfg.parallel,
                                                     zero_update=True))
    failures = []
    with tempfile.TemporaryDirectory() as d:
        src = os.path.join(d, "src_4x2")
        mesh42 = mesh_from_config(cfg42.mesh)
        state = target_template(cfg42, mesh42, zero_update=True)
        ck = Checkpointer(src, async_save=False)
        ck.save(0, state, {"batches_consumed": 5})
        ck.close()
        save_config(cfg42, os.path.join(src, "config.json"))
        canonical = target_template(cfg42, None)

        events = os.path.join(d, "events.jsonl")
        # Leg 1 stays on the 8-device set (a real collective schedule);
        # legs 2/3 change the device set (honest host_staged reporting).
        legs = [("8x1", "collective"), ("1", "host_staged"),
                ("4x2", "host_staged")]
        prev = src
        for i, (spec, want_sched) in enumerate(legs):
            dst = os.path.join(d, f"leg{i}_{spec.replace('x', 'by')}")
            from proteinbert_tpu.obs import Telemetry

            tele = Telemetry(events_path=events)
            try:
                out = reshard_checkpoint(
                    prev, dst, target_mesh_cfg=parse_mesh_spec(spec),
                    telemetry=tele)
            finally:
                tele.close()
            print(json.dumps({"leg": f"{prev.split('/')[-1]}->{spec}",
                              **out}))
            if out["parity"] is not True:
                failures.append(f"leg {spec}: parity not verified")
            if out["schedule"] != want_sched:
                failures.append(f"leg {spec}: schedule {out['schedule']} "
                                f"!= expected {want_sched}")
            if want_sched == "collective" \
                    and out["wire_bytes"].get("total", 0) <= 0:
                failures.append(f"leg {spec}: collective schedule with "
                                "zero wire bytes")
            # Mesh-independent canonical parity vs the ORIGINAL state.
            ck = Checkpointer(dst, async_save=False)
            back, data_state = ck.restore(canonical)
            ck.close()
            if data_state != {"batches_consumed": 5}:
                failures.append(f"leg {spec}: data_state lost "
                                f"({data_state})")
            if not states_byte_identical(state, back):
                failures.append(f"leg {spec}: restored state is NOT "
                                "byte-identical to the original")
            prev = dst

        recs = read_events(events, strict=True)
        reshards = [r for r in recs if r["event"] == "reshard"]
        if len(reshards) != len(legs):
            failures.append(f"{len(reshards)} reshard events != "
                            f"{len(legs)} legs")

    if failures:
        print("RESHARD SMOKE FAILED: " + "; ".join(failures),
              file=sys.stderr)
        return 1
    print("reshard smoke OK: 4x2 -> 8x1 -> 1 -> 4x2 byte-identical "
          "(ZeRO-1 layout), schedules byte-accounted, events valid",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
