#!/usr/bin/env python
"""One-pass trunk kernel smoke (ISSUE 16, tier-1 stage).

Tiny shapes through the real dispatch entries (interpret mode on CPU —
the same kernel Mosaic compiles on TPU), gates:

  1. PACKED BIT-IDENTITY — the one-pass kernel (local track + ragged
     attention in ONE grid program) vs the two-kernel Pallas
     composition on a training-style layout AND a serving-style layout
     (bucket-quantized spans with <pad> tails via real_mask),
     bit-identical on BOTH outputs, counted on
     `onepass_kernel_path_total{path=pallas,reason=packed}` with ZERO
     reason=segments fallbacks.
  2. SINGLE KERNEL BOUNDARY — the one-pass trace contains exactly ONE
     pallas_call (the composition two): the inter-track activation
     never leaves VMEM, so there is no HBM round-trip to spill.
  3. DENSE BIT-IDENTITY — the S=1 entry vs the dense composition,
     including a fully-padded batch-class row (uniform-softmax
     semantics preserved), counted as path=pallas/reason=dense.
  4. VJP — gradient parity of the custom-VJP backward vs autodiff
     through the one-hot reference, <= 1e-4.
  5. FORCED OVERRIDE — PBT_FORCE_REFERENCE_KERNEL routes a fresh
     one-pass trace onto the reference composition (reason=forced),
     bit-identical to it.
  6. INT8 IN-KERNEL DEQUANT — `quantize_params` int8 weights + scales
     dequantized inside the kernel bit-match HLO-dequantizing the same
     tree first (both entries).
  7. NOTE SCHEMA — a synthetic `note(kind=onepass_capture)` record
     round-trips the events validator (the sentinel-series contract).

Exit nonzero on any violation — this stage GATES (run_tier1.sh).
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

GRAD_BOUND = 1e-4


def main() -> int:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from proteinbert_tpu.configs import ModelConfig
    from proteinbert_tpu.kernels import attention as ka
    from proteinbert_tpu.kernels import fused_block as fb
    from proteinbert_tpu.kernels import one_pass as op
    from proteinbert_tpu.models import proteinbert
    from proteinbert_tpu.parallel.quant import quantize_params

    failures = []

    def gate(ok: bool, msg: str) -> None:
        print(("PASS " if ok else "FAIL ") + msg)
        if not ok:
            failures.append(msg)

    B, L, C, S = 2, 128, 128, 4
    G, KD, H = 64, 16, 4
    cfg = ModelConfig(local_dim=C, global_dim=G, key_dim=KD, num_heads=H,
                      num_blocks=1, num_annotations=16, dtype="float32")
    block = proteinbert.block_init(jax.random.PRNGKey(0), cfg)
    track = {k: block[k] for k in ("narrow_conv", "wide_conv",
                                   "local_ln1", "local_dense",
                                   "local_ln2")}
    attn = block["attention"]
    x = jax.random.normal(jax.random.PRNGKey(1), (B, L, C), jnp.float32)
    bc = jax.random.normal(jax.random.PRNGKey(2), (B, S, C), jnp.float32)
    g = jax.random.normal(jax.random.PRNGKey(3), (B, S, G), jnp.float32)
    seg = np.zeros((B, L), np.int32)
    seg[0, :60] = 1
    seg[0, 60:110] = 2
    seg[1, :L] = 1
    seg = jnp.asarray(seg)

    gate(op.pallas_onepass_supported(C, G, L, S, KD, H, "float32"),
         "guard: (128, 64, 128, 4) fp32 shape has a one-pass plan")

    def one(tp, ap, xx, bb, gg, ss):
        return op.fused_onepass_segments(tp, ap, xx, bb, gg, ss)

    def two(tp, ap, xx, bb, gg, ss):
        loc = fb.fused_local_track_segments(tp, xx, bb, ss, 1, 5, True)
        return loc, ka.fused_packed_attention(ap, loc, gg, ss,
                                              interpret=True)

    # ---- gate 1: packed bit-identity + counter coverage --------------
    before = dict(op.ONEPASS_PATH_TOTAL)
    got = jax.jit(one)(track, attn, x, bc, g, seg)
    delta_p = (op.ONEPASS_PATH_TOTAL.get(("pallas", "packed"), 0)
               - before.get(("pallas", "packed"), 0))
    delta_s = (op.ONEPASS_PATH_TOTAL.get(("reference", "segments"), 0)
               - before.get(("reference", "segments"), 0))
    want = jax.jit(two)(track, attn, x, bc, g, seg)
    bit = all(np.array_equal(np.asarray(a), np.asarray(b))
              for a, b in zip(got, want))
    gate(bit, "packed one-pass bit-matches the two-kernel composition")
    gate(delta_p >= 1 and delta_s == 0,
         f"packed dispatch on the one-pass path (pallas/packed "
         f"+{delta_p}, reference/segments +{delta_s})")

    # Serving layout: spans bucket-quantized, tails are <pad>.
    real = np.zeros((B, L), bool)
    real[0, :41] = True
    real[0, 60:60 + 30] = True
    real[1, :100] = True
    real = jnp.asarray(real)
    got_m = op.fused_onepass_segments(track, attn, x, bc, g, seg,
                                      real_mask=real)
    loc_m = fb.fused_local_track_segments(track, x, bc, seg, 1, 5, True)
    want_m = (loc_m, ka.fused_packed_attention(attn, loc_m, g, seg,
                                               real_mask=real,
                                               interpret=True))
    bit_m = all(np.array_equal(np.asarray(a), np.asarray(b))
                for a, b in zip(got_m, want_m))
    gate(bit_m, "serving real_mask layout bit-matches the composition")

    # ---- gate 2: one kernel boundary (the HBM round-trip claim) ------
    calls_one = str(jax.make_jaxpr(one)(
        track, attn, x, bc, g, seg)).count("pallas_call")
    calls_two = str(jax.make_jaxpr(two)(
        track, attn, x, bc, g, seg)).count("pallas_call")
    gate(calls_one == 1 and calls_two == 2,
         f"one-pass trace has exactly 1 pallas_call boundary "
         f"(composition {calls_two}) — inter-track activation stays "
         "in VMEM")

    # ---- gate 3: dense bit-identity (incl. an all-pad row) -----------
    bc_d, g_d = bc[:, 0, :], g[:, 0, :]
    pad = np.ones((B, L), bool)
    pad[1, :] = False
    pad = jnp.asarray(pad)
    before = dict(op.ONEPASS_PATH_TOTAL)
    got_d = op.fused_onepass_dense(track, attn, x, bc_d, g_d,
                                   pad_mask=pad)
    delta_d = (op.ONEPASS_PATH_TOTAL.get(("pallas", "dense"), 0)
               - before.get(("pallas", "dense"), 0))
    loc_d = fb.fused_local_track(track, x, bc_d, 1, 5, True)
    want_d = (loc_d, ka.fused_global_attention(attn, loc_d, g_d, pad,
                                               interpret=True))
    bit_d = all(np.array_equal(np.asarray(a), np.asarray(b))
                for a, b in zip(got_d, want_d))
    gate(bit_d and delta_d >= 1,
         f"dense one-pass bit-matches the dense composition on the "
         f"Pallas path (pallas/dense +{delta_d}, all-pad row keeps "
         "uniform softmax)")

    # ---- gate 4: VJP gradient parity ---------------------------------
    seg_oh = jnp.asarray(
        (np.asarray(seg)[:, :, None] == np.arange(1, S + 1)),
        jnp.float32)
    ones_real = jnp.ones((B, L, 1), jnp.float32)

    def loss_f(tp, ap, xx, bb, gg):
        lo, at = op.fused_onepass_segments(tp, ap, xx, bb, gg, seg)
        return jnp.sum(lo ** 2) + jnp.sum(at ** 2)

    def loss_r(tp, ap, xx, bb, gg):
        lo, at = op.onepass_oh_reference(tp, ap, xx, bb, gg, seg_oh,
                                         ones_real)
        return jnp.sum(lo ** 2) + jnp.sum(at ** 2)

    gf = jax.grad(loss_f, argnums=(0, 1, 2, 3, 4))(track, attn, x, bc, g)
    gr = jax.grad(loss_r, argnums=(0, 1, 2, 3, 4))(track, attn, x, bc, g)
    gdiff = max(float(np.abs(np.asarray(a) - np.asarray(b)).max())
                for a, b in zip(jax.tree.leaves(gf), jax.tree.leaves(gr)))
    gate(gdiff <= GRAD_BOUND,
         f"custom-VJP gradient parity {gdiff:.2e} <= {GRAD_BOUND}")

    # ---- gate 5: forced-reference override ---------------------------
    os.environ[fb.FORCE_REFERENCE_ENV] = "1"
    try:
        before = dict(op.ONEPASS_PATH_TOTAL)
        # Fresh lambdas: re-jitting a cached function object would hit
        # the trace cache and skip the trace-time env read.
        got_fo = jax.jit(lambda tp, ap, xx, bb, gg: (
            op.fused_onepass_segments(tp, ap, xx, bb, gg, seg)))(
            track, attn, x, bc, g)
        want_fo = jax.jit(lambda tp, ap, xx, bb, gg: (
            lambda loc: (loc, ka.fused_packed_attention(
                ap, loc, gg, seg, interpret=True)))(
            fb.fused_local_track_segments(tp, xx, bb, seg, 1, 5, True)))(
            track, attn, x, bc, g)
        bumps = (op.ONEPASS_PATH_TOTAL.get(("reference", "forced"), 0)
                 - before.get(("reference", "forced"), 0))
        bit_fo = all(np.array_equal(np.asarray(a), np.asarray(b))
                     for a, b in zip(got_fo, want_fo))
        gate(bumps >= 1 and bit_fo,
             "PBT_FORCE_REFERENCE_KERNEL routes one-pass onto the "
             f"reference path (forced +{bumps}, bit_identical={bit_fo})")
    finally:
        del os.environ[fb.FORCE_REFERENCE_ENV]

    # ---- gate 6: int8 in-kernel dequant bit-identity -----------------
    qtrack, qattn = quantize_params(track), quantize_params(attn)
    dtrack, dattn = fb.dequant_params(qtrack), fb.dequant_params(qattn)
    got_q = op.fused_onepass_segments(qtrack, qattn, x, bc, g, seg)
    want_q = op.fused_onepass_segments(dtrack, dattn, x, bc, g, seg)
    bit_q = all(np.array_equal(np.asarray(a), np.asarray(b))
                for a, b in zip(got_q, want_q))
    got_qd = op.fused_onepass_dense(qtrack, qattn, x, bc_d, g_d)
    want_qd = op.fused_onepass_dense(dtrack, dattn, x, bc_d, g_d)
    bit_qd = all(np.array_equal(np.asarray(a), np.asarray(b))
                 for a, b in zip(got_qd, want_qd))
    gate(bit_q and bit_qd,
         "int8 in-kernel dequant bit-matches HLO dequant (both entries)")

    # ---- gate 7: onepass_capture note schema -------------------------
    from proteinbert_tpu.obs.events import validate_record

    rec = {"v": 1, "event": "note", "seq": 0, "t": 0.0,
           "source": "bench", "kind": "onepass_capture",
           "platform": "cpu", "onepass_speedup_x": 1.0,
           "parity_max_abs_diff": 0.0, "mfu_raw": 0.01,
           "mfu_effective": 0.01}
    try:
        validate_record(rec)
        ok = True
    except ValueError as e:
        ok = False
        print(f"  validator rejected a well-formed capture: {e}")
    bad_rejected = False
    try:
        validate_record({**rec, "onepass_speedup_x": 0.0})
    except ValueError:
        bad_rejected = True
    gate(ok and bad_rejected,
         "note(kind=onepass_capture) schema round-trip + negative")

    print(f"\n{len(failures)} failure(s)")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
