#!/usr/bin/env python
"""Quantized-collectives + int8-serving smoke (ISSUE 12, tier-1 stage).

One tiny model on an 8-device CPU-virtual 4x2 mesh, three gates:

  1. TRAIN PARITY — two int8-reduction ZeRO-1 steps vs the replicated
     fp32 reference on the same batch: step-1 loss identical (same
     corruption ops, same key), final param deviation within the
     documented quantization bounds (int8 <= 1e-3, bf16 <= 5e-4,
     nonzero — rounding really happened), the fp32-PAYLOAD explicit
     control within 1e-6 (isolates harness error from quantization
     error), and the int8 step bit-DETERMINISTIC across two runs from
     the same state (the multi-host-lockstep property: noise is a pure
     function of the replicated step key + replica index).
  2. WIRE BYTES — grad-reduction wire bytes of the compiled int8 step
     <= 0.30x the fp32-payload explicit reduce-scatter's, counted from
     the compiled HLO (zero.collective_wire_bytes_from_hlo: output
     shapes + replica_groups — never inferred from source dtypes).
  3. SERVE PARITY — a quant=int8 server (weight-only int8 executables,
     fp32 parity shadow every batch) vs a fp32 server on identical
     requests: per-request deviation within the documented 0.15 bound,
     live parity sampling recorded, quantized trunk weight bytes
     <= 0.40x fp32, and the emitted serve events (with their `quant`
     fields) schema-valid.

Exit nonzero on any violation — this stage GATES (run_tier1.sh).
"""

from __future__ import annotations

import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PBT_DISABLE_DONATION", "1")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

INT8_PARAM_BOUND = 1e-3   # docs/distributed.md, quantized reduction
BF16_PARAM_BOUND = 5e-4
CONTROL_BOUND = 1e-6      # fp32-payload explicit harness
SERVE_PARITY_BOUND = 0.15  # docs/serving.md, int8 arm
WEIGHT_RATIO_BOUND = 0.40  # tiny dims; large dims approach 0.26
WIRE_RATIO_BOUND = 0.30   # ROADMAP item 1 acceptance


def main() -> int:
    import numpy as np

    from proteinbert_tpu.utils.compat import request_cpu_devices

    request_cpu_devices(8)
    import jax

    from proteinbert_tpu.configs import (
        DataConfig, MeshConfig, ModelConfig, OptimizerConfig,
        ParallelConfig, PretrainConfig, TrainConfig,
    )
    from proteinbert_tpu.data import (
        InMemoryPretrainingDataset, make_pretrain_iterator,
    )
    from proteinbert_tpu.data.vocab import ALPHABET
    from proteinbert_tpu.obs import Telemetry, read_events
    from proteinbert_tpu.parallel import (
        batch_sharding, make_mesh, make_zero_train_step,
        shard_train_state,
    )
    from proteinbert_tpu.parallel.quant import make_quant_zero_train_step
    from proteinbert_tpu.parallel.sharding import state_sharding
    from proteinbert_tpu.parallel.zero import (
        collective_wire_bytes_from_hlo, grad_reduce_wire_bytes,
    )
    from proteinbert_tpu.serve import Server
    from proteinbert_tpu.train import create_train_state, train_step

    failures = []

    def gate(ok: bool, msg: str) -> None:
        print(("PASS " if ok else "FAIL ") + msg)
        if not ok:
            failures.append(msg)

    mesh_cfg = MeshConfig(data=4, fsdp=2)
    model = ModelConfig(local_dim=16, global_dim=32, key_dim=8,
                        num_heads=4, num_blocks=2, num_annotations=64,
                        dtype="float32")

    def cfg_for(parallel):
        return PretrainConfig(
            model=model,
            data=DataConfig(seq_len=32, batch_size=16),
            optimizer=OptimizerConfig(learning_rate=1e-3,
                                      warmup_steps=10),
            mesh=mesh_cfg, parallel=parallel,
            train=TrainConfig(max_steps=2))

    rng = np.random.default_rng(0)
    alphabet = np.array(list(ALPHABET))
    seqs = ["".join(rng.choice(alphabet, size=int(n)))
            for n in rng.integers(10, 30, size=16)]
    ann = (rng.random((16, 64)) < 0.05).astype(np.float32)
    ds = InMemoryPretrainingDataset(seqs, ann, 32)
    batch = next(make_pretrain_iterator(ds, 16, seed=0))

    # ---- 1. train parity -------------------------------------------
    ref_cfg = cfg_for(ParallelConfig())
    ref = create_train_state(jax.random.PRNGKey(0), ref_cfg)
    ref, rm1 = train_step(ref, dict(batch), ref_cfg)
    ref, _ = train_step(ref, dict(batch), ref_cfg)

    mesh = make_mesh(mesh_cfg)
    bsh = batch_sharding(mesh)
    dbatch = {k: jax.device_put(v, bsh[k]) for k, v in batch.items()}

    def two_steps(step, cfg):
        st = shard_train_state(
            create_train_state(jax.random.PRNGKey(0), cfg), mesh,
            zero_update=True)
        st, m1 = step(st, dbatch)
        st, _ = step(st, dbatch)
        return st, m1

    def param_dev(st):
        worst = 0.0
        for r, g in zip(jax.tree.leaves(ref.params),
                        jax.tree.leaves(st.params)):
            worst = max(worst, float(np.max(np.abs(
                np.asarray(r, np.float64)
                - np.asarray(jax.device_get(g), np.float64)))))
        return worst

    int8_cfg = cfg_for(ParallelConfig(zero_update=True,
                                      grad_reduce_dtype="int8"))
    st8, m8 = two_steps(make_zero_train_step(mesh, int8_cfg), int8_cfg)
    dev8 = param_dev(st8)
    gate(abs(float(m8["loss"]) - float(rm1["loss"])) <= 2e-5,
         f"int8 step-1 loss matches fp32 reference "
         f"(d={abs(float(m8['loss']) - float(rm1['loss'])):.2e})")
    gate(0.0 < dev8 <= INT8_PARAM_BOUND,
         f"int8 2-step param deviation {dev8:.2e} within "
         f"(0, {INT8_PARAM_BOUND}]")

    bf_cfg = cfg_for(ParallelConfig(zero_update=True,
                                    grad_reduce_dtype="bf16"))
    stb, _ = two_steps(make_zero_train_step(mesh, bf_cfg), bf_cfg)
    devb = param_dev(stb)
    gate(0.0 < devb <= BF16_PARAM_BOUND,
         f"bf16 2-step param deviation {devb:.2e} within "
         f"(0, {BF16_PARAM_BOUND}]")

    ctrl_step = make_quant_zero_train_step(mesh, int8_cfg,
                                           payload="fp32")
    stc, _ = two_steps(ctrl_step, int8_cfg)
    devc = param_dev(stc)
    gate(devc <= CONTROL_BOUND,
         f"fp32-payload explicit control deviation {devc:.2e} <= "
         f"{CONTROL_BOUND}")

    st8b, _ = two_steps(make_zero_train_step(mesh, int8_cfg), int8_cfg)
    identical = all(
        np.array_equal(np.asarray(jax.device_get(a)),
                       np.asarray(jax.device_get(b)))
        for a, b in zip(jax.tree.leaves(st8.params),
                        jax.tree.leaves(st8b.params)))
    gate(identical, "int8 stochastic rounding is deterministic "
                    "(same state key -> bit-identical params)")

    # ---- 2. wire bytes from compiled HLO ---------------------------
    abstract = jax.eval_shape(
        lambda: create_train_state(jax.random.PRNGKey(0), int8_cfg))
    sh = state_sharding(mesh, abstract, zero_update=True)
    st_abs = jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        abstract, sh)
    batch_abs = {
        k: jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=bsh[k])
        for k, v in batch.items()}

    def reduce_wire(step):
        hlo = step.lower(st_abs, batch_abs).compile().as_text()
        return grad_reduce_wire_bytes(
            collective_wire_bytes_from_hlo(hlo, mesh.size))

    wire8 = reduce_wire(make_zero_train_step(mesh, int8_cfg))
    wire32 = reduce_wire(ctrl_step)
    ratio = wire8 / max(wire32, 1)
    gate(ratio <= WIRE_RATIO_BOUND,
         f"int8 grad-reduction wire bytes {wire8} <= "
         f"{WIRE_RATIO_BOUND}x fp32 reduce-scatter {wire32} "
         f"(ratio {ratio:.3f})")

    # ---- 3. quantized serve arm ------------------------------------
    serve_cfg = PretrainConfig(
        model=model, data=DataConfig(seq_len=64, batch_size=4))
    params = create_train_state(jax.random.PRNGKey(1), serve_cfg).params
    reqs = ["".join(rng.choice(alphabet, size=int(n)))
            for n in rng.integers(8, 50, size=12)]
    events_path = os.path.join(
        tempfile.mkdtemp(prefix="pbt_quant_smoke_"), "events.jsonl")
    tele = Telemetry(events_path=events_path)
    fp32_srv = Server(params, serve_cfg, max_batch=4, max_wait_s=0.005)
    q_srv = Server(params, serve_cfg, max_batch=4, max_wait_s=0.005,
                   quant="int8", quant_parity_every=1, telemetry=tele)
    with fp32_srv, q_srv:
        worst = 0.0
        for s in reqs:
            a = fp32_srv.embed(s, timeout=120)
            b = q_srv.embed(s, timeout=120)
            for k in a:
                worst = max(worst, float(np.max(np.abs(a[k] - b[k]))))
        stats = q_srv.stats()
    tele.close()
    q = stats["quant"] or {}
    gate(worst <= SERVE_PARITY_BOUND,
         f"int8-arm per-request parity {worst:.4f} <= "
         f"{SERVE_PARITY_BOUND} vs the fp32 arm")
    gate(bool(q.get("parity_samples")),
         f"live parity shadow sampled "
         f"{q.get('parity_samples', 0)} batch(es)")
    gate(q.get("weight_bytes_ratio", 1.0) <= WEIGHT_RATIO_BOUND,
         f"quantized trunk weight bytes ratio "
         f"{q.get('weight_bytes_ratio')} <= {WEIGHT_RATIO_BOUND}")
    recs = read_events(events_path, strict=True)  # raises on invalid
    quant_tagged = [r for r in recs if r.get("quant") == "int8"]
    gate(len(quant_tagged) > 0,
         f"{len(quant_tagged)} schema-valid event(s) carry "
         f"quant='int8' ({len(recs)} total)")

    if failures:
        print(f"\nquant smoke: {len(failures)} gate(s) FAILED")
        return 1
    print("\nquant smoke: all gates passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
