"""Recover per-window step rates from a CUMULATIVE metrics stream.

The r3 sustained run (experiments/sustained_r3/) recorded only the
cumulative-since-warmup `steps_per_sec` at each log point — the very
limitation that left its throughput collapse unattributed for two
rounds (BASELINE.md). But the cumulative stream DETERMINES the window
stream: with anchor a and cumulative rate c_i at step s_i, the wall
time since anchor is (s_i - a)/c_i, so the i-th window's duration is

    dt_i = (s_i - a)/c_i - (s_{i-1} - a)/c_{i-1}

and its rate is (s_i - s_{i-1})/dt_i. This tool applies that inversion
per phase (a preemption seam re-anchors the timer in the resumed
process), flags windows slower than half the median, and reports how
many of them are adjacent to an eval/checkpoint cadence boundary —
turning the already-recorded r3 stream into an attribution, no
hardware required. (Runs recorded from round 4 on carry native
window_* rates and don't need this inversion; it remains the tool for
auditing any cumulative-only stream.)

Usage:
  python tools/reconstruct_windows.py METRICS_JSONL \
      [--seam STEP] [--cadence N] [--log-every N]
Prints one JSON line; exit 0 on success.
"""

from __future__ import annotations

import argparse
import json


def load_train_records(path):
    ded = {}
    with open(path) as f:
        for line in f:
            try:
                r = json.loads(line)
            except ValueError:
                continue
            if "loss" in r and "lr" in r and r.get("steps_per_sec"):
                ded[r["step"]] = r  # keep LAST record per step (seam re-log)
    return ded


def phase_windows(ded, phase_steps, anchor):
    out = []
    for s0, s1 in zip(phase_steps, phase_steps[1:]):
        c0, c1 = ded[s0]["steps_per_sec"], ded[s1]["steps_per_sec"]
        if not (c0 > 0 and c1 > 0):
            continue
        dt = (s1 - anchor) / c1 - (s0 - anchor) / c0
        if dt > 0:
            out.append({"step": s1, "n_steps": s1 - s0, "dt_s": dt,
                        "rate": (s1 - s0) / dt})
    return out


def reconstruct(path, seam=None, cadence=None, log_every=None):
    ded = load_train_records(path)
    steps = sorted(ded)
    if len(steps) < 3:
        return {"error": f"too few usable records in {path}"}
    phases = ([[s for s in steps if s <= seam], [s for s in steps if s > seam]]
              if seam else [steps])
    windows = []
    for ph in phases:
        if len(ph) < 2:
            continue
        # The timer's anchor is the phase's start (warmup excluded); the
        # first logged step minus one log interval approximates it, and
        # any anchor error decays as 1/c_i with distance from the start.
        anchor = (ph[0] - (log_every or (ph[1] - ph[0]))
                  if ph is phases[0] or not seam else seam)
        windows += phase_windows(ded, ph, anchor)
    rates = sorted(w["rate"] for w in windows)
    med = rates[len(rates) // 2]
    total_t = sum(w["dt_s"] for w in windows)
    slow = [w for w in windows if w["rate"] < 0.5 * med]
    excess = sum(w["dt_s"] - w["n_steps"] / med for w in slow)
    out = {
        "path": path,
        "windows": len(windows),
        "median_rate": round(med, 3),
        "total_time_s": round(total_t, 1),
        "overall_rate": round(sum(w["n_steps"] for w in windows) / total_t, 3),
        "slow_windows": [
            {"step": w["step"], "rate": round(w["rate"], 2),
             "dt_s": round(w["dt_s"], 1)} for w in slow],
        "slow_time_s": round(sum(w["dt_s"] for w in slow), 1),
        "slow_time_frac": round(sum(w["dt_s"] for w in slow) / total_t, 3),
        "excess_time_s": round(excess, 1),
    }
    if cadence and log_every:
        adj = [w["step"] for w in slow
               if (w["step"] - log_every) % cadence == 0]
        out["boundary_adjacent"] = adj
        out["boundary_adjacent_frac"] = (round(len(adj) / len(slow), 3)
                                         if slow else None)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("metrics_jsonl")
    ap.add_argument("--seam", type=int,
                    help="preemption step: the resumed process re-anchors "
                         "its timer, so windows are reconstructed per phase")
    ap.add_argument("--cadence", type=int,
                    help="eval/checkpoint cadence for boundary-adjacency")
    ap.add_argument("--log-every", type=int, dest="log_every")
    args = ap.parse_args()
    out = reconstruct(args.metrics_jsonl, seam=args.seam,
                      cadence=args.cadence, log_every=args.log_every)
    print(json.dumps(out))
    return 1 if "error" in out else 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
