"""Recover per-window step rates from a CUMULATIVE metrics stream.

The r3 sustained run (experiments/sustained_r3/) recorded only the
cumulative-since-warmup `steps_per_sec` at each log point — the very
limitation that left its throughput collapse unattributed for two
rounds (BASELINE.md). But the cumulative stream DETERMINES the window
stream: with anchor a and cumulative rate c_i at step s_i, the wall
time since anchor is (s_i - a)/c_i, so the i-th window's duration is

    dt_i = (s_i - a)/c_i - (s_{i-1} - a)/c_{i-1}

and its rate is (s_i - s_{i-1})/dt_i. This tool applies that inversion
per phase (a preemption seam re-anchors the timer in the resumed
process), flags windows slower than half the median, and reports how
many of them are adjacent to an eval/checkpoint cadence boundary —
turning the already-recorded r3 stream into an attribution, no
hardware required. (Runs recorded from round 4 on carry native
window_* rates and don't need this inversion; it remains the tool for
auditing any cumulative-only stream.)

A second mode, `--wall`, reads the wall-clock `t` stamped on every log
record (round 4+) instead of inverting rates. The two views are
complementary BY DESIGN: `window_mfu`/`steps_per_sec` DISCOUNT
eval/checkpoint brackets (StepTimer.discount — the train-rate numbers
stay honest), so a bracket that blocks the host shows up ONLY as a gap
in `t`. `--wall` finds log intervals whose wall duration exceeds the
run's median by >THRESH seconds, and tags each with whether it sits on
the eval/ckpt cadence and whether the next window latched
ckpt_in_flight — the full wall-time attribution the discounted stream
cannot give. Preemption seams are reported separately from gaps:
re-log seams auto-detected from the file-order step reset, monotonic
seams (preemption save at the kill step itself) declared via --seam.
A --seam that coincides with a detected re-log reset is suppressed
(same preemption, already under `seams`); one elsewhere in the stream
is honored even when an unrelated reset exists.

Overlapped boundaries (checkpoint fetch+write hidden behind training,
StepTimer.overlap) appear in the records as `window_overlap_s`; --wall
sums them into `overlapped_boundary_s` and stamps any gap that still
carries overlap seconds — so "boundary cost went to ~zero" is read off
the attribution (no gap + nonzero overlapped seconds), not assumed.

Usage:
  python tools/reconstruct_windows.py METRICS_JSONL \
      [--seam STEP] [--cadence N] [--log-every N] \
      [--wall [--gap-thresh S]]
Prints one JSON line; exit 0 on success.
"""

from __future__ import annotations

import argparse
import json


def load_train_records(path):
    ded = {}
    with open(path) as f:
        for line in f:
            try:
                r = json.loads(line)
            except ValueError:
                continue
            # "step" in the filter too: a step-less record (a writer
            # that logs aggregate lines without one) must be skipped,
            # not KeyError the whole reconstruction.
            if ("step" in r and "loss" in r and "lr" in r
                    and r.get("steps_per_sec")):
                ded[r["step"]] = r  # keep LAST record per step (seam re-log)
    return ded


def phase_windows(ded, phase_steps, anchor):
    out = []
    for s0, s1 in zip(phase_steps, phase_steps[1:]):
        c0, c1 = ded[s0]["steps_per_sec"], ded[s1]["steps_per_sec"]
        if not (c0 > 0 and c1 > 0):
            continue
        dt = (s1 - anchor) / c1 - (s0 - anchor) / c0
        if dt > 0:
            out.append({"step": s1, "n_steps": s1 - s0, "dt_s": dt,
                        "rate": (s1 - s0) / dt})
    return out


def reconstruct(path, seam=None, cadence=None, log_every=None):
    ded = load_train_records(path)
    steps = sorted(ded)
    if len(steps) < 3:
        return {"error": f"too few usable records in {path}"}
    phases = ([[s for s in steps if s <= seam], [s for s in steps if s > seam]]
              if seam else [steps])
    windows = []
    for ph in phases:
        if len(ph) < 2:
            continue
        # The timer's anchor is the phase's start (warmup excluded); the
        # first logged step minus one log interval approximates it, and
        # any anchor error decays as 1/c_i with distance from the start.
        anchor = (ph[0] - (log_every or (ph[1] - ph[0]))
                  if ph is phases[0] or not seam else seam)
        windows += phase_windows(ded, ph, anchor)
    med = _median([w["rate"] for w in windows])
    total_t = sum(w["dt_s"] for w in windows)
    slow = [w for w in windows if w["rate"] < 0.5 * med]
    excess = sum(w["dt_s"] - w["n_steps"] / med for w in slow)
    out = {
        "path": path,
        "windows": len(windows),
        "median_rate": round(med, 3),
        "total_time_s": round(total_t, 1),
        "overall_rate": round(sum(w["n_steps"] for w in windows) / total_t, 3),
        "slow_windows": [
            {"step": w["step"], "rate": round(w["rate"], 2),
             "dt_s": round(w["dt_s"], 1)} for w in slow],
        "slow_time_s": round(sum(w["dt_s"] for w in slow), 1),
        "slow_time_frac": round(sum(w["dt_s"] for w in slow) / total_t, 3),
        "excess_time_s": round(excess, 1),
    }
    if cadence and log_every:
        _boundary_adjacency(out, [w["step"] for w in slow],
                            cadence, log_every)
    return out


def _median(vals):
    return sorted(vals)[len(vals) // 2]


def _boundary_adjacency(out, steps, cadence, log_every):
    """Tag which flagged steps sit one log interval past an eval/ckpt
    cadence boundary — shared by both modes so they cannot diverge in
    how they classify the same boundary."""
    adj = [s for s in steps if (s - log_every) % cadence == 0]
    out["boundary_adjacent"] = adj
    out["boundary_adjacent_frac"] = (round(len(adj) / len(steps), 3)
                                     if steps else None)


def wall_gaps(path, cadence=None, log_every=None, gap_thresh=10.0,
              seam=None):
    """Attribute wall-clock gaps the discounted rate stream excludes.

    A gap is a log interval whose `t` span exceeds median + gap_thresh.
    Preemption seams (restart + restore + recompile — not brackets) are
    kept out of the gap list two ways, covering both real resume shapes:

    - RE-LOG seams are detected from file order: a step that does not
      advance starts a new segment (the resumed process restored from a
      cadence checkpoint BELOW the kill step and re-logs forward).
      Intervals are computed within segments only; each between-segment
      span goes under `seams`. No dedup — it would splice phase-2 wall
      clocks onto phase-1 steps and misattribute the restart to a
      bracket (often a boundary-adjacent one, since cadence checkpoints
      are where restores land).
    - MONOTONIC seams (the preemption save wrote at the kill step
      itself, so phase 2's steps strictly advance and no reset exists
      in the stream) cannot be detected and must be declared: the span
      containing the caller's `seam` step is moved to `seams`.

    Unlike the inversion mode, this needs no rate fields: records are
    kept on `loss`+`lr`+`t` alone, so pre-warmup log points (which
    carry no steps_per_sec yet) still bound their intervals.
    """
    recs = []
    with open(path) as f:
        for line in f:
            try:
                r = json.loads(line)
            except ValueError:
                continue
            # "step" in the filter alongside loss/lr/t: a step-less
            # record must be skipped, not KeyError the segment split.
            if ("step" in r and "loss" in r and "lr" in r
                    and r.get("t") is not None):
                recs.append(r)
    if len(recs) < 3:
        return {"error": f"too few t-stamped records in {path}"}
    segments, cur = [], [recs[0]]
    for r in recs[1:]:
        if r["step"] == cur[-1]["step"]:
            # A duplicated log line (flush retry, double writer) is a
            # record to DROP, not a reset: starting a new segment here
            # would fabricate a zero-duration seam and split real
            # intervals. Only a strict step DECREASE is a re-log reset.
            continue
        if r["step"] < cur[-1]["step"]:
            segments.append(cur)
            cur = [r]
        else:
            cur.append(r)
    segments.append(cur)
    # An explicit seam is suppressed only when it falls INSIDE a
    # detected between-segment span — i.e. it declares the same
    # preemption the re-log reset already reports (the resumed segment
    # re-crosses the kill step as a normal interval that must not be
    # re-classified). A monotonic preemption elsewhere in the stream
    # keeps its declared seam even when an unrelated re-log reset was
    # detected.
    if seam is not None and any(
            segments[i][0]["step"] <= seam <= segments[i - 1][-1]["step"]
            for i in range(1, len(segments))):
        seam = None
    spans, seams = [], []
    for i, seg in enumerate(segments):
        if i:
            prev = segments[i - 1][-1]
            seams.append({"after_step": prev["step"],
                          "resumed_at": seg[0]["step"],
                          "dt_s": round(seg[0]["t"] - prev["t"], 1)})
        for r0, r1 in zip(seg, seg[1:]):
            if seam is not None and r0["step"] <= seam < r1["step"]:
                seams.append({"after_step": r0["step"],
                              "resumed_at": r1["step"],
                              "dt_s": round(r1["t"] - r0["t"], 1)})
                continue
            spans.append({"step": r1["step"], "dt_s": r1["t"] - r0["t"],
                          "ckpt_in_flight":
                              bool(r1.get("ckpt_in_flight")),
                          # Overlapped-boundary seconds recorded inside
                          # this window (StepTimer.overlap): checkpoint
                          # fetch+write that ran HIDDEN behind training.
                          # An overlapped boundary should NOT produce a
                          # gap — the overlap_s column is its wall-time
                          # attribution (the stall a synchronous
                          # boundary would have cost here instead).
                          "overlap_s":
                              float(r1.get("window_overlap_s") or 0.0)})
    if not spans:
        return {"error": f"no within-segment intervals in {path}"}
    med = _median([sp["dt_s"] for sp in spans])
    gaps = [sp for sp in spans if sp["dt_s"] > med + gap_thresh]
    total = (sum(sp["dt_s"] for sp in spans)
             + sum(sm["dt_s"] for sm in seams))
    gap_excess = sum(sp["dt_s"] - med for sp in gaps)
    overlapped = sum(sp["overlap_s"] for sp in spans)
    out = {
        "path": path, "intervals": len(spans),
        "median_interval_s": round(med, 2),
        "total_wall_s": round(total, 1),
        "gaps": [{"step": sp["step"], "dt_s": round(sp["dt_s"], 1),
                  "ckpt_in_flight": sp["ckpt_in_flight"],
                  **({"overlap_s": round(sp["overlap_s"], 1)}
                     if sp["overlap_s"] else {})}
                 for sp in gaps],
        "gap_excess_s": round(gap_excess, 1),
        "gap_excess_frac": round(gap_excess / total, 3) if total else None,
        # Boundary seconds the run HID behind compute (overlapped
        # checkpoint pipeline) — wall time that does not appear in any
        # gap precisely because it was overlapped.
        "overlapped_boundary_s": round(overlapped, 1),
        "seams": seams,
    }
    if cadence and log_every:
        _boundary_adjacency(out, [g["step"] for g in out["gaps"]],
                            cadence, log_every)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("metrics_jsonl")
    ap.add_argument("--seam", type=int,
                    help="preemption step: the resumed process re-anchors "
                         "its timer, so windows are reconstructed per phase")
    ap.add_argument("--cadence", type=int,
                    help="eval/checkpoint cadence for boundary-adjacency")
    ap.add_argument("--log-every", type=int, dest="log_every")
    ap.add_argument("--wall", action="store_true",
                    help="attribute wall-clock t gaps instead of "
                         "inverting the discounted rate stream")
    ap.add_argument("--gap-thresh", type=float, default=10.0,
                    dest="gap_thresh",
                    help="seconds over the median interval that makes "
                         "a wall gap (--wall mode)")
    args = ap.parse_args()
    if args.wall:
        out = wall_gaps(args.metrics_jsonl, cadence=args.cadence,
                        log_every=args.log_every,
                        gap_thresh=args.gap_thresh, seam=args.seam)
    else:
        out = reconstruct(args.metrics_jsonl, seam=args.seam,
                          cadence=args.cadence, log_every=args.log_every)
    print(json.dumps(out))
    return 1 if "error" in out else 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
