"""Opportunistic TPU capture daemon (VERDICT r2 item 1).

The axon tunnel to the one real chip flaps for hours; both prior rounds'
driver bench captures landed in the CPU fallback because the tunnel
happened to be down at round end. Treat it as an availability problem:
poll cheaply all session, and the MOMENT a probe succeeds run the full
bench sweep, refreshing bench_last_tpu.json with every variant.

Run detached:  nohup python tools/tpu_watch.py >> tpu_watch.log 2>&1 &
Exits 0 after a successful sweep, 3 on deadline without ever reaching
the TPU. To chain the heavier hardware experiments automatically while
the tunnel is proven up, set PBT_WATCH_AFTER_SWEEP to a shell command
(e.g. "python examples/transfer_experiment.py --scale full"); it runs
best-effort after the sweep persists, bounded by PBT_WATCH_HOOK_TIMEOUT
(default 7200 s, process group killed on timeout), BEFORE the daemon
exits — so do not also start experiments manually on exit 0 when the
hook is set.

Status is mirrored to tpu_watch_status.json for cheap polling.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STATUS_PATH = os.path.join(REPO, "tpu_watch_status.json")

sys.path.insert(0, REPO)
from bench import atomic_json_dump, probe_tpu  # noqa: E402

PROBE_TIMEOUT = int(os.environ.get("PBT_WATCH_PROBE_TIMEOUT", 90))
POLL_WAIT = int(os.environ.get("PBT_WATCH_POLL_WAIT", 120))
DEADLINE_H = float(os.environ.get("PBT_WATCH_HOURS", 11))
SWEEP_TIMEOUT = int(os.environ.get("PBT_WATCH_SWEEP_TIMEOUT", 2700))
HARD_FAIL_CAP = int(os.environ.get("PBT_WATCH_HARD_FAIL_CAP", 10))
SWEEP_FAIL_CAP = int(os.environ.get("PBT_WATCH_SWEEP_FAIL_CAP", 3))
# Parsed at import like every other knob: a malformed value must fail at
# startup, not at the single success moment hours later.
HOOK_TIMEOUT = int(os.environ.get("PBT_WATCH_HOOK_TIMEOUT", 7200))


def put_status(**kv):
    kv["at"] = time.strftime("%Y-%m-%dT%H:%M:%S%z")
    kv["pid"] = os.getpid()  # lets the single-instance guard see us
    try:
        atomic_json_dump(kv, STATUS_PATH)
    except OSError as e:  # status mirror is best-effort; never die on it
        print(f"[tpu_watch] could not write status: {e}", flush=True)


def probe():
    """(ok, hard_failure_reason_or_None).

    A probe timeout is the normal down-tunnel signature (blackhole
    hang). Anything else — wrong platform, nonzero rc — LOOKS
    deterministic, but a flap can also surface as a fast init failure,
    so the caller logs it loudly and keeps watching rather than dying;
    only an unbroken streak of such failures is treated as hopeless.
    """
    ok, reason = probe_tpu(timeout=PROBE_TIMEOUT, attempts=1)
    hard = not ok and "timed out" not in reason
    return ok, (reason if hard else None)


def main():
    # Single-instance guard: two daemons probe-succeeding together would
    # run contending sweeps on the one chip and persist skewed timings.
    # The pid must still belong to a tpu_watch process — a bare
    # /proc/<pid> check would lock new watchers out forever once the OS
    # recycles an exited watcher's pid.
    if os.path.exists(STATUS_PATH):
        try:
            prev = json.load(open(STATUS_PATH))
            pid = prev.get("pid")
            if pid and pid != os.getpid():
                with open(f"/proc/{pid}/cmdline", "rb") as f:
                    cmdline = f.read().decode(errors="replace")
                if "tpu_watch" in cmdline:
                    print(f"[tpu_watch] another watcher (pid {pid}) is "
                          "alive; exiting", flush=True)
                    return 2
        except (OSError, ValueError):
            pass
    t0 = time.time()
    n = 0
    hard_streak = 0
    sweep_failures = 0
    put_status(status="watching", probes=0)
    while time.time() - t0 < DEADLINE_H * 3600:
        n += 1
        ok, hard_fail = probe()
        if hard_fail:
            hard_streak += 1
            print(f"[tpu_watch] probe {n}: non-timeout failure "
                  f"({hard_streak} consecutive) — {hard_fail}",
                  flush=True)
            put_status(status="hard_failure_retrying", probes=n,
                       reason=hard_fail, streak=hard_streak)
            if hard_streak >= HARD_FAIL_CAP:
                print(f"[tpu_watch] {hard_streak} consecutive "
                      "non-timeout failures; giving up", flush=True)
                put_status(status="hard_failure", probes=n,
                           reason=hard_fail)
                return 4
            time.sleep(POLL_WAIT)
            continue
        hard_streak = 0
        if ok:
            print(f"[tpu_watch] probe {n}: TPU UP — running full sweep",
                  flush=True)
            put_status(status="sweeping", probes=n)
            env = dict(os.environ,
                       PBT_BENCH_PROBE_ATTEMPTS="1",
                       PBT_BENCH_PROBE_TIMEOUT=str(PROBE_TIMEOUT))
            try:
                out = subprocess.run(
                    [sys.executable, os.path.join(REPO, "bench.py")],
                    cwd=REPO, env=env, capture_output=True, text=True,
                    timeout=SWEEP_TIMEOUT)
            except subprocess.TimeoutExpired:
                # bench.py persists after every variant, so whatever ran
                # is already in bench_last_tpu.json; keep watching.
                print("[tpu_watch] sweep timed out (tunnel dropped "
                      "mid-run?); partial results persisted", flush=True)
                put_status(status="sweep_timeout", probes=n)
                continue
            print(out.stderr, flush=True)
            print(out.stdout, flush=True)
            lines = out.stdout.strip().splitlines()
            rec = {}
            try:
                rec = json.loads(lines[-1]) if lines else {}
            except ValueError:
                pass
            if rec.get("platform") == "tpu":
                after = os.environ.get("PBT_WATCH_AFTER_SWEEP")
                if after:
                    # Chain the heavier hardware experiments while the
                    # tunnel is PROVEN up (e.g. PBT_WATCH_AFTER_SWEEP=
                    # "python examples/transfer_experiment.py --scale
                    # full") instead of telling the operator to start
                    # them by hand — up-windows are too rare to waste
                    # on reaction time. Bounded and best-effort: the
                    # sweep capture above is already safe.
                    print(f"[tpu_watch] sweep captured; running "
                          f"after-sweep hook: {after}", flush=True)
                    put_status(status="after_sweep_hook", probes=n,
                               record=rec, hook=after)
                    try:
                        # Own session so a timeout can kill the WHOLE
                        # process group — run(shell=True) would kill
                        # only the sh wrapper and leave a compound
                        # command's experiment processes hammering the
                        # one shared chip.
                        import signal

                        proc = subprocess.Popen(
                            after, shell=True, cwd=REPO,
                            start_new_session=True)
                        try:
                            proc.wait(timeout=HOOK_TIMEOUT)
                            print(f"[tpu_watch] hook rc="
                                  f"{proc.returncode}", flush=True)
                        except subprocess.TimeoutExpired:
                            os.killpg(proc.pid, signal.SIGKILL)
                            print("[tpu_watch] after-sweep hook timed "
                                  "out; process group killed",
                                  flush=True)
                    except Exception as e:  # hook is best-effort; the
                        # sweep capture (and terminal status) must win
                        print(f"[tpu_watch] after-sweep hook failed: "
                              f"{e}", flush=True)
                # Terminal status LAST so pollers never read a stale
                # mid-hook state after the daemon exits.
                put_status(status="captured", probes=n, record=rec)
                print("[tpu_watch] full TPU sweep captured; exiting",
                      flush=True)
                return 0
            if out.returncode != 0:
                # A real bench failure (all variants failed, crash) is
                # NOT a tunnel flap — say so, don't diagnose it as one,
                # and don't hammer the one shared chip with identical
                # failing sweeps for 11 hours: cap the retries.
                sweep_failures += 1
                put_status(status="sweep_failed", probes=n,
                           returncode=out.returncode,
                           failures=sweep_failures)
                print(f"[tpu_watch] bench exited rc={out.returncode} "
                      f"({sweep_failures}/{SWEEP_FAIL_CAP}); see log "
                      "above", flush=True)
                if sweep_failures >= SWEEP_FAIL_CAP:
                    print("[tpu_watch] repeated on-TPU bench failures; "
                          "giving up so the chip stays free", flush=True)
                    put_status(status="sweep_failed_cap", probes=n,
                               returncode=out.returncode)
                    return 5
            else:
                put_status(status="sweep_fell_back", probes=n)
                print("[tpu_watch] sweep fell back to CPU; keep watching",
                      flush=True)
        else:
            if n % 10 == 1:
                print(f"[tpu_watch] probe {n}: tunnel down "
                      f"({(time.time() - t0) / 60:.0f} min elapsed)",
                      flush=True)
            put_status(status="watching", probes=n)
        time.sleep(POLL_WAIT)
    put_status(status="deadline", probes=n)
    return 3


if __name__ == "__main__":
    sys.exit(main())
