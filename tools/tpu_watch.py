"""Opportunistic TPU capture daemon (VERDICT r2 item 1).

The axon tunnel to the one real chip flaps for hours; both prior rounds'
driver bench captures landed in the CPU fallback because the tunnel
happened to be down at round end. Treat it as an availability problem:
poll cheaply all session, and the MOMENT a probe succeeds run the full
bench sweep, refreshing bench_last_tpu.json with every variant.

Run detached:  nohup python tools/tpu_watch.py >> tpu_watch.log 2>&1 &
Exit codes: 0 after a successful sweep; 2 another watcher is alive;
3 deadline without ever reaching the TPU; 4 repeated non-timeout probe
failures; 5 repeated on-TPU bench failures; 6 sweep timeouts (repeated,
or one whose orphan drain would cross the deadline); 7 tunnel up but
too little deadline left to land even one variant (the window is left
to the round driver's own bench).
To chain the heavier hardware experiments automatically while the
tunnel is proven up, set PBT_WATCH_AFTER_SWEEP to a shell command
(e.g. "python examples/transfer_experiment.py --scale full"); it runs
best-effort after the sweep persists, bounded by PBT_WATCH_HOOK_TIMEOUT
(default 7200 s, process group killed on timeout; <=0 means UNBOUNDED),
BEFORE the daemon exits — so do not also start experiments manually on
exit 0 when the hook is set.

Status is mirrored to tpu_watch_status.json for cheap polling.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STATUS_PATH = os.path.join(REPO, "tpu_watch_status.json")

sys.path.insert(0, REPO)
from bench import (  # noqa: E402
    LAST_GOOD_PATH, atomic_json_dump, build_variants,
    last_good_captured_at, probe_tpu, stale_age_hours, stale_warn_hours,
    variant_timeout,
)


def _default_sweep_timeout():
    """Sized from the variant list, not a constant (ADVICE r3, medium):
    each of the TPU variants is individually bounded by
    PBT_BENCH_VARIANT_TIMEOUT, so a healthy cold-cache first sweep can
    legitimately take nearly N x that; a fixed 45-min cap SIGKILLed it
    before 'captured', and the after-sweep hook never fired.
    gate_pallas=False gives the UNGATED variant count — an upper bound,
    exactly right for a timeout. (It does NOT avoid the jax import:
    build_variants pulls in configs and transitively jax. That import is
    a one-time startup cost and creates no PJRT client — backend init is
    lazy — so the one-client-per-chip invariant still holds; ADVICE r4.)
    """
    try:
        n = len(build_variants(True, gate_pallas=False)[0])
    except Exception:
        # Generous upper bound, deliberately ABOVE the current list
        # size: an exact count here silently under-times the sweep the
        # moment a variant is added (the mid-sweep SIGKILL this dynamic
        # sizing exists to prevent).
        n = 24
    return n * variant_timeout() + 600


PROBE_TIMEOUT = int(os.environ.get("PBT_WATCH_PROBE_TIMEOUT", 90))
POLL_WAIT = int(os.environ.get("PBT_WATCH_POLL_WAIT", 120))
DEADLINE_H = float(os.environ.get("PBT_WATCH_HOURS", 11))
# Env override wins when nonzero; 0/unset derives from the variant list.
SWEEP_TIMEOUT = (int(os.environ.get("PBT_WATCH_SWEEP_TIMEOUT", 0))
                 or _default_sweep_timeout())
HARD_FAIL_CAP = int(os.environ.get("PBT_WATCH_HARD_FAIL_CAP", 10))
SWEEP_FAIL_CAP = int(os.environ.get("PBT_WATCH_SWEEP_FAIL_CAP", 3))
# Sweep TIMEOUTS get their own cap (ADVICE r3): each one means the
# daemon held the chip for the whole sweep budget without finishing —
# likely a mid-run tunnel drop, worth a few retries but not an
# unbounded loop of multi-hour SIGKILLed sweeps.
SWEEP_TIMEOUT_CAP = int(os.environ.get("PBT_WATCH_SWEEP_TIMEOUT_CAP", 4))
# Parsed at import like every other knob: a malformed value must fail at
# startup, not at the single success moment hours later.
HOOK_TIMEOUT = int(os.environ.get("PBT_WATCH_HOOK_TIMEOUT", 7200))


# The headline row's captured_at; every status write derives a CURRENT
# age from it so pollers always see the staleness signal (a startup-only
# field was erased by the first in-loop put_status and pollers almost
# never saw it). Refreshed after any sweep that may have rewritten the
# record — else a just-captured sweep would be reported weeks stale.
LAST_GOOD_STAMP = [None]


def refresh_last_good_stamp():
    try:
        with open(LAST_GOOD_PATH) as f:
            LAST_GOOD_STAMP[0] = last_good_captured_at(json.load(f))
    except (OSError, ValueError):
        pass


# Status transitions also land on a telemetry events stream (obs.events
# `note` records) beside the status mirror — so `pbt diagnose` /
# tools/validate_events.py read the watcher's history in the SAME format
# as training runs, instead of this tool keeping a private one. The
# mirror file stays (cheap point-in-time polling); the stream adds the
# ordered history a post-mortem wants. Keyed by STATUS_PATH's directory
# so tests that repoint the mirror repoint the stream too.
_EVENT_LOGS = {}


def _event_log():
    path = os.path.join(os.path.dirname(os.path.abspath(STATUS_PATH)),
                        "tpu_watch_events.jsonl")
    log = _EVENT_LOGS.get(path)
    if log is None:
        try:
            from proteinbert_tpu.obs.events import EventLog

            log = _EVENT_LOGS[path] = EventLog(path)
        except Exception as e:  # best-effort, like the status mirror
            print(f"[tpu_watch] events stream unavailable: {e}", flush=True)
            _EVENT_LOGS[path] = False
    return log or None


def put_status(**kv):
    kv["at"] = time.strftime("%Y-%m-%dT%H:%M:%S%z")
    kv["pid"] = os.getpid()  # lets the single-instance guard see us
    age = stale_age_hours(LAST_GOOD_STAMP[0])
    if age is not None:
        kv.setdefault("last_good_age_h", round(age, 1))
    try:
        atomic_json_dump(kv, STATUS_PATH)
    except OSError as e:  # status mirror is best-effort; never die on it
        print(f"[tpu_watch] could not write status: {e}", flush=True)
    ev = _event_log()
    if ev is not None:
        # The bench record is already persisted in bench_last_tpu.json;
        # keep the stream lean.
        ev.emit("note", source="tpu_watch",
                **{k: v for k, v in kv.items() if k != "record"})


def probe():
    """(ok, hard_failure_reason_or_None).

    A probe timeout is the normal down-tunnel signature (blackhole
    hang). Anything else — wrong platform, nonzero rc — LOOKS
    deterministic, but a flap can also surface as a fast init failure,
    so the caller logs it loudly and keeps watching rather than dying;
    only an unbroken streak of such failures is treated as hopeless.
    """
    ok, reason = probe_tpu(timeout=PROBE_TIMEOUT, attempts=1)
    hard = not ok and "timed out" not in reason
    return ok, (reason if hard else None)


def main():
    # Single-instance guard: two daemons probe-succeeding together would
    # run contending sweeps on the one chip and persist skewed timings.
    # The pid must still belong to a tpu_watch process — a bare
    # /proc/<pid> check would lock new watchers out forever once the OS
    # recycles an exited watcher's pid.
    if os.path.exists(STATUS_PATH):
        try:
            prev = json.load(open(STATUS_PATH))
            pid = prev.get("pid")
            if pid and pid != os.getpid():
                with open(f"/proc/{pid}/cmdline", "rb") as f:
                    cmdline = f.read().decode(errors="replace")
                if "tpu_watch" in cmdline:
                    print(f"[tpu_watch] another watcher (pid {pid}) is "
                          "alive; exiting", flush=True)
                    return 2
        except (OSError, ValueError):
            pass
    t0 = time.time()
    n = 0
    hard_streak = 0
    sweep_failures = 0
    sweep_timeouts = 0
    # Age guard (VERDICT r4 weak #5): if the only TPU evidence on disk
    # is old, say so LOUDLY at startup — the whole point of this daemon
    # is that a fresh capture is overdue, and the operator reading this
    # log must not mistake a stale 1.4x for current truth. The stamp is
    # resolved from the HEADLINE row (shared helper: a recent partial
    # sweep restamps the file-level captured_at without re-measuring
    # the headline shape) and cached so EVERY status write carries a
    # current last_good_age_h for pollers.
    refresh_last_good_stamp()
    age = stale_age_hours(LAST_GOOD_STAMP[0])
    if age is not None and age > stale_warn_hours():
        print(f"[tpu_watch] WARNING: last-good TPU record is "
              f"{age:.0f}h old (> {stale_warn_hours():.0f}h) — "
              "its numbers predate recent commits; a fresh "
              "sweep capture is REQUIRED to trust vs_baseline",
              flush=True)
    put_status(status="watching", probes=0, sweep_timeout_s=SWEEP_TIMEOUT)
    while time.time() - t0 < DEADLINE_H * 3600:
        n += 1
        ok, hard_fail = probe()
        if hard_fail:
            hard_streak += 1
            print(f"[tpu_watch] probe {n}: non-timeout failure "
                  f"({hard_streak} consecutive) — {hard_fail}",
                  flush=True)
            put_status(status="hard_failure_retrying", probes=n,
                       reason=hard_fail, streak=hard_streak)
            if hard_streak >= HARD_FAIL_CAP:
                print(f"[tpu_watch] {hard_streak} consecutive "
                      "non-timeout failures; giving up", flush=True)
                put_status(status="hard_failure", probes=n,
                           reason=hard_fail)
                return 4
            time.sleep(POLL_WAIT)
            continue
        hard_streak = 0
        if ok:
            print(f"[tpu_watch] probe {n}: TPU UP — running full sweep",
                  flush=True)
            # A sweep that STARTS near the watcher deadline must not
            # run its full budget past it: on a shared chip the round
            # driver's own bench follows the deadline, and an overhang
            # sweep would contend with (and skew) that measurement.
            # bench's first variant always gets the full
            # variant_timeout (uncapped by its wall budget), so with
            # less deadline than that even a clamped sweep would be
            # SIGKILLed mid-first-variant with NOTHING persisted and
            # the kill misdiagnosed as a tunnel drop — leave such a
            # window to the driver's own bench instead.
            remaining_dl = DEADLINE_H * 3600 - (time.time() - t0)
            if remaining_dl < variant_timeout() + 120:
                print("[tpu_watch] tunnel is up but the deadline is "
                      "inside one variant's budget; leaving the chip "
                      "to the round driver's bench", flush=True)
                put_status(status="deadline_before_sweep", probes=n)
                return 7
            sweep_to = min(SWEEP_TIMEOUT, int(remaining_dl))
            put_status(status="sweeping", probes=n, sweep_budget_s=sweep_to)
            env = dict(os.environ,
                       PBT_BENCH_PROBE_ATTEMPTS="1",
                       PBT_BENCH_PROBE_TIMEOUT=str(PROBE_TIMEOUT),
                       # The watcher wants the FULL sweep when time
                       # allows: its bound is the clamped sweep budget,
                       # not bench's impatient-caller default. When
                       # clamped, hand bench the budget minus a small
                       # stop margin so it winds down BETWEEN variants
                       # (persisting rows): bench's own child-timeout
                       # clamp bounds any overshoot past its budget to
                       # ~60s, so 120s suffices — a bigger margin would
                       # forfeit measurement time from exactly the
                       # scarce capture windows this daemon exists for.
                       PBT_BENCH_MAX_SECONDS=str(
                           max(1, sweep_to - 120)
                           if sweep_to < SWEEP_TIMEOUT else 0))
            try:
                out = subprocess.run(
                    [sys.executable, os.path.join(REPO, "bench.py")],
                    cwd=REPO, env=env, capture_output=True, text=True,
                    timeout=sweep_to)
            except subprocess.TimeoutExpired:
                # bench.py persists after every variant, so whatever ran
                # is already in bench_last_tpu.json; keep watching —
                # but capped: each timeout burned the full sweep budget
                # on the one shared chip.
                sweep_timeouts += 1
                refresh_last_good_stamp()  # partial rows persisted
                print(f"[tpu_watch] sweep timed out after {sweep_to}s "
                      f"({sweep_timeouts}/{SWEEP_TIMEOUT_CAP}; tunnel "
                      "dropped mid-run?); partial results persisted",
                      flush=True)
                put_status(status="sweep_timeout", probes=n,
                           timeouts=sweep_timeouts)
                if sweep_timeouts >= SWEEP_TIMEOUT_CAP:
                    print("[tpu_watch] repeated sweep timeouts; giving up "
                          "so the chip stays free", flush=True)
                    put_status(status="sweep_timeout_cap", probes=n)
                    return 6
                # The SIGKILLed sweep's in-flight --run-index child is
                # NOT in our process group; it self-destructs via its
                # own SIGALRM up to variant_timeout+60s after ITS start.
                # Wait that bound out before re-probing so a fresh sweep
                # never measures under contention with the orphan on the
                # one shared chip (the skew the single-instance guard
                # exists to prevent).
                # Bound the drain by the remaining deadline (ADVICE r4:
                # an unconditional 960s sleep can overstay DEADLINE_H)
                # and tell status pollers we're draining, not stalled.
                # If the deadline can't absorb a FULL drain, exit
                # instead: a truncated drain followed by another loop
                # iteration would probe-succeed and launch a fresh
                # multi-hour sweep under contention with the orphan —
                # the exact skew the drain exists to prevent — while
                # overstaying the deadline by up to SWEEP_TIMEOUT.
                drain = variant_timeout() + 60
                remaining = DEADLINE_H * 3600 - (time.time() - t0)
                if remaining <= drain:
                    print("[tpu_watch] deadline inside the orphan-drain "
                          "window; exiting rather than sweeping under "
                          "contention", flush=True)
                    put_status(status="deadline_during_drain", probes=n,
                               timeouts=sweep_timeouts)
                    return 6
                print(f"[tpu_watch] draining {drain}s for the orphaned "
                      "variant child before re-probing", flush=True)
                put_status(status="draining", probes=n,
                           timeouts=sweep_timeouts, drain_s=drain,
                           wake_at=time.strftime(
                               "%Y-%m-%dT%H:%M:%S%z",
                               time.localtime(time.time() + drain)))
                time.sleep(drain)
                continue
            # The sweep (even a failed one) may have rewritten the
            # last-good record; re-resolve so the terminal "captured"
            # status reports the FRESH capture's age, not the pre-sweep
            # record's.
            refresh_last_good_stamp()
            print(out.stderr, flush=True)
            print(out.stdout, flush=True)
            lines = out.stdout.strip().splitlines()
            rec = {}
            try:
                rec = json.loads(lines[-1]) if lines else {}
            except ValueError:
                pass
            # "stale" guards against bench's CPU-fallback record, which
            # now PROMOTES the last-good TPU row to the top level
            # (platform "tpu" + stale true) — evidence of a PAST window,
            # not of this sweep having captured anything.
            if rec.get("platform") == "tpu" and not rec.get("stale"):
                after = os.environ.get("PBT_WATCH_AFTER_SWEEP")
                if after:
                    # Chain the heavier hardware experiments while the
                    # tunnel is PROVEN up (e.g. PBT_WATCH_AFTER_SWEEP=
                    # "python examples/transfer_experiment.py --scale
                    # full") instead of telling the operator to start
                    # them by hand — up-windows are too rare to waste
                    # on reaction time. Bounded and best-effort: the
                    # sweep capture above is already safe.
                    print(f"[tpu_watch] sweep captured; running "
                          f"after-sweep hook: {after}", flush=True)
                    put_status(status="after_sweep_hook", probes=n,
                               record=rec, hook=after)
                    try:
                        # Own session so a timeout can kill the WHOLE
                        # process group — run(shell=True) would kill
                        # only the sh wrapper and leave a compound
                        # command's experiment processes hammering the
                        # one shared chip.
                        import signal

                        proc = subprocess.Popen(
                            after, shell=True, cwd=REPO,
                            start_new_session=True)
                        try:
                            # <=0 means unbounded, not instant-kill.
                            proc.wait(timeout=HOOK_TIMEOUT
                                      if HOOK_TIMEOUT > 0 else None)
                            print(f"[tpu_watch] hook rc="
                                  f"{proc.returncode}", flush=True)
                        except subprocess.TimeoutExpired:
                            try:
                                os.killpg(proc.pid, signal.SIGKILL)
                            except ProcessLookupError:
                                pass  # group exited in the gap between
                                # TimeoutExpired and the kill (ADVICE r3)
                            proc.wait()  # reap — no zombie child
                            print("[tpu_watch] after-sweep hook timed "
                                  "out; process group killed",
                                  flush=True)
                    except Exception as e:  # hook is best-effort; the
                        # sweep capture (and terminal status) must win
                        print(f"[tpu_watch] after-sweep hook failed: "
                              f"{e}", flush=True)
                # Terminal status LAST so pollers never read a stale
                # mid-hook state after the daemon exits.
                put_status(status="captured", probes=n, record=rec)
                print("[tpu_watch] full TPU sweep captured; exiting",
                      flush=True)
                return 0
            if out.returncode != 0:
                # A real bench failure (all variants failed, crash) is
                # NOT a tunnel flap — say so, don't diagnose it as one,
                # and don't hammer the one shared chip with identical
                # failing sweeps for 11 hours: cap the retries.
                sweep_failures += 1
                put_status(status="sweep_failed", probes=n,
                           returncode=out.returncode,
                           failures=sweep_failures)
                print(f"[tpu_watch] bench exited rc={out.returncode} "
                      f"({sweep_failures}/{SWEEP_FAIL_CAP}); see log "
                      "above", flush=True)
                if sweep_failures >= SWEEP_FAIL_CAP:
                    print("[tpu_watch] repeated on-TPU bench failures; "
                          "giving up so the chip stays free", flush=True)
                    put_status(status="sweep_failed_cap", probes=n,
                               returncode=out.returncode)
                    return 5
            else:
                put_status(status="sweep_fell_back", probes=n)
                print("[tpu_watch] sweep fell back to CPU; keep watching",
                      flush=True)
        else:
            if n % 10 == 1:
                print(f"[tpu_watch] probe {n}: tunnel down "
                      f"({(time.time() - t0) / 60:.0f} min elapsed)",
                      flush=True)
            put_status(status="watching", probes=n)
        time.sleep(POLL_WAIT)
    put_status(status="deadline", probes=n)
    return 3


if __name__ == "__main__":
    sys.exit(main())
