#!/usr/bin/env python
"""Perf-regression sentinel over the bench history (ISSUE 6 tentpole).

The repo accumulates a performance trajectory nobody gates on: one
`BENCH_rNN.json` per review round (headline residues/s/chip capture)
and `bench_events.jsonl` (serve/pack sweep captures mirrored as `note`
events). This tool parses that history, fits a robust per-metric
baseline (median + MAD of the PRIOR points), and flags the newest
point when it falls outside the noise band — so "PR N made serving 20%
slower" is a machine-readable verdict, not an archaeology project.

Noise policy (the zero-false-positive contract over the real history):

- a series is judged only with >= MIN_HISTORY prior points — two
  captures are an anecdote, not a baseline;
- the band is max(K_SIGMA * 1.4826*MAD, REL_FLOOR * |median|): wide
  when the history is genuinely noisy (CPU captures on shared CI boxes
  swing 2-4x), floored at REL_FLOOR so a tight series still needs a
  real move (>10%) to flag;
- CPU and TPU captures are SEPARATE series (a platform change is not a
  regression), as are `live_fallback` probes vs primary captures.

Report-only by default: exit 0 with verdicts in the artifact; exit 2
only on parse/schema errors in the inputs (the tier-1 stage's gate);
`--fail-on-regression` opts into exit 1 on a flagged metric.
`bench_events.jsonl` is read through `obs.events.read_events` — the
same torn-tail-tolerant reader every other consumer of the stream
uses; schema-invalid records are errors (strict), a torn final line is
not.

Usage:
  python tools/bench_trajectory.py [--repo DIR] [--output verdict.json]
      [--events-jsonl PATH]        # mirror the verdict as a note event
      [--fail-on-regression]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from statistics import median
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from proteinbert_tpu.obs.events import read_events  # noqa: E402

MIN_HISTORY = 3        # prior points required before judging
K_SIGMA = 3.0          # band half-width in robust sigmas
REL_FLOOR = 0.10       # …never narrower than 10% of the baseline
VERDICT_SCHEMA = 1

# Per-series ABSOLUTE noise floors (keyed by base name, before the
# /platform suffix), for DIFFERENCE series whose center sits near 0:
# there REL_FLOOR * |median| collapses to ~nothing and the MAD of a
# handful of sign-flipping points understates the true swing.
# fleet_trace_overhead_pct is a matched-pair throughput delta in
# percentage points measured on loaded CI hosts — it sign-flipped 3/7
# rounds in the PR 18 captures (observed swing ±14pp), so anything
# inside ±10pp is load noise, not a propagation-cost change.
_ABS_FLOOR = {"fleet_trace_overhead_pct": 10.0}


def fit_baseline(prior: List[float],
                 abs_floor: float = 0.0) -> Tuple[float, float]:
    """(center, band) from the prior points: robust location (median)
    and a noise band from the scaled MAD, floored at REL_FLOOR of the
    center so near-constant series still tolerate small wobble, and at
    `abs_floor` for near-zero-centered difference series."""
    center = median(prior)
    mad = median([abs(x - center) for x in prior])
    scale = 1.4826 * mad  # MAD → sigma under normality
    band = max(K_SIGMA * scale, REL_FLOOR * abs(center), abs_floor)
    return center, band


def judge_series(values: List[float],
                 higher_is_better: bool = True,
                 name: Optional[str] = None) -> Dict[str, Any]:
    """Verdict for one metric series (oldest → newest). The newest
    point is judged against a baseline fit on everything before it.
    `name` (the series key) selects any per-series absolute noise
    floor from _ABS_FLOOR."""
    out: Dict[str, Any] = {
        "values": [round(v, 6) for v in values],
        "n": len(values),
        "higher_is_better": higher_is_better,
    }
    prior = values[:-1]
    if len(prior) < MIN_HISTORY:
        out["verdict"] = "insufficient_data"
        out["reason"] = (f"{len(prior)} prior point(s) < {MIN_HISTORY} "
                         "required for a baseline")
        return out
    newest = values[-1]
    abs_floor = _ABS_FLOOR.get((name or "").split("/")[0], 0.0)
    center, band = fit_baseline(prior, abs_floor=abs_floor)
    out.update(baseline=round(center, 6), noise_band=round(band, 6),
               newest=round(newest, 6))
    delta = newest - center
    regressed = (delta < -band) if higher_is_better else (delta > band)
    improved = (delta > band) if higher_is_better else (delta < -band)
    # A center of exactly 0 is legitimate (e.g. a clean
    # check_findings_total history is all zeros) — report absolute
    # deltas there instead of dividing by it.
    def rel(x: float) -> str:
        return (f"{abs(x) / abs(center) * 100:.1f}%" if center
                else f"{abs(x):.6g} (absolute; baseline is 0)")

    if regressed:
        out["verdict"] = "regression"
        out["reason"] = (f"newest {newest:.6g} is {rel(delta)} "
                         f"{'below' if higher_is_better else 'above'} "
                         f"baseline {center:.6g} (band {rel(band)})")
    elif improved:
        out["verdict"] = "improved"
        out["reason"] = (f"newest {newest:.6g} beats baseline "
                         f"{center:.6g} beyond the noise band")
    else:
        out["verdict"] = "ok"
        out["reason"] = (f"newest {newest:.6g} within ±{band:.6g} of "
                         f"baseline {center:.6g}")
    return out


# ------------------------------------------------------------ extraction

def series_from_bench_files(paths: List[str],
                            errors: List[str]) -> Dict[str, List[float]]:
    """BENCH_rNN.json → {series key: values} in round order. Primary
    captures and live_fallback probes are separate series, split by
    platform (cross-platform deltas are not regressions)."""
    series: Dict[str, List[float]] = {}
    for path in paths:
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError) as e:
            errors.append(f"{path}: unreadable: {e}")
            continue
        if not isinstance(rec, dict):
            errors.append(f"{path}: expected a JSON object, got "
                          f"{type(rec).__name__}")
            continue
        parsed = rec.get("parsed")
        if not isinstance(parsed, dict):
            continue  # a round with no parsed capture (recorded as null)
        metric = parsed.get("metric", "unknown")
        platform = parsed.get("platform", "unknown")
        value = parsed.get("value")
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            series.setdefault(f"{metric}/{platform}",
                              []).append(float(value))
        fb = parsed.get("live_fallback")
        if isinstance(fb, dict):
            fbv = fb.get("value")
            if isinstance(fbv, (int, float)) and not isinstance(fbv, bool):
                series.setdefault(
                    f"{metric}/{fb.get('platform', 'unknown')}"
                    "/live_fallback", []).append(float(fbv))
    return series


# (event kind, payload field) → series name; higher-is-better unless
# the series name is in _LOWER_IS_BETTER below.
_EVENT_METRICS = (
    ("serve_capture", "served_requests_per_sec", "serve_requests_per_sec"),
    ("serve_capture", "speedup_x", "serve_speedup_x"),
    ("pack_capture", "effective_speedup_x", "pack_effective_speedup_x"),
    # Packed fused fast path (ISSUE 10): fused-vs-reference forward
    # wall-clock on the packed A/B arm (interpret-mode plumbing number
    # on CPU, the real kernel on TPU — platform-split like the rest).
    ("pack_fused_capture", "fused_speedup_x", "pack_fused_speedup_x"),
    # Ragged Pallas attention (ISSUE 13): the attention A/B arm's
    # wall-clock ratio, and the packed train step's pad-adjusted MFU —
    # the packing × fused-kernels compound claim as a sentinel series
    # (CPU-interpret points and TPU hardware points are separate
    # series via the platform split, so the honest CPU numbers never
    # masquerade as the hardware capture).
    ("pack_attn_capture", "attn_speedup_x", "pack_attn_speedup_x"),
    ("pack_attn_capture", "mfu_effective", "pack_mfu_effective"),
    # One-pass trunk (ISSUE 16): the fused block-pass vs two-kernel
    # composition A/B ratio, and its pad-adjusted MFU — the
    # HBM-round-trip-elimination claim as a sentinel series (same
    # platform split: CPU-interpret points never masquerade as the
    # hardware capture).
    ("onepass_capture", "onepass_speedup_x", "pack_onepass_speedup_x"),
    ("onepass_capture", "mfu_effective", "onepass_mfu_effective"),
    # Multi-tenant heads (ISSUE 8): mixed-head throughput + the WORST
    # normalized downstream-eval score across heads — finetune-quality
    # regressions gate through the same sentinel as perf.
    ("heads_capture", "mixed_requests_per_sec",
     "heads_mixed_requests_per_sec"),
    ("heads_capture", "mixed_speedup_x", "heads_mixed_speedup_x"),
    ("heads_capture", "eval_score_min", "heads_eval_score_min"),
    # Quantized collectives + int8 serving (ISSUE 12): the int8 grad-
    # reduction wire ratio vs the fp32 reduce-scatter (bench --comm,
    # LOWER is better — creeping back toward 1.0 means the compression
    # regressed), the quantized serve arm's throughput and its worst
    # per-request parity vs the fp32 arm (bench --serve phase 5), and
    # the quantized-trunk downstream-eval floor (bench --heads — the
    # heads_eval_score_min sentinel's quantized sibling).
    ("comm_quant", "int8_grad_wire_ratio", "comm_bytes_int8_ratio"),
    ("serve_quant_capture", "quant_requests_per_sec",
     "serve_quant_requests_per_sec"),
    ("serve_quant_capture", "parity_max", "serve_quant_parity_max"),
    ("heads_capture", "eval_score_min_quant",
     "heads_eval_score_min_quant"),
    # Offline batch inference (ISSUE 14): the map drill's control-run
    # throughput (tools/map_drill.py --bench-events) — a regression
    # here means the pod-scale UniRef90 embedding job got slower.
    ("map_capture", "map_seqs_per_s", "map_seqs_per_s"),
    # Static-analyzer findings (ISSUE 15): new + baselined `pbt check`
    # findings per capture (`--events-jsonl` mirror, or the fresh
    # artifact via --check-json) — suppression creep moves this series
    # even while the gate stays green. LOWER is better.
    ("check_capture", "check_findings_total", "check_findings_total"),
    # ANN serving (ISSUE 17, bench --neighbors): sustained int8-index
    # lookup QPS and recall@10 vs exact brute force — throughput AND
    # answer-quality regressions gate through the same sentinel (a
    # recall drop means quantization/probing broke what the index
    # answers, even if it got faster doing it).
    ("neighbors_capture", "neighbors_qps", "neighbors_qps"),
    ("neighbors_capture", "neighbors_recall_at_10",
     "neighbors_recall_at_10"),
    # Fleet trace propagation (ISSUE 18, bench --serve fleet arm): the
    # matched propagation-on vs propagation-off fleet throughput delta
    # as a percentage. LOWER is better — creep here means stamping the
    # trace context onto every routed request got more expensive.
    ("fleet_trace_capture", "fleet_trace_overhead_pct",
     "fleet_trace_overhead_pct"),
    # Pipelined dispatch (ISSUE 19): depth-2 vs depth-1 serve
    # throughput ratio (bench --serve pipeline phase; parity- and
    # seal-gated), and the mapper's overlapped-commit share from the
    # map drill's control run. CPU points are honest plumbing numbers
    # — host and device share cores — and stay separate from TPU
    # points via the platform split like every other series.
    ("serve_pipeline_capture", "serve_pipeline_speedup_x",
     "serve_pipeline_speedup_x"),
    ("map_capture", "map_overlap_ratio", "map_overlap_ratio"),
    # Blue-green rollout (ISSUE 20, tools/rollout_drill.py): worst
    # shadow parity through the GOOD candidate (creep = the mirrored
    # arm drifting from the resident numerics) and the atomic-flip
    # latency (creep = the swap-lock hold growing — the zero-dropped-
    # requests promotion depends on it staying O(pointer)). Both
    # LOWER-is-better.
    ("rollout_capture", "rollout_shadow_parity_max",
     "rollout_shadow_parity_max"),
    ("rollout_capture", "rollout_flip_seconds", "rollout_flip_seconds"),
)

# Series (by base name, before the /platform suffix) where a LOWER
# value is the good direction — ratios and error bounds.
_LOWER_IS_BETTER = {"comm_bytes_int8_ratio", "serve_quant_parity_max",
                    "check_findings_total", "fleet_trace_overhead_pct",
                    "rollout_shadow_parity_max", "rollout_flip_seconds"}


def series_direction(name: str) -> bool:
    """higher_is_better for one series key (base name before the
    platform/fallback suffixes)."""
    return name.split("/")[0] not in _LOWER_IS_BETTER


def series_from_events(path: str,
                       errors: List[str]) -> Dict[str, List[float]]:
    """bench_events.jsonl note events → {series key: values} in stream
    order, via the shared torn-tail-tolerant reader (strict: a
    schema-invalid record is an input error, a torn tail is not)."""
    series: Dict[str, List[float]] = {}
    try:
        records = read_events(path, strict=True)
    except (OSError, ValueError) as e:
        errors.append(f"{path}: {e}")
        return series
    for rec in records:
        if rec.get("event") != "note":
            continue
        kind = rec.get("kind")
        platform = rec.get("platform", "unknown")
        for ev_kind, field, name in _EVENT_METRICS:
            if kind != ev_kind:
                continue
            v = rec.get(field)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                series.setdefault(f"{name}/{platform}",
                                  []).append(float(v))
    return series


# -------------------------------------------------------------- verdict

def check_findings_from_artifact(path: str,
                                 errors: List[str]) -> Optional[int]:
    """check_findings_total out of one `pbt check --json-artifact`
    report (the tier-1 stage hands its fresh artifact here so the
    current round's point rides the series without touching the
    checked-in history)."""
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError) as e:
        errors.append(f"{path}: unreadable check artifact: {e}")
        return None
    if not isinstance(rec, dict) or rec.get("kind") != "pbt_check_report":
        errors.append(f"{path}: not a pbt_check_report artifact")
        return None
    v = (rec.get("counts") or {}).get("check_findings_total")
    if not isinstance(v, int) or isinstance(v, bool) or v < 0:
        errors.append(f"{path}: counts.check_findings_total must be a "
                      f"non-negative int, got {v!r}")
        return None
    return v


def build_verdict(bench_paths: List[str],
                  events_path: Optional[str],
                  check_json: Optional[str] = None) -> Dict[str, Any]:
    errors: List[str] = []
    series = series_from_bench_files(bench_paths, errors)
    if events_path and os.path.exists(events_path):
        series.update(series_from_events(events_path, errors))
    if check_json:
        v = check_findings_from_artifact(check_json, errors)
        if v is not None:
            series.setdefault("check_findings_total/static",
                              []).append(float(v))
    judged = {name: judge_series(values,
                                 higher_is_better=series_direction(name),
                                 name=name)
              for name, values in sorted(series.items())}
    verdicts = [s["verdict"] for s in judged.values()]
    if errors:
        overall = "error"
    elif "regression" in verdicts:
        overall = "regression"
    elif any(v in ("ok", "improved") for v in verdicts):
        overall = "ok"
    else:
        overall = "insufficient_data"
    return {
        "v": VERDICT_SCHEMA,
        "kind": "bench_trajectory_verdict",
        "overall": overall,
        "inputs": {"bench_files": [os.path.basename(p)
                                   for p in bench_paths],
                   "events": events_path},
        "policy": {"min_history": MIN_HISTORY, "k_sigma": K_SIGMA,
                   "rel_floor": REL_FLOOR, "abs_floors": dict(_ABS_FLOOR)},
        "series": judged,
        "errors": errors,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--repo", default=REPO,
                    help="directory holding BENCH_r*.json + "
                         "bench_events.jsonl (default: repo root)")
    ap.add_argument("--bench-glob", default="BENCH_r*.json")
    ap.add_argument("--events", default=None,
                    help="bench events stream (default: "
                         "<repo>/bench_events.jsonl)")
    ap.add_argument("--output", default=None,
                    help="write the verdict artifact here (JSON)")
    ap.add_argument("--events-jsonl", default=None,
                    help="ALSO mirror the overall verdict as a `note` "
                         "event on this stream (obs integration)")
    ap.add_argument("--check-json", default=None,
                    help="a fresh `pbt check --json-artifact` report; "
                         "its check_findings_total rides the "
                         "suppression-creep series as the newest point")
    ap.add_argument("--fail-on-regression", action="store_true",
                    help="exit 1 on a flagged regression (default: "
                         "report-only — only input errors fail)")
    args = ap.parse_args(argv)

    bench_paths = sorted(glob.glob(os.path.join(args.repo,
                                                args.bench_glob)))
    events_path = args.events or os.path.join(args.repo,
                                              "bench_events.jsonl")
    verdict = build_verdict(bench_paths, events_path,
                            check_json=args.check_json)

    if args.output:
        with open(args.output, "w") as f:
            json.dump(verdict, f, indent=1)

    if args.events_jsonl:
        from proteinbert_tpu.obs.events import EventLog

        ev = EventLog(args.events_jsonl)
        ev.emit("note", source="bench_trajectory", kind="verdict",
                overall=verdict["overall"],
                regressions=[k for k, s in verdict["series"].items()
                             if s["verdict"] == "regression"],
                errors=len(verdict["errors"]))
        ev.close()

    for name, s in verdict["series"].items():
        print(f"{s['verdict']:>18}  {name}: {s.get('reason', '')}")
    for err in verdict["errors"]:
        print(f"INPUT ERROR: {err}", file=sys.stderr)
    print(f"overall: {verdict['overall']} "
          f"({len(verdict['series'])} series, "
          f"{len(verdict['errors'])} input error(s))")
    if verdict["errors"]:
        return 2
    if args.fail_on_regression and verdict["overall"] == "regression":
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
