#!/usr/bin/env python
"""Pipeline smoke (ISSUE 19, tier-1 stage): the pipelined-dispatch
window exercised end to end on an in-process CPU server, and GATED on
its three invariants rather than wall-clock:

  - **overlap observed** — under a saturated single-thread burst the
    depth-2 window actually fills: `pipeline_stats()['inflight_max']`
    (the high-water mark behind the `serve_inflight_batches` gauge)
    reaches >= 2, and the `serve_finalize_seconds` histogram saw every
    finalize;
  - **async-vs-sync bit-parity** — one full same-bucket micro-batch,
    formed deterministically (max_wait 60s + exactly max_batch FIFO
    submits) on a depth-1 and a depth-2 server, produces BIT-identical
    per-request outputs: the submit/fetch split moves the host fetch,
    never the math;
  - **schema-valid events, exactly-once seals** — both arms' fully
    traced event streams re-read with `read_events(strict=True)`, and
    the depth-2 stream carries exactly one `serve_request` record per
    submitted request with no duplicated ids (zero lost or duplicate
    seals through the completer thread).

Exit nonzero on any violation — this stage GATES (run_tier1.sh).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

SEQ_LEN = int(os.environ.get("PBT_PIPELINE_SMOKE_SEQ_LEN", 96))
DIM = int(os.environ.get("PBT_PIPELINE_SMOKE_DIM", 32))
N_REQUESTS = int(os.environ.get("PBT_PIPELINE_SMOKE_REQUESTS", 48))
MAX_BATCH = int(os.environ.get("PBT_PIPELINE_SMOKE_MAX_BATCH", 4))


def main() -> int:
    import jax
    import numpy as np

    from proteinbert_tpu.configs import (
        DataConfig, ModelConfig, OptimizerConfig, PretrainConfig,
        TrainConfig,
    )
    from proteinbert_tpu.data.vocab import ALPHABET
    from proteinbert_tpu.obs import Telemetry, read_events
    from proteinbert_tpu.serve import Server
    from proteinbert_tpu.train import create_train_state

    buckets = (SEQ_LEN // 4, SEQ_LEN // 2, SEQ_LEN)
    cfg = PretrainConfig(
        model=ModelConfig(local_dim=DIM, global_dim=2 * DIM, key_dim=8,
                          num_heads=2, num_blocks=1,
                          num_annotations=128, dtype="float32"),
        data=DataConfig(seq_len=SEQ_LEN, batch_size=MAX_BATCH,
                        buckets=buckets),
        optimizer=OptimizerConfig(warmup_steps=10),
        train=TrainConfig(max_steps=1))
    params = create_train_state(jax.random.PRNGKey(0), cfg).params

    rng = np.random.default_rng(7)
    alphabet = np.array(list(ALPHABET))
    lengths = rng.integers(8, SEQ_LEN - 2, size=N_REQUESTS)
    seqs = ["".join(rng.choice(alphabet, size=int(n))) for n in lengths]

    failures = []
    tdir = tempfile.mkdtemp(prefix="pbt_pipeline_smoke_")

    # ---- deterministic full-batch bit-parity (depth 1 vs depth 2) ----
    # Same-bucket group, FIFO submits, max_wait 60s: both depths form
    # ONE identical (bucket_len, rows) batch over identical rows.
    probe = Server(params, cfg, max_batch=MAX_BATCH, max_wait_s=60.0,
                   cache_size=0, warm_kinds=())
    by_bucket = {}
    for s in seqs:
        by_bucket.setdefault(probe.dispatcher.bucket_len(len(s)),
                             []).append(s)
    group = max(by_bucket.values(), key=len)
    group = (group * MAX_BATCH)[:MAX_BATCH]
    outs = {}
    for depth in (1, 2):
        psrv = Server(params, cfg, max_batch=len(group), max_wait_s=60.0,
                      cache_size=0, warm_kinds=(), pipeline_depth=depth)
        psrv.start()  # depth 2 runs the live completer thread
        futs = [psrv.submit("embed", s) for s in group]
        outs[depth] = [f.result(timeout=120) for f in futs]
        psrv.drain(timeout=60)
    bit = sum(
        all(np.array_equal(a[k], b[k]) for k in ("global", "local_mean"))
        for a, b in zip(outs[1], outs[2]))
    if bit != len(group):
        failures.append(
            f"async-vs-sync parity: {len(group) - bit}/{len(group)} "
            "outputs not bit-identical on an identical batch")

    # ---- saturated burst through the window, fully traced ------------
    arm_events = {}
    inflight_max = 0
    snap = {}
    for name, depth in (("serial", 1), ("pipelined", 2)):
        events = os.path.join(tdir, f"{name}.jsonl")
        arm_events[name] = events
        tele = Telemetry(events_path=events)
        srv = Server(params, cfg, max_batch=MAX_BATCH, max_wait_s=0.005,
                     queue_depth=4 * N_REQUESTS, cache_size=0,
                     warm_kinds=("embed",), telemetry=tele,
                     trace_sample_rate=1.0, pipeline_depth=depth)
        srv.start()
        burst = [srv.submit("embed", s) for s in seqs]
        srv.drain(timeout=120)  # drain with work in flight
        unresolved = sum(1 for f in burst if not f.done())
        errored = sum(1 for f in burst if f.done() and f.exception())
        if unresolved or errored:
            failures.append(
                f"{name}: {unresolved} unresolved / {errored} errored "
                f"of {len(burst)} burst futures under drain")
        pstats = srv.scheduler.pipeline_stats()
        if name == "pipelined":
            inflight_max = pstats["inflight_max"]
            snap = tele.metrics.snapshot()
            if inflight_max < 2:
                failures.append(
                    f"overlap not observed: inflight_max "
                    f"{inflight_max} < 2 on the depth-2 burst")
            if not any("serve_inflight_batches" in k
                       for k in snap["gauges"]):
                failures.append("serve_inflight_batches gauge never "
                                "registered on the pipelined arm")
            if not any("serve_finalize_seconds" in k
                       for k in snap["histograms"]):
                failures.append("serve_finalize_seconds histogram never "
                                "observed on the pipelined arm")
        elif pstats["depth"] != 1:
            failures.append(f"serial arm reports depth "
                            f"{pstats['depth']}, expected 1")
        tele.close()

    # ---- events: schema-valid, exactly one seal per request ----------
    for name, events in arm_events.items():
        try:
            recs = read_events(events, strict=True)
        except Exception as e:  # noqa: BLE001 — the gate itself
            failures.append(f"{name}: event stream failed strict "
                            f"re-read: {type(e).__name__}: {e}")
            continue
        ids = [r["request_id"] for r in recs
               if r["event"] == "serve_request"]
        if len(ids) != N_REQUESTS or len(set(ids)) != len(ids):
            failures.append(
                f"{name}: {len(ids)} serve_request records "
                f"({len(ids) - len(set(ids))} duplicated ids) for "
                f"{N_REQUESTS} submitted requests")

    summary = {
        "metric": "pipeline_smoke",
        "platform": jax.devices()[0].platform,
        "seq_len": SEQ_LEN, "max_batch": MAX_BATCH,
        "n_requests": N_REQUESTS,
        "parity": {"checked": len(group), "bit_identical": bit},
        "inflight_max": inflight_max,
        "failures": failures,
    }
    print(json.dumps(summary))
    if failures:
        for f in failures:
            print(f"PIPELINE SMOKE FAILURE: {f}", file=sys.stderr)
        return 1
    print("pipeline smoke OK: window filled (inflight_max "
          f"{inflight_max}), async==sync bit-identical, "
          "exactly-once seals, events schema-valid", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
