#!/usr/bin/env python
"""Shared fault-injection surface for the chaos drills (ISSUE 14
satellite): ONE module both tools/fleet_drill.py and tools/map_drill.py
import for their injection needs, so the drills cannot drift apart in
how they kill, tear, delay, or error-inject.

What lives here:

- `FaultInjector` — the in-process router injector (latency spikes,
  simulated connection kills, torn health) re-exported from
  serve/fleet.py and EXTENDED with generic error hooks
  (`fail(key, times)` / `check(key)`) so a drill can make any
  instrumented call site raise N times.
- Torn-file helpers (`tear_file`, `flip_byte`) — simulate a crash
  mid-write / bit rot on cursors, health responses, and store objects.
- `sigkill` — the hardest process landing, for subprocess drills.
- `map_fault_spec` — builder for the PBT_MAP_FAULTS env spec the map
  engine consumes (proteinbert_tpu/mapper/faults.py is the parser; the
  format is documented there and round-tripped by `MapFaults.parse`).

Scripts in tools/ put the repo root on sys.path and import this as
`faults` (after inserting the tools dir) or via importlib.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from proteinbert_tpu.mapper.faults import (  # noqa: E402,F401
    FAULT_ENV, CRASH_POINTS, MapFaults, TransientDispatchError,
)
from proteinbert_tpu.serve.fleet import (  # noqa: E402
    FaultInjector as _RouterFaultInjector,
)


class FaultInjector(_RouterFaultInjector):
    """The fleet router's injector (latency / kill / torn health) plus
    keyed error hooks: `fail(key, n)` arms `check(key)` to raise
    `exc_type` on the next n calls. Thread-safe like the base."""

    def __init__(self):
        super().__init__()
        self._fail_lock = threading.Lock()
        self._fail: Dict[str, Tuple[int, type]] = {}

    def fail(self, key: str, times: int,
             exc_type: type = TransientDispatchError) -> None:
        with self._fail_lock:
            self._fail[key] = (int(times), exc_type)

    def check(self, key: str) -> None:
        """Raise the armed exception for `key` (consuming one count);
        no-op otherwise — safe to leave in production code paths."""
        with self._fail_lock:
            left, exc_type = self._fail.get(key, (0, None))
            if left <= 0:
                return
            self._fail[key] = (left - 1, exc_type)
        raise exc_type(f"injected failure ({key})")


def map_fault_spec(crash: Optional[Tuple[int, int, str]] = None,
                   fail: Optional[Tuple[int, int, int]] = None,
                   nan: Optional[Tuple[int, int]] = None,
                   latency_s: float = 0.0) -> str:
    """Build a PBT_MAP_FAULTS spec string (see mapper/faults.py for the
    grammar); validated by round-tripping through the real parser so a
    drill can never ship a spec the engine will not honor."""
    parts: List[str] = []
    if crash is not None:
        parts.append("crash=%d:%d:%s" % crash)
    if fail is not None:
        parts.append("fail=%d:%d:%d" % fail)
    if nan is not None:
        parts.append("nan=%d:%d" % nan)
    if latency_s > 0:
        parts.append(f"latency={latency_s}")
    spec = ";".join(parts)
    MapFaults.parse(spec)  # raises on a malformed spec
    return spec


def tear_file(path: str, keep_bytes: Optional[int] = None,
              keep_frac: float = 0.5) -> int:
    """Truncate a file the way a crash mid-write leaves it (keep the
    first `keep_bytes`, default `keep_frac` of it). Returns the bytes
    kept; refuses to 'tear' by keeping everything."""
    with open(path, "rb") as f:
        data = f.read()
    keep = keep_bytes if keep_bytes is not None \
        else max(1, int(len(data) * keep_frac))
    if keep >= len(data):
        raise ValueError(f"tear_file would keep all {len(data)} bytes "
                         f"of {path}")
    with open(path, "wb") as f:
        f.write(data[:keep])
    return keep


def flip_byte(path: str, offset: int = -1) -> None:
    """XOR one byte in place (bit rot / torn sector simulation — the
    `--verify` detection target)."""
    with open(path, "rb") as f:
        data = bytearray(f.read())
    data[offset] ^= 0xFF
    with open(path, "wb") as f:
        f.write(bytes(data))


def sigkill(proc_or_pid) -> None:
    """SIGKILL a subprocess.Popen or raw pid — no drain, no handlers,
    the landing the cursor protocol is built to survive."""
    pid = getattr(proc_or_pid, "pid", proc_or_pid)
    os.kill(int(pid), signal.SIGKILL)
