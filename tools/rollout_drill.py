#!/usr/bin/env python
"""Blue-green rollout drill (ISSUE 20): prove the shadow → gate →
flip → rollback lifecycle end to end against a REAL in-process fleet.

Three serve replicas (each a full `serve.Server` + HTTP endpoint with
its own telemetry stream) run behind a real `FleetRouter` + HTTP
front under concurrent client traffic. The drill then:

  1. ships a deliberately DEGRADED candidate trunk (large weight
     perturbation) — the parity gate must refuse it after
     `windows_required` consecutive red windows, unload it everywhere,
     and the shadow traffic must have been INVISIBLE: live responses
     stay bit-identical to the resident baseline, the seal funnel
     never counts a shadow, and the candidate arm leaves no residue;
  2. ships a GOOD candidate (tiny perturbation) under continuous
     traffic — the gates (parity, SLO burn, heads-eval delta, zero
     shadow failures) go green, auto-promotion flips each replica
     atomically, and the drill KILLS one replica immediately before
     its flip verb (`_pre_flip_hook`, the hardest-landing mid-flip
     crash) — the fleet must converge anyway: survivors on the
     candidate fingerprint, victim dead (not mixed), zero lost
     requests, exactly-once sealing intact; frozen heads re-pin via
     `registry.migrate_fingerprint` with an audit trail while the
     unfrozen head gets the typed refusal;
  3. breaches the promoted rollout — instant rollback to the
     host-parked trunk, head pins restored, and post-rollback probes
     BIT-IDENTICAL (parity 0.0) to the pre-rollout baseline.

Gates (exit nonzero on violation — tier-1 runs this as a smoke stage):
  - degraded candidate refused; good candidate promoted; rollback
    restores bit-identical numerics;
  - router accepted == sealed == client calls across ALL phases; the
    merged fleet stream (FleetCollector) is schema-valid with
    exactly-once sealing and attempts == retries + 1 per trace —
    shadows never contaminate the attempt plane;
  - every rollout_* event round-trips the schema validator; the
    note(kind=rollout_capture) sentinel sample lands on the stream.

Usage:
  python tools/rollout_drill.py [--outdir DIR] [--json]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PBT_DISABLE_DONATION", "1")

SEQ_LEN = 48
BUCKETS = (24, 48)
AA = "ACDEFGHIKLMNPQRSTVWY"


def _tiny_cfg():
    from proteinbert_tpu.configs import (
        DataConfig, ModelConfig, OptimizerConfig, PretrainConfig,
        TrainConfig,
    )

    return PretrainConfig(
        model=ModelConfig(local_dim=16, global_dim=32, key_dim=8,
                          num_heads=2, num_blocks=2, num_annotations=32,
                          dtype="float32"),
        data=DataConfig(seq_len=SEQ_LEN, batch_size=4),
        optimizer=OptimizerConfig(warmup_steps=5),
        train=TrainConfig(seed=0, max_steps=1),
    )


class LocalReplica:
    """One in-process serve replica with a rollout-capable candidate
    arm: Server + HTTP endpoint + its own telemetry stream."""

    def __init__(self, name, params, cfg, events_path, loader):
        from proteinbert_tpu.obs import Telemetry
        from proteinbert_tpu.serve import Server
        from proteinbert_tpu.serve.http import make_http_server

        self.name = name
        self.events_path = events_path
        self.tele = Telemetry(events_path=events_path)
        self.server = Server(
            params, cfg, buckets=BUCKETS, max_batch=4, max_wait_s=0.005,
            queue_depth=64, cache_size=256, telemetry=self.tele,
            trace_sample_rate=1.0, replica_id=name,
            candidate_loader=loader)
        self.server.start()
        self.httpd = make_http_server(self.server, "127.0.0.1", 0)
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        self.thread = threading.Thread(target=self.httpd.serve_forever,
                                       daemon=True, name=f"{name}-http")
        self.thread.start()
        self.killed = False

    def kill(self):
        """Mid-flip hard landing: pending work fails typed (503), then
        the socket goes away (connection refused for the flip verb)."""
        self.killed = True
        self.server.abort()
        self.httpd.shutdown()
        self.httpd.server_close()
        self.tele.close()

    def drain(self):
        if self.killed:
            return
        self.httpd.shutdown()
        self.httpd.server_close()
        self.server.drain(timeout=30)
        self.tele.close()


class SpyTele:
    """Telemetry pass-through that records every finite shadow parity —
    the drill's source for the rollout_capture sentinel sample."""

    def __init__(self, inner):
        self._inner = inner
        self.metrics = inner.metrics
        self.parities = []

    def emit(self, event, **fields):
        if event == "rollout_shadow" and "parity_max" in fields:
            self.parities.append(float(fields["parity_max"]))
        return self._inner.emit(event, **fields)


def _post(url, payload, timeout=60.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read())
        except ValueError:
            return e.code, None


def run_drill(args) -> dict:
    import jax
    import numpy as np

    from proteinbert_tpu.configs import TaskConfig
    from proteinbert_tpu.data.synthetic import make_task_batches
    from proteinbert_tpu.heads import HeadRegistry, trunk_fingerprint
    from proteinbert_tpu.models import finetune as ft_model
    from proteinbert_tpu.obs import Telemetry, read_events, validate_record
    from proteinbert_tpu.obs.diagnose import summarize_fleet
    from proteinbert_tpu.rollout import HeadsEvalGate, RolloutController
    from proteinbert_tpu.rollout.controller import parity_delta
    from proteinbert_tpu.serve.fleet import (
        FleetCollector, FleetRouter, make_fleet_http_server,
    )
    from proteinbert_tpu.train import create_train_state

    outdir = args.outdir or tempfile.mkdtemp(prefix="pbt_rollout_drill_")
    os.makedirs(outdir, exist_ok=True)
    cfg = _tiny_cfg()
    params = create_train_state(jax.random.PRNGKey(0), cfg).params

    def perturb(tree, scale, seed):
        leaves, treedef = jax.tree.flatten(tree)
        rng = np.random.default_rng(seed)
        out = []
        for leaf in leaves:
            a = np.asarray(leaf)
            out.append(a + (scale * rng.standard_normal(a.shape))
                       .astype(a.dtype))
        return jax.tree.unflatten(treedef, out)

    # Good candidate: numerically close (parity gate passes but the
    # fingerprint differs). Bad candidate: large perturbation — its
    # shadow outputs diverge far past any sane parity threshold.
    good_params = perturb(params, 1e-5, 1)
    bad_params = perturb(params, 0.5, 2)
    resident_fp = trunk_fingerprint(params)
    good_fp = trunk_fingerprint(good_params)
    bad_fp = trunk_fingerprint(bad_params)
    assert len({resident_fp, good_fp, bad_fp}) == 3

    # Registry: one FROZEN head (migrates on promotion) + one UNFROZEN
    # head (typed migration refusal; still scores in the eval gate).
    registry = HeadRegistry(os.path.join(outdir, "registry"))
    frozen_task = TaskConfig(kind="sequence_classification",
                             num_outputs=3, freeze_trunk=True)
    unfrozen_task = TaskConfig(kind="sequence_regression",
                               num_outputs=1, freeze_trunk=False)
    frozen_id = registry.save(
        jax.tree.map(np.asarray,
                     ft_model.head_init(jax.random.PRNGKey(1), cfg.model,
                                        frozen_task)),
        frozen_task, resident_fp, name="frozen")
    unfrozen_id = registry.save(
        jax.tree.map(np.asarray,
                     ft_model.head_init(jax.random.PRNGKey(2), cfg.model,
                                        unfrozen_task)),
        unfrozen_task, resident_fp, name="unfrozen")

    def batches_for(head):
        return make_task_batches(8, np.random.default_rng(5),
                                 head.task.kind, head.task.num_outputs,
                                 SEQ_LEN, 4)

    loader = lambda src: {"good": good_params, "bad": bad_params}[src]  # noqa: E731
    replicas = [
        LocalReplica(f"r{i}", params, cfg,
                     os.path.join(outdir, f"replica{i}.events.jsonl"),
                     loader)
        for i in range(3)
    ]
    router_events = os.path.join(outdir, "router.events.jsonl")
    tele = Telemetry(events_path=router_events)
    router = FleetRouter(
        [(r.name, r.url) for r in replicas], telemetry=tele,
        health_interval_s=0.1, health_timeout_s=1.0,
        fail_threshold=2, readmit_threshold=2,
        max_retries=3, backoff_base_s=0.02, backoff_cap_s=0.2,
        retry_budget_ratio=0.5, retry_budget_floor=64,
        request_timeout_s=60.0, cache_size=512,
    ).start()
    httpd = make_fleet_http_server(router, "127.0.0.1", 0)
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    threading.Thread(target=httpd.serve_forever, daemon=True,
                     name="router-http").start()

    failures = []
    sent = [0]
    seq_rng = np.random.default_rng(args.seed)
    seq_lock = threading.Lock()

    def _fresh_seq():
        # Unique sequences so no request ever cache-hits: every live
        # 200 must travel the forwarded (mirrorable) path.
        with seq_lock:
            n = int(seq_rng.integers(6, SEQ_LEN - 2))
            return "".join(seq_rng.choice(list(AA), size=n))

    def traffic(n, clients=4):
        """n unique requests over concurrent clients; every reply must
        be 200 or typed-error JSON. Returns the (status, body) list."""
        results = [None] * n
        payloads = []
        for i in range(n):
            seq = _fresh_seq()
            if i % 3 == 2:
                payloads.append(("/v1/predict_go",
                                 {"seq": seq, "top_k": 3}))
            else:
                payloads.append(("/v1/embed", {"seq": seq}))

        def client(w):
            for i in range(w, n, clients):
                path, payload = payloads[i]
                results[i] = _post(base + path, payload)

        threads = [threading.Thread(target=client, args=(w,),
                                    daemon=True)
                   for w in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        sent[0] += n
        for st, body in results:
            if st != 200 and not (isinstance(body, dict)
                                  and "type" in body):
                failures.append(f"untyped client reply (HTTP {st}): "
                                f"{str(body)[:120]}")
        return results

    def wait_for(pred, timeout, what):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return True
        failures.append(f"timed out waiting for {what}")
        return False

    # All replicas admitted before any traffic.
    wait_for(lambda: all(r["state"] in ("up", "degraded")
                         for r in router.replica_status()),
             30, "replica admission")

    # -------------------------------------------------- baseline probes
    probe_seqs = ["".join(seq_rng.choice(list(AA), size=20))
                  for _ in range(4)]
    baseline = []
    for s in probe_seqs:
        st, body = _post(base + "/v1/embed", {"seq": s})
        sent[0] += 1
        if st != 200:
            failures.append(f"baseline probe failed: HTTP {st}")
        baseline.append(body)

    def drive_until_terminal(ctl, timeout, what):
        deadline = time.monotonic() + timeout
        while not ctl.terminal() and time.monotonic() < deadline:
            traffic(8)
            time.sleep(0.02)
        if not ctl.terminal():
            failures.append(f"rollout never terminated during {what} "
                            f"(state {ctl.state!r})")

    # ------------------------------------------- phase 1: bad candidate
    spy_bad = SpyTele(tele)
    ctl_bad = RolloutController(
        router, telemetry=spy_bad, source="bad", sample_every=1,
        window_requests=4, windows_required=2, shadow_parity_max=1e-3,
        slo_burn_delta_max=5.0, auto_promote=True)
    router.attach_rollout(ctl_bad)
    ctl_bad.start()
    for r in replicas:
        cand = r.server.rollout_status()["candidate_fingerprint"]
        if cand != bad_fp:
            failures.append(f"{r.name}: bad candidate not loaded "
                            f"(fingerprint {cand})")
    drive_until_terminal(ctl_bad, 120, "the degraded rollout")
    if ctl_bad.state != "refused":
        failures.append(f"degraded candidate ended {ctl_bad.state!r}, "
                        "want 'refused'")
    shadow_n = sum(r.server.rollout_status()["shadow_requests"]
                   for r in replicas)
    if shadow_n < 8:
        failures.append(f"only {shadow_n} shadow requests ran during "
                        "the degraded rollout (want >= 8)")
    for r in replicas:
        st = r.server.rollout_status()
        if st["candidate_fingerprint"] is not None:
            failures.append(f"{r.name}: refused candidate not unloaded")
        if r.server.trunk_fp() != resident_fp:
            failures.append(f"{r.name}: resident trunk changed during "
                            "a refused rollout")
    # Shadow invisibility: live numerics stayed the resident trunk's.
    for s, base_body in zip(probe_seqs, baseline):
        st, body = _post(base + "/v1/embed", {"seq": s})
        sent[0] += 1
        if st != 200 or parity_delta(base_body, body) != 0.0:
            failures.append("live response drifted during the degraded "
                            "rollout — shadow traffic was not invisible")
            break

    # ------------------------- phase 2: good candidate, mid-flip crash
    gate = HeadsEvalGate(registry, cfg.model, batches_for,
                         params, good_params, resident_fp, good_fp,
                         telemetry=tele)
    spy = SpyTele(tele)
    ctl = RolloutController(
        router, telemetry=spy, source="good", sample_every=1,
        window_requests=4, windows_required=2, shadow_parity_max=0.1,
        slo_burn_delta_max=5.0, heads_eval_drop_max=0.2,
        heads_eval=gate, auto_promote=True)
    victim = replicas[-1]
    killed = []

    def pre_flip(name):
        # The chaos seam: SIGKILL-equivalent on the victim IMMEDIATELY
        # before its flip verb — the flip must fail on it, land on the
        # survivors, and the fleet must converge via the health plane.
        if name == victim.name and not killed:
            killed.append(name)
            victim.kill()

    ctl._pre_flip_hook = pre_flip
    router.attach_rollout(ctl)
    ctl.start()
    drive_until_terminal(ctl, 300, "the good rollout")
    survivors = [r for r in replicas if r is not victim]
    if ctl.state != "promoted":
        failures.append(f"good candidate ended {ctl.state!r}, "
                        "want 'promoted'")
    else:
        if killed != [victim.name]:
            failures.append("the pre-flip kill never fired — the "
                            "mid-flip crash path was not exercised")
        if sorted(ctl.flipped) != sorted(r.name for r in survivors):
            failures.append(f"flipped {ctl.flipped}, want exactly the "
                            f"survivors {[r.name for r in survivors]}")
        if ctl._flip_seconds is None:
            failures.append("promotion recorded no flip_seconds")
    for r in survivors:
        if r.server.trunk_fp() != good_fp:
            failures.append(f"{r.name}: resident fingerprint is not "
                            "the candidate's after the flip")
    # Head migration: frozen re-pinned with an audit record, unfrozen
    # refused (typed) and left on the old trunk.
    frozen_meta = registry._read_meta(frozen_id)
    if frozen_meta["trunk_fingerprint"] != good_fp:
        failures.append("frozen head was not re-pinned on promotion")
    if len(frozen_meta.get("migrations") or []) != 1:
        failures.append("frozen head migration left no audit record")
    if registry._read_meta(unfrozen_id)["trunk_fingerprint"] \
            != resident_fp:
        failures.append("unfrozen head was re-pinned — the typed "
                        "refusal did not hold")
    if [r["head_id"] for r in gate.refused] != [unfrozen_id]:
        failures.append(f"migration refusals {gate.refused} do not "
                        "name exactly the unfrozen head")
    # Fleet convergence: victim dead (not mixed), survivors coherent on
    # the candidate fingerprint.
    wait_for(lambda: {r["name"]: r["state"]
                      for r in router.replica_status()}[victim.name]
             == "dead", 15, "the killed replica to be marked dead")
    survivor_names = {r.name for r in survivors}
    wait_for(lambda: router.fingerprint_status()["fleet_state"]
             == "coherent"
             and all(fp == good_fp for name, fp in
                     router.fingerprint_status()["fingerprints"]
                     .items() if name in survivor_names),
             15, "post-flip fingerprint coherence")
    traffic(8)  # the flipped fleet still serves

    # --------------------------------- phase 3: breach → instant rollback
    ctl.breach(reason="drill_breach")
    if ctl.state != "rolled_back":
        failures.append(f"breach ended {ctl.state!r}, want "
                        "'rolled_back'")
    frozen_meta = registry._read_meta(frozen_id)
    if frozen_meta["trunk_fingerprint"] != resident_fp:
        failures.append("rollback did not restore the frozen head's "
                        "trunk pin")
    if len(frozen_meta.get("migrations") or []) != 2:
        failures.append("rollback re-pin left no audit record")
    for r in survivors:
        if r.server.trunk_fp() != resident_fp:
            failures.append(f"{r.name}: rollback did not restore the "
                            "resident fingerprint")
    # The headline numerics gate: post-rollback responses BIT-IDENTICAL
    # to the pre-rollout baseline (parked-trunk restoration).
    rollback_parity = 0.0
    for s, base_body in zip(probe_seqs, baseline):
        st, body = _post(base + "/v1/embed", {"seq": s})
        sent[0] += 1
        delta = parity_delta(base_body, body) if st == 200 else math.inf
        rollback_parity = max(rollback_parity, delta)
    if rollback_parity != 0.0:
        failures.append(f"rollback numerics are NOT bit-identical to "
                        f"the baseline (parity {rollback_parity})")

    # ------------------------------------- capture + teardown + audits
    finite = [p for p in spy.parities if math.isfinite(p)]
    if not finite:
        failures.append("the good rollout produced no finite shadow "
                        "parity sample")
    tele.emit("note", source="rollout_drill", kind="rollout_capture",
              rollout_shadow_parity_max=max(finite, default=0.0),
              rollout_flip_seconds=ctl._flip_seconds or 0.0)

    httpd.shutdown()
    httpd.server_close()
    router.drain()
    for r in replicas:
        r.drain()
    tele.close()

    stats = router.stats()
    if stats["accepted"] != stats["sealed"]:
        failures.append(f"router accepted {stats['accepted']} != "
                        f"sealed {stats['sealed']}")
    if stats["accepted"] != sent[0]:
        failures.append(f"router accepted {stats['accepted']} != "
                        f"{sent[0]} client calls — shadow traffic "
                        "leaked into the seal funnel")

    rrecs = read_events(router_events, strict=True)
    states = [r["state"] for r in rrecs if r["event"] == "rollout_state"]
    for want in ("shadowing", "refused", "promoting", "promoted",
                 "rolled_back"):
        if want not in states:
            failures.append(f"no rollout_state{{state={want}}} on the "
                            "router stream")
    windows = [r for r in rrecs if r["event"] == "rollout_window"]
    verdicts = {r["verdict"] for r in windows}
    if not {"pass", "fail"} <= verdicts:
        failures.append(f"rollout windows never recorded both verdicts "
                        f"(saw {sorted(verdicts)})")
    shadows = [r for r in rrecs if r["event"] == "rollout_shadow"]
    if len(shadows) < 16:
        failures.append(f"only {len(shadows)} rollout_shadow events "
                        "(want >= 16 across both rollouts)")
    sealed_ids = {r.get("trace_id") or r.get("request_id")
                  for r in rrecs if r["event"] == "fleet_request"}
    orphan = [r["trace_id"] for r in shadows
              if r["trace_id"] not in sealed_ids]
    if orphan:
        failures.append(f"shadow events reference unsealed traces: "
                        f"{orphan[:5]}")
    captures = [r for r in rrecs if r["event"] == "note"
                and r.get("kind") == "rollout_capture"]
    if len(captures) != 1:
        failures.append("the rollout_capture sentinel note is missing")

    collector = FleetCollector({"router": router_events})
    for r in replicas:
        collector.add_source(r.name, r.events_path)
    merged_path = os.path.join(outdir, "merged.events.jsonl")
    merged_n = collector.write(merged_path)
    merged = read_events(merged_path, strict=True)
    for i, rec in enumerate(merged):
        try:
            validate_record(rec)
        except ValueError as e:
            failures.append(f"merged stream schema break at record "
                            f"{i}: {e}")
            break
    viol = FleetCollector.seal_violations(merged)
    if viol:
        failures.append(f"exactly-once sealing broke: "
                        f"{dict(list(viol.items())[:5])}")
    fsum = summarize_fleet(merged)
    if fsum["attempt_mismatches"]:
        failures.append(f"attempts != retries + 1 for traces "
                        f"{fsum['attempt_mismatches'][:5]} — shadows "
                        "contaminated the attempt plane")
    flips = [r for r in merged if r["event"] == "rollout_flip"]
    flip_phases = [r["phase"] for r in flips]
    if flip_phases.count("flip") != len(survivors) \
            or flip_phases.count("rollback") != len(survivors):
        failures.append(f"rollout_flip events {flip_phases} do not "
                        f"match {len(survivors)} flips + rollbacks")

    summary = {
        "client_calls": sent[0],
        "router": {k: stats[k] for k in
                   ("accepted", "sealed", "outcomes", "retries_spent")},
        "bad_rollout_state": ctl_bad.state,
        "good_rollout_state": ctl.state,
        "victim": victim.name,
        "flipped": sorted(ctl.flipped),
        "flip_seconds": ctl._flip_seconds,
        "shadow_events": len(shadows),
        "shadow_parity_max": max(finite, default=None),
        "heads_eval_delta": gate.delta,
        "migrated_then_restored": frozen_id,
        "migration_refused": unfrozen_id,
        "rollback_parity": rollback_parity,
        "merged_records": merged_n,
        "outdir": outdir,
        "failures": failures,
        "ok": not failures,
    }
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--outdir", help="artifact dir (default: temp)")
    ap.add_argument("--json", action="store_true",
                    help="print the summary as one JSON object only")
    ap.add_argument("--bench-events",
                    help="append a note(kind=rollout_capture) record to "
                         "this bench events stream "
                         "(tools/bench_trajectory.py fits the "
                         "rollout_shadow_parity_max and "
                         "rollout_flip_seconds series from it)")
    args = ap.parse_args(argv)
    summary = run_drill(args)
    if args.bench_events and summary["ok"]:
        # Sentinel mirror (map_drill idiom): the worst shadow parity
        # through the GOOD candidate + the atomic-flip latency,
        # platform-split like every other capture.
        from proteinbert_tpu.obs import EventLog

        elog = EventLog(args.bench_events)
        elog.emit("note", source="rollout_drill", kind="rollout_capture",
                  platform="cpu",
                  rollout_shadow_parity_max=summary["shadow_parity_max"]
                  or 0.0,
                  rollout_flip_seconds=summary["flip_seconds"] or 0.0,
                  shadow_events=summary["shadow_events"])
        elog.close()
    if args.json:
        print(json.dumps(summary))
    else:
        print(json.dumps(summary, indent=2))
    if not summary["ok"]:
        print("ROLLOUT DRILL FAILED:", "; ".join(summary["failures"]),
              file=sys.stderr)
        return 1
    print(f"rollout drill OK: degraded candidate refused after "
          f"{summary['shadow_events']} shadows, good candidate "
          f"promoted (flip {summary['flip_seconds']}s, victim "
          f"{summary['victim']} killed mid-flip, survivors "
          f"{summary['flipped']} converged), rollback bit-identical "
          f"(parity {summary['rollback_parity']}); "
          f"{summary['client_calls']} client calls all sealed exactly "
          f"once ({summary['router']['outcomes']})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
