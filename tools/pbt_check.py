#!/usr/bin/env python
"""Jax-free entry point for `pbt check` (ISSUE 15) — the tier-1 stage.

The analyzer package (`proteinbert_tpu/analysis/`) is stdlib-only, but
a plain `import proteinbert_tpu.analysis` would execute the package
root `__init__.py`, which imports jax (it pins the threefry flag at
import time). A pre-test lint gate must not pay — or require — jax
device init, so this wrapper registers a STUB parent package whose
`__path__` points at the real directory before importing the
submodule: the import system finds the parent in sys.modules and never
runs the real root `__init__`. The `pbt check` CLI verb runs the same
`runner.main` with the package imported normally.

Usage (identical flags to `pbt check`):
  python tools/pbt_check.py [--json] [--json-artifact PATH]
      [--rule NAME] [--baseline FILE] [--root DIR] [--write-baseline]
"""

from __future__ import annotations

import os
import sys
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

if "proteinbert_tpu" not in sys.modules:
    stub = types.ModuleType("proteinbert_tpu")
    stub.__path__ = [os.path.join(REPO, "proteinbert_tpu")]
    sys.modules["proteinbert_tpu"] = stub
sys.path.insert(0, REPO)

from proteinbert_tpu.analysis.runner import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(repo_root=REPO))
