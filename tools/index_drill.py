#!/usr/bin/env python
"""Index chaos drill (ISSUE 17 satellite): prove `pbt index` builds are
kill-anywhere resumable and `--verify` catches corruption.

A synthetic embedding store (hand-written through the mapper's own
commit_block protocol — no model, no jax in the writer) is indexed
twice through REAL `pbt index` subprocesses:

- the CHAOS line: run 1 is SIGKILLed deterministically in the worst
  window (between an index block's object write and its cursor advance
  — the PBT_INDEX_FAULTS crash hook at the exact seam the map drill
  exercises); run 2 resumes and must complete;
- the CONTROL line: one uninterrupted build over the same store into a
  fresh index directory.

Gates (exit nonzero on violation — tier-1 runs this as a smoke stage):
  - the resumed chaos index is BYTE-IDENTICAL to the control index
    (same {centroids, (shard, block)} → digest map via index_digests,
    same object bytes, same index_identity);
  - re-work is bounded: the resumed build reports at most ONE re-worked
    block per shard;
  - `pbt index --verify` (the real CLI) exits 0 on the intact chaos
    index, DETECTS a deliberately flipped byte in a vector block
    (typed digest_mismatch, nonzero exit), reports a deleted object as
    a hole, and verifies clean again after restoration;
  - rebuilding against a DIFFERENT store (stale corpus/model pins) is a
    typed refusal before any write — the chaos index is unchanged;
  - every emitted event validates against the schema (strict reader),
    and the chaos line seals index_build/completed exactly once.

Usage:
  python tools/index_drill.py [--outdir DIR] [--json] [--seed N]
      [--vectors N]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

NUM_SHARDS = 2
STORE_BLOCK = 8        # store geometry (what `pbt map` would have cut)
INDEX_BLOCK = 8        # index geometry: >= 2 blocks/shard at defaults
DIM = 16
CENTROIDS = 4
CRASH = (0, 1, "after_object")  # shard 0 block 1, object durable,
#                                 cursor NOT advanced — the worst window


def make_store(store_dir: str, n: int, seed: int) -> None:
    """A complete, verified embedding store written through the REAL
    durability protocol (ensure_manifest + commit_block + done markers)
    with synthetic vectors — the builder's input contract without a
    trunk forward (jax-free, seconds not minutes)."""
    import numpy as np

    from proteinbert_tpu.mapper.store import (
        EmbeddingStore, ShardCursor, block_digest, commit_block,
        corpus_digest, serialize_block, shard_ranges,
    )

    rng = np.random.default_rng(seed)
    ids = [f"syn{i:05d}" for i in range(n)]
    seqs = ["A" * (10 + i % 7) for i in range(n)]  # identity only
    # Clustered vectors (not isotropic noise) so the IVF shortlist is a
    # meaningful structure, same shape the trunk would emit.
    anchors = rng.standard_normal((CENTROIDS, DIM)).astype(np.float32)
    vecs = (anchors[rng.integers(0, CENTROIDS, size=n)]
            + 0.15 * rng.standard_normal((n, DIM))).astype(np.float32)

    store = EmbeddingStore(store_dir)
    fingerprint = "deadbeef" * 8  # a pinned trunk identity, not a model
    store.ensure_manifest({
        "kind": "embedding_store",
        "corpus_n": n,
        "corpus_digest": corpus_digest(ids, seqs),
        "model_fingerprint": fingerprint,
        "num_shards": NUM_SHARDS,
        "block_size": STORE_BLOCK,
        "rows_per_batch": 2,
        "max_segments": 4,
        "seq_len": 48,
        "buckets": [16, 32, 48],
    })
    for shard, (lo, hi) in enumerate(shard_ranges(n, NUM_SHARDS)):
        cursor = ShardCursor(store_dir, shard)
        state = cursor.write_state(cursor.fresh_state())
        for start in range(0, hi - lo, STORE_BLOCK):
            end = min(start + STORE_BLOCK, hi - lo)
            rows = slice(lo + start, lo + end)
            arrays = {
                "ids": np.array(ids[rows], dtype="S"),
                "lengths": np.array([len(s) for s in seqs[rows]],
                                    np.int32),
                "global": vecs[rows],
                "local_mean": np.zeros((end - start, DIM), np.float32),
            }
            meta = {"shard": shard, "block": start // STORE_BLOCK,
                    "start": start, "end": end,
                    "model_fingerprint": fingerprint}
            payload = serialize_block(meta, arrays)
            entry = {"block": start // STORE_BLOCK,
                     "digest": block_digest(payload), "start": start,
                     "end": end, "n": end - start, "quarantined": []}
            state = commit_block(store, cursor, state, payload, entry)
        cursor.write_state(dict(state, done=True))


def _index_cmd(store: str, index: str, events: str):
    return [sys.executable, "-m", "proteinbert_tpu", "--platform", "cpu",
            "index", "--store", store, "--index", index,
            "--centroids", str(CENTROIDS),
            "--block-size", str(INDEX_BLOCK), "--json",
            "--events-jsonl", events]


def _run(cmd, env_extra=None, log_path=None, timeout=300):
    env = dict(os.environ)
    env.update(env_extra or {})
    with open(log_path, "ab") as lf:
        lf.write((" ".join(cmd) + "\n").encode())
        proc = subprocess.run(cmd, stdout=subprocess.PIPE, stderr=lf,
                              env=env, timeout=timeout)
    return proc.returncode, proc.stdout.decode()


def run_drill(args) -> dict:
    from faults import flip_byte, map_fault_spec
    from proteinbert_tpu.index import (
        INDEX_FAULT_ENV, index_digests, index_identity, verify_index,
    )
    from proteinbert_tpu.mapper import EmbeddingStore, verify_store
    from proteinbert_tpu.obs import read_events

    outdir = args.outdir or tempfile.mkdtemp(prefix="pbt_index_drill_")
    os.makedirs(outdir, exist_ok=True)
    log_path = os.path.join(outdir, "drill.log")
    store_dir = os.path.join(outdir, "store")
    chaos_index = os.path.join(outdir, "chaos_index")
    control_index = os.path.join(outdir, "control_index")
    ev1 = os.path.join(outdir, "chaos_run1.events.jsonl")
    ev2 = os.path.join(outdir, "chaos_run2.events.jsonl")
    evc = os.path.join(outdir, "control.events.jsonl")
    failures = []
    t0 = time.monotonic()

    make_store(store_dir, args.vectors, args.seed)
    srep = verify_store(store_dir)
    if not (srep["ok"] and srep["complete"]):
        failures.append(f"synthetic store failed verify_store: {srep}")

    # ---- chaos run 1: SIGKILL between block 1's object write and its
    # cursor advance on shard 0 (block 0 of each shard already durable).
    rc1 = out1 = None
    if not failures:
        rc1, out1 = _run(
            _index_cmd(store_dir, chaos_index, ev1),
            env_extra={INDEX_FAULT_ENV: map_fault_spec(crash=CRASH)},
            log_path=log_path)
        if rc1 not in (-9, 137):
            failures.append(f"chaos run 1 exited {rc1}, expected a "
                            "SIGKILL death (-9/137) — the crash hook "
                            "never fired; see " + log_path)

    # ---- chaos run 2: resume, must complete with bounded re-work.
    stats2 = {}
    if not failures:
        rc2, out2 = _run(_index_cmd(store_dir, chaos_index, ev2),
                         log_path=log_path)
        if rc2 != 0:
            failures.append(f"chaos run 2 (resume) exited {rc2}; see "
                            f"{log_path}")
        else:
            stats2 = next(json.loads(ln) for ln in out2.splitlines()
                          if ln.startswith("{"))
            if stats2["outcome"] != "completed":
                failures.append(f"resume outcome {stats2['outcome']!r}")
            if stats2["reworked_blocks"] > NUM_SHARDS:
                failures.append(
                    f"resume re-worked {stats2['reworked_blocks']} "
                    f"block(s) > bound of 1 per shard ({NUM_SHARDS})")

    # ---- control: one uninterrupted build.
    if not failures:
        rcc, _outc = _run(_index_cmd(store_dir, control_index, evc),
                          log_path=log_path)
        if rcc != 0:
            failures.append(f"control build exited {rcc}; see {log_path}")

    rework = stats2.get("reworked_blocks")
    if not failures:
        # ---- byte identity: digest maps, object bytes, identity key.
        dg_chaos = index_digests(chaos_index)
        dg_control = index_digests(control_index)
        if dg_chaos != dg_control:
            failures.append(
                f"indexes differ: chaos {sorted(dg_chaos.items())} vs "
                f"control {sorted(dg_control.items())}")
        else:
            cst = EmbeddingStore(chaos_index)
            kst = EmbeddingStore(control_index)
            for dg in dg_chaos.values():
                with open(cst.object_path(dg), "rb") as a, \
                        open(kst.object_path(dg), "rb") as b:
                    if a.read() != b.read():
                        failures.append(f"object {dg[:16]}… bytes "
                                        "differ between indexes")
        if index_identity(chaos_index) != index_identity(control_index):
            failures.append("index_identity (the cache-scoping key) "
                            "differs between chaos and control")

        # ---- events: schema-valid, chaos line seals completed once.
        recs = []
        for p in (ev1, ev2, evc):
            recs.append(read_events(p, strict=True))
        sealed = [r for r in recs[1] if r["event"] == "index_build"
                  and r["state"] == "completed"]
        if len(sealed) != 1:
            failures.append(f"chaos resume sealed {len(sealed)} "
                            "index_build/completed record(s), expected "
                            "exactly 1")
        if not any(r["event"] == "index_shard" and r["state"] == "resume"
                   for r in recs[1]):
            failures.append("chaos resume emitted no "
                            "index_shard/resume record")

        # ---- the --verify detection gates, through the REAL CLI ----
        import contextlib
        import io

        from proteinbert_tpu.cli.main import main as cli_main

        def cli_verify():
            with contextlib.redirect_stdout(io.StringIO()):
                try:
                    return cli_main(["index", "--index", chaos_index,
                                     "--verify"])
                except SystemExit as e:
                    return int(e.code or 0)

        if cli_verify() != 0:
            failures.append("pbt index --verify failed on the intact "
                            "chaos index")
        victim = sorted(v for k, v in dg_chaos.items()
                        if k != "centroids")[0]
        vpath = EmbeddingStore(chaos_index).object_path(victim)
        backup = vpath + ".backup"
        shutil.copyfile(vpath, backup)
        flip_byte(vpath)
        if cli_verify() == 0:
            failures.append("pbt index --verify MISSED a flipped byte")
        else:
            rep = verify_index(chaos_index)
            if not any(c.get("reason") == "digest_mismatch"
                       for c in rep["corrupt"]):
                failures.append("flipped byte not typed digest_mismatch:"
                                f" {rep['corrupt']}")
        os.replace(backup, vpath)
        shutil.copyfile(vpath, backup)
        os.remove(vpath)
        if cli_verify() == 0:
            failures.append("pbt index --verify MISSED a deleted block")
        else:
            rep = verify_index(chaos_index)
            if not any(h["digest"] == victim for h in rep["holes"]):
                failures.append(f"deleted block not reported as a hole: "
                                f"{rep['holes']}")
        os.replace(backup, vpath)
        if cli_verify() != 0:
            failures.append("chaos index did not verify clean after "
                            "restoring the mauled object")

        # ---- stale-pin refusal: a DIFFERENT store (new corpus/model)
        # must be a typed refusal BEFORE any write to the chaos index.
        other_store = os.path.join(outdir, "other_store")
        make_store(other_store, args.vectors, args.seed + 1)
        before = index_digests(chaos_index)
        rcs, _ = _run(_index_cmd(other_store, chaos_index,
                                 os.path.join(outdir, "stale.events.jsonl")),
                      log_path=log_path)
        if rcs == 0:
            failures.append("rebuilding the chaos index against a "
                            "different store succeeded — the manifest "
                            "pin did not refuse")
        if index_digests(chaos_index) != before:
            failures.append("the refused rebuild MUTATED the chaos "
                            "index — refusal must precede any write")

    summary = {
        "vectors": args.vectors,
        "shards": NUM_SHARDS,
        "index_blocks": stats2.get("blocks"),
        "rework_blocks": rework,
        "bytes_ratio": stats2.get("bytes_ratio"),
        "wall_s": round(time.monotonic() - t0, 1),
        "outdir": outdir,
        "failures": failures,
        "ok": not failures,
    }
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--vectors", type=int, default=40,
                    help="synthetic corpus size (2 shards x >= 2 index "
                         "blocks at the default geometry)")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--outdir", help="artifact dir (default: temp)")
    ap.add_argument("--json", action="store_true",
                    help="print the summary as one JSON object only")
    args = ap.parse_args(argv)
    if args.vectors < NUM_SHARDS * 2 * INDEX_BLOCK:
        ap.error(f"--vectors must give every shard >= 2 index blocks "
                 f"(>= {NUM_SHARDS * 2 * INDEX_BLOCK})")
    summary = run_drill(args)
    if args.json:
        print(json.dumps(summary))
    else:
        print(json.dumps(summary, indent=2))
    if not summary["ok"]:
        print("INDEX DRILL FAILED:", "; ".join(summary["failures"]),
              file=sys.stderr)
        return 1
    print(f"index drill OK: SIGKILL between object write and cursor "
          f"advance → byte-identical resume, "
          f"{summary['rework_blocks']} re-worked block(s) "
          f"(bound {NUM_SHARDS}), --verify catches flip/hole, stale "
          f"store pin refused ({summary['wall_s']}s)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
