#!/usr/bin/env python
"""Telemetry events-schema validator (CI/tooling satellite, ISSUE 3).

Validates an events JSONL (every line against obs.events.validate_record,
plus per-stream seq monotonicity) and, optionally, a flight-recorder
dump. `--self-test` round-trips one synthetic record of EVERY event type
through the validator — and asserts a deliberately broken record fails —
so a schema/fixture drift breaks CI immediately; tools/run_tier1.sh runs
it after the pytest tier.

No jax import (the obs package is stdlib-only): artifacts validate on
any machine.

Usage:
  python tools/validate_events.py events.jsonl [--flight flight_123.json]
  python tools/validate_events.py --self-test
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from proteinbert_tpu.obs.events import (  # noqa: E402
    EVENT_FIELDS, make_example, validate_record,
)
from proteinbert_tpu.obs.flight import validate_flight_dump  # noqa: E402


# Negative control: records the validator MUST reject, at least one
# per event type in EVENT_FIELDS (the --schema-sync mode asserts that
# coverage — a new event type cannot ship without a validator
# negative). Module-level so self_test and schema_sync share one list.
NEGATIVE_CASES = [
        {"v": 99, "event": "step", "seq": 0, "t": 0.0,
         "step": 1, "metrics": {}},
        {"v": 1, "event": "run_start", "seq": 0, "t": 0.0,
         "config": {}, "jax_version": "0.0.0"},  # missing pid
        {"v": 1, "event": "eval", "seq": 0, "t": 0.0,
         "step": -1, "metrics": {}},  # step must be >= 0
        {"v": 1, "event": "requeue", "seq": 0, "t": 0.0,
         "step": 1},  # missing reason
        {"v": 1, "event": "nan_halt", "seq": 0, "t": 0.0,
         "step": 1},  # missing metrics
        {"v": 1, "event": "serve_start", "seq": 0, "t": 0.0,
         "config": {}},  # missing pid
        {"v": 1, "event": "serve_end", "seq": 0, "t": 0.0,
         "outcome": "collapsed", "stats": {}},  # outcome drained|aborted
        {"v": 1, "event": "no_such_event", "seq": 0, "t": 0.0},
        {"v": 1, "event": "step", "seq": 0, "t": 0.0},  # missing fields
        {"v": 1, "event": "ckpt_stage", "seq": 0, "t": 0.0,
         "step": 1, "phase": "bogus"},
        {"v": 1, "event": "run_end", "seq": -1, "t": 0.0,
         "outcome": "completed", "perf": {}},
        # serve tracing / SLO types (ISSUE 6):
        {"v": 1, "event": "serve_request", "seq": 0, "t": 0.0,
         "kind": "embed", "outcome": "vanished", "request_id": "r1",
         "stages": {}},
        {"v": 1, "event": "serve_request", "seq": 0, "t": 0.0,
         "kind": "embed", "outcome": "ok", "request_id": "r1",
         "stages": {"queue": -0.5}},
        {"v": 1, "event": "serve_reject", "seq": 0, "t": 0.0,
         "reason": "queue_full", "queue_depth": -3},
        {"v": 1, "event": "slo_breach", "seq": 0, "t": 0.0,
         "objective": "latency_e2e"},  # missing burn_rate
        {"v": 1, "event": "slo_breach", "seq": 0, "t": 0.0,
         "objective": "latency_e2e", "burn_rate": float("nan")},
        # multi-tenant head registry (ISSUE 8):
        {"v": 1, "event": "head_registered", "seq": 0, "t": 0.0,
         "kind": "token_classification"},  # missing head_id
        {"v": 1, "event": "head_eval", "seq": 0, "t": 0.0,
         "head_id": "a1b2", "metrics": {"score": [0.5]}},  # non-scalar
        {"v": 1, "event": "serve_request", "seq": 0, "t": 0.0,
         "kind": "predict_task", "outcome": "ok", "request_id": "r1",
         "stages": {}, "head_id": 17},  # head_id must be a string
        {"v": 1, "event": "serve_reject", "seq": 0, "t": 0.0,
         "reason": "no_such_reason"},  # unknown_head is valid; this isn't
        # ragged packed serving (ISSUE 9): packed fields are optional
        # but TYPED — a writer bug must not slip through as "extra".
        {"v": 1, "event": "serve_batch", "seq": 0, "t": 0.0,
         "kind": "embed", "bucket_len": 256, "rows": 4,
         "segments": -1},  # segments must be >= 0
        {"v": 1, "event": "serve_batch", "seq": 0, "t": 0.0,
         "kind": "embed", "bucket_len": 256, "rows": 4,
         "mode": "bogus"},  # mode is bucketed|ragged
        {"v": 1, "event": "serve_batch", "seq": 0, "t": 0.0,
         "kind": "embed", "bucket_len": 256, "rows": 4,
         "pad_fraction": 1.5},  # pad_fraction in [0, 1]
        {"v": 1, "event": "serve_request", "seq": 0, "t": 0.0,
         "kind": "embed", "outcome": "ok", "request_id": "r1",
         "stages": {}, "segments_per_row": -2.0},  # must be >= 0
        {"v": 1, "event": "serve_request", "seq": 0, "t": 0.0,
         "kind": "embed", "outcome": "ok", "request_id": "r1",
         "stages": {}, "mode": "packed"},  # not a serve mode
        # elastic topology (ISSUE 11): reshard + fleet events.
        {"v": 1, "event": "reshard", "seq": 0, "t": 0.0,
         "step": 1, "target_mesh": {"data": 4}},  # missing wire_bytes
        {"v": 1, "event": "reshard", "seq": 0, "t": 0.0,
         "step": 1, "target_mesh": {"data": 4},
         "wire_bytes": {"total": -8}},  # bytes must be >= 0
        {"v": 1, "event": "reshard", "seq": 0, "t": 0.0,
         "step": 1, "target_mesh": {"data": 4},
         "wire_bytes": {"total": 1.5}},  # bytes are ints, not floats
        {"v": 1, "event": "fleet_replica", "seq": 0, "t": 0.0,
         "replica": "r0", "state": "limping"},  # unknown state
        {"v": 1, "event": "fleet_request", "seq": 0, "t": 0.0,
         "outcome": "vanished", "path": "/v1/embed"},  # unknown outcome
        {"v": 1, "event": "fleet_request", "seq": 0, "t": 0.0,
         "outcome": "ok", "path": "/v1/embed",
         "retries": -1},  # retries must be >= 0
        {"v": 1, "event": "fleet_request", "seq": 0, "t": 0.0,
         "outcome": "ok", "path": "/v1/embed",
         "status": 42},  # not an HTTP status code
        {"v": 1, "event": "fleet_request", "seq": 0, "t": 0.0,
         "outcome": "ok"},  # missing path
        {"v": 1, "event": "fleet_end", "seq": 0, "t": 0.0,
         "outcome": "collapsed", "stats": {}},  # outcome is drained|aborted
        {"v": 1, "event": "fleet_start", "seq": 0, "t": 0.0,
         "config": {}},  # missing pid
        # quantized serving arm (ISSUE 12): optional but TYPED fields.
        {"v": 1, "event": "serve_request", "seq": 0, "t": 0.0,
         "kind": "embed", "outcome": "ok", "request_id": "r1",
         "stages": {}, "quant": "int4"},  # not a quant mode
        {"v": 1, "event": "serve_batch", "seq": 0, "t": 0.0,
         "kind": "embed", "bucket_len": 256, "rows": 4,
         "quant": "quantized"},  # not a quant mode
        {"v": 1, "event": "serve_batch", "seq": 0, "t": 0.0,
         "kind": "embed", "bucket_len": 256, "rows": 4,
         "quant": "int8", "quant_parity_max": -0.5},  # must be >= 0
        {"v": 1, "event": "serve_request", "seq": 0, "t": 0.0,
         "kind": "embed", "outcome": "ok", "request_id": "r1",
         "stages": {}, "quant_parity_max": float("inf")},  # finite
        # the comm_quant capture note (bench --comm): the sentinel's
        # input series, so its ratio fields are typed + required.
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "bench", "kind": "comm_quant"},  # missing ratio
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "bench", "kind": "comm_quant",
         "int8_grad_wire_ratio": 0.0},  # ratio must be > 0
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "bench", "kind": "comm_quant",
         "int8_grad_wire_ratio": 0.27,
         "bf16_grad_wire_ratio": "half"},  # typed when present
        # the pack_attn_capture note (bench --pack attention arm,
        # ISSUE 13): sentinel-input fields are typed + required.
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "bench", "kind": "pack_attn_capture"},  # no speedup
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "bench", "kind": "pack_attn_capture",
         "attn_speedup_x": 0.0},  # speedup must be > 0
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "bench", "kind": "pack_attn_capture",
         "attn_speedup_x": 1.1,
         "mfu_effective": -0.2},  # MFU must be >= 0 when present
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "bench", "kind": "pack_attn_capture",
         "attn_speedup_x": 1.1,
         "parity_max_abs_diff": float("nan")},  # finite when present
        # the onepass_capture note (bench --pack one-pass arm, ISSUE
        # 16): sentinel-input fields are typed + required.
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "bench", "kind": "onepass_capture"},  # no speedup
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "bench", "kind": "onepass_capture",
         "onepass_speedup_x": 0.0},  # speedup must be > 0
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "bench", "kind": "onepass_capture",
         "onepass_speedup_x": 1.3,
         "mfu_effective": -0.1},  # MFU must be >= 0 when present
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "bench", "kind": "onepass_capture",
         "onepass_speedup_x": 1.3,
         "parity_max_abs_diff": float("inf")},  # finite when present
        # offline batch inference (ISSUE 14): map_* rows are typed —
        # the chaos drill audits streams with this validator, so a
        # writer bug must fail here, not corrupt the drill's verdict.
        {"v": 1, "event": "map_start", "seq": 0, "t": 0.0,
         "config": {"num_shards": 2}},  # missing pid
        {"v": 1, "event": "map_shard", "seq": 0, "t": 0.0,
         "shard": 0, "state": "crawling"},  # unknown shard state
        {"v": 1, "event": "map_shard", "seq": 0, "t": 0.0,
         "shard": -1, "state": "start"},  # shard must be >= 0
        {"v": 1, "event": "map_block", "seq": 0, "t": 0.0,
         "shard": 0, "block": 0, "digest": "xyz",
         "n": 8},  # digest must be a sha256 hex
        {"v": 1, "event": "map_block", "seq": 0, "t": 0.0,
         "shard": 0, "block": 0, "digest": "0" * 64,
         "n": 8, "retries": -2},  # retries must be >= 0
        {"v": 1, "event": "map_block", "seq": 0, "t": 0.0,
         "shard": 0, "block": 0, "digest": "0" * 64, "n": 8,
         "seqs_per_s": float("inf")},  # finite when present
        {"v": 1, "event": "map_end", "seq": 0, "t": 0.0,
         "outcome": "vanished", "stats": {}},  # unknown outcome
        # the map_capture throughput note (tools/map_drill.py
        # --bench-events): the sentinel's input series, typed+required.
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "map_drill", "kind": "map_capture"},  # missing rate
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "map_drill", "kind": "map_capture",
         "map_seqs_per_s": 0.0},  # rate must be > 0
        # the checkpointer's restore_fallback note: bad_step required,
        # landed_step (ISSUE 14 satellite) typed when present.
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "checkpoint", "kind": "restore_fallback"},  # no step
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "checkpoint", "kind": "restore_fallback",
         "bad_step": 3, "landed_step": -2},  # landed_step >= 0
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "checkpoint", "kind": "restore_fallback",
         "bad_step": 3, "landed_step": 2.5},  # landed_step is an int
        # the check_capture note (`pbt check --events-jsonl`, ISSUE
        # 15): the suppression-creep series, typed + required.
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "pbt_check", "kind": "check_capture"},  # no count
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "pbt_check", "kind": "check_capture",
         "check_findings_total": -1},  # count must be >= 0
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "pbt_check", "kind": "check_capture",
         "check_findings_total": 2,
         "check_baselined_total": 1.5},  # typed when present
        # the ANN index + /v1/neighbors subsystem (ISSUE 17): build
        # lifecycle, shard durability, and served-lookup rows are
        # typed — the index drill audits streams with this validator.
        {"v": 1, "event": "index_build", "seq": 0, "t": 0.0,
         "state": "running", "stats": {}},  # unknown build state
        {"v": 1, "event": "index_build", "seq": 0, "t": 0.0,
         "state": "completed"},  # missing stats
        {"v": 1, "event": "index_shard", "seq": 0, "t": 0.0,
         "shard": 0, "state": "crawling"},  # unknown shard state
        {"v": 1, "event": "index_shard", "seq": 0, "t": 0.0,
         "shard": -1, "state": "start"},  # shard must be >= 0
        {"v": 1, "event": "index_shard", "seq": 0, "t": 0.0,
         "shard": 0, "state": "resume",
         "tail_reworked": -1},  # rework count must be >= 0
        {"v": 1, "event": "neighbor_query", "seq": 0, "t": 0.0,
         "k": 0, "nprobe": 8},  # k must be >= 1
        {"v": 1, "event": "neighbor_query", "seq": 0, "t": 0.0,
         "k": 10, "nprobe": 8,
         "lookup_s": -0.001},  # lookup leg must be >= 0
        {"v": 1, "event": "neighbor_query", "seq": 0, "t": 0.0,
         "k": 10, "nprobe": 8,
         "outcome": "vanished"},  # not a request outcome
        # the neighbors_capture note (bench --neighbors): QPS + recall
        # feed trajectory-sentinel series, typed + required.
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "bench", "kind": "neighbors_capture"},  # no fields
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "bench", "kind": "neighbors_capture",
         "neighbors_qps": 0.0,
         "neighbors_recall_at_10": 0.97},  # qps must be > 0
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "bench", "kind": "neighbors_capture",
         "neighbors_qps": 5000.0,
         "neighbors_recall_at_10": 1.2},  # recall in [0, 1]
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "bench", "kind": "neighbors_capture",
         "neighbors_qps": 5000.0, "neighbors_recall_at_10": 0.97,
         "index_bytes_ratio": -0.3},  # typed when present
        # fleet-scope causal tracing (ISSUE 18): the propagated trace
        # context is optional but TYPED on every carrier event, and
        # fleet_attempt (one sibling record per router try) is fully
        # constrained — the fleet drill audits the MERGED stream with
        # this validator, so a propagation bug must fail here.
        {"v": 1, "event": "serve_request", "seq": 0, "t": 0.0,
         "kind": "embed", "outcome": "ok", "request_id": "r1",
         "stages": {}, "trace_id": 17},  # trace_id must be a string
        {"v": 1, "event": "serve_request", "seq": 0, "t": 0.0,
         "kind": "embed", "outcome": "ok", "request_id": "r1",
         "stages": {}, "replica_id": 0},  # replica_id must be a string
        {"v": 1, "event": "serve_batch", "seq": 0, "t": 0.0,
         "kind": "embed", "bucket_len": 256, "rows": 4,
         "replica_id": ["r0"]},  # replica_id must be a string
        {"v": 1, "event": "fleet_request", "seq": 0, "t": 0.0,
         "outcome": "ok", "path": "/v1/embed",
         "trace_id": 3.5},  # trace_id must be a string
        {"v": 1, "event": "fleet_attempt", "seq": 0, "t": 0.0,
         "trace_id": "f1-1", "attempt": 0, "replica": "r0",
         "outcome": "vanished"},  # not an attempt outcome
        {"v": 1, "event": "fleet_attempt", "seq": 0, "t": 0.0,
         "trace_id": "f1-1", "attempt": -1, "replica": "r0",
         "outcome": "ok"},  # attempt index must be >= 0
        {"v": 1, "event": "fleet_attempt", "seq": 0, "t": 0.0,
         "trace_id": "f1-1", "attempt": 0, "replica": "r0",
         "outcome": "retryable", "status": 42},  # not an HTTP status
        {"v": 1, "event": "fleet_attempt", "seq": 0, "t": 0.0,
         "trace_id": "f1-1", "attempt": 0, "replica": "r0",
         "outcome": "retryable", "backoff_s": -0.02},  # wait >= 0
        {"v": 1, "event": "fleet_attempt", "seq": 0, "t": 0.0,
         "trace_id": 99, "attempt": 0, "replica": "r0",
         "outcome": "ok"},  # trace_id must be a string
        # the fleet_trace_capture note (bench --serve fleet A/B arm):
        # the propagation-overhead sentinel's input, typed + required.
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "bench", "kind": "fleet_trace_capture"},  # no pct
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "bench", "kind": "fleet_trace_capture",
         "fleet_trace_overhead_pct": float("nan")},  # finite
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "bench", "kind": "fleet_trace_capture",
         "fleet_trace_overhead_pct": 0.4,
         "fleet_rps_on": 0.0},  # throughput must be > 0 when present
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "bench", "kind": "fleet_trace_capture",
         "fleet_trace_overhead_pct": 0.4,
         "rounds": 0},  # median round count must be >= 1 when present
        # the serve_pipeline_capture note (bench --serve pipeline A/B,
        # ISSUE 19): the pipelined-dispatch sentinel's input.
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "bench", "kind": "serve_pipeline_capture"},  # no x
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "bench", "kind": "serve_pipeline_capture",
         "serve_pipeline_speedup_x": 0.0},  # speedup must be > 0
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "bench", "kind": "serve_pipeline_capture",
         "serve_pipeline_speedup_x": 1.2,
         "serve_overlap_ratio": 1.5},  # a ratio: [0, 1]
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "bench", "kind": "serve_pipeline_capture",
         "serve_pipeline_speedup_x": 1.2,
         "inflight_max": -1},  # window depth watermark is >= 0
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "map_drill", "kind": "map_capture",
         "map_seqs_per_s": 10.0,
         "map_overlap_ratio": -0.1},  # a ratio: [0, 1]
        # blue-green trunk rollout (ISSUE 20): lifecycle, window
        # verdicts, shadow siblings, flips, and fleet coherence are
        # typed — the rollout drill audits the merged stream with this
        # validator, so a controller bug must fail here.
        {"v": 1, "event": "rollout_state", "seq": 0, "t": 0.0,
         "state": "sideways"},  # unknown rollout state
        {"v": 1, "event": "rollout_state", "seq": 0, "t": 0.0,
         "state": "promoted", "windows_green": -1},  # streak >= 0
        {"v": 1, "event": "rollout_state", "seq": 0, "t": 0.0,
         "state": "promoted",
         "flip_seconds": float("inf")},  # finite when present
        {"v": 1, "event": "rollout_window", "seq": 0, "t": 0.0,
         "window": 0, "verdict": "maybe"},  # verdict is pass|fail
        {"v": 1, "event": "rollout_window", "seq": 0, "t": 0.0,
         "window": -1, "verdict": "pass"},  # window index >= 0
        {"v": 1, "event": "rollout_window", "seq": 0, "t": 0.0,
         "window": 0, "verdict": "pass",
         "parity_max": -0.5},  # parity must be >= 0
        {"v": 1, "event": "rollout_window", "seq": 0, "t": 0.0,
         "window": 0, "verdict": "fail",
         "slo_burn_delta": float("nan")},  # finite when present
        {"v": 1, "event": "rollout_shadow", "seq": 0, "t": 0.0,
         "trace_id": "f1-1", "replica": "r0", "outcome": "ok",
         "shadow": False},  # a shadow record MUST flag shadow=true
        {"v": 1, "event": "rollout_shadow", "seq": 0, "t": 0.0,
         "trace_id": "f1-1", "replica": "r0", "outcome": "mirrored",
         "shadow": True},  # outcome is ok|failed
        {"v": 1, "event": "rollout_shadow", "seq": 0, "t": 0.0,
         "trace_id": "f1-1", "replica": "r0", "outcome": "failed",
         "shadow": True, "status": 42},  # HTTP status or 0
        {"v": 1, "event": "rollout_flip", "seq": 0, "t": 0.0,
         "replica": "r0", "phase": "sideways",
         "seconds": 0.01},  # phase is flip|rollback
        {"v": 1, "event": "rollout_flip", "seq": 0, "t": 0.0,
         "replica": "r0", "phase": "flip",
         "seconds": -0.5},  # swap latency must be >= 0
        {"v": 1, "event": "rollout_fleet", "seq": 0, "t": 0.0,
         "state": "mixed"},  # state is coherent|degraded
        {"v": 1, "event": "rollout_fleet", "seq": 0, "t": 0.0,
         "state": "degraded", "fingerprints": -2},  # count >= 0
        # the rollout_capture note (tools/rollout_drill.py): shadow
        # parity + flip latency feed trajectory-sentinel series,
        # typed + required.
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "rollout_drill", "kind": "rollout_capture"},  # none
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "rollout_drill", "kind": "rollout_capture",
         "rollout_shadow_parity_max": -1e-6,
         "rollout_flip_seconds": 0.2},  # parity must be >= 0
        {"v": 1, "event": "note", "seq": 0, "t": 0.0,
         "source": "rollout_drill", "kind": "rollout_capture",
         "rollout_shadow_parity_max": 1e-6,
         "rollout_flip_seconds": float("inf")},  # finite
]


def self_test() -> int:
    for event in sorted(EVENT_FIELDS):
        rec = make_example(event)
        try:
            validate_record(rec)
            # And through a JSON round trip, like real consumers see it.
            validate_record(json.loads(json.dumps(rec)))
        except ValueError as e:
            print(f"SELF-TEST FAIL: example {event!r} does not validate: {e}")
            return 1
    for rec in NEGATIVE_CASES:
        try:
            validate_record(rec)
        except ValueError:
            continue
        print(f"SELF-TEST FAIL: accepted invalid record {rec!r}")
        return 1
    print(f"self-test OK: {len(EVENT_FIELDS)} event types round-trip, "
          f"{len(NEGATIVE_CASES)} invalid records rejected")
    return 0


def schema_sync() -> int:
    """--schema-sync (ISSUE 15 satellite): every event type in
    EVENT_FIELDS must have at least one negative case above — adding
    an event without teaching the validator's negative suite what a
    BROKEN record of it looks like fails the `pbt check` tier-1 stage,
    so schema growth and validator coverage move together."""
    covered = {rec.get("event") for rec in NEGATIVE_CASES}
    covered.discard(None)
    missing = sorted(set(EVENT_FIELDS) - covered)
    if missing:
        print("SCHEMA-SYNC FAIL: event type(s) with no negative case "
              f"in tools/validate_events.py: {missing} — add at least "
              "one deliberately-broken record per type")
        return 1
    extra = sorted(c for c in covered
                   if c not in EVENT_FIELDS and c != "no_such_event")
    if extra:
        print(f"SCHEMA-SYNC FAIL: negative cases reference unknown "
              f"event type(s) {extra}")
        return 1
    print(f"schema-sync OK: all {len(EVENT_FIELDS)} event types have "
          "validator negatives")
    return 0


def validate_file(path: str) -> int:
    errors = 0
    count = 0
    last_seq: dict = {}
    with open(path) as f:
        for lineno, line in enumerate(f, start=1):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                print(f"{path}:{lineno}: not JSON: {e}")
                errors += 1
                continue
            try:
                validate_record(rec)
            except ValueError as e:
                print(f"{path}:{lineno}: {e}")
                errors += 1
                continue
            # seq must be monotonic within one emitting process; seq 0
            # legitimately restarts the stream (a requeued run appends
            # its fresh run_start to the same file).
            prev = last_seq.get("run")
            if prev is not None and rec["seq"] <= prev and rec["seq"] != 0:
                print(f"{path}:{lineno}: seq {rec['seq']} not > previous "
                      f"{prev} (and not a fresh stream)")
                errors += 1
            last_seq["run"] = rec["seq"]
            count += 1
    print(f"{path}: {count} records, {errors} errors")
    return 1 if errors else 0


def validate_flight(path: str) -> int:
    with open(path) as f:
        try:
            payload = json.load(f)
        except ValueError as e:
            print(f"{path}: not JSON: {e}")
            return 1
    try:
        validate_flight_dump(payload)
    except ValueError as e:
        print(f"{path}: invalid flight dump: {e}")
        return 1
    print(f"{path}: valid flight dump ({len(payload['events'])} events, "
          f"reason={payload['reason']!r})")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("events", nargs="?", help="events JSONL to validate")
    ap.add_argument("--flight", help="flight-recorder dump to validate")
    ap.add_argument("--self-test", action="store_true",
                    help="validate the schema fixtures themselves")
    ap.add_argument("--schema-sync", action="store_true",
                    help="assert the negative-case list covers every "
                         "event type in EVENT_FIELDS (the `pbt check` "
                         "stage's coverage gate)")
    args = ap.parse_args(argv)
    if not any((args.events, args.flight, args.self_test,
                args.schema_sync)):
        ap.error("give an events JSONL, --flight, --self-test, or "
                 "--schema-sync")
    # All requested checks COMPOSE — combining --schema-sync with an
    # events file must validate both, never silently skip one.
    rc = 0
    if args.schema_sync:
        rc |= schema_sync()
    if args.self_test:
        rc |= self_test()
    if args.events:
        rc |= validate_file(args.events)
    if args.flight:
        rc |= validate_flight(args.flight)
    return rc


if __name__ == "__main__":
    sys.exit(main())
