#!/usr/bin/env python
"""Fleet fault-injection drill (ISSUE 11): kill a replica mid-request,
inject latency spikes and torn health responses, and PROVE — via the
router's exactly-once seal funnel plus each replica's PR 6 trace
funnel — that every accepted request terminates in exactly one sealed
outcome (served or typed-rejected; none lost, none double-sealed).

The drill runs a real fleet in one process on CPU: N in-process serve
replicas (each a full `serve.Server` + HTTP endpoint with its own
telemetry stream) behind a real `FleetRouter` + HTTP front, driven by
concurrent HTTP clients. Mid-run it

  1. injects a latency spike on the victim replica (so requests are
     genuinely in flight on it),
  2. KILLS the victim — `Server.abort()` + socket close, the
     hardest-landing kill an in-process replica can take: pending
     futures fail with ServerClosedError (HTTP 503) and new
     connections are refused — the router must retry both shapes,
  3. tears another replica's health responses for a few checks (it
     must go dead and then be re-admitted once the tear clears).

Gates (exit nonzero on violation — tier-1 runs this as a smoke stage):
  - router accepted == router sealed == sum(outcomes); every client
    call got exactly one typed response (2xx or typed-error JSON);
  - zero lost: client 200s == ok+retried_ok+cache_hit,
    typed rejections == shed+failed;
  - failover actually happened: retried_ok >= 1 and the victim's
    stream shows aborted/rejected seals;
  - every router/replica event record round-trips the schema
    validator; no request_id seals twice within a stream;
  - fleet tracing (ISSUE 18): every client 200 carries an
    X-PBT-Request-Id naming a sealed trace; the MERGED stream
    (FleetCollector over router + every replica) is schema-valid and
    re-sequenced 0..N-1 with exactly-once sealing and attempts ==
    retries + 1 per trace; a request whose FIRST attempt died on the
    killed victim reconstructs as one COMPLETE causal chain via a
    `pbt diagnose --fleet --trace-id` subprocess over the merged
    stream alone;
  - grey failure: with one replica answering health checks SLOWLY
    (injector.set_health_latency), the health loop keeps visiting
    every replica (scrape counts advance — no starvation), measured
    by the fleet_health_scrape_seconds histogram.

Latency/shed ratios are reported, not gated (a 1-core CI box is noisy).

Usage:
  python tools/fleet_drill.py [--replicas 3] [--requests 60]
      [--clients 8] [--kill-frac 0.3] [--outdir DIR] [--json]
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PBT_DISABLE_DONATION", "1")

SEQ_LEN = 48
BUCKETS = (16, 32, 48)
AA = "ACDEFGHIKLMNPQRSTVWY"


def _tiny_cfg():
    from proteinbert_tpu.configs import (
        DataConfig, ModelConfig, OptimizerConfig, PretrainConfig,
        TrainConfig,
    )

    return PretrainConfig(
        model=ModelConfig(local_dim=16, global_dim=32, key_dim=8,
                          num_heads=2, num_blocks=2, num_annotations=32,
                          dtype="float32"),
        data=DataConfig(seq_len=SEQ_LEN, batch_size=4),
        optimizer=OptimizerConfig(warmup_steps=5),
        train=TrainConfig(seed=0, max_steps=1),
    )


class LocalReplica:
    """One in-process serve replica: Server + HTTP endpoint + its own
    telemetry events stream (the PR 6 per-request trace funnel)."""

    def __init__(self, name: str, params, cfg, events_path: str):
        from proteinbert_tpu.obs import Telemetry
        from proteinbert_tpu.serve import Server
        from proteinbert_tpu.serve.http import make_http_server

        self.name = name
        self.events_path = events_path
        self.tele = Telemetry(events_path=events_path)
        self.server = Server(
            params, cfg, buckets=BUCKETS, max_batch=4, max_wait_s=0.005,
            queue_depth=64, cache_size=256, telemetry=self.tele,
            trace_sample_rate=1.0, replica_id=name)
        self.server.start()
        self.httpd = make_http_server(self.server, "127.0.0.1", 0)
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        self.thread = threading.Thread(target=self.httpd.serve_forever,
                                       daemon=True, name=f"{name}-http")
        self.thread.start()
        self.killed = False

    def kill(self):
        """Mid-request hard landing: pending work fails typed (503),
        then the socket goes away (connection refused)."""
        self.killed = True
        self.server.abort()
        self.httpd.shutdown()
        self.httpd.server_close()
        self.tele.close()

    def drain(self):
        if self.killed:
            return
        self.httpd.shutdown()
        self.httpd.server_close()
        self.server.drain(timeout=30)
        self.tele.close()


def _post(url: str, payload: dict, timeout: float = 60.0):
    """POST returning (status, body, fleet id) — the X-PBT-Request-Id
    header is the trace id `pbt diagnose --fleet --trace-id` takes."""
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return (resp.status, json.loads(resp.read()),
                    resp.headers.get("X-PBT-Request-Id"))
    except urllib.error.HTTPError as e:
        rid = e.headers.get("X-PBT-Request-Id") if e.headers else None
        try:
            return e.code, json.loads(e.read()), rid
        except ValueError:
            return e.code, None, rid


def run_drill(args) -> dict:
    import numpy as np

    from faults import FaultInjector  # tools/faults.py: the one shared
    # injection surface of the fleet and map drills (ISSUE 14)
    from proteinbert_tpu.obs import Telemetry, read_events
    from proteinbert_tpu.serve.fleet import (
        FleetRouter, make_fleet_http_server,
    )
    from proteinbert_tpu.train import create_train_state

    outdir = args.outdir or tempfile.mkdtemp(prefix="pbt_fleet_drill_")
    os.makedirs(outdir, exist_ok=True)
    cfg = _tiny_cfg()
    import jax

    params = create_train_state(jax.random.PRNGKey(0), cfg).params

    replicas = [
        LocalReplica(f"r{i}", params, cfg,
                     os.path.join(outdir, f"replica{i}.events.jsonl"))
        for i in range(args.replicas)
    ]
    router_events = os.path.join(outdir, "router.events.jsonl")
    tele = Telemetry(events_path=router_events)
    injector = FaultInjector()
    router = FleetRouter(
        [(r.name, r.url) for r in replicas], telemetry=tele,
        health_interval_s=0.1, health_timeout_s=1.0,
        fail_threshold=2, readmit_threshold=2,
        max_retries=args.replicas, backoff_base_s=0.02,
        backoff_cap_s=0.2, retry_budget_ratio=0.5,
        retry_budget_floor=max(8, args.requests // 2),
        request_timeout_s=60.0, cache_size=512,
        fault_injector=injector,
    ).start()
    httpd = make_fleet_http_server(router, "127.0.0.1", 0)
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    threading.Thread(target=httpd.serve_forever, daemon=True,
                     name="router-http").start()

    rng = np.random.default_rng(args.seed)
    payloads = []
    for i in range(args.requests):
        n = int(rng.integers(5, SEQ_LEN - 2))
        seq = "".join(rng.choice(list(AA), size=n))
        if i % 3 == 2:
            payloads.append(("/v1/predict_go", {"seq": seq, "top_k": 3}))
        else:
            payloads.append(("/v1/embed", {"seq": seq}))

    results: list = [None] * args.requests
    done_count = [0]
    done_lock = threading.Lock()
    victim = replicas[1 % len(replicas)]
    torn = replicas[0]

    def client(worker: int):
        for i in range(worker, args.requests, args.clients):
            path, payload = payloads[i]
            results[i] = _post(base + path, payload)
            with done_lock:
                done_count[0] += 1

    threads = [threading.Thread(target=client, args=(w,), daemon=True)
               for w in range(args.clients)]
    for t in threads:
        t.start()

    # Fault sequence: latency spike on the victim (requests pile onto
    # it), kill it mid-flight, tear another replica's health for a few
    # checks, then clear the tear (it must come back).
    kill_at = max(1, int(args.requests * args.kill_frac))
    while True:
        with done_lock:
            if done_count[0] >= kill_at:
                break
        time.sleep(0.005)
    injector.set_latency(victim.name, 0.15)
    time.sleep(0.05)  # let some requests enter the spike window
    victim.kill()
    injector.set_latency(victim.name, 0.0)
    injector.tear_health(torn.name)
    time.sleep(0.35)  # >= fail_threshold * health_interval → dead
    injector.tear_health(torn.name, torn=False)

    for t in threads:
        t.join(timeout=300)
    assert not any(t.is_alive() for t in threads), "client hang"
    # Let the torn replica's re-admission land on the record.
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        st = {r["name"]: r["state"] for r in router.replica_status()}
        if st[torn.name] in ("up", "degraded"):
            break
        time.sleep(0.05)

    # Grey-failure window (ISSUE 18): one replica answers health
    # checks SLOWLY (not dead, not torn — the failure mode health
    # binaries miss). The health loop must keep visiting EVERY
    # replica: scrape counts all advance across the window, and the
    # slow replica's latency lands in fleet_health_scrape_seconds.
    grey_failures = []
    injector.set_health_latency(torn.name, 0.35)
    before = {name: h.count for name, h in router._scrape_h.items()}
    time.sleep(1.6)  # several sweeps even at ~0.35s+interval each
    after = {name: h.count for name, h in router._scrape_h.items()}
    injector.set_health_latency(torn.name, 0.0)
    starved = sorted(n for n in before if after[n] <= before[n])
    if starved:
        grey_failures.append(
            f"health loop starved under a slow replica: no new scrape "
            f"of {starved} during the grey window")
    slow_max = router._scrape_h[torn.name].max
    if slow_max < 0.3:
        grey_failures.append(
            f"fleet_health_scrape_seconds never measured the injected "
            f"0.35s health latency (max {slow_max:.3f}s)")

    httpd.shutdown()
    httpd.server_close()
    router.drain()
    for r in replicas:
        r.drain()
    tele.close()

    # ------------------------------------------------------------ audit
    failures = []
    stats = router.stats()
    outcomes = stats["outcomes"]
    if stats["accepted"] != stats["sealed"]:
        failures.append(f"router accepted {stats['accepted']} != sealed "
                        f"{stats['sealed']}")
    if sum(outcomes.values()) != stats["sealed"]:
        failures.append(f"outcome sum {sum(outcomes.values())} != sealed "
                        f"{stats['sealed']}")
    if stats["accepted"] != args.requests:
        failures.append(f"router accepted {stats['accepted']} != "
                        f"{args.requests} sent")

    lost = sum(1 for r in results if r is None)
    if lost:
        failures.append(f"{lost} client requests got NO response")
    ok_like = sum(1 for r in results if r and r[0] == 200)
    typed_rejects = sum(
        1 for r in results
        if r and r[0] != 200 and isinstance(r[1], dict) and "type" in r[1])
    untyped = args.requests - lost - ok_like - typed_rejects
    if untyped:
        failures.append(f"{untyped} client responses were neither 200 "
                        "nor typed-error JSON")
    want_ok = (outcomes.get("ok", 0) + outcomes.get("retried_ok", 0)
               + outcomes.get("cache_hit", 0))
    if ok_like != want_ok:
        failures.append(f"client 200s {ok_like} != router ok-like "
                        f"{want_ok}")
    want_reject = outcomes.get("shed", 0) + outcomes.get("failed", 0)
    if typed_rejects != want_reject:
        failures.append(f"client typed rejections {typed_rejects} != "
                        f"router shed+failed {want_reject}")
    if not outcomes.get("retried_ok"):
        failures.append("no retried_ok outcome — the kill never "
                        "exercised failover")

    # Schema validity + per-stream exactly-once sealing.
    from proteinbert_tpu.obs.events import validate_record  # noqa: F401

    rrecs = read_events(router_events, strict=True)
    freqs = [r for r in rrecs if r["event"] == "fleet_request"]
    if len(freqs) != stats["sealed"]:
        failures.append(f"{len(freqs)} fleet_request events != "
                        f"{stats['sealed']} sealed")
    rids = [r["request_id"] for r in freqs if "request_id" in r]
    dupes = [k for k, n in collections.Counter(rids).items() if n > 1]
    if dupes:
        failures.append(f"router double-sealed request ids: {dupes[:5]}")
    states_seen = [r["state"] for r in rrecs
                   if r["event"] == "fleet_replica"]
    if "dead" not in states_seen:
        failures.append("no fleet_replica{state=dead} transition on "
                        "the record")
    if "admitted" not in states_seen:
        failures.append("torn-health replica was never re-admitted")

    victim_aborted = 0
    for r in replicas:
        recs = read_events(r.events_path, strict=True)
        seals = [x for x in recs if x["event"] == "serve_request"]
        per_id = collections.Counter(x["request_id"] for x in seals)
        dup = [k for k, n in per_id.items() if n > 1]
        if dup:
            failures.append(f"replica {r.name} double-sealed: {dup[:5]}")
        if r is victim:
            victim_aborted = sum(1 for x in seals
                                 if x["outcome"] in ("aborted", "error"))

    failures.extend(grey_failures)

    # ------------------------------------ fleet trace plane (ISSUE 18)
    from proteinbert_tpu.obs.diagnose import summarize_fleet
    from proteinbert_tpu.serve.fleet import FleetCollector

    # Every client 200 must carry the fleet id, and every id a client
    # saw must name a sealed trace — one id end-to-end.
    sealed_ids = {r.get("trace_id") or r.get("request_id")
                  for r in freqs}
    no_header = sum(1 for r in results if r and r[0] == 200 and not r[2])
    if no_header:
        failures.append(f"{no_header} client 200s carried no "
                        "X-PBT-Request-Id header")
    unknown_ids = sorted({r[2] for r in results if r and r[2]}
                         - sealed_ids)
    if unknown_ids:
        failures.append(f"client-visible fleet ids never sealed: "
                        f"{unknown_ids[:5]}")

    # One merged, seq-ordered fleet stream: router + every replica
    # through the torn-tail-tolerant reader, re-sequenced 0..N-1.
    collector = FleetCollector({"router": router_events})
    for r in replicas:
        collector.add_source(r.name, r.events_path)
    merged_path = os.path.join(outdir, "merged.events.jsonl")
    merged_n = collector.write(merged_path)
    merged = read_events(merged_path, strict=True)
    if len(merged) != merged_n:
        failures.append(f"merged stream re-read {len(merged)} of "
                        f"{merged_n} written records")
    for i, rec in enumerate(merged):
        try:
            validate_record(rec)
        except ValueError as e:
            failures.append(f"merged stream schema break at record "
                            f"{i}: {e}")
            break
    if [r["seq"] for r in merged] != list(range(len(merged))):
        failures.append("merged stream seq is not a dense 0..N-1 "
                        "re-sequencing")
    viol = FleetCollector.seal_violations(merged)
    if viol:
        failures.append(f"exactly-once sealing broke in the merged "
                        f"stream: {dict(list(viol.items())[:5])}")
    fsum = summarize_fleet(merged)
    if fsum["attempt_mismatches"]:
        failures.append(f"attempts != retries + 1 for traces "
                        f"{fsum['attempt_mismatches'][:5]}")
    if fsum["incomplete"]:
        failures.append(f"incomplete causal chains in the merged "
                        f"stream: {fsum['incomplete'][:5]}")

    # The headline gate: a request whose attempt DIED on the killed
    # victim must reconstruct as one complete causal chain — via the
    # actual CLI subprocess, from the merged stream ALONE.
    attempts_by_tid: dict = {}
    for rec in merged:
        if rec["event"] == "fleet_attempt":
            attempts_by_tid.setdefault(rec["trace_id"], []).append(rec)
    victim_tid = None
    for rec in freqs:
        if rec.get("outcome") != "retried_ok":
            continue
        atts = sorted(attempts_by_tid.get(rec.get("trace_id"), []),
                      key=lambda a: a["attempt"])
        if (atts and atts[-1]["outcome"] == "ok"
                and any(a["replica"] == victim.name
                        and a["outcome"] in ("transport_failed",
                                             "retryable")
                        for a in atts)):
            victim_tid = rec["trace_id"]
            break
    chain = None
    if victim_tid is None:
        failures.append(
            "no retried_ok trace with a failed attempt on the killed "
            "victim — the reconstruction gate never ran")
    else:
        import subprocess

        perfetto_path = os.path.join(outdir, "fleet_trace.json")
        proc = subprocess.run(
            [sys.executable, "-m", "proteinbert_tpu", "diagnose",
             merged_path, "--fleet", "--trace-id", victim_tid,
             "--trace-perfetto", perfetto_path, "--json"],
            capture_output=True, text=True, timeout=120, cwd=REPO)
        if proc.returncode != 0:
            failures.append(f"pbt diagnose --fleet subprocess failed "
                            f"rc={proc.returncode}: "
                            f"{proc.stderr.strip()[:300]}")
        else:
            # --trace-perfetto logs a line before the JSON summary.
            chain = json.loads(
                proc.stdout.strip().splitlines()[-1])["fleet"].get(
                "chain")
            if chain is None:
                failures.append(f"diagnose found no chain for trace "
                                f"{victim_tid} in the merged stream")
            elif not chain["complete"]:
                failures.append(f"trace {victim_tid} reconstructed "
                                f"INCOMPLETE: {chain}")
            elif not any(a["replica"] == victim.name
                         for a in chain["attempts"]):
                failures.append(f"reconstructed chain for {victim_tid} "
                                "lost the victim attempt")
            elif chain["attempts"][-1]["serve"] is None:
                failures.append(
                    f"winning attempt of {victim_tid} joined no "
                    "replica-side serve_request (stage tiling missing)")
            with open(perfetto_path) as f:
                lanes = {e.get("tid") for e in
                         json.load(f)["traceEvents"]
                         if e.get("ph") == "X"}
            if len(lanes) < 3:
                failures.append(
                    f"cross-process Perfetto export has {len(lanes)} "
                    "lane(s); want router + one per attempt (>= 3)")

    summary = {
        "requests": args.requests,
        "clients": args.clients,
        "replicas": args.replicas,
        "router": {k: stats[k] for k in
                   ("accepted", "sealed", "outcomes", "retries_spent")},
        "client_200": ok_like,
        "client_typed_rejects": typed_rejects,
        "victim": victim.name,
        "victim_aborted_or_errored_seals": victim_aborted,
        "replica_states_seen": sorted(set(states_seen)),
        "cache": stats["cache"],
        "outdir": outdir,
        "merged_stream": merged_path,
        "merged_records": merged_n,
        "traces": fsum["traces"],
        "attempts_recorded": fsum["attempts_recorded"],
        "reconstructed_trace": victim_tid,
        "reconstructed_attempts": (len(chain["attempts"])
                                   if chain else None),
        "health_scrapes_in_grey_window": {
            n: after[n] - before[n] for n in sorted(after)},
        "slow_health_scrape_max_s": round(slow_max, 3),
        "failures": failures,
        "ok": not failures,
    }
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--requests", type=int, default=60)
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--kill-frac", type=float, default=0.3,
                    help="kill the victim after this fraction of "
                         "requests completed")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--outdir", help="artifact dir (default: temp)")
    ap.add_argument("--json", action="store_true",
                    help="print the summary as one JSON object only")
    args = ap.parse_args(argv)
    if args.replicas < 2:
        ap.error("the drill needs >= 2 replicas (one dies)")
    summary = run_drill(args)
    if args.json:
        print(json.dumps(summary))
    else:
        print(json.dumps(summary, indent=2))
    if not summary["ok"]:
        print("FLEET DRILL FAILED:", "; ".join(summary["failures"]),
              file=sys.stderr)
        return 1
    print(f"fleet drill OK: {summary['requests']} accepted, all sealed "
          f"exactly once ({summary['router']['outcomes']}), victim "
          f"{summary['victim']} killed mid-request, "
          f"{summary['router']['retries_spent']} retries; merged "
          f"{summary['merged_records']} records across "
          f"{summary['traces']} traces, killed-victim trace "
          f"{summary['reconstructed_trace']} reconstructed with "
          f"{summary['reconstructed_attempts']} attempts",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
