"""Render the round-5 hardware evidence into one markdown report.

The watcher's after-sweep hook chains: sweep → sweep_decision → transfer
full → sustained full. This tool folds whatever artifacts exist into
`experiments/r5_report.md` so the capture's story (fresh scan-variant
rows, the flip-or-null call, the transfer two-arm table, the sustained
run's per-window MFU attribution — VERDICT r4 items 1/2/4) is readable
in one place the moment the chain finishes, even unattended. Missing
artifacts render as explicit "not captured" sections, never as silence.

Usage: python tools/post_capture_report.py [--out PATH]
Exit 0 always (a report about missing evidence is still a report).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _load(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _fmt_row(r):
    # .get throughout: legacy (round-2) merged rows carry no
    # ms_per_step (bench.py's merge path), and one malformed row must
    # not kill the whole "never fail, never go silent" report.
    ms = r.get("ms_per_step")
    return ("| {} | {} | {} | {} | {:,.0f} | {:.4f} | {} |".format(
        r.get("variant", "?"), r.get("seq_len", "?"), r.get("batch", "?"),
        f"{ms:.1f}" if ms is not None else "?",
        r.get("residues_per_sec", 0), r.get("mfu", 0),
        r.get("captured_at", "?")))


def bench_section(lines):
    from bench import LAST_GOOD_PATH, last_good_captured_at, stale_age_hours

    lg = _load(LAST_GOOD_PATH)
    lines.append("## Bench sweep (bench_last_tpu.json)\n")
    if not lg or lg.get("platform") != "tpu":
        lines.append("**Not captured** — no last-good TPU record.\n")
        return
    age = stale_age_hours(last_good_captured_at(lg))
    lines.append(f"Headline: **{lg.get('value'):,.0f} res/s/chip** "
                 f"(vs_baseline {lg.get('vs_baseline')}), headline row "
                 f"age {age:.1f} h at report time.\n"
                 if age is not None else "Headline present, age unknown.\n")
    lines.append("| variant | seq | batch | ms/step | res/s | MFU | captured |")
    lines.append("|---|---|---|---|---|---|---|")
    for r in sorted(lg.get("sweep", []),
                    key=lambda r: -r.get("residues_per_sec", 0)):
        lines.append(_fmt_row(r))
    lines.append("")


def decision_section(lines):
    path = os.path.join(REPO, "experiments", "sweep_decision_r5.txt")
    rec = _load(path)
    lines.append("## Scan-lever decision (tools/sweep_decision.py)\n")
    if rec is None:
        lines.append(f"**Not recorded** — {path} absent/unparseable; run "
                     "`python tools/sweep_decision.py > "
                     "experiments/sweep_decision_r5.txt` (the tool prints "
                     "to stdout only).\n")
        return
    lines.append(f"Decision: **{rec.get('decision')}**")
    if rec.get("action"):
        lines.append(f"\nAction: {rec['action']}")
    for name, row in (rec.get("scan_variants") or {}).items():
        lines.append(f"- `{name}`: "
                     + (f"MFU {row['mfu']}, gain {row['gain_vs_baseline']:+.2%}"
                        if row else "unmeasured"))
    lines.append("")


def transfer_section(lines):
    rec = _load(os.path.join(REPO, "experiments", "transfer_r5",
                             "transfer_result.json"))
    lines.append("## Transfer (--scale full, experiments/transfer_r5)\n")
    if rec is None:
        lines.append("**Not captured** — transfer_result.json absent "
                     "(BASELINE.md's full-scale table stays pending).\n")
        return
    lines.append("```json")
    lines.append(json.dumps(rec, indent=2))
    lines.append("```\n")


def sustained_section(lines):
    outdir = os.path.join(REPO, "experiments", "sustained_r5")
    summ = _load(os.path.join(outdir, "sustained_summary.json"))
    lines.append("## Sustained run (experiments/sustained_r5)\n")
    if summ is None:
        lines.append("**Not captured** — sustained_summary.json absent; "
                     "the r3 collapse attribution stays open.\n")
        return
    win = summ.get("windows") or {}
    lines.append(f"Steps {summ.get('steps')}, killed at "
                 f"{summ.get('killed_at')} (rc {summ.get('resume_rc')}), "
                 f"final loss {summ.get('final_loss')}, final cumulative "
                 f"MFU {summ.get('final_mfu')}.\n")
    if win:
        lines.append(f"Window MFU median {win.get('median_mfu')} "
                     f"(min {win.get('min_mfu')}, max {win.get('max_mfu')}).")
        slow = win.get("slow_windows") or []
        if slow:
            lines.append(f"{len(slow)} slow windows (<50% of median): "
                         + ", ".join(
                             f"step {s} (MFU {m}, t={t})"
                             for s, m, t in slow))
            lines.append("Save-overlapped (ckpt_in_flight) among them: "
                         f"{win.get('slow_with_ckpt_in_flight')}")
        else:
            lines.append("No slow windows — per-window rate held through "
                         "the run (the r3 collapse did NOT reproduce).")
        lines.append(f"LR cuts at: {summ.get('lr_cuts_at')}")
    lines.append("")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(REPO, "experiments",
                                                  "r5_report.md"))
    args = ap.parse_args()
    lines = ["# Round-5 hardware evidence report\n"]
    for section in (bench_section, decision_section, transfer_section,
                    sustained_section):
        try:
            section(lines)
        except Exception as e:  # a malformed artifact must cost one
            # section, not the report ("never fail, never go silent")
            lines.append(f"**Section {section.__name__} failed to "
                         f"render: {e!r}** — inspect the artifact.\n")
    if os.path.dirname(args.out):
        os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        f.write("\n".join(lines) + "\n")
    print(args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
