#!/usr/bin/env python
"""Ragged Pallas attention + tiled-segment fused-block smoke (ISSUE 13,
tier-1 stage).

Tiny shapes through the real dispatch entries (interpret mode on CPU —
the same kernels Mosaic compiles on TPU), gates:

  1. PACKED ATTENTION PARITY — the segment-layout Pallas attention
     kernel vs `packed_global_attention_apply` on a training-style
     layout AND a serving-style layout (bucket-quantized spans with
     <pad> tails via real_mask), per-output deviation <= 1e-5, with the
     dispatch counted on `attention_kernel_path_total{path=pallas,
     reason=packed}` and ZERO reason=segments fallbacks.
  2. DENSE ATTENTION PARITY — the S=1 entry vs `global_attention_apply`
     including a fully-padded batch-class row (uniform-softmax
     semantics preserved), counted as path=pallas/reason=dense.
  3. VJP — gradient parity of the custom-VJP backward vs autodiff
     through the masked-XLA reference, <= 1e-4.
  4. FORCED OVERRIDE — PBT_FORCE_REFERENCE_KERNEL routes a fresh
     attention trace onto the reference path (reason=forced),
     bit-identical to the reference.
  5. TILED SEGMENT FUSED BLOCK — one C=1024 packed row through
     `fused_local_track_segments` runs the channel-tiled SEGMENT
     variant (pallas/packed, zero reason=segments) and matches the
     boundary-masked reference at bf16 tolerance.
  6. NOTE SCHEMA — a synthetic `note(kind=pack_attn_capture)` record
     round-trips the events validator (the sentinel-series contract).

Exit nonzero on any violation — this stage GATES (run_tier1.sh).
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

PARITY_BOUND = 1e-5   # documented jitted tolerance
GRAD_BOUND = 1e-4
TILED_BOUND = 0.05    # bf16 tiled tolerance (tests/test_kernels.py)


def main() -> int:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from proteinbert_tpu.kernels import attention as ka
    from proteinbert_tpu.kernels import fused_block as fb
    from proteinbert_tpu.ops.attention import (
        global_attention_apply,
        global_attention_init,
        packed_global_attention_apply,
    )

    failures = []

    def gate(ok: bool, msg: str) -> None:
        print(("PASS " if ok else "FAIL ") + msg)
        if not ok:
            failures.append(msg)

    B, L, C, S = 2, 128, 128, 4
    G, KD, H = 64, 16, 4
    params = global_attention_init(jax.random.PRNGKey(0), C, G, KD, H)
    local = jax.random.normal(jax.random.PRNGKey(1), (B, L, C),
                              jnp.float32)
    gseg = jax.random.normal(jax.random.PRNGKey(2), (B, S, G),
                             jnp.float32)
    seg = np.zeros((B, L), np.int32)
    seg[0, :60] = 1
    seg[0, 60:110] = 2
    seg[1, :L] = 1
    seg = jnp.asarray(seg)

    gate(ka.pallas_attention_supported(C, G, L, S, KD, H, "float32"),
         "guard: (128, 64, 128, 4) fp32 shape is supported")

    # ---- gate 1: packed parity + counter coverage --------------------
    before = dict(ka.ATTN_PATH_TOTAL)
    got = jax.jit(lambda p, x, g, s: ka.fused_packed_attention(
        p, x, g, s))(params, local, gseg, seg)
    delta_p = (ka.ATTN_PATH_TOTAL.get(("pallas", "packed"), 0)
               - before.get(("pallas", "packed"), 0))
    delta_s = (ka.ATTN_PATH_TOTAL.get(("reference", "segments"), 0)
               - before.get(("reference", "segments"), 0))
    want = jax.jit(lambda p, x, g, s: packed_global_attention_apply(
        p, x, g, s))(params, local, gseg, seg)
    diff = float(np.abs(np.asarray(got) - np.asarray(want)).max())
    gate(diff <= PARITY_BOUND,
         f"packed attention parity {diff:.2e} <= {PARITY_BOUND}")
    gate(delta_p >= 1 and delta_s == 0,
         f"packed dispatch on the Pallas path (pallas/packed +{delta_p},"
         f" reference/segments +{delta_s})")

    # Serving layout: spans bucket-quantized, tails are <pad>.
    real = np.zeros((B, L), bool)
    real[0, :41] = True
    real[0, 60:60 + 30] = True
    real[1, :100] = True
    real = jnp.asarray(real)
    got_m = ka.fused_packed_attention(params, local, gseg, seg,
                                      real_mask=real)
    want_m = packed_global_attention_apply(params, local, gseg, seg,
                                           real_mask=real)
    diff_m = float(np.abs(np.asarray(got_m) - np.asarray(want_m)).max())
    gate(diff_m <= PARITY_BOUND,
         f"serving real_mask parity {diff_m:.2e} <= {PARITY_BOUND}")

    # ---- gate 2: dense parity (incl. an all-pad row) -----------------
    g2 = jax.random.normal(jax.random.PRNGKey(3), (B, G), jnp.float32)
    pad = np.ones((B, L), bool)
    pad[1, :] = False
    pad = jnp.asarray(pad)
    before = dict(ka.ATTN_PATH_TOTAL)
    got_d = ka.fused_global_attention(params, local, g2, pad)
    delta_d = (ka.ATTN_PATH_TOTAL.get(("pallas", "dense"), 0)
               - before.get(("pallas", "dense"), 0))
    want_d = global_attention_apply(params, local, g2, pad)
    diff_d = float(np.abs(np.asarray(got_d) - np.asarray(want_d)).max())
    gate(diff_d <= PARITY_BOUND and delta_d >= 1,
         f"dense attention parity {diff_d:.2e} <= {PARITY_BOUND} on "
         "the Pallas path (all-pad row keeps uniform softmax)")

    # ---- gate 3: VJP gradient parity ---------------------------------
    def loss_f(p, x, g):
        return jnp.sum(ka.fused_packed_attention(p, x, g, seg) ** 2)

    def loss_r(p, x, g):
        return jnp.sum(packed_global_attention_apply(p, x, g, seg) ** 2)

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(params, local, gseg)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(params, local, gseg)
    gdiff = max(float(np.abs(np.asarray(a) - np.asarray(b)).max())
                for a, b in zip(jax.tree.leaves(gf), jax.tree.leaves(gr)))
    gate(gdiff <= GRAD_BOUND,
         f"custom-VJP gradient parity {gdiff:.2e} <= {GRAD_BOUND}")

    # ---- gate 4: forced-reference override ---------------------------
    os.environ[fb.FORCE_REFERENCE_ENV] = "1"
    try:
        before = dict(ka.ATTN_PATH_TOTAL)
        got_fo = jax.jit(lambda p, x, g, s: ka.fused_packed_attention(
            p, x, g, s))(params, local, gseg, seg)
        bumps = (ka.ATTN_PATH_TOTAL.get(("reference", "forced"), 0)
                 - before.get(("reference", "forced"), 0))
        bit = np.array_equal(np.asarray(got_fo), np.asarray(want))
        gate(bumps >= 1 and bit,
             "PBT_FORCE_REFERENCE_KERNEL routes attention onto the "
             f"reference path (forced +{bumps}, bit_identical={bit})")
    finally:
        del os.environ[fb.FORCE_REFERENCE_ENV]

    # ---- gate 5: tiled segment fused block at C=1024 -----------------
    from proteinbert_tpu.configs import ModelConfig
    from proteinbert_tpu.models import proteinbert

    Ct = 1024
    cfg = ModelConfig(local_dim=Ct, global_dim=64, key_dim=16,
                      num_heads=4, num_blocks=1, num_annotations=32,
                      dtype="bfloat16")
    block = proteinbert.block_init(jax.random.PRNGKey(4), cfg)
    tparams = {k: block[k] for k in ("narrow_conv", "wide_conv",
                                     "local_ln1", "local_dense",
                                     "local_ln2")}
    xt = jax.random.normal(jax.random.PRNGKey(5), (1, 128, Ct),
                           jnp.bfloat16)
    bct = jax.random.normal(jax.random.PRNGKey(6), (1, 2, Ct),
                            jnp.bfloat16)
    segt = jnp.asarray(np.array([[1] * 70 + [2] * 50 + [0] * 8],
                                np.int32))
    gate(fb.pallas_segments_supported(Ct, 128, 2),
         "guard: C=1024 packed shape has a tiled segment plan")
    before = dict(fb.PATH_TOTAL)
    got_t = fb.fused_local_track_segments(tparams, xt, bct, segt, 1, 5,
                                          True).astype(jnp.float32)
    dp = (fb.PATH_TOTAL.get(("pallas", "packed"), 0)
          - before.get(("pallas", "packed"), 0))
    dsg = (fb.PATH_TOTAL.get(("reference", "segments"), 0)
           - before.get(("reference", "segments"), 0))
    want_t = fb.local_track_segment_reference(
        tparams, xt, fb.gather_segment_broadcast(bct, segt), segt, 1, 5
    ).astype(jnp.float32)
    diff_t = float(np.abs(np.asarray(got_t) - np.asarray(want_t)).max())
    scale_t = float(np.abs(np.asarray(want_t)).max())
    gate(diff_t <= TILED_BOUND * max(scale_t, 1.0) and dp >= 1
         and dsg == 0,
         f"tiled segment C=1024 parity {diff_t:.3f} (bf16) on the "
         f"Pallas path (pallas/packed +{dp}, reference/segments +{dsg})")

    # ---- gate 6: pack_attn_capture note schema -----------------------
    from proteinbert_tpu.obs.events import validate_record

    rec = {"v": 1, "event": "note", "seq": 0, "t": 0.0,
           "source": "bench", "kind": "pack_attn_capture",
           "platform": "cpu", "attn_speedup_x": 1.0,
           "parity_max_abs_diff": diff, "mfu_raw": 0.01,
           "mfu_effective": 0.01}
    try:
        validate_record(rec)
        ok = True
    except ValueError as e:
        ok = False
        print(f"  validator rejected a well-formed capture: {e}")
    bad_rejected = False
    try:
        validate_record({**rec, "attn_speedup_x": 0.0})
    except ValueError:
        bad_rejected = True
    gate(ok and bad_rejected,
         "note(kind=pack_attn_capture) schema round-trip + negative")

    print(f"\n{len(failures)} failure(s)")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
