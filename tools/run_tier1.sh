#!/usr/bin/env bash
# The ROADMAP tier-1 verify command, verbatim — one place to edit, so a
# local run, CI, and the driver's gate can never drift apart.
#
# --pod64: ALSO run the opt-in 64-virtual-device pod-shape tier
# (tests/test_parallel64.py) after the tier-1 suite. It is slow-marked
# and env-gated, so the tier-1 pass itself is byte-identical with or
# without the flag; the pod tier's pass/fail is OR-ed into the exit
# code but its dots are reported separately (the DOTS_PASSED contract
# counts tier-1 only).
#
# --packed-md: ALSO run the opt-in multi-device PACKED-batch parity
# tier (tests/test_packing.py slow lane, PBT_RUN_PACKED_MD gate — same
# style as --pod64): fresh 8-virtual-device children prove the packed
# sharding rules (segment_ids like tokens) under plain DP+fsdp and the
# ZeRO-1 zero-update.
set -o pipefail

# Per-stage wall-time accounting (ISSUE 19 satellite): each stage calls
# mark_stage <name> when it finishes; the one-line summary printed at
# exit makes "which stage ate the tier-1 budget" a grep, not a rerun
# (the tier-1 timeout is host-bound — see ROADMAP).
STAGE_SUMMARY=""
stage_t0=$SECONDS
mark_stage() {
  local now=$SECONDS
  STAGE_SUMMARY="$STAGE_SUMMARY $1=$((now - stage_t0))s"
  stage_t0=$now
}

POD64=0
PACKED_MD=0
for arg in "$@"; do
  case "$arg" in
    --pod64) POD64=1 ;;
    --packed-md) PACKED_MD=1 ;;
    *) echo "unknown flag: $arg (supported: --pod64, --packed-md)" >&2; exit 2 ;;
  esac
done

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
mark_stage pytest

# Events-schema validator self-test (ISSUE 3 satellite): every telemetry
# event type must round-trip the validator, and garbage must be
# rejected. --schema-sync (ISSUE 15) additionally asserts the negative
# suite covers every event type, so a new event cannot ship without a
# validator negative. Stdlib-only (<2 s, no jax) — runs even when the
# pytest tier timed out, and its failure fails the gate.
echo "=== telemetry events-schema validator self-test + schema-sync ==="
python "$(dirname "$0")/validate_events.py" --self-test --schema-sync
rcv=$?
mark_stage events_schema
[ "$rc" -eq 0 ] && rc=$rcv

# Project-invariant static analyzer (ISSUE 15 tentpole): six AST rules
# (jit purity, lock discipline, durability protocol, event-schema call
# sites, obs-doc drift, dead exports) over the whole tree, GATED — a
# non-baselined finding fails tier-1. Pure python, no jax import
# (tools/pbt_check.py stub-imports the analysis package past the jax-
# importing package root); the JSON artifact feeds the trajectory
# sentinel's suppression-creep series below. docs/analysis.md is the
# rule catalog + suppression format.
echo "=== pbt check (project-invariant static analyzer, gated) ==="
check_json=$(mktemp /tmp/_pbt_check.XXXXXX.json)
timeout -k 10 120 python "$(dirname "$0")/pbt_check.py" \
  --json-artifact "$check_json"
rcc=$?
echo "check artifact: $check_json"
mark_stage pbt_check
[ "$rc" -eq 0 ] && rc=$rcc

# Perf-regression sentinel (ISSUE 6 satellite): fit per-metric
# baselines over the checked-in bench trajectory (BENCH_r*.json +
# bench_events.jsonl) and report any point outside the noise band.
# REPORT-ONLY: verdicts never fail the gate — only parse/schema errors
# in the inputs do (exit 2). Stdlib+obs only, <2 s, no jax.
echo "=== bench trajectory sentinel (report-only) ==="
verdict_json=$(mktemp /tmp/_bench_verdict.XXXXXX.json)
python "$(dirname "$0")/bench_trajectory.py" --output "$verdict_json" \
  --check-json "$check_json"
rct=$?
echo "verdict artifact: $verdict_json"
mark_stage sentinel
[ "$rc" -eq 0 ] && rc=$rct

# Serving smoke (ISSUE 5 satellite): in-process server on CPU under
# concurrent clients — continuous micro-batching vs the sequential
# baseline, per-bucket bit-parity, bounded-queue rejection. Small knobs
# keep it ~1 min; contract failures (parity / lost / un-rejected
# overflow) exit nonzero and fail the gate, wall-clock ratios are
# reported, not gated (bench.py --serve docstring).
echo "=== serve smoke (in-process server, CPU, concurrent clients) ==="
timeout -k 10 420 env JAX_PLATFORMS=cpu \
  PBT_SERVE_BENCH_SEQ_LEN=256 PBT_SERVE_BENCH_DIM=32 \
  PBT_SERVE_BENCH_REQUESTS=64 PBT_SERVE_BENCH_CLIENTS=24 \
  PBT_SERVE_BENCH_TRACE_ROUNDS=3 PBT_SERVE_BENCH_PHASES=core \
  python "$(dirname "$0")/../bench.py" --serve
rcs=$?
mark_stage serve_smoke
[ "$rc" -eq 0 ] && rc=$rcs

# Ragged serve smoke (ISSUE 9 satellite): bucketed vs ragged packed
# serving on a mixed-length log-normal workload. GATED: per-request
# parity within the documented jitted 1e-5 tolerance (matched ladder vs
# the live bucketed server, dense ladder vs the offline dense-bucketed
# reference), no lost requests, ragged warm-executable count O(kinds).
# Wall-clock speedup and pad_wasted are reported, not gated.
echo "=== ragged serve smoke (bucketed vs packed A/B, mixed lengths) ==="
timeout -k 10 420 env JAX_PLATFORMS=cpu \
  PBT_SERVE_BENCH_SEQ_LEN=256 PBT_SERVE_BENCH_DIM=32 \
  PBT_SERVE_BENCH_REQUESTS=96 PBT_SERVE_BENCH_CLIENTS=12 \
  PBT_SERVE_BENCH_PHASES=ragged PBT_SERVE_BENCH_RAGGED_ROUNDS=3 \
  python "$(dirname "$0")/../bench.py" --serve \
  --serve-length-mix 'median=32,sigma=1.0,seed=7'
rcr=$?
mark_stage ragged_smoke
[ "$rc" -eq 0 ] && rc=$rcr

# Pipeline smoke (ISSUE 19 satellite): the pipelined-dispatch window on
# an in-process depth-1 vs depth-2 server pair. GATED: overlap observed
# (inflight_max >= 2, the serve_inflight_batches high-water mark),
# async-vs-sync BIT-parity on a deterministically formed batch, and
# exactly-once seals with schema-valid event streams on both arms.
echo "=== pipeline smoke (pipelined dispatch window, CPU) ==="
timeout -k 10 300 python "$(dirname "$0")/pipeline_smoke.py"
rcpl=$?
mark_stage pipeline_smoke
[ "$rc" -eq 0 ] && rc=$rcpl

# Packed fused fast-path smoke (ISSUE 10 satellite): a tiny packed
# batch through the segment-aware Pallas kernel at a lane-aligned dim
# (the bench --pack fused A/B arm — which since ISSUE 13 ALSO runs the
# attention fused-vs-reference arm and emits its pack_attn_capture
# note under the same gates). GATED: fused-vs-reference parity
# within the documented 1e-5 jitted tolerance, supported shapes take
# the Pallas path with ZERO reason=segments fallbacks, and the
# PBT_FORCE_REFERENCE_KERNEL debug override (documented in
# docs/performance.md) still routes a fresh trace onto the reference
# path. Wall-clock is reported, not gated (interpret mode on CPU).
echo "=== packed fused smoke (fused-vs-reference A/B, CPU) ==="
timeout -k 10 420 env JAX_PLATFORMS=cpu \
  PBT_PACK_BENCH_SEQ_LEN=128 PBT_PACK_BENCH_BATCH=2 \
  PBT_PACK_BENCH_DIM=32 PBT_PACK_BENCH_STEPS=2 \
  PBT_PACK_BENCH_MEDIAN_LEN=40 PBT_PACK_BENCH_FUSED_DIM=128 \
  PBT_PACK_BENCH_FUSED_REPS=2 \
  python "$(dirname "$0")/../bench.py" --pack
rcf=$?
mark_stage pack_smoke
[ "$rc" -eq 0 ] && rc=$rcf

# Packed attention smoke (ISSUE 13): the ragged Pallas attention
# kernel and the tiled-segment fused block through their real dispatch
# entries on tiny shapes. GATED: packed/dense/serving-real_mask parity
# within the documented 1e-5 jitted tolerance, custom-VJP gradient
# parity, supported shapes take the Pallas path with ZERO
# reason=segments fallbacks (attention AND the C=1024 tiled segment
# fused block), PBT_FORCE_REFERENCE_KERNEL routes attention onto the
# reference path, and the pack_attn_capture note schema round-trips.
echo "=== packed attention smoke (Pallas attention + tiled segment, CPU) ==="
timeout -k 10 420 python "$(dirname "$0")/attn_smoke.py"
rca=$?
mark_stage attn_smoke
[ "$rc" -eq 0 ] && rc=$rca

# One-pass trunk smoke (ISSUE 16 tentpole): the whole block pass —
# local conv track + ragged attention — as ONE VMEM-resident Pallas
# grid program through the real dispatch entries. GATED: packed/dense/
# serving-real_mask BIT-identity vs the two-kernel composition, exactly
# one pallas_call boundary in the one-pass trace (the HBM round-trip
# is eliminated, not just faster), custom-VJP gradient parity, the
# PBT_FORCE_REFERENCE_KERNEL override, int8 in-kernel dequant
# bit-matching the HLO dequant, and the onepass_capture note schema.
echo "=== one-pass trunk smoke (fused block pass + int8 dequant, CPU) ==="
timeout -k 10 420 python "$(dirname "$0")/onepass_smoke.py"
rco=$?
mark_stage onepass_smoke
[ "$rc" -eq 0 ] && rc=$rco

# Reshard smoke (ISSUE 11): save a tiny ZeRO-1 train state on a 4x2
# CPU-virtual mesh, reshard 4x2 -> 8x1 -> 1 -> 4x2 through the real
# reshard verb. GATED: byte-identical round-trip parity (params + Adam
# moments), collective wire bytes counted on the same-device-set leg
# (honest host_staged on the cross-set legs), schema-valid `reshard`
# events.
echo "=== reshard smoke (mesh-agnostic checkpoint resharding, CPU) ==="
timeout -k 10 300 python "$(dirname "$0")/reshard_smoke.py"
rcre=$?
mark_stage reshard_smoke
[ "$rc" -eq 0 ] && rc=$rcre

# Fleet drill smoke (ISSUE 11): 3 in-process serve replicas behind the
# FleetRouter, one KILLED mid-request under concurrent load (latency
# spike first so requests are genuinely in flight), torn health on
# another. GATED: every accepted request seals exactly once (served or
# typed-rejected, none lost), failover observed (retried_ok >= 1, dead
# + re-admitted on the record), router/replica events schema-valid.
echo "=== fleet drill smoke (kill one of three replicas under load) ==="
timeout -k 10 420 python "$(dirname "$0")/fleet_drill.py" --json \
  --replicas 3 --requests 48 --clients 8
rcfd=$?
mark_stage fleet_drill
[ "$rc" -eq 0 ] && rc=$rcfd

# Rollout drill smoke (ISSUE 20): the blue-green trunk lifecycle on 3
# in-process replicas behind the FleetRouter — a deliberately-degraded
# candidate (the parity gate must refuse it, shadow traffic invisible),
# then a good one (gates green → atomic flip with one replica KILLED
# immediately before its flip verb — fleet must converge with zero
# lost requests and exactly-once sealing), then a forced breach
# (rollback bit-identical to the pre-rollout baseline, head pins
# restored). GATED: all of the above + schema-valid rollout_* events
# + the note(kind=rollout_capture) sentinel sample on the stream.
echo "=== rollout drill smoke (shadow → gate → flip → rollback, CPU) ==="
timeout -k 10 420 python "$(dirname "$0")/rollout_drill.py" --json
rcro=$?
mark_stage rollout_drill
[ "$rc" -eq 0 ] && rc=$rcro

# Map drill smoke (ISSUE 14): kill-anywhere offline inference through
# real `pbt map` subprocesses — SIGKILL between a block's object write
# and its cursor advance, a torn cursor, a torn block object, one
# poisoned record, and an injected transient dispatch failure. GATED:
# the resumed store is byte-identical to an uninterrupted control,
# re-work <= 1 block per shard, quarantined == injected poison,
# `pbt map --verify` detects a flipped byte (typed) and a deleted
# block (hole), all events schema-valid.
echo "=== map drill smoke (SIGKILL + torn artifacts, resume, verify) ==="
timeout -k 10 480 python "$(dirname "$0")/map_drill.py" --json
rcmd=$?
mark_stage map_drill
[ "$rc" -eq 0 ] && rc=$rcmd

# Index drill smoke (ISSUE 17): kill-anywhere ANN index construction
# through real `pbt index` subprocesses over a synthetic store —
# SIGKILL between an index block's object write and its cursor advance,
# then resume. GATED: the resumed index is byte-identical to an
# uninterrupted control (digests + object bytes + index_identity),
# re-work <= 1 block per shard, `pbt index --verify` detects a flipped
# byte (typed digest_mismatch) and a deleted object (hole), a rebuild
# against a different store is a typed refusal BEFORE any write, all
# events schema-valid. Store is hand-written through commit_block (no
# model forward) — seconds, not minutes.
echo "=== index drill smoke (SIGKILL mid-build, resume, verify) ==="
timeout -k 10 300 python "$(dirname "$0")/index_drill.py" --json
rcid=$?
mark_stage index_drill
[ "$rc" -eq 0 ] && rc=$rcid

# Quant smoke (ISSUE 12): tiny int8 ZeRO-1 steps on the 4x2 CPU-virtual
# mesh vs the replicated fp32 reference + the quantized serve arm.
# GATED: step-1 loss identity, param deviation within the documented
# quantization bounds (fp32-payload control isolates harness error),
# int8 determinism, int8 grad-reduction wire bytes <= 0.30x the fp32
# reduce-scatter FROM COMPILED HLO, serve-arm parity + weight-bytes
# ratio, schema-valid quant-tagged events.
echo "=== quant smoke (int8 reduce-scatter + int8 serve arm, CPU) ==="
timeout -k 10 420 python "$(dirname "$0")/quant_smoke.py"
rcq=$?
mark_stage quant_smoke
[ "$rc" -eq 0 ] && rc=$rcq

# Multi-tenant heads smoke (ISSUE 8 satellite): the platform loop end
# to end — tiny finetune → register into a head registry → serve one
# mixed-head micro-batch through the shared trunk → downstream eval.
# Contract failures (mixed-batch parity, trunk-recompile-on-add, lost
# requests, schema-invalid events) exit nonzero and fail the gate; the
# mixed-vs-partitioned throughput is reported, not gated.
echo "=== heads smoke (finetune → register → mixed serve → eval, CPU) ==="
timeout -k 10 420 env JAX_PLATFORMS=cpu \
  PBT_HEADS_BENCH_SEQ_LEN=96 PBT_HEADS_BENCH_DIM=32 \
  PBT_HEADS_BENCH_REQUESTS=36 PBT_HEADS_BENCH_CLIENTS=9 \
  PBT_HEADS_BENCH_ROUNDS=2 \
  python "$(dirname "$0")/../bench.py" --heads
rch=$?
mark_stage heads_smoke
[ "$rc" -eq 0 ] && rc=$rch

if [ "$PACKED_MD" = "1" ]; then
  echo "=== packed multi-device parity tier (8 virtual devices, opt-in) ==="
  timeout -k 10 900 env JAX_PLATFORMS=cpu PBT_RUN_PACKED_MD=1 \
    python -m pytest tests/test_packing.py -q -m 'slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly
  rcp=$?
  mark_stage packed_md
  [ "$rc" -eq 0 ] && rc=$rcp
fi

if [ "$POD64" = "1" ]; then
  echo "=== pod64 tier (64 virtual devices, opt-in) ==="
  timeout -k 10 2700 env JAX_PLATFORMS=cpu PBT_RUN_TIER64=1 \
    python -m pytest tests/test_parallel64.py -q -m 'tier64' \
    -p no:cacheprovider -p no:xdist -p no:randomly
  rc64=$?
  mark_stage pod64
  [ "$rc" -eq 0 ] && rc=$rc64
fi

echo "STAGE_WALL_TIMES:${STAGE_SUMMARY} total=${SECONDS}s"
exit $rc
